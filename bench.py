"""Headline benchmark: PromQL samples/sec scanned on sum by (rate[5m]).

Mirrors the reference's QueryInMemoryBenchmark workload shape
(ref: jmh/src/main/scala/filodb.jmh/QueryInMemoryBenchmark.scala:31-35,
126-133 — Prom-schema counters, 720 samples @10s, 5m rate windows, sum
aggregation) at the BASELINE.json north-star scale: the headline config is
1,048,576 series x 720 samples (f32 values ~2.9 GB, chip-resident), with a
262,144-series stage first so a flaky tunnel still leaves evidence behind.

Accounting is conservative: "samples scanned" counts every stored sample in
the queried span ONCE (S * samples_in_span), not once per overlapping window
the way the JVM SlidingWindowIterator would touch them — so the number is a
lower bound on iterator-equivalent throughput.

vs_baseline compares against the same algorithm implemented in vectorized
NumPy on host CPU (the strongest portable CPU stand-in we can run here; the
reference publishes no absolute numbers — see BASELINE.md). A second,
per-window loop baseline ("iterator") mimicking ChunkedWindowIterator's
per-window access pattern is reported as an extra field.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Robustness (the round-1/round-2 lesson): backend init on the tunneled TPU
('axon') can fail or hang indefinitely, and it can die BETWEEN stages. Two
defenses:
  - the default invocation runs as a SUPERVISOR executing the measurement
    in a child process under a hard timeout, retrying once, then falling
    back to a (smaller) CPU run — a JSON line with a `platform` field is
    always emitted;
  - the worker persists EVERY completed stage incrementally to
    BENCH_PARTIAL.json (atomic rename), so a tunnel that wedges mid-run
    still leaves TPU evidence; the supervisor recovers those stages into
    the final line (`"partial": true`) when the worker dies.
"""
import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

REPO_DIR = os.path.dirname(os.path.abspath(__file__))
PARTIAL_PATH = os.environ.get(
    "FILODB_BENCH_PARTIAL", os.path.join(REPO_DIR, "BENCH_PARTIAL.json"))

# FLOP/byte model for the fused kernel (see doc/kernels.md): since round
# 5 the boundary selections are exact per-tile gathers (data movement, 0
# model FLOPs); the matmul work is the [Gp,BS]x[BS,Wp] group epilogue.
# The legacy matmul-selection path (FILODB_FUSED_GATHER=0) adds 2 (dense
# precorrected) selection matmuls.
_FUSED_MATMULS = (0 if os.environ.get("FILODB_FUSED_GATHER", "1") != "0"
                  else 2)


def make_counter_data(S, T, step_ms=10_000, seed=7):
    rng = np.random.default_rng(seed)
    ts_row = np.arange(T, dtype=np.int64) * step_ms
    vals = np.cumsum(rng.exponential(10.0, size=(S, T)).astype(np.float32),
                     axis=1)
    return ts_row, vals


def numpy_vectorized_baseline(ts_row, vals, gids, G, wends, range_ms):
    """Same algorithm as the device kernel, vectorized NumPy on host: window
    is samples in [wend-range+1, wend] and the rate uses full Prometheus
    extrapolation with the counter-zero clamp (semantics of ref:
    query/.../rangefn/RateFunctions.scala:37-76 extrapolatedRate), so in f64
    this doubles as the conformance oracle for the f32 device result."""
    lo = np.searchsorted(ts_row, wends - range_ms + 1, side="left")
    hi = np.searchsorted(ts_row, wends, side="right") - 1
    n = hi - lo + 1
    ok = n >= 2
    lo_c = np.minimum(lo, len(ts_row) - 1)
    t1 = ts_row[lo_c].astype(np.float64)
    t2 = ts_row[hi].astype(np.float64)                 # [W]
    v1 = vals[:, lo_c].astype(np.float64)
    v2 = vals[:, hi].astype(np.float64)                # [S, W]
    wstart = (wends - range_ms).astype(np.float64)
    wend = wends.astype(np.float64)
    dur_start = (t1 - wstart) / 1000.0
    dur_end = (wend - t2) / 1000.0
    sampled = (t2 - t1) / 1000.0
    avg = sampled / np.maximum(n - 1, 1)
    delta = v2 - v1
    with np.errstate(invalid="ignore", divide="ignore"):
        dur_zero = sampled * (v1 / delta)              # counter hit 0 here
        ds = np.where((delta > 0) & (v1 >= 0) & (dur_zero < dur_start),
                      dur_zero, dur_start)
        threshold = avg * 1.1
        extrap = (sampled + np.where(ds < threshold, ds, avg / 2)
                  + np.where(dur_end < threshold, dur_end, avg / 2))
        rate = delta * (extrap / sampled) / (wend - wstart) * 1000.0
    rate = np.where(ok & (sampled > 0), rate, np.nan)
    out = np.zeros((G, rate.shape[1]))
    np.add.at(out, gids, np.nan_to_num(rate))
    return out


def numpy_iterator_baseline(ts_row, vals, wends, range_ms):
    """Per-(series,window) loop mimicking ChunkedWindowIterator's access
    pattern (ref: query/.../exec/PeriodicSamplesMapper.scala:202-292)."""
    S = vals.shape[0]
    out = np.empty((S, len(wends)))
    for s in range(S):
        row_v = vals[s]
        for wi, wend in enumerate(wends):
            lo = np.searchsorted(ts_row, wend - range_ms, side="left")
            hi = np.searchsorted(ts_row, wend, side="right")
            if hi - lo < 2:
                out[s, wi] = np.nan
                continue
            t1, t2 = ts_row[lo], ts_row[hi - 1]
            out[s, wi] = ((row_v[hi - 1] - row_v[lo]) / (t2 - t1) * 1000.0
                          if t2 > t1 else np.nan)
    return out


class PartialWriter:
    """Atomic incremental persistence of completed bench stages."""

    def __init__(self, run_id, platform):
        self.doc = {"run_id": run_id, "platform": platform,
                    "started_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                                 time.gmtime()),
                    "stages": {}, "done": False}
        self.flush()

    def stage(self, name, data):
        self.doc["stages"][name] = data
        self.flush()

    def finish(self):
        self.doc["done"] = True
        self.flush()

    def flush(self):
        self.doc["updated_utc"] = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                                time.gmtime())
        tmp = PARTIAL_PATH + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.doc, f, indent=1)
        os.replace(tmp, PARTIAL_PATH)


def run_pallas_fused(ts_row, vals_dev, vbase32, gids, wends, range_ms, G,
                     xla_res, iters):
    """Time ops/pallas_fused for one config; cross-check against the XLA
    result when available.  Returns (p50_seconds, max_rel_err) where the
    error is inf when the NaN patterns disagree, and None when xla_res is
    None (conformance then comes from a smaller stage).  Values arrive
    host-precorrected + rebased (leaf-path parity), so the kernel runs
    with_drops=False — the same configuration the leaf exec uses."""
    from filodb_tpu.ops import pallas_fused as pf
    plan = pf.build_plan(ts_row, np.asarray(wends, np.int64), range_ms)
    prep = pf.pad_inputs(vals_dev, vbase32, gids, plan, G)

    def fused_query():
        sums, counts = pf.fused_rate_groupsum(
            None, None, None, plan, G, "rate", True, prepared=prep)
        return pf.present_sum(sums, counts)

    got = fused_query()                               # compile + warm
    if xla_res is None:
        err = None
    elif (np.isnan(got) != np.isnan(xla_res)).any():
        err = float("inf")
    else:
        err = float(np.nanmax(
            np.abs(got - xla_res) / np.maximum(np.abs(xla_res), 1e-6)))
    lat = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fused_query()
        lat.append(time.perf_counter() - t0)
    return float(np.median(np.asarray(lat))), err


CONFORMANCE_SERIES_CAP = 262_144


def cpu_f64_conformance(stage, xla_res, ts_row, vals, gids, G, wends,
                        range_ms):
    """Self-certify a CPU stage: cross-check the XLA f32 result against the
    same algorithm in f64 NumPy (round-3 verdict weak #3 — the artifact must
    carry an in-run correctness certificate even on the CPU fallback).
    Callers cap the series count (CONFORMANCE_SERIES_CAP) so the f64
    temporaries (~8 [S,W] arrays) can't OOM a smaller fallback host; vals
    stays f32 here — the oracle casts only the gathered [S,W] columns."""
    ref = numpy_vectorized_baseline(ts_row, vals, gids,
                                    G, wends.astype(np.int64), range_ms)
    got = np.nan_to_num(np.asarray(xla_res, np.float64))
    err = float(np.max(np.abs(got - ref) / np.maximum(np.abs(ref), 1e-6)))
    stage["xla_max_rel_err_vs_f64"] = round(err, 9)
    if vals.shape[0] != stage["series"]:
        stage["conformance_series"] = vals.shape[0]
    return err < 1e-3


def measure_stage(S, T, iters, platform, do_fused, persist,
                  prior_conformance_ok=False):
    """One bench configuration end-to-end; returns the stage dict.
    `persist(partial_dict)` is called at every sub-milestone so a tunnel
    death mid-stage still leaves the finished sub-measurements behind."""
    import jax
    from filodb_tpu.ops.rangefns import evaluate_range_function
    from filodb_tpu.ops import agg as agg_ops
    from filodb_tpu.ops.timewindow import to_offsets, make_window_ends

    G = min(1000, S)
    range_ms, step_ms = 300_000, 60_000      # rate[5m], 1m steps
    stage = {"series": S, "samples_per_series": T, "groups": G}

    ts_row, vals = make_counter_data(S, T)
    # leaf-path parity (r4): counters are reset-corrected + rebased in f64
    # ON THE HOST once per working set — the DeviceMirror does exactly this
    # at upload (core/devicecache.py refresh; ops/counter.rebase_values),
    # so steady-state queries must NOT pay a per-query correction scan.
    # Round 2/3 benches ran precorrected=False and the scan was ~90% of
    # CPU query time (see doc/kernels.md, BENCH_TREND.json).
    t0 = time.perf_counter()
    # make_counter_data is monotone by construction, so the f64 reset
    # correction (ops/counter.host_counter_correct) is the identity —
    # only the f64 rebase matters for f32 delta exactness.  Chunked so
    # the 1M-series stage doesn't materialize ~30 GB of f64 temporaries
    # (the full rebase_values took 500s host-side at 1M x 720).
    vbase64 = vals[:, 0].astype(np.float64)
    vals32 = np.empty_like(vals, dtype=np.float32)
    for i in range(0, S, 65_536):
        j = min(i + 65_536, S)
        vals32[i:j] = (vals[i:j].astype(np.float64)
                       - vbase64[i:j, None]).astype(np.float32)
    vbase32 = vbase64.astype(np.float32)
    stage["host_prep_s"] = round(time.perf_counter() - t0, 2)
    # shared scrape grid: ship ONE [1, T] offset row and let it broadcast
    # (exact for every range fn — saves S*T*4 bytes of HBM at 1M series)
    ts_one = to_offsets(ts_row[None, :], np.full(1, T), 0)
    gids = (np.arange(S) % G).astype(np.int32)
    qstart = 600_000
    qend = int(ts_row[-1])
    wends = make_window_ends(qstart, qend, step_ms).astype(np.int32)
    stage["windows"] = W = len(wends)
    span_lo = np.searchsorted(ts_row, qstart - range_ms)
    span_hi = np.searchsorted(ts_row, qend, side="right")
    scanned = S * int(span_hi - span_lo)
    stage["samples_scanned_per_query"] = scanned
    value_bytes = S * T * 4

    dev_ts = jax.device_put(ts_one)
    dev_vals = jax.device_put(vals32)
    dev_vbase = jax.device_put(vbase32)
    dev_gids = jax.device_put(gids)
    dev_wends = jax.device_put(wends)

    @jax.jit
    def query(ts_off, v, vb, g, w):
        res = evaluate_range_function(ts_off, v, w, range_ms, "rate",
                                      shared_grid=True, vbase=vb,
                                      precorrected=True)
        return agg_ops.aggregate("sum", res, g, G)

    xla_res = None
    try:
        t0 = time.perf_counter()
        # np.asarray forces execution AND result fetch: block_until_ready
        # is not a reliable completion barrier on the tunneled TPU backend
        xla_res = np.asarray(query(dev_ts, dev_vals, dev_vbase, dev_gids,
                                   dev_wends))
        stage["xla_compile_s"] = round(time.perf_counter() - t0, 2)
        lat = []
        for _ in range(iters):
            t0 = time.perf_counter()
            np.asarray(query(dev_ts, dev_vals, dev_vbase, dev_gids,
                             dev_wends))
            lat.append(time.perf_counter() - t0)
        p50 = float(np.median(np.asarray(lat)))
        stage.update({
            "xla_p50_s": round(p50, 5),
            "xla_samples_per_sec": round(scanned / p50, 1),
            "xla_hbm_gb_s_lower_bound": round(value_bytes / p50 / 1e9, 1),
        })
        persist(stage)
    except Exception as e:  # noqa: BLE001 — OOM etc.: still try fused
        stage["xla_error"] = f"{type(e).__name__}: {e}"[:300]
        persist(stage)

    if do_fused:
        try:
            fused_iters = max(3, iters // 2) if S >= 1 << 20 else iters
            p50_f, err = run_pallas_fused(ts_row, dev_vals, vbase32, gids,
                                          wends, range_ms, G, xla_res,
                                          fused_iters)
            stage["pallas_p50_s"] = round(p50_f, 5)
            stage["pallas_samples_per_sec"] = round(scanned / p50_f, 1)
            # one HBM pass over the values by construction
            stage["pallas_hbm_gb_s"] = round(value_bytes / p50_f / 1e9, 1)
            Tp = (T + 127) // 128 * 128
            Wp = (W + 127) // 128 * 128
            Gp = max(G, 8)
            flops = 2 * S * Tp * Wp * _FUSED_MATMULS + 2 * Gp * S * Wp
            stage["pallas_model_tflops_per_s"] = round(flops / p50_f / 1e12,
                                                       2)
            if err is not None:
                stage["pallas_max_rel_err_vs_xla"] = (
                    round(err, 9) if np.isfinite(err) else "inf")
            persist(stage)
        except Exception as e:  # noqa: BLE001
            stage["pallas_error"] = f"{type(e).__name__}: {e}"[:300]
            persist(stage)

    # headline for this stage: fastest path whose result is trusted —
    # fused needs a clean cross-check HERE, or (when XLA was unavailable,
    # e.g. OOM at 1M) a clean cross-check recorded at a PREVIOUS stage
    paths = []
    if "xla_p50_s" in stage:
        paths.append(("xla", stage["xla_p50_s"]))
    err_ok = stage.get("pallas_max_rel_err_vs_xla")
    checked_here = isinstance(err_ok, float) and err_ok < 1e-4
    cpu_cert_failed = False
    if platform == "cpu" and xla_res is not None:
        # no Pallas on the CPU path: certify XLA against the f64 oracle so
        # the artifact's number is still self-checking.  Above the cap,
        # certify a group-representative subset (gids cycle through all G
        # groups) by re-running the jitted query on the sliced inputs.
        try:
            Sc = min(S, CONFORMANCE_SERIES_CAP)
            if Sc == S:
                sub_res = xla_res
            else:
                sub_res = np.asarray(query(dev_ts, dev_vals[:Sc],
                                           dev_vbase[:Sc], dev_gids[:Sc],
                                           dev_wends))
            checked_here = cpu_f64_conformance(
                stage, sub_res, ts_row, vals[:Sc], gids[:Sc], G, wends,
                range_ms)
            cpu_cert_failed = not checked_here
        except Exception as e:  # noqa: BLE001 — a cert CRASH (OOM etc.) is
            # not evidence the result is wrong: record it and fall back to
            # conformance inherited from a previously-certified stage
            stage["conformance_error"] = f"{type(e).__name__}: {e}"[:200]
    if "pallas_p50_s" in stage and (
            checked_here or (err_ok is None and xla_res is None
                             and prior_conformance_ok)):
        paths.append(("pallas_fused", stage["pallas_p50_s"]))
        if not checked_here:
            stage["pallas_conformance"] = "inherited from previous stage"
    stage["conformance_ok"] = checked_here or (prior_conformance_ok
                                               and not cpu_cert_failed)
    if cpu_cert_failed:
        # a stage whose own certificate failed must not publish a trusted
        # headline number (raw xla_* timings stay recorded above)
        paths = []
    if paths:
        kernel, p50 = min(paths, key=lambda kv: kv[1])
        stage.update({
            "kernel": kernel,
            "p50_s": round(p50, 5),
            "samples_per_sec": round(scanned / p50, 1),
        })
    persist(stage)
    del dev_ts, dev_vals, dev_vbase, dev_gids, dev_wends
    return stage, ts_row, vals, gids, wends, range_ms, span_hi - span_lo


def measure_ingest(series=262_144, max_seconds=10.0, max_t=256):
    """Host-path ingest throughput: columnar grid appends into one live
    shard (partition creation warmed out of the timed window, no flush, no
    queries) — the `ingest_samples_per_sec` stage of the one-line bench
    contract, so the trajectory tracks the host half of the pipeline and
    not just the device scan path.  Bounded two ways: wall clock and
    samples-per-series (memory)."""
    import numpy as np

    from filodb_tpu.core.memstore import TimeSeriesMemStore
    from filodb_tpu.ingest.generator import counter_batch

    START = 1_600_000_000_000
    ms = TimeSeriesMemStore()
    sh = ms.setup("bench_ingest", 0)
    t0 = time.perf_counter()
    base = counter_batch(series, 1, start_ms=START)
    build_s = time.perf_counter() - t0
    k = 2
    row_base = np.arange(series, dtype=np.float64)[:, None]

    def ingest_once(t_idx):
        ts_row = START + (t_idx + np.arange(k, dtype=np.int64)) * 10_000
        ts2d = np.broadcast_to(ts_row, (series, k))
        vals = (t_idx + np.arange(k, dtype=np.float64))[None, :] * 5.0 \
            + row_base
        return sh.ingest_columns("prom-counter", base.part_keys, ts2d,
                                 {"count": vals}, offset=t_idx)

    ingest_once(0)                       # warm: creates all partitions
    t_idx = k
    n0 = sh.stats.rows_ingested
    t0 = time.perf_counter()
    while (time.perf_counter() - t0 < max_seconds) and t_idx < max_t:
        ingest_once(t_idx)
        t_idx += k
    dt = time.perf_counter() - t0
    n = sh.stats.rows_ingested - n0
    return {"series": series, "samples": int(n),
            "elapsed_s": round(dt, 2),
            "partkey_build_s": round(build_s, 2),
            "dropped": int(sh.stats.rows_dropped),
            "ingest_samples_per_sec": round(n / max(dt, 1e-9), 1)}


def measure_wal(quick=False, series=None):
    """Durability stage (ISSUE 7): WAL-on vs WAL-off columnar ingest
    throughput, restart-replay rate, the remote_write front-door rate,
    and the kill-chaos proof.

    One-line JSON keys:
      wal_off_samples_per_sec / wal_on_samples_per_sec — the same
          ingest_columns loop with and without the group-committed WAL
          in front (fresh store each, same batch shapes)
      wal_overhead_pct / wal_on_vs_off_pct — the durability tax;
          acceptance gate: WAL-on >= 50% of WAL-off
      wal_replay_samples_per_sec — cold-restart replay of the log just
          written, through the same ingest_columns path
      remote_write_samples_per_sec — snappy+protobuf POST /api/v1/write
          end to end (decode -> slabs -> ingest), reference-shaped
          payloads, no socket (the route layer, like the QPS stages)
      wal_kill_acked_lost — SIGKILL a real ingesting node subprocess
          (bench/walchaos.py), replay its WAL, count client-observed
          acknowledged batches missing from the recovered store
          (acceptance gate: 0) — and wal_kill_query_identical: the
          recovered store's query_range answer is byte-identical to an
          uninterrupted run over the same replayed batches
    """
    import shutil
    import tempfile

    from bench.walchaos import START_MS, chaos_batch, chaos_keys
    from filodb_tpu.config import WalConfig
    from filodb_tpu.core.memstore import TimeSeriesMemStore
    from filodb_tpu.wal import WalManager

    S = series or (8_192 if quick else 65_536)
    k = 4
    budget_s = 2.0 if quick else 6.0
    max_batches = 16 if quick else 32
    out = {"series": S, "k": k}
    root = tempfile.mkdtemp(prefix="filodb-wal-bench-")
    keys = chaos_keys(S)

    def ingest_run(wal):
        ms = TimeSeriesMemStore()
        sh = ms.setup("prometheus", 0)
        ts0, v0 = chaos_batch(S, k, 0, START_MS)
        sh.ingest_columns("gauge", keys, ts0, {"value": v0})  # warm: creates
        n0 = sh.stats.rows_ingested
        b = 1
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < budget_s and b <= max_batches:
            ts, vals = chaos_batch(S, k, b, START_MS)
            if wal is not None:
                # the production sink's ordering: append (no wait) ->
                # in-memory ingest overlapping the committer's fsync ->
                # ONE commit wait before the ack
                seq = wal.append_grid(0, "gauge", keys, ts,
                                      {"value": vals}, wait=False)
            else:
                seq = -1
            sh.ingest_columns("gauge", keys, ts, {"value": vals},
                              offset=seq)
            if wal is not None:
                wal.commit(seq)
            b += 1
        dt = time.perf_counter() - t0
        return (sh.stats.rows_ingested - n0) / max(dt, 1e-9)

    # --- WAL-off vs WAL-on, same shapes, fresh stores.  Interleaved
    # rounds, best of each: container/overlay filesystems throw
    # multi-second sync stalls that would otherwise report a durability
    # tax the WAL does not have (one observed run: a single 8 s first
    # fsync at zero load)
    off_sps = on_sps = 0.0
    for rnd in range(2):
        off_sps = max(off_sps, ingest_run(None))
        wal = WalManager(os.path.join(root, f"on{rnd}"), "prometheus",
                         WalConfig(enabled=True))
        try:
            on_sps = max(on_sps, ingest_run(wal))
        finally:
            wal.close()
    out["wal_off_samples_per_sec"] = round(off_sps, 1)
    out["wal_on_samples_per_sec"] = round(on_sps, 1)
    out["wal_overhead_pct"] = round((1.0 - on_sps / max(off_sps, 1e-9))
                                    * 100.0, 1)
    out["wal_on_vs_off_pct"] = round(on_sps / max(off_sps, 1e-9) * 100.0,
                                     1)
    out["wal_gate_ok"] = bool(on_sps >= 0.5 * off_sps)

    # --- cold replay of the last round's log
    from filodb_tpu.wal import replay_dir
    ms2 = TimeSeriesMemStore()
    stats = replay_dir(os.path.join(root, "on1", "prometheus"), ms2,
                       "prometheus")
    out["wal_replay_records"] = stats.records
    out["wal_replay_samples_per_sec"] = round(stats.samples_per_sec, 1)

    # --- remote_write front door (route layer, no socket)
    out.update(_measure_remote_write(quick))

    # --- kill-mid-ingest chaos
    try:
        out.update(_wal_kill_chaos(root, quick))
    except Exception as e:  # noqa: BLE001 — the proof failing must be LOUD
        out["wal_kill_error"] = f"{type(e).__name__}: {e}"[:300]
    shutil.rmtree(root, ignore_errors=True)
    return out


def _measure_remote_write(quick):
    """POST /api/v1/write throughput through the route handler: snappy
    block decompress + prompb decode + slab grouping + ingest_columns
    (the whole server-side cost; payload ENCODE is the client's)."""
    from filodb_tpu.http import remotepb
    from filodb_tpu.standalone import DatasetConfig, FiloServer
    from filodb_tpu.utils import snappy as fsnappy

    S_rw = 2_048 if quick else 8_192
    k = 4
    start = 1_600_000_000_000
    srv = FiloServer(datasets=[DatasetConfig("prometheus", num_shards=2)])
    try:
        payloads = []
        for b in range(6):
            series = []
            for i in range(S_rw):
                labels = [("__name__", "rw_bench_total"), ("_ws_", "rw"),
                          ("_ns_", "bench"), ("inst", f"i{i:05d}")]
                samples = [(float(i + j), start + (b * k + j) * 10_000)
                           for j in range(k)]
                series.append(remotepb.PromTimeSeries(labels, samples))
            payloads.append(fsnappy.compress(
                remotepb.encode_write_request(series)))
        st, _ = srv.api.handle("POST", "/api/v1/write", {}, payloads[0])
        assert st == 204, f"remote_write bench got {st}"
        posted = 0
        t0 = time.perf_counter()
        budget = 2.0 if quick else 5.0
        i = 1
        while time.perf_counter() - t0 < budget and i < len(payloads):
            st, _ = srv.api.handle("POST", "/api/v1/write", {},
                                   payloads[i])
            assert st == 204, f"remote_write bench got {st}"
            posted += S_rw * k
            i += 1
        dt = time.perf_counter() - t0
        return {"remote_write_series": S_rw,
                "remote_write_samples_per_sec":
                    round(posted / max(dt, 1e-9), 1)}
    finally:
        srv.shutdown()


def _wal_kill_chaos(root, quick):
    """SIGKILL a real WAL-ingesting subprocess mid-batch, replay what it
    left on disk, and prove (a) every client-observed acknowledged batch
    survived and (b) the recovered store answers queries byte-identical
    to an uninterrupted run over the same batches."""
    import signal

    from bench.walchaos import START_MS
    from filodb_tpu.config import FilodbSettings
    from filodb_tpu.standalone import DatasetConfig, FiloServer

    S_kill = 1_024 if quick else 4_096
    k = 2
    kill_after = 4 if quick else 8
    wal_root = os.path.join(root, "kill")
    worker = os.path.join(REPO_DIR, "bench", "walchaos.py")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_DIR
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [sys.executable, worker, "--wal-dir", wal_root,
         "--series", str(S_kill), "--k", str(k)],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=env, cwd=REPO_DIR)
    acked = -1
    try:
        ready = proc.stdout.readline()
        assert ready.startswith("CHAOS_READY"), f"child: {ready!r}"
        while acked + 1 < kill_after:
            line = proc.stdout.readline()
            if not line:
                raise RuntimeError("chaos child exited early")
            if line.startswith("ACKED"):
                acked = int(line.split()[1])
        # kill MID-batch: the child is inside append/commit of the next
        # batch right after we read this ack
        time.sleep(0.02)
    finally:
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)

    # recovery: a fresh server on the same WAL dir replays at boot
    cfg = FilodbSettings()
    cfg.wal.enabled = True
    cfg.wal.dir = wal_root
    rec = FiloServer(datasets=[DatasetConfig("prometheus", num_shards=1)],
                     config=cfg)
    try:
        shard = rec.memstore.get_shard("prometheus", 0)
        replayed = int(shard.ingested_offset) + 1   # seq b == batch b
        lost = max(0, (acked + 1) - replayed)
        # uninterrupted reference: same batches, no crash, no WAL
        ref = FiloServer(
            datasets=[DatasetConfig("prometheus", num_shards=1)])
        try:
            from bench.walchaos import chaos_batch, chaos_keys
            rkeys = chaos_keys(S_kill)
            rshard = ref.memstore.get_shard("prometheus", 0)
            for b in range(replayed):
                ts, vals = chaos_batch(S_kill, k, b, START_MS)
                rshard.ingest_columns("gauge", rkeys, ts,
                                      {"value": vals})
            q = {"query": "sum(wal_chaos_total)",
                 "start": str(START_MS // 1000),
                 "end": str(START_MS // 1000 + replayed * k * 10),
                 "step": "10"}
            st_a, pay_a = rec.api.handle("GET", "/api/v1/query_range",
                                         dict(q), b"")
            st_b, pay_b = ref.api.handle("GET", "/api/v1/query_range",
                                         dict(q), b"")
            for p in (pay_a, pay_b):
                if isinstance(p, dict):
                    p.pop("traceID", None)   # per-request random id
            identical = (st_a == st_b == 200
                         and json.dumps(pay_a, sort_keys=True)
                         == json.dumps(pay_b, sort_keys=True))
        finally:
            ref.shutdown()
    finally:
        rec.shutdown()
    return {"wal_kill_acked_batches": acked + 1,
            "wal_kill_replayed_batches": replayed,
            "wal_kill_acked_lost": lost,
            "wal_kill_query_identical": bool(identical)}


def _rw_payloads(series, k, batches, start_ms=None, ws="trc"):
    """Pre-encoded remote_write payloads (snappy+prompb) with distinct,
    near-now timestamps per batch — client encode cost stays out of the
    measured server path, and now-ish stamps keep the freshness
    histograms meaningful."""
    from filodb_tpu.http import remotepb
    from filodb_tpu.utils import snappy as fsnappy
    start = start_ms or (int(time.time() * 1000) - batches * k * 1000)
    payloads = []
    for b in range(batches):
        srs = []
        for i in range(series):
            labels = [("__name__", "trace_bench_total"), ("_ws_", ws),
                      ("_ns_", "bench"), ("inst", f"i{i:05d}")]
            samples = [(float(i + j), start + (b * k + j) * 1000)
                       for j in range(k)]
            srs.append(remotepb.PromTimeSeries(labels, samples))
        payloads.append(fsnappy.compress(
            remotepb.encode_write_request(srs)))
    return payloads


def measure_ingesttrace(quick=False, series=None):
    """Write-path tracing stage (ISSUE 12): the observability tax on the
    ingest path, the stitched 2-node trace proof, and the fault-
    visibility drill.

    One-line JSON keys:
      ingest_trace_overhead_pct / ingest_trace_on_samples_per_sec —
          remote_write door throughput with the span+exemplar pipeline
          on vs off (fresh server each round, interleaved, best-of;
          acceptance gate: tracing-on >= 98% of tracing-off)
      ingest_trace_stitched / ingest_trace_nodes / ingest_trace_spans —
          a 2-node RF-2 run (real replica subprocess, quorum acks)
          produces ONE trace id whose span tree covers door -> WAL
          append -> fsync wait -> replication fan-out -> replica WAL ->
          memstore ingest on BOTH nodes
      ingesttrace_fault_visible — an injected wal.fsync delay
          (utils/faults.py) shows up in the fsync-latency histogram,
          the ingest slowlog, AND the freshness histograms, and flips
          health to degraded while sustained
      ingest_freshness_p99_s — the ingest-to-ack p99 over the traced
          run's batches
    """
    import shutil
    import tempfile

    from filodb_tpu.standalone import DatasetConfig, FiloServer
    from filodb_tpu.utils.metrics import (collector, registry,
                                          set_exemplars_enabled,
                                          set_spans_enabled)

    S = series or (1_024 if quick else 2_048)
    k = 4
    batches = 17 if quick else 49
    out = {"ingest_trace_series": S}
    root = tempfile.mkdtemp(prefix="filodb-ingesttrace-")

    # --- tracing tax on the remote_write door.  The per-POST fixed cost
    # (protobuf decode + per-series key hashing) is ~4 orders above the
    # span pipeline's, so a rate-over-rounds compare is pure noise at a
    # 2% gate; instead INTERLEAVE modes POST by POST on one server
    # (distinct pre-encoded payloads, store grows identically under
    # both modes) and compare per-POST MEDIANS — the observability
    # stage's measured-pairs pattern
    def door_tax():
        import gc
        import statistics
        srv = FiloServer(
            datasets=[DatasetConfig("prometheus", num_shards=2)])
        times = {True: [], False: []}
        try:
            payloads = _rw_payloads(S, k, batches)
            st, _ = srv.api.handle("POST", "/api/v1/write", {},
                                   payloads[0])
            assert st == 204, f"ingesttrace warm got {st}"
            # GC pinned: the decode path allocates ~100 objects per
            # series per POST, and gen-2 collections landing on random
            # POSTs are a bimodal ±30% that buries a 2% gate; collect
            # OUTSIDE each timed window instead
            gc.disable()
            for i, p in enumerate(payloads[1:]):
                # ABBA pairing: per-POST cost drifts as the store
                # grows, and a fixed on-then-off order would book the
                # drift entirely against one mode
                pair, first = divmod(i, 2)
                on = (first == 0) == (pair % 2 == 0)
                set_spans_enabled(on)
                set_exemplars_enabled(on)
                gc.collect()
                t0 = time.perf_counter()
                st, _ = srv.api.handle("POST", "/api/v1/write", {}, p)
                assert st == 204, f"ingesttrace bench got {st}"
                times[on].append(time.perf_counter() - t0)
        finally:
            gc.enable()
            srv.shutdown()

        def fastq(xs):
            # mean of the fastest quartile: the modes' best-case paths
            # are the comparable ones — residual scheduler/IO stalls
            # land in the slow tail of BOTH modes but not evenly
            xs = sorted(xs)
            q = max(len(xs) // 4, 1)
            return statistics.mean(xs[:q])

        return fastq(times[True]), fastq(times[False])

    try:
        on_p50, off_p50 = door_tax()
    finally:
        set_spans_enabled(True)
        set_exemplars_enabled(True)
    on_sps = S * k / max(on_p50, 1e-9)
    off_sps = S * k / max(off_p50, 1e-9)
    out["ingest_trace_off_samples_per_sec"] = round(off_sps, 1)
    out["ingest_trace_on_samples_per_sec"] = round(on_sps, 1)
    out["ingest_trace_overhead_pct"] = round(
        (1.0 - on_sps / max(off_sps, 1e-9)) * 100.0, 2)
    overhead_ok = on_sps >= 0.98 * off_sps

    # --- stitched 2-node trace + fault drill: node B is a REAL replica
    # subprocess (bench/chaosnode.py — replication door + its own WAL),
    # node A an in-process FiloServer fanning out at RF-2/quorum
    from filodb_tpu.config import FilodbSettings
    from filodb_tpu.utils.freshness import freshness
    from filodb_tpu.utils.metrics import make_traceparent, mint_trace_id
    from filodb_tpu.utils.slowlog import ingestlog

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_DIR
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO_DIR, "bench", "chaosnode.py"),
         "--name", "B", "--port", "0", "--repl-port", "0",
         "--shards", "0", "--dataset", "tracetest",
         "--series", "8", "--samples", "4",
         "--wal-dir", os.path.join(root, "walB")],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=env, cwd=REPO_DIR)
    srv = None
    try:
        ready = json.loads(proc.stdout.readline())
        assert ready.get("ready"), f"chaosnode: {ready}"
        cfg = FilodbSettings()
        cfg.wal.enabled = True
        cfg.wal.dir = os.path.join(root, "walA")
        cfg.replication.enabled = True
        cfg.replication.factor = 2
        cfg.replication.ack_mode = "quorum"
        # a tight SLO so the injected fsync delay below counts as a
        # sustained breach within a few batches
        cfg.ingest.slow_batch_threshold_s = 0.05
        cfg.ingest.freshness_breach_count = 3
        freshness.reset()
        ingestlog.clear()
        srv = FiloServer(
            datasets=[DatasetConfig("tracetest", num_shards=1)],
            config=cfg, node_name="A",
            replication_peers={"B": ("127.0.0.1", ready["repl_port"])})
        tid = mint_trace_id()
        ws = "trc"
        st, pay = srv.api.handle(
            "POST", "/api/v1/write", {}, _rw_payloads(64, 2, 1)[0],
            headers={"traceparent": make_traceparent(tid)})
        assert st == 204, f"traced write got {st}: {pay}"
        assert pay["_headers"]["X-Trace-Id"] == tid
        evs = collector.trace(tid)
        by_node = {}
        for e in evs:
            leaf = e["span"].rsplit(".", 1)[-1]
            by_node.setdefault(e.get("node", ""), set()).add(leaf)
        a_spans = by_node.get("A", set())
        b_spans = by_node.get("B", set())
        stitched = (
            {"remote_write", "wal_append", "wal_commit_wait",
             "replication_fanout", "replica_append",
             "ingest_columns"} <= a_spans
            and {"wal_append", "ingest_columns"} <= b_spans)
        out["ingest_trace_spans"] = len(evs)
        out["ingest_trace_nodes"] = sorted(by_node)
        out["ingest_trace_stitched"] = bool(stitched)
        if not stitched:
            out["ingest_trace_span_tree"] = {
                n: sorted(s) for n, s in by_node.items()}

        # --- fault drill: delay node A's group-commit fsync; the delay
        # must surface in the fsync histogram, the ingest slowlog, the
        # freshness histograms, AND the health verdict (sustained)
        from filodb_tpu.utils.faults import faults
        delay = 0.25
        fsync_hist = registry.histogram("wal_fsync_seconds",
                                        dataset="tracetest")
        ack_hist = registry.histogram("ingest_ack_seconds", ws=ws,
                                      origin="remote_write")
        with faults.plan("wal.fsync", "delay", first_k=8,
                         delay_s=delay):
            for p in _rw_payloads(64, 2, 4, ws=ws):
                st, _ = srv.api.handle("POST", "/api/v1/write", {}, p)
                assert st == 204
        slow_recs = [r for r in ingestlog.entries()
                     if r["stages"]["wal_commit_wait_s"] >= delay * 0.5
                     and r["trace_id"]]
        fresh_hist = registry.histogram("ingest_freshness_seconds",
                                        ws=ws)
        health = srv.api.handle("GET", "/api/v1/status/health",
                                {}, b"")[1]["data"]
        ingest_verdict = health["subsystems"]["ingest"]
        fault_visible = (fsync_hist.max >= delay * 0.8
                         and len(slow_recs) >= 3
                         and ack_hist.max >= delay * 0.8
                         and fresh_hist.count >= 4
                         and ingest_verdict["status"] == "degraded"
                         and health["status"] != "ok")
        out["ingesttrace_fault_visible"] = bool(fault_visible)
        out["ingest_freshness_p99_s"] = round(
            ack_hist.percentile(0.99), 4)
        if not fault_visible:
            out["ingesttrace_fault_detail"] = {
                "fsync_max_s": round(fsync_hist.max, 4),
                "slow_recs": len(slow_recs),
                "ack_max_s": round(ack_hist.max, 4),
                "freshness_count": fresh_hist.count,
                "ingest_verdict": ingest_verdict}
    finally:
        proc.kill()
        proc.wait(timeout=10)
        if srv is not None:
            srv.shutdown()
        freshness.reset()
        freshness.configure(threshold_s=5.0, breach_count=3,
                            window_s=60.0)
        shutil.rmtree(root, ignore_errors=True)

    out["ingesttrace_gate_ok"] = bool(
        out.get("ingest_trace_stitched")
        and out.get("ingesttrace_fault_visible")
        and (quick or overhead_ok))
    return out


COVERAGE_QUERIES = [
    # (name, promql, ragged_ok) — a realistic dashboard mix, expanded from
    # the reference's QueryInMemoryBenchmark set (QUERY_SET in bench/suite).
    # r4: the rate family and instant selectors take ragged working sets
    # (valid-boundary kernel scans / validity one-hots)
    ("sum_rate", 'sum(rate(request_total[5m]))', True),
    ("sum_by_rate", 'sum by (_ns_)(rate(request_total[5m]))', True),
    ("avg_rate", 'avg by (_ns_)(rate(request_total[5m]))', True),
    ("max_rate", 'max by (_ns_)(rate(request_total[5m]))', True),
    ("count_rate", 'count by (_ns_)(rate(request_total[5m]))', True),
    ("sum_increase", 'sum(increase(request_total[5m]))', True),
    ("instant_sum", 'sum by (_ns_)(heap_usage)', True),
    ("sum_over_time", 'sum(sum_over_time(heap_usage[5m]))', True),
    ("avg_over_time", 'avg by (_ns_)(avg_over_time(heap_usage[5m]))',
     True),
    ("count_over_time", 'sum(count_over_time(heap_usage[5m]))', True),
    ("min_over_time", 'min by (_ns_)(min_over_time(heap_usage[5m]))',
     True),
    ("max_over_time", 'max(max_over_time(heap_usage[5m]))', True),
    ("hist_quantile",
     'histogram_quantile(0.9, sum(rate(http_latency[5m])) by (_ns_))',
     False),
]


def measure_fused_coverage():
    """Fraction of the realistic query mix that actually engages a fused
    leaf path (kernel, host fast path, or reduce_window) — measured on a
    live engine, not inferred from the eligibility table.  Runs the same
    mix against a NaN-holed (ragged) working set for the kinds that admit
    it (VERDICT r2 item 2 'emit a fused_coverage fraction')."""
    os.environ["FILODB_TPU_FUSED_INTERPRET"] = "1"
    import numpy as _np

    from filodb_tpu.core.memstore import TimeSeriesMemStore
    from filodb_tpu.core.records import RecordBatch
    from filodb_tpu.ingest.generator import (counter_batch, gauge_batch,
                                             histogram_batch)
    from filodb_tpu.parallel.shardmapper import ShardEvent, ShardMapper
    from filodb_tpu.query.engine import QueryEngine
    from filodb_tpu.utils.metrics import registry

    START = 1_600_000_000_000
    S, T = 64, 240

    def mk_engine(ragged):
        ms = TimeSeriesMemStore()
        sh = ms.setup("prometheus", 0)
        cb = counter_batch(S, T, start_ms=START)
        gb = gauge_batch(S, T, start_ms=START)
        if ragged:
            # production-shaped working set: scrape gaps in counters AND
            # gauges (r4: the rate family fuses over these too)
            def hole(b, col, seed):
                vals = b.columns[col].copy()
                vals[np.random.default_rng(seed).random(vals.shape)
                     < 0.1] = _np.nan
                return RecordBatch(b.schema, b.part_keys, b.part_idx,
                                   b.timestamps, {col: vals},
                                   b.bucket_les)
            cb = hole(cb, "count", 4)
            gb = hole(gb, "value", 5)
        sh.ingest(cb)
        sh.ingest(gb)
        try:
            sh.ingest(histogram_batch(16, T, start_ms=START))
        except Exception:  # noqa: BLE001 — hist generator optional
            pass
        mapper = ShardMapper(1)
        mapper.update_from_event(
            ShardEvent("IngestionStarted", "prometheus", 0, "b"))
        return QueryEngine("prometheus", ms, mapper)

    counters = ("leaf_fused_kernel", "leaf_fused_count_host",
                "leaf_fused_minmax", "leaf_host_routed")

    def fused_total():
        return sum(registry.counter(c).value for c in counters)

    results = {}
    for mode, ragged in (("dense", False), ("ragged", True)):
        eng = mk_engine(ragged)
        s = START // 1000
        engaged = []
        for name, q, ragged_ok in COVERAGE_QUERIES:
            res = eng.query_range(q, s + 600, 60, s + T * 10)
            if res.error is not None:
                continue
            before = fused_total()
            eng.query_range(q, s + 600, 60, s + T * 10)  # mirror warm now
            if fused_total() > before:
                engaged.append(name)
        applicable = [n for n, _, r_ok in COVERAGE_QUERIES
                      if not ragged or r_ok]
        results[f"fused_coverage_{mode}"] = round(
            len([n for n in engaged if n in applicable])
            / max(len(applicable), 1), 3)
        results[f"fused_engaged_{mode}"] = engaged
    return results


def measure_dashboard_batch(platform):
    """Ops-level dashboard batching (r4): 8 aggregation panels over ONE
    65k working set — merged multi-hot dispatch (fused_leaf_agg_batch)
    vs one dispatch per panel (fused_leaf_agg).  A fused query through
    the tunnel is dispatch-bound (doc/kernels.md), so this is the
    dashboard-latency number; on-chip reference capture:
    TPU_BATCH_r04.json (4.71x at 262k)."""
    from filodb_tpu.ops import pallas_fused as pf
    from filodb_tpu.ops.timewindow import make_window_ends
    interpret = platform != "tpu"
    if interpret and not os.environ.get("FILODB_TPU_FUSED_INTERPRET"):
        return {"skipped": "kernel is MXU-targeted; no TPU backend"}
    S, T, iters = 65_536, 720, 7
    ts_row, vals = make_counter_data(S, T)
    vbase64 = vals[:, 0].astype(np.float64)
    vals32 = (vals.astype(np.float64) - vbase64[:, None]).astype(np.float32)
    vbase32 = vbase64.astype(np.float32)
    wends = make_window_ends(600_000, int(ts_row[-1]), 60_000)
    plan = pf.build_plan(ts_row.astype(np.int64),
                         np.asarray(wends, np.int64), 300_000)
    pv = pf.pad_values(vals32, vbase32, plan)
    groupings = [(1000, "sum"), (100, "avg"), (10, "sum"), (8, "sum"),
                 (500, "sum"), (50, "avg"), (250, "sum"), (2, "sum")]
    panels = [(pf.pad_groups((np.arange(S) % g).astype(np.int32), S, g),
               g, op) for g, op in groupings]

    def batched():
        return pf.fused_leaf_agg_batch(plan, pv, panels, "rate",
                                       precorrected=True,
                                       interpret=interpret, ragged=False,
                                       num_series=S)

    # host copies OUTSIDE the timed region: fused_leaf_agg only takes
    # len(gids) from this, and a per-iteration device pull would bias
    # sequential_p50_s (and so the speedup) upward
    gids_rows = [np.asarray(groups.gids_p[:S, 0]) for groups, _, _ in panels]

    def sequential():
        out = []
        for (g, op), (groups, G, _), grow in zip(groupings, panels,
                                                 gids_rows):
            prep = pf.PreparedInputs(pv.vals_p, pv.vbase_p,
                                     groups.gids_p, groups.gsize)
            out.append(pf.fused_leaf_agg(
                plan, prep, grow, G, "rate", op,
                precorrected=True, interpret=interpret))
        return out

    st = {"series": S, "panels": len(panels),
          "total_groups": sum(g for g, _ in groupings)}
    t0 = time.perf_counter()
    got_b = batched()
    st["batched_compile_s"] = round(time.perf_counter() - t0, 2)
    t0 = time.perf_counter()
    got_s = sequential()
    st["sequential_compile_s"] = round(time.perf_counter() - t0, 2)
    for name, fn in (("batched", batched), ("sequential", sequential)):
        ts = []
        for _ in range(iters):
            t1 = time.perf_counter()
            fn()
            ts.append(time.perf_counter() - t1)
        ts.sort()
        st[f"{name}_p50_s"] = round(ts[len(ts) // 2], 5)
    st["speedup_p50"] = round(st["sequential_p50_s"]
                              / st["batched_p50_s"], 2)
    st["max_rel_err_batched_vs_sequential"] = max(
        float(np.nanmax(np.abs(b - q) / np.maximum(np.abs(q), 1e-6)))
        for b, q in zip(got_b, got_s))
    return st


def _frontend_fixture(S, T, dataset):
    """Shared workload for the query_frontend and observability stages:
    one live store of S counter series x T 10s scrapes, a QueryFrontend
    over it, and the dashboard-panel query — ONE definition so the two
    acceptance stages can never silently measure different workloads.
    Returns (frontend, engine, query, start_s, end_s, planner_params)."""
    import numpy as np

    from filodb_tpu.core.memstore import TimeSeriesMemStore
    from filodb_tpu.ingest.generator import counter_batch
    from filodb_tpu.query.engine import QueryEngine
    from filodb_tpu.query.frontend import QueryFrontend
    from filodb_tpu.query.rangevector import PlannerParams

    START = 1_600_000_000_000
    ms = TimeSeriesMemStore()
    sh = ms.setup(dataset, 0)
    base = counter_batch(S, 1, start_ms=START)
    row_base = np.arange(S, dtype=np.float64)[:, None]
    for t0 in range(0, T, 40):
        n = min(40, T - t0)
        ts2d = np.broadcast_to(
            START + (t0 + np.arange(n, dtype=np.int64)) * 10_000, (S, n))
        vals = (t0 + np.arange(n, dtype=np.float64))[None, :] * 5.0 \
            + row_base
        sh.ingest_columns("prom-counter", base.part_keys, ts2d,
                          {"count": vals}, offset=t0)
    eng = QueryEngine(dataset, ms)
    fe = QueryFrontend(eng)
    pp = PlannerParams(sample_limit=2_000_000_000, scan_limit=2_000_000_000)
    q = 'sum by (_ns_)(rate(request_total[5m]))'
    s = START // 1000
    start_s, end_s = s + 600, s + (T - 1) * 10   # end == newest sample
    return fe, eng, q, start_s, end_s, pp


def measure_query_frontend(quick=False, series=None, iters=7):
    """Query-serving frontend (PR 2): cached re-poll latency and
    concurrent dashboard-repeat QPS against the sequential no-frontend
    baseline, on one live store at the 262k-series acceptance scale
    (8k under --quick).

    Two numbers ride into the one-line JSON:
      cached_repoll_p50_s — warm identical re-poll through the frontend
        (result-cache hit) vs cold_p50_s (cache cleared per iteration;
        kernel/mirror caches warm in both, so the delta is the frontend's)
      concurrent_qps — 8 threads polling one dashboard panel through the
        frontend (singleflight + cache) vs sequential_baseline_qps (one
        thread straight into the engine: the pre-frontend serving path)
    """
    import threading

    from filodb_tpu.utils.metrics import registry

    S = series or (8_192 if quick else 262_144)
    T = 120                              # 20 min of 10s scrapes
    fe, eng, q, start_s, end_s, pp = _frontend_fixture(
        S, T, "bench_frontend")
    r = fe.query_range(q, start_s, 60, end_s, pp)      # warm everything
    if r.error:
        return {"series": S, "error": r.error[:200]}
    st = {"series": S, "samples_per_series": T, "result_series":
          r.num_series}

    def p50(fn, n=iters):
        ts = []
        for _ in range(n):
            t0 = time.perf_counter()
            res = fn()
            ts.append(time.perf_counter() - t0)
            assert res.error is None, res.error
        ts.sort()
        return ts[len(ts) // 2]

    def cold():
        if fe.cache is not None:
            fe.cache.clear()
        return fe.query_range(q, start_s, 60, end_s, pp)

    st["cold_p50_s"] = round(p50(cold), 5)
    fe.query_range(q, start_s, 60, end_s, pp)          # fill the cache
    st["cached_repoll_p50_s"] = round(
        p50(lambda: fe.query_range(q, start_s, 60, end_s, pp)), 5)
    st["repoll_ratio"] = round(
        st["cached_repoll_p50_s"] / max(st["cold_p50_s"], 1e-9), 4)

    # --- concurrent dashboard-repeat QPS vs the pre-frontend baseline ---
    dur_s = 4.0 if quick else 8.0

    def pump(fn):
        stop_t = time.perf_counter() + dur_s
        n = 0
        while time.perf_counter() < stop_t:
            res = fn()
            assert res.error is None, res.error
            n += 1
        return n / dur_s

    # sequential baseline: the serving path before this PR — every poll
    # pays the full engine cost
    st["sequential_baseline_qps"] = round(
        pump(lambda: eng.query_range(q, start_s, 60, end_s, pp)), 1)
    sf0 = registry.counter("query_singleflight_hits").value
    counts = []
    errors = []
    stop_t = [0.0]

    def client():
        n = 0
        while time.perf_counter() < stop_t[0]:
            res = fe.query_range(q, start_s, 60, end_s, pp)
            if res.error is not None:
                # surface, don't swallow: a thread dying silently would
                # leave a passing-looking concurrent_qps behind
                errors.append(res.error)
                break
            n += 1
        counts.append(n)

    threads = [threading.Thread(target=client) for _ in range(8)]
    stop_t[0] = time.perf_counter() + dur_s
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    if errors:
        st["error"] = f"concurrent stage: {errors[0]}"[:200]
        st["concurrent_errors"] = len(errors)
        return st
    st["concurrent_qps"] = round(sum(counts) / max(wall, 1e-9), 1)
    st["concurrent_threads"] = 8
    st["singleflight_hits"] = int(
        registry.counter("query_singleflight_hits").value - sf0)
    st["qps_vs_sequential"] = round(
        st["concurrent_qps"] / max(st["sequential_baseline_qps"], 1e-9), 1)
    return st


def measure_observability(quick=False, series=None):
    """PR 3 acceptance: the span+stats attribution layer must cost <= 5%
    of the query_frontend concurrent QPS.  Same workload shape as
    measure_query_frontend (8 threads polling one panel through the
    frontend: singleflight + result cache + stats accounting), measured
    with the span pipeline ON vs OFF (utils.metrics.set_spans_enabled)
    in interleaved pairs; `span_overhead_pct` rides the one-line JSON.
    Also sanity-checks the stats payload itself: a run whose overhead is
    low because attribution silently broke must not pass."""
    import threading

    from filodb_tpu.utils import metrics as um

    S = series or (4_096 if quick else 65_536)
    T = 120
    fe, eng, q, start_s, end_s, pp = _frontend_fixture(S, T, "bench_obs")
    r = fe.query_range(q, start_s, 60, end_s, pp)
    if r.error:
        return {"series": S, "error": r.error[:200]}
    st = {"series": S}
    # the attribution payload itself must be live before we credit any
    # overhead number: phases populated, scan counters nonzero
    d = r.stats.to_dict()
    st["stats_phases_ok"] = bool(
        d["phases"]["exec_s"] > 0 and d["samplesScanned"] > 0
        and d["phases"]["parse_s"] >= 0 and "cache" in d)

    dur_s = 1.0 if quick else 2.0
    errors = []

    def pump():
        counts = []
        stop_t = time.perf_counter() + dur_s

        def client():
            n = 0
            while time.perf_counter() < stop_t:
                res = fe.query_range(q, start_s, 60, end_s, pp)
                if res.error is not None:
                    # surface, don't swallow (same stance as the
                    # query_frontend stage): a thread dying silently
                    # would ship a passing-looking overhead number
                    errors.append(res.error)
                    break
                n += 1
            counts.append(n)

        threads = [threading.Thread(target=client) for _ in range(8)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return sum(counts) / max(time.perf_counter() - t0, 1e-9)

    on, off = [], []
    try:
        for _ in range(2 if quick else 3):
            um.set_spans_enabled(True)
            on.append(pump())
            um.set_spans_enabled(False)
            off.append(pump())
    finally:
        um.set_spans_enabled(True)
    if errors:
        st["error"] = f"pump: {errors[0]}"[:200]
        st["pump_errors"] = len(errors)
        return st
    on.sort(); off.sort()
    st["qps_spans_on"] = round(on[len(on) // 2], 1)
    st["qps_spans_off"] = round(off[len(off) // 2], 1)
    st["span_overhead_pct"] = round(
        100.0 * (st["qps_spans_off"] - st["qps_spans_on"])
        / max(st["qps_spans_off"], 1e-9), 2)
    return st


def measure_devicetelem(quick=False, series=None, iters=0):
    """ISSUE-18 acceptance: the per-chip device telemetry subsystem
    (utils/devicetelem.py) measured three ways:

      devicetelem_overhead_pct — the kernel ledger's tax on a concurrent
        8-thread ENGINE workload (every poll dispatches real kernels —
        a frontend cache-hit pump would never touch the ledger), telem
        on vs off in interleaved pairs, medians; gate <= 2%.
      devicetelem_fused_overhead_pct — the same tax on the flagship
        single-thread fused scan p50; gate <= 2%.
      the compile-storm drill — 12 distinct shapes through watched_call
        under one trace id: every compile must land in the ledger with
        shape + origin, fill jit_compile_seconds{kernel}, and flip the
        health `device` subsystem to degraded while sustained.
      devicetelem_mesh_reconciled (>= 2 devices only; the standalone
        `bench.py devicetelem` entry forces 8 virtual host devices) —
        per-device ledger mesh_fused counts reconcile 1:1 with
        mesh_fused_perdevice_dispatches and every mesh chip appears in
        the /admin/devices table.

    A parity check (the ?stats=true per-device split sums to the
    device_s phase) must hold before any overhead number is credited —
    a run whose overhead is low because attribution silently broke must
    not pass."""
    import threading

    import jax

    from filodb_tpu.utils import devicetelem as dt
    from filodb_tpu.utils.health import DEGRADED, HealthEvaluator
    from filodb_tpu.utils.metrics import registry, trace_context

    # flagship scale: the ledger's tax is a fixed few-tens-of-us per
    # dispatch, so the honest denominator is the flagship fused scan's
    # real query time, not a toy store whose 3 ms queries inflate the
    # same microseconds into a fake 2%
    S = series or (16_384 if quick else 65_536)
    T = 120
    fe, eng, q, start_s, end_s, pp = _frontend_fixture(
        S, T, "bench_devtelem")
    r = eng.query_range(q, start_s, 60, end_s, pp)   # cold: real kernels
    if r.error:
        return {"series": S, "error": r.error[:200]}
    st = {"series": S}
    d = r.stats.to_dict()
    split = sum(k["seconds"] for dev in d["devices"].values()
                for k in dev.values())
    dev_s = d["phases"]["device_s"]
    st["devicetelem_parity_ok"] = bool(
        abs(split - dev_s) <= max(1e-4, 0.02 * dev_s)
        and (dev_s == 0 or d["devices"]))

    # --- tax on the concurrent engine workload, telem on vs off ---
    dur_s = 1.5 if quick else 3.0
    errors = []

    def pump():
        counts = []
        stop_t = time.perf_counter() + dur_s

        def client():
            n = 0
            while time.perf_counter() < stop_t:
                res = eng.query_range(q, start_s, 60, end_s, pp)
                if res.error is not None:
                    # surface, don't swallow: a thread dying silently
                    # would ship a passing-looking overhead number
                    errors.append(res.error)
                    break
                n += 1
            counts.append(n)

        threads = [threading.Thread(target=client) for _ in range(8)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return sum(counts) / max(time.perf_counter() - t0, 1e-9)

    on, off = [], []
    try:
        pump()                       # discarded: thread/alloc warmup
        # alternate which arm goes first per pair — CPU frequency ramp
        # and cache warmup drift monotonically across the run, and a
        # fixed on-first order books all of that drift against the
        # ledger.  BEST-of-N per arm (timeit methodology): co-tenant
        # interference only ever subtracts throughput, so the max over
        # attempts compares the two arms on the clean machine instead
        # of on whichever arm a noise spike happened to land on.
        for i in range(4 if quick else 5):
            first_on = (i % 2 == 0)
            dt.set_enabled(first_on)
            (on if first_on else off).append(pump())
            dt.set_enabled(not first_on)
            (off if first_on else on).append(pump())
    finally:
        dt.set_enabled(True)
    if errors:
        st["error"] = f"pump: {errors[0]}"[:200]
        st["pump_errors"] = len(errors)
        return st
    st["devicetelem_qps_on"] = round(max(on), 1)
    st["devicetelem_qps_off"] = round(max(off), 1)
    st["devicetelem_overhead_pct"] = round(
        100.0 * (st["devicetelem_qps_off"] - st["devicetelem_qps_on"])
        / max(st["devicetelem_qps_off"], 1e-9), 2)

    # --- tax on the flagship single-thread fused scan ---
    # query-level PAIRED comparison: adjacent queries (ms apart) see
    # near-identical machine state, so per-pair relative deltas cancel
    # the drift that swamps independent p50s; the 20%-trimmed mean
    # drops GC/interrupt outliers without the median's tiny-sample
    # noise.  Order within a pair alternates so toggling cost (if any)
    # can't book against one arm.
    n_pairs = iters or (50 if quick else 40)
    diffs, on_ts, off_ts = [], [], []

    def one():
        t0 = time.perf_counter()
        res = eng.query_range(q, start_s, 60, end_s, pp)
        assert res.error is None, res.error
        return time.perf_counter() - t0

    try:
        for _ in range(3):                      # discarded warmup
            eng.query_range(q, start_s, 60, end_s, pp)
        for i in range(n_pairs):
            first_on = (i % 2 == 0)
            dt.set_enabled(first_on)
            a = one()
            dt.set_enabled(not first_on)
            b = one()
            on_t, off_t = (a, b) if first_on else (b, a)
            on_ts.append(on_t)
            off_ts.append(off_t)
            diffs.append((on_t - off_t) / off_t)
    finally:
        dt.set_enabled(True)
    on_ts.sort(); off_ts.sort(); diffs.sort()
    k = n_pairs // 5
    core = diffs[k:n_pairs - k]
    st["devicetelem_fused_p50_on_s"] = round(on_ts[n_pairs // 2], 5)
    st["devicetelem_fused_p50_off_s"] = round(off_ts[n_pairs // 2], 5)
    st["devicetelem_fused_overhead_pct"] = round(
        100.0 * sum(core) / max(len(core), 1), 2)

    # --- the compile-storm drill: attributable and health-visible ---
    import jax.numpy as jnp
    storm_fn = jax.jit(lambda x: (x * 2.0).sum())
    origin = "benchstorm" + "0" * 22
    n_storm = 12
    c0 = registry.counter("jit_compile_events", fn="bench_storm").value
    with trace_context(origin):
        for i in range(n_storm):
            x = jnp.zeros((i + 31,))
            dt.watched_call("bench_storm", storm_fn, f"S{i + 31}",
                            lambda x=x: storm_fn(x))
    compiled = int(registry.counter("jit_compile_events",
                                    fn="bench_storm").value - c0)
    st["devicetelem_storm_compiles"] = compiled
    mine = [e for e in dt.telem.recent(limit=200, kind="compile")
            if e["kernel"] == "bench_storm"]
    st["devicetelem_storm_attributed"] = bool(
        len(mine) >= n_storm
        and all(e["origin"] == origin and e["shape"] for e in mine))
    hist_count = 0
    for name, tags, value in registry.snapshot_samples():
        if name == "jit_compile_seconds_count" \
                and ("kernel", "bench_storm") in tags:
            hist_count = int(value)
    st["devicetelem_storm_hist_count"] = hist_count
    dv = HealthEvaluator().evaluate()["subsystems"]["device"]
    st["devicetelem_storm_health_degraded"] = bool(
        dv["status"] == DEGRADED and "compile_storm" in dv["reasons"])

    # --- per-chip placement reconcile (multi-device boxes only) ---
    n_dev = jax.local_device_count()
    st["devicetelem_devices"] = n_dev
    if n_dev >= 2:
        from filodb_tpu.core.index import Equals
        from filodb_tpu.ops.timewindow import make_window_ends
        from filodb_tpu.parallel.mesh import MeshExecutor, make_mesh
        n_time = 2 if n_dev % 2 == 0 and n_dev >= 4 else 1
        n_shard = n_dev // n_time
        total = 512 - (512 % n_shard)
        ms, START = _multichip_store("bench_devtelem_mesh", total, T,
                                     n_shard)
        mesh = make_mesh(n_shard, n_time, devices=jax.devices()[:n_dev])
        ex = MeshExecutor(ms, "bench_devtelem_mesh", mesh)
        end_ms = START + (T - 1) * 10_000
        packed = ex.lookup_and_pack(
            [Equals("_metric_", "request_total")], START, end_ms,
            by=("_ns_",), fn_name="rate")
        wends = make_window_ends(START + 600_000, end_ms, 60_000)

        def counts_by_dev():
            snap = dt.telem.snapshot(recent=0)
            return {dev: row["kernels"].get("mesh_fused",
                                            {}).get("count", 0)
                    for dev, row in snap["devices"].items()}

        before = counts_by_dev()
        pc0 = registry.counter("mesh_fused_perdevice_dispatches").value
        # the reconcile needs the PER-DEVICE kernel branch, which the
        # host-platform router diverts to ops/hostleaf (one host pass,
        # no per-chip dispatches) — interpret-mode Pallas restores the
        # real dispatch topology at this deliberately tiny scale
        had_interp = os.environ.get("FILODB_TPU_FUSED_INTERPRET")
        os.environ["FILODB_TPU_FUSED_INTERPRET"] = "1"
        try:
            for _ in range(3):
                ex.run_agg(packed, wends, range_ms=300_000,
                           fn_name="rate", agg_op="sum")
        finally:
            if had_interp is None:
                os.environ.pop("FILODB_TPU_FUSED_INTERPRET", None)
            else:
                os.environ["FILODB_TPU_FUSED_INTERPRET"] = had_interp
        pc_delta = int(registry.counter(
            "mesh_fused_perdevice_dispatches").value - pc0)
        after = counts_by_dev()
        deltas = {dev: after.get(dev, 0) - before.get(dev, 0)
                  for dev in after}
        touched = {dev for dev, v in deltas.items() if v > 0}
        st["devicetelem_mesh_perdevice_dispatches"] = pc_delta
        st["devicetelem_mesh_devices_touched"] = len(touched)
        st["devicetelem_mesh_reconciled"] = bool(
            pc_delta > 0 and sum(deltas.values()) == pc_delta
            and len(touched) >= 2)

    st["devicetelem_gate_ok"] = bool(
        st["devicetelem_overhead_pct"] <= 2.0
        and st["devicetelem_fused_overhead_pct"] <= 2.0
        and st["devicetelem_parity_ok"]
        and compiled >= 10
        and st["devicetelem_storm_attributed"]
        and st["devicetelem_storm_hist_count"] >= 10
        and st["devicetelem_storm_health_degraded"]
        and st.get("devicetelem_mesh_reconciled", True))
    return st


def measure_activequeries(quick=False, series=None):
    """ISSUE-13 acceptance: live query introspection.

    Two halves ride the one-line JSON:
      activequeries_overhead_pct — the registry's tax on the
        query_frontend concurrent-QPS workload (8 threads polling one
        panel), registry ON vs OFF in interleaved pairs (gate: <= 2%).
      the kill drill — a long COLD two-node query (all data flushed to
        the column store; every leaf demand-pages) is listed in the
        registry with live phase/counters on the coordinator AND the
        remote node, then killed mid-execution: the client gets the
        structured query_canceled, the concurrency slot frees (a
        follow-up query admits without queue wait), and the remote
        leaf's counters stop advancing (registry drains) within 250 ms.
    """
    import threading

    from filodb_tpu.config import FilodbSettings
    from filodb_tpu.query.activequeries import active_queries
    from filodb_tpu.query.frontend import QueryFrontend

    st = {}
    # --- half 1: registry overhead on the concurrent-QPS workload ---
    # cache and singleflight are DISABLED for the pump: a cache hit or
    # dedup follower never registers (by design — it pays two thread-
    # local writes), so the honest tax measurement needs every query to
    # take the registration path: scheduler slot -> engine -> exec tree.
    # The pump scale is pinned SMALL (per-query a few ms): the ratio
    # needs thousands of queries per window to resolve a 2% gate — at
    # 65k series a cache-off query costs ~1 s on CPU, so a 2 s pump
    # would measure ~20 queries of noise, not a tax
    S = series or 2_048
    fe0, eng, q, start_s, end_s, pp = _frontend_fixture(S, 120, "bench_aq")
    cfg = FilodbSettings()
    cfg.query.result_cache_enabled = False
    cfg.query.singleflight_enabled = False
    cfg.query.tenant_usage_enabled = False
    fe = QueryFrontend(eng, config=cfg)
    r = fe.query_range(q, start_s, 60, end_s, pp)
    if r.error:
        return {"series": S, "error": r.error[:200]}
    st["series"] = S
    dur_s = 1.0 if quick else 3.0
    errors = []

    def pump():
        counts = []
        stop_t = time.perf_counter() + dur_s

        def client():
            n = 0
            while time.perf_counter() < stop_t:
                res = fe.query_range(q, start_s, 60, end_s, pp)
                if res.error is not None:
                    errors.append(res.error)
                    break
                n += 1
            counts.append(n)

        threads = [threading.Thread(target=client) for _ in range(8)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return sum(counts) / max(time.perf_counter() - t0, 1e-9)

    on, off = [], []
    try:
        # alternate which mode leads each pair: a monotone warm-up
        # drift across the run must not systematically favor the
        # second-of-pair mode
        for i in range(2 if quick else 5):
            for enabled in ((True, False) if i % 2 == 0
                            else (False, True)):
                active_queries.configure(enabled=enabled)
                (on if enabled else off).append(pump())
    finally:
        active_queries.configure(enabled=True)
    if errors:
        st["error"] = f"pump: {errors[0]}"[:200]
        return st
    on.sort(); off.sort()
    st["qps_registry_on"] = round(on[len(on) // 2], 1)
    st["qps_registry_off"] = round(off[len(off) // 2], 1)
    st["activequeries_overhead_pct"] = round(
        100.0 * (st["qps_registry_off"] - st["qps_registry_on"])
        / max(st["qps_registry_off"], 1e-9), 2)

    # --- half 2: the end-to-end kill drill ---
    drill = _activequeries_kill_drill(quick=quick)
    st.update(drill)
    st["activequeries_gate_ok"] = bool(
        drill.get("activequeries_kill_structured")
        and drill.get("activequeries_listed_remote")
        and drill.get("activequeries_slot_freed")
        and (quick or (st["activequeries_overhead_pct"] <= 2.0
                       and drill.get("activequeries_stop_ms", 1e9)
                       <= 250.0)))
    return st


def _activequeries_kill_drill(quick=False):
    """Two in-process nodes over the real cross-node transport, every
    shard COLD (flushed to a column store, memstore recovered from the
    index only), a frontend coordinator with ONE concurrency slot — the
    'query eating the node' scenario the runbook kills."""
    import threading

    from filodb_tpu.config import FilodbSettings
    from filodb_tpu.core.memstore import TimeSeriesMemStore
    from filodb_tpu.core.store import InMemoryColumnStore, InMemoryMetaStore
    from filodb_tpu.gateway.router import split_batch_by_shard
    from filodb_tpu.ingest.generator import gauge_batch
    from filodb_tpu.parallel.shardmapper import (ShardEvent, ShardMapper,
                                                 SpreadProvider)
    from filodb_tpu.parallel.transport import (NodeQueryServer,
                                               RemoteNodeDispatcher)
    from filodb_tpu.query.activequeries import active_queries
    from filodb_tpu.query.engine import QueryEngine
    from filodb_tpu.query.frontend import QueryFrontend
    from filodb_tpu.query.planner import SingleClusterPlanner
    from filodb_tpu.query.rangevector import PlannerParams

    S = 1_024 if quick else 8_192
    T = 240
    num_shards = 4
    mapper = ShardMapper(num_shards)
    spread = SpreadProvider(default_spread=1)
    owner = {s: ("nodeA" if s < num_shards // 2 else "nodeB")
             for s in range(num_shards)}
    batch = gauge_batch(S, T)
    cold_stores = {}
    for node in ("nodeA", "nodeB"):
        cs, meta = InMemoryColumnStore(), InMemoryMetaStore()
        warm = TimeSeriesMemStore(column_store=cs, meta_store=meta)
        for s, n in owner.items():
            if n == node:
                warm.setup("prometheus", s)
                mapper.update_from_event(
                    ShardEvent("IngestionStarted", "prometheus", s, n))
        for s, sub in split_batch_by_shard(batch, mapper, spread).items():
            if owner[s] == node:
                warm.get_shard("prometheus", s).ingest(sub)
        for s, n in owner.items():
            if n == node:
                warm.get_shard("prometheus", s).flush_all_groups()
        # the COLD node: index recovered, zero resident samples — every
        # query demand-pages through the cancellable loop
        cold = TimeSeriesMemStore(column_store=cs, meta_store=meta)
        for s, n in owner.items():
            if n == node:
                cold.setup("prometheus", s).recover_index()
        cold_stores[node] = cold
    servers = {n: NodeQueryServer(st_).start()
               for n, st_ in cold_stores.items()}
    dispatchers = {n: RemoteNodeDispatcher(*srv.address)
                   for n, srv in servers.items()}
    planner = SingleClusterPlanner(
        "prometheus", mapper, spread,
        dispatcher_factory=lambda s: dispatchers[owner[s]])
    eng = QueryEngine("prometheus", TimeSeriesMemStore(), mapper,
                      planner=planner)
    cfg = FilodbSettings()
    cfg.query.max_concurrent_queries = 1
    cfg.query.result_cache_enabled = False
    cfg.query.tenant_usage_enabled = False
    fe = QueryFrontend(eng, config=cfg)
    pp = PlannerParams(sample_limit=2_000_000_000,
                       scan_limit=2_000_000_000)
    s0 = 1_600_000_000
    out = {}
    res_box = {}

    def victim():
        res_box["res"] = fe.query_range(
            "avg by (_ns_)(avg_over_time(heap_usage[5m]))",
            s0 + 300, 30, s0 + (T - 1) * 10, pp)

    try:
        t = threading.Thread(target=victim)
        t.start()
        # wait for the distributed query to be LIVE: the coordinator
        # entry past the queue AND a remote-role entry with counters
        listed_remote = False
        coord_ent = None
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            ents = active_queries.entries()
            for e in ents:
                if e.role == "frontend" and e.phase in ("executing",
                                                        "gathering"):
                    coord_ent = e
                if e.role == "remote":
                    listed_remote = True
            if coord_ent is not None and listed_remote:
                break
            time.sleep(0.002)
        out["activequeries_listed_remote"] = bool(
            coord_ent is not None and listed_remote)
        if coord_ent is None:
            out["activequeries_error"] = \
                "victim query never reached execution"
            return out
        t_kill = time.perf_counter()
        fe_kill = active_queries.kill(coord_ent.query_id, reason="admin")
        out["activequeries_kill_fanout_nodes"] = \
            len(fe_kill.get("remoteNodes", []))
        t.join(timeout=30)
        out["activequeries_kill_to_client_ms"] = round(
            (time.perf_counter() - t_kill) * 1e3, 1)
        res = res_box.get("res")
        out["activequeries_kill_structured"] = bool(
            res is not None and res.error is not None
            and res.error.startswith("query_canceled"))
        # remote leaves must STOP: all entries under the id drain (their
        # counters cannot advance after deregistration)
        stop_deadline = time.monotonic() + 5.0
        while active_queries.get(coord_ent.query_id) \
                and time.monotonic() < stop_deadline:
            time.sleep(0.002)
        out["activequeries_stop_ms"] = round(
            (time.perf_counter() - t_kill) * 1e3, 1)
        out["activequeries_remote_drained"] = \
            not active_queries.get(coord_ent.query_id)
        # the slot freed: a follow-up query admits with no queue wait
        # (1-slot semaphore — a leaked slot would park it for the full
        # ask timeout)
        res2 = fe.query_range("count(heap_usage)", s0 + 300, 60,
                              s0 + 600, pp)
        out["activequeries_followup_queue_wait_s"] = round(
            res2.stats.queue_wait_s, 4)
        out["activequeries_slot_freed"] = bool(
            res2.error is None and res2.stats.queue_wait_s < 0.5)
    finally:
        for srv in servers.values():
            srv.stop()
    return out


def measure_selfmon(quick=False, series=None):
    """ISSUE-10 acceptance: self-scrape meta-monitoring must cost <= 2%
    of the concurrent-QPS number at the default `selfmon.interval_s`.
    Same 8-thread dashboard-repeat workload as the query_frontend /
    observability stages, measured in interleaved pairs with the
    self-scrape loop ON vs OFF.  Each ON pump window contains exactly
    ONE scrape (the loop's immediate first scrape — including its
    result-cache invalidation, the expensive part: the write moves the
    append horizon, so the next re-poll per thread recomputes the grid
    tail), so the raw pair delta is the cost of one scrape amortized
    over the pump window.  Steady state runs one scrape per
    `selfmon.interval_s` (default 15 s), so the headline
    `selfmon_overhead_pct` normalizes the raw delta by
    pump_window / interval; the raw number rides along as
    `selfmon_overhead_raw_pct`.  Plus the scrape itself timed directly
    (`selfmon_scrape_p50_s`) and a sanity check that the scraped series
    actually ARE queryable through PromQL — a run whose overhead is low
    because the scrape silently wrote nothing must not pass."""
    import threading

    from filodb_tpu.config import SelfMonConfig
    from filodb_tpu.utils.selfmon import SelfScraper

    S = series or (4_096 if quick else 65_536)
    T = 120
    fe, eng, q, start_s, end_s, pp = _frontend_fixture(S, T, "bench_selfmon")
    r = fe.query_range(q, start_s, 60, end_s, pp)
    if r.error:
        return {"series": S, "error": r.error[:200]}
    st = {"series": S}

    # --- the scrape itself, timed directly (no loop thread)
    scraper = SelfScraper(eng.source, "bench_selfmon",
                          node_name="bench",
                          interval_s=SelfMonConfig().interval_s)
    times = []
    for _ in range(3 if quick else 7):
        t0 = time.perf_counter()
        n = scraper.scrape_once()
        times.append(time.perf_counter() - t0)
    times.sort()
    st["selfmon_scrape_p50_s"] = round(times[len(times) // 2], 5)
    st["selfmon_scrape_series"] = n
    if n <= 0:
        st["error"] = "self-scrape wrote zero series"
        return st

    # --- the scraped series must be PromQL-queryable via the ordinary
    # engine path (the entire point of self-scraping); +1 s because the
    # instant API floors to whole seconds and looks back, never forward
    chk = eng.query_instant("selfmon_samples_total", int(time.time()) + 1)
    if chk.error or chk.num_series == 0:
        st["error"] = (f"self-scraped series not queryable: "
                       f"{chk.error or 'no series'}")[:200]
        return st

    dur_s = 1.0 if quick else 2.0
    errors = []

    def pump():
        counts = []
        stop_t = time.perf_counter() + dur_s

        def client():
            c = 0
            while time.perf_counter() < stop_t:
                res = fe.query_range(q, start_s, 60, end_s, pp)
                if res.error is not None:
                    errors.append(res.error)
                    break
                c += 1
            counts.append(c)

        threads = [threading.Thread(target=client) for _ in range(8)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return sum(counts) / max(time.perf_counter() - t0, 1e-9)

    on, off = [], []
    for _ in range(2 if quick else 3):
        live = SelfScraper(eng.source, "bench_selfmon",
                           node_name="bench",
                           interval_s=SelfMonConfig().interval_s)
        live.start()                     # immediate first scrape, then 15 s
        try:
            on.append(pump())
        finally:
            live.stop()
        off.append(pump())
    if errors:
        st["error"] = f"pump: {errors[0]}"[:200]
        return st
    on.sort(); off.sort()
    st["selfmon_qps_on"] = round(on[len(on) // 2], 1)
    st["selfmon_qps_off"] = round(off[len(off) // 2], 1)
    raw = 100.0 * (st["selfmon_qps_off"] - st["selfmon_qps_on"]) \
        / max(st["selfmon_qps_off"], 1e-9)
    st["selfmon_overhead_raw_pct"] = round(raw, 2)
    # one scrape per pump window measured -> one per interval_s steady
    # state: normalize the per-scrape cost to the default cadence
    interval = SelfMonConfig().interval_s
    st["selfmon_interval_s"] = interval
    st["selfmon_overhead_pct"] = round(raw * dur_s / interval, 2)
    st["selfmon_gate_ok"] = bool(st["selfmon_overhead_pct"] <= 2.0)
    return st


def measure_qos(quick=False, series=None):
    """ISSUE-14 acceptance: multi-tenant QoS under overload — the
    noisy-neighbor drill.

    Five tenants share one frontend (cache + singleflight OFF so every
    query contends for real scheduler slots): four well-behaved
    tenants poll their own dashboard panel back-to-back; the abuser
    floods the frontend from 8 threads at full concurrency with a
    dashboard storm of short panels (the classic noisy-neighbor shape:
    thousands of cheap queries saturating every slot).  Phases:

      idle  — the good tenants poll alone: their baseline p99.
      noisy — the abuser floods while the good tenants keep polling.

    Gate (qos_gate_ok): the good tenants' p99 stays within 1.5x of
    their idle p99 (weighted-fair dispatch kept their slots coming),
    the abuser receives structured `tenant_overloaded` 429s WITH a
    Retry-After value (adaptive shedding engaged — never silent queue
    starvation), and the abuser never hits `query_timeout` (doomed
    queries are shed at admission, not left to die in the queue).

    Scheduler capacity scales with the host's cores: concurrent
    EXECUTIONS share the machine, and a capacity past the core count
    measures CPU timeslicing, not admission fairness (on the 1-core
    bench boxes capacity is 1 — the drill's point is who gets the next
    slot, not how many run at once).
    """
    import threading

    from filodb_tpu.config import FilodbSettings
    from filodb_tpu.core.memstore import TimeSeriesMemStore
    from filodb_tpu.ingest.generator import gauge_part_keys
    from filodb_tpu.query.engine import QueryEngine
    from filodb_tpu.query.frontend import QueryFrontend
    from filodb_tpu.query.rangevector import PlannerParams

    S = series or (1_024 if quick else 4_096)
    T = 120
    START = 1_600_000_000_000
    goods = ["good0", "good1", "good2", "good3"]
    tenants = goods + ["abuser"]
    capacity = max(1, min(8, os.cpu_count() or 1))
    st = {"series": S, "tenants": len(tenants),
          "qos_capacity": capacity}
    ms = TimeSeriesMemStore()
    sh = ms.setup("bench_qos", 0)
    row_base = np.arange(S, dtype=np.float64)[:, None]
    for ws in tenants:
        keys = gauge_part_keys(S, metric="request_total", ws=ws)
        for t0 in range(0, T, 40):
            n = min(40, T - t0)
            ts2d = np.broadcast_to(
                START + (t0 + np.arange(n, dtype=np.int64)) * 10_000,
                (S, n))
            vals = (t0 + np.arange(n, dtype=np.float64))[None, :] * 5.0 \
                + row_base
            sh.ingest_columns("prom-counter", keys, ts2d,
                              {"count": vals}, offset=t0)
    eng = QueryEngine("bench_qos", ms)
    cfg = FilodbSettings()
    cfg.query.result_cache_enabled = False
    cfg.query.singleflight_enabled = False
    cfg.query.max_concurrent_queries = capacity
    cfg.query.tenant_max_queue_depth = 4
    fe = QueryFrontend(eng, config=cfg)
    pp = PlannerParams(sample_limit=2_000_000_000,
                       scan_limit=2_000_000_000)
    s0 = START // 1000
    start_s, end_s = s0 + 600, s0 + (T - 1) * 10
    ab_end_s = s0 + 660                   # the abuser's short panel

    def q_of(ws):
        return f'sum by (_ns_)(rate(request_total{{_ws_="{ws}"}}[5m]))'

    for ws in goods:                      # warm compile/mirror per shape
        r = fe.query_range(q_of(ws), start_s, 60, end_s, pp)
        if r.error:
            st["error"] = f"warmup[{ws}]: {r.error}"[:200]
            return st
    r = fe.query_range(q_of("abuser"), start_s, 60, ab_end_s, pp)
    if r.error:
        st["error"] = f"warmup[abuser]: {r.error}"[:200]
        return st
    dur_s = 1.5 if quick else 5.0
    good_errors = []

    good_waits = []

    def good_loop(ws, lats, stop_t):
        while time.perf_counter() < stop_t:
            t0 = time.perf_counter()
            res = fe.query_range(q_of(ws), start_s, 60, end_s, pp)
            lats.append(time.perf_counter() - t0)
            good_waits.append(res.stats.queue_wait_s)
            if res.error is not None:
                good_errors.append(f"{ws}: {res.error}"[:200])
                return

    def run_goods(extra=()):
        lats = {ws: [] for ws in goods}
        stop_t = time.perf_counter() + dur_s
        threads = [threading.Thread(target=good_loop,
                                    args=(ws, lats[ws], stop_t))
                   for ws in goods]
        threads += list(extra)
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return [x for ws in goods for x in lats[ws]]

    def p99(xs):
        xs = sorted(xs)
        return xs[min(int(0.99 * len(xs)), len(xs) - 1)] if xs else 0.0

    # --- phase 1: idle baseline ---
    idle = run_goods()
    good_waits.clear()                   # keep only the noisy phase's
    # --- phase 2: the abuser floods at full concurrency ---
    abuse = {"shed": 0, "timeouts": 0, "completed": 0, "other": 0,
             "retry_bad": 0}
    alock = threading.Lock()
    stop_abuse = threading.Event()

    import random as _random

    def abuser_loop():
        rng = _random.Random(id(threading.current_thread()))
        while not stop_abuse.is_set():
            res = fe.query_range(q_of("abuser"), start_s, 60, ab_end_s,
                                 pp)
            err = res.error or ""
            with alock:
                if not err:
                    abuse["completed"] += 1
                elif err.startswith("tenant_overloaded"):
                    abuse["shed"] += 1
                    if not (getattr(res, "retry_after_s", 0.0) > 0.0):
                        abuse["retry_bad"] += 1
                elif err.startswith("query_timeout"):
                    abuse["timeouts"] += 1
                else:
                    abuse["other"] += 1
            if err.startswith("tenant_overloaded"):
                # a minimally-compliant client: back off briefly on a
                # 429 (NOT the full Retry-After — the drill needs
                # sustained flood pressure, just not a shed spin-loop
                # that would measure interpreter contention, not QoS);
                # jittered so 8 threads don't wake in lockstep
                time.sleep(0.02 * (0.5 + rng.random()))

    flood = [threading.Thread(target=abuser_loop, daemon=True)
             for _ in range(8)]
    for t in flood:
        t.start()
    time.sleep(0.3)                      # let the flood saturate first
    noisy = run_goods()
    stop_abuse.set()
    for t in flood:
        t.join(timeout=5)
    if good_errors:
        st["error"] = f"good tenant failed: {good_errors[0]}"[:200]
        return st
    st["qos_good_polls_idle"] = len(idle)
    st["qos_good_polls_noisy"] = len(noisy)
    # how much of the noisy-phase latency was SCHEDULER wait (vs the
    # execution itself) — the diagnostic that says whether a ratio
    # regression is queueing or CPU contention
    st["qos_good_queue_wait_p99_s"] = round(p99(list(good_waits)), 5)
    st["qos_good_p99_idle_s"] = round(p99(idle), 5)
    st["qos_good_p99_noisy_s"] = round(p99(noisy), 5)
    st["qos_p99_ratio"] = round(
        p99(noisy) / max(p99(idle), 1e-9), 3)
    st["qos_abuser_shed"] = abuse["shed"]
    st["qos_abuser_timeouts"] = abuse["timeouts"]
    st["qos_abuser_completed"] = abuse["completed"]
    st["qos_abuser_other_errors"] = abuse["other"]
    st["qos_shed_retry_after_ok"] = bool(abuse["shed"] > 0
                                         and abuse["retry_bad"] == 0)
    # correctness halves of the gate always hold; the p99 ratio is
    # judged at FULL scale only (quick's short phases are too noisy)
    st["qos_gate_ok"] = bool(
        abuse["shed"] > 0 and abuse["timeouts"] == 0
        and abuse["other"] == 0 and st["qos_shed_retry_after_ok"]
        and abuse["completed"] > 0
        and (quick or st["qos_p99_ratio"] <= 1.5))
    return st


def measure_ruler(quick=False, series=None):
    """PR 5 acceptance: the ruler as a precompute engine.  A group of 8
    aggregation rules (the dashboard-panel shapes) evaluates against the
    live store at ticks spanning the query window, then:

      ruler_eval_p50_s         — one full group iteration (8 instant
                                 queries through the frontend + columnar
                                 write-back) at the acceptance scale
      recorded_query_speedup_x — the SAME dashboard aggregate served
                                 from the recorded series vs evaluating
                                 the raw expression over the range
                                 (gate: >= 10x — the entire point of
                                 recording rules)
      ruler_overhead_pct       — frontend QPS with the ruler's
                                 evaluation loops live vs stopped (the
                                 standing-query tax on serving traffic,
                                 result-cache invalidation churn from
                                 the write-backs included)
    """
    import threading

    from filodb_tpu.config import RulesConfig
    from filodb_tpu.rules import MemstoreSink, Ruler, WebhookNotifier
    from filodb_tpu.rules.config import Rule, RuleGroup

    S = series or (8_192 if quick else 262_144)
    T = 120
    fe, eng, q, start_s, end_s, pp = _frontend_fixture(S, T, "bench_ruler")
    rules = tuple(
        Rule(name, expr, "recording") for name, expr in [
            ("ns:request_total:rate5m",
             "sum by (_ns_)(rate(request_total[5m]))"),
            ("dc:request_total:rate5m",
             "sum by (dc)(rate(request_total[5m]))"),
            ("total:request_total:rate5m",
             "sum(rate(request_total[5m]))"),
            ("ns:request_total:avg_rate5m",
             "avg by (_ns_)(rate(request_total[5m]))"),
            ("ns:request_total:max_rate5m",
             "max by (_ns_)(rate(request_total[5m]))"),
            ("dc:request_total:increase1m",
             "sum by (dc)(increase(request_total[1m]))"),
            ("ns:request_total:series",
             "count by (_ns_)(rate(request_total[5m]))"),
            ("total:recorded:rate5m",      # 2nd-order: reads rule 1
             "sum(ns:request_total:rate5m)"),
        ])
    group = RuleGroup("bench", 30.0, rules)
    ruler = Ruler(fe, MemstoreSink(eng.source, "bench_ruler"),
                  groups=[group], config=RulesConfig(),
                  notifier=WebhookNotifier(sleep=lambda s: None))
    st = {"series": S, "rules": len(rules)}

    # materialize the recorded series across the query window (30s
    # ticks), timing each full iteration
    ticks = list(range(start_s, end_s + 1, 30))
    durs = []
    for ts in ticks:
        t0 = time.perf_counter()
        if not ruler.evaluate_group("bench", ts=ts):
            bad = [r["lastError"]
                   for r in ruler.rules_payload()["groups"][0]["rules"]
                   if r["lastError"]]
            return {**st, "error": f"rule eval failed: {bad[:1]}"[:200]}
        durs.append(time.perf_counter() - t0)
    durs.sort()
    st["iterations"] = len(ticks)
    st["ruler_eval_p50_s"] = round(durs[len(durs) // 2], 5)

    # the dashboard aggregate from the recorded series vs the raw expr
    def p50(fn, n=5):
        ts = []
        for _ in range(n):
            t0 = time.perf_counter()
            res = fn()
            if res.error:
                raise RuntimeError(res.error)
            ts.append(time.perf_counter() - t0)
        ts.sort()
        return ts[len(ts) // 2]

    raw_p50 = p50(lambda: eng.query_range(
        "sum by (_ns_)(rate(request_total[5m]))", start_s, 30, end_s, pp))
    rec_p50 = p50(lambda: eng.query_range(
        "ns:request_total:rate5m", start_s, 30, end_s, pp))
    st["raw_aggregate_p50_s"] = round(raw_p50, 5)
    st["recorded_aggregate_p50_s"] = round(rec_p50, 5)
    st["recorded_query_speedup_x"] = round(raw_p50 / max(rec_p50, 1e-9), 1)

    # serving overhead: frontend QPS with the evaluation loops live vs
    # stopped.  The ruler's clock is pinned into the data window so the
    # rules do real work; a short interval keeps several iterations
    # inside the measurement window.
    dur_s = 2.0 if quick else 4.0
    errors = []

    def pump(seconds=None):
        counts = []
        stop_t = time.perf_counter() + (seconds or dur_s)

        def client():
            n = 0
            while time.perf_counter() < stop_t:
                res = fe.query_range(q, start_s, 60, end_s, pp)
                if res.error is not None:
                    errors.append(res.error)
                    break
                n += 1
            counts.append(n)

        threads = [threading.Thread(target=client) for _ in range(4)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return sum(counts) / max(time.perf_counter() - t0, 1e-9)

    pump(1.0)     # warm the serving path: off/on must differ only by
    qps_off = pump()  # the ruler, not by who ran first on cold caches
    interval = max(1.0, 2.0 * st["ruler_eval_p50_s"])
    offset = end_s - time.time()
    live = Ruler(fe, MemstoreSink(eng.source, "bench_ruler"),
                 groups=[RuleGroup("bench", interval, rules)],
                 config=RulesConfig(),
                 notifier=WebhookNotifier(sleep=lambda s: None),
                 clock=lambda: time.time() + offset)
    live.start()
    try:
        # the loop's first tick lands anywhere up to one interval +
        # stagger after start(): measure over >= 1.5 intervals so the
        # window is guaranteed to contain evaluations — otherwise a
        # short pump can miss the phase entirely and report ~0 overhead
        qps_on = pump(max(dur_s, 1.5 * interval))
    finally:
        live.stop()
    if errors:
        st["error"] = f"pump: {errors[0]}"[:200]
        return st
    st["qps_ruler_off"] = round(qps_off, 1)
    st["qps_ruler_on"] = round(qps_on, 1)
    st["ruler_overhead_pct"] = round(
        100.0 * (qps_off - qps_on) / max(qps_off, 1e-9), 2)
    return st


def _multichip_block(START, t0, n, r0, r1):
    """One [r1-r0, n] (timestamps, values) block of the multichip
    stage's monotone counter workload starting at scrape index t0 —
    the SINGLE home of the value formula, shared by the store builder
    and the acceptance probe's tail ingest (a divergent tail would
    introduce counter resets and invalidate the pack-memo check)."""
    import numpy as np
    ts2d = np.broadcast_to(
        START + (t0 + np.arange(n, dtype=np.int64)) * 10_000, (r1 - r0, n))
    vals = (t0 + np.arange(n, dtype=np.float64))[None, :] * 5.0 \
        + np.arange(r0, r1, dtype=np.float64)[:, None]
    return ts2d, vals


def _multichip_store(dataset, total_series, T, n_shard):
    """n_shard-sharded memstore of monotone counter series — the
    multichip stage's workload, split contiguously across shards so the
    mesh's 'shard' axis maps 1:1 onto memstore shards."""
    from filodb_tpu.core.memstore import TimeSeriesMemStore
    from filodb_tpu.ingest.generator import counter_batch

    START = 1_600_000_000_000
    ms = TimeSeriesMemStore()
    base = counter_batch(total_series, 1, start_ms=START)
    per = total_series // n_shard
    for s in range(n_shard):
        sh = ms.setup(dataset, s)
        r0 = s * per
        r1 = total_series if s == n_shard - 1 else r0 + per
        keys = base.part_keys[r0:r1]
        for t0 in range(0, T, 40):
            n = min(40, T - t0)
            ts2d, vals = _multichip_block(START, t0, n, r0, r1)
            sh.ingest_columns("prom-counter", keys, ts2d, {"count": vals},
                              offset=t0)
    return ms, START


def measure_multichip(quick=False, series=None, iters=0):
    """Multi-chip fused scan stage (ISSUE 6 / ROADMAP item 2): the
    flagship `sum by (rate())` aggregate over an n-device
    ('shard' x 'time') mesh through MeshExecutor.run_agg, which routes
    fused-eligible aggregates through PER-DEVICE dispatch of the
    single-chip kernel + partial-only merges (parallel/mesh.py) — never
    the fused-in-shard_map composition that inverted the single-chip win
    ~30x (MULTICHIP_r05.json: warm 25.3 s vs 0.88 s general).

    Emits (one-line JSON keys):
      multichip_fused_warm_s   — warm p50 of the per-device fused route
      multichip_general_warm_s — warm p50 of the general mesh path over
                                 the SAME pack (the shard_map XLA path)
      multichip_scaling_x      — single-device warm p50 / mesh warm p50
                                 for the same total workload
    Gate: fused warm <= general warm (the inversion is dead), checked in
    `multichip_inversion_gone`.

    A box that claims TPU but exposes < 2 local devices FAILS this stage
    (raises — recorded as a loud stage error, never a silent skip): a
    single-chip tunnel must not masquerade as a scaling measurement.
    Host platforms need XLA_FLAGS=--xla_force_host_platform_device_count
    (the `bench.py multichip` standalone entry sets it before jax init).
    """
    import jax

    from filodb_tpu.core.index import Equals
    from filodb_tpu.ops import agg as agg_ops
    from filodb_tpu.ops.timewindow import make_window_ends
    from filodb_tpu.parallel.mesh import (MeshExecutor, make_mesh,
                                          distributed_window_agg)
    from filodb_tpu.utils.metrics import registry

    n_dev = jax.local_device_count()
    platform = jax.default_backend()
    if n_dev < 2:
        raise RuntimeError(
            f"multichip stage needs >= 2 local devices, have {n_dev} on "
            f"backend {platform!r}"
            + ("" if platform == "tpu" else " — run `python bench.py "
               "multichip` (forces 8 virtual host devices) or set "
               "XLA_FLAGS=--xla_force_host_platform_device_count=8"))
    n_time = 2 if n_dev % 2 == 0 and n_dev >= 4 else 1
    n_shard = n_dev // n_time
    total = series or (8_192 if quick else 262_144)
    total -= total % n_shard
    T = 120                              # 20 min of 10s scrapes
    iters = iters or (3 if quick else 5)
    st = {"devices": n_dev, "mesh": f"{n_shard}x{n_time}",
          "series": total, "samples_per_series": T}

    ms, START = _multichip_store("bench_multichip", total, T, n_shard)
    mesh = make_mesh(n_shard, n_time, devices=jax.devices()[:n_dev])
    ex = MeshExecutor(ms, "bench_multichip", mesh)
    filters = [Equals("_metric_", "request_total")]
    end_ms = START + (T - 1) * 10_000
    wends = make_window_ends(START + 600_000, end_ms, 60_000)
    range_ms = 300_000
    span = total * (T - 60)              # samples inside the queried span

    def p50(fn, n=iters):
        ts = []
        for _ in range(n):
            t0 = time.perf_counter()
            fn()
            ts.append(time.perf_counter() - t0)
        ts.sort()
        return ts[len(ts) // 2]

    packed = ex.lookup_and_pack(filters, START, end_ms, by=("_ns_",),
                                fn_name="rate")
    k0 = registry.counter("mesh_fused_kernel").value
    h0 = registry.counter("mesh_fused_host").value

    def fused_once():
        out, _ = ex.run_agg(packed, wends, range_ms=range_ms,
                            fn_name="rate", agg_op="sum")
        return out

    t0 = time.perf_counter()
    fused_res = fused_once()             # compile + warm every cache
    st["fused_cold_s"] = round(time.perf_counter() - t0, 4)
    took_kernel = registry.counter("mesh_fused_kernel").value > k0
    took_host = registry.counter("mesh_fused_host").value > h0
    st["multichip_fused_route"] = ("kernel" if took_kernel
                                   else "host" if took_host
                                   else "general(fallback)")
    fused_warm = p50(fused_once)
    st["multichip_fused_warm_s"] = round(fused_warm, 5)
    st["multichip_samples_per_sec"] = round(span / fused_warm, 1)
    st["multichip_perdevice_dispatches"] = \
        registry.counter("mesh_fused_perdevice_dispatches").value

    # general mesh path (shard_map XLA kernels) over the SAME pack — the
    # 0.88 s side of the MULTICHIP_r05 inversion
    from jax.sharding import NamedSharding, PartitionSpec as P
    wends_p, W = ex._prep_wends(packed, wends)
    wends_dev = jax.device_put(wends_p, NamedSharding(mesh, P("time")))

    def general_once():
        partials = distributed_window_agg(
            mesh, packed.ts_off, packed.values, packed.group_ids,
            wends_dev, range_ms=range_ms, fn_name="rate", agg_op="sum",
            num_groups=packed.num_groups, base_ms=packed.base_ms,
            vbase=packed.vbase, precorrected=packed.precorrected,
            dense=packed.dense)
        return np.asarray(agg_ops.present("sum", partials))[:, :W]

    t0 = time.perf_counter()
    general_res = general_once()
    st["general_cold_s"] = round(time.perf_counter() - t0, 4)
    general_warm = p50(general_once)
    st["multichip_general_warm_s"] = round(general_warm, 5)
    # the gate needs dispatch EVIDENCE, not just timing: a silent
    # fallback to the general path makes fused ~= general and would
    # pass a coin-flip comparison with zero per-device work measured
    st["multichip_inversion_gone"] = bool(
        (took_kernel or took_host) and fused_warm <= general_warm)
    err = float(np.nanmax(np.abs(np.asarray(fused_res, np.float64)
                                 - general_res)
                          / np.maximum(np.abs(general_res), 1e-9)))
    st["max_rel_err_vs_general"] = round(err, 9)

    # scaling: same total workload on ONE device (1x1 mesh, 1-shard
    # store) — the denominator every later device should shrink
    ms1, _ = _multichip_store("bench_multichip1", total, T, 1)
    mesh1 = make_mesh(1, 1, devices=jax.devices()[:1])
    ex1 = MeshExecutor(ms1, "bench_multichip1", mesh1)
    packed1 = ex1.lookup_and_pack(filters, START, end_ms, by=("_ns_",),
                                  fn_name="rate")

    def single_once():
        out, _ = ex1.run_agg(packed1, wends, range_ms=range_ms,
                             fn_name="rate", agg_op="sum")
        return out

    single_once()                        # compile
    single_warm = p50(single_once)
    st["multichip_single_device_warm_s"] = round(single_warm, 5)
    st["multichip_scaling_x"] = round(single_warm / fused_warm, 3)

    # ISSUE-6 acceptance: a re-poll after value-only ingest must hit the
    # packing-layout memo (repack out of the warm-query profile)
    m0 = registry.counter("mesh_pack_memo_hits").value
    from filodb_tpu.ingest.generator import counter_batch as _cb
    tail = _cb(total, 1, start_ms=START)
    per = total // n_shard
    for s in range(n_shard):
        r0 = s * per
        r1 = total if s == n_shard - 1 else r0 + per
        ts2d, vals = _multichip_block(START, T, 1, r0, r1)
        ms.get_shard("bench_multichip", s).ingest_columns(
            "prom-counter", tail.part_keys[r0:r1], ts2d, {"count": vals},
            offset=T)
    ex.lookup_and_pack(filters, START, end_ms + 10_000, by=("_ns_",),
                       fn_name="rate")
    st["multichip_pack_memo_hits"] = \
        registry.counter("mesh_pack_memo_hits").value - m0
    return st


def run_chaos(quick=False, series=None):
    """Failure-domain chaos stage — REPLICATED (ISSUE 11, flipping the
    PR 4 gate): three real data-node processes each own copies of
    shards at RF=2 (primary + replica, never co-located); this process
    is the distributor (replication/replicator.py fan-out with quorum
    acks) AND the query coordinator (ReplicaFailoverDispatcher per
    shard).  Mid-traffic one node is SIGKILLed, later respawned on the
    same address and repaired by WAL-segment catch-up.  Gates:

      chaos_availability        == 1.0 — every fault-phase query
                                  answers in budget, served FULL via
                                  replica failover
      chaos_partial_rate        == 0.0 — the partial path never engages
                                  while any owner of a shard lives
      chaos_acked_lost          == 0  — every slab acked during the
                                  fault is queryable afterwards (the
                                  surviving owner held it; catch-up
                                  repaired the respawn)
      chaos_wrong_full_results  == 0  — a FULL result always carries
                                  every shard's group

    Full phase detail lands in SOAK_CHAOS.json."""
    import signal
    import socket as _socket
    import tempfile

    import numpy as np

    import jax
    jax.config.update("jax_platforms", "cpu")

    from bench.chaosnode import chaos_column
    from filodb_tpu.config import ReplicationConfig
    from filodb_tpu.core.memstore import TimeSeriesMemStore
    from filodb_tpu.core.partkey import PartKey
    from filodb_tpu.core.schemas import PROM_COUNTER
    from filodb_tpu.parallel.breaker import breakers
    from filodb_tpu.parallel.shardmapper import (ShardEvent, ShardMapper,
                                                 ShardStatus,
                                                 SpreadProvider)
    from filodb_tpu.parallel.transport import RemoteNodeDispatcher
    from filodb_tpu.query.engine import QueryEngine
    from filodb_tpu.query.planner import SingleClusterPlanner
    from filodb_tpu.query.rangevector import PlannerParams
    from filodb_tpu.replication import (ReplicaClient, ReplicationManager,
                                        failover_dispatcher_factory)
    from filodb_tpu.replication.catchup import relay_wal

    S_NODE = series or (512 if quick else 4_096)
    T = 420                              # 70 min of 10s scrapes
    START = 1_600_000_000_000
    STEP = 10_000
    BUDGET_S = 5.0
    phase_s = 4.0 if quick else 10.0
    dataset = "chaos"
    NODES = ("A", "B", "C")
    NUM_SHARDS = 4
    # RF-2 placement, replicas never co-located: shard s -> primary
    # NODES[s % 3], replica NODES[(s + 1) % 3]
    owners = {s: (NODES[s % 3], NODES[(s + 1) % 3])
              for s in range(NUM_SHARDS)}
    shards_of = {n: sorted(s for s, (p, r) in owners.items()
                           if n in (p, r)) for n in NODES}
    worker = os.path.join(REPO_DIR, "bench", "chaosnode.py")
    wal_root = tempfile.mkdtemp(prefix="filodb-chaos-wal-")

    def free_port():
        with _socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    env = {k: v for k, v in os.environ.items()}
    env["PYTHONPATH"] = REPO_DIR
    env["JAX_PLATFORMS"] = "cpu"
    logs = {n: open(os.path.join(REPO_DIR, f".chaos_node{n}.log"), "w")
            for n in NODES}

    def spawn(name):
        proc = subprocess.Popen(
            [sys.executable, worker, "--name", name,
             "--port", str(qports[name]),
             "--repl-port", str(rports[name]),
             "--shards", ",".join(str(s) for s in shards_of[name]),
             "--dataset", dataset,
             "--series", str(S_NODE), "--samples", str(T),
             "--start-ms", str(START),
             "--wal-dir", os.path.join(wal_root, name),
             "--platform", "cpu"],
            stdout=subprocess.PIPE, stderr=logs[name], text=True,
            env=env, cwd=REPO_DIR)
        line = proc.stdout.readline()
        ready = json.loads(line) if line.strip().startswith("{") else {}
        if not ready.get("ready"):
            raise RuntimeError(f"chaos node {name} failed to start: "
                               f"{line!r}")
        return proc

    qports = {n: free_port() for n in NODES}
    rports = {n: free_port() for n in NODES}
    procs = {n: spawn(n) for n in NODES}

    # coordinator state: replica-aware mapper, failover dispatchers,
    # quorum fan-out manager — no local data
    mapper = ShardMapper(NUM_SHARDS, replication_factor=2)
    for s, (p, r) in owners.items():
        mapper.update_from_event(
            ShardEvent("IngestionStarted", dataset, s, p))
        mapper.register_replica(s, r, status=ShardStatus.ACTIVE)
    dispatchers = {n: RemoteNodeDispatcher("127.0.0.1", qports[n],
                                           timeout_s=30.0)
                   for n in NODES}
    repl_clients = {n: ReplicaClient("127.0.0.1", rports[n],
                                     timeout_s=5.0) for n in NODES}
    planner = SingleClusterPlanner(
        dataset, mapper, SpreadProvider(default_spread=1),
        dispatcher_factory=failover_dispatcher_factory(
            mapper, lambda n: dispatchers[n]))
    engine = QueryEngine(dataset, TimeSeriesMemStore(), mapper,
                         planner=planner)
    manager = ReplicationManager(
        dataset, mapper, lambda n: repl_clients[n],
        config=ReplicationConfig(enabled=True, factor=2,
                                 ack_mode="quorum"))
    breakers.reset()
    breakers.configure(failure_threshold=3, open_base_s=0.3,
                       open_max_s=2.0, jitter=0.1)
    pp = PlannerParams(allow_partial_results=True, timeout_s=BUDGET_S,
                      sample_limit=2_000_000_000,
                      scan_limit=2_000_000_000)
    Q = 'sum by (_ns_)(rate(chaos_total[5m]))'
    qs, qe = START // 1000 + 600, START // 1000 + (T - 1) * 10
    ALL_GROUPS = sorted(f"s{s}" for s in range(NUM_SHARDS))

    skeys = {s: [PartKey.make("chaos_total",
                              {"_ws_": "chaos", "_ns_": f"s{s}",
                               "instance": f"s{s}-{i}"})
                 for i in range(S_NODE)] for s in range(NUM_SHARDS)}
    tick = {"n": T}
    acked = {s: -1 for s in range(NUM_SHARDS)}   # last acked tick
    seq = {"n": 0}

    def ingest_tick():
        """One fresh scrape column per shard through the quorum
        fan-out; on a primary-owner death the coordinator promotes the
        replica (the ClusterCoordinator deathwatch path, exercised
        in-process by tests) and keeps acking on the survivor."""
        t_idx = tick["n"]
        tick["n"] += 1
        for s in range(NUM_SHARDS):
            col_ts, col_v = chaos_column(s, S_NODE, t_idx, START, STEP)
            res = manager.replicate(s, PROM_COUNTER.name, skeys[s],
                                    col_ts, {"count": col_v},
                                    seq=seq["n"], require_primary=False)
            seq["n"] += 1
            primary = mapper.node_for_shard(s)
            if primary not in res.acked:
                live = [n for n in mapper.replicas[s]
                        if n in res.acked]
                if live:
                    # demote_old=False — the dead primary must NOT
                    # re-enter the owner list as a query-ready replica
                    # (same stance as ShardManager.remove_member); the
                    # respawn re-registers it after catch-up
                    mapper.promote_replica(s, live[0], demote_old=False)
            if res.acked:
                acked[s] = t_idx

    def drive(phase_name, dur_s):
        """Mixed ingest+query loop for one phase."""
        recs = []
        t_end = time.perf_counter() + dur_s
        last_ingest = 0.0
        while time.perf_counter() < t_end:
            if time.perf_counter() - last_ingest >= 1.0:
                ingest_tick()
                last_ingest = time.perf_counter()
            t0 = time.perf_counter()
            res = engine.query_range(Q, qs, 60, qe, pp)
            lat = time.perf_counter() - t0
            groups = {k.labels_dict.get("_ns_") for k, _, _ in
                      res.series()} if res.error is None else set()
            recs.append({"lat_s": lat, "error": res.error,
                         "partial": bool(res.partial),
                         "groups": sorted(g for g in groups if g)})
        return recs

    def p99(recs):
        if not recs:
            return 0.0
        lats = sorted(r["lat_s"] for r in recs)
        return lats[min(int(len(lats) * 0.99), len(lats) - 1)]

    # warmup WITHOUT the deadline: first-hit XLA compiles (coordinator
    # merge + node-side leaf kernels) must not eat the chaos budget
    warm_pp = PlannerParams(allow_partial_results=True,
                            sample_limit=2_000_000_000,
                            scan_limit=2_000_000_000)
    warm = engine.query_range(Q, qs, 60, qe, warm_pp)
    if warm.error:
        raise RuntimeError(f"chaos warmup failed: {warm.error}")

    # phase 1: healthy baseline (replicated ingest + full queries)
    healthy = drive("healthy", phase_s)

    # phase 2: SIGKILL node B mid-traffic.  B is primary for some
    # shards and replica for others — queries must stay FULL (failover)
    # and ingest must keep acking (promotion + surviving owner)
    victim = "B"
    os.kill(procs[victim].pid, signal.SIGKILL)
    procs[victim].wait()
    fault = drive("fault", phase_s)

    # phase 3: B respawns on the same address: replays its own WAL,
    # then the coordinator repairs the gap by relaying the current
    # primaries' WAL segments through B's door, and only THEN lists B
    # as a query-ready replica again
    procs[victim] = spawn(victim)
    repl_clients[victim].reset()
    dispatchers[victim]._reset()
    caught_up = 0
    by_src = {}
    for s in shards_of[victim]:
        src = mapper.node_for_shard(s)
        if src != victim and src is not None:
            by_src.setdefault(src, []).append(s)
    for src, shards in by_src.items():
        # one relay per SOURCE (not per shard — each relay streams the
        # source's whole log); restore windows buffer live fan-out
        # probes reaching B mid-relay so a fresh tick can never
        # OOO-drop the relayed gap
        for s in shards:
            repl_clients[victim].begin_restore(dataset, s)
        caught_up += relay_wal(repl_clients[src], repl_clients[victim],
                               dataset, shards=shards)
        for s in shards:
            repl_clients[victim].end_restore(dataset, s)
    if by_src:
        manager.mark_repaired(victim)
    for s in shards_of[victim]:
        if mapper.node_for_shard(s) != victim \
                and victim not in mapper.replicas[s]:
            mapper.register_replica(s, victim,
                                    status=ShardStatus.ACTIVE)
    recovery = drive("recovery", phase_s)

    # zero acked-ingest loss: for every shard, the latest ACKED tick's
    # column must be queryable now (value = 5*tick + row; max over the
    # shard's series at the acked tick's timestamp = 5*tick + S-1)
    acked_lost = 0
    loss_detail = {}
    for s in range(NUM_SHARDS):
        t_idx = acked[s]
        if t_idx < 0:
            continue
        t_s = (START + t_idx * STEP) // 1000
        res = engine.query_range(
            f'max(chaos_total{{_ns_="s{s}"}})', t_s, 1, t_s, warm_pp)
        want = 5.0 * t_idx + (S_NODE - 1)
        got = None
        if res.error is None:
            for _k, _w, vals in res.series():
                v = np.asarray(vals)
                if v.size and not np.isnan(v[-1]):
                    got = float(v[-1])
        if got is None or abs(got - want) > 1e-6:
            acked_lost += 1
            loss_detail[s] = {"want": want, "got": got,
                              "acked_tick": t_idx}

    for name, proc in procs.items():
        if proc.poll() is None:
            proc.kill()
            proc.wait()
    for f in logs.values():
        f.close()

    def ok_within_budget(r):
        return r["error"] is None and r["lat_s"] <= BUDGET_S

    wrong_full = [r for r in fault
                  if r["error"] is None and not r["partial"]
                  and r["groups"] != ALL_GROUPS]
    avail = (sum(ok_within_budget(r) for r in fault) / len(fault)
             if fault else 0.0)
    partial_rate = (sum(r["partial"] for r in fault) / len(fault)
                    if fault else 0.0)
    healthy_p99 = p99(healthy)
    fault_p99 = p99(fault)
    recovered_full = sum(1 for r in recovery
                         if r["error"] is None and not r["partial"]
                         and r["groups"] == ALL_GROUPS)
    result = {
        "metric": "chaos_availability", "unit": "fraction",
        "value": round(avail, 4),
        "chaos_availability": round(avail, 4),
        "chaos_partial_rate": round(partial_rate, 4),
        "chaos_acked_lost": acked_lost,
        "chaos_p99_during_fault_s": round(fault_p99, 4),
        "healthy_p99_s": round(healthy_p99, 4),
        "chaos_p99_ratio": round(fault_p99 / max(healthy_p99, 1e-9), 2),
        "chaos_wrong_full_results": len(wrong_full),
        "chaos_queries": {"healthy": len(healthy), "fault": len(fault),
                          "recovery": len(recovery)},
        "chaos_recovered_full_results": recovered_full,
        "chaos_catchup_records": caught_up,
        "chaos_rf": 2, "chaos_nodes": len(NODES),
        "chaos_gate_ok": bool(avail == 1.0 and partial_rate == 0.0
                              and acked_lost == 0
                              and not wrong_full),
        "breakers": breakers.snapshot(),
        "replica_lag": manager.snapshot(),
        "series_per_shard": S_NODE, "budget_s": BUDGET_S,
        "platform": "cpu",
    }
    if loss_detail:
        result["chaos_acked_loss_detail"] = loss_detail
    artifact = {
        "run": "chaos", "quick": quick, "result": result,
        "owners": {str(s): list(o) for s, o in owners.items()},
        "phases": {"healthy": healthy, "fault": fault,
                   "recovery": recovery},
    }
    with open(os.path.join(REPO_DIR, "SOAK_CHAOS.json"), "w") as f:
        json.dump(artifact, f, indent=1)
    manager.stop()
    breakers.configure()
    breakers.reset()
    import shutil as _shutil
    _shutil.rmtree(wal_root, ignore_errors=True)
    return result


def run_replication(quick=False, series=None):
    """Replication stage (ISSUE 11): in-process RF-2 cluster on the real
    transports.  Three measurements + gates:

      replication_rf2_vs_rf1_pct   — quorum-acked RF-2 fan-out ingest
                                     throughput vs RF-1 (gate >= 50%:
                                     the durability copy may not halve
                                     the front door twice over)
      replication_catchup_samples_per_sec — WAL-segment catch-up drain
                                     rate into a fresh replica
      replication_handoff_*        — live handoff of a shard during
                                     mixed ingest+query traffic: zero
                                     failed queries, zero partials, and
                                     the final query_range byte-
                                     identical to an undisturbed
                                     single-store truth run
    """
    import tempfile
    import threading

    import numpy as np

    import jax
    jax.config.update("jax_platforms", "cpu")

    from filodb_tpu.core.memstore import TimeSeriesMemStore
    from filodb_tpu.core.partkey import PartKey
    from filodb_tpu.core.schemas import PROM_COUNTER
    from filodb_tpu.parallel.shardmapper import ShardEvent, ShardMapper
    from filodb_tpu.parallel.testcluster import make_replicated_cluster
    from filodb_tpu.query.engine import QueryEngine
    from filodb_tpu.query.rangevector import PlannerParams
    from filodb_tpu.replication import HandoffCoordinator

    S = series or (256 if quick else 2_048)
    K = 8                                # samples per slab column
    T = 64                               # base samples per series
    START = 1_600_000_000_000
    STEP = 10_000
    dataset = "prometheus"
    pump_s = 1.5 if quick else 4.0

    def skeys_for(shard, n):
        return [PartKey.make("repl_total",
                             {"_ws_": "w", "_ns_": f"s{shard}",
                              "i": str(i)}) for i in range(n)]

    def grid(n_series, n_samples, base_idx=0):
        ts = (np.arange(n_samples, dtype=np.int64)[None, :]
              + base_idx) * STEP + START
        ts = np.repeat(ts, n_series, axis=0)
        vals = (np.arange(n_samples, dtype=np.float64)[None, :]
                + base_idx) * 5.0 \
            + np.arange(n_series, dtype=np.float64)[:, None]
        return ts, vals

    # ---------------------------------------- RF-1 vs RF-2 throughput
    def pump(cluster, dur_s):
        keys = {s: skeys_for(s, S) for s in range(2)}
        n = 0
        b = 0
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < dur_s:
            for s in range(2):
                ts, vals = grid(S, K, base_idx=b * K)
                cluster.manager.replicate(s, PROM_COUNTER.name, keys[s],
                                          ts, {"count": vals},
                                          require_primary=True)
                n += S * K
            b += 1
        return n / (time.perf_counter() - t0)

    rates = {}
    for rf in (1, 2):
        cluster = make_replicated_cluster(num_shards=2,
                                          replication_factor=rf)
        try:
            pump(cluster, 0.3)           # warm sockets + key memos
            rates[rf] = pump(cluster, pump_s)
        finally:
            cluster.stop()
    rf2_pct = 100.0 * rates[2] / max(rates[1], 1e-9)

    # ------------------------------------------------ catch-up drain
    from filodb_tpu.replication import (ReplicaClient, ReplicationServer,
                                        catchup_shards)
    from filodb_tpu.wal import WalManager
    wal_root = tempfile.mkdtemp(prefix="filodb-replbench-")
    primary = TimeSeriesMemStore()
    primary.setup(dataset, 0)
    wal = WalManager(wal_root, dataset)
    keys0 = skeys_for(0, S)
    n_grids = 20 if quick else 60
    for b in range(n_grids):
        ts, vals = grid(S, K, base_idx=b * K)
        seq = wal.append_grid(0, PROM_COUNTER.name, keys0, ts,
                              {"count": vals})
        primary.get_shard(dataset, 0).ingest_columns(
            PROM_COUNTER.name, keys0, ts, {"count": vals}, offset=seq)
    srv = ReplicationServer(primary, node="P",
                            wals={dataset: wal}).start()
    try:
        replica = TimeSeriesMemStore()
        stats = catchup_shards(ReplicaClient(*srv.address), dataset,
                               replica, shards=[0], node="bench")
        catchup_sps = stats.samples_per_sec
        catchup_ok = stats.records == n_grids
    finally:
        srv.stop()
        wal.close()
        import shutil as _shutil
        _shutil.rmtree(wal_root, ignore_errors=True)

    # ------------------------------- live handoff under mixed traffic
    Q = 'sum by (_ns_)(rate(repl_total[5m]))'
    qs, qe = START // 1000 + 600, START // 1000 + 630
    cluster = make_replicated_cluster(nodes=("A", "B", "C"),
                                      num_shards=2, with_truth=True)
    handoff_summary = {}
    try:
        skeys = {s: skeys_for(s, S) for s in range(2)}
        ts, vals = grid(S, T)
        for s in range(2):
            cluster.ingest_grid(s, PROM_COUNTER.name, skeys[s], ts,
                                {"count": vals})
        pp = PlannerParams(allow_partial_results=True)
        warm = cluster.engine.query_range(Q, qs, 30, qe, pp)
        if warm.error:
            raise RuntimeError(f"replication warmup failed: "
                               f"{warm.error}")
        stop = threading.Event()
        qerrs, qpartials, qok = [], [], [0]
        tick = [T]

        def query_loop():
            while not stop.is_set():
                res = cluster.engine.query_range(Q, qs, 30, qe, pp)
                if res.error is not None:
                    qerrs.append(res.error)
                elif res.partial:
                    qpartials.append(True)
                else:
                    qok[0] += 1
                time.sleep(0.02)

        def ingest_loop():
            while not stop.is_set():
                b = tick[0]
                tick[0] += 1
                for s in range(2):
                    ts2, vals2 = grid(S, 1, base_idx=b)
                    cluster.ingest_grid(s, PROM_COUNTER.name, skeys[s],
                                        ts2, {"count": vals2})
                time.sleep(0.05)

        threads = [threading.Thread(target=query_loop, daemon=True),
                   threading.Thread(target=ingest_loop, daemon=True)]
        for t in threads:
            t.start()
        time.sleep(1.0)
        shard = 0
        owners = set(cluster.mapper.owners(shard))
        target = next(n for n in ("A", "B", "C") if n not in owners)
        coord = HandoffCoordinator(dataset, cluster.mapper,
                                   lambda n: cluster.repl_clients[n])
        t0 = time.perf_counter()
        handoff_summary = coord.handoff(shard, target)
        handoff_s = time.perf_counter() - t0
        time.sleep(1.0)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        # quiesced comparison vs the undisturbed truth store
        res = cluster.engine.query_range(Q, qs, 30, qe, PlannerParams())
        tmapper = ShardMapper(2)
        for s in range(2):
            tmapper.update_from_event(
                ShardEvent("IngestionStarted", dataset, s, "local"))
        truth_engine = QueryEngine(dataset, cluster.truth, tmapper)
        want = truth_engine.query_range(Q, qs, 30, qe, PlannerParams())

        def payload(r):
            p = QueryEngine.to_prom_matrix(r)
            p.pop("traceID", None)
            return json.dumps(p, sort_keys=True)

        handoff_identical = (res.error is None and want.error is None
                             and payload(res) == payload(want))
        handoff_failed_queries = len(qerrs)
        handoff_partials = len(qpartials)
        handoff_queries_ok = qok[0]
    finally:
        cluster.stop()

    gate_ok = bool(rf2_pct >= 50.0 and catchup_ok
                   and handoff_failed_queries == 0
                   and handoff_partials == 0 and handoff_identical)
    return {
        "metric": "replication_rf2_vs_rf1_pct", "unit": "%",
        "value": round(rf2_pct, 1),
        "replication_rf1_samples_per_sec": round(rates[1]),
        "replication_rf2_samples_per_sec": round(rates[2]),
        "replication_rf2_vs_rf1_pct": round(rf2_pct, 1),
        "replication_catchup_samples_per_sec": round(catchup_sps),
        "replication_handoff_failed_queries": handoff_failed_queries,
        "replication_handoff_partials": handoff_partials,
        "replication_handoff_identical": handoff_identical,
        "replication_handoff_seconds": round(handoff_s, 3),
        "replication_handoff_queries_ok": handoff_queries_ok,
        "replication_handoff_states": handoff_summary.get("states", []),
        "replication_gate_ok": gate_ok,
        "series_per_shard": S, "platform": "cpu",
    }


def run_objectstore(quick=False, series=None):
    """Disaggregated cold-tier stage (ISSUE 19): the disk-loss +
    elastic-read drills over persist/objectstore.py.  Three parts,
    each gated:

      (a) disk-kill drill — a FiloServer compacts + uploads two windows
          to a shared object store, takes a WAL-riding remote_write
          tail, then loses its ENTIRE store root (chunks.log, segments,
          meta).  While it is down, a stateless cold-read cluster over
          the same shared store keeps answering the historical range
          (objectstore_drill_availability == 1.0).  A reboot on the
          empty disk restores segments from the manifests, replays the
          WAL tail, and must answer the full-range query_range
          byte-identical to the pre-kill baseline (traceID stripped).
      (b) elastic-read gate — a cold-only 4-shard dataset in the shared
          store, served by real query-node OS processes
          (bench/coldnode.py: zero owned shards, manifest mount only).
          1 node vs 1 data + 2 query-only under the same concurrent
          client load: objectstore_elastic_qps_ratio >= 1.8 (on hosts
          with >= 3 cores; no-collapse + identity on smaller hosts) and
          results bit-identical.
      (c) dead-store degrade — every objectstore.get errors (fault
          point + breaker): a partial-tolerant query returns a FLAGGED
          partial in bounded wall time; a strict query surfaces the
          typed error.  Never a hang, never a silent full.
    """
    import shutil
    import signal
    import socket as _socket
    import tempfile
    import threading

    import numpy as np

    import jax
    jax.config.update("jax_platforms", "cpu")

    from filodb_tpu.config import FilodbSettings
    from filodb_tpu.core.memstore import TimeSeriesMemStore
    from filodb_tpu.core.partkey import PartKey
    from filodb_tpu.http import remotepb
    from filodb_tpu.parallel.breaker import breakers
    from filodb_tpu.parallel.shardmapper import (ShardEvent, ShardMapper,
                                                 SpreadProvider)
    from filodb_tpu.parallel.testcluster import make_cold_read_cluster
    from filodb_tpu.parallel.transport import RemoteNodeDispatcher
    from filodb_tpu.persist.compactor import SegmentCompactor
    from filodb_tpu.persist.localstore import (LocalDiskColumnStore,
                                               LocalDiskMetaStore)
    from filodb_tpu.persist.objectstore import (LocalObjectStore,
                                                SegmentUploader,
                                                make_query_tier)
    from filodb_tpu.persist.segments import SegmentStore
    from filodb_tpu.query.engine import QueryEngine
    from filodb_tpu.query.planners import PersistedClusterPlanner
    from filodb_tpu.query.rangevector import PlannerParams
    from filodb_tpu.replication.failover import cold_dispatcher_factory
    from filodb_tpu.standalone import DatasetConfig, FiloServer
    from filodb_tpu.utils import snappy as fsnappy
    from filodb_tpu.utils.faults import faults

    WINDOW = 3600 * 1000
    INTERVAL = 60_000
    root = tempfile.mkdtemp(prefix="filodb-objbench-")
    procs = []
    try:
        # ------------------------------- (a) disk-kill drill (FiloServer)
        S_a = 128 if quick else 512
        now_ms = int(time.time() * 1000)
        t0 = (now_ms - 5 * WINDOW) - ((now_ms - 5 * WINDOW) % WINDOW)
        na = 2 * WINDOW // INTERVAL
        grid_a = t0 + np.arange(na, dtype=np.int64) * INTERVAL
        vals_a = (np.arange(S_a)[:, None] * 7.0
                  + (np.arange(na) % 13)[None, :])
        pks_a = [PartKey("m", (("inst", f"i{i}"), ("_ws_", "w"),
                               ("_ns_", "drill"))) for i in range(S_a)]
        tail_batches, tail_k = 4, 8
        tail_start = int(grid_a[-1]) + INTERVAL

        def tail_payload(b):
            srs = []
            for i in range(S_a):
                labels = [("__name__", "m"), ("_ws_", "w"),
                          ("_ns_", "drill"), ("inst", f"i{i}")]
                samples = [(float(i + j + b),
                            tail_start + (b * tail_k + j) * INTERVAL)
                           for j in range(tail_k)]
                srs.append(remotepb.PromTimeSeries(labels, samples))
            return fsnappy.compress(remotepb.encode_write_request(srs))

        cfg = FilodbSettings()
        cfg.store.segment_window_ms = WINDOW
        cfg.store.segment_closed_lag_ms = WINDOW
        cfg.store.segment_retain_raw_ms = 1
        cfg.objectstore.root = os.path.join(root, "shared-a")
        cfg.objectstore.retry_base_s = 0.001
        cfg.objectstore.retry_max_s = 0.01
        cfg.wal.enabled = True
        cfg.wal.dir = os.path.join(root, "wal-a")
        store_root = os.path.join(root, "node-a")
        tail_end = tail_start + tail_batches * tail_k * INTERVAL
        # grid chosen so no instant lands inside the raw/cold seam band
        # [earliest_raw, earliest_raw + lookback): instants there route
        # to the cold tier, whose coverage legitimately ends before the
        # WAL tail — the same conservative split FiloDB's raw/downsample
        # boundary makes.  step 600s > lookback 300s and a +300s phase
        # puts the grid at seam±300s exactly, where both tiers agree.
        q_full = {"query": "sum(m)", "start": str(t0 // 1000 + 300),
                  "end": str(tail_end // 1000), "step": "600"}

        def filo_query(server, query):
            st, pay = server.api.handle("GET", "/api/v1/query_range",
                                        dict(query), b"")
            assert st == 200, pay
            pay.pop("traceID", None)
            return pay

        srv = FiloServer([DatasetConfig("prometheus", num_shards=1)],
                         column_store=LocalDiskColumnStore(store_root),
                         meta_store=LocalDiskMetaStore(store_root),
                         config=cfg)
        try:
            shard = srv.memstore.get_shard("prometheus", 0)
            shard.ingest_columns("gauge", pks_a,
                                 np.broadcast_to(grid_a, (S_a, na)),
                                 {"value": vals_a})
            shard.flush_all_groups()
            # compact -> upload -> retention (upload ack gates the prune)
            srv.compaction_schedulers["prometheus"].run_once()
            uploaded = srv.uploaders["prometheus"].uploads
            tail_acked = 0
            for b in range(tail_batches):        # WAL-riding tail
                st, _ = srv.api.handle("POST", "/api/v1/write", {},
                                       tail_payload(b))
                assert st == 204, f"remote_write got {st}"
                tail_acked += 1
            baseline = filo_query(srv, q_full)
            assert baseline["data"]["result"], "drill baseline empty"
        finally:
            srv.shutdown()

        # the disk dies — WAL and shared store survive, nothing else
        shutil.rmtree(store_root)

        # while the node is down, stateless readers over the shared tier
        # keep the historical range answerable: that IS the availability
        shared_a = LocalObjectStore(cfg.objectstore.root, name="avail")
        cold = make_cold_read_cluster(shared_a, num_shards=1,
                                      dataset="prometheus",
                                      data_nodes=("b0",),
                                      query_nodes=("qb",))
        avail_ok = avail_n = 0
        try:
            qs_a = t0 // 1000 + 600
            qe_a = int(grid_a[-1]) // 1000
            for _ in range(20):
                avail_n += 1
                r = cold.engine.query_range("sum(m)", qs_a, 300, qe_a)
                if r.error is None and not r.partial and \
                        list(r.series()):
                    avail_ok += 1
        finally:
            cold.stop()
        availability = avail_ok / max(avail_n, 1)

        # reboot on the empty disk: manifests restore the segments, the
        # WAL replays the tail, the answer must not have changed a byte
        srv2 = FiloServer([DatasetConfig("prometheus", num_shards=1)],
                          column_store=LocalDiskColumnStore(store_root),
                          meta_store=LocalDiskMetaStore(store_root),
                          config=cfg)
        try:
            restored = len(SegmentStore(store_root).list("prometheus", 0))
            mount_ok = srv2.health.pending_manifest_mounts() == []
            rebuilt = filo_query(srv2, q_full)
            drill_identical = (json.dumps(rebuilt, sort_keys=True)
                               == json.dumps(baseline, sort_keys=True))
        finally:
            srv2.shutdown()

        # -------------------------- (b) elastic read: real node processes
        DSB = "coldbench"
        NSH = 4
        S_b = series or (512 if quick else 2_048)
        T0B = 1_600_000_000_000 - (1_600_000_000_000 % WINDOW)
        nb = 2 * WINDOW // INTERVAL
        grid_b = T0B + np.arange(nb, dtype=np.int64) * INTERVAL
        broot = os.path.join(root, "shared-b")
        disk_b = os.path.join(root, "disk-b")
        cs_b = LocalDiskColumnStore(disk_b)
        ms_b = TimeSeriesMemStore(column_store=cs_b,
                                  meta_store=LocalDiskMetaStore(disk_b))
        for s in range(NSH):
            sh = ms_b.setup(DSB, s)
            keys = [PartKey("m", (("inst", f"i{i}"), ("_ws_", "w"),
                                  ("_ns_", f"s{s}")))
                    for i in range(S_b)]
            vals = (np.arange(S_b)[:, None] * 3.0 + s
                    + (np.arange(nb) % 17)[None, :])
            sh.ingest_columns("gauge", keys,
                              np.broadcast_to(grid_b, (S_b, nb)),
                              {"value": vals})
            sh.flush_all_groups()
        seg_b = SegmentStore(disk_b)
        comp_b = SegmentCompactor(cs_b, seg_b, DSB, NSH,
                                  window_ms=WINDOW, closed_lag_ms=0)
        n_segs = comp_b.compact_all(now_ms=int(grid_b[-1]) + 10 * WINDOW)
        store_b = LocalObjectStore(broot, name="bench-up")
        up_b = SegmentUploader(store_b, seg_b, DSB, NSH,
                               retry_base_s=0.001, retry_max_s=0.01)
        up_b.mount()
        n_up = up_b.run_once()

        def free_port():
            with _socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                return s.getsockname()[1]

        env = dict(os.environ)
        env["PYTHONPATH"] = REPO_DIR
        env["JAX_PLATFORMS"] = "cpu"
        worker = os.path.join(REPO_DIR, "bench", "coldnode.py")
        ports = {}

        def spawn_cold(name):
            port = free_port()
            p = subprocess.Popen(
                [sys.executable, worker, "--name", name,
                 "--port", str(port), "--objstore", broot,
                 "--dataset", DSB, "--num-shards", str(NSH)],
                stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                text=True, env=env, cwd=REPO_DIR)
            procs.append(p)
            ready = json.loads(p.stdout.readline())
            assert ready.get("ready"), f"cold node {name}: {ready}"
            ports[name] = ready["port"]

        def make_engine(query_nodes=()):
            mapper = ShardMapper(NSH)
            for s in range(NSH):
                mapper.update_from_event(
                    ShardEvent("IngestionStarted", DSB, s, "data0"))
            for qn in query_nodes:
                mapper.register_query_node(qn)
            dispatchers = {}

            def dispatcher_for(node):
                d = dispatchers.get(node)
                if d is None:
                    dispatchers[node] = d = RemoteNodeDispatcher(
                        "127.0.0.1", ports[node])
                return d

            tier, _remote = make_query_tier(store_b, DSB, NSH)
            planner = PersistedClusterPlanner(
                DSB, mapper, tier,
                spread_provider=SpreadProvider(default_spread=1),
                dispatcher_factory=cold_dispatcher_factory(
                    mapper, dispatcher_for))
            return QueryEngine(DSB, TimeSeriesMemStore(), mapper,
                               planner=planner)

        qs_b = T0B // 1000 + 600
        qe_b = int(grid_b[-1]) // 1000
        Q_b = "sum by (_ns_)(m)"

        def payload(res):
            p = QueryEngine.to_prom_matrix(res)
            p.pop("traceID", None)
            return json.dumps(p, sort_keys=True)

        def measure_qps(engine, dur_s, threads=8):
            for _ in range(3):                   # warm every node's leaves
                warm = engine.query_range(Q_b, qs_b, 300, qe_b)
                assert warm.error is None, warm.error
            stop = time.perf_counter() + dur_s
            counts = [0] * threads
            errs = []

            def loop(i):
                while time.perf_counter() < stop:
                    r = engine.query_range(Q_b, qs_b, 300, qe_b)
                    if r.error is not None or r.partial:
                        errs.append(r.error or "partial")
                        return
                    counts[i] += 1

            ths = [threading.Thread(target=loop, args=(i,))
                   for i in range(threads)]
            for t in ths:
                t.start()
            for t in ths:
                t.join()
            assert not errs, f"elastic load errors: {errs[:3]}"
            return sum(counts) / dur_s

        dur = 2.0 if quick else 5.0
        spawn_cold("data0")
        eng1 = make_engine()
        ref1 = payload(eng1.query_range(Q_b, qs_b, 300, qe_b))
        qps1 = measure_qps(eng1, dur)
        spawn_cold("q1")
        spawn_cold("q2")
        eng3 = make_engine(query_nodes=("q1", "q2"))
        ref3 = payload(eng3.query_range(Q_b, qs_b, 300, qe_b))
        qps3 = measure_qps(eng3, dur)
        elastic_identical = ref1 == ref3
        ratio = qps3 / max(qps1, 1e-9)
        # the 1.8x scale-out gate needs real parallel hardware: three
        # node processes on a 1-core host share that core, so there the
        # stage gates on no-collapse + bit-identity instead (the spread
        # machinery is still exercised end-to-end)
        cores = len(os.sched_getaffinity(0)) if hasattr(
            os, "sched_getaffinity") else (os.cpu_count() or 1)
        if cores >= 3:
            elastic_gate = "qps_ratio>=1.8"
            elastic_ok = ratio >= 1.8 and elastic_identical
        else:
            elastic_gate = f"no-collapse ({cores} core host)"
            elastic_ok = ratio >= 0.5 and elastic_identical
        for p in procs:
            p.send_signal(signal.SIGKILL)
        for p in procs:
            p.wait(timeout=30)
        procs.clear()

        # ------------------------------------- (c) dead-store degrade
        def make_local_engine():
            mapper = ShardMapper(NSH)
            for s in range(NSH):
                mapper.update_from_event(
                    ShardEvent("IngestionStarted", DSB, s, "local"))
            # fresh tier + cache each time: nothing pre-paged, so the
            # dead-store query MUST touch objectstore.get
            tier, _remote = make_query_tier(store_b, DSB, NSH,
                                            ttl_s=1_000.0)
            planner = PersistedClusterPlanner(
                DSB, mapper, tier,
                spread_provider=SpreadProvider(default_spread=1))
            return QueryEngine(DSB, TimeSeriesMemStore(), mapper,
                               planner=planner)

        healthy = make_local_engine().query_range(Q_b, qs_b, 300, qe_b)
        assert healthy.error is None and not healthy.partial
        eng_part, eng_strict = make_local_engine(), make_local_engine()
        breakers.configure(failure_threshold=2, open_base_s=0.05,
                           open_max_s=0.1, jitter=0.0)
        try:
            t_dead = time.perf_counter()
            with faults.plan("objectstore.get", "error",
                             first_k=1_000_000):
                res_p = eng_part.query_range(
                    Q_b, qs_b, 300, qe_b,
                    PlannerParams(allow_partial_results=True))
            dead_s = time.perf_counter() - t_dead
            partial_flagged = res_p.error is None and bool(res_p.partial)
            with faults.plan("objectstore.get", "error",
                             first_k=1_000_000):
                res_s = eng_strict.query_range(Q_b, qs_b, 300, qe_b)
            strict_error = res_s.error is not None
        finally:
            faults.disarm()
            breakers.configure()
            breakers.reset()
        bounded = dead_s < 10.0

        gate_ok = bool(drill_identical and mount_ok
                       and availability == 1.0
                       and restored == 2 and uploaded == 2
                       and n_segs == n_up == NSH * 2
                       and elastic_ok
                       and partial_flagged and strict_error and bounded)
        return {
            "metric": "objectstore_elastic_qps_ratio", "unit": "x",
            "value": round(ratio, 2),
            "objectstore_drill_identical": drill_identical,
            "objectstore_drill_availability": round(availability, 3),
            "objectstore_drill_restored_segments": restored,
            "objectstore_drill_uploaded_segments": uploaded,
            "objectstore_drill_wal_tail_batches": tail_acked,
            "objectstore_elastic_qps_1node": round(qps1, 1),
            "objectstore_elastic_qps_3node": round(qps3, 1),
            "objectstore_elastic_qps_ratio": round(ratio, 2),
            "objectstore_elastic_identical": elastic_identical,
            "objectstore_elastic_cores": cores,
            "objectstore_elastic_gate": elastic_gate,
            "objectstore_deadstore_partial_flagged": partial_flagged,
            "objectstore_deadstore_strict_error": strict_error,
            "objectstore_deadstore_seconds": round(dead_s, 3),
            "objectstore_gate_ok": gate_ok,
            "series_per_shard": S_b, "platform": "cpu",
        }
    finally:
        for p in procs:
            try:
                p.send_signal(signal.SIGKILL)
                p.wait(timeout=10)
            except Exception:  # noqa: BLE001 — already dead
                pass
        shutil.rmtree(root, ignore_errors=True)


def run_federation(quick=False, series=None):
    """Cross-cluster federation stage (ISSUE 20): the two-cluster
    testbench over parallel/testcluster.make_federated_pair.  Gated:

      (a) bit-identity — a federated exactly-mergeable `sum by` (west
          replies one [G, W] cluster partial over the door) and a
          non-mergeable per-series shape (series shipping) must be
          bit-identical to a single-cluster truth engine holding every
          series; a cross-cluster binary join likewise.
      (b) dead-cluster degrade — west's door dies with the SIGKILL
          signature mid-bench: a partial-tolerant query must return a
          FLAGGED partial NAMING cluster:west in bounded wall time
          (never a hang, never silent short data), and after the door
          revives the half-open breaker must recover to full
          bit-identical answers.
      (c) wire ratio — the same `sum by` against a push_partials=False
          strawman pair (every remote series ships raw): the pushed
          wire bytes must be at least federation_wire_ratio_x smaller,
          the O(groups)-vs-O(series) win federation exists for.
    """
    import numpy as np

    import jax
    jax.config.update("jax_platforms", "cpu")

    from filodb_tpu.parallel.breaker import breakers
    from filodb_tpu.parallel.testcluster import make_federated_pair
    from filodb_tpu.query.rangevector import PlannerParams

    S_f = int(series) if series else (8 if quick else 32)
    n_samples = 60 if quick else 240
    s0 = 1_600_000_020
    q_sum = "sum by (_ns_) (fed_gauge)"
    q_series = "avg_over_time(fed_gauge[2m])"
    q_join = ('sum by (_ns_) (fed_gauge{region="west"}) '
              '+ sum by (_ns_) (fed_gauge{region="east"})')
    args = (s0 + 180, 60, s0 + (n_samples - 2) * 10)
    pp = PlannerParams(allow_partial_results=True, timeout_s=30.0)

    def identical(res, truth):
        if res.error is not None or truth.error is not None:
            return False
        got = {str(k): np.asarray(v) for k, _, v in res.series()}
        want = {str(k): np.asarray(v) for k, _, v in truth.series()}
        return set(got) == set(want) and all(
            np.array_equal(got[k], want[k], equal_nan=True) for k in want)

    breakers.configure(failure_threshold=3, open_base_s=0.2,
                       open_max_s=0.5, jitter=0.0)
    breakers.reset()
    pair = make_federated_pair(num_series=S_f, num_samples=n_samples,
                               start=False)
    try:
        # --------------------------------------------- (a) bit-identity
        res_sum = pair.engine.query_range(q_sum, *args)
        ident = (identical(res_sum, pair.truth.query_range(q_sum, *args))
                 and res_sum.stats.pushdown_pushed >= 1
                 and identical(pair.engine.query_range(q_series, *args),
                               pair.truth.query_range(q_series, *args)))
        join_ident = identical(pair.engine.query_range(q_join, *args),
                               pair.truth.query_range(q_join, *args))
        pushed_bytes = res_sum.stats.wire_bytes

        # --------------------------------------- (b) dead-cluster drill
        pair.kill_west()
        t0 = time.perf_counter()
        dead = pair.engine.query_range(q_sum, *args, planner_params=pp)
        dead_s = time.perf_counter() - t0
        partial_flagged = (dead.error is None and dead.partial
                          and dead_s < 30.0)
        names_cluster = any("cluster:west" in w
                            for w in dead.stats.warnings)
        pair.revive_west()
        recovered = False
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            res = pair.engine.query_range(q_sum, *args, planner_params=pp)
            if res.error is None and not res.partial:
                recovered = identical(res, pair.truth.query_range(q_sum,
                                                                  *args))
                break
            time.sleep(0.2)
    finally:
        pair.stop()
        breakers.reset()

    # ------------------------------------------------- (c) wire ratio
    straw = make_federated_pair(num_series=S_f, num_samples=n_samples,
                                push_partials=False, start=False)
    try:
        res = straw.engine.query_range(q_sum, *args)
        shipped_ok = identical(res, straw.truth.query_range(q_sum, *args))
        shipped_bytes = res.stats.wire_bytes
    finally:
        straw.stop()
        breakers.configure()
        breakers.reset()
    ratio = (shipped_bytes / pushed_bytes) if pushed_bytes else 0.0

    gate_ok = bool(ident and join_ident and partial_flagged
                   and names_cluster and recovered and shipped_ok
                   and ratio >= 1.2)
    return {
        "metric": "federation_wire_ratio_x",
        "value": round(ratio, 2), "unit": "x",
        "federation_identical": 1.0 if ident else 0.0,
        "federation_join_identical": 1.0 if join_ident else 0.0,
        "federation_partial_on_dead_cluster":
            1.0 if partial_flagged else 0.0,
        "federation_dead_names_cluster": 1.0 if names_cluster else 0.0,
        "federation_dead_seconds": round(dead_s, 3),
        "federation_recovered_full": 1.0 if recovered else 0.0,
        "federation_wire_ratio_x": round(ratio, 2),
        "federation_pushed_wire_bytes": pushed_bytes,
        "federation_shipped_wire_bytes": shipped_bytes,
        "federation_gate_ok": gate_ok,
        "series_per_region": S_f, "platform": "cpu",
    }


def measure_longrange(quick=False, series=None):
    """Historical-tier stage (ISSUE 8): multi-day persisted dataset,
    compacted into columnar segments, served through the cold DeviceMirror
    region and the tier-stitched planner.

    One-line JSON keys:
      longrange_cold_scan_samples_per_sec — FIRST scan over the persisted
          range (segments decoded + uploaded on the query's critical
          path); gate (a): >= 1/10 of the in-memory scan number
      longrange_warm_cold_ratio — cold-region-resident re-scan vs the
          in-memory number; gate (b): >= 0.5
      longrange_stitch_identical — a query_range spanning
          raw + downsample + persisted stitched into one grid,
          bit-identical to an all-in-memory reference store holding the
          same samples; gate (c): True
    """
    import shutil
    import tempfile

    from filodb_tpu.core.devicecache import ColdSegmentCache
    from filodb_tpu.core.memstore import TimeSeriesMemStore
    from filodb_tpu.core.partkey import PartKey
    from filodb_tpu.downsample import (DownsampleClusterPlanner,
                                       DownsampledTimeSeriesStore,
                                       ShardDownsampler)
    from filodb_tpu.parallel.shardmapper import ShardEvent, ShardMapper
    from filodb_tpu.persist.compactor import SegmentCompactor
    from filodb_tpu.persist.localstore import LocalDiskColumnStore
    from filodb_tpu.persist.segments import PersistedTier, SegmentStore
    from filodb_tpu.query.engine import QueryEngine
    from filodb_tpu.query.planner import SingleClusterPlanner
    from filodb_tpu.query.planners import (LongTimeRangePlanner,
                                           PersistedClusterPlanner)

    DS = "prometheus"
    S = series or (512 if quick else 4_096)
    INTERVAL = 300_000                   # 5m scrape == ds resolution
    # 24h windows: long-retention sizing (doc/operations.md runbook) —
    # per-segment fixed costs amortize over 288 samples/series
    WINDOW = (6 if quick else 24) * 3600 * 1000
    days = 1 if quick else 4
    NS = days * 24 * 3600 * 1000 // INTERVAL
    T0 = 1_600_000_000_000 - (1_600_000_000_000 % WINDOW)
    ts_grid = T0 + np.arange(NS, dtype=np.int64) * INTERVAL
    pks = [PartKey("m", (("inst", f"i{i}"), ("_ws_", "bench"),
                         ("_ns_", "lr")))
           for i in range(S)]
    # small integers: every op exact in f32, so the stitch gate can demand
    # BIT-identical results across tiers
    vals = (np.arange(S)[:, None] % 97 * 10.0
            + (np.arange(NS) % 11)[None, :])

    def fill(shard, t_slice=slice(None)):
        tg = ts_grid[t_slice]
        shard.ingest_columns("gauge", pks,
                             np.broadcast_to(tg, (S, len(tg))),
                             {"value": vals[:, t_slice]})

    out = {"series": S, "samples": int(S * NS), "days": days}
    root = tempfile.mkdtemp(prefix="filodb-longrange-")
    try:
        # persisted side: ingest -> flush -> compact -> segments
        cs = LocalDiskColumnStore(root)
        ms_disk = TimeSeriesMemStore(column_store=cs)
        sh = ms_disk.setup(DS, 0)
        sh.shard_downsampler = ShardDownsampler(resolutions=(INTERVAL,))
        fill(sh)
        t0 = time.perf_counter()
        sh.flush_all_groups()
        out["flush_s"] = round(time.perf_counter() - t0, 2)
        ds_store = DownsampledTimeSeriesStore(DS, column_store=cs,
                                              resolutions=(INTERVAL,))
        ds_store.setup_shard(0)
        ds_store.ingest_downsample_batches(
            0, sh.shard_downsampler.result_batches())
        seg_store = SegmentStore(root)
        comp = SegmentCompactor(cs, seg_store, DS, 1, window_ms=WINDOW,
                                closed_lag_ms=0)
        t0 = time.perf_counter()
        n_segs = comp.compact_all(now_ms=int(ts_grid[-1]) + 10 * WINDOW)
        out["compact_s"] = round(time.perf_counter() - t0, 2)
        out["segments"] = n_segs
        # drop the OLDEST segment: that span is downsample-only, so the
        # stitch query genuinely crosses all three tiers
        metas = seg_store.list(DS, 0)
        seg_store.remove(metas[0])
        ds_only_end = metas[0].end_ms
        cache = ColdSegmentCache(8 << 30, use_placer=False)
        tier = PersistedTier(seg_store, DS, 1, cache)
        # live memory: the last window only (the working set)
        tail_from = NS - WINDOW // INTERVAL
        ms_live = TimeSeriesMemStore()
        fill(ms_live.setup(DS, 0), slice(tail_from, None))
        earliest_raw = int(ts_grid[tail_from])
        # reference: everything in one in-memory store
        ms_ref = TimeSeriesMemStore()
        fill(ms_ref.setup(DS, 0))

        mapper = ShardMapper(1)
        mapper.update_from_event(
            ShardEvent("IngestionStarted", DS, 0, "n"))

        class _Src:
            def __init__(self, store):
                self.store = store

            def get_shard(self, dataset, shard_num):
                if "::ds::" in dataset:
                    return ds_store.get_shard(dataset, shard_num)
                return self.store.get_shard(dataset, shard_num)

            def shards_for(self, dataset):
                return self.store.shards_for(dataset)

        ltr = LongTimeRangePlanner(
            SingleClusterPlanner(DS, mapper),
            DownsampleClusterPlanner(ds_store, mapper),
            earliest_raw_time_fn=lambda: earliest_raw,
            latest_downsample_time_fn=lambda: 1 << 62,
            persisted_planner=PersistedClusterPlanner(DS, mapper, tier),
            persisted_range_fn=tier.range)
        eng_tier = QueryEngine(DS, _Src(ms_live), mapper, planner=ltr)
        eng_ref = QueryEngine(DS, _Src(ms_ref), mapper,
                              planner=SingleClusterPlanner(DS, mapper))

        from filodb_tpu.query.rangevector import PlannerParams
        params = PlannerParams(sample_limit=1 << 40)
        q = "sum(m)"
        # persisted-only span (cold scan target): past the ds-only head,
        # before the in-memory tail
        cold_start_s = ds_only_end // 1000 + 1800
        cold_end_s = earliest_raw // 1000 - 1800
        step_s = 600

        def run(eng, start_s, end_s):
            t0 = time.perf_counter()
            res = eng.query_range(q, start_s, step_s, end_s,
                                  planner_params=params)
            dt = time.perf_counter() - t0
            if res.error:
                raise RuntimeError(f"longrange query failed: {res.error}")
            return res, dt

        # in-memory FIRST-scan number over the SAME span: a fresh engine
        # with no device mirror yet, so the hot path pays its own page-in
        # (the [S, T] upload) on the query's critical path — the
        # apples-to-apples comparator for the cold tier's first scan
        res, dt = run(eng_ref, cold_start_s, cold_end_s)
        mem_first_sps = res.stats.samples_scanned / max(dt, 1e-9)
        out["longrange_mem_first_samples_per_sec"] = round(mem_first_sps, 1)
        # warm in-memory number (mirror resident, caches hot): best of 3
        mem_sps = 0.0
        for _ in range(3):
            res, dt = run(eng_ref, cold_start_s, cold_end_s)
            mem_sps = max(mem_sps,
                          res.stats.samples_scanned / max(dt, 1e-9))
        out["longrange_mem_samples_per_sec"] = round(mem_sps, 1)
        # cold FIRST-EVER scan: segments decode + upload + first-shape XLA
        # compiles on the critical path (recorded, not gated — production
        # restarts deserialize compiles from the persistent cache the
        # server wires in apply_jax_runtime)
        res, dt = run(eng_tier, cold_start_s, cold_end_s)
        out["longrange_cold_first_samples_per_sec"] = round(
            res.stats.samples_scanned / max(dt, 1e-9), 1)
        out["longrange_cold_verdict"] = res.stats.cold_tier
        out["longrange_cold_samples_paged"] = res.stats.samples_paged
        # the GATED cold number: fresh cold region + fresh tier over the
        # same segment files (every block re-decodes and re-uploads), warm
        # code paths — the restart-with-compile-cache shape
        tier2 = PersistedTier(seg_store, DS, 1,
                              ColdSegmentCache(8 << 30, use_placer=False))
        ltr2 = LongTimeRangePlanner(
            SingleClusterPlanner(DS, mapper),
            DownsampleClusterPlanner(ds_store, mapper),
            earliest_raw_time_fn=lambda: earliest_raw,
            latest_downsample_time_fn=lambda: 1 << 62,
            persisted_planner=PersistedClusterPlanner(DS, mapper, tier2),
            persisted_range_fn=tier2.range)
        eng_tier2 = QueryEngine(DS, _Src(ms_live), mapper, planner=ltr2)
        res, dt = run(eng_tier2, cold_start_s, cold_end_s)
        if res.stats.cold_tier != "cold_paged":
            raise RuntimeError("gated cold scan did not page")
        cold_sps = res.stats.samples_scanned / max(dt, 1e-9)
        out["longrange_cold_scan_samples_per_sec"] = round(cold_sps, 1)
        # warm re-scan: cold region resident (best of 3, like mem)
        warm_sps = 0.0
        for _ in range(3):
            res, dt = run(eng_tier, cold_start_s, cold_end_s)
            warm_sps = max(warm_sps,
                           res.stats.samples_scanned / max(dt, 1e-9))
        out["longrange_warm_verdict"] = res.stats.cold_tier
        out["longrange_warm_samples_per_sec"] = round(warm_sps, 1)
        # gate (a) compares first-scan to first-scan (both tiers pay
        # their page-in); the warm-based ratio rides along for context
        out["longrange_cold_vs_mem_ratio"] = round(
            cold_sps / max(mem_first_sps, 1e-9), 3)
        out["longrange_cold_vs_mem_warm_ratio"] = round(
            cold_sps / max(mem_sps, 1e-9), 3)
        out["longrange_warm_cold_ratio"] = round(
            warm_sps / max(mem_sps, 1e-9), 3)
        # stitched three-tier query vs the all-in-memory reference:
        # bit-identical over the same samples
        full_start_s = int(ts_grid[0]) // 1000 + 1800
        full_end_s = int(ts_grid[-1]) // 1000
        identical = True
        for qq in ("m", "sum(m)"):
            rt = eng_tier.query_range(qq, full_start_s, step_s, full_end_s,
                                      planner_params=params)
            rr = eng_ref.query_range(qq, full_start_s, step_s, full_end_s,
                                     planner_params=params)
            if rt.error or rr.error:
                raise RuntimeError(rt.error or rr.error)
            a = {k: (w, v) for k, w, v in rt.series()}
            b = {k: (w, v) for k, w, v in rr.series()}
            if set(a) != set(b):
                identical = False
                continue
            for k in a:
                wa, va = a[k]
                wb, vb = b[k]
                nn = np.isnan(va) & np.isnan(vb)
                if not (np.array_equal(wa, wb)
                        and np.array_equal(va[~nn], vb[~nn])
                        and np.array_equal(np.isnan(va), np.isnan(vb))):
                    identical = False
        out["longrange_stitch_identical"] = bool(identical)
        out["longrange_gate_cold_ok"] = bool(
            cold_sps >= 0.1 * mem_first_sps)
        out["longrange_gate_warm_ok"] = bool(warm_sps >= 0.5 * mem_sps)
        out["longrange_gate_ok"] = bool(
            out["longrange_gate_cold_ok"] and out["longrange_gate_warm_ok"]
            and identical)
        # LRU bound proof rides the stage too: sweep with a budget half
        # the working set and counter-assert the booked bytes
        small = ColdSegmentCache(
            max(m.device_bytes_estimate() for m in seg_store.list(DS, 0))
            * 3 // 2, use_placer=False)
        tier_small = PersistedTier(seg_store, DS, 1, small)
        over = False
        for m in seg_store.list(DS, 0):
            tier_small.get_block(m)
            over = over or small.bytes_booked > small.limit_bytes
        out["longrange_lru_bounded"] = bool(not over)
        return out
    finally:
        shutil.rmtree(root, ignore_errors=True)


def host_baselines(ts_row, vals, gids, wends, range_ms, span):
    """CPU reference numbers: vectorized numpy, per-window Python-loop
    iterator, and the single-core C iterator (the compiled
    ChunkedWindowIterator stand-in — no JVM exists in this environment,
    so this is the honest 'iterator on one core' comparator; see
    native/filodb_native.cc filodb_iter_rate and BASELINE.md)."""
    G = int(gids.max()) + 1
    Sv = min(vals.shape[0], 65_536)
    t0 = time.perf_counter()
    numpy_vectorized_baseline(ts_row, vals[:Sv].astype(np.float64),
                              gids[:Sv], G, wends.astype(np.int64), range_ms)
    vec_sps = (Sv * span) / (time.perf_counter() - t0)
    Sb = min(vals.shape[0], 512)
    t0 = time.perf_counter()
    numpy_iterator_baseline(ts_row, vals[:Sb].astype(np.float64),
                            wends.astype(np.int64), range_ms)
    it_sps = (Sb * span) / (time.perf_counter() - t0)
    c_sps = 0.0
    from filodb_tpu import native
    if native.lib is not None:
        Sc = min(vals.shape[0], 16_384)
        t0 = time.perf_counter()
        native.lib.iter_rate(ts_row, vals[:Sc].astype(np.float64),
                             wends.astype(np.int64), range_ms)
        c_sps = (Sc * span) / (time.perf_counter() - t0)
    return vec_sps, it_sps, c_sps


def measure_distexec(quick=False, series=None):
    """ISSUE-15 acceptance: aggregation pushdown + streaming distributed
    execution.

    Three proofs ride the one-line JSON:
      distexec_wire_bytes_ratio — a fan-out `sum by (...)` over FOUR
        data nodes with node-level pushdown ON vs the ship-everything
        baseline (map phase on the coordinator, full per-shard series
        blocks crossing the wire), measured from QueryStats.wire_bytes.
        Gate: >= 10x fewer bytes, results BIT-identical (integer-valued
        samples keep every partial-sum component exact, so the merge
        tree's association cannot perturb a bit).
      distexec_frontend_peak_rss_mb — a long-range-shaped (30-day-grid-
        sized, W~3k steps) single-node query whose [S, W] reply streams
        as bounded CRC frames into a preallocated block, traced-peak
        (tracemalloc, numpy included) vs the materialize-everything
        single-frame baseline.  Gate: streamed peak under a FIXED
        budget (3/4 of the bytes the children shipped + 2 MB frame
        slack) that the materialize-everything baseline exceeds.
      distexec_pushdown_speedup_x — wall p50 of the fan-out aggregation
        pushed vs ship-everything (reported, not gated: the wire is
        loopback here; real networks only widen it).
    """
    import tracemalloc

    from filodb_tpu.config import settings
    from filodb_tpu.ingest.generator import gauge_batch
    from filodb_tpu.parallel.testcluster import make_fanout_cluster
    from filodb_tpu.query.rangevector import PlannerParams

    st = {}
    START = 1_600_000_020_000
    S0 = START // 1000

    def as_map(res):
        out = {}
        for b in res.blocks:
            vals = np.asarray(b.values)
            for i, k in enumerate(b.keys):
                out[k] = (tuple(np.asarray(b.wends).tolist()),
                          vals[i].tobytes())
        return out

    # ---- half 1: 4-node fan-out aggregation, pushed vs ship-everything
    S = series or (2_048 if quick else 16_384)
    T = 360 if quick else 720                    # 10 s scrape samples
    batch = gauge_batch(S, T, start_ms=START, metric="bench_gauge")
    batch.columns["value"] = np.floor(batch.columns["value"])
    cluster = make_fanout_cluster([batch], num_shards=8,
                                  nodes=("n1", "n2", "n3", "n4"),
                                  with_truth=False)
    st["series"] = S
    try:
        q = "sum by (dc)(bench_gauge)"
        rng_args = (S0 + 600, 60, S0 + 600 + 60 * (60 if quick else 110))
        runs = {}
        iters = 3 if quick else 5
        for push in (True, False):
            # the off side is the SHIP-EVERYTHING strawman (full per-
            # series blocks over the wire), not pushdown=False — that
            # merely restores the per-shard [G, W] partial dispatch
            pp = PlannerParams(aggregation_pushdown=push,
                               ship_raw_series=not push)
            walls, wires, frames, verdicts, rmap = [], [], [], [], None
            for _ in range(iters):
                t0 = time.perf_counter()
                r = cluster.engine.query_range(q, *rng_args, pp)
                walls.append(time.perf_counter() - t0)
                if r.error:
                    st["error"] = f"fanout ({push=}): {r.error}"[:300]
                    return st
                wires.append(r.stats.wire_bytes)
                frames.append(r.stats.streamed_frames)
                verdicts.append((r.stats.pushdown_pushed,
                                 r.stats.pushdown_fallback))
                rmap = as_map(r)
            runs[push] = {"wall_p50": sorted(walls)[len(walls) // 2],
                          "wire": sorted(wires)[len(wires) // 2],
                          "frames": max(frames),
                          "verdicts": verdicts[-1], "map": rmap}
        on, off = runs[True], runs[False]
        st["distexec_wire_on_bytes"] = int(on["wire"])
        st["distexec_wire_off_bytes"] = int(off["wire"])
        st["distexec_wire_bytes_ratio"] = round(
            off["wire"] / max(on["wire"], 1), 1)
        st["distexec_pushdown_speedup_x"] = round(
            off["wall_p50"] / max(on["wall_p50"], 1e-9), 2)
        st["distexec_bit_identical"] = bool(on["map"] == off["map"]
                                            and on["map"])
        st["distexec_pushed_nodes"] = int(on["verdicts"][0])
    finally:
        cluster.stop()

    # ---- half 2: long-range streamed aggregation vs materialize-all.
    # A 30-day-grid-sized [S, W] block lives on ONE data node; the
    # coordinator runs `sum by (...)` over ship_raw_series children (the
    # full-series-over-the-wire shape raw selectors and non-pushable
    # ops always have, forced here for a deterministic bound).  Baseline
    # buffers each whole reply + decode copies; streamed mode folds
    # every CRC frame through map+reduce as it arrives, so the
    # coordinator never holds more than a frame and the [G, W] partial.
    Sw = 512 if quick else 1_024
    W = 1_440 if quick else 2_880               # 30-day-grid-sized [S, W]
    wide = gauge_batch(Sw, W, start_ms=START, step_ms=60_000,
                       metric="wide_gauge")
    wide.columns["value"] = np.floor(wide.columns["value"])
    one = make_fanout_cluster([wide], num_shards=2, nodes=("n1",),
                              with_truth=False)
    saved_frame = settings().query.stream_frame_bytes
    try:
        qw = "sum by (_ns_)(wide_gauge)"
        wargs = (S0 + 600, 60, S0 + 60 * W)
        pp = PlannerParams(aggregation_pushdown=False,
                           ship_raw_series=True,
                           sample_limit=200_000_000)
        peaks = {}
        maps = {}
        shipped = 0
        # frame bound scaled to the stage size so quick mode streams too
        # (production default stays 2 MiB; the bound just has to be well
        # under one shard's reply for the fold to engage)
        frame = (256 << 10) if quick else (1 << 20)
        for mode, frame_bytes in (("baseline", 0), ("streamed", frame)):
            settings().query.stream_frame_bytes = frame_bytes
            one.engine.query_range(qw, *wargs, pp)      # warm the path
            tracemalloc.start()
            tracemalloc.reset_peak()
            r = one.engine.query_range(qw, *wargs, pp)
            _, peak = tracemalloc.get_traced_memory()
            tracemalloc.stop()
            if r.error:
                st["error"] = f"longrange ({mode}): {r.error}"[:300]
                return st
            peaks[mode] = peak
            maps[mode] = as_map(r)
            shipped = max(shipped, r.stats.wire_bytes)
            if mode == "streamed":
                st["distexec_stream_frames"] = int(r.stats.streamed_frames)
        st["distexec_frontend_peak_rss_mb"] = round(
            peaks["streamed"] / (1 << 20), 1)
        st["distexec_baseline_peak_rss_mb"] = round(
            peaks["baseline"] / (1 << 20), 1)
        # FIXED budget: 3/4 of the bytes the children ship plus frame
        # slack — the materialize-everything baseline necessarily
        # exceeds the shipped bytes (whole reply buffer + decode
        # copies), while the fold holds a frame and a [G, W] partial
        budget_mb = round(shipped / (1 << 20) * 0.75 + 2.0, 1)
        st["distexec_rss_budget_mb"] = budget_mb
        st["distexec_stream_identical"] = bool(
            maps["streamed"] == maps["baseline"] and maps["streamed"])
    finally:
        settings().query.stream_frame_bytes = saved_frame
        one.stop()

    st["distexec_gate_ok"] = bool(
        st["distexec_wire_bytes_ratio"] >= 10.0
        and st["distexec_bit_identical"]
        and st["distexec_stream_identical"]
        and st["distexec_stream_frames"] > 1
        and st["distexec_frontend_peak_rss_mb"] <= budget_mb
        and st["distexec_baseline_peak_rss_mb"] > budget_mb)
    return st


def measure_index(quick=False, series=None):
    """ISSUE-16 acceptance: the bitmap posting engine under high
    cardinality.

    Builds a zipf-skewed shard index (10M part keys at full scale; the
    head metric/namespace own most series, a 100k-value instance label
    carries the regex load), then measures:
      index_build_keys_per_sec — add_partition throughput (reported).
      index_equals_lookup_p50_ms — point lookups on the high-cardinality
        label via part_ids_from_filters.  Gate: < 1 ms.
      index_regex_plan_p50_ms — first-plan `=~` queries over DISTINCT
        patterns (alternation / prefix / trigram-contains / class
        shapes), so the per-(label,pattern) memo cannot flatter the
        number; the one-time trigram-map build is warmed first and
        reported separately.  Gate: p50 < 10 ms.
      index_churn_rss_growth_pct — a 3x-shard-size churn soak on a
        separate index (evict-all / refill generations with ever-
        increasing pids, tombstone-threshold compaction like the
        index_compaction job); full-occupancy memory_bytes() of the
        last generation vs the first.  Gate: <= 10%.
    """
    from filodb_tpu.core.index import (Equals, EqualsRegex, MAX_TIME,
                                       PartKeyIndex)
    from filodb_tpu.core.partkey import PartKey

    st = {}
    S = series or (1_000_000 if quick else 10_000_000)
    st["index_series"] = S
    rng = np.random.default_rng(16)

    def p50_ms(xs):
        return round(sorted(xs)[len(xs) // 2] * 1000.0, 3)

    # ---- zipf label universe.  kv tuples are interned so 10M PartKeys
    # share label-pair objects (the index stores refs, not copies)
    n_inst = min(100_000, max(1_000, S // 100))
    metrics = [f"metric_{i:04d}" for i in range(1_000)]
    nss = [f"ns-{i:04d}" for i in range(5_000)]
    wss = [f"ws-{i:02d}" for i in range(50)]
    insts = [f"host-{i:06d}-dc{i % 8}" for i in range(n_inst)]
    ns_kv = [("_ns_", v) for v in nss]
    ws_kv = [("_ws_", v) for v in wss]
    inst_kv = [("instance", v) for v in insts]
    gen_kv = [("gen", f"g{i}") for i in range(S // n_inst + 1)]
    mi = np.minimum(rng.zipf(1.3, size=S) - 1, len(metrics) - 1).tolist()
    ni = np.minimum(rng.zipf(1.2, size=S) - 1, len(nss) - 1).tolist()
    wi = np.minimum(rng.zipf(1.5, size=S) - 1, len(wss) - 1).tolist()

    idx = PartKeyIndex()
    t0 = time.perf_counter()
    for i in range(S):
        pk = PartKey(metrics[mi[i]],
                     (ns_kv[ni[i]], ws_kv[wi[i]],
                      gen_kv[i // n_inst], inst_kv[i % n_inst]))
        idx.add_partition(i, pk, 1_000_000)
    build_s = time.perf_counter() - t0
    st["index_build_keys_per_sec"] = int(S / build_s)
    st["index_memory_bytes"] = int(idx.memory_bytes())

    # ---- equals point lookups on the 100k-value label
    eq_walls = []
    for k in rng.integers(0, n_inst, size=(100 if quick else 300)):
        f = [Equals("instance", insts[int(k)])]
        t0 = time.perf_counter()
        ids = idx.part_ids_from_filters(f, 0, MAX_TIME)
        eq_walls.append(time.perf_counter() - t0)
        assert ids.size == S // n_inst, "equals lookup lost series"
    st["index_equals_lookup_p50_ms"] = p50_ms(eq_walls)

    # ---- regex planning: warm the one-time sorted-dict + trigram build
    # (amortized per label until its value set changes), then time
    # DISTINCT first-plan patterns so the memo can't answer
    t0 = time.perf_counter()
    idx.part_ids_from_filters(
        [EqualsRegex("instance", ".*zz-warmup-zz.*")], 0, MAX_TIME)
    st["index_trigram_build_ms"] = round(
        (time.perf_counter() - t0) * 1000.0, 1)
    pats = []
    for k in range(8):
        a, b = (k * 37) % n_inst, (n_inst - 1 - k * 53) % n_inst
        pats.append(f"{insts[a]}|{insts[b]}")           # alternation
    for k in range(8):
        pats.append(f"host-{(k * 997) % n_inst:06d}.*")  # narrow prefix
    for k in range(8):
        pats.append(f"host-{k:04d}.*")                  # ~100-value prefix
    for k in range(8):
        pats.append(f".*{k:03d}-dc{k % 8}")             # trigram contains
    for k in range(4):
        pats.append(f"host-0{k:02d}[0-4].*")            # prefix + class
    plan_walls = []
    for pat in pats:
        f = [EqualsRegex("instance", pat)]
        t0 = time.perf_counter()
        idx.part_ids_from_filters(f, 0, MAX_TIME)
        plan_walls.append(time.perf_counter() - t0)
    st["index_regex_plan_p50_ms"] = p50_ms(plan_walls)
    st["index_regex_plan_max_ms"] = round(max(plan_walls) * 1000.0, 3)
    memo_walls = []
    for pat in pats:
        f = [EqualsRegex("instance", pat)]
        t0 = time.perf_counter()
        idx.part_ids_from_filters(f, 0, MAX_TIME)
        memo_walls.append(time.perf_counter() - t0)
    st["index_regex_memo_p50_ms"] = p50_ms(memo_walls)
    del idx, mi, ni, wi

    # ---- churn soak: 3 evict-all/refill generations, pids never reused
    # (the shard assigns monotonically), compaction driven through the
    # same maybe_compact(threshold) entry point as the background job
    churn_n = 80_000 if quick else 400_000
    st["index_churn_series"] = churn_n
    cidx = PartKeyIndex()
    pid = 0
    mems = []
    for gen in range(3):
        pids = []
        for i in range(churn_n):
            pk = PartKey(metrics[i % 200],
                         (ns_kv[i % 500], ws_kv[i % 50],
                          inst_kv[i % n_inst]))
            cidx.add_partition(pid, pk, 1_000_000)
            pids.append(pid)
            pid += 1
        mems.append(cidx.memory_bytes())        # full-occupancy footprint
        if gen < 2:
            for j, p in enumerate(pids):
                cidx.remove_partition(p)
                if (j + 1) % 50_000 == 0:
                    cidx.maybe_compact(8_192)
            cidx.maybe_compact(1)               # the job's final sweep
            if cidx.tombstone_count:
                st["error"] = "churn compaction left tombstones"
                return st
    st["index_churn_rss_growth_pct"] = round(
        (mems[-1] - mems[0]) / mems[0] * 100.0, 1)
    st["index_gate_ok"] = bool(
        st["index_regex_plan_p50_ms"] < 10.0
        and st["index_equals_lookup_p50_ms"] < 1.0
        and st["index_churn_rss_growth_pct"] <= 10.0)
    return st


def measure_exprfuse(quick=False, series=None, iters=0):
    """ISSUE-17 acceptance: whole-expression device compilation.

    An 8-panel mixed dashboard (aggregated rates, a rank aggregation,
    and two vector-matching binary ops) over ONE shared working set,
    evaluated two ways:

      optimized — engine.query_range_batch with query.exprfuse on: the
        expression compiler walks every tree, runs each in-process
        leaf's fused preflight under one batch-gather-memo scope (the
        working set is scanned, offset-gridded, and counter-corrected
        ONCE for the whole dashboard), and the leaves evaluate as [G, W]
        partials — no per-node [S, W] intermediates.
      per-node assembly — one query_range per panel with exprfuse off
        and leaf fusion diverted (host_route_max_samples=0): every plan
        node materializes its full output (the leaf ships raw series,
        PeriodicSamplesMapper materializes [S, W] per panel, the
        aggregate reduces it), and every panel re-gathers the store.

    Gate (full scale): optimized p50 >= 5x faster, results BIT-identical
    (same wends, same value bytes, per series key).  The stage pins the
    host-route configuration on every backend — it measures expression-
    level fusion and scan sharing; the kernel-dispatch amortization has
    its own stage (dashboard_batch) and on-chip capture.
    """
    from filodb_tpu.config import settings
    from filodb_tpu.core.memstore import TimeSeriesMemStore
    from filodb_tpu.ingest.generator import counter_batch
    from filodb_tpu.query.engine import QueryEngine
    from filodb_tpu.query.rangevector import PlannerParams
    from filodb_tpu.utils.metrics import registry

    S = series or (8_192 if quick else 1_048_576)
    T = 96                               # 16 min of 10s scrapes
    START = 1_600_000_000_000
    st = {"series": S, "samples_per_series": T, "panels": 8}
    qconf = settings().query
    sconf = settings().store
    saved = (qconf.exprfuse_enabled, qconf.host_route_max_samples,
             sconf.device_mirror_enabled,
             os.environ.get("FILODB_TPU_FORCE_HOST_ROUTE"),
             qconf.default_timeout_s)
    try:
        # deterministic routing for the comparison: no device mirror
        # (its snapshot gather is a third path, measured elsewhere),
        # host-routed fused leaves on any backend; no query deadline
        # (the 1M-series COLD baseline pass on a host backend can
        # exceed the serving default — this is a bench, not a server)
        sconf.device_mirror_enabled = False
        qconf.default_timeout_s = 0.0
        os.environ["FILODB_TPU_FORCE_HOST_ROUTE"] = "1"
        ms = TimeSeriesMemStore()
        ms.setup("bench_exprfuse", 0)
        sh = ms.get_shard("bench_exprfuse", 0)
        base = counter_batch(S, 1, start_ms=START)
        row_base = np.arange(S, dtype=np.float64)[:, None]
        for t0 in range(0, T, 40):
            n = min(40, T - t0)
            ts2d = np.broadcast_to(
                START + (t0 + np.arange(n, dtype=np.int64)) * 10_000,
                (S, n))
            vals = (t0 + np.arange(n, dtype=np.float64))[None, :] * 5.0 \
                + row_base
            sh.ingest_columns("prom-counter", base.part_keys, ts2d,
                              {"count": vals}, offset=t0)
        eng = QueryEngine("bench_exprfuse", ms)
        pp = PlannerParams(sample_limit=2_000_000_000,
                           scan_limit=2_000_000_000)
        s0 = START // 1000
        args = (s0 + 600, 60, s0 + (T - 1) * 10)
        m = "request_total"
        panels = [
            f'sum by (_ns_)(rate({m}[5m]))',
            f'avg by (_ns_)(rate({m}[5m]))',
            f'max by (_ns_)(max_over_time({m}[5m]))',
            f'count by (_ns_)(rate({m}[5m]))',
            f'sum by (_ns_)(rate({m}[5m]))'
            f' / on (_ns_) count by (_ns_)(rate({m}[5m]))',
            f'sum by (_ns_)(increase({m}[5m]))',
            f'topk(3, sum by (_ns_)(rate({m}[5m])))',
            f'sum by (_ns_)(rate({m}[5m]))'
            f' > bool on (_ns_) avg by (_ns_)(rate({m}[5m]))',
        ]

        def as_map(res):
            out = {}
            for b in res.blocks:
                vals = np.asarray(b.values)
                for i, k in enumerate(b.keys):
                    out[k] = (tuple(np.asarray(b.wends).tolist()),
                              vals[i].tobytes())
            return out

        def p50(fn, n):
            ts = []
            for _ in range(n):
                t0 = time.perf_counter()
                fn()
                ts.append(time.perf_counter() - t0)
            ts.sort()
            return ts[len(ts) // 2]

        # --- optimized: one compiled batch over the dashboard
        qconf.exprfuse_enabled = True
        qconf.host_route_max_samples = 1 << 60
        memo0 = registry.counter("leaf_gather_memo_hits").value
        on = eng.query_range_batch(panels, *args, pp)       # warm
        for q, r in zip(panels, on):
            if r.error:
                st["exprfuse_error"] = f"batch: {q}: {r.error}"[:300]
                return st
        st["exprfuse_memo_hits"] = int(
            registry.counter("leaf_gather_memo_hits").value - memo0)
        st["exprfuse_fused"] = sum(r.stats.exprfuse_fused for r in on)
        st["exprfuse_degraded"] = sum(r.stats.exprfuse_degraded
                                      for r in on)
        on_iters = iters or (3 if quick else 5)
        st["exprfuse_p50_s"] = round(p50(
            lambda: eng.query_range_batch(panels, *args, pp), on_iters), 5)

        # --- per-node assembly: sequential, every node materializes
        qconf.exprfuse_enabled = False
        qconf.host_route_max_samples = 0
        off = [eng.query_range(q, *args, pp) for q in panels]    # warm
        for q, r in zip(panels, off):
            if r.error:
                st["exprfuse_error"] = f"per-node: {q}: {r.error}"[:300]
                return st
        off_iters = iters or 3
        st["exprfuse_baseline_p50_s"] = round(p50(
            lambda: [eng.query_range(q, *args, pp) for q in panels],
            off_iters), 5)

        st["exprfuse_speedup_x"] = round(
            st["exprfuse_baseline_p50_s"]
            / max(st["exprfuse_p50_s"], 1e-9), 2)
        maps_on = [as_map(r) for r in on]
        maps_off = [as_map(r) for r in off]
        st["exprfuse_identical"] = bool(
            maps_on == maps_off and any(m for m in maps_on))
        # quick's toy store can't amortize the one shared scan; the 5x
        # gate is judged at FULL scale only (the ratio still rides the
        # line), correctness gates always hold
        st["exprfuse_gate_ok"] = bool(
            st["exprfuse_identical"] and st["exprfuse_fused"] > 0
            and st["exprfuse_degraded"] == 0
            and (quick or st["exprfuse_speedup_x"] >= 5.0))
    finally:
        (qconf.exprfuse_enabled, qconf.host_route_max_samples,
         sconf.device_mirror_enabled) = saved[:3]
        qconf.default_timeout_s = saved[4]
        if saved[3] is None:
            os.environ.pop("FILODB_TPU_FORCE_HOST_ROUTE", None)
        else:
            os.environ["FILODB_TPU_FORCE_HOST_ROUTE"] = saved[3]
    return st


def parse_args(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("stage", nargs="?", default="",
                    choices=["", "chaos", "multichip", "wal", "longrange",
                             "selfmon", "replication", "ingesttrace",
                             "activequeries", "qos", "distexec", "index",
                             "exprfuse", "devicetelem", "objectstore",
                             "federation"],
                    help="optional standalone stage: 'federation' runs "
                         "the cross-cluster federation stage (two-"
                         "cluster testbench: pushed [G, W] cluster "
                         "partials and shipped series bit-identical to "
                         "a single-cluster truth, dead-cluster flagged "
                         "partial naming the cluster + breaker "
                         "recovery, pushed-vs-shipped wire ratio >= "
                         "1.2x) and exits nonzero on a gate failure; "
                         "'objectstore' runs "
                         "the disaggregated cold-tier stage (disk-kill "
                         "drill with byte-identical rebuild from shared "
                         "object store + WAL tail, elastic-read gate "
                         ">=1.8x QPS with 2 stateless query nodes, "
                         "dead-store flagged-partial degrade) and exits "
                         "nonzero on a gate failure; 'chaos' runs the "
                         "failure-domain chaos harness (SIGKILL one of "
                         "three RF-2 data nodes mid-traffic; gates "
                         "availability=1.0 with zero partials and zero "
                         "acked loss) and writes SOAK_CHAOS.json; "
                         "'replication' runs the in-process replication "
                         "stage (RF-2 vs RF-1 fan-out throughput, WAL-"
                         "segment catch-up drain, live shard handoff "
                         "under traffic) and exits nonzero on a gate "
                         "failure; "
                         "'multichip' runs the multi-device fused-scan "
                         "stage in-process (8 virtual devices on host "
                         "platforms) and exits nonzero if the fused "
                         "path loses to the general path; 'wal' runs "
                         "the durability stage (WAL on/off ingest, "
                         "replay, remote_write door, kill-mid-ingest "
                         "zero-acked-loss proof) and exits nonzero on "
                         "a gate failure; 'longrange' runs the "
                         "historical-tier stage (compacted segments, "
                         "cold DeviceMirror region, tier-stitched "
                         "planning) and exits nonzero when a cold-scan "
                         "or stitch gate fails; 'selfmon' runs the "
                         "self-scrape meta-monitoring stage (overhead "
                         "on concurrent QPS + scrape p50) and exits "
                         "nonzero when overhead exceeds 2%; "
                         "'ingesttrace' runs the write-path tracing "
                         "stage (span-pipeline tax on the remote_write "
                         "door, the stitched 2-node trace proof, the "
                         "wal.fsync fault-visibility drill) and exits "
                         "nonzero when tracing-on falls under 98% of "
                         "tracing-off or the trace/fault evidence is "
                         "missing; 'activequeries' runs the live-"
                         "introspection stage (registry tax on "
                         "concurrent QPS, gate <= 2%, plus the two-node "
                         "cold-query kill drill: structured "
                         "query_canceled, slot freed, remote drained "
                         "within 250 ms) and exits nonzero on a gate "
                         "failure; 'qos' runs the multi-tenant "
                         "noisy-neighbor stage (one abusive tenant "
                         "floods the frontend at full concurrency "
                         "while well-behaved tenants keep polling; "
                         "gates good-tenant p99 within 1.5x of idle "
                         "and the abuser receiving structured 429 + "
                         "Retry-After, never query_timeout) and exits "
                         "nonzero on a gate failure; 'index' runs the "
                         "high-cardinality bitmap-index stage (10M-key "
                         "zipf shard; gates regex first-plan p50 < 10 "
                         "ms, equals p50 < 1 ms, and a 3x churn soak "
                         "holding index memory within 10%) and exits "
                         "nonzero on a gate failure; 'exprfuse' runs "
                         "the whole-expression compilation stage (an "
                         "8-mixed-panel dashboard incl. vector-matching "
                         "binary ops over a 1M-series store, compiled "
                         "batch vs per-node assembly; gates >= 5x p50 "
                         "and bit-identical results) and exits nonzero "
                         "on a gate failure; 'devicetelem' runs the "
                         "device-telemetry stage on 8 virtual devices "
                         "(kernel-ledger tax on concurrent engine QPS "
                         "and on the flagship fused scan, both gated "
                         "<= 2%; a 12-shape compile-storm drill that "
                         "must be attributable in the ledger, fill "
                         "jit_compile_seconds, and flip device health; "
                         "per-chip mesh dispatch reconcile) and exits "
                         "nonzero on a gate failure")
    ap.add_argument("--quick", action="store_true",
                    help="small config for smoke runs")
    ap.add_argument("--series", type=int, default=0)
    ap.add_argument("--iters", type=int, default=0)
    ap.add_argument("--_worker", action="store_true",
                    help="internal: run the measurement in this process")
    ap.add_argument("--run-id", default="")
    ap.add_argument("--platform", default="default",
                    choices=["default", "cpu"],
                    help="internal: pin the jax platform for a worker run")
    return ap.parse_args(argv)


def assemble_result(platform, stages, vec_sps, it_sps, c_sps=0.0,
                    partial=False):
    """One JSON line from whatever stages completed.  The headline is the
    highest-throughput trusted stage — comparable round-over-round; on
    chip the 1M north-star stage wins this naturally (bigger batches
    amortize better), while the CPU fallback's relaxed-budget 1M point
    rides along in the north_star_* fields instead of deflating the
    headline."""
    best_name, best = None, None
    for name, st in stages.items():
        if "samples_per_sec" in st and (
                best is None or st["samples_per_sec"]
                > best["samples_per_sec"]):
            best_name, best = name, st
    result = {"metric": "promql_samples_scanned_per_sec",
              "unit": "samples/s", "platform": platform}
    if best is None:
        result.update({"value": 0.0, "vs_baseline": 0.0,
                       "error": "no stage produced a trusted number"})
    else:
        result.update({
            "value": best["samples_per_sec"],
            "vs_baseline": (round(best["samples_per_sec"] / vec_sps, 2)
                            if vec_sps else 0.0),
            "p50_query_latency_s": best["p50_s"],
            "kernel": best.get("kernel"),
            "series": best["series"], "windows": best["windows"],
            "groups": best["groups"], "headline_stage": best_name,
        })
        if vec_sps:
            result["baseline_samples_per_sec"] = round(vec_sps, 1)
            result["baseline_kind"] = \
                "vectorized numpy, same algorithm, host CPU"
        if it_sps:
            result["iterator_baseline_samples_per_sec"] = round(it_sps, 1)
            result["vs_iterator_baseline"] = \
                round(best["samples_per_sec"] / it_sps, 1)
        if c_sps:
            # the honest compiled-iterator comparator (single C core; no
            # JVM exists here — see BASELINE.md north-star note)
            result["iterator_c_samples_per_sec"] = round(c_sps, 1)
            result["vs_iterator_c"] = \
                round(best["samples_per_sec"] / c_sps, 1)
    ing = stages.get("ingest", {})
    if "ingest_samples_per_sec" in ing:
        # the host half of the pipeline, in the parsed line from round 1
        # (this PR's ISSUE: the driver must track ingest, not just scan)
        result["ingest_samples_per_sec"] = ing["ingest_samples_per_sec"]
        result["ingest_series"] = ing["series"]
    cov = stages.get("fused_coverage", {})
    for k in ("fused_coverage_dense", "fused_coverage_ragged"):
        if k in cov:
            result[k] = cov[k]
    db = stages.get("dashboard_batch", {})
    if "speedup_p50" in db:
        result["dashboard_batch_speedup"] = db["speedup_p50"]
    qf = stages.get("query_frontend", {})
    for k in ("concurrent_qps", "cached_repoll_p50_s", "cold_p50_s",
              "sequential_baseline_qps", "qps_vs_sequential",
              "repoll_ratio"):
        if k in qf:
            # the PR-2 serving acceptance pair (+ context): concurrent
            # dashboard QPS through the frontend and the warm re-poll p50
            result[k] = qf[k]
    obs = stages.get("observability", {})
    if "span_overhead_pct" in obs:
        # PR-3 acceptance: span+stats attribution overhead on the
        # query_frontend QPS number (gate: <= 5%)
        result["span_overhead_pct"] = obs["span_overhead_pct"]
        result["observability_stats_ok"] = obs.get("stats_phases_ok")
    aq = stages.get("activequeries", {})
    for k in ("activequeries_overhead_pct", "activequeries_gate_ok",
              "activequeries_kill_structured", "activequeries_stop_ms",
              "activequeries_slot_freed", "activequeries_listed_remote",
              "activequeries_kill_to_client_ms"):
        if k in aq:
            # ISSUE-13 acceptance: registry tax on concurrent QPS
            # (gate <= 2%) + the kill-drill evidence
            result[k] = aq[k]
    if "error" in aq:
        result["activequeries_error"] = aq["error"]
    sm = stages.get("selfmon", {})
    for k in ("selfmon_overhead_pct", "selfmon_scrape_p50_s",
              "selfmon_scrape_series", "selfmon_gate_ok"):
        if k in sm:
            # ISSUE-10 acceptance: the self-scrape tax on concurrent QPS
            # (gate: <= 2% at the default selfmon.interval_s) and the
            # scrape p50
            result[k] = sm[k]
    if "error" in sm:
        # loud-fail contract (like multichip/wal/longrange): a broken
        # self-monitoring stage rides into the parsed line
        result["selfmon_error"] = sm["error"]
    rul = stages.get("ruler", {})
    for k in ("ruler_eval_p50_s", "recorded_query_speedup_x",
              "ruler_overhead_pct"):
        if k in rul:
            # PR-5 acceptance: full group-iteration p50 (8 rules through
            # the frontend + write-back), the dashboard aggregate served
            # from the recorded series vs the raw expression (gate:
            # >= 10x), and the standing-query tax on serving QPS
            result[k] = rul[k]
    mc = stages.get("multichip", {})
    for k in ("multichip_fused_warm_s", "multichip_general_warm_s",
              "multichip_scaling_x", "multichip_inversion_gone",
              "multichip_fused_route", "multichip_pack_memo_hits"):
        if k in mc:
            # ISSUE-6 acceptance: per-device fused dispatch vs the
            # general mesh path (gate: fused <= general — the
            # MULTICHIP_r05 30x inversion is dead) + mesh scaling vs one
            # device and the repack-memo hit evidence
            result[k] = mc[k]
    if "error" in mc:
        # the loud-fail contract: a TPU box without >= 2 devices (or any
        # multichip failure) rides into the parsed line, never vanishes
        result["multichip_error"] = mc["error"]
    wl = stages.get("wal", {})
    for k in ("remote_write_samples_per_sec", "wal_overhead_pct",
              "wal_on_vs_off_pct", "wal_on_samples_per_sec",
              "wal_replay_samples_per_sec", "wal_kill_acked_lost",
              "wal_kill_query_identical"):
        if k in wl:
            # ISSUE-7 acceptance: the durability tax (gate: WAL-on >=
            # 50% of WAL-off), replay rate, the remote_write door rate,
            # and the kill-chaos zero-acked-loss proof (gate: 0 lost,
            # recovered answers byte-identical)
            result[k] = wl[k]
    for k in ("error", "wal_kill_error"):
        if k in wl:
            result["wal_error"] = wl[k]
    it = stages.get("ingesttrace", {})
    for k in ("ingest_trace_overhead_pct",
              "ingest_trace_on_samples_per_sec",
              "ingest_trace_stitched", "ingest_trace_nodes",
              "ingest_freshness_p99_s", "ingesttrace_fault_visible",
              "ingesttrace_gate_ok"):
        if k in it:
            # ISSUE-12 acceptance: tracing-on >= 98% of tracing-off on
            # the remote_write door, ONE stitched 2-node write-path
            # trace, and the wal.fsync fault drill visible in the fsync
            # histogram + ingest slowlog + freshness histograms + the
            # health verdict
            result[k] = it[k]
    if "error" in it:
        # loud-fail contract (like wal/selfmon): a broken write-path
        # tracing stage rides into the parsed line, never vanishes
        result["ingesttrace_error"] = it["error"]
    lr = stages.get("longrange", {})
    for k in ("longrange_cold_scan_samples_per_sec",
              "longrange_warm_cold_ratio", "longrange_stitch_identical",
              "longrange_cold_vs_mem_ratio",
              "longrange_mem_samples_per_sec", "longrange_lru_bounded",
              "longrange_gate_ok"):
        if k in lr:
            # ISSUE-8 acceptance: cold first-scan >= 1/10 of in-memory,
            # cold-region-resident re-scan >= 1/2, stitched
            # raw+downsample+persisted bit-identical to a single-tier
            # reference, and the cold region's LRU byte bound held
            result[k] = lr[k]
    if "error" in lr:
        # loud-fail contract (like multichip): a broken historical tier
        # rides into the parsed line, never vanishes
        result["longrange_error"] = lr["error"]
    dx = stages.get("distexec", {})
    for k in ("distexec_wire_bytes_ratio", "distexec_pushdown_speedup_x",
              "distexec_bit_identical", "distexec_frontend_peak_rss_mb",
              "distexec_baseline_peak_rss_mb", "distexec_rss_budget_mb",
              "distexec_stream_frames", "distexec_stream_identical",
              "distexec_pushed_nodes", "distexec_gate_ok"):
        if k in dx:
            # ISSUE-15 acceptance: 4-node fan-out aggregation moves
            # >= 10x fewer wire bytes pushed vs ship-everything (bit-
            # identical), and a long-range streamed reply holds traced
            # peak memory under a fixed budget that the materialize-
            # everything baseline exceeds
            result[k] = dx[k]
    if "error" in dx:
        result["distexec_error"] = dx["error"]
    ix = stages.get("index", {})
    for k in ("index_series", "index_build_keys_per_sec",
              "index_equals_lookup_p50_ms", "index_regex_plan_p50_ms",
              "index_regex_plan_max_ms", "index_regex_memo_p50_ms",
              "index_trigram_build_ms", "index_churn_rss_growth_pct",
              "index_memory_bytes", "index_gate_ok"):
        if k in ix:
            # ISSUE-16 acceptance: bitmap postings plan `=~` under 10 ms
            # and answer equals under 1 ms on a zipf shard, while the
            # churn soak holds index memory within 10% across evict-all
            # generations (compaction + container rebase working)
            result[k] = ix[k]
    if "error" in ix:
        result["index_error"] = ix["error"]
    ef = stages.get("exprfuse", {})
    for k in ("exprfuse_p50_s", "exprfuse_baseline_p50_s",
              "exprfuse_speedup_x", "exprfuse_identical",
              "exprfuse_fused", "exprfuse_degraded",
              "exprfuse_memo_hits", "exprfuse_gate_ok"):
        if k in ef:
            # ISSUE-17 acceptance: the 8-mixed-panel dashboard compiled
            # as one batch runs >= 5x faster than per-node assembly with
            # BIT-identical results (and every panel fused, none
            # degraded)
            result[k] = ef[k]
    for k in ("error", "exprfuse_error"):
        if k in ef:
            result["exprfuse_error"] = ef[k]
    dtl = stages.get("devicetelem", {})
    for k in ("devicetelem_overhead_pct", "devicetelem_fused_overhead_pct",
              "devicetelem_parity_ok", "devicetelem_storm_compiles",
              "devicetelem_storm_attributed",
              "devicetelem_storm_hist_count",
              "devicetelem_storm_health_degraded",
              "devicetelem_mesh_reconciled", "devicetelem_gate_ok"):
        if k in dtl:
            # ISSUE-18 acceptance: the per-chip kernel ledger costs
            # <= 2% on concurrent QPS and on the flagship fused scan,
            # an injected recompile storm is attributable (shape +
            # origin) and flips device health, and per-device mesh
            # dispatch counts reconcile with the untagged counter
            result[k] = dtl[k]
    if "error" in dtl:
        result["devicetelem_error"] = dtl["error"]
    ns = stages.get("north_star_1m") or stages.get("cpu_north_star_1m")
    if ns and "samples_per_sec" in ns:
        result.update({
            "north_star_series": ns["series"],
            "north_star_p50_s": ns["p50_s"],
            "north_star_samples_per_sec": ns["samples_per_sec"],
            "north_star_kernel": ns.get("kernel"),
        })
    if partial:
        result["partial"] = True
    # ONE COMPACT LINE is the driver contract (BENCH_r04.json came back
    # "parsed": null when embedded stage detail outgrew the driver's tail
    # capture) — full stage dicts live in BENCH_PARTIAL.json; the line
    # carries only a per-stage p50 summary
    result["stage_p50_s"] = {
        name: st.get("p50_s") for name, st in stages.items()
        if isinstance(st, dict) and "p50_s" in st}
    result["stage_detail"] = "BENCH_PARTIAL.json"
    return result


def run_worker(args):
    import jax

    # persistent compile cache: repeated tunnel-window attempts must not pay
    # cold XLA compiles again (round-3 verdict item 1c)
    cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR",
                               os.path.join(REPO_DIR, ".jax_cache"))
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # noqa: BLE001 — cache is an optimization only
        pass

    if args.platform == "cpu":
        # Env vars are too late once the sitecustomize hook has imported
        # jax — pin via jax.config (same fix as tests/conftest.py).
        jax.config.update("jax_platforms", "cpu")

    raw_platform = jax.devices()[0].platform
    # the tunneled TPU registers as the experimental 'axon' platform; label
    # it by the hardware it is, keeping the raw backend name alongside
    platform = "tpu" if raw_platform == "axon" else raw_platform
    quick = args.quick
    T = 720                                  # 2h of 10s samples
    iters = args.iters or (3 if quick else 10)
    writer = PartialWriter(args.run_id or "adhoc", platform)
    writer.doc["jax_platform"] = raw_platform

    if args.series:
        ladder = [("explicit", args.series, iters)]
    elif quick:
        ladder = [("quick_8k", 8_192, iters)]
    elif platform == "cpu":
        # fallback runs must finish within the supervisor timeout; the 1M
        # north-star SHAPE still gets a measured point (relaxed iters) so
        # the target workload has executed somewhere every round
        ladder = [("cpu_65k", 65_536, iters),
                  ("cpu_north_star_1m", 1_048_576, 3)]
    else:
        # smallest-first: a 5-minute tunnel window must still leave a
        # trusted TPU number behind before the big stages start
        ladder = [("warm_8k", 8_192, iters),
                  ("warm_65k", 65_536, iters),
                  ("warm_262k", 262_144, iters),
                  ("north_star_1m", 1_048_576, iters)]

    stages = {}
    baseline_inputs = None
    conformance_ok = False
    for name, S, stage_iters in ladder:
        try:
            st, ts_row, vals, gids, wends, range_ms, span = measure_stage(
                S, T, stage_iters, platform,
                do_fused=platform != "cpu",
                persist=lambda d, n=name: writer.stage(n, d),
                prior_conformance_ok=conformance_ok)
            conformance_ok = conformance_ok or bool(
                st.get("conformance_ok"))
            stages[name] = st
            if baseline_inputs is None or S <= 262_144:
                baseline_inputs = (ts_row, vals, gids, wends, range_ms,
                                   span)
            else:
                del ts_row, vals
        except Exception as e:  # noqa: BLE001 — later stages may still work
            stages[name] = {"series": S, "samples_per_series": T,
                            "error": f"{type(e).__name__}: {e}"[:300]}
            writer.stage(name, stages[name])

    vec_sps = it_sps = c_sps = 0.0
    if baseline_inputs is not None:
        vec_sps, it_sps, c_sps = host_baselines(*baseline_inputs)
        writer.stage("host_baselines", {
            "vectorized_numpy_samples_per_sec": round(vec_sps, 1),
            "iterator_numpy_samples_per_sec": round(it_sps, 1),
            "iterator_c_samples_per_sec": round(c_sps, 1)})

    try:
        ing = measure_ingest(series=65_536 if quick else 262_144,
                             max_seconds=5.0 if quick else 10.0)
        writer.stage("ingest", ing)
        stages["ingest"] = ing
    except Exception as e:  # noqa: BLE001 — ingest stage must not sink the run
        writer.stage("ingest", {"error": f"{type(e).__name__}: {e}"[:300]})

    try:
        cov = measure_fused_coverage()
        writer.stage("fused_coverage", cov)
        stages["fused_coverage"] = cov
    except Exception as e:  # noqa: BLE001 — coverage must not sink the run
        writer.stage("fused_coverage",
                     {"error": f"{type(e).__name__}: {e}"[:300]})

    if not quick:
        try:
            db = measure_dashboard_batch(platform)
            writer.stage("dashboard_batch", db)
            stages["dashboard_batch"] = db
        except Exception as e:  # noqa: BLE001 — must not sink the run
            writer.stage("dashboard_batch",
                         {"error": f"{type(e).__name__}: {e}"[:300]})

    try:
        qf = measure_query_frontend(quick=quick)
        writer.stage("query_frontend", qf)
        stages["query_frontend"] = qf
    except Exception as e:  # noqa: BLE001 — must not sink the run
        writer.stage("query_frontend",
                     {"error": f"{type(e).__name__}: {e}"[:300]})

    try:
        obs = measure_observability(quick=quick)
        writer.stage("observability", obs)
        stages["observability"] = obs
    except Exception as e:  # noqa: BLE001 — must not sink the run
        writer.stage("observability",
                     {"error": f"{type(e).__name__}: {e}"[:300]})

    try:
        # live-introspection stage (ISSUE 13): registry tax on the
        # concurrent-QPS workload (gate: <= 2%) + the two-node cold-
        # query kill drill
        aq = measure_activequeries(quick=quick)
        writer.stage("activequeries", aq)
        stages["activequeries"] = aq
    except Exception as e:  # noqa: BLE001 — must not sink the run
        stages["activequeries"] = {"error": f"{type(e).__name__}: {e}"[:300]}
        writer.stage("activequeries", stages["activequeries"])

    try:
        # self-observability stage (ISSUE 10): self-scrape overhead on
        # the serving QPS number + the scrape p50 (gate: <= 2%)
        sm = measure_selfmon(quick=quick)
        writer.stage("selfmon", sm)
        stages["selfmon"] = sm
    except Exception as e:  # noqa: BLE001 — must not sink the run
        stages["selfmon"] = {"error": f"{type(e).__name__}: {e}"[:300]}
        writer.stage("selfmon", stages["selfmon"])

    try:
        rul = measure_ruler(quick=quick)
        writer.stage("ruler", rul)
        stages["ruler"] = rul
    except Exception as e:  # noqa: BLE001 — must not sink the run
        writer.stage("ruler", {"error": f"{type(e).__name__}: {e}"[:300]})

    try:
        # durability stage (ISSUE 7): WAL on/off ingest, replay rate,
        # remote_write door, kill-mid-ingest zero-acked-loss proof
        wl = measure_wal(quick=quick)
        writer.stage("wal", wl)
        stages["wal"] = wl
    except Exception as e:  # noqa: BLE001 — must not sink the run
        stages["wal"] = {"error": f"{type(e).__name__}: {e}"[:300]}
        writer.stage("wal", stages["wal"])

    try:
        # write-path tracing stage (ISSUE 12): span-pipeline tax on the
        # remote_write door, stitched 2-node trace, fault visibility
        it = measure_ingesttrace(quick=quick)
        writer.stage("ingesttrace", it)
        stages["ingesttrace"] = it
    except Exception as e:  # noqa: BLE001 — must not sink the run
        stages["ingesttrace"] = {"error": f"{type(e).__name__}: {e}"[:300]}
        writer.stage("ingesttrace", stages["ingesttrace"])

    try:
        # historical-tier stage (ISSUE 8): compacted segments, cold
        # DeviceMirror region, tier-stitched planning
        lr = measure_longrange(quick=quick)
        writer.stage("longrange", lr)
        stages["longrange"] = lr
    except Exception as e:  # noqa: BLE001 — must not sink the run
        stages["longrange"] = {"error": f"{type(e).__name__}: {e}"[:300]}
        writer.stage("longrange", stages["longrange"])

    try:
        # distributed-execution stage (ISSUE 15): 4-node aggregation
        # pushdown wire ratio + bit-identity, streamed-reply peak-RSS
        # bound vs the materialize-everything baseline
        dx = measure_distexec(quick=quick)
        writer.stage("distexec", dx)
        stages["distexec"] = dx
    except Exception as e:  # noqa: BLE001 — must not sink the run
        stages["distexec"] = {"error": f"{type(e).__name__}: {e}"[:300]}
        writer.stage("distexec", stages["distexec"])

    try:
        # bitmap index stage (ISSUE 16): ladder-sized shard (1M full /
        # 50k quick — the gating 10M run is the standalone `index`
        # stage); regex planning + equals p50, churn memory flatness
        ix = measure_index(quick=quick,
                           series=(50_000 if quick else 1_000_000))
        writer.stage("index", ix)
        stages["index"] = ix
    except Exception as e:  # noqa: BLE001 — must not sink the run
        stages["index"] = {"error": f"{type(e).__name__}: {e}"[:300]}
        writer.stage("index", stages["index"])

    try:
        # whole-expression compilation stage (ISSUE 17): 8-mixed-panel
        # dashboard (incl. vector-matching binary ops) compiled as one
        # batch vs per-node assembly — 1M series full, 8k quick
        ef = measure_exprfuse(quick=quick)
        writer.stage("exprfuse", ef)
        stages["exprfuse"] = ef
    except Exception as e:  # noqa: BLE001 — must not sink the run
        stages["exprfuse"] = {"error": f"{type(e).__name__}: {e}"[:300]}
        writer.stage("exprfuse", stages["exprfuse"])

    try:
        # kernel-ledger tax + compile-storm drill; the mesh reconcile
        # leg self-skips on a 1-device box (the standalone entry forces
        # 8 virtual devices for it)
        dtl = measure_devicetelem(quick=quick)
        writer.stage("devicetelem", dtl)
        stages["devicetelem"] = dtl
    except Exception as e:  # noqa: BLE001 — must not sink the run
        stages["devicetelem"] = {
            "error": f"{type(e).__name__}: {e}"[:300]}
        writer.stage("devicetelem", stages["devicetelem"])

    try:
        # measure_fused_coverage leaves FILODB_TPU_FUSED_INTERPRET=1
        # behind for the dashboard stage's interpret-mode CPU kernel
        # runs; inheriting it here would reroute the per-device unit
        # from the host fused leaf into interpret-mode Pallas at full
        # scale — orders of magnitude slower, and a route production
        # never takes.  Nothing after this stage reads the var.
        os.environ.pop("FILODB_TPU_FUSED_INTERPRET", None)
        mc = measure_multichip(quick=quick)
        writer.stage("multichip", mc)
        stages["multichip"] = mc
    except Exception as e:  # noqa: BLE001 — a 1-device box records a
        # LOUD error here (never a skip): a TPU claim without >= 2
        # devices must surface in the one-line JSON (ISSUE 6 satellite)
        stages["multichip"] = {"error": f"{type(e).__name__}: {e}"[:300]}
        writer.stage("multichip", stages["multichip"])

    result = assemble_result(platform, stages, vec_sps, it_sps,
                             c_sps)
    result["jax_platform"] = raw_platform
    writer.finish()
    print(json.dumps(result))


def _spawn_worker(args, platform, timeout_s, run_id):
    """Run the measurement in a child under a hard timeout; return the
    parsed JSON result dict or None."""
    cmd = [sys.executable, os.path.abspath(__file__), "--_worker",
           "--platform", platform, "--run-id", run_id]
    if args.quick:
        cmd.append("--quick")
    if args.series:
        cmd += ["--series", str(args.series)]
    if args.iters:
        cmd += ["--iters", str(args.iters)]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout_s)
    except subprocess.TimeoutExpired:
        print(f"bench: worker ({platform}) timed out after {timeout_s}s",
              file=sys.stderr)
        return None
    if proc.returncode != 0:
        tail = "\n".join(proc.stderr.strip().splitlines()[-5:])
        print(f"bench: worker ({platform}) rc={proc.returncode}:\n{tail}",
              file=sys.stderr)
        return None
    for line in reversed(proc.stdout.strip().splitlines()):
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    print(f"bench: worker ({platform}) emitted no JSON", file=sys.stderr)
    return None


def _recover_partial(run_id):
    """If a dead worker left completed stages behind, synthesize the final
    line from them (partial=true) rather than discarding TPU evidence."""
    try:
        with open(PARTIAL_PATH) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if doc.get("run_id") != run_id or not doc.get("stages"):
        return None
    hb = doc["stages"].get("host_baselines", {})
    result = assemble_result(
        doc.get("platform", "unknown"), doc["stages"],
        hb.get("vectorized_numpy_samples_per_sec", 0.0),
        hb.get("iterator_numpy_samples_per_sec", 0.0),
        hb.get("iterator_c_samples_per_sec", 0.0), partial=True)
    if result.get("value"):
        return result
    return None


def _probe_default_backend(timeout_s):
    """Init the default jax backend in a child; return its platform name or
    None if init fails/hangs.  Cheap insurance against the tunneled-TPU
    backend hanging indefinitely (it did in round 1)."""
    code = "import jax; print(jax.devices()[0].platform)"
    try:
        p = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        print(f"bench: backend probe timed out after {timeout_s}s",
              file=sys.stderr)
        return None
    if p.returncode == 0 and p.stdout.strip():
        return p.stdout.strip().splitlines()[-1]
    return None


def main():
    args = parse_args()
    if args.stage == "multichip":
        # standalone multi-chip stage: runs IN THIS process.  Host
        # platforms get 8 virtual devices — XLA_FLAGS must land before
        # the first backend init (jax may already be imported by the
        # sitecustomize hook; backends initialize lazily, so the env var
        # still takes).  A TPU backend ignores the host-platform flag.
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
        try:
            mc = measure_multichip(quick=args.quick,
                                   series=args.series or None,
                                   iters=args.iters)
        except Exception as e:  # noqa: BLE001 — loud one-line fail
            print(json.dumps({
                "metric": "multichip_fused_warm_s", "unit": "s",
                "error": f"{type(e).__name__}: {e}"[:300]}))
            sys.exit(1)
        mc = {"metric": "multichip_fused_warm_s", "unit": "s",
              "value": mc.get("multichip_fused_warm_s"), **mc}
        print(json.dumps(mc))
        sys.exit(0 if mc.get("multichip_inversion_gone") else 1)
    if args.stage == "wal":
        # standalone durability stage: CPU-pinned (the WAL measures the
        # host ingest + fsync path, not kernels); prints the one-line
        # wal JSON and exits nonzero when a hard gate fails — WAL-on
        # under 50% of WAL-off, or ANY acknowledged sample lost in the
        # kill-chaos replay
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        wl = measure_wal(quick=args.quick, series=args.series or None)
        wl = {"metric": "wal_on_samples_per_sec", "unit": "samples/s",
              "value": wl.get("wal_on_samples_per_sec"), **wl}
        print(json.dumps(wl))
        # the durability gates always hold; the 50% throughput gate is
        # judged at FULL scale only (quick's toy batches cannot amortize
        # an fsync — the reported ratio still rides the line)
        ok = (wl.get("wal_kill_acked_lost") == 0
              and wl.get("wal_kill_query_identical")
              and (args.quick or wl.get("wal_gate_ok")))
        sys.exit(0 if ok else 1)
    if args.stage == "longrange":
        # standalone historical-tier stage: CPU-pinned like wal (the
        # gates are ratios against an in-memory reference on the same
        # backend); prints the one-line longrange JSON and exits nonzero
        # when a gate fails
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        # persistent XLA compile cache, like run_worker: the stage's warm
        # numbers must not be polluted by first-boot compiles
        cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR",
                                   os.path.join(REPO_DIR, ".jax_cache"))
        try:
            import jax
            os.makedirs(cache_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 1.0)
        except Exception:  # noqa: BLE001 — cache is an optimization only
            pass
        try:
            lr = measure_longrange(quick=args.quick,
                                   series=args.series or None)
        except Exception as e:  # noqa: BLE001 — loud one-line fail
            print(json.dumps({
                "metric": "longrange_cold_scan_samples_per_sec",
                "unit": "samples/s",
                "longrange_error": f"{type(e).__name__}: {e}"[:300]}))
            sys.exit(1)
        lr = {"metric": "longrange_cold_scan_samples_per_sec",
              "unit": "samples/s",
              "value": lr.get("longrange_cold_scan_samples_per_sec"),
              **lr}
        print(json.dumps(lr))
        # correctness gates always hold; the throughput ratios are judged
        # at FULL scale only (quick's toy windows cannot amortize a
        # page-in — the measured ratios still ride the line)
        ok = (lr.get("longrange_stitch_identical")
              and lr.get("longrange_lru_bounded")
              and (args.quick or lr.get("longrange_gate_ok")))
        sys.exit(0 if ok else 1)
    if args.stage == "selfmon":
        # standalone self-observability stage: CPU-pinned (it measures
        # the scrape + serving overhead, not kernels); prints the
        # one-line selfmon JSON, exits nonzero when the 2% overhead
        # gate fails or the stage errors (loud-fail contract)
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        try:
            sm = measure_selfmon(quick=args.quick,
                                 series=args.series or None)
        except Exception as e:  # noqa: BLE001 — loud one-line fail
            print(json.dumps({
                "metric": "selfmon_overhead_pct", "unit": "%",
                "selfmon_error": f"{type(e).__name__}: {e}"[:300]}))
            sys.exit(1)
        sm = {"metric": "selfmon_overhead_pct", "unit": "%",
              "value": sm.get("selfmon_overhead_pct"), **sm}
        if "error" in sm:
            sm["selfmon_error"] = sm["error"]
        print(json.dumps(sm))
        # quick's short pumps are too noisy to judge a 2% ratio; the
        # measured number still rides the line
        sys.exit(0 if "error" not in sm
                 and (args.quick or sm.get("selfmon_gate_ok")) else 1)
    if args.stage == "ingesttrace":
        # standalone write-path tracing stage: CPU-pinned (it measures
        # the door + WAL + replication path, not kernels); prints the
        # one-line ingesttrace JSON and exits nonzero when a gate fails
        # (loud-fail contract like wal/selfmon)
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        try:
            it = measure_ingesttrace(quick=args.quick,
                                     series=args.series or None)
        except Exception as e:  # noqa: BLE001 — loud one-line fail
            print(json.dumps({
                "metric": "ingest_trace_overhead_pct", "unit": "%",
                "ingesttrace_error": f"{type(e).__name__}: {e}"[:300]}))
            sys.exit(1)
        it = {"metric": "ingest_trace_overhead_pct", "unit": "%",
              "value": it.get("ingest_trace_overhead_pct"), **it}
        print(json.dumps(it))
        # the stitched-trace and fault-visibility proofs always gate;
        # the 2% throughput tax is judged at FULL scale only (quick's
        # toy batches cannot average out scheduler noise)
        sys.exit(0 if it.get("ingesttrace_gate_ok") else 1)
    if args.stage == "activequeries":
        # standalone live-introspection stage: CPU-pinned (it measures
        # registry/kill machinery, not kernels); prints the one-line
        # activequeries JSON and exits nonzero when a gate fails
        # (loud-fail contract like selfmon/ingesttrace)
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        try:
            aq = measure_activequeries(quick=args.quick,
                                       series=args.series or None)
        except Exception as e:  # noqa: BLE001 — loud one-line fail
            print(json.dumps({
                "metric": "activequeries_overhead_pct", "unit": "%",
                "activequeries_error": f"{type(e).__name__}: {e}"[:300]}))
            sys.exit(1)
        aq = {"metric": "activequeries_overhead_pct", "unit": "%",
              "value": aq.get("activequeries_overhead_pct"), **aq}
        if "error" in aq:
            aq["activequeries_error"] = aq["error"]
        print(json.dumps(aq))
        # the kill-drill correctness gates always hold; the 2% overhead
        # and 250 ms drain ratios are judged at FULL scale only (quick's
        # short pumps are too noisy)
        sys.exit(0 if "error" not in aq
                 and aq.get("activequeries_gate_ok") else 1)
    if args.stage == "qos":
        # standalone multi-tenant QoS stage: CPU-pinned (it measures the
        # fairness/shedding machinery, not kernels); prints the one-line
        # qos JSON and exits nonzero when the noisy-neighbor gate fails
        # (loud-fail contract like selfmon/activequeries)
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        try:
            qs = measure_qos(quick=args.quick,
                             series=args.series or None)
        except Exception as e:  # noqa: BLE001 — loud one-line fail
            print(json.dumps({
                "metric": "qos_p99_ratio", "unit": "x",
                "qos_error": f"{type(e).__name__}: {e}"[:300]}))
            sys.exit(1)
        qs = {"metric": "qos_p99_ratio", "unit": "x",
              "value": qs.get("qos_p99_ratio"), **qs}
        if "error" in qs:
            qs["qos_error"] = qs["error"]
        print(json.dumps(qs))
        sys.exit(0 if "error" not in qs and qs.get("qos_gate_ok")
                 else 1)
    if args.stage == "distexec":
        # standalone distributed-execution stage: CPU-pinned (it
        # measures wire/merge machinery, not kernels); prints the
        # one-line distexec JSON and exits nonzero when a gate fails
        # (loud-fail contract like selfmon/activequeries)
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        try:
            dx = measure_distexec(quick=args.quick,
                                  series=args.series or None)
        except Exception as e:  # noqa: BLE001 — loud one-line fail
            print(json.dumps({
                "metric": "distexec_wire_bytes_ratio", "unit": "x",
                "distexec_error": f"{type(e).__name__}: {e}"[:300]}))
            sys.exit(1)
        dx = {"metric": "distexec_wire_bytes_ratio", "unit": "x",
              "value": dx.get("distexec_wire_bytes_ratio"), **dx}
        if "error" in dx:
            dx["distexec_error"] = dx["error"]
        print(json.dumps(dx))
        sys.exit(0 if "error" not in dx and dx.get("distexec_gate_ok")
                 else 1)
    if args.stage == "index":
        # standalone high-cardinality index stage: CPU-pinned (it
        # measures posting/planning machinery, not kernels); builds the
        # full 10M-key zipf shard, prints the one-line index JSON and
        # exits nonzero when a gate fails (loud-fail contract like
        # selfmon/distexec)
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        try:
            ix = measure_index(quick=args.quick,
                               series=args.series or None)
        except Exception as e:  # noqa: BLE001 — loud one-line fail
            print(json.dumps({
                "metric": "index_regex_plan_p50_ms", "unit": "ms",
                "index_error": f"{type(e).__name__}: {e}"[:300]}))
            sys.exit(1)
        ix = {"metric": "index_regex_plan_p50_ms", "unit": "ms",
              "value": ix.get("index_regex_plan_p50_ms"), **ix}
        if "error" in ix:
            ix["index_error"] = ix["error"]
        print(json.dumps(ix))
        sys.exit(0 if "error" not in ix and ix.get("index_gate_ok")
                 else 1)
    if args.stage == "exprfuse":
        # standalone whole-expression compilation stage: CPU-pinned (it
        # measures the expression compiler + scan sharing, not kernels —
        # the stage pins host-routed leaves on every backend anyway);
        # builds the full 1M-series dashboard store, prints the one-line
        # exprfuse JSON and exits nonzero when a gate fails (loud-fail
        # contract like distexec/index)
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        try:
            ef = measure_exprfuse(quick=args.quick,
                                  series=args.series or None,
                                  iters=args.iters)
        except Exception as e:  # noqa: BLE001 — loud one-line fail
            print(json.dumps({
                "metric": "exprfuse_speedup_x", "unit": "x",
                "exprfuse_error": f"{type(e).__name__}: {e}"[:300]}))
            sys.exit(1)
        ef = {"metric": "exprfuse_speedup_x", "unit": "x",
              "value": ef.get("exprfuse_speedup_x"), **ef}
        if "error" in ef:
            ef["exprfuse_error"] = ef["error"]
        print(json.dumps(ef))
        sys.exit(0 if "error" not in ef and "exprfuse_error" not in ef
                 and ef.get("exprfuse_gate_ok") else 1)
    if args.stage == "devicetelem":
        # standalone device-telemetry stage: CPU-pinned with 8 virtual
        # host devices so the per-chip mesh reconcile leg runs (ISSUE-18
        # acceptance wants /admin/devices reflecting real per-chip
        # placement, not a 1-device degenerate); prints the one-line
        # devicetelem JSON and exits nonzero when a gate fails
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        try:
            dtl = measure_devicetelem(quick=args.quick,
                                      series=args.series or None,
                                      iters=args.iters)
        except Exception as e:  # noqa: BLE001 — loud one-line fail
            print(json.dumps({
                "metric": "devicetelem_overhead_pct", "unit": "%",
                "devicetelem_error": f"{type(e).__name__}: {e}"[:300]}))
            sys.exit(1)
        dtl = {"metric": "devicetelem_overhead_pct", "unit": "%",
               "value": dtl.get("devicetelem_overhead_pct"), **dtl}
        if "error" in dtl:
            dtl["devicetelem_error"] = dtl["error"]
        print(json.dumps(dtl))
        sys.exit(0 if "error" not in dtl
                 and "devicetelem_error" not in dtl
                 and dtl.get("devicetelem_gate_ok") else 1)
    if args.stage == "chaos":
        # standalone failure-domain stage: runs IN THIS process (CPU-
        # pinned; chaos measures degradation machinery, not kernels),
        # SIGKILLs and respawns one of three RF-2 data-node
        # subprocesses mid-traffic, prints the one-line chaos JSON and
        # writes SOAK_CHAOS.json; nonzero exit when the flipped gate
        # (availability 1.0, zero partials, zero acked loss) fails
        try:
            r = run_chaos(quick=args.quick, series=args.series or None)
        except Exception as e:  # noqa: BLE001 — loud one-line fail
            print(json.dumps({
                "metric": "chaos_availability", "unit": "fraction",
                "chaos_error": f"{type(e).__name__}: {e}"[:300]}))
            sys.exit(1)
        print(json.dumps(r))
        sys.exit(0 if r.get("chaos_gate_ok") else 1)
    if args.stage == "replication":
        try:
            r = run_replication(quick=args.quick,
                                series=args.series or None)
        except Exception as e:  # noqa: BLE001 — loud one-line fail
            print(json.dumps({
                "metric": "replication_rf2_vs_rf1_pct", "unit": "%",
                "replication_error": f"{type(e).__name__}: {e}"[:300]}))
            sys.exit(1)
        print(json.dumps(r))
        sys.exit(0 if r.get("replication_gate_ok") else 1)
    if args.stage == "objectstore":
        try:
            r = run_objectstore(quick=args.quick,
                                series=args.series or None)
        except Exception as e:  # noqa: BLE001 — loud one-line fail
            print(json.dumps({
                "metric": "objectstore_elastic_qps_ratio", "unit": "x",
                "objectstore_error": f"{type(e).__name__}: {e}"[:300]}))
            sys.exit(1)
        print(json.dumps(r))
        sys.exit(0 if r.get("objectstore_gate_ok") else 1)
    if args.stage == "federation":
        try:
            r = run_federation(quick=args.quick,
                               series=args.series or None)
        except Exception as e:  # noqa: BLE001 — loud one-line fail
            print(json.dumps({
                "metric": "federation_wire_ratio_x", "unit": "x",
                "federation_error": f"{type(e).__name__}: {e}"[:300]}))
            sys.exit(1)
        print(json.dumps(r))
        sys.exit(0 if r.get("federation_gate_ok") else 1)
    if args._worker:
        run_worker(args)
        return

    run_id = f"bench-{os.getpid()}-{int(time.time())}"
    # Supervisor: probe the default backend (the real chip) under a short
    # timeout, run the measurement there if it answers, and otherwise fall
    # back to CPU — so the round always records a number.
    if args.platform == "cpu":
        # explicit CPU request: no probe, no fallback relabeling
        result = _spawn_worker(args, "cpu", 2700, run_id)
        print(json.dumps(result if result is not None else {
            "metric": "promql_samples_scanned_per_sec", "value": 0.0,
            "unit": "samples/s", "vs_baseline": 0.0, "platform": "none",
            "error": "cpu bench attempt failed"}))
        return
    tpu_timeout = int(os.environ.get("FILODB_BENCH_TPU_TIMEOUT",
                                     "600" if args.quick else "2400"))
    plat = _probe_default_backend(180) or _probe_default_backend(90)
    if plat is not None:
        for _ in range(2):
            result = _spawn_worker(args, "default", tpu_timeout, run_id)
            if result is not None:
                print(json.dumps(result))
                return
            rec = _recover_partial(run_id)
            if rec is not None:
                print(json.dumps(rec))
                return
    else:
        # probes hung, but probe flakiness is not proof the chip is gone:
        # one bounded direct attempt before surrendering to CPU
        result = _spawn_worker(args, "default", min(tpu_timeout, 600),
                               run_id)
        if result is not None:
            print(json.dumps(result))
            return
        rec = _recover_partial(run_id)
        if rec is not None:
            print(json.dumps(rec))
            return
    result = _spawn_worker(args, "cpu", 2700, run_id)
    if result is not None:
        result["fallback"] = "cpu (default backend unavailable: probe=%s)" % plat
        print(json.dumps(result))
        return
    rec = _recover_partial(run_id)
    if rec is not None:
        print(json.dumps(rec))
        return
    print(json.dumps({
        "metric": "promql_samples_scanned_per_sec", "value": 0.0,
        "unit": "samples/s", "vs_baseline": 0.0, "platform": "none",
        "error": "all bench attempts failed (default backend + cpu)",
    }))


if __name__ == "__main__":
    main()
