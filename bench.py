"""Headline benchmark: PromQL samples/sec scanned on sum by (rate[5m]).

Mirrors the reference's QueryInMemoryBenchmark workload shape
(ref: jmh/src/main/scala/filodb.jmh/QueryInMemoryBenchmark.scala:31-35,
126-133 — Prom-schema counters, 720 samples @10s, 5m rate windows, sum
aggregation) scaled toward the BASELINE.json north star (1M-series
sum by(rate()) on one chip; multi-chip scales via the mesh path, see
tests/test_mesh.py and __graft_entry__.dryrun_multichip).

Accounting is conservative: "samples scanned" counts every stored sample in
the queried span ONCE (S * samples_in_span), not once per overlapping window
the way the JVM SlidingWindowIterator would touch them — so the number is a
lower bound on iterator-equivalent throughput.

vs_baseline compares against the same algorithm implemented in vectorized
NumPy on host CPU (the strongest portable CPU stand-in we can run here; the
reference publishes no absolute numbers — see BASELINE.md). A second,
per-window loop baseline ("iterator") mimicking ChunkedWindowIterator's
per-window access pattern is reported as an extra field.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""
import argparse
import json
import time

import numpy as np


def make_counter_data(S, T, step_ms=10_000, seed=7):
    rng = np.random.default_rng(seed)
    ts_row = np.arange(T, dtype=np.int64) * step_ms
    vals = np.cumsum(rng.exponential(10.0, size=(S, T)).astype(np.float32),
                     axis=1)
    return ts_row, vals


def numpy_vectorized_baseline(ts_row, vals, gids, G, wends, range_ms):
    """Same algorithm as the device kernel, vectorized NumPy on host."""
    lo = np.searchsorted(ts_row, wends - range_ms, side="left")
    hi = np.searchsorted(ts_row, wends, side="right") - 1
    ok = hi > lo
    t1, t2 = ts_row[lo], ts_row[hi]
    v1, v2 = vals[:, lo], vals[:, hi]                  # [S, W]
    with np.errstate(invalid="ignore", divide="ignore"):
        rate = np.where(ok & (t2 > t1), (v2 - v1) / (t2 - t1) * 1000.0,
                        np.nan)
    out = np.zeros((G, rate.shape[1]))
    np.add.at(out, gids, np.nan_to_num(rate))
    return out


def numpy_iterator_baseline(ts_row, vals, wends, range_ms):
    """Per-(series,window) loop mimicking ChunkedWindowIterator's access
    pattern (ref: query/.../exec/PeriodicSamplesMapper.scala:202-292)."""
    S = vals.shape[0]
    out = np.empty((S, len(wends)))
    for s in range(S):
        row_v = vals[s]
        for wi, wend in enumerate(wends):
            lo = np.searchsorted(ts_row, wend - range_ms, side="left")
            hi = np.searchsorted(ts_row, wend, side="right")
            if hi - lo < 2:
                out[s, wi] = np.nan
                continue
            t1, t2 = ts_row[lo], ts_row[hi - 1]
            out[s, wi] = ((row_v[hi - 1] - row_v[lo]) / (t2 - t1) * 1000.0
                          if t2 > t1 else np.nan)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="small config for smoke runs")
    ap.add_argument("--series", type=int, default=0)
    ap.add_argument("--iters", type=int, default=0)
    args = ap.parse_args()

    import jax
    from filodb_tpu.ops.rangefns import evaluate_range_function
    from filodb_tpu.ops import agg as agg_ops
    from filodb_tpu.ops.timewindow import to_offsets, make_window_ends

    platform = jax.devices()[0].platform
    quick = args.quick
    S = args.series or (8_192 if quick else 262_144)
    T = 720                                  # 2h of 10s samples
    G = min(1000, S)                         # sum by() group count
    range_ms, step_ms = 300_000, 60_000      # rate[5m], 1m steps
    iters = args.iters or (3 if quick else 10)

    ts_row, vals = make_counter_data(S, T)
    ts_off = to_offsets(np.tile(ts_row, (S, 1)), np.full(S, T), 0)
    gids = (np.arange(S) % G).astype(np.int32)
    qstart, qend = 600_000, 7_190_000        # inside the data range
    wends = make_window_ends(qstart, qend, step_ms).astype(np.int32)
    W = len(wends)
    # conservative accounting: every stored sample in the span, once
    span_lo = np.searchsorted(ts_row, qstart - range_ms)
    span_hi = np.searchsorted(ts_row, qend, side="right")
    scanned_per_query = S * int(span_hi - span_lo)

    dev_ts = jax.device_put(ts_off)
    dev_vals = jax.device_put(vals)
    dev_gids = jax.device_put(gids)
    dev_wends = jax.device_put(wends)

    @jax.jit
    def query(ts_off, vals, gids, wends):
        res = evaluate_range_function(ts_off, vals, wends, range_ms, "rate",
                                      shared_grid=True)
        return agg_ops.aggregate("sum", res, gids, G)

    np.asarray(query(dev_ts, dev_vals, dev_gids, dev_wends))  # compile + warm
    lat = []
    for _ in range(iters):
        t0 = time.perf_counter()
        # np.asarray forces execution AND result fetch: block_until_ready
        # is not a reliable completion barrier on the tunneled TPU backend
        np.asarray(query(dev_ts, dev_vals, dev_gids, dev_wends))
        lat.append(time.perf_counter() - t0)
    p50 = float(np.median(np.asarray(lat)))
    samples_per_sec = scanned_per_query / p50

    # vectorized-NumPy CPU baseline, same algorithm, capped working set
    Sv = min(S, 65_536)
    t0 = time.perf_counter()
    numpy_vectorized_baseline(ts_row, vals[:Sv].astype(np.float64),
                              gids[:Sv], G, wends.astype(np.int64), range_ms)
    vec_elapsed = time.perf_counter() - t0
    vec_samples_per_sec = (Sv * (span_hi - span_lo)) / vec_elapsed

    # per-window loop baseline on a small subset (slow by construction)
    Sb = min(S, 512)
    t0 = time.perf_counter()
    numpy_iterator_baseline(ts_row, vals[:Sb].astype(np.float64),
                            wends.astype(np.int64), range_ms)
    it_elapsed = time.perf_counter() - t0
    it_samples_per_sec = (Sb * (span_hi - span_lo)) / it_elapsed

    result = {
        "metric": "promql_samples_scanned_per_sec",
        "value": round(samples_per_sec, 1),
        "unit": "samples/s",
        "vs_baseline": round(samples_per_sec / vec_samples_per_sec, 2),
        "p50_query_latency_s": round(p50, 5),
        "series": S, "windows": W, "groups": G,
        "platform": platform,
        "baseline_samples_per_sec": round(vec_samples_per_sec, 1),
        "baseline_kind": "vectorized numpy, same algorithm, host CPU",
        "iterator_baseline_samples_per_sec": round(it_samples_per_sec, 1),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
