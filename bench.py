"""Headline benchmark: PromQL samples/sec scanned on sum by (rate[5m]).

Mirrors the reference's QueryInMemoryBenchmark workload shape
(ref: jmh/src/main/scala/filodb.jmh/QueryInMemoryBenchmark.scala:31-35,
126-133 — Prom-schema counters, 720 samples @10s, 5m rate windows, sum
aggregation) scaled toward the BASELINE.json north star (1M-series
sum by(rate()) on one chip; multi-chip scales via the mesh path, see
tests/test_mesh.py and __graft_entry__.dryrun_multichip).

Accounting is conservative: "samples scanned" counts every stored sample in
the queried span ONCE (S * samples_in_span), not once per overlapping window
the way the JVM SlidingWindowIterator would touch them — so the number is a
lower bound on iterator-equivalent throughput.

vs_baseline compares against the same algorithm implemented in vectorized
NumPy on host CPU (the strongest portable CPU stand-in we can run here; the
reference publishes no absolute numbers — see BASELINE.md). A second,
per-window loop baseline ("iterator") mimicking ChunkedWindowIterator's
per-window access pattern is reported as an extra field.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Robustness: backend init on the tunneled TPU ('axon') can fail or hang
indefinitely, which in round 1 destroyed the whole round's bench artifact.
The default invocation therefore runs as a SUPERVISOR that executes the
measurement in a child process under a hard timeout, retries once, and
falls back to a (smaller) CPU run — so a JSON line with a `platform` field
is always emitted, no matter what the TPU tunnel does.
"""
import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np


def make_counter_data(S, T, step_ms=10_000, seed=7):
    rng = np.random.default_rng(seed)
    ts_row = np.arange(T, dtype=np.int64) * step_ms
    vals = np.cumsum(rng.exponential(10.0, size=(S, T)).astype(np.float32),
                     axis=1)
    return ts_row, vals


def numpy_vectorized_baseline(ts_row, vals, gids, G, wends, range_ms):
    """Same algorithm as the device kernel, vectorized NumPy on host."""
    lo = np.searchsorted(ts_row, wends - range_ms, side="left")
    hi = np.searchsorted(ts_row, wends, side="right") - 1
    ok = hi > lo
    t1, t2 = ts_row[lo], ts_row[hi]
    v1, v2 = vals[:, lo], vals[:, hi]                  # [S, W]
    with np.errstate(invalid="ignore", divide="ignore"):
        rate = np.where(ok & (t2 > t1), (v2 - v1) / (t2 - t1) * 1000.0,
                        np.nan)
    out = np.zeros((G, rate.shape[1]))
    np.add.at(out, gids, np.nan_to_num(rate))
    return out


def numpy_iterator_baseline(ts_row, vals, wends, range_ms):
    """Per-(series,window) loop mimicking ChunkedWindowIterator's access
    pattern (ref: query/.../exec/PeriodicSamplesMapper.scala:202-292)."""
    S = vals.shape[0]
    out = np.empty((S, len(wends)))
    for s in range(S):
        row_v = vals[s]
        for wi, wend in enumerate(wends):
            lo = np.searchsorted(ts_row, wend - range_ms, side="left")
            hi = np.searchsorted(ts_row, wend, side="right")
            if hi - lo < 2:
                out[s, wi] = np.nan
                continue
            t1, t2 = ts_row[lo], ts_row[hi - 1]
            out[s, wi] = ((row_v[hi - 1] - row_v[lo]) / (t2 - t1) * 1000.0
                          if t2 > t1 else np.nan)
    return out


def run_pallas_fused(ts_row, vals_or_dev, gids, wends, range_ms, G,
                     xla_res, iters):
    """Time ops/pallas_fused for one config and cross-check it against the
    XLA result.  Returns (p50_seconds, max_rel_err) where the error is inf
    when the NaN patterns disagree (nanmax alone would silently drop
    positions where only one side is NaN)."""
    import time as _time

    from filodb_tpu.ops import pallas_fused as pf
    S = vals_or_dev.shape[0]
    plan = pf.build_plan(ts_row, np.asarray(wends, np.int64), range_ms)
    prep = pf.pad_inputs(vals_or_dev, np.zeros(S, np.float32), gids, plan, G)

    def fused_query():
        sums, counts = pf.fused_rate_groupsum(
            None, None, None, plan, G, "rate", False, prepared=prep)
        return pf.present_sum(sums, counts)

    got = fused_query()                               # compile + warm
    if (np.isnan(got) != np.isnan(xla_res)).any():
        err = float("inf")
    else:
        err = float(np.nanmax(
            np.abs(got - xla_res) / np.maximum(np.abs(xla_res), 1e-6)))
    lat = []
    for _ in range(iters):
        t0 = _time.perf_counter()
        fused_query()
        lat.append(_time.perf_counter() - t0)
    return float(np.median(np.asarray(lat))), err


def parse_args(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="small config for smoke runs")
    ap.add_argument("--series", type=int, default=0)
    ap.add_argument("--iters", type=int, default=0)
    ap.add_argument("--_worker", action="store_true",
                    help="internal: run the measurement in this process")
    ap.add_argument("--platform", default="default",
                    choices=["default", "cpu"],
                    help="internal: pin the jax platform for a worker run")
    return ap.parse_args(argv)


def run_worker(args):
    import jax

    if args.platform == "cpu":
        # Env vars are too late once the sitecustomize hook has imported
        # jax — pin via jax.config (same fix as tests/conftest.py).
        jax.config.update("jax_platforms", "cpu")

    from filodb_tpu.ops.rangefns import evaluate_range_function
    from filodb_tpu.ops import agg as agg_ops
    from filodb_tpu.ops.timewindow import to_offsets, make_window_ends

    platform = jax.devices()[0].platform
    quick = args.quick
    S = args.series or (8_192 if quick else 262_144)
    if platform == "cpu" and not args.series:
        # fallback runs must finish within the supervisor timeout
        S = min(S, 65_536)
    T = 720                                  # 2h of 10s samples
    G = min(1000, S)                         # sum by() group count
    range_ms, step_ms = 300_000, 60_000      # rate[5m], 1m steps
    iters = args.iters or (3 if quick else 10)

    ts_row, vals = make_counter_data(S, T)
    ts_off = to_offsets(np.tile(ts_row, (S, 1)), np.full(S, T), 0)
    gids = (np.arange(S) % G).astype(np.int32)
    qstart, qend = 600_000, 7_190_000        # inside the data range
    wends = make_window_ends(qstart, qend, step_ms).astype(np.int32)
    W = len(wends)
    # conservative accounting: every stored sample in the span, once
    span_lo = np.searchsorted(ts_row, qstart - range_ms)
    span_hi = np.searchsorted(ts_row, qend, side="right")
    scanned_per_query = S * int(span_hi - span_lo)

    dev_ts = jax.device_put(ts_off)
    dev_vals = jax.device_put(vals)
    dev_gids = jax.device_put(gids)
    dev_wends = jax.device_put(wends)

    @jax.jit
    def query(ts_off, vals, gids, wends):
        res = evaluate_range_function(ts_off, vals, wends, range_ms, "rate",
                                      shared_grid=True)
        return agg_ops.aggregate("sum", res, gids, G)

    np.asarray(query(dev_ts, dev_vals, dev_gids, dev_wends))  # compile + warm
    lat = []
    for _ in range(iters):
        t0 = time.perf_counter()
        # np.asarray forces execution AND result fetch: block_until_ready
        # is not a reliable completion barrier on the tunneled TPU backend
        np.asarray(query(dev_ts, dev_vals, dev_gids, dev_wends))
        lat.append(time.perf_counter() - t0)
    p50 = float(np.median(np.asarray(lat)))
    samples_per_sec = scanned_per_query / p50

    # vectorized-NumPy CPU baseline, same algorithm, capped working set
    Sv = min(S, 65_536)
    t0 = time.perf_counter()
    numpy_vectorized_baseline(ts_row, vals[:Sv].astype(np.float64),
                              gids[:Sv], G, wends.astype(np.int64), range_ms)
    vec_elapsed = time.perf_counter() - t0
    vec_samples_per_sec = (Sv * (span_hi - span_lo)) / vec_elapsed

    # per-window loop baseline on a small subset (slow by construction)
    Sb = min(S, 512)
    t0 = time.perf_counter()
    numpy_iterator_baseline(ts_row, vals[:Sb].astype(np.float64),
                            wends.astype(np.int64), range_ms)
    it_elapsed = time.perf_counter() - t0
    it_samples_per_sec = (Sb * (span_hi - span_lo)) / it_elapsed

    result = {
        "metric": "promql_samples_scanned_per_sec",
        "value": round(samples_per_sec, 1),
        "unit": "samples/s",
        "vs_baseline": round(samples_per_sec / vec_samples_per_sec, 2),
        "p50_query_latency_s": round(p50, 5),
        "series": S, "windows": W, "groups": G,
        "platform": platform,
        "baseline_samples_per_sec": round(vec_samples_per_sec, 1),
        "baseline_kind": "vectorized numpy, same algorithm, host CPU",
        "iterator_baseline_samples_per_sec": round(it_samples_per_sec, 1),
    }

    # Pallas fused path (ops/pallas_fused.py): one-HBM-pass MXU kernel for
    # the same query over the device-resident working set.  Cross-checked
    # against the XLA result above; headline takes the faster path.
    if platform != "cpu":
        try:
            xla_res = np.asarray(query(dev_ts, dev_vals, dev_gids,
                                       dev_wends))
            p50_f, err = run_pallas_fused(ts_row, dev_vals, gids, wends,
                                          range_ms, G, xla_res, iters)
            result["pallas_fused_p50_s"] = round(p50_f, 5)
            result["pallas_fused_max_rel_err_vs_xla"] = round(err, 9)
            if err < 1e-4 and p50_f < p50:
                fused_sps = scanned_per_query / p50_f
                result.update({
                    "value": round(fused_sps, 1),
                    "vs_baseline": round(fused_sps / vec_samples_per_sec, 2),
                    "p50_query_latency_s": round(p50_f, 5),
                    "kernel": "pallas_fused",
                    "xla_path_p50_s": round(p50, 5),
                })
        except Exception as e:  # noqa: BLE001 — keep the XLA headline
            result["pallas_fused_error"] = f"{type(e).__name__}: {e}"

    # North-star config (BASELINE.md: 1M-series sum by(rate()) + p50):
    # 1M series x 1h of 10s samples, chip-resident, same query shape.
    # Skipped on CPU fallback and --quick (would blow the supervisor
    # timeout); reported as extra fields on the same JSON line.
    if not quick and platform != "cpu" and not args.series:
        try:
            ns_S, ns_T, ns_G = 1_000_000, 360, 1000
            ts_row1, vals1 = make_counter_data(ns_S, ns_T)
            ts_off1 = to_offsets(np.tile(ts_row1, (ns_S, 1)),
                                 np.full(ns_S, ns_T), 0)
            gids1 = (np.arange(ns_S) % ns_G).astype(np.int32)
            wends1 = make_window_ends(600_000, 3_590_000, step_ms).astype(np.int32)
            lo1 = np.searchsorted(ts_row1, 600_000 - range_ms)
            hi1 = np.searchsorted(ts_row1, 3_590_000, side="right")
            scanned1 = ns_S * int(hi1 - lo1)
            d_ts = jax.device_put(ts_off1)
            d_vals = jax.device_put(vals1)
            d_gids = jax.device_put(gids1)
            d_wends = jax.device_put(wends1)

            @jax.jit
            def query1m(ts_off, vals, gids, wends):
                res = evaluate_range_function(ts_off, vals, wends, range_ms,
                                              "rate", shared_grid=True)
                return agg_ops.aggregate("sum", res, gids, ns_G)

            xla1m = np.asarray(query1m(d_ts, d_vals, d_gids, d_wends))
            lat1 = []
            for _ in range(max(3, iters // 2)):
                t0 = time.perf_counter()
                np.asarray(query1m(d_ts, d_vals, d_gids, d_wends))
                lat1.append(time.perf_counter() - t0)
            p50_1m = float(np.median(np.asarray(lat1)))
            result.update({
                "north_star_series": ns_S,
                "north_star_p50_s": round(p50_1m, 5),
                "north_star_samples_per_sec": round(scanned1 / p50_1m, 1),
            })
            try:
                del d_ts                              # free HBM for the pad
                p50_1mf, err1m = run_pallas_fused(
                    ts_row1, d_vals, gids1, wends1, range_ms, ns_G, xla1m,
                    max(3, iters // 2))
                del d_vals
                result["north_star_pallas_p50_s"] = round(p50_1mf, 5)
                result["north_star_pallas_max_rel_err"] = round(err1m, 9)
                if err1m < 1e-4 and p50_1mf < p50_1m:
                    result.update({
                        "north_star_p50_s": round(p50_1mf, 5),
                        "north_star_samples_per_sec":
                            round(scanned1 / p50_1mf, 1),
                        "north_star_kernel": "pallas_fused",
                    })
            except Exception as e:  # noqa: BLE001
                result["north_star_pallas_error"] = f"{type(e).__name__}: {e}"
        except Exception as e:  # noqa: BLE001 — keep the headline number
            result["north_star_error"] = f"{type(e).__name__}: {e}"
    print(json.dumps(result))


def _spawn_worker(args, platform, timeout_s):
    """Run the measurement in a child under a hard timeout; return the
    parsed JSON result dict or None."""
    cmd = [sys.executable, os.path.abspath(__file__), "--_worker",
           "--platform", platform]
    if args.quick:
        cmd.append("--quick")
    if args.series:
        cmd += ["--series", str(args.series)]
    if args.iters:
        cmd += ["--iters", str(args.iters)]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout_s)
    except subprocess.TimeoutExpired:
        print(f"bench: worker ({platform}) timed out after {timeout_s}s",
              file=sys.stderr)
        return None
    if proc.returncode != 0:
        tail = "\n".join(proc.stderr.strip().splitlines()[-5:])
        print(f"bench: worker ({platform}) rc={proc.returncode}:\n{tail}",
              file=sys.stderr)
        return None
    for line in reversed(proc.stdout.strip().splitlines()):
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    print(f"bench: worker ({platform}) emitted no JSON", file=sys.stderr)
    return None


def _probe_default_backend(timeout_s):
    """Init the default jax backend in a child; return its platform name or
    None if init fails/hangs.  Cheap insurance against the tunneled-TPU
    backend hanging indefinitely (it did in round 1)."""
    code = "import jax; print(jax.devices()[0].platform)"
    try:
        p = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        print(f"bench: backend probe timed out after {timeout_s}s",
              file=sys.stderr)
        return None
    if p.returncode == 0 and p.stdout.strip():
        return p.stdout.strip().splitlines()[-1]
    return None


def main():
    args = parse_args()
    if args._worker:
        run_worker(args)
        return

    # Supervisor: probe the default backend (the real chip) under a short
    # timeout, run the measurement there if it answers, and otherwise fall
    # back to CPU — so the round always records a number.
    if args.platform == "cpu":
        # explicit CPU request: no probe, no fallback relabeling
        result = _spawn_worker(args, "cpu", 1200)
        print(json.dumps(result if result is not None else {
            "metric": "promql_samples_scanned_per_sec", "value": 0.0,
            "unit": "samples/s", "vs_baseline": 0.0, "platform": "none",
            "error": "cpu bench attempt failed"}))
        return
    tpu_timeout = int(os.environ.get("FILODB_BENCH_TPU_TIMEOUT",
                                     "600" if args.quick else "1800"))
    plat = _probe_default_backend(180) or _probe_default_backend(90)
    if plat is not None:
        for _ in range(2):
            result = _spawn_worker(args, "default", tpu_timeout)
            if result is not None:
                print(json.dumps(result))
                return
    else:
        # probes hung, but probe flakiness is not proof the chip is gone:
        # one bounded direct attempt before surrendering to CPU
        result = _spawn_worker(args, "default", min(tpu_timeout, 600))
        if result is not None:
            print(json.dumps(result))
            return
    result = _spawn_worker(args, "cpu", 1200)
    if result is not None:
        result["fallback"] = "cpu (default backend unavailable: probe=%s)" % plat
        print(json.dumps(result))
        return
    print(json.dumps({
        "metric": "promql_samples_scanned_per_sec", "value": 0.0,
        "unit": "samples/s", "vs_baseline": 0.0, "platform": "none",
        "error": "all bench attempts failed (default backend + cpu)",
    }))


if __name__ == "__main__":
    main()
