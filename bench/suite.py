"""Benchmark suite mirroring the reference's jmh classes.

ref: jmh/src/main/scala/filodb.jmh/ — IngestionBenchmark,
EncodingBenchmark, PartKeyIndexBenchmark, GatewayBenchmark,
QueryInMemoryBenchmark (:31-35,126-133 query set),
QueryHiCardInMemoryBenchmark, HistogramIngestBenchmark,
HistogramQueryBenchmark; runner run_benchmarks.sh.

Each benchmark prints one JSON line {"bench", "metric", "value", "unit"}.
Run all: python -m bench.suite            (add --quick for smoke sizing)
Run one: python -m bench.suite ingestion
The headline driver benchmark stays in bench.py at the repo root.
"""
from __future__ import annotations

import argparse
import os
import json
import time
from typing import Callable, Dict, List

import numpy as np

START = 1_600_000_020_000


def _emit(bench: str, metric: str, value: float, unit: str, **extra):
    print(json.dumps({"bench": bench, "metric": metric,
                      "value": round(value, 1), "unit": unit, **extra}))


def _time_it(fn: Callable, iters: int) -> float:
    t0 = time.perf_counter()
    for _ in range(iters):
        fn()
    return (time.perf_counter() - t0) / iters


# ------------------------------------------------------------- ingestion


def bench_ingestion(quick: bool):
    """Samples/sec through the shard ingest path
    (ref: IngestionBenchmark.scala)."""
    from filodb_tpu.core.memstore import TimeSeriesMemStore
    from filodb_tpu.ingest.generator import gauge_batch
    S, T = (500, 200) if quick else (2000, 720)
    batch = gauge_batch(S, T, start_ms=START)
    iters = 3 if quick else 5
    times = []
    for i in range(iters):
        ms = TimeSeriesMemStore()
        sh = ms.setup(f"bench{i}", 0)
        t0 = time.perf_counter()
        sh.ingest(batch)
        times.append(time.perf_counter() - t0)
    best = min(times)
    _emit("ingestion", "samples_per_sec", S * T / best, "samples/s",
          series=S, samples=T)


# -------------------------------------------------------------- encoding


def bench_encoding(quick: bool):
    """Chunk encode/decode throughput (ref: EncodingBenchmark.scala,
    IntSumReadBenchmark)."""
    from filodb_tpu.memory.chunks import decode_chunkset, encode_chunkset
    n = 10_000 if quick else 100_000
    ts = START + np.arange(n, dtype=np.int64) * 10_000
    vals = np.cumsum(np.random.default_rng(0).exponential(10, n))
    col_types = {"value": "double"}
    enc = lambda: encode_chunkset(ts, {"value": vals}, col_types, START)  # noqa: E731
    per = _time_it(enc, 3 if quick else 10)
    _emit("encoding", "encode_samples_per_sec", n / per, "samples/s")
    cs = enc()
    per = _time_it(lambda: decode_chunkset(cs), 3 if quick else 10)
    _emit("encoding", "decode_samples_per_sec", n / per, "samples/s",
          bytes_per_sample=round(cs.nbytes / n, 2))


# ----------------------------------------------------------------- index


def bench_index(quick: bool):
    """Tag-index add + filter lookup ops/sec
    (ref: PartKeyIndexBenchmark.scala)."""
    from filodb_tpu.core.index import Equals, EqualsRegex, PartKeyIndex
    from filodb_tpu.core.partkey import PartKey
    # full mode runs the 1M-doc config from the VERDICT target
    # (index lookup <= ~10ms at 1M series, ref PartKeyIndexBenchmark.scala)
    n = 20_000 if quick else 1_000_000
    keys = [PartKey.make(f"metric_{i % 50}",
                         {"_ws_": "demo", "_ns_": f"App-{i % 100}",
                          "instance": f"i{i}"}) for i in range(n)]
    idx = PartKeyIndex()
    t0 = time.perf_counter()
    for i, pk in enumerate(keys):
        idx.add_partition(i, pk, START)
    add_per_sec = n / (time.perf_counter() - t0)
    _emit("partkey_index", "adds_per_sec", add_per_sec, "ops/s", keys=n)
    filters = [Equals("_metric_", "metric_7"), Equals("_ns_", "App-42")]
    per = _time_it(lambda: idx.part_ids_from_filters(filters, 0, 1 << 62),
                   50 if quick else 200)
    _emit("partkey_index", "equals_lookups_per_sec", 1 / per, "ops/s",
          keys=n, latency_ms=round(per * 1000, 3))
    rx = [EqualsRegex("_ns_", "App-1.*")]
    per = _time_it(lambda: idx.part_ids_from_filters(rx, 0, 1 << 62),
                   20 if quick else 50)
    _emit("partkey_index", "regex_lookups_per_sec", 1 / per, "ops/s",
          keys=n, latency_ms=round(per * 1000, 3))


# --------------------------------------------------------------- gateway


def bench_gateway(quick: bool):
    """Influx line parse -> RecordBatch throughput
    (ref: GatewayBenchmark.scala)."""
    from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
    from filodb_tpu.gateway.influx import influx_lines_to_batches
    n = 5_000 if quick else 20_000
    lines = [f"cpu_usage,_ws_=demo,_ns_=App-{i % 8},host=h{i % 100} "
             f"value={i * 0.5} {(START + i) * 1_000_000}" for i in range(n)]
    per = _time_it(lambda: influx_lines_to_batches(lines, DEFAULT_SCHEMAS),
                   3 if quick else 5)
    _emit("gateway", "influx_lines_per_sec", n / per, "lines/s")


# ------------------------------------------------------------ query set


def _mk_query_engine(S, T, quick):
    from filodb_tpu.core.memstore import TimeSeriesMemStore
    from filodb_tpu.ingest.generator import counter_batch, gauge_batch
    from filodb_tpu.parallel.shardmapper import ShardEvent, ShardMapper
    from filodb_tpu.query.engine import QueryEngine
    ms = TimeSeriesMemStore()
    sh = ms.setup("prometheus", 0)
    sh.ingest(counter_batch(S, T, start_ms=START))
    sh.ingest(gauge_batch(S, T, start_ms=START))
    mapper = ShardMapper(1)
    mapper.update_from_event(
        ShardEvent("IngestionStarted", "prometheus", 0, "b"))
    return QueryEngine("prometheus", ms, mapper)


QUERY_SET = [  # ref: QueryInMemoryBenchmark.scala:126-133
    ("raw_scan", 'request_total{_ws_="demo"}'),
    ("sum_rate", 'sum(rate(request_total[5m]))'),
    ("sum_by_rate", 'sum by (_ns_)(rate(request_total[5m]))'),
    ("quantile", 'quantile(0.75,heap_usage)'),
    ("sum_over_time", 'sum(sum_over_time(heap_usage[5m]))'),
]


def bench_query(quick: bool):
    """PromQL QPS over the in-memory store
    (ref: QueryInMemoryBenchmark.scala:31-35 — 100 series x 720 samples
    per shard; QPS per query shape)."""
    S, T = (100, 200) if quick else (100, 720)
    eng = _mk_query_engine(S, T, quick)
    s = START // 1000
    end = s + T * 10
    for name, q in QUERY_SET:
        run = lambda: eng.query_range(q, s + 600, 60, end)  # noqa: E731
        assert run().error is None, (name, run().error)
        per = _time_it(run, 5 if quick else 20)
        _emit("query_inmemory", f"{name}_qps", 1 / per, "queries/s",
              series=S)


def bench_dashboard_batch(quick: bool):
    """Dashboard panel throughput: P fused panels over one window grid,
    batched into merged kernel dispatches (engine.query_range_batch)
    vs issued one at a time.  The round-4 on-chip finding: a fused leaf
    query is dispatch-bound, so batching is where dashboard latency goes
    (doc/kernels.md; no reference analogue — iterator engines pay
    per-series either way)."""
    import os
    had = os.environ.get("FILODB_TPU_FUSED_INTERPRET")
    os.environ["FILODB_TPU_FUSED_INTERPRET"] = "1"
    S, T = (2_000, 240) if quick else (20_000, 720)
    eng = _mk_query_engine(S, T, quick)
    s = START // 1000
    end = s + T * 10
    panels = ['sum(rate(request_total[5m])) by (_ns_)',
              'avg(rate(request_total[5m])) by (dc)',
              'sum(rate(request_total[5m])) by (_ns_, dc)',
              'count(rate(request_total[5m])) by (dc)',
              'min(rate(request_total[5m])) by (_ns_)',
              'max(rate(request_total[5m])) by (dc)',
              'sum(rate(request_total[5m])) by (dc)',
              'sum(rate(request_total[5m])) by (instance)']
    args = (s + 600, 60, end)

    def seq():
        for q in panels:
            assert eng.query_range(q, *args).error is None

    def batch():
        for r in eng.query_range_batch(panels, *args):
            assert r.error is None

    try:
        seq(); batch()                   # warm mirror + caches
        iters = 3 if quick else 10
        t_seq = _time_it(seq, iters)
        t_batch = _time_it(batch, iters)
    finally:
        # restore: leaking interpret mode would silently reroute every
        # later bench's queries through the interpret fused path
        if had is None:
            os.environ.pop("FILODB_TPU_FUSED_INTERPRET", None)
        else:
            os.environ["FILODB_TPU_FUSED_INTERPRET"] = had
    _emit("dashboard_batch", "sequential_panels_per_s",
          len(panels) / t_seq, "panels/s", series=S)
    _emit("dashboard_batch", "batched_panels_per_s",
          len(panels) / t_batch, "panels/s", series=S,
          speedup=round(t_seq / t_batch, 2))


def bench_query_hicard(quick: bool):
    """Single-shard high-cardinality scan
    (ref: QueryHiCardInMemoryBenchmark.scala)."""
    S, T = (20_000, 40) if quick else (100_000, 60)
    eng = _mk_query_engine(S, T, quick)
    s = START // 1000
    q = 'sum(rate(request_total[5m]))'
    run = lambda: eng.query_range(q, s + 360, 60, s + T * 10)  # noqa: E731
    assert run().error is None
    per = _time_it(run, 2 if quick else 5)
    _emit("query_hicard", "sum_rate_qps", 1 / per, "queries/s", series=S)


def bench_query_odp(quick: bool):
    """Query served by on-demand paging from the persistence tier after the
    dense working set was truncated (ref: QueryOnDemandBenchmark.scala —
    queries against data that must page in from the column store)."""
    import tempfile
    from filodb_tpu.core.memstore import TimeSeriesMemStore
    from filodb_tpu.ingest.generator import counter_batch
    from filodb_tpu.persist.localstore import (LocalDiskColumnStore,
                                               LocalDiskMetaStore)
    from filodb_tpu.query.engine import QueryEngine
    S, T = (500, 240) if quick else (2000, 720)
    tmp = tempfile.mkdtemp(prefix="filodb_odp_bench_")
    cs, meta = LocalDiskColumnStore(tmp), LocalDiskMetaStore(tmp)
    ms = TimeSeriesMemStore(column_store=cs, meta_store=meta)
    sh = ms.setup("prometheus", 0)
    sh.ingest(counter_batch(S, T, start_ms=START), offset=1)
    sh.flush_all_groups()
    # cold store: recovered index, no resident data -> every query pages
    cold = TimeSeriesMemStore(column_store=cs, meta_store=meta)
    sh2 = cold.setup("prometheus", 0)
    sh2.recover_index()
    eng = QueryEngine("prometheus", cold)
    s = START // 1000
    q = 'sum(rate(request_total[5m]))'
    t0 = time.perf_counter()
    res = eng.query_range(q, s + 600, 60, s + T * 10)
    first = time.perf_counter() - t0
    assert res.error is None, res.error
    _emit("query_odp", "first_query_page_in_s", first, "s",
          series=S, samples=S * T,
          samples_paged_per_sec=round(S * T / first, 1))
    # warm: data now resident, same query
    per = _time_it(lambda: eng.query_range(q, s + 600, 60, s + T * 10),
                   3 if quick else 10)
    _emit("query_odp", "warm_qps_after_page_in", 1 / per, "queries/s",
          series=S)


def bench_partition_list(quick: bool):
    """lookup_partitions throughput over a populated shard
    (ref: PartitionListBenchmark.scala)."""
    from filodb_tpu.core.index import Equals
    from filodb_tpu.core.memstore import TimeSeriesMemStore
    from filodb_tpu.ingest.generator import counter_batch
    S = 20_000 if quick else 200_000
    ms = TimeSeriesMemStore()
    sh = ms.setup("prometheus", 0)
    sh.ingest(counter_batch(S, 2, start_ms=START, num_apps=100))
    lo, hi = 0, 1 << 62
    broad = [Equals("_metric_", "request_total")]
    per = _time_it(lambda: sh.lookup_partitions(broad, lo, hi),
                   20 if quick else 50)
    _emit("partition_list", "broad_lookups_per_sec", 1 / per, "ops/s",
          series=S, latency_ms=round(per * 1000, 3))
    narrow = [Equals("_metric_", "request_total"), Equals("_ns_", "App-7")]
    per = _time_it(lambda: sh.lookup_partitions(narrow, lo, hi),
                   50 if quick else 200)
    _emit("partition_list", "narrow_lookups_per_sec", 1 / per, "ops/s",
          series=S, latency_ms=round(per * 1000, 3))


def bench_query_under_ingest(quick: bool):
    """Query QPS while a thread continuously ingests into the same shard
    (ref: QueryAndIngestBenchmark.scala — the reference runs queries during
    its second window of live ingestion).  Reports concurrent QPS and the
    quiesced QPS for the same store so the interference cost is visible."""
    import threading
    from filodb_tpu.core.memstore import TimeSeriesMemStore
    from filodb_tpu.core.records import RecordBatch
    from filodb_tpu.ingest.generator import counter_batch
    from filodb_tpu.parallel.shardmapper import ShardEvent, ShardMapper
    from filodb_tpu.query.engine import QueryEngine
    S, T = (500, 360) if quick else (2000, 720)
    full = counter_batch(S, T, start_ms=START)
    ms = TimeSeriesMemStore()
    sh = ms.setup("prometheus", 0)
    half_ms = START + (T // 2) * 10_000
    keep = full.timestamps < half_ms
    sh.ingest(RecordBatch(full.schema, full.part_keys, full.part_idx[keep],
                          full.timestamps[keep],
                          {k: v[keep] for k, v in full.columns.items()},
                          full.bucket_les))
    mapper = ShardMapper(1)
    mapper.update_from_event(ShardEvent("IngestionStarted", "prometheus", 0, "b"))
    eng = QueryEngine("prometheus", ms, mapper)
    s = START // 1000
    q = 'sum by (_ns_)(rate(request_total[5m]))'
    run = lambda: eng.query_range(q, s + 600, 60, s + T * 10)  # noqa: E731
    assert run().error is None
    stop = threading.Event()

    def ingester():
        # stream the second half in small slices until the bench ends
        idx = T // 2
        while not stop.is_set():
            if idx >= T:
                idx = T // 2  # wrap: re-deliver (dropped as out-of-order)
            lo = START + idx * 10_000
            hi = lo + 20 * 10_000
            k = (full.timestamps >= lo) & (full.timestamps < hi)
            sh.ingest(RecordBatch(full.schema, full.part_keys,
                                  full.part_idx[k], full.timestamps[k],
                                  {kk: v[k] for kk, v in full.columns.items()},
                                  full.bucket_les))
            idx += 20
    t = threading.Thread(target=ingester, daemon=True)
    t.start()
    try:
        per_concurrent = _time_it(run, 5 if quick else 20)
    finally:
        stop.set()
        t.join(timeout=30)
    per_quiesced = _time_it(run, 5 if quick else 20)
    _emit("query_under_ingest", "concurrent_qps", 1 / per_concurrent,
          "queries/s", series=S,
          quiesced_qps=round(1 / per_quiesced, 1))


def bench_query_1m(quick: bool):
    """North-star end-to-end: memstore ingest -> index lookup -> dense
    gather -> mesh pack (cached group ids) -> kernel, at 1M series
    (BASELINE.md config 3; VERDICT r1 item 4).  Runs the full host path
    the flagship query takes, so host-side per-series Python would show
    up here immediately."""
    import jax
    from filodb_tpu.core.memstore import TimeSeriesMemStore
    from filodb_tpu.ingest.generator import counter_batch
    from filodb_tpu.parallel.mesh import MeshExecutor, make_mesh
    from filodb_tpu.ops.timewindow import make_window_ends
    from filodb_tpu.core.index import Equals
    # T=60 in both modes: the 5m-rate window grid needs >= 300s of data
    # or make_window_ends returns an empty grid and p50 measures nothing
    S, T = (50_000, 60) if quick else (1_000_000, 60)
    ms = TimeSeriesMemStore()
    sh = ms.setup("prometheus", 0)
    t0 = time.perf_counter()
    # ingest in slices to bound the peak batch footprint
    step = 250_000
    from filodb_tpu.core.partkey import PartKey
    from filodb_tpu.core.records import RecordBatch
    for lo in range(0, S, step):
        n = min(step, S - lo)
        b = counter_batch(n, T, start_ms=START, num_apps=100)
        if lo:
            # re-key the slice so series identities stay distinct
            keys = [PartKey.make(pk.metric,
                                 {**dict(pk.tags),
                                  "instance": f"I{lo}-{i}"})
                    for i, pk in enumerate(b.part_keys)]
            b = RecordBatch(b.schema, keys, b.part_idx, b.timestamps,
                            b.columns, b.bucket_les)
        sh.ingest(b)
    ingest_s = time.perf_counter() - t0
    _emit("query_1m", "ingest_samples_per_sec", S * T / ingest_s,
          "samples/s", series=S)
    mesh = make_mesh(1, 1, devices=jax.devices()[:1])
    ex = MeshExecutor(ms, "prometheus", mesh)
    filters = [Equals("_metric_", "request_total")]
    end_ms = START + (T - 1) * 10_000
    wends = make_window_ends(START + 300_000, end_ms, 60_000)

    def run():
        packed = ex.lookup_and_pack(filters, START, end_ms, by=("_ns_",),
                                    fn_name="rate")
        out, labels = ex.run_agg(packed, wends, range_ms=300_000,
                                 fn_name="rate", agg_op="sum")
        return np.asarray(out)

    t1 = time.perf_counter()
    run()                      # cold: compile + group cache + pack upload
    cold_s = time.perf_counter() - t1
    lat = []
    for _ in range(2 if quick else 5):
        t1 = time.perf_counter()
        run()
        lat.append(time.perf_counter() - t1)
    p50 = float(np.median(lat))
    _emit("query_1m", "sum_by_rate_p50_latency", p50 * 1000, "ms",
          series=S, samples_scanned_per_sec=round(S * T / p50, 1),
          cold_first_query_s=round(cold_s, 3))


# -------------------------------------------------------------- histogram


def bench_histogram(quick: bool):
    """Histogram-schema ingest + quantile query
    (ref: HistogramIngestBenchmark.scala:24, HistogramQueryBenchmark)."""
    from filodb_tpu.core.memstore import TimeSeriesMemStore
    from filodb_tpu.ingest.generator import histogram_batch
    from filodb_tpu.parallel.shardmapper import ShardEvent, ShardMapper
    from filodb_tpu.query.engine import QueryEngine
    S, T = (50, 100) if quick else (200, 360)
    batch = histogram_batch(S, T, start_ms=START)
    times = []
    for i in range(3):
        ms = TimeSeriesMemStore()
        sh = ms.setup(f"hb{i}", 0)
        t0 = time.perf_counter()
        sh.ingest(batch)
        times.append(time.perf_counter() - t0)
    _emit("histogram", "ingest_samples_per_sec", S * T / min(times),
          "samples/s", buckets=batch.columns["h"].shape[-1])
    ms = TimeSeriesMemStore()
    sh = ms.setup("prometheus", 0)
    sh.ingest(batch)
    mapper = ShardMapper(1)
    mapper.update_from_event(
        ShardEvent("IngestionStarted", "prometheus", 0, "b"))
    eng = QueryEngine("prometheus", ms, mapper)
    s = START // 1000
    q = 'histogram_quantile(0.9,sum by (le)(rate(http_latency[5m])))'
    run = lambda: eng.query_range(q, s + 600, 60, s + T * 10)  # noqa: E731
    res = run()
    assert res.error is None, res.error
    per = _time_it(run, 2 if quick else 5)
    _emit("histogram", "quantile_qps", 1 / per, "queries/s", series=S)


def bench_histogram_compression(quick: bool):
    """Histogram storage-format efficiency, the HistogramCompressor
    harness analogue (ref: memory/.../HistogramCompressor.scala:1-216;
    doc/compression.md:97 claims ~50x vs the traditional per-bucket
    Prometheus data model at 64 buckets).  Measures bytes/histogram-sample
    for: the per-bucket time-series model, BinaryHistogram ingest blobs,
    the section-based appendable vector, and the sealed 2D-delta matrix
    codec."""
    import numpy as np

    from filodb_tpu.core.partkey import PartKey
    from filodb_tpu.memory.binhist import (AppendableSectHistVector,
                                           encode_blob_column)
    from filodb_tpu.memory.histogram import encode_hist_matrix

    B = 64
    T = 300 if quick else 2_000
    rng = np.random.default_rng(9)
    # busy + quiet mixture like real request-latency histograms
    rate = np.where(rng.random(B) < 0.3, 8.0, 0.2)
    inc = rng.poisson(rate, size=(T, B))
    per_bucket = np.cumsum(inc, axis=0)
    mat = np.cumsum(per_bucket, axis=1).astype(np.float64)
    les = 2.0 * 2.0 ** np.arange(B)

    # traditional prom data model: one series per bucket; each sample is
    # (ts i64 + value f64) plus the bucket series' part key amortized
    labels = {"_ws_": "demo", "_ns_": "App-0", "instance": "host-1",
              "path": "/api/v1/query"}
    pk_bytes = sum(
        len(PartKey.make("http_latency_bucket",
                         dict(labels, le=str(le))).to_bytes())
        for le in les)
    bucket_series_bytes = T * B * 16 + pk_bytes
    per_hist_bucket_series = bucket_series_bytes / T

    blob_bytes = len(encode_blob_column(mat, les))
    vec = AppendableSectHistVector(les)
    for row in mat:
        vec.append(row)
    sealed_bytes = len(encode_hist_matrix(mat))

    per_hist_blob = blob_bytes / T
    _emit("hist_compression", "bucket_series_bytes_per_hist",
          per_hist_bucket_series, "bytes", buckets=B)
    _emit("hist_compression", "binhist_blob_bytes_per_hist", per_hist_blob,
          "bytes", buckets=B,
          vs_bucket_series=round(per_hist_bucket_series / per_hist_blob, 1))
    _emit("hist_compression", "section_vector_bytes_per_hist",
          vec.num_bytes / T, "bytes", buckets=B,
          vs_bucket_series=round(per_hist_bucket_series
                                 / (vec.num_bytes / T), 1))
    _emit("hist_compression", "sealed_2d_delta_bytes_per_hist",
          sealed_bytes / T, "bytes", buckets=B,
          vs_bucket_series=round(per_hist_bucket_series
                                 / (sealed_bytes / T), 1))


def bench_cardinality(quick: bool):
    """Cardinality store at the reference's millions-of-prefixes scale
    (ref: RocksDbCardinalityStore.scala:256): batched write throughput,
    flush cost, and top-k query latency on the durable SQLite store."""
    import tempfile

    from filodb_tpu.core.ratelimit import (CardinalityRecord,
                                           SqliteCardinalityStore)
    n = 50_000 if quick else 1_000_000
    path = tempfile.mktemp(prefix="filodb_card_bench_", suffix=".db")
    store = SqliteCardinalityStore(path, flush_every=4096)
    t0 = time.perf_counter()
    for i in range(n):
        store.write(CardinalityRecord(
            ("demo", f"ns-{i % 1000}", f"app-{i}"), ts_count=i % 97 + 1))
    store.flush()
    wall = time.perf_counter() - t0
    _emit("cardinality", "writes_per_sec", n / wall, "ops/s", prefixes=n)
    t0 = time.perf_counter()
    kids = store.scan_children(("demo", "ns-7"))
    scan_s = time.perf_counter() - t0
    _emit("cardinality", "scan_children_latency_ms", scan_s * 1000, "ms",
          children=len(kids))
    store.close()
    import os as _os
    _os.unlink(path)


def bench_memory(quick: bool):
    """Resident memory per series after sealing history to the compressed
    tier (ref: doc/ingestion.md:110 '1.5 million time series fit within
    1GB heap' — the reference's only quantitative memory claim)."""
    from filodb_tpu.core.memstore import TimeSeriesMemStore
    from filodb_tpu.ingest.generator import counter_batch

    S = 2_000 if quick else 20_000
    T = 360                                   # 1h of 10s samples
    ms = TimeSeriesMemStore()
    shard = ms.setup("prometheus", 0)
    for lo in range(0, S, 2_000):             # batch to bound peak RAM
        n = min(2_000, S - lo)
        b = counter_batch(n, T, start_ms=START,
                          metric=f"m{lo}")
        # real counters are integral — exercises the delta-delta-as-long
        # double encoding (ref: DoubleVector.scala 'when integral')
        b.columns["count"] = np.floor(b.columns["count"])
        shard.ingest(b)
    dense_before = shard.memory_usage()["dense_bytes"]
    shard.enforce_memory(budget_bytes=1, active_tail_rows=32)
    u = shard.memory_usage()
    per_series = u["total_bytes"] / S
    _emit("memory", "bytes_per_series_1h", per_series, "bytes",
          series=S, samples_per_series=T,
          dense_bytes=u["dense_bytes"], resident_bytes=u["resident_bytes"],
          dense_before_bytes=dense_before,
          series_per_gb=round((1 << 30) / per_series),
          compressed_bytes_per_sample=round(
              u["resident_bytes"] / (S * T), 3))


def bench_intsum(quick: bool):
    """Bit-packed int vector decode + scan-sum (ref: IntSumReadBenchmark,
    BasicFiloBenchmark — sum over an encoded int vector)."""
    from filodb_tpu.memory import intvec
    n = 100_000 if quick else 1_000_000
    vals = np.random.default_rng(1).integers(0, 1000, n).astype(np.int64)
    enc = intvec.pack_ints(vals)
    iters = 5 if quick else 20
    per = _time_it(lambda: int(intvec.unpack_ints(enc, n).sum()), iters)
    _emit("intsum", "decode_sum_values_per_sec", n / per, "values/s",
          width_bits=intvec.packed_width_bits(enc),
          bytes_per_value=round(len(enc) / n, 3))
    per = _time_it(lambda: intvec.pack_ints(vals), iters)
    _emit("intsum", "encode_values_per_sec", n / per, "values/s")


def bench_utf8(quick: bool):
    """UTF8 blob + dictionary string vector encode/decode
    (ref: UTF8StringBenchmark, DictStringBenchmark)."""
    from filodb_tpu.memory import utf8vec
    n = 10_000 if quick else 100_000
    vocab = [f"value-{i}".encode() for i in range(64)]
    col = [vocab[i % 64] for i in range(n)]
    iters = 3 if quick else 10
    per = _time_it(lambda: utf8vec.pack_utf8(col), iters)
    _emit("utf8", "blob_encode_strings_per_sec", n / per, "strings/s")
    enc = utf8vec.pack_dict_utf8(col)
    per = _time_it(lambda: utf8vec.pack_dict_utf8(col), iters)
    _emit("utf8", "dict_encode_strings_per_sec", n / per, "strings/s",
          bytes_per_string=round(len(enc) / n, 3),
          plain_bytes_per_string=round(len(utf8vec.pack_utf8(col)) / n, 3))
    per = _time_it(lambda: utf8vec.unpack_dict_utf8(enc), iters)
    _emit("utf8", "dict_decode_strings_per_sec", n / per, "strings/s")


def bench_downsample(quick: bool):
    """Batch downsampler throughput: raw persisted chunks -> 5m rollups
    (ref: spark-jobs/.../DownsamplerMain.scala — the 5th driver-designated
    target config in BASELINE.md)."""
    from filodb_tpu.core.memstore import TimeSeriesMemStore
    from filodb_tpu.core.store import InMemoryColumnStore, InMemoryMetaStore
    from filodb_tpu.downsample.batch_job import DownsamplerJob
    from filodb_tpu.ingest.generator import gauge_batch, counter_batch

    S, T = (200, 360) if quick else (2000, 720)
    raw_cs, raw_meta = InMemoryColumnStore(), InMemoryMetaStore()
    ms = TimeSeriesMemStore(column_store=raw_cs, meta_store=raw_meta)
    shard = ms.setup("prometheus", 0)
    shard.ingest(gauge_batch(S // 2, T, start_ms=START))
    shard.ingest(counter_batch(S // 2, T, start_ms=START))
    shard.flush_all_groups()
    samples = S * T
    iters = 2 if quick else 3
    times = []
    for _ in range(iters):
        job = DownsamplerJob(raw_cs, InMemoryColumnStore(), "prometheus",
                             resolutions=(300_000,))
        t0 = time.perf_counter()
        stats = job.run([0], START, START + T * 10_000)
        times.append(time.perf_counter() - t0)
    best = min(times)
    _emit("downsample", "raw_samples_per_sec", samples / best, "samples/s",
          series=S, parts=stats.parts_scanned,
          records_emitted=stats.records_emitted,
          chunks_written=stats.chunks_written)


def bench_downsample_dist(quick: bool):
    """Distributed downsampler rollup throughput vs worker count: shard
    splits over worker processes on the shared local store (ref:
    DownsamplerMain.scala:64-90 Spark fan-out over scan splits).  Reports
    samples rolled/s for 1 worker and N workers — on a multi-core host the
    scaling approaches N x; this 1-core CI box mostly shows the fan-out
    machinery overhead staying small."""
    import tempfile

    from filodb_tpu.core.memstore import TimeSeriesMemStore
    from filodb_tpu.core.store import InMemoryMetaStore
    from filodb_tpu.downsample.dist_job import DistributedDownsamplerJob
    from filodb_tpu.ingest.generator import counter_batch, gauge_batch
    from filodb_tpu.persist.localstore import LocalDiskColumnStore

    shards, S, T = (2, 100, 240) if quick else (6, 400, 720)
    tmp = tempfile.mkdtemp(prefix="bench_dsdist_")
    raw_root = os.path.join(tmp, "raw")
    cs = LocalDiskColumnStore(raw_root)
    ms = TimeSeriesMemStore(column_store=cs, meta_store=InMemoryMetaStore())
    for sh in range(shards):
        s = ms.setup("prometheus", sh)
        s.ingest(gauge_batch(S // 2, T, start_ms=START, seed=sh))
        s.ingest(counter_batch(S // 2, T, start_ms=START, seed=sh + 100))
        s.flush_all_groups()
    cs.close()
    samples = shards * S * T
    for workers in (1, 2 if quick else 4):
        ds_root = os.path.join(tmp, f"ds_w{workers}")
        job = DistributedDownsamplerJob(raw_root, ds_root, "prometheus",
                                        workers=workers,
                                        resolutions=(300_000,))
        t0 = time.perf_counter()
        stats = job.run(list(range(shards)), START, START + T * 10_000)
        dt = time.perf_counter() - t0
        _emit("downsample_dist", f"rolled_samples_per_sec_w{workers}",
              samples / dt, "samples/s", workers=workers, shards=shards,
              parts=stats.parts_scanned,
              records_emitted=stats.records_emitted)


def bench_dispatch(quick: bool):
    """Cross-node query dispatch QPS over the TCP wire (the Akka-remoting
    analogue; ref: exec/PlanDispatcher.scala:20-57, client/Serializer —
    plan subtree + serialized results over the socket)."""
    from filodb_tpu.ingest.generator import counter_batch
    from filodb_tpu.parallel.testcluster import make_two_node_cluster

    S, T = (100, 240) if quick else (400, 720)
    cluster = make_two_node_cluster([counter_batch(S, T, start_ms=START)])
    try:
        start_s = START // 1000
        q = 'sum by (_ns_)(rate(request_total[5m]))'
        run = lambda: cluster.engine.query_range(  # noqa: E731
            q, start_s + 600, 60, start_s + T * 10)
        assert run().error is None
        n = 20 if quick else 50
        per = _time_it(run, n)
        _emit("dispatch", "cross_node_queries_per_sec", 1.0 / per,
              "queries/s", shards=4, nodes=2, series=S)
    finally:
        cluster.stop()


def bench_persist(quick: bool):
    """Flush-to-disk and read-back throughput through the CRC-framed
    column store (the ChunkSink/RawChunkSource analogue of the reference's
    Cassandra write/read path, ref: CassandraColumnStore.scala:53-80)."""
    import shutil
    import tempfile

    from filodb_tpu.core.memstore import TimeSeriesMemStore
    from filodb_tpu.ingest.generator import counter_batch
    from filodb_tpu.persist.localstore import (LocalDiskColumnStore,
                                               LocalDiskMetaStore)
    S, T = (500, 360) if quick else (2000, 720)
    tmp = tempfile.mkdtemp(prefix="filodb-bench-persist-")
    try:
        cs = LocalDiskColumnStore(tmp)
        ms = TimeSeriesMemStore(column_store=cs,
                                meta_store=LocalDiskMetaStore(tmp))
        sh = ms.setup("prometheus", 0)
        sh.ingest(counter_batch(S, T, start_ms=START))
        t0 = time.perf_counter()
        sh.flush_all_groups()
        fl = time.perf_counter() - t0
        _emit("persist", "flush_samples_per_sec", S * T / fl, "samples/s",
              series=S)
        # COLD store for the read: a fresh instance pays the real
        # recovery frame scan, not the writer's warm in-memory index
        cold = LocalDiskColumnStore(tmp)
        t0 = time.perf_counter()
        n = 0
        for rec in cold.read_part_keys("prometheus", 0):
            for c in cold.read_chunks("prometheus", 0, rec.part_key,
                                      0, 1 << 62):
                n += c.info.num_rows
        rd = time.perf_counter() - t0
        _emit("persist", "read_samples_per_sec", n / rd, "samples/s",
              samples=n)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


BENCHES: Dict[str, Callable[[bool], None]] = {
    "dispatch": bench_dispatch,
    "persist": bench_persist,
    "downsample": bench_downsample,
    "downsample_dist": bench_downsample_dist,
    "ingestion": bench_ingestion,
    "intsum": bench_intsum,
    "utf8": bench_utf8,
    "memory": bench_memory,
    "encoding": bench_encoding,
    "index": bench_index,
    "gateway": bench_gateway,
    "query": bench_query,
    "query_hicard": bench_query_hicard,
    "dashboard_batch": bench_dashboard_batch,
    "query_1m": bench_query_1m,
    "query_odp": bench_query_odp,
    "partition_list": bench_partition_list,
    "query_under_ingest": bench_query_under_ingest,
    "histogram": bench_histogram,
    "hist_compression": bench_histogram_compression,
    "cardinality": bench_cardinality,
}


def main(argv: List[str] = None):
    ap = argparse.ArgumentParser(description="filodb-tpu benchmark suite")
    ap.add_argument("bench", nargs="?", choices=sorted(BENCHES),
                    help="run one benchmark (default: all)")
    ap.add_argument("--quick", action="store_true")
    from bench.platform import add_platform_arg, apply_platform
    add_platform_arg(ap)
    args = ap.parse_args(argv)
    apply_platform(args)
    targets = [args.bench] if args.bench else sorted(BENCHES)
    for name in targets:
        BENCHES[name](args.quick)


if __name__ == "__main__":
    main()
