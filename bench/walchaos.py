"""WAL kill-chaos child: ingest forever through the WAL, print each ack.

The bench's durability proof (bench.py `measure_wal` / the `wal` stage)
runs this as a REAL subprocess, SIGKILLs it mid-ingest, and then replays
the WAL directory it left behind.  The parent is the "client": the only
batches it counts as acknowledged are the ones whose `ACKED <batch>
<seq>` line it read — printed strictly AFTER the group commit returned —
so "zero acknowledged samples lost" is measured from the client's side
of the ack, exactly the contract remote_write makes.

Batches are DETERMINISTIC in (series, k, batch index): the parent
regenerates the same grids to build the uninterrupted-run reference
store and compares query results bit-for-bit against the recovered one.
"""
from __future__ import annotations

import argparse
import sys

import numpy as np


def chaos_batch(series: int, k: int, b: int, start_ms: int):
    """Deterministic batch b: ts [S, k] and values [S, k] (shared with
    the parent's reference-store rebuild — one formula, no drift)."""
    ts_row = start_ms + (np.arange(k, dtype=np.int64) + b * k) * 10_000
    ts = np.broadcast_to(ts_row, (series, k))
    vals = (np.arange(series, dtype=np.float64)[:, None] * 3.0
            + (np.arange(k, dtype=np.float64) + b * k)[None, :])
    return ts, vals


def chaos_keys(series: int):
    from filodb_tpu.core.partkey import PartKey
    return [PartKey.make("wal_chaos_total",
                         {"_ws_": "chaos", "_ns_": "wal",
                          "inst": f"i{i:05d}"})
            for i in range(series)]


START_MS = 1_600_000_000_000


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--wal-dir", required=True)
    ap.add_argument("--dataset", default="prometheus")
    ap.add_argument("--series", type=int, default=2048)
    ap.add_argument("--k", type=int, default=2)
    ap.add_argument("--max-batches", type=int, default=1_000_000)
    args = ap.parse_args(argv)

    from filodb_tpu.config import WalConfig
    from filodb_tpu.core.memstore import TimeSeriesMemStore
    from filodb_tpu.wal import WalManager

    ms = TimeSeriesMemStore()
    shard = ms.setup(args.dataset, 0)
    wal = WalManager(args.wal_dir, args.dataset, WalConfig(enabled=True))
    keys = chaos_keys(args.series)
    print(f"CHAOS_READY series={args.series} k={args.k}", flush=True)
    for b in range(args.max_batches):
        ts, vals = chaos_batch(args.series, args.k, b, START_MS)
        seq = wal.append_grid(0, "gauge", keys, ts, {"value": vals})
        shard.ingest_columns("gauge", keys, ts, {"value": vals},
                             offset=seq)
        # the ack the parent counts: printed only after the group commit
        # (wal.append_grid blocks on it) — the client-visible 2xx
        print(f"ACKED {b} {seq}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
