"""Shared --platform plumbing for bench/stress entry points.

The ambient environment points JAX at a tunneled TPU whose first connect can
hang for minutes; pinning must happen via jax.config BEFORE any filodb import
touches jax (env vars are too late once the sitecustomize hook ran)."""
from __future__ import annotations


def add_platform_arg(ap) -> None:
    ap.add_argument("--platform", default="",
                    help="pin the jax platform (e.g. cpu) — the tunneled "
                         "TPU backend's init can hang for minutes")


def apply_platform(args) -> None:
    if getattr(args, "platform", ""):
        import jax
        jax.config.update("jax_platforms", args.platform)
