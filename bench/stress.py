"""Long-running stress/soak harnesses, assertion-checked.

Ports of the reference's stress apps (ref: stress/src/main/scala/
filodb.stress/ — IngestionStress.scala, InMemoryQueryStress.scala): keep
the system under continuous load for minutes, verify invariants the unit
suite can't (stable RSS under churn, no correctness drift under sustained
concurrent ingest+query+flush), and print one JSON line per harness.

Opt-in (not part of the driver's bench):
    python -m bench.stress ingest --minutes 10
    python -m bench.stress query  --minutes 10
    python -m bench.stress all    --minutes 5
"""
from __future__ import annotations

import argparse
import json
import os
import threading
import time
from typing import List


def _rss_mb() -> float:
    with open("/proc/self/statm") as f:
        pages = int(f.read().split()[1])
    return pages * os.sysconf("SC_PAGE_SIZE") / (1 << 20)


def _emit(harness: str, ok: bool, **extra):
    print(json.dumps({"stress": harness, "ok": ok, **extra}), flush=True)


def _overlap_flags(sh):
    """(eviction_in_progress, mirror_rebuild_in_progress) for latency
    attribution: every recorded query latency is tagged with these so a
    tail outlier (like SOAK_LONG_r05's 752 s p99) is attributable to its
    overlapping maintenance window from the artifact alone."""
    evicting = bool(getattr(sh, "eviction_in_progress", False))
    rebuilding = any(
        getattr(getattr(st, "device_mirror", None), "rebuild_in_progress",
                False)
        for st in sh.stores.values())
    return evicting, rebuilding


def _flag_breakdown(lat, flags):
    """Per-overlap-category counts and percentiles from parallel lists of
    latencies and (evict, rebuild) flag tuples."""
    import numpy as np
    cats = {"clean": [], "evict_overlap": [], "rebuild_overlap": []}
    for dt, (ev, rb) in zip(lat, flags):
        if rb:
            cats["rebuild_overlap"].append(dt)
        elif ev:
            cats["evict_overlap"].append(dt)
        else:
            cats["clean"].append(dt)
    out = {}
    for name, vals in cats.items():
        out[name] = {"n": len(vals)}
        if vals:
            arr = np.asarray(vals)
            out[name]["p50_s"] = round(float(np.percentile(arr, 50)), 4)
            out[name]["p99_s"] = round(float(np.percentile(arr, 99)), 4)
            out[name]["max_s"] = round(float(arr.max()), 4)
    return out


def ingestion_stress(minutes: float, series: int = 5_000) -> bool:
    """Continuous ingest + background flush + memory enforcement; asserts
    zero drops/errors and a stable RSS after warm-up (the
    IngestionStress.scala shape: heavy + quick streams, verified counts)."""
    import numpy as np
    from filodb_tpu.core.flush import FlushScheduler
    from filodb_tpu.core.memstore import TimeSeriesMemStore
    from filodb_tpu.ingest.generator import counter_batch
    from filodb_tpu.persist.localstore import (LocalDiskColumnStore,
                                               LocalDiskMetaStore)
    import tempfile
    tmp = tempfile.mkdtemp(prefix="filodb_stress_")
    ms = TimeSeriesMemStore(column_store=LocalDiskColumnStore(tmp),
                            meta_store=LocalDiskMetaStore(tmp))
    sh = ms.setup("stress", 0)
    sh.config.store.shard_mem_size = 256 << 20
    # small resident budget so every tier reaches steady state within the
    # soak window — the point is proving the plateaus hold, not sizing
    sh.resident.budget_bytes = 64 << 20
    sched = FlushScheduler(ms, "stress", interval_s=5.0).start()
    START = 1_600_000_000_000
    deadline = time.time() + minutes * 60
    t_idx = 0
    total = 0
    # The dense tier saw-tooths by design (fill until the headroom task
    # truncates), so raw RSS samples mix cycle phases.  Leak detection
    # compares SAME-PHASE marks: RSS at each post-enforcement trough.
    troughs: List[float] = []
    last_evictions = 0
    base = counter_batch(series, 1, start_ms=START)
    try:
        while time.time() < deadline:
            # 20 new samples per series per iteration, strictly in-order,
            # through the columnar grid path (shard.ingest_columns) — the
            # scrape-cycle shape needs no flatten/re-sort round trip
            n = 20
            ts2d = np.broadcast_to(
                START + (t_idx + np.arange(n, dtype=np.int64)) * 10_000,
                (series, n))
            vals = (t_idx + np.arange(n, dtype=np.float64))[None, :] \
                * 5.0 + np.arange(series)[:, None]
            total += sh.ingest_columns("prom-counter", base.part_keys,
                                       ts2d, {"count": vals}, offset=t_idx)
            t_idx += n
            if sh.stats.evictions > last_evictions:
                last_evictions = sh.stats.evictions
                troughs.append(_rss_mb())
    finally:
        sched.stop(final_flush=True)
    dropped = sh.stats.rows_dropped
    # Stable = the troughs stop climbing once tiers filled: compare the
    # last trough against the median of the middle third.
    stable = True
    if minutes >= 2 and len(troughs) >= 6:
        third = len(troughs) // 3
        mid = float(np.median(troughs[third:2 * third]))
        stable = troughs[-1] / max(mid, 1.0) < 1.2
    ok = (dropped == 0 and sched.errors == 0 and stable
          and total == series * t_idx)
    _emit("ingestion", ok, samples=total, dropped=int(dropped),
          flush_errors=sched.errors, rss_mb=round(_rss_mb(), 1),
          rss_stable=stable, evictions=sh.stats.evictions,
          trough_rss_mb=[round(t, 1) for t in troughs[-6:]])
    return ok


def _setup_live_ingest(series: int):
    """Shared scaffold for the query-under-ingest harnesses: a memstore
    warmed with 30min of deterministic counters (+5 per 10s per series)
    plus an ingester loop extending them live.  Returns
    (engine, ingester_fn, stop_event, ingested_counter); both harnesses'
    rate bound checks depend on the +5/10s invariant — change it here,
    not in a copy."""
    import numpy as np
    from filodb_tpu.core.memstore import TimeSeriesMemStore
    from filodb_tpu.core.records import RecordBatch
    from filodb_tpu.ingest.generator import counter_batch
    from filodb_tpu.query.engine import QueryEngine
    START = 1_600_000_000_000
    ms = TimeSeriesMemStore()
    sh = ms.setup("stress", 0)
    base = counter_batch(series, 1, start_ms=START)
    warm = 180
    ts = np.tile(START + np.arange(warm, dtype=np.int64) * 10_000, series)
    idx = np.repeat(np.arange(series, dtype=np.int32), warm)
    vals = np.arange(warm, dtype=np.float64)[None, :] * 5.0 \
        + np.arange(series)[:, None]
    sh.ingest(RecordBatch(base.schema, base.part_keys, idx, ts,
                          {"count": vals.ravel()}))
    stop = threading.Event()
    ingested = [0]

    def ingester():
        t_idx = warm
        while not stop.is_set():
            n = 10
            its = np.broadcast_to(
                START + (t_idx + np.arange(n, dtype=np.int64)) * 10_000,
                (series, n))
            ivals = (t_idx + np.arange(n, dtype=np.float64))[None, :] \
                * 5.0 + np.arange(series)[:, None]
            sh.ingest_columns("prom-counter", base.part_keys, its,
                              {"count": ivals})
            t_idx += n
            ingested[0] += n * series
            time.sleep(0.01)

    return QueryEngine("stress", ms), ingester, stop, ingested


def query_stress(minutes: float, series: int = 2_000,
                 query_threads: int = 4) -> bool:
    """Concurrent PromQL queries against live ingest for the duration;
    asserts every query succeeds and rates stay in the generator's bounds
    (InMemoryQueryStress.scala: parallel queries, verified results)."""
    import numpy as np
    from filodb_tpu.query.rangevector import PlannerParams
    pp = PlannerParams(sample_limit=200_000_000)
    eng, ingester, stop, _ = _setup_live_ingest(series)
    s = 1_600_000_000_000 // 1000
    deadline = time.time() + minutes * 60
    counts = [0] * query_threads
    errors: List[str] = []

    def querier(i):
        while time.time() < deadline and not errors:
            res = eng.query_range('sum by (_ns_)(rate(request_total[5m]))',
                                  s + 600, 60, s + 1700, pp)
            if res.error is not None:
                errors.append(res.error)
                return
            for _, _, vs in res.series():
                arr = np.asarray(vs)
                finite = arr[np.isfinite(arr)]
                # each series gains +5 per 10s -> rate 0.5/s; per _ns_
                # group of series/10 members the sum is bounded
                if finite.size and ((finite < 0).any()
                                    or (finite > series * 2.0).any()):
                    errors.append(f"rate out of bounds: {finite.min()}"
                                  f"..{finite.max()}")
                    return
            counts[i] += 1

    ing = threading.Thread(target=ingester, daemon=True)
    ing.start()
    threads = [threading.Thread(target=querier, args=(i,))
               for i in range(query_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    ing.join(timeout=10)
    ok = not errors and sum(counts) > 0
    _emit("query", ok, queries=sum(counts),
          qps=round(sum(counts) / max(minutes * 60, 1e-9), 1),
          errors=errors[:3], rss_mb=round(_rss_mb(), 1))
    return ok


def batch_query_stress(minutes: float, series: int = 2_000,
                       batch_threads: int = 2,
                       coalesce_threads: int = 3) -> bool:
    """Dashboard-batch machinery under live ingest for the duration:
    rotating panel sets through engine.query_range_batch AND single
    panels through the server-side coalescer (query/coalesce.py), every
    result verified, RSS tracked — the leak check for the r4 batch
    caches (merged gid matrices, panel groupings, coalescer groups)
    whose entries pin device arrays."""
    import numpy as np
    from filodb_tpu.query.coalesce import QueryCoalescer
    from filodb_tpu.query.rangevector import PlannerParams
    had_interp = os.environ.get("FILODB_TPU_FUSED_INTERPRET")
    os.environ["FILODB_TPU_FUSED_INTERPRET"] = "1"
    pp = PlannerParams(sample_limit=200_000_000)
    eng, ingester, stop, ingested = _setup_live_ingest(series)
    co = QueryCoalescer(eng, window_s=0.02)
    s0 = 1_600_000_000_000 // 1000
    args = (s0 + 600, 60, s0 + 1700)
    panel_sets = [
        ['sum(rate(request_total[5m])) by (_ns_)',
         'avg(rate(request_total[5m])) by (dc)',
         'sum(rate(request_total[5m])) by (dc)'],
        ['sum(rate(request_total[5m])) by (_ns_, dc)',
         'count(rate(request_total[5m])) by (_ns_)',
         'min(rate(request_total[5m])) by (dc)'],
        ['sum(rate(request_total[5m]))',
         'max(rate(request_total[5m])) by (_ns_)'],
    ]
    deadline = time.time() + minutes * 60
    counts = [0] * (batch_threads + coalesce_threads)
    errors: List[str] = []

    nonempty = [0]

    def check(res, q):
        if res.error is not None:
            errors.append(f"{q}: {res.error}")
            return False
        n = 0
        for _, _, vs in res.series():
            n += 1
            arr = np.asarray(vs)
            finite = arr[np.isfinite(arr)]
            if finite.size and (finite < -1e-6).any():
                errors.append(f"{q}: negative rate {finite.min()}")
                return False
        nonempty[0] += n > 0
        return True

    def batcher(i):
        k = 0
        while time.time() < deadline and not errors:
            panels = panel_sets[k % len(panel_sets)]
            k += 1
            for q, res in zip(panels,
                              eng.query_range_batch(panels, *args, pp)):
                if not check(res, q):
                    return
            counts[i] += 1

    def coalescer(i):
        k = 0
        while time.time() < deadline and not errors:
            q = panel_sets[0][k % 3]
            k += 1
            if not check(co.query_range(q, *args, pp), q):
                return
            counts[i] += 1

    rss0 = _rss_mb()
    ing = threading.Thread(target=ingester, daemon=True)
    ing.start()
    threads = [threading.Thread(target=batcher, args=(i,))
               for i in range(batch_threads)]
    threads += [threading.Thread(target=coalescer,
                                 args=(batch_threads + i,))
                for i in range(coalesce_threads)]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        stop.set()
        if had_interp is None:
            os.environ.pop("FILODB_TPU_FUSED_INTERPRET", None)
        else:
            os.environ["FILODB_TPU_FUSED_INTERPRET"] = had_interp
    ing.join(timeout=10)
    # "every result verified" must not hold vacuously: a regression
    # returning zero series everywhere is a failure, not a pass
    ok = not errors and sum(counts) > 0 and nonempty[0] > 0
    # rss grows with the live-ingested working set; report the ingested
    # volume alongside so cache leaks are distinguishable from data
    _emit("batch", ok, rounds=sum(counts), errors=errors[:3],
          ingested_samples=ingested[0],
          ingested_mb=round(ingested[0] * 16 / 1e6, 1),
          rss_start_mb=round(rss0, 1), rss_mb=round(_rss_mb(), 1))
    return ok


def north_star_soak(minutes: float, series: int = 1_048_576,
                    report_path: str = "",
                    target_ingest_per_s: float = 2_200_000.0) -> bool:
    """The full pipeline at the BASELINE.md north-star scale for the whole
    soak window: 1M-series ingest -> scheduled flush -> memory enforcement
    (evict to the compressed resident tier / disk, ODP-able) -> CONCURRENT
    PromQL sum-by(rate) queries, with RSS troughs and query p50/p99
    tracked and leak/correctness assertions at the end (ref:
    stress/.../MemStoreStress.scala; VERDICT r3 item 8 — prove the
    memstore story at target scale even with the chip absent)."""
    import tempfile

    import numpy as np

    from filodb_tpu.core.flush import FlushScheduler
    from filodb_tpu.core.memstore import TimeSeriesMemStore
    from filodb_tpu.ingest.generator import counter_batch
    from filodb_tpu.persist.localstore import (LocalDiskColumnStore,
                                               LocalDiskMetaStore)
    from filodb_tpu.query.engine import QueryEngine
    from filodb_tpu.query.rangevector import PlannerParams

    import sys

    def _phase(msg: str) -> None:
        # progress to STDERR: the stdout one-JSON-line contract stays
        # intact, and a wedged soak shows WHERE it wedged
        print(f"[soak +{time.time() - _soak_t0:.0f}s] {msg}",
              file=sys.stderr, flush=True)

    _soak_t0 = time.time()
    START = 1_600_000_000_000
    tmp = tempfile.mkdtemp(prefix="filodb_soak_")
    ms = TimeSeriesMemStore(column_store=LocalDiskColumnStore(tmp),
                            meta_store=LocalDiskMetaStore(tmp))
    sh = ms.setup("stress", 0)
    # budgets sized so every tier CYCLES within the window — the dense
    # store must overflow into enforcement (seal + evict to the resident
    # tier/disk) during the soak, not just grow; the device mirror is off
    # (re-mirroring 1M series per ingest generation would measure the
    # mirror, not the memstore)
    sh.config.store.shard_mem_size = 1 << 30
    sh.config.store.device_mirror_enabled = False
    sh.resident.budget_bytes = 256 << 20
    t0_build = time.time()
    base = counter_batch(series, 1, start_ms=START)
    build_s = time.time() - t0_build
    eng = QueryEngine("stress", ms)
    # the north-star query legitimately scans ~60M samples (1M series x a
    # 10-minute window): lift the default per-query caps for the soak
    pp = PlannerParams(sample_limit=2_000_000_000,
                       scan_limit=2_000_000_000)
    sched = FlushScheduler(ms, "stress", interval_s=20.0).start()

    stop = threading.Event()
    state = {"t_idx": 0, "ingested": 0, "iters": 0}
    lat: List[float] = []
    lat_flags: List[tuple] = []
    errors: List[str] = []
    troughs: List[float] = []
    last_evictions = 0
    s = START // 1000
    step_ms = 10_000

    def ingest_once():
        # columnar grid ingest: the scrape-cycle shape goes straight to
        # the SoA store as rectangular slice writes (shard.ingest_columns)
        t_idx = state["t_idx"]
        ts2d = np.broadcast_to(
            START + (t_idx + np.arange(2, dtype=np.int64)) * step_ms,
            (series, 2))
        vals = ((t_idx + np.arange(2, dtype=np.float64))[None, :] * 5.0
                + np.arange(series)[:, None])
        state["ingested"] += sh.ingest_columns(
            "prom-counter", base.part_keys, ts2d, {"count": vals},
            offset=t_idx)
        state["t_idx"] += 2
        state["iters"] += 1

    # ---- idle-p50 pre-phase: preload >600s of stream so the idle
    # queries cover the SAME 600s span the live loop's queries will
    # (a shorter preload would clamp lo to s+600 and compare unequal
    # workloads), no concurrent ingest — the under-ingest degradation
    # is then measured in-artifact against the same process/box
    # (round-5 verdict item 3)
    _phase(f"partkeys built in {build_s:.0f}s; preloading")
    for _ in range(65):
        ingest_once()
    _phase("preload done; idle queries")
    idle_lat: List[float] = []
    for _ in range(5):
        hi = s + state["t_idx"] * 10
        lo = max(s + 600, hi - 600)
        t0 = time.perf_counter()
        res = eng.query_range(
            'sum by (_ns_)(rate(request_total[5m]))', lo, 60, hi, pp)
        if res.error is not None:
            errors.append(res.error)
            break
        idle_lat.append(time.perf_counter() - t0)
        _phase(f"idle query {len(idle_lat)}: {idle_lat[-1]:.1f}s")
    idle_p50 = float(np.median(idle_lat)) if idle_lat else float("nan")

    # ---- ingest-only capacity: unpaced, no queries — the sustained
    # rate the pipeline itself supports.  On this 1-core box the
    # STEADY-STATE rate below divides the core with the query thread
    # and the flush encoder (a scheduling identity, not a pipeline
    # limit), so the capacity number is measured separately.
    cap_t0 = time.time()
    cap_n0 = state["ingested"]
    while time.time() - cap_t0 < 30 and not errors:
        ingest_once()
    ingest_only_rate = (state["ingested"] - cap_n0) \
        / max(time.time() - cap_t0, 1e-9)
    _phase(f"ingest-only capacity: {ingest_only_rate / 1e6:.2f}M/s; "
           f"starting {minutes:.1f}min soak window")

    def querier():
        # rate over the freshest 10 minutes of the stream, group-summed —
        # the headline shape against live data (absent windows before the
        # stream reaches 10m are fine; correctness bound checked below)
        while not stop.is_set() and not errors:
            hi = s + state["t_idx"] * 10
            lo = max(s + 600, hi - 600)
            if hi <= lo:
                time.sleep(1.0)
                continue
            f0 = _overlap_flags(sh)
            t0 = time.perf_counter()
            res = eng.query_range(
                'sum by (_ns_)(rate(request_total[5m]))', lo, 60, hi, pp)
            dt = time.perf_counter() - t0
            f1 = _overlap_flags(sh)
            if res.error is not None:
                errors.append(res.error)
                return
            lat.append(dt)
            lat_flags.append((f0[0] or f1[0], f0[1] or f1[1]))
            for _, _, vs in res.series():
                arr = np.asarray(vs)
                finite = arr[np.isfinite(arr)]
                # every series gains +5/10s => rate 0.5/s; group sums are
                # bounded by series * 0.5 with headroom for extrapolation
                if finite.size and ((finite < 0).any()
                                    or (finite > series).any()):
                    errors.append(
                        f"rate bound: {finite.min()}..{finite.max()}")
                    return
            time.sleep(0.5)

    qt = threading.Thread(target=querier, daemon=True)
    qt.start()
    # the soak window starts AFTER the pre-phase — preload + idle
    # queries must not silently eat the reported minutes
    deadline = time.time() + minutes * 60
    ingest_t0 = time.time()
    ingested0 = state["ingested"]
    try:
        while time.time() < deadline and not errors:
            # 2 new samples per series per iteration, in-order; PACED to
            # the target sustained rate (a scrape pipeline delivers on a
            # cadence — unpaced max-rate ingest would just measure one
            # core timeslicing two saturated threads)
            ingest_once()
            if sh.stats.evictions > last_evictions:
                last_evictions = sh.stats.evictions
                troughs.append(_rss_mb())
            if target_ingest_per_s > 0:
                ahead = (state["ingested"] - ingested0) \
                    / target_ingest_per_s - (time.time() - ingest_t0)
                if ahead > 0:
                    time.sleep(min(ahead, 5.0))
    finally:
        stop.set()
        qt.join(timeout=120)
        _phase("soak window done; final flush")
        sched.stop(final_flush=True)
        _phase("final flush done")
    ingest_wall_s = max(time.time() - ingest_t0, 1e-9)

    stable = True
    if len(troughs) >= 6:
        third = len(troughs) // 3
        mid = float(np.median(troughs[third:2 * third]))
        stable = troughs[-1] / max(mid, 1.0) < 1.2
    larr = np.asarray(lat) if lat else np.asarray([float("nan")])
    ok = (not errors and sh.stats.rows_dropped == 0 and sched.errors == 0
          and stable and len(lat) > 0
          and state["ingested"] == series * state["t_idx"])
    p50_under = float(np.nanpercentile(larr, 50))
    report = {
        "stress": "north_star_soak", "ok": ok, "series": series,
        "minutes": round(minutes, 1),
        "samples_ingested": state["ingested"],
        "samples_per_sec_ingest": round(
            (state["ingested"] - ingested0) / ingest_wall_s, 1),
        "ingest_only_samples_per_sec": round(ingest_only_rate, 1),
        "target_ingest_per_s": target_ingest_per_s,
        "dropped": int(sh.stats.rows_dropped),
        "flush_errors": sched.errors, "evictions": sh.stats.evictions,
        "chunks_flushed": sh.stats.chunks_flushed
        if hasattr(sh.stats, "chunks_flushed") else None,
        "queries": len(lat),
        "query_p50_idle_s": round(idle_p50, 3),
        "query_p50_s": round(p50_under, 3),
        "query_p99_s": round(float(np.nanpercentile(larr, 99)), 3),
        # overlap-tagged breakdown: tail outliers are attributable to
        # their eviction / mirror-rebuild window from the artifact alone
        "query_overlap_breakdown": _flag_breakdown(lat, lat_flags),
        "under_ingest_vs_idle": round(p50_under / idle_p50, 2)
        if idle_p50 and np.isfinite(idle_p50) else None,
        "cpu_cores": os.cpu_count(),
        "errors": errors[:3],
        "rss_mb": round(_rss_mb(), 1), "rss_stable": stable,
        "trough_rss_mb": [round(t, 1) for t in troughs[-8:]],
        "partkey_build_s": round(build_s, 1),
    }
    print(json.dumps(report), flush=True)
    if report_path:
        with open(report_path, "w") as f:
            json.dump(report, f, indent=1)
    return ok


def eviction_window_soak(minutes: float = 2.0, series: int = 20_000,
                         report_path: str = "SOAK_PR2_EVICT.json") -> bool:
    """Eviction-window soak (PR 2 acceptance): continuous frontend queries
    while memory enforcement repeatedly shifts store rows (shift_version
    bumps -> full DeviceMirror rebuilds).  Every latency is tagged with
    overlap flags, and the harness asserts STRUCTURALLY that no query
    thread ever ran a post-eviction full `_refresh` — queries must ride
    the host-gather fallback while the rebuild happens in the background
    (the SOAK_LONG_r05 752 s p99 was one query paying that rebuild
    inline)."""
    import numpy as np

    from filodb_tpu.core.devicecache import DeviceMirror
    from filodb_tpu.core.memstore import TimeSeriesMemStore
    from filodb_tpu.ingest.generator import counter_batch
    from filodb_tpu.query.engine import QueryEngine
    from filodb_tpu.query.frontend import QueryFrontend
    from filodb_tpu.query.rangevector import PlannerParams
    from filodb_tpu.utils.metrics import registry

    START = 1_600_000_000_000
    ms = TimeSeriesMemStore()
    sh = ms.setup("stress", 0)
    base = counter_batch(series, 1, start_ms=START)
    warm = 240
    row_base = np.arange(series, dtype=np.float64)[:, None]

    def ingest_slab(t_idx, n):
        ts2d = np.broadcast_to(
            START + (t_idx + np.arange(n, dtype=np.int64)) * 10_000,
            (series, n))
        vals = (t_idx + np.arange(n, dtype=np.float64))[None, :] * 5.0 \
            + row_base
        sh.ingest_columns("prom-counter", base.part_keys, ts2d,
                          {"count": vals}, offset=t_idx)

    for t0 in range(0, warm, 60):
        ingest_slab(t0, min(60, warm - t0))
    # budget sized so enforcement fires repeatedly as the stream grows;
    # each enforcement truncates to the active tail = a shift_version bump
    budget = int(sum(s.nbytes for s in sh.stores.values()) * 0.75)
    tail_rows = warm // 2

    eng = QueryEngine("stress", ms)
    fe = QueryFrontend(eng)
    pp = PlannerParams(sample_limit=2_000_000_000, scan_limit=2_000_000_000)
    s = START // 1000
    stop = threading.Event()
    state = {"t_idx": warm}
    errors: List[str] = []
    lat: List[float] = []
    flags: List[tuple] = []

    # structural instrumentation: record which THREAD runs every full
    # mirror upload and whether it was the post-eviction (shift moved)
    # case — those must only ever run on mirror-rebuild threads
    refresh_calls: List[dict] = []
    orig_refresh = DeviceMirror._refresh

    def traced_refresh(self, store):
        snap = self._snap
        refresh_calls.append({
            "thread": threading.current_thread().name,
            "shift_moved": bool(snap is not None and
                                snap.shift_version != store.shift_version)})
        return orig_refresh(self, store)

    DeviceMirror._refresh = traced_refresh

    def ingester():
        while not stop.is_set():
            ingest_slab(state["t_idx"], 5)
            state["t_idx"] += 5
            time.sleep(0.05)

    def evictor():
        while not stop.is_set():
            time.sleep(8.0)
            try:
                sh.enforce_memory(budget, tail_rows)
            except Exception as e:  # noqa: BLE001 — soak must report it
                errors.append(f"evictor: {type(e).__name__}: {e}")
                return

    def querier():
        q = 'sum by (_ns_)(rate(request_total[5m]))'
        while not stop.is_set() and not errors:
            # step-aligned poll grid (Grafana aligns start/end to the
            # step): sliding re-polls share a window grid, so the result
            # cache serves the frozen prefix and computes only the tail
            hi = s + (state["t_idx"] * 10 // 60) * 60
            lo = max(s + 600, hi - 600)
            f0 = _overlap_flags(sh)
            t0 = time.perf_counter()
            res = fe.query_range(q, lo, 60, hi, pp)
            dt = time.perf_counter() - t0
            f1 = _overlap_flags(sh)
            if res.error is not None:
                errors.append(res.error)
                return
            lat.append(dt)
            flags.append((f0[0] or f1[0], f0[1] or f1[1]))
            time.sleep(0.1)

    fe.query_range('sum by (_ns_)(rate(request_total[5m]))',
                   s + 600, 60, s + warm * 10, pp)       # warm the mirror
    bg0 = registry.counter("device_mirror_bg_rebuilds").value
    fb0 = registry.counter("device_mirror_query_fallbacks").value
    threads = [threading.Thread(target=fn, daemon=True)
               for fn in (ingester, evictor, querier)]
    try:
        for t in threads:
            t.start()
        time.sleep(minutes * 60)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=60)
        DeviceMirror._refresh = orig_refresh

    bg_rebuilds = int(
        registry.counter("device_mirror_bg_rebuilds").value - bg0)
    fallbacks = int(
        registry.counter("device_mirror_query_fallbacks").value - fb0)
    # the acceptance invariant: every post-eviction full upload ran on a
    # background rebuild thread, never on a query's critical path
    inline_shift_refreshes = [
        c for c in refresh_calls
        if c["shift_moved"] and not c["thread"].startswith("mirror-rebuild")]
    larr = np.asarray(lat) if lat else np.asarray([float("nan")])
    ok = (not errors and len(lat) > 10 and bg_rebuilds >= 1
          and fallbacks >= 1 and not inline_shift_refreshes)
    report = {
        "stress": "eviction_window_soak", "ok": ok, "series": series,
        "minutes": round(minutes, 1), "queries": len(lat),
        "errors": errors[:3],
        "query_p50_s": round(float(np.nanpercentile(larr, 50)), 4),
        "query_p99_s": round(float(np.nanpercentile(larr, 99)), 4),
        "query_max_s": round(float(np.nanmax(larr)), 4),
        "query_overlap_breakdown": _flag_breakdown(lat, flags),
        "mirror_bg_rebuilds": bg_rebuilds,
        "mirror_query_fallbacks": fallbacks,
        "full_refresh_calls": len(refresh_calls),
        "inline_shift_refreshes": inline_shift_refreshes,
        "result_cache_invalidations": int(registry.counter(
            "query_result_cache_invalidations").value),
        "result_cache_partial_hits": int(registry.counter(
            "query_result_cache_partial_hits").value),
        "evictions": sh.stats.evictions,
        "rss_mb": round(_rss_mb(), 1),
        # every latency, tagged (ms, evict_overlap, rebuild_overlap):
        # tail outliers are attributable from the artifact alone
        "query_latencies_tagged": [
            [round(dt * 1000, 1), int(ev), int(rb)]
            for dt, (ev, rb) in zip(lat, flags)],
    }
    print(json.dumps({k: v for k, v in report.items()
                      if k != "query_latencies_tagged"}), flush=True)
    if report_path:
        with open(report_path, "w") as f:
            json.dump(report, f, indent=1)
    return ok


def main(argv=None):
    ap = argparse.ArgumentParser(description="filodb-tpu stress harnesses")
    ap.add_argument("harness",
                    choices=["ingest", "query", "batch", "soak", "evict",
                             "all"])
    ap.add_argument("--minutes", type=float, default=10.0)
    ap.add_argument("--series", type=int, default=1_048_576)
    ap.add_argument("--report", default="")
    ap.add_argument("--target-rate", type=float, default=2_200_000.0,
                    help="paced ingest samples/s for the soak (0 = max)")
    from bench.platform import add_platform_arg, apply_platform
    add_platform_arg(ap)
    args = ap.parse_args(argv)
    apply_platform(args)
    ok = True
    if args.harness in ("ingest", "all"):
        ok &= ingestion_stress(args.minutes)
    if args.harness in ("query", "all"):
        ok &= query_stress(args.minutes)
    if args.harness in ("batch", "all"):
        ok &= batch_query_stress(args.minutes)
    if args.harness == "soak":
        ok &= north_star_soak(args.minutes, series=args.series,
                              report_path=args.report,
                              target_ingest_per_s=args.target_rate)
    if args.harness == "evict":
        ok &= eviction_window_soak(
            args.minutes,
            series=args.series if args.series != 1_048_576 else 20_000,
            report_path=args.report or "SOAK_PR2_EVICT.json")
    raise SystemExit(0 if ok else 1)


if __name__ == "__main__":
    main()
