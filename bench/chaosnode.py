"""Chaos-bench data node: one OS process = one shard owner.

Spawned (and SIGKILLed, and respawned) by `python bench.py chaos`: builds
a deterministic counter dataset for its shard, serves it over the real
cross-node query transport, and keeps ingesting fresh scrape columns
while it lives — so the chaos run exercises mixed ingest+query traffic
through genuine process death, not a mock.  Series are tagged
`_ns_=<node name>`, which is what lets the coordinator distinguish a
correct partial result (dead node's group absent, flagged) from a
silently-wrong full one (group absent, NOT flagged).

Run: python bench/chaosnode.py --name A --port 7071 --shard 0 \
         --series 2048 [--platform cpu]
Prints one JSON line {"ready": true, ...} once serving.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

# REPLACE the script-dir path entry (bench/) with the repo root: bench/
# contains a platform.py that would shadow the stdlib module jax needs
sys.path[0] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402


def main(argv=None) -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--name", required=True)
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--shard", type=int, required=True)
    ap.add_argument("--dataset", default="chaos")
    ap.add_argument("--series", type=int, default=2048)
    ap.add_argument("--samples", type=int, default=420)
    ap.add_argument("--start-ms", type=int, default=1_600_000_000_000)
    ap.add_argument("--step-ms", type=int, default=10_000)
    ap.add_argument("--ingest-interval", type=float, default=0.5)
    ap.add_argument("--platform", default="cpu",
                    help="pin jax platform ('' keeps the default)")
    args = ap.parse_args(argv)

    if args.platform:
        import jax
        jax.config.update("jax_platforms", args.platform)

    from filodb_tpu.core.memstore import TimeSeriesMemStore
    from filodb_tpu.core.partkey import PartKey
    from filodb_tpu.core.records import RecordBatch
    from filodb_tpu.core.schemas import PROM_COUNTER
    from filodb_tpu.parallel.transport import NodeQueryServer
    from filodb_tpu.utils import metrics as _metrics

    _metrics.NODE_NAME = args.name
    S, T, step = args.series, args.samples, args.step_ms
    keys = [PartKey.make("chaos_total",
                         {"_ws_": "chaos", "_ns_": args.name,
                          "instance": f"{args.name}-{i}"})
            for i in range(S)]
    # deterministic monotonic counters: value = 5.0 * sample index + row
    part_idx = np.repeat(np.arange(S, dtype=np.int32), T)
    ts = np.tile(args.start_ms
                 + np.arange(T, dtype=np.int64) * step, S)
    vals = (np.arange(T, dtype=np.float64)[None, :] * 5.0
            + np.arange(S, dtype=np.float64)[:, None])
    batch = RecordBatch(PROM_COUNTER, keys, part_idx, ts,
                        {"count": vals.ravel()})
    ms = TimeSeriesMemStore()
    sh = ms.setup(args.dataset, args.shard)
    sh.ingest(batch)
    # warm the leaf query path BEFORE reporting ready: a restarted
    # node's first dispatched plan must answer within the probing
    # query's remaining deadline budget, not pay cold XLA compiles on
    # it (production nodes warm at boot via standalone warmup_shapes).
    # Execute exactly the subtree the coordinator dispatches.
    from filodb_tpu.core.index import Equals
    from filodb_tpu.query.exec import (AggregateMapReduce,
                                       MultiSchemaPartitionsExec,
                                       PeriodicSamplesMapper)
    from filodb_tpu.query.rangevector import QueryContext
    q_start = (args.start_ms // 1000 + 600) * 1000
    q_end = args.start_ms + (T - 1) * step
    warm = MultiSchemaPartitionsExec(
        QueryContext(), args.dataset, args.shard,
        [Equals("_metric_", "chaos_total")], args.start_ms, q_end)
    warm.add_transformer(PeriodicSamplesMapper(
        q_start, 60_000, q_end, 300_000, "rate", ()))
    warm.add_transformer(AggregateMapReduce("sum", (), ("_ns_",), ()))
    warm.execute_internal(ms)
    srv = NodeQueryServer(ms, port=args.port).start()
    print(json.dumps({"ready": True, "name": args.name,
                      "port": srv.address[1], "series": S,
                      "samples": T}), flush=True)
    # live ingest: one fresh scrape column per tick past the base window
    # (the chaos run's "mixed ingest+query" half) until we are killed
    t_idx = T
    while True:
        time.sleep(args.ingest_interval)
        col_ts = np.full((S, 1), args.start_ms + t_idx * step, np.int64)
        col_v = (np.full((S, 1), t_idx * 5.0)
                 + np.arange(S, dtype=np.float64)[:, None])
        sh.ingest_columns(PROM_COUNTER.name, keys, col_ts,
                          {"count": col_v})
        t_idx += 1


if __name__ == "__main__":
    main()
