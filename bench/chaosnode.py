"""Chaos-bench data node: one OS process owning COPIES of shards.

Spawned (and SIGKILLed, and respawned) by `python bench.py chaos`: for
every shard in --shards it builds the same deterministic counter
dataset any other owner of that shard builds (series are tagged
`_ns_=s<shard>` — shard-keyed, so primary and replica copies are
byte-identical by construction), replays its own WAL if one survives a
kill, then serves two doors:

  * the cross-node query transport (NodeQueryServer) — the coordinator
    scatter-gathers here, failing over between owners;
  * the replication door (ReplicationServer) — the coordinator's
    ReplicationManager fans live ingest slabs here (appended to this
    node's WAL before the ack), and a respawned peer catches up by
    streaming this node's WAL segments back out.

The node never self-ingests: all post-boot data arrives through the
replication door, which is exactly what makes "zero acked-ingest loss
through a SIGKILL" a provable property of the REPLICATION layer rather
than of scripted local writes.

Run: python bench/chaosnode.py --name A --port 7071 --repl-port 7171 \
         --shards 0,3 --wal-dir /tmp/chaosA [--platform cpu]
Prints one JSON line {"ready": true, ...} once serving.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

# REPLACE the script-dir path entry (bench/) with the repo root: bench/
# contains a platform.py that would shadow the stdlib module jax needs
sys.path[0] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402


def build_shard_batch(shard: int, series: int, samples: int,
                      start_ms: int, step_ms: int):
    """The shard's deterministic base dataset — every owner builds the
    identical copy.  value = 5.0 * sample index + row."""
    from filodb_tpu.core.partkey import PartKey
    from filodb_tpu.core.records import RecordBatch
    from filodb_tpu.core.schemas import PROM_COUNTER
    keys = [PartKey.make("chaos_total",
                         {"_ws_": "chaos", "_ns_": f"s{shard}",
                          "instance": f"s{shard}-{i}"})
            for i in range(series)]
    part_idx = np.repeat(np.arange(series, dtype=np.int32), samples)
    ts = np.tile(start_ms
                 + np.arange(samples, dtype=np.int64) * step_ms, series)
    vals = (np.arange(samples, dtype=np.float64)[None, :] * 5.0
            + np.arange(series, dtype=np.float64)[:, None])
    return RecordBatch(PROM_COUNTER, keys, part_idx, ts,
                       {"count": vals.ravel()}), keys


def chaos_column(shard: int, series: int, tick: int, start_ms: int,
                 step_ms: int):
    """One fresh scrape column for a shard at `tick` — the coordinator
    fans these through the replication door."""
    col_ts = np.full((series, 1), start_ms + tick * step_ms, np.int64)
    col_v = (np.full((series, 1), tick * 5.0)
             + np.arange(series, dtype=np.float64)[:, None])
    return col_ts, col_v


def main(argv=None) -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--name", required=True)
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--repl-port", type=int, required=True)
    ap.add_argument("--shards", required=True,
                    help="comma-separated shard numbers this node owns "
                         "a copy of (primary or replica)")
    ap.add_argument("--dataset", default="chaos")
    ap.add_argument("--series", type=int, default=2048)
    ap.add_argument("--samples", type=int, default=420)
    ap.add_argument("--start-ms", type=int, default=1_600_000_000_000)
    ap.add_argument("--step-ms", type=int, default=10_000)
    ap.add_argument("--wal-dir", default="",
                    help="WAL root for this node ('' disables): appends "
                         "through the replication door become durable, "
                         "and a SIGKILL'd node replays them on respawn")
    ap.add_argument("--platform", default="cpu",
                    help="pin jax platform ('' keeps the default)")
    args = ap.parse_args(argv)

    if args.platform:
        import jax
        jax.config.update("jax_platforms", args.platform)

    from filodb_tpu.core.memstore import TimeSeriesMemStore
    from filodb_tpu.parallel.transport import NodeQueryServer
    from filodb_tpu.replication import ReplicationServer
    from filodb_tpu.utils import metrics as _metrics

    _metrics.NODE_NAME = args.name
    shards = [int(s) for s in args.shards.split(",") if s != ""]
    S, T, step = args.series, args.samples, args.step_ms
    ms = TimeSeriesMemStore()
    warm_keys = {}
    for shard in shards:
        sh = ms.setup(args.dataset, shard)
        batch, keys = build_shard_batch(shard, S, T, args.start_ms, step)
        sh.ingest(batch)
        warm_keys[shard] = keys
    wals = {}
    replayed = 0
    if args.wal_dir:
        from filodb_tpu.wal import WalManager
        wal = WalManager(args.wal_dir, args.dataset)
        # a respawn after SIGKILL recovers everything the door acked
        # before the kill (the base dataset is deterministic; only door
        # appends live in the log)
        stats = wal.replay(ms)
        replayed = stats.records
        wals[args.dataset] = wal

    # warm the leaf query path BEFORE reporting ready: a restarted
    # node's first dispatched plan must answer within the probing
    # query's remaining deadline budget, not pay cold XLA compiles on
    # it (production nodes warm at boot via standalone warmup_shapes).
    from filodb_tpu.core.index import Equals
    from filodb_tpu.query.exec import (AggregateMapReduce,
                                       MultiSchemaPartitionsExec,
                                       PeriodicSamplesMapper)
    from filodb_tpu.query.rangevector import QueryContext
    q_start = (args.start_ms // 1000 + 600) * 1000
    q_end = args.start_ms + (T - 1) * step
    for shard in shards:
        warm = MultiSchemaPartitionsExec(
            QueryContext(), args.dataset, shard,
            [Equals("_metric_", "chaos_total")], args.start_ms, q_end)
        warm.add_transformer(PeriodicSamplesMapper(
            q_start, 60_000, q_end, 300_000, "rate", ()))
        warm.add_transformer(AggregateMapReduce("sum", (), ("_ns_",), ()))
        warm.execute_internal(ms)
    srv = NodeQueryServer(ms, port=args.port).start()
    rsrv = ReplicationServer(ms, node=args.name, wals=wals,
                             port=args.repl_port).start()
    print(json.dumps({"ready": True, "name": args.name,
                      "port": srv.address[1],
                      "repl_port": rsrv.address[1],
                      "shards": shards, "series": S, "samples": T,
                      "wal_replayed_records": replayed}), flush=True)
    # serve-only: every post-boot sample arrives through the
    # replication door until we are killed
    while True:
        time.sleep(1.0)


if __name__ == "__main__":
    main()
