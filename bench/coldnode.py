"""Elastic-read bench node: one STATELESS query-only OS process.

Spawned by `python bench.py objectstore`: it owns NO shards and holds
NO local data — its entire serving state is a mounted manifest snapshot
over the shared object store (persist/objectstore.py make_query_tier)
plus a cold cache.  The coordinator scatter-gathers cold leaves here
via the ordinary cross-node transport; decoded leaves rebind to the
object-store tier through the per-process query-tier registry, so
adding one of these processes adds cold read capacity with zero data
movement — the elastic-read property the stage gates on.

Run: python bench/coldnode.py --name q1 --port 7071 \
         --objstore /tmp/shared --dataset coldbench --num-shards 4
Prints one JSON line {"ready": true, ...} once serving.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

# REPLACE the script-dir path entry (bench/) with the repo root: bench/
# contains a platform.py that would shadow the stdlib module jax needs
sys.path[0] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv=None) -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--name", required=True)
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--objstore", required=True,
                    help="shared object-store root (LocalObjectStore)")
    ap.add_argument("--dataset", default="coldbench")
    ap.add_argument("--num-shards", type=int, default=4)
    ap.add_argument("--platform", default="cpu",
                    help="pin jax platform ('' keeps the default)")
    args = ap.parse_args(argv)

    if args.platform:
        import jax
        jax.config.update("jax_platforms", args.platform)

    from filodb_tpu.core.memstore import TimeSeriesMemStore
    from filodb_tpu.parallel.transport import NodeQueryServer
    from filodb_tpu.persist.objectstore import (LocalObjectStore,
                                                make_query_tier)
    from filodb_tpu.utils import metrics as _metrics

    _metrics.NODE_NAME = args.name
    store = LocalObjectStore(args.objstore, name=args.name)
    # mounts the manifests and registers the tier for the dataset: every
    # cold leaf dispatched here pages the SHARED tier, nothing local
    tier, remote = make_query_tier(store, args.dataset, args.num_shards)
    ms = TimeSeriesMemStore()            # empty: query-only by contract
    srv = NodeQueryServer(ms, port=args.port).start()
    print(json.dumps({"ready": True, "name": args.name,
                      "port": srv.address[1],
                      "manifest_entries":
                          sum(len(remote.list(args.dataset, s))
                              for s in range(args.num_shards))}),
          flush=True)
    # serve-only until the bench kills us
    while True:
        time.sleep(1.0)


if __name__ == "__main__":
    main()
