#!/usr/bin/env python
"""On-chip evidence beyond the staged headline bench (round 4).

Captures, while a TPU tunnel window is live, the three measurements the
round-3 verdict asked for that the headline ladder doesn't cover:

  1. ragged_rate: the r4 ragged (NaN-holed counters + restarts) rate
     family on the fused one-pass kernel at production scale, vs the
     general XLA path, with an f64 scalar-oracle spot check — proof the
     "production-shaped data falls off the fused cliff" weakness is gone
     ON CHIP, not just under CPU interpret mode.
  2. shardmap_fused: the fused kernel composed inside jax.shard_map on
     real hardware (1-device mesh) vs the direct call — round-3 verdict
     weak #4: "the distributed-fused configuration has never been shown
     faster anywhere" (CPU interpret mode made it look 7.8x slower).
  3. hbm_peak / mxu_peak: measured achievable HBM copy bandwidth and
     bf16/f32 matmul throughput on this chip, so doc/kernels.md can quote
     the fused kernel's achieved GB/s and model TFLOP/s against a
     *measured* roofline instead of datasheet model numbers.

Every section persists incrementally to TPU_EXTRA_r04.json so a tunnel
death mid-run still leaves the finished sections behind.

Usage: python tools/tpu_extra.py   (refuses to run on a non-TPU backend)
"""
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(REPO, ".jax_cache"))
OUT = os.path.join(REPO, "TPU_EXTRA_r04.json")

DOC = {"utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())}


def persist():
    tmp = OUT + ".tmp"
    with open(tmp, "w") as f:
        json.dump(DOC, f, indent=1)
    os.replace(tmp, OUT)


def p50(fn, iters=10):
    lat = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        lat.append(time.perf_counter() - t0)
    return float(np.median(np.asarray(lat)))


def mk_ragged_counters(S, T, hole_frac=0.10, reset_frac=0.02, seed=7,
                       step_ms=10_000):
    """Production-shaped counters at scale: NaN scrape gaps + restarts
    (vectorized — the per-series loop in tests/test_pallas_fused.py is
    fine at 64 series, not at 262k)."""
    rng = np.random.default_rng(seed)
    ts_row = np.arange(T, dtype=np.int64) * step_ms
    inc = rng.exponential(10.0, size=(S, T))
    # restarts: at reset points the counter restarts from a small value —
    # inject by subtracting the running value (vectorized via segment
    # cumsum trick: cumsum of increments, minus cumsum frozen at resets)
    raw = np.cumsum(inc, axis=1)
    resets = rng.random((S, T)) < reset_frac
    resets[:, :2] = False
    # value carried away at each reset = raw just before it
    carried = np.where(resets, np.roll(raw, 1, axis=1), 0.0)
    raw = raw - np.maximum.accumulate(
        np.where(resets, carried, 0.0), axis=1)
    raw = np.maximum(raw, 0.0)
    raw[rng.random((S, T)) < hole_frac] = np.nan
    return ts_row, raw


def section_ragged(jax, jnp):
    from filodb_tpu.ops import pallas_fused as pf
    from filodb_tpu.ops.counter import rebase_values
    from filodb_tpu.ops.rangefns import evaluate_range_function
    from filodb_tpu.ops import agg as agg_ops
    from filodb_tpu.ops.timewindow import make_window_ends, to_offsets

    S, T, G = 262_144, 720, 1000
    range_ms, step_ms = 300_000, 60_000
    sec = {"series": S, "samples_per_series": T, "groups": G,
           "hole_frac": 0.10, "reset_frac": 0.02}
    DOC["ragged_rate_262k"] = sec
    # datagen vs production prep, split (round-5 verdict item 10b: the r4
    # artifact's single host_prep_s=153.5 read as a production prep cost;
    # it was overwhelmingly synthetic data GENERATION, which a live store
    # never pays — the production-side prep is the f64 reset-correction +
    # rebase the mirror pays once per working-set refresh)
    t0 = time.perf_counter()
    ts_row, raw = mk_ragged_counters(S, T)
    datagen_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    reb, vbase = rebase_values(raw, True)
    vals32 = reb.astype(np.float32)
    vbase32 = vbase.astype(np.float32)
    prep_s = time.perf_counter() - t0
    gids = (np.arange(S) % G).astype(np.int32)
    wends = make_window_ends(600_000, int(ts_row[-1]), step_ms)
    W = len(wends)
    span = S * int(np.searchsorted(ts_row, int(ts_row[-1]), side="right")
                   - np.searchsorted(ts_row, 600_000 - range_ms))
    sec.update({"windows": W, "samples_scanned_per_query": span,
                "synthetic_datagen_s": round(datagen_s, 2),
                "production_prep_s": round(prep_s, 2),
                "host_prep_s": round(datagen_s + prep_s, 2)})
    persist()

    ts_one = to_offsets(ts_row[None, :], np.full(1, T), 0)
    dev = {k: jax.device_put(v) for k, v in
           (("ts", ts_one), ("vals", vals32), ("vb", vbase32),
            ("g", gids), ("w", wends.astype(np.int32)))}

    @jax.jit
    def general(ts, v, vb, g, w):
        res = evaluate_range_function(ts, v, w, range_ms, "rate",
                                      shared_grid=True, vbase=vb,
                                      precorrected=True, dense=False)
        return agg_ops.aggregate("sum", res, g, G)

    t0 = time.perf_counter()
    xla_res = np.asarray(general(dev["ts"], dev["vals"], dev["vb"],
                                 dev["g"], dev["w"]))
    sec["xla_compile_s"] = round(time.perf_counter() - t0, 2)
    g50 = p50(lambda: np.asarray(general(dev["ts"], dev["vals"],
                                         dev["vb"], dev["g"], dev["w"])))
    sec.update({"xla_p50_s": round(g50, 5),
                "xla_samples_per_sec": round(span / g50, 1)})
    persist()

    plan = pf.build_plan(ts_row, np.asarray(wends, np.int64), range_ms)
    prep = pf.pad_inputs(dev["vals"], vbase32, gids, plan, G)

    def fused():
        sums, counts = pf.fused_rate_groupsum(
            None, None, None, plan, G, "rate", True, prepared=prep,
            ragged=True)
        return pf.present_sum(sums, counts)

    t0 = time.perf_counter()
    got = fused()
    sec["pallas_compile_s"] = round(time.perf_counter() - t0, 2)
    f50 = p50(fused)
    sec.update({"pallas_p50_s": round(f50, 5),
                "pallas_samples_per_sec": round(span / f50, 1),
                "pallas_speedup_vs_general": round(g50 / f50, 2)})
    # on-chip cross-check: fused vs general XLA over the full shape
    same_nan = bool((np.isnan(got) == np.isnan(xla_res)).all())
    err = float(np.nanmax(np.abs(got - xla_res)
                          / np.maximum(np.abs(xla_res), 1e-6)))
    sec["pallas_max_rel_err_vs_xla"] = round(err, 9) if same_nan else "inf"
    # f64 scalar-oracle spot check: 96 random series as singleton groups
    from oracle import eval_series
    rng = np.random.default_rng(3)
    idx = rng.choice(S, size=96, replace=False)
    sub32 = vals32[idx]
    subvb = vbase32[idx]
    subg = np.arange(96, dtype=np.int32)
    sums, counts = pf.fused_rate_groupsum(
        sub32, subvb, subg, plan, 96, "rate", True, ragged=True)
    got_sub = pf.present_sum(sums, counts)
    want = np.stack([eval_series(ts_row, raw[i], wends, range_ms, "rate")
                     for i in idx])
    ok_nan = bool((np.isnan(got_sub) == np.isnan(want)).all())
    oerr = float(np.nanmax(np.abs(got_sub - want)
                           / np.maximum(np.abs(want), 1e-6)))
    sec["oracle_series_checked"] = 96
    sec["pallas_max_rel_err_vs_f64_oracle"] = (round(oerr, 9) if ok_nan
                                               else "inf")
    sec["conformance_ok"] = bool(same_nan and err < 1e-3
                                 and ok_nan and oerr < 1e-3)
    persist()


def section_shardmap(jax, jnp):
    from jax.sharding import Mesh
    from filodb_tpu.ops import pallas_fused as pf
    from filodb_tpu.ops.timewindow import make_window_ends
    from filodb_tpu.parallel import mesh as fmesh

    S, T, G = 262_144, 720, 1000
    range_ms, step_ms = 300_000, 60_000
    sec = {"series": S, "mesh": "1 shard x 1 time (single real chip)"}
    DOC["shardmap_fused_262k"] = sec
    rng = np.random.default_rng(5)
    ts_row = np.arange(T, dtype=np.int64) * 10_000
    vals32 = np.cumsum(rng.exponential(10.0, size=(S, T)),
                       axis=1).astype(np.float32)
    vb = vals32[:, 0].copy()
    vals32 -= vb[:, None]
    gids = (np.arange(S) % G).astype(np.int32)
    wends = make_window_ends(600_000, int(ts_row[-1]), step_ms)
    W = len(wends)
    span = S * T
    plan = pf.build_plan(ts_row, np.asarray(wends, np.int64), range_ms)
    prep = pf.pad_inputs(vals32, vb, gids, plan, G)

    def direct():
        sums, counts = pf.fused_rate_groupsum(
            None, None, None, plan, G, "rate", True, prepared=prep)
        return pf.present_sum(sums, counts)

    want = direct()
    d50 = p50(direct)
    sec.update({"direct_p50_s": round(d50, 5),
                "direct_samples_per_sec": round(span / d50, 1)})
    persist()

    mesh = Mesh(np.asarray(jax.devices()[:1]).reshape(1, 1),
                ("shard", "time"))
    dv = jax.device_put(vals32[None])          # [1, S, T]
    dg = jax.device_put(gids[None])
    dvb = jax.device_put(vb[None])
    mats = tuple(jax.device_put(getattr(plan, a)[None]) for a in
                 ("o1", "o2", "l1", "l2", "t1", "t2", "n",
                  "wstart_x", "wend_x", "tsrow", "idx1", "idx2"))

    def via_shardmap():
        out = fmesh._mesh_fused_call(
            mesh, dv, dg[..., None], dvb, *mats, G=G, S=S, T=T, Tp=plan.Tp,
            is_counter=True, is_rate=True, interpret=False)
        counts = prep.gsize[:, None].astype(np.float64) * \
            plan.wvalid[None, :].astype(np.float64)
        s = np.asarray(out, np.float64)[:G, :plan.W]
        return np.where(counts > 0, s, np.nan)

    t0 = time.perf_counter()
    got = via_shardmap()
    sec["shardmap_compile_s"] = round(time.perf_counter() - t0, 2)
    m50 = p50(via_shardmap)
    err = float(np.nanmax(np.abs(got - want)
                          / np.maximum(np.abs(want), 1e-6)))
    sec.update({
        "shardmap_p50_s": round(m50, 5),
        "shardmap_samples_per_sec": round(span / m50, 1),
        "shardmap_overhead_vs_direct": round(m50 / d50, 3),
        "max_rel_err_vs_direct": round(err, 9),
        "note": ("fused-in-shard_map is the LEGACY A/B probe: on the "
                 "real 8-device mesh it inverted the single-chip win "
                 "~30x (MULTICHIP_r05, warm 25.3s vs 0.88s general); "
                 "production routes per-device dispatch + partial-only "
                 "merges instead (doc/multichip.md, bench.py multichip)"),
    })
    persist()


def section_roofline(jax, jnp):
    """Dispatch through the tunnel costs ~75ms per call, so single-op
    timings measure the tunnel, not the chip (the first cut of this
    section reported 6.9 GB/s / 1.8 TFLOP/s — all three microbenches hit
    the same ~76ms wall).  Chain K dependent iterations inside ONE jit
    via lax.fori_loop so device work dominates the call."""
    from jax import lax
    sec = {}
    DOC["roofline"] = sec
    n = 256 * 1024 * 1024 // 4                 # 256 MiB f32
    K = 64
    x = jax.device_put(np.ones(n, np.float32))

    @jax.jit
    def copy_k(a):
        return lax.fori_loop(0, K, lambda i, y: y * np.float32(1.0000001),
                             a)

    np.asarray(copy_k(x)[:1])
    c50 = p50(lambda: copy_k(x).block_until_ready(), iters=10)
    sec["hbm_copy_gb_s"] = round(K * 2 * n * 4 / c50 / 1e9, 1)
    sec["hbm_copy_note"] = (f"{K} dependent read+write passes over 256 MiB "
                            "in one jit; per-call tunnel latency amortized")
    persist()

    # bf16 = the MXU's native pass; f32_highest = the multi-pass f32
    # decomposition the fused kernel actually runs (Precision.HIGHEST).
    # Plain f32 jnp.dot at default precision lowers to the bf16 pass on
    # TPU, so timing it would mislabel bf16 throughput as f32.
    for dt, prec, name in (
            (jnp.bfloat16, jax.lax.Precision.DEFAULT, "bf16"),
            (jnp.float32, jax.lax.Precision.HIGHEST, "f32_highest")):
        k = 4096
        rng = np.random.default_rng(0)
        a = jax.device_put(
            (rng.standard_normal((k, k)) / np.sqrt(k)).astype(dt))

        @jax.jit
        def mm_k(p):
            return lax.fori_loop(
                0, K, lambda i, z: jnp.dot(z, p, precision=prec), p)

        np.asarray(mm_k(a)[:1], np.float32)
        m50 = p50(lambda: mm_k(a).block_until_ready(), iters=10)
        sec[f"mxu_{name}_tflops_per_s"] = round(
            K * 2 * k**3 / m50 / 1e12, 1)
        persist()


def main():
    import jax
    import jax.numpy as jnp
    plat = jax.devices()[0].platform
    DOC["platform"] = plat
    DOC["device"] = str(jax.devices()[0])
    if plat not in ("tpu",):
        print(f"not a TPU backend ({plat}); refusing", file=sys.stderr)
        return 2
    # merge previously-captured sections so a selective rerun keeps them
    if os.path.exists(OUT):
        try:
            with open(OUT) as f:
                prior = json.load(f)
            for k, v in prior.items():
                DOC.setdefault(k, v)
        except Exception:  # noqa: BLE001
            pass
    persist()
    sections = (("roofline", section_roofline),
                ("ragged", section_ragged),
                ("shardmap", section_shardmap))
    want = set(sys.argv[1:])
    known = {name for name, _ in sections}
    if want - known:
        print(f"unknown section(s) {sorted(want - known)}; "
              f"valid: {sorted(known)}", file=sys.stderr)
        return 2
    for name, fn in sections:
        if want and name not in want:
            continue
        DOC.pop(f"{name}_error", None)
        try:
            t0 = time.perf_counter()
            fn(jax, jnp)
            print(f"{name}: ok in {time.perf_counter() - t0:.1f}s",
                  flush=True)
        except Exception as e:  # noqa: BLE001 — keep later sections alive
            DOC[f"{name}_error"] = f"{type(e).__name__}: {e}"[:400]
            persist()
            print(f"{name}: FAILED {e}", flush=True)
    DOC["done"] = True
    persist()
    return 0


if __name__ == "__main__":
    sys.exit(main())
