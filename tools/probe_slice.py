"""Mosaic probe: can the TPU Pallas kernel do STATIC lane-strided slices?

The fused kernel's boundary gathers (v @ o1 one-hot matmuls) are pure
column selections at host-static positions f0 + w*stride whenever the
window geometry is uniform (every Prometheus query_range).  If Mosaic
lowers `x[:, f0:stop:stride]` on the lane dim, the gathers cost ~nothing
instead of 6-pass HIGHEST matmuls.  This probe compiles three candidate
gather strategies on a [256, 768] block and times K-chained runs:

  a) lane_strided:  y = x[:, f0::stride]           (direct lane slice)
  b) transpose:     y = x.T[f0::stride, :].T       (sublane slice path)
  c) matmul:        y = x @ onehot                 (the current kernel's)

Run on the tunneled chip; prints one JSON line per strategy.
"""
import functools
import json
import sys

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

BS, TP, WP = 256, 768, 128
F0, STRIDE, W = 5, 6, 110
GRID = 1024        # series blocks per call (262k series equivalent)


def _pad_w(y):
    return jnp.concatenate(
        [y, jnp.zeros((y.shape[0], WP - y.shape[1]), jnp.float32)], axis=1)


IDX = np.zeros(TP, np.int32)
IDX[:W] = F0 + STRIDE * np.arange(W, dtype=np.int32)


def k_dyngather(x_ref, o_ref, i1_ref, i2_ref, y_ref):
    x = x_ref[:]
    idx = jnp.broadcast_to(i1_ref[:], x.shape)
    g = jnp.take_along_axis(x, idx, axis=1, mode="promise_in_bounds")
    y_ref[:] = g[:, :WP] * _pad_w(jnp.ones((x.shape[0], W), jnp.float32))


def k_two_gathers(x_ref, o_ref, i1_ref, i2_ref, y_ref):
    """Dense-rate shape: two gathers (v1, v2) + elementwise, one output."""
    x = x_ref[:]
    idx1 = jnp.broadcast_to(i1_ref[:], x.shape)
    idx2 = jnp.broadcast_to(i2_ref[:], x.shape)
    v1 = jnp.take_along_axis(x, idx1, axis=1, mode="promise_in_bounds")
    v2 = jnp.take_along_axis(x, idx2, axis=1, mode="promise_in_bounds")
    mask = _pad_w(jnp.ones((x.shape[0], W), jnp.float32))
    y_ref[:] = (v2[:, :WP] - v1[:, :WP]) * mask


def _tiled_gather(x, idx_row):
    """Gather x[s, idx[w]] as W columns via per-128-lane-tile dynamic
    gathers (dynamic_gather across vreg boundaries fails to compile):
    out[:, w] = x[:, idx[w]] for w < WP, where idx rides a [1, WP] row."""
    bs = x.shape[0]
    out = jnp.zeros((bs, WP), jnp.float32)
    idx = jnp.broadcast_to(idx_row, (bs, WP))
    for k in range(TP // 128):
        tile = x[:, 128 * k:128 * (k + 1)]
        local = jnp.clip(idx - 128 * k, 0, 127)
        g = jnp.take_along_axis(tile, local, axis=1,
                                mode="promise_in_bounds")
        out = jnp.where((idx >= 128 * k) & (idx < 128 * (k + 1)), g, out)
    return out


def k_tiled_gather(x_ref, o_ref, i1_ref, i2_ref, y_ref):
    x = x_ref[:]
    mask = _pad_w(jnp.ones((x.shape[0], W), jnp.float32))
    y_ref[:] = _tiled_gather(x, i1_ref[:, :WP]) * mask


def k_tiled_two(x_ref, o_ref, i1_ref, i2_ref, y_ref):
    x = x_ref[:]
    mask = _pad_w(jnp.ones((x.shape[0], W), jnp.float32))
    v1 = _tiled_gather(x, i1_ref[:, :WP])
    v2 = _tiled_gather(x, i2_ref[:, :WP])
    y_ref[:] = (v2 - v1) * mask


def k_matmul(x_ref, o_ref, i1_ref, i2_ref, y_ref):
    y_ref[:] = jnp.dot(x_ref[:], o_ref[:],
                       preferred_element_type=jnp.float32,
                       precision=lax.Precision.HIGHEST)


def run(kern, x, o, i1, i2, interpret=False):
    from jax.experimental.pallas import tpu as pltpu
    space = {} if interpret else {"memory_space": pltpu.VMEM}
    return pl.pallas_call(
        kern, grid=(GRID,),
        in_specs=[pl.BlockSpec((BS, TP), lambda i: (i, 0), **space),
                  pl.BlockSpec((TP, WP), lambda i: (0, 0), **space),
                  pl.BlockSpec((1, TP), lambda i: (0, 0), **space),
                  pl.BlockSpec((1, TP), lambda i: (0, 0), **space)],
        out_specs=pl.BlockSpec((BS, WP), lambda i: (i, 0), **space),
        out_shape=jax.ShapeDtypeStruct((GRID * BS, WP), jnp.float32),
        interpret=interpret)(x, o, i1, i2)


def main():
    interpret = jax.devices()[0].platform == "cpu"
    rng = np.random.default_rng(0)
    x = jax.device_put(rng.standard_normal((GRID * BS, TP)).astype(np.float32))
    onehot = np.zeros((TP, WP), np.float32)
    for w in range(W):
        onehot[F0 + STRIDE * w, w] = 1.0
    o = jax.device_put(onehot)
    i1 = jax.device_put(IDX[None, :])
    i2 = jax.device_put((IDX + (STRIDE - 1))[None, :])
    xh = np.asarray(x)
    gather1 = xh @ onehot
    onehot2 = np.zeros((TP, WP), np.float32)
    for w in range(W):
        onehot2[F0 + STRIDE * w + STRIDE - 1, w] = 1.0
    wants = {"dyngather": gather1, "matmul": gather1,
             "tiled_gather": gather1,
             "two_gathers": xh @ onehot2 - gather1,
             "tiled_two": xh @ onehot2 - gather1}

    import time
    KS = (2, 16)
    for name, kern in (("tiled_gather", k_tiled_gather),
                       ("tiled_two", k_tiled_two),
                       ("matmul", k_matmul)):
        rec = {"strategy": name}
        try:
            fn = functools.partial(run, kern, interpret=interpret)
            got = np.asarray(fn(x, o, i1, i2))
            rec["max_abs_err"] = float(np.abs(got - wants[name]).max())
            p50s = {}
            for K in KS:
                @jax.jit
                def chain(x0, o0, K=K):
                    def body(i, acc):
                        y = fn(x0 + acc * 1e-30, o0, i1, i2)
                        return acc + y[0, 0] * 1e-30
                    return lax.fori_loop(0, K, body, jnp.float32(0.0))

                t0 = time.perf_counter()
                chain(x, o).block_until_ready()
                rec[f"k{K}_compile_s"] = round(time.perf_counter() - t0, 2)
                lat = []
                for _ in range(7):
                    t0 = time.perf_counter()
                    chain(x, o).block_until_ready()
                    lat.append(time.perf_counter() - t0)
                p50s[K] = float(np.median(lat))
                rec[f"k{K}_p50_s"] = round(p50s[K], 5)
            slope = (p50s[KS[1]] - p50s[KS[0]]) / (KS[1] - KS[0])
            rec["device_ms_per_call"] = round(slope * 1e3, 3)
            rec["intercept_ms"] = round(
                (p50s[KS[0]] - slope * KS[0]) * 1e3, 1)
        except Exception as e:  # noqa: BLE001 — probe failure is the result
            rec["error"] = f"{type(e).__name__}: {str(e)[:200]}"
        print(json.dumps(rec))
        sys.stdout.flush()


if __name__ == "__main__":
    main()
