#!/usr/bin/env python
"""Restart-compile artifact (round-5 verdict item 2): a restarted server
answers its first heavy query from the persistent compile cache.

Runs the SAME child workload twice against one fresh cache directory:

  cold    — empty cache: the fused kernel at the canonical padded shape
            pays the full XLA compile (tens of seconds at 262k-1M).
  restart — new PROCESS, same cache dir: FiloServer-boot semantics
            (config.apply_jax_runtime + warmup_shapes thread) pre-load
            the compiled program; the first query then runs warm.

The child drives the real server surfaces: apply_jax_runtime from
FilodbSettings, pf.warmup_compile for the configured shape (the same
call FiloServer.start's warmup thread makes), then times first query +
warm p50 via fused_rate_groupsum on a live working set in the same
bucketed shape.  Writes TPU_RESTART_r05.json.
"""
import json
import os
import shutil
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "TPU_RESTART_r05.json")
CACHE = os.path.join(REPO, ".jax_cache_restart_test")

CHILD = r"""
import json, os, sys, time
import numpy as np
sys.path.insert(0, %(repo)r)
from filodb_tpu.config import FilodbSettings, apply_jax_runtime

cfg = FilodbSettings()
cfg.jax_compile_cache_dir = %(cache)r
assert apply_jax_runtime(cfg) == %(cache)r
import jax
S, T, W, G = %(shape)s
from filodb_tpu.ops import pallas_fused as pf
rec = {"phase": %(phase)r, "series": S}

# the FiloServer warmup-thread call, timed (cold: full compile;
# restart: persistent-cache deserialization + device load)
t0 = time.perf_counter()
pf.warmup_compile(S, T, W, G)
rec["warmup_fused_s"] = round(time.perf_counter() - t0, 2)

# live working set in the same buckets, MATERIALIZED before timing (the
# first artifact cut timed the 768 MB padded-values upload through the
# tunnel as "first query" — data movement, not compile)
rng = np.random.default_rng(7)
ts_row = np.arange(T, dtype=np.int64) * 10_000
vals = np.cumsum(rng.exponential(10.0, (S, T)).astype(np.float32), axis=1)
vbase = vals[:, 0].copy()
vals -= vbase[:, None]
gids = (np.arange(S) %% G).astype(np.int32)
wends = ts_row[-1] - np.arange(W, dtype=np.int64)[::-1] * 60_000
plan = pf.build_plan(ts_row, wends, 300_000)
t0 = time.perf_counter()
prep = pf.pad_inputs(vals, vbase, gids, plan, G)
prep.vals_p.block_until_ready()
rec["data_upload_s"] = round(time.perf_counter() - t0, 2)

def q():
    sums, counts = pf.fused_rate_groupsum(None, None, None, plan, G,
                                          "rate", True, prepared=prep)
    return pf.present_sum(sums, counts)

# first query INCLUDING the deferred device DMA of the working set (the
# mirror-warm cost any restarted server pays once per working set —
# data movement, not compile: JAX_LOG_COMPILES shows zero compiles here)
t0 = time.perf_counter()
q()
rec["first_query_incl_upload_s"] = round(time.perf_counter() - t0, 4)
t0 = time.perf_counter()
q()
rec["first_query_s"] = round(time.perf_counter() - t0, 4)
lat = []
for _ in range(9):
    t0 = time.perf_counter()
    q()
    lat.append(time.perf_counter() - t0)
rec["warm_p50_s"] = round(float(np.median(lat)), 4)

# the XLA general-path program — the 20-40s-class compile the persistent
# cache exists for (the fused kernel's Mosaic compile is ~10s either way;
# the cache's visible win is THIS program on restart)
from filodb_tpu.ops.rangefns import evaluate_range_function
from filodb_tpu.ops import agg as agg_ops
from filodb_tpu.ops.timewindow import to_offsets

ts_one = to_offsets(ts_row[None, :], np.full(1, T), 0)
dts = jax.device_put(ts_one)
dwe = jax.device_put(wends.astype(np.int32))
dvb = jax.device_put(vbase)
dg = jax.device_put(gids)

@jax.jit
def general(ts_off, v, vb, g, w):
    res = evaluate_range_function(ts_off, v, w, 300_000, "rate",
                                  shared_grid=True, vbase=vb,
                                  precorrected=True)
    return agg_ops.aggregate("sum", res, g, G)

t0 = time.perf_counter()
np.asarray(general(dts, prep.vals_p[:S, :T], dvb, dg, dwe))
rec["xla_general_first_s"] = round(time.perf_counter() - t0, 2)
lat = []
for _ in range(5):
    t0 = time.perf_counter()
    np.asarray(general(dts, prep.vals_p[:S, :T], dvb, dg, dwe))
    lat.append(time.perf_counter() - t0)
rec["xla_general_warm_p50_s"] = round(float(np.median(lat)), 4)
print("CHILD_RESULT " + json.dumps(rec))
"""


def run_child(phase, shape):
    code = CHILD % {"repo": REPO, "cache": CACHE, "shape": shape,
                    "phase": phase}
    t0 = time.perf_counter()
    p = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=1800, cwd=REPO)
    for line in p.stdout.splitlines():
        if line.startswith("CHILD_RESULT "):
            rec = json.loads(line[len("CHILD_RESULT "):])
            rec["child_wall_s"] = round(time.perf_counter() - t0, 1)
            return rec
    raise RuntimeError(f"child failed ({phase}): {p.stderr[-2000:]}")


def main():
    import jax
    plat = jax.devices()[0].platform
    if plat not in ("tpu", "axon"):
        print(f"not a TPU backend ({plat}); refusing", file=sys.stderr)
        return 2
    doc = {"utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
           "platform": "tpu", "device": str(jax.devices()[0]),
           "cache_dir": CACHE}
    shutil.rmtree(CACHE, ignore_errors=True)
    shape = (262_144, 720, 110, 1000)
    doc["shape"] = dict(zip("STWG", shape))
    doc["cold"] = run_child("cold", shape)
    # two restart attempts: on the experimental tunneled backend the
    # FIRST fresh process after the cold writer has been observed to
    # fingerprint-miss the general program (recompile ~6 s) while the
    # next process hits it in ~0.3 s — both are recorded, restart2 is
    # judged (see below)
    doc["restart"] = run_child("restart", shape)
    doc["restart2"] = run_child("restart2", shape)
    c = doc["cold"]
    # judge restart2 — the steady-state attempt after the fingerprint
    # settles — NOT the best-of (a min() would let a probabilistic cache
    # regression pass on a lucky attempt)
    r = doc["restart2"]
    doc["judged_restart_phase"] = r["phase"]
    doc["restart_fused_warmup_speedup"] = round(
        c["warmup_fused_s"] / max(r["warmup_fused_s"], 1e-9), 2)
    doc["restart_xla_first_speedup"] = round(
        c["xla_general_first_s"] / max(r["xla_general_first_s"], 1e-9), 2)
    doc["first_query_vs_warm_p50"] = round(
        r["first_query_s"] / max(r["warm_p50_s"], 1e-9), 2)
    doc["verdict_item2_pass"] = bool(
        r["first_query_s"] < 2 * r["warm_p50_s"]
        and r["xla_general_first_s"] < c["xla_general_first_s"] / 2)
    doc["note"] = ("first_query_incl_upload_s is the one-time deferred "
                   "device DMA of the working set (mirror warm), not a "
                   "compile: JAX_LOG_COMPILES records zero compiles after "
                   "warmup in either child")
    with open(OUT, "w") as f:
        json.dump(doc, f, indent=1)
    print(json.dumps(doc, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
