"""Round-over-round bench trend collection -> BENCH_TREND.json.

The driver captures each round's one-line bench JSON inside a
``BENCH_rNN.json`` artifact (shape ``{"n": round, "rc": .., "tail": ..}``
— the line is the last JSON object in the tail).  This tool folds those
artifacts into ``BENCH_TREND.json``'s ``rounds`` list so the trajectory
of every headline metric is greppable in one file:

  - the scan headline (``headline_samples_per_sec`` + p50/kernel/series)
  - the ingest number (``ingest_samples_per_sec``, PR 1)
  - the serving numbers (``concurrent_qps`` / ``cached_repoll_p50_s``,
    PR 2; ``span_overhead_pct``, PR 3; ``ruler_*``, PR 5)
  - the multi-chip fused-scan numbers (``multichip_fused_warm_s`` /
    ``multichip_general_warm_s`` / ``multichip_scaling_x`` /
    ``multichip_inversion_gone``, PR 6) — including a LOUD
    ``multichip_error`` when a box that claims TPU exposed < 2 devices
    (the bench stage fails rather than skips; the trend must show it).
  - the durability numbers (PR 7): ``remote_write_samples_per_sec``,
    ``wal_overhead_pct`` / ``wal_on_vs_off_pct`` (gate: WAL-on >= 50%
    of WAL-off), ``wal_replay_samples_per_sec``, and the kill-chaos
    proof ``wal_kill_acked_lost`` (gate: 0) /
    ``wal_kill_query_identical`` — plus a loud ``wal_error``.
  - the historical-tier numbers (PR 8):
    ``longrange_cold_scan_samples_per_sec`` (gate: >= 1/10 of the
    in-memory first-scan number), ``longrange_warm_cold_ratio``
    (gate: >= 0.5), ``longrange_stitch_identical`` (gate: true — the
    raw+downsample+persisted stitch is bit-identical to a single-tier
    store), ``longrange_lru_bounded`` (the cold region never exceeded
    its byte budget) — plus a loud ``longrange_error`` when the stage
    fails (merge-not-clobber like every other key).
  - the self-observability numbers (PR 10): ``selfmon_overhead_pct``
    (gate: <= 2% at the default ``selfmon.interval_s``),
    ``selfmon_scrape_p50_s`` / ``selfmon_scrape_series``, and a loud
    ``selfmon_error`` when the stage fails.
  - the write-path tracing numbers (PR 12):
    ``ingest_trace_overhead_pct`` (gate: tracing-on >= 98% of
    tracing-off on the remote_write door),
    ``ingest_trace_stitched`` (gate: ONE 2-node trace covering door ->
    WAL -> fsync wait -> fan-out -> replica WAL -> memstore ingest),
    ``ingesttrace_fault_visible`` (an injected wal.fsync delay surfaces
    in the fsync histogram + ingest slowlog + freshness histograms +
    health), ``ingest_freshness_p99_s`` — plus a loud
    ``ingesttrace_error``.
  - the live-introspection numbers (PR 13):
    ``activequeries_overhead_pct`` (gate: registry tax <= 2% of
    concurrent QPS), ``activequeries_kill_structured`` /
    ``activequeries_slot_freed`` / ``activequeries_listed_remote`` /
    ``activequeries_stop_ms`` (gate: <= 250 ms) from the two-node
    cold-query kill drill — plus a loud ``activequeries_error``.
  - the multi-tenant QoS numbers (PR 14): ``qos_p99_ratio`` (gate:
    good-tenant p99 under one abusive tenant's full-concurrency flood
    stays <= 1.5x of idle), ``qos_abuser_shed`` /
    ``qos_shed_retry_after_ok`` (the abuser gets structured 429 +
    Retry-After), ``qos_abuser_timeouts`` (gate: 0 — doomed queries
    shed at admission, never left to die in the queue) — plus a loud
    ``qos_error`` when the stage fails.
  - the distributed-execution numbers (PR 15):
    ``distexec_wire_bytes_ratio`` (gate: a 4-node fan-out
    ``sum by (...)`` moves >= 10x fewer wire bytes pushed vs the
    ship-everything baseline, results BIT-identical),
    ``distexec_frontend_peak_rss_mb`` vs ``distexec_rss_budget_mb``
    (gate: the streamed long-range aggregation holds traced peak
    memory under a fixed budget the materialize-everything baseline
    exceeds), ``distexec_pushdown_speedup_x`` — plus a loud
    ``distexec_error`` when the stage fails.
  - the whole-expression compilation numbers (PR 17):
    ``exprfuse_speedup_x`` (gate: the 8-panel mixed dashboard —
    aggregated rates, a ratio and a comparison binary op, increase,
    topk — compiled as ONE fused batch runs >= 5x faster than
    per-node assembly, results BIT-identical per
    ``exprfuse_identical``), ``exprfuse_fused`` / ``exprfuse_degraded``
    verdict counts (gate: 0 degraded on the eligible mix) and
    ``exprfuse_memo_hits`` (the shared per-shard gather memo doing the
    work) — plus a loud ``exprfuse_error`` when the stage fails.
  - the disaggregated cold-tier numbers (PR 19):
    ``objectstore_drill_identical`` (gate: wipe the entire store root,
    rebuild from the shared object store + WAL tail, query_range
    byte-identical) with ``objectstore_drill_availability`` (gate: 1.0
    — stateless readers keep the historical range answerable while the
    node is down), ``objectstore_elastic_qps_ratio`` (gate: >= 1.8x
    with 2 query-only node processes on >= 3-core hosts; no-collapse +
    bit-identity on smaller ones), and the dead-store degrade proof
    ``objectstore_deadstore_partial_flagged`` /
    ``objectstore_deadstore_strict_error`` (flagged partial in bounded
    time, typed error when strict) — plus a loud ``objectstore_error``
    when the stage fails.

Existing hand-written round entries are MERGED, never clobbered: only
missing keys are added, so curated notes survive re-runs.

Usage:
    python tools/trend.py            # print the merged trend to stdout
    python tools/trend.py --write    # update BENCH_TREND.json in place
"""
import argparse
import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# one-line keys carried into the trend, in display order
CARRY = [
    "platform", "value", "p50_query_latency_s", "kernel", "series",
    "headline_stage", "vs_baseline",
    "ingest_samples_per_sec",
    "concurrent_qps", "cached_repoll_p50_s", "qps_vs_sequential",
    "span_overhead_pct",
    "ruler_eval_p50_s", "recorded_query_speedup_x", "ruler_overhead_pct",
    "multichip_fused_warm_s", "multichip_general_warm_s",
    "multichip_scaling_x", "multichip_inversion_gone",
    "multichip_fused_route", "multichip_pack_memo_hits",
    "multichip_error",
    "remote_write_samples_per_sec", "wal_overhead_pct",
    "wal_on_vs_off_pct", "wal_on_samples_per_sec",
    "wal_replay_samples_per_sec", "wal_kill_acked_lost",
    "wal_kill_query_identical", "wal_error",
    "longrange_cold_scan_samples_per_sec", "longrange_warm_cold_ratio",
    "longrange_stitch_identical", "longrange_cold_vs_mem_ratio",
    "longrange_lru_bounded", "longrange_gate_ok", "longrange_error",
    "selfmon_overhead_pct", "selfmon_scrape_p50_s",
    "selfmon_scrape_series", "selfmon_gate_ok", "selfmon_error",
    # replication layer (ISSUE 11): RF-2 fan-out throughput, catch-up
    # drain, live-handoff drill, and the FLIPPED chaos gates
    # (availability 1.0 / zero partials / zero acked loss at RF-2)
    "replication_rf1_samples_per_sec", "replication_rf2_samples_per_sec",
    "replication_rf2_vs_rf1_pct", "replication_catchup_samples_per_sec",
    "replication_handoff_failed_queries", "replication_handoff_partials",
    "replication_handoff_identical", "replication_handoff_seconds",
    "replication_gate_ok", "replication_error",
    "chaos_availability", "chaos_partial_rate", "chaos_acked_lost",
    "chaos_p99_ratio", "chaos_wrong_full_results", "chaos_gate_ok",
    "chaos_error",
    # write-path tracing (ISSUE 12): the span+exemplar pipeline's tax on
    # the remote_write door (gate: tracing-on >= 98% of tracing-off),
    # the stitched 2-node trace proof, the wal.fsync fault-visibility
    # drill, and the ingest-to-ack p99 — plus a loud ingesttrace_error
    "ingest_trace_overhead_pct", "ingest_trace_on_samples_per_sec",
    "ingest_trace_stitched", "ingest_freshness_p99_s",
    "ingesttrace_fault_visible", "ingesttrace_gate_ok",
    "ingesttrace_error",
    # live query introspection (ISSUE 13): the registry's tax on the
    # concurrent-QPS stage (gate: <= 2%) and the two-node cold-query
    # kill-drill evidence (structured query_canceled, semaphore slot
    # freed, remote leaf drained within 250 ms) — plus a loud
    # activequeries_error
    "activequeries_overhead_pct", "activequeries_gate_ok",
    "activequeries_kill_structured", "activequeries_stop_ms",
    "activequeries_slot_freed", "activequeries_listed_remote",
    "activequeries_kill_to_client_ms", "activequeries_error",
    # multi-tenant QoS (ISSUE 14): the noisy-neighbor drill — good-
    # tenant p99 under flood vs idle (gate: <= 1.5x), the abuser's
    # structured-shed evidence (429 + Retry-After, zero query_timeout,
    # zero silent starvation) — plus a loud qos_error when the stage
    # fails
    "qos_p99_ratio", "qos_good_p99_idle_s", "qos_good_p99_noisy_s",
    "qos_abuser_shed", "qos_abuser_timeouts", "qos_abuser_completed",
    "qos_shed_retry_after_ok", "qos_capacity", "qos_gate_ok",
    "qos_error",
    # distributed execution (ISSUE 15): the 4-node fan-out aggregation's
    # pushed-vs-ship-everything wire ratio (gate: >= 10x, BIT-identical
    # results), the long-range streamed-reply traced-peak bound (gate:
    # streamed under a fixed budget the materialize-everything baseline
    # exceeds), and the pushdown wall speedup — plus a loud
    # distexec_error when the stage fails
    "distexec_wire_bytes_ratio", "distexec_pushdown_speedup_x",
    "distexec_bit_identical", "distexec_frontend_peak_rss_mb",
    "distexec_baseline_peak_rss_mb", "distexec_rss_budget_mb",
    "distexec_stream_frames", "distexec_stream_identical",
    "distexec_pushed_nodes", "distexec_gate_ok", "distexec_error",
    # high-cardinality bitmap index (ISSUE 16): `=~` first-plan p50
    # (gate: < 10 ms on the zipf shard), equals point-lookup p50 (gate:
    # < 1 ms), churn-soak memory growth across evict-all generations
    # (gate: <= 10%, compaction + container rebase holding the line),
    # plus build throughput and the one-time trigram-map build — and a
    # loud index_error when the stage fails
    "index_series", "index_build_keys_per_sec",
    "index_equals_lookup_p50_ms", "index_regex_plan_p50_ms",
    "index_regex_plan_max_ms", "index_regex_memo_p50_ms",
    "index_trigram_build_ms", "index_churn_rss_growth_pct",
    "index_memory_bytes", "index_gate_ok", "index_error",
    # whole-expression compilation (ISSUE 17): the 8-panel dashboard's
    # fused-batch p50 vs per-node-assembly baseline (gate: >= 5x,
    # results BIT-identical), the fused/degraded verdict counts (gate:
    # 0 degraded on the eligible panel mix) and the batch gather-memo
    # hit count — plus a loud exprfuse_error when the stage fails
    "exprfuse_p50_s", "exprfuse_baseline_p50_s", "exprfuse_speedup_x",
    "exprfuse_identical", "exprfuse_fused", "exprfuse_degraded",
    "exprfuse_memo_hits", "exprfuse_gate_ok", "exprfuse_error",
    # device telemetry (ISSUE 18): the per-chip kernel ledger's tax on
    # concurrent engine QPS and on the flagship fused-scan p50 (both
    # gated <= 2%), the ?stats=true per-device parity check, the
    # compile-storm drill (attributable in the ledger, fills
    # jit_compile_seconds, flips device health), and the per-device
    # mesh dispatch reconcile — plus a loud devicetelem_error
    "devicetelem_overhead_pct", "devicetelem_fused_overhead_pct",
    "devicetelem_parity_ok", "devicetelem_storm_compiles",
    "devicetelem_storm_attributed", "devicetelem_storm_hist_count",
    "devicetelem_storm_health_degraded", "devicetelem_mesh_reconciled",
    "devicetelem_gate_ok", "devicetelem_error",
    # disaggregated cold tier (ISSUE 19): the disk-kill drill (wipe the
    # whole store root, rebuild from shared object store + WAL tail,
    # byte-identical query_range, availability 1.0 via stateless
    # readers while the node is down), the elastic-read gate (2
    # query-only node processes; >= 1.8x QPS on >= 3-core hosts,
    # no-collapse + identity on smaller ones), and the dead-store
    # degrade proof (flagged partial in bounded time, typed error when
    # strict) — plus a loud objectstore_error when the stage fails
    "objectstore_drill_identical", "objectstore_drill_availability",
    "objectstore_drill_restored_segments",
    "objectstore_drill_uploaded_segments",
    "objectstore_drill_wal_tail_batches",
    "objectstore_elastic_qps_1node", "objectstore_elastic_qps_3node",
    "objectstore_elastic_qps_ratio", "objectstore_elastic_identical",
    "objectstore_elastic_cores", "objectstore_elastic_gate",
    "objectstore_deadstore_partial_flagged",
    "objectstore_deadstore_strict_error", "objectstore_deadstore_seconds",
    "objectstore_gate_ok", "objectstore_error",
    # cross-cluster federation (ISSUE 20): the two-cluster testbench's
    # bit-identity proof (federated sum-by AND a cross-cluster join vs
    # the single-store ground truth), the dead-cluster degrade drill
    # (flagged partial NAMING the cluster, zero hangs / zero wrong-full
    # results, breaker fail-fast then half-open recovery), and the
    # partial-pushdown wire ratio vs the ship-everything strawman —
    # plus a loud federation_error when the stage fails
    "federation_identical", "federation_join_identical",
    "federation_partial_on_dead_cluster", "federation_dead_names_cluster",
    "federation_dead_seconds", "federation_recovered_full",
    "federation_wire_ratio_x", "federation_pushed_wire_bytes",
    "federation_shipped_wire_bytes", "federation_gate_ok",
    "federation_error",
]
RENAME = {"value": "headline_samples_per_sec",
          "p50_query_latency_s": "p50_s"}


def parse_oneline(tail: str):
    """Last parseable JSON object line in a driver artifact's tail."""
    for line in reversed((tail or "").splitlines()):
        line = line.strip()
        if not (line.startswith("{") and line.endswith("}")):
            continue
        try:
            doc = json.loads(line)
        except ValueError:
            continue
        if isinstance(doc, dict) and ("metric" in doc or "value" in doc):
            return doc
    return None


def collect_rounds(repo: str):
    """{round: trend-entry} from every BENCH_rNN.json artifact."""
    rounds = {}
    for path in sorted(glob.glob(os.path.join(repo, "BENCH_r*.json"))):
        m = re.match(r"BENCH_r(\d+)\.json$", os.path.basename(path))
        if not m:
            continue
        try:
            with open(path) as f:
                art = json.load(f)
        except ValueError:
            continue
        n = int(art.get("n", m.group(1)))
        entry = {"round": n, "artifact": os.path.basename(path),
                 "rc": art.get("rc")}
        line = parse_oneline(art.get("tail", ""))
        if line is None:
            entry["note"] = "no parseable one-line JSON in artifact tail"
        else:
            for k in CARRY:
                if k in line:
                    entry[RENAME.get(k, k)] = line[k]
        rounds[n] = entry
    return rounds


def merge(trend: dict, rounds: dict) -> dict:
    """Fold collected rounds into the trend doc; hand keys win."""
    have = {r.get("round"): r for r in trend.setdefault("rounds", [])}
    for n in sorted(rounds):
        if n in have:
            for k, v in rounds[n].items():
                have[n].setdefault(k, v)
        else:
            trend["rounds"].append(rounds[n])
    trend["rounds"].sort(key=lambda r: (r.get("round") or 0))
    return trend


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--repo", default=REPO)
    ap.add_argument("--write", action="store_true",
                    help="update BENCH_TREND.json in place (default: "
                         "print the merged doc to stdout)")
    args = ap.parse_args(argv)
    path = os.path.join(args.repo, "BENCH_TREND.json")
    trend = {}
    if os.path.exists(path):
        with open(path) as f:
            trend = json.load(f)
    merged = merge(trend, collect_rounds(args.repo))
    out = json.dumps(merged, indent=1)
    if args.write:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(out + "\n")
        os.replace(tmp, path)
        print(f"wrote {path} ({len(merged['rounds'])} rounds)")
    else:
        print(out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
