#!/usr/bin/env python
"""TPU tunnel watcher: arm at round open, strike at any live window.

The tunneled TPU backend ('axon') has been unreliable across rounds — alive
early in round 2, dead for all of round 3.  This watcher makes TPU-evidence
capture unconditional on tunnel luck (round-3 verdict, next-round item 1):

  - every PROBE_INTERVAL seconds, probe backend init in a bounded child;
  - log every probe to TPU_WATCH_r{N}.jsonl (committed periodically, so the
    repo carries proof the watcher was armed even if the tunnel never wakes);
  - on a live probe, launch the staged bench worker (smallest stage first —
    bench.py ladder: 8k -> 65k -> 262k -> 1M) and, while it runs, poll
    BENCH_PARTIAL.json; every time a NEW stage lands with a trusted number,
    snapshot it to BENCH_TPU_SNAPSHOT_r{N}.json and git-commit immediately.
    A 5-minute tunnel window therefore still leaves a committed TPU number.
  - stop once the 1M north-star stage has a trusted number (or on
    tools/tpu_watch.stop).

XLA compile cache persists across attempts via JAX_COMPILATION_CACHE_DIR so
a second window doesn't pay cold compiles again.

Usage:  nohup python tools/tpu_watch.py --round 4 >/tmp/tpu_watch.out 2>&1 &
"""
import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
STOP_FILE = os.path.join(REPO, "tools", "tpu_watch.stop")
CACHE_DIR = os.path.join(REPO, ".jax_cache")

# one shared notion of "tunnel alive" between the bench supervisor and the
# watcher.  Loaded by file path: `import bench` would resolve to the
# bench/ suite package, which shadows the bench.py module at repo root.
import importlib.util  # noqa: E402

_spec = importlib.util.spec_from_file_location(
    "_bench_headline", os.path.join(REPO, "bench.py"))
_bench = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_bench)
probe = _bench._probe_default_backend


def utcnow():
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def git_commit(paths, msg):
    """Best-effort commit of specific artifact paths (retries index-lock
    races with the interactive session)."""
    for attempt in range(5):
        try:
            subprocess.run(["git", "-C", REPO, "add", "--"] + paths,
                           check=True, capture_output=True, timeout=60)
            r = subprocess.run(["git", "-C", REPO, "commit", "-m", msg,
                                "--no-verify"],
                               capture_output=True, text=True, timeout=60)
            return (r.returncode == 0
                    or "nothing to commit" in r.stdout + r.stderr)
        except (subprocess.CalledProcessError, subprocess.TimeoutExpired):
            time.sleep(3 * (attempt + 1))
    return False


class WatchLog:
    def __init__(self, path, commit_every):
        self.path = path
        self.commit_every = commit_every
        self.since_commit = 0

    def log(self, **kv):
        kv["utc"] = utcnow()
        with open(self.path, "a") as f:
            f.write(json.dumps(kv) + "\n")
        self.since_commit += 1
        if self.since_commit >= self.commit_every:
            if git_commit([self.path],
                          "tpu_watch: probe log checkpoint (armed)"):
                self.since_commit = 0


def trusted_stages(partial_path):
    """Stage names in BENCH_PARTIAL.json that carry a trusted number from a
    TPU run."""
    try:
        with open(partial_path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return {}, None
    if doc.get("platform") != "tpu":
        return {}, doc
    return {k: v for k, v in doc.get("stages", {}).items()
            if isinstance(v, dict) and "samples_per_sec" in v}, doc


def snapshot(doc, stages, snap_path, log, committed):
    """Write/commit the snapshot artifact if it carries new trusted stages.
    The dedup key includes the run_id so a fresh tunnel window that reaches
    the same stage set as a previous one is still captured."""
    names = sorted(stages)
    key = doc.get("run_id", "") + ":" + ",".join(names)
    if not names or key == committed:
        return committed
    tmp = snap_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
    os.replace(tmp, snap_path)
    ok = git_commit([snap_path, log.path],
                    f"tpu_watch: TPU bench snapshot ({key})")
    log.log(event="snapshot", stages=names, committed=ok)
    return key if ok else committed


def run_bench_window(args, log, committed):
    """One live-tunnel strike: staged bench with concurrent snapshotting."""
    partial = os.path.join(REPO, "BENCH_PARTIAL.json")
    snap = os.path.join(REPO, f"BENCH_TPU_SNAPSHOT_r{args.round:02d}.json")
    run_id = f"watch-r{args.round}-{int(time.time())}"
    env = dict(os.environ, JAX_COMPILATION_CACHE_DIR=CACHE_DIR)
    cmd = [sys.executable, os.path.join(REPO, "bench.py"), "--_worker",
           "--platform", "default", "--run-id", run_id]
    log.log(event="bench_start", run_id=run_id)
    proc = subprocess.Popen(cmd, cwd=REPO, env=env,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    deadline = time.time() + args.bench_timeout
    north_star_done = False
    stopped = False
    while proc.poll() is None and time.time() < deadline:
        time.sleep(15)
        if os.path.exists(STOP_FILE):
            stopped = True
            break
        stages, doc = trusted_stages(partial)
        if doc is not None and doc.get("run_id") == run_id and stages:
            committed = snapshot(doc, stages, snap, log, committed)
            if "north_star_1m" in stages:
                north_star_done = True
    if proc.poll() is None:
        proc.kill()
        log.log(event="bench_stopped" if stopped else "bench_timeout",
                run_id=run_id)
    else:
        log.log(event="bench_exit", run_id=run_id, rc=proc.returncode)
    stages, doc = trusted_stages(partial)
    if doc is not None and doc.get("run_id") == run_id and stages:
        committed = snapshot(doc, stages, snap, log, committed)
        north_star_done = north_star_done or "north_star_1m" in stages
    return committed, north_star_done


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--round", type=int, required=True)
    ap.add_argument("--probe-timeout", type=int, default=90)
    ap.add_argument("--probe-interval", type=int, default=180)
    ap.add_argument("--bench-timeout", type=int, default=3600)
    ap.add_argument("--log-commit-every", type=int, default=12,
                    help="commit the probe log every N probes")
    ap.add_argument("--once", action="store_true",
                    help="single probe+strike cycle (dry-run / testing)")
    args = ap.parse_args()

    os.makedirs(CACHE_DIR, exist_ok=True)
    log = WatchLog(os.path.join(REPO, f"TPU_WATCH_r{args.round:02d}.jsonl"),
                   args.log_commit_every)
    log.log(event="armed", pid=os.getpid(),
            probe_interval_s=args.probe_interval,
            probe_timeout_s=args.probe_timeout)
    committed = ""
    while True:
        if os.path.exists(STOP_FILE):
            log.log(event="stopped", reason="stop file")
            break
        plat = probe(args.probe_timeout)
        log.log(event="probe", platform=plat)
        if plat not in (None, "cpu"):
            committed, done = run_bench_window(args, log, committed)
            if done:
                log.log(event="north_star_captured")
                git_commit([log.path], "tpu_watch: north star captured")
                break
        if args.once:
            break
        time.sleep(args.probe_interval)
    # final log flush
    git_commit([log.path], "tpu_watch: final probe log")


if __name__ == "__main__":
    main()
