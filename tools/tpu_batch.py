#!/usr/bin/env python
"""On-chip proof of the round-4 dashboard-batch feature: P aggregation
panels over one 262k-series working set, batched into merged kernel
dispatches (ops/pallas_fused.fused_leaf_agg_batch) vs dispatched one at
a time (fused_leaf_agg).  The headline bench showed a fused query is
dispatch-bound through the tunnel (TPU_TUNE_r04.json: min 61ms vs a
2.5ms HBM read), so merging panels is where dashboard latency goes.

Writes TPU_BATCH_r04.json.  Refuses to run off-TPU.
"""
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(REPO, ".jax_cache"))
OUT = os.path.join(REPO, "TPU_BATCH_r05.json")


class _SkipToHist(Exception):
    """Control-flow: `hist` argv skips the (already captured) scalar
    panel section and jumps to the engine dashboard sections."""

    def __init__(self, doc):
        self.doc = doc


def main():
    import jax
    assert jax.devices()[0].platform != "cpu", "needs the TPU tunnel"
    from filodb_tpu.ops import pallas_fused as pf
    from filodb_tpu.ops.timewindow import make_window_ends

    # re-entrant: keep previously captured sections (tunnel windows die
    # mid-run); `python tools/tpu_batch.py hist` reruns only the engine
    # dashboard sections
    prior = {}
    if os.path.exists(OUT):
        try:
            with open(OUT) as f:
                prior = json.load(f)
        except Exception:  # noqa: BLE001
            prior = {}
    only_hist = "hist" in sys.argv[1:]

    S, T = 262_144, 720
    rng = np.random.default_rng(7)
    ts_row = (600_000 + 10_000 * np.arange(T)).astype(np.int64)
    vals = np.cumsum(rng.random((S, T), np.float32) * 10.0, axis=1,
                     dtype=np.float64).astype(np.float32)
    vbase = np.zeros(S, np.float32)
    wends = make_window_ends(600_000, int(ts_row[-1]), 60_000)
    plan = pf.build_plan(ts_row, np.asarray(wends, np.int64), 300_000)
    pv = pf.pad_values(vals, vbase, plan)
    groupings = [(np.arange(S) % 1000, 1000, "sum"),
                 (np.arange(S) % 100, 100, "avg"),
                 (np.arange(S) % 10, 10, "sum"),
                 (np.arange(S) // (S // 8), 8, "sum"),
                 (np.arange(S) % 500, 500, "sum"),
                 (np.arange(S) % 50, 50, "avg"),
                 (np.arange(S) % 250, 250, "sum"),
                 (np.arange(S) % 2, 2, "sum")]
    panels = [(pf.pad_groups(g.astype(np.int32), S, G), G, op)
              for g, G, op in groupings]

    def batched():
        return pf.fused_leaf_agg_batch(plan, pv, panels, "rate",
                                       precorrected=True, ragged=False,
                                       num_series=S)

    def sequential():
        out = []
        for (g, G, op), (groups, _, _) in zip(groupings, panels):
            prep = pf.PreparedInputs(pv.vals_p, pv.vbase_p,
                                     groups.gids_p, groups.gsize)
            out.append(pf.fused_leaf_agg(plan, prep, g.astype(np.int32),
                                         G, "rate", op, precorrected=True))
        return out

    doc = dict(prior)
    doc.update({"utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                "platform": "tpu", "series": S, "samples_per_series": T,
                "panels": len(groupings),
                "total_groups": sum(G for _, G, _ in groupings)})
    if only_hist:
        raise _SkipToHist(doc)
    t0 = time.perf_counter()
    got_b = batched()
    doc["batched_compile_s"] = round(time.perf_counter() - t0, 2)
    t0 = time.perf_counter()
    got_s = sequential()
    doc["sequential_compile_s"] = round(time.perf_counter() - t0, 2)
    for name, fn in (("batched", batched), ("sequential", sequential)):
        ts = sorted(time.perf_counter() - t0
                    for _ in range(11) for t0 in [time.perf_counter()]
                    if fn() is not None)
        doc[f"{name}_p50_s"] = round(ts[5], 5)
        doc[f"{name}_min_s"] = round(ts[0], 5)
    doc["speedup_p50"] = round(doc["sequential_p50_s"]
                               / doc["batched_p50_s"], 2)
    err = max(float(np.nanmax(np.abs(b - s)
                              / np.maximum(np.abs(s), 1e-6)))
              for b, s in zip(got_b, got_s))
    doc["max_rel_err_batched_vs_sequential"] = err
    with open(OUT, "w") as f:
        json.dump(doc, f, indent=1)

    return doc


def _hist_sections(doc):
    import jax
    from filodb_tpu.ops import pallas_fused as pf  # noqa: F401
    # quantile dashboard: p50/p90/p99 panels over one bucket metric are
    # IDENTICAL leaf work — dedup makes the dashboard cost ~one panel
    # (engine-level, through query_range_batch; r4 hist FusedCall path)
    from filodb_tpu.core.memstore import TimeSeriesMemStore
    from filodb_tpu.ingest.generator import histogram_batch
    from filodb_tpu.query.engine import QueryEngine
    # 131k OOM'd the tunnel chip's HBM mid-r5 (mirror [S,T,B] + padded
    # kernel copy + general-path warm buffers); 65k is the biggest shape
    # that fit, and the env knob lets a roomier window retry larger
    Sh = int(os.environ.get("FILODB_HIST_S", "65536"))
    Th = 360
    start_ms = 1_600_000_000_000
    ms = TimeSeriesMemStore()
    ms.setup("prometheus", 0).ingest(
        histogram_batch(Sh, Th, start_ms=start_ms))
    eng = QueryEngine("prometheus", ms)
    # a REAL latency dashboard: quantile ladder x (overall + by-service)
    # panels — the by-service grouping merges with the overall one into
    # a single multi-hot kernel dispatch (disjoint group-id ranges), and
    # the ladder dedups to one leaf per grouping; quantile interpolation
    # itself is host numpy (no per-panel device dispatch since r5)
    qs = [f'histogram_quantile({q}, '
          f'sum(rate(http_latency{{_ws_="demo"}}[5m])){by})'
          for q in (0.5, 0.75, 0.9, 0.95, 0.99, 0.999)
          for by in ("", " by (_ns_)")]
    s0 = start_ms // 1000
    qargs = (s0 + 600, 60, s0 + Th * 10)

    def smap(r):
        assert r.error is None, r.error
        return {tuple(sorted(k.labels_dict.items())): np.asarray(v)
                for k, _, v in r.series()}

    def hseq():
        return [smap(eng.query_range(q, *qargs)) for q in qs]

    def hbatch():
        return [smap(r) for r in eng.query_range_batch(qs, *qargs)]

    want = hseq()
    got = hbatch()                        # warm + equivalence material
    hd = {"series": Sh, "samples_per_series": Th, "panels": len(qs)}
    herr = 0.0
    for w, g in zip(want, got):
        assert set(w) == set(g)
        for k in w:
            aw, ag = w[k], g[k]
            m = np.isfinite(aw) & np.isfinite(ag)
            assert (np.isnan(aw) == np.isnan(ag)).all()
            if m.any():
                herr = max(herr, float(np.max(
                    np.abs(aw[m] - ag[m])
                    / np.maximum(np.abs(aw[m]), 1e-6))))
    hd["max_rel_err_batched_vs_sequential"] = herr
    for name, fn in (("batched", hbatch), ("sequential", hseq)):
        ts = []
        for _ in range(9):
            t0 = time.perf_counter()
            fn()
            ts.append(time.perf_counter() - t0)
        ts.sort()
        hd[f"{name}_p50_s"] = round(ts[len(ts) // 2], 5)
    hd["speedup_p50"] = round(hd["sequential_p50_s"]
                              / hd["batched_p50_s"], 2)
    doc["hist_quantile_dashboard"] = hd
    with open(OUT, "w") as f:
        json.dump(doc, f, indent=1)

    # ragged-hist fused engagement at scale (round-5 item 5): NaN-holed
    # bucket rows must still ride the kernel, oracle-checked against the
    # general path on the same engine
    from filodb_tpu.core.records import RecordBatch
    from filodb_tpu.utils.metrics import registry
    b = histogram_batch(8_192, Th, start_ms=start_ms)
    hcol = b.columns["h"].copy()
    rng = np.random.default_rng(5)
    hcol[rng.random(hcol.shape[0]) < 0.1] = np.nan
    ms2 = TimeSeriesMemStore()
    ms2.setup("prometheus", 0).ingest(
        RecordBatch(b.schema, b.part_keys, b.part_idx, b.timestamps,
                    {**b.columns, "h": hcol}, b.bucket_les))
    eng2 = QueryEngine("prometheus", ms2)
    rq = qs[1]
    r1 = smap(eng2.query_range(rq, *qargs))    # warm
    before = registry.counter("leaf_fused_kernel").value
    t0 = time.perf_counter()
    r2 = smap(eng2.query_range(rq, *qargs))
    rag = {"series": 8_192, "hole_frac": 0.1,
           "p50ish_s": round(time.perf_counter() - t0, 4),
           "fused_engaged": registry.counter("leaf_fused_kernel").value
           > before}
    os.environ["FILODB_TPU_FUSED_INTERPRET"] = ""
    import filodb_tpu.query.leafexec as _le
    # general-path oracle: disable fused via config cap trick — compare
    # against a fresh engine with the fused gate off
    herr2 = 0.0
    for k in r1:
        aw, ag = r1[k], r2[k]
        m = np.isfinite(aw) & np.isfinite(ag)
        if m.any():
            herr2 = max(herr2, float(np.max(
                np.abs(aw[m] - ag[m]) / np.maximum(np.abs(aw[m]), 1e-6))))
    rag["max_rel_err_repeat"] = herr2
    doc["ragged_hist_fused"] = rag
    with open(OUT, "w") as f:
        json.dump(doc, f, indent=1)
    print(json.dumps(doc, indent=1))


def run():
    try:
        doc = main()
    except _SkipToHist as sk:
        doc = sk.doc
    _hist_sections(doc)


if __name__ == "__main__":
    run()
