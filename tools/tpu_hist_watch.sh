#!/bin/bash
# Probe the tunnel every 4 minutes; when alive, run the hist dashboard
# sections of tools/tpu_batch.py once and exit.
cd /root/repo
for i in $(seq 1 60); do
  if timeout 70 python -c "import os; os.environ.pop('JAX_PLATFORMS',None); import jax; assert jax.devices()[0].platform != 'cpu'" 2>/dev/null; then
    echo "tunnel alive at attempt $i; running hist sections"
    JAX_COMPILATION_CACHE_DIR=/root/repo/.jax_cache timeout 2400 python tools/tpu_batch.py hist 2>&1 | grep -v WARNING | tail -5
    exit 0
  fi
  sleep 240
done
echo "tunnel never returned"
exit 1
