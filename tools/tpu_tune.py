#!/usr/bin/env python
"""On-chip A/B sweep of fused-kernel MXU precision and series-block size.

The round-4 roofline capture (TPU_EXTRA_r04.json) showed the fused kernel
at ~27% MFU against the f32-HIGHEST matmul roofline it runs at — MXU
passes, not bandwidth, are a visible fraction of device time.  Every
matmul in the kernel has one exact-in-bf16 operand (0/1 selection/band/
one-hot matrices), so per-operand precision (ops/pallas_fused._matmuls)
should halve the MXU passes with no accuracy loss.  This script measures
that ON CHIP: each variant runs in a subprocess (the knobs are read at
import) over identical seeded data, and the parent compares p50 latency
and max relative error vs the all-HIGHEST baseline.

Usage: python tools/tpu_tune.py [S] (default 262144; refuses non-TPU).
Writes TPU_TUNE_r04.json incrementally.
"""
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "TPU_TUNE_r04.json")

VARIANTS = [
    ("base", {"FILODB_FUSED_PRECISION": "highest", "FILODB_FUSED_BS": "256"}),
    ("split", {"FILODB_FUSED_PRECISION": "split", "FILODB_FUSED_BS": "256"}),
    ("bs512", {"FILODB_FUSED_PRECISION": "highest", "FILODB_FUSED_BS": "512"}),
    ("split512", {"FILODB_FUSED_PRECISION": "split",
                  "FILODB_FUSED_BS": "512"}),
]

CHILD = r"""
import json, os, sys, time
import numpy as np
sys.path.insert(0, %(repo)r)
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(%(repo)r, ".jax_cache"))
import jax
assert jax.devices()[0].platform != "cpu", "needs the TPU tunnel"
from filodb_tpu.ops import pallas_fused as pf
from filodb_tpu.ops.timewindow import make_window_ends

S, T, G = %(S)d, 720, 1000
ragged = %(ragged)r
rng = np.random.default_rng(7)
ts_row = (600_000 + 10_000 * np.arange(T)).astype(np.int64)
# leaf-path parity (bench.py): host pre-corrected counters -> monotone
# rebased values on device, precorrected=True (with_drops=False dense)
incr = rng.random((S, T), np.float32) * 10.0
vals = np.cumsum(incr, axis=1, dtype=np.float64).astype(np.float32)
if ragged:
    vals[rng.random((S, T)) < 0.10] = np.nan
vbase = np.zeros(S, np.float32)
gids = (np.arange(S) %% G).astype(np.int32)
wends = make_window_ends(600_000, int(ts_row[-1]), 60_000)
range_ms = 300_000
plan = pf.build_plan(ts_row, np.asarray(wends, np.int64), range_ms)
prep = pf.pad_inputs(vals, vbase, gids, plan, G)

def run():
    sums, counts = pf.fused_rate_groupsum(
        None, None, None, plan, G, "rate", True, prepared=prep,
        ragged=ragged)
    return pf.present_sum(sums, counts)

t0 = time.perf_counter()
res = run()
compile_s = time.perf_counter() - t0
times = []
for _ in range(15):
    t0 = time.perf_counter(); run(); times.append(time.perf_counter() - t0)
times.sort()
p50 = times[len(times) // 2]
# samples scanned per query: grid slots from the earliest window start
# to the last window end, per series (this grid starts AT the first
# window end, so all T slots are covered -- don't copy tpu_extra's 690)
lo = np.searchsorted(ts_row, int(wends[0]) - range_ms)
hi = np.searchsorted(ts_row, int(wends[-1]), side="right")
span = S * int(hi - lo)
np.save(%(resfile)r, res)
print(json.dumps({"p50_s": round(p50, 5), "compile_s": round(compile_s, 2),
                  "samples_per_sec": round(span / p50, 1),
                  "min_s": round(times[0], 5)}))
"""


def main():
    S = int(sys.argv[1]) if len(sys.argv) > 1 else 262_144
    doc = {"utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
           "series": S, "samples_per_series": 720, "groups": 1000,
           "variants": {}}
    import numpy as np
    for ragged in (False, True):
        tag = "ragged" if ragged else "dense"
        base_res = None
        for name, env in VARIANTS:
            resfile = f"/tmp/tune_{tag}_{name}.npy"
            child_env = dict(os.environ, **env)
            code = CHILD % {"repo": REPO, "S": S, "mode": name,
                            "ragged": ragged, "resfile": resfile}
            t0 = time.perf_counter()
            r = subprocess.run([sys.executable, "-c", code],
                               capture_output=True, text=True, timeout=1800,
                               env=child_env)
            key = f"{tag}_{name}"
            if r.returncode != 0:
                doc["variants"][key] = {"error": r.stderr[-1200:]}
                print(f"{key}: FAILED\n{r.stderr[-1200:]}")
            else:
                rec = json.loads(r.stdout.strip().splitlines()[-1])
                res = np.load(resfile)
                if base_res is None and name == "base":
                    base_res = res
                if base_res is None and name != "base":
                    # never let a sweep read as "faster AND conformant"
                    # when the conformance reference failed to run
                    rec["max_rel_err_vs_base"] = "base-missing"
                if base_res is not None and name != "base":
                    same_nan = bool((np.isnan(res) == np.isnan(base_res))
                                    .all())
                    err = float(np.nanmax(
                        np.abs(res - base_res)
                        / np.maximum(np.abs(base_res), 1e-6)))
                    rec["max_rel_err_vs_base"] = (round(err, 9) if same_nan
                                                  else "nan-mismatch")
                rec["wall_s"] = round(time.perf_counter() - t0, 1)
                doc["variants"][key] = rec
                print(f"{key}: {rec}")
            with open(OUT, "w") as f:
                json.dump(doc, f, indent=1)
    print(json.dumps(doc, indent=1))


if __name__ == "__main__":
    main()
