#!/usr/bin/env python
"""True fused-kernel device time via chained-K dispatch (round-5 item 1).

Round 4's doc/kernels.md derived "net device time" by SUBTRACTING an
assumed ~75 ms dispatch floor from the per-call p50 — never measuring
it.  This tool runs K dependent fused-kernel iterations inside ONE jit
(the tools/tpu_extra.py roofline pattern) and fits

    t(K) = intercept (dispatch + fixed overhead) + K * slope (device/query)

so the per-query device time is a measured slope, not an assumption.
Modes per shape:

  group       — the production path (selection matmuls + group epilogue)
  per_series  — same kernel, epilogue matmul ablated (raw [S, W] out);
                group-minus-per_series ~ epilogue cost (+ the bigger
                output write, reported alongside)
  segsum      — FILODB_CHAIN_SEGSUM=1: per-series kernel output
                finished by XLA segment_sum in the same jit — the
                complete-query scatter alternative to the in-kernel
                one-hot epilogue (measured SLOWER; doc/kernels.md)

Shapes mirror bench.py's ladder stages (dense counters, precorrected,
shared grid, G=1000, rate[5m] @ 1m steps over 2 h of 10 s samples).

Writes TPU_CHAIN_r05.json incrementally; refuses non-TPU backends.
"""
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(REPO, ".jax_cache"))
OUT = os.path.join(REPO, "TPU_CHAIN_r05.json")

DOC = {"utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())}


def persist():
    tmp = OUT + ".tmp"
    with open(tmp, "w") as f:
        json.dump(DOC, f, indent=1)
    os.replace(tmp, OUT)


def p50(fn, iters=9):
    lat = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        lat.append(time.perf_counter() - t0)
    return float(np.median(np.asarray(lat)))


def build(S, T=720, G=1000, range_ms=300_000, step_ms=60_000,
          hole_frac=0.0):
    """bench.py measure_stage's working set, minus the f64 rebase detour
    (make_counter_data is monotone, so rebase == subtract first column).
    hole_frac > 0 punches NaN scrape gaps (the ragged variant)."""
    from filodb_tpu.ops import pallas_fused as pf
    from filodb_tpu.ops.timewindow import make_window_ends

    # bench.py's make_counter_data (the repo-root module is shadowed by
    # the bench/ package, so the 4 lines are restated here)
    rng = np.random.default_rng(7)
    ts_row = np.arange(T, dtype=np.int64) * 10_000
    vals = np.cumsum(rng.exponential(10.0, size=(S, T)).astype(np.float32),
                     axis=1)
    if hole_frac > 0:
        vals[rng.random((S, T)) < hole_frac] = np.nan
    vbase = vals[:, 0].astype(np.float32)
    vals32 = vals - vbase[:, None]
    gids = (np.arange(S) % G).astype(np.int32)
    wends = make_window_ends(600_000, int(ts_row[-1]), step_ms)
    plan = pf.build_plan(ts_row, wends, range_ms)
    prep = pf.pad_inputs(vals32, vbase, gids, plan, G)
    span = S * int(np.searchsorted(ts_row, int(ts_row[-1]), side="right")
                   - np.searchsorted(ts_row, 600_000 - range_ms))
    return plan, prep, span, len(wends)


def chain_fn(jax, jnp, plan, prep, G, K, per_series, ragged=False,
             segsum=False):
    """K dependent fused calls in one jit; the carry perturbs vbase by a
    denormal-scale epsilon so XLA cannot CSE the iterations, while values
    stay the same HBM-resident array each pass (the steady-state query
    re-reads them from HBM exactly like this).  segsum=True measures the
    alternative COMPLETE-query epilogue: per-series kernel output
    finished by an XLA segment-sum scatter (instead of the in-kernel
    one-hot matmul) in the same jit."""
    from jax import lax
    from filodb_tpu.ops import pallas_fused as pf

    Gp = pf.pad_group_count(G)
    gather = os.environ.get("FILODB_CHAIN_GATHER", "0") == "1"
    mats = pf._kernel_mats(plan, over_time=False, gather=gather)
    if segsum:
        # pad rows carry gid -1: route them to an overflow segment Gp
        seg_ids = jnp.where(prep.gids_p[:, 0] >= 0, prep.gids_p[:, 0], Gp)

    @jax.jit
    def run(vals_p, vbase_p, gids_p):
        def body(i, acc):
            res = pf.run_kernel(
                vals_p, vbase_p + acc * 1e-30, gids_p, *mats,
                gather=gather,
                num_groups=Gp, is_counter=True, is_rate=True,
                with_drops=False, interpret=False, kind="rate_family",
                ragged=ragged, per_series=per_series or segsum)
            if ragged:
                res = res[0]
            if segsum:
                res = jax.ops.segment_sum(res, seg_ids,
                                          num_segments=Gp + 1)
            return acc + res[0, 0] * 1e-30
        return lax.fori_loop(0, K, body, jnp.float32(0.0))

    return lambda: run(prep.vals_p, prep.vbase_p,
                       prep.gids_p).block_until_ready()


def section_shape(jax, jnp, name, S, hole_frac=0.0):
    sec = {"series": S, "groups": 1000}
    if hole_frac:
        sec["hole_frac"] = hole_frac
    DOC[name] = sec
    t0 = time.perf_counter()
    plan, prep, span, W = build(S, hole_frac=hole_frac)
    sec["windows"] = W
    sec["samples_scanned_per_query"] = span
    sec["host_prep_s"] = round(time.perf_counter() - t0, 2)
    persist()

    KS = (1, 4, 16)
    modes = [("group", False, False), ("per_series", True, False)]
    if os.environ.get("FILODB_CHAIN_SEGSUM") == "1":
        modes.append(("segsum", False, True))
    for mode, per_series, segsum in modes:
        times = {}
        for K in KS:
            fn = chain_fn(jax, jnp, plan, prep, 1000, K, per_series,
                          ragged=hole_frac > 0, segsum=segsum)
            t0 = time.perf_counter()
            fn()
            times[f"k{K}_compile_s"] = round(time.perf_counter() - t0, 2)
            times[f"k{K}_p50_s"] = round(p50(fn), 5)
            sec[mode] = times
            persist()
        # least-squares fit over the three (K, p50) points
        ks = np.asarray(KS, np.float64)
        ys = np.asarray([times[f"k{k}_p50_s"] for k in KS], np.float64)
        slope, intercept = np.polyfit(ks, ys, 1)
        times["device_ms_per_query"] = round(slope * 1e3, 2)
        times["dispatch_intercept_ms"] = round(intercept * 1e3, 2)
        times["device_samples_per_sec"] = round(span / slope, 1)
        sec[mode] = times
        persist()
    g = sec["group"]["device_ms_per_query"]
    p = sec["per_series"]["device_ms_per_query"]
    # per_series writes [Sp, Wp] f32 instead of [Gp, Wp]: report the extra
    # HBM write so the epilogue attribution can subtract it
    extra_write_gb = prep.vals_p.shape[0] * 128 * 4 / 1e9
    sec["epilogue_attribution_ms"] = round(g - p, 2)
    sec["per_series_extra_write_gb"] = round(extra_write_gb, 3)
    persist()


def main():
    import jax
    import jax.numpy as jnp
    plat = jax.devices()[0].platform
    DOC["platform"] = "tpu" if plat == "axon" else plat
    DOC["device"] = str(jax.devices()[0])
    if plat not in ("tpu", "axon"):
        print(f"not a TPU backend ({plat}); refusing", file=sys.stderr)
        return 2
    if os.path.exists(OUT):
        try:
            with open(OUT) as f:
                for k, v in json.load(f).items():
                    DOC.setdefault(k, v)
        except Exception:  # noqa: BLE001
            pass
    persist()
    suffix = "_gather" if os.environ.get("FILODB_CHAIN_GATHER") == "1" \
        else ""
    prec = os.environ.get("FILODB_FUSED_PRECISION")
    if prec in ("split", "episplit"):
        # epilogue-precision A/B: with gather selections the one-hot
        # group epilogue is the kernel's only large matmul, so "split"/
        # "episplit" (3 single-pass dots) vs "highest" (6-pass emulation)
        # isolates its cost — the r4 sweep that measured split slower
        # predates gather and was dominated by the since-removed
        # selection matmuls
        suffix += "_" + prec
    shapes = [("chain_262k" + suffix, 262_144),
              ("chain_1m" + suffix, 1_048_576)]
    if os.environ.get("FILODB_CHAIN_RAGGED") == "1":
        # ragged device-time slope (round-4 weak #6: ragged cost 2x
        # dense; the gather selections should narrow it)
        shapes = [("chain_262k_ragged" + suffix, 262_144)]
    want = set(sys.argv[1:])
    ragged_run = os.environ.get("FILODB_CHAIN_RAGGED") == "1"
    for name, S in shapes:
        if want and name not in want:
            continue
        section_shape(jax, jnp, name, S,
                      hole_frac=0.1 if ragged_run else 0.0)
    DOC["done"] = True
    persist()
    print(json.dumps({k: v for k, v in DOC.items() if k != "done"},
                     indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
