#!/bin/bash
# After the hist watcher completes, capture the ragged chained-K slopes
# (matmul + gather) in the same tunnel window.
cd /root/repo
for i in $(seq 1 60); do
  if timeout 70 python -c "import os; os.environ.pop('JAX_PLATFORMS',None); import jax; assert jax.devices()[0].platform != 'cpu'" 2>/dev/null; then
    echo "tunnel alive; ragged chains"
    FILODB_CHAIN_RAGGED=1 timeout 1800 python tools/tpu_chain.py 2>&1 | grep -v WARNING | tail -2
    FILODB_CHAIN_RAGGED=1 FILODB_CHAIN_GATHER=1 timeout 1800 python tools/tpu_chain.py 2>&1 | grep -v WARNING | tail -2
    exit 0
  fi
  sleep 240
done
exit 1
