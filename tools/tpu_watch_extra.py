#!/usr/bin/env python
"""Companion watcher for tools/tpu_extra.py: after the headline watcher
(tools/tpu_watch.py) captured the north star and stopped, this one waits
for the next live tunnel window to (re)capture the sections that need the
fixed ragged kernel — ragged_rate_262k with the adaptive series block and
the Precision.HIGHEST f32 roofline — then commits and stops.

Usage: nohup python tools/tpu_watch_extra.py >/tmp/tpu_watch_extra.out 2>&1 &
Stop:  touch tools/tpu_watch.stop
"""
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
STOP_FILE = os.path.join(REPO, "tools", "tpu_watch.stop")
LOG = os.path.join(REPO, "TPU_WATCH_r04.jsonl")
OUT = os.path.join(REPO, "TPU_EXTRA_r04.json")
PROBE_INTERVAL = 240
SECTIONS = ["roofline", "ragged"]

import importlib.util  # noqa: E402

_spec = importlib.util.spec_from_file_location(
    "_bench_headline", os.path.join(REPO, "bench.py"))
_bench = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_bench)
probe = _bench._probe_default_backend


def log(event, **kw):
    rec = {"event": event,
           "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()), **kw}
    with open(LOG, "a") as f:
        f.write(json.dumps(rec) + "\n")


def commit(msg):
    for _ in range(5):
        try:
            subprocess.run(["git", "-C", REPO, "add", "--", OUT, LOG],
                           check=True, capture_output=True, timeout=60)
            r = subprocess.run(["git", "-C", REPO, "commit", "-m", msg,
                                "--no-verify"],
                               capture_output=True, text=True, timeout=60)
            if r.returncode == 0 or "nothing to commit" in r.stdout:
                return
        except Exception:  # noqa: BLE001
            pass
        time.sleep(5)


def main():
    log("extra_watch_armed", sections=SECTIONS)
    while True:
        if os.path.exists(STOP_FILE):
            log("extra_watch_stopped", reason="stop file")
            return 0
        plat = probe(90)
        log("extra_probe", platform=plat)
        if plat == "tpu":
            r = subprocess.run(
                [sys.executable, os.path.join(REPO, "tools", "tpu_extra.py")]
                + SECTIONS, capture_output=True, text=True, timeout=3600)
            log("extra_run", rc=r.returncode, tail=r.stdout[-300:])
            ok = False
            try:
                with open(OUT) as f:
                    doc = json.load(f)
                ok = (doc.get("ragged_rate_262k", {}).get("conformance_ok")
                      and "ragged_error" not in doc)
            except Exception:  # noqa: BLE001
                pass
            commit("tpu_watch_extra: ragged+roofline recapture "
                   f"(rc={r.returncode}, conformant={bool(ok)})")
            if ok:
                log("extra_watch_done")
                return 0
        time.sleep(PROBE_INTERVAL)


if __name__ == "__main__":
    sys.exit(main())
