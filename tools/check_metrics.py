"""Metric-hygiene gate: boot a test server, drive the main paths, and
fail on any metric that is illegally named, collides across metric
kinds, or is missing from doc/observability.md's reference table.

The exposition grammar is already tier-1-gated
(tests/test_metrics_exposition.py); this tool closes the remaining
gaps a grammar check can't see:

  * duplicate/colliding families — a counter `foo` exposes `foo_total`,
    a histogram `foo` exposes `foo_bucket`/`foo_sum`/`foo_count`; a
    second metric registered under one of those EXPOSED names silently
    produces duplicate sample lines a Prometheus scraper drops;
  * illegal names/labels that only appear under traffic (tag values are
    escaped, tag NAMES are not — a bad tag name poisons every scrape);
  * undocumented metrics — every live metric family must appear in the
    `## Metrics reference` table in doc/observability.md (entries may
    use `*` globs for per-name families like `span_*_seconds`), so the
    operator-facing catalog can never silently rot behind the code.

Run:  python tools/check_metrics.py            # exit 0 clean / 1 dirty
      python tools/check_metrics.py --emit-table   # print a fresh table

Wired as a tier-1 test (tests/test_check_metrics.py runs it in a
subprocess so the walked registry holds exactly this boot's metrics).
"""
from __future__ import annotations

import argparse
import fnmatch
import os
import re
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
DOC_PATH = os.path.join(REPO, "doc", "observability.md")
TABLE_HEADER = "## Metrics reference"


def boot_and_drive():
    """A small standalone server + one pass over every major path:
    remote_write (traced), influx, query_range (cold + warm), metadata,
    a scrape, the self-scrape snapshot, a WAL commit, and a slow-batch
    record — so the registry holds a representative live metric set."""
    import tempfile

    from filodb_tpu.config import FilodbSettings
    from filodb_tpu.http import remotepb
    from filodb_tpu.standalone import DatasetConfig, FiloServer
    from filodb_tpu.utils import snappy as fsnappy

    from filodb_tpu.persist.localstore import (LocalDiskColumnStore,
                                               LocalDiskMetaStore)

    cfg = FilodbSettings()
    cfg.wal.enabled = True
    cfg.wal.dir = tempfile.mkdtemp(prefix="filodb-checkmetrics-wal-")
    # disk-backed store + shared object-store root: the disaggregated
    # cold tier's metric families (objectstore_*) must go live too
    disk_root = tempfile.mkdtemp(prefix="filodb-checkmetrics-store-")
    cfg.objectstore.root = tempfile.mkdtemp(
        prefix="filodb-checkmetrics-objstore-")
    cfg.store.segment_window_ms = 3600 * 1000
    cfg.store.segment_closed_lag_ms = 3600 * 1000
    srv = FiloServer(datasets=[DatasetConfig("prometheus", num_shards=2)],
                     column_store=LocalDiskColumnStore(disk_root),
                     meta_store=LocalDiskMetaStore(disk_root),
                     config=cfg)
    try:
        now = int(time.time() * 1000)
        series = []
        for i in range(32):
            labels = [("__name__", "hygiene_total"), ("_ws_", "hy"),
                      ("_ns_", "check"), ("inst", f"i{i:03d}")]
            samples = [(float(i + j), now - 60_000 + j * 10_000)
                       for j in range(6)]
            series.append(remotepb.PromTimeSeries(labels, samples))
        payload = fsnappy.compress(remotepb.encode_write_request(series))
        st, _ = srv.api.handle("POST", "/api/v1/write", {}, payload)
        assert st == 204, f"remote_write drive got {st}"
        st, _ = srv.api.handle(
            "POST", "/influx/write", {},
            b"gw,_ws_=hy,_ns_=check,inst=i0 value=1.5\n")
        assert st in (204, 200), f"influx drive got {st}"
        q = {"query": "sum(hygiene_total)",
             "start": str(now // 1000 - 120), "end": str(now // 1000),
             "step": "15"}
        for _ in range(2):                      # cold + cached re-poll
            st, _ = srv.api.handle("GET", "/api/v1/query_range",
                                   dict(q), b"")
            assert st == 200, f"query drive got {st}"
        srv.api.handle("GET", "/api/v1/labels", {}, b"")
        for fmt in ({}, {"format": "openmetrics"}):
            st, _ = srv.api.handle("GET", "/metrics", dict(fmt), b"")
            assert st == 200
        # one registry self-snapshot (what the selfmon loop ingests)
        from filodb_tpu.utils.metrics import registry
        registry.snapshot_samples()
        srv.memstore.get_shard("prometheus", 0).flush_all_groups()
        # cold-tier drive: a closed window through compact -> upload ->
        # manifest swap, then a segment-loss restore — the
        # objectstore_* families must be live (and documented)
        import shutil as _shutil

        import numpy as np

        from filodb_tpu.core.partkey import PartKey
        from filodb_tpu.persist.objectstore import restore_from_objectstore
        from filodb_tpu.persist.segments import SegmentStore
        win = cfg.store.segment_window_ms
        t0 = (now - 4 * win) - ((now - 4 * win) % win)
        ts = t0 + np.arange(8, dtype=np.int64) * 60_000
        keys = [PartKey("hygiene_cold", (("inst", f"c{i}"), ("_ws_", "hy"),
                                         ("_ns_", "check")))
                for i in range(4)]
        sh = srv.memstore.get_shard("prometheus", 0)
        sh.ingest_columns("gauge", keys, np.broadcast_to(ts, (4, 8)),
                          {"value": np.ones((4, 8))})
        sh.flush_all_groups()
        srv.compaction_schedulers["prometheus"].run_once()
        seg_store = SegmentStore(disk_root)
        _shutil.rmtree(seg_store.seg_dir("prometheus", 0),
                       ignore_errors=True)
        restore_from_objectstore(srv.object_store, seg_store,
                                 "prometheus", 2)
    finally:
        srv.shutdown()
    # federation drive (ISSUE 20): one two-cluster pair, one pushed
    # federated aggregate, one probe round, and one query against a
    # dead cluster door — the federation_* families (dispatches,
    # wire_bytes, cluster_up, errors) must be live and documented
    from filodb_tpu.parallel.testcluster import make_federated_pair
    from filodb_tpu.query.rangevector import PlannerParams
    pair = make_federated_pair(num_series=4, num_samples=30, start=False)
    try:
        s0 = 1_600_000_020
        res = pair.engine.query_range("sum by (_ns_) (fed_gauge)",
                                      s0 + 60, 60, s0 + 240)
        assert res.error is None, f"federation drive: {res.error}"
        pair.east.federation_registry.probe_once()
        pair.kill_west()
        pair.engine.query_range(
            "sum by (_ns_) (fed_gauge)", s0 + 120, 60, s0 + 240,
            planner_params=PlannerParams(allow_partial_results=True,
                                         timeout_s=10.0))
        pair.east.federation_registry.probe_once()
    finally:
        pair.stop()
    from filodb_tpu.utils.metrics import registry
    return registry


def live_families(registry):
    """{(base_name, kind)} + the tag-name set, walked off the live
    registry."""
    fams = set()
    labels = set()
    with registry._lock:
        keys = ([(n, t, "counter") for (n, t) in registry._counters]
                + [(n, t, "gauge") for (n, t) in registry._gauges]
                + [(n, t, "histogram") for (n, t) in registry._hists])
    for name, tags, kind in keys:
        fams.add((name, kind))
        labels.update(k for k, _ in tags)
    return fams, labels


def exposed_names(name: str, kind: str):
    if kind == "counter":
        return [name + "_total"]
    if kind == "histogram":
        return [name + "_bucket", name + "_sum", name + "_count"]
    return [name]


def doc_table_names(doc_path: str = DOC_PATH):
    """Backticked first-column entries of the `## Metrics reference`
    table (globs allowed)."""
    try:
        with open(doc_path) as f:
            text = f.read()
    except OSError:
        return None
    if TABLE_HEADER not in text:
        return None
    section = text.split(TABLE_HEADER, 1)[1]
    # the table runs until the next heading
    section = re.split(r"\n## ", section, 1)[0]
    return set(re.findall(r"^\|\s*`([^`]+)`", section, re.MULTILINE))


def check(registry, doc_path: str = DOC_PATH):
    """Returns the violation list (empty = clean)."""
    fams, labels = live_families(registry)
    violations = []
    for name, kind in sorted(fams):
        if not NAME_RE.match(name):
            violations.append(f"illegal metric name: {name!r} ({kind})")
    for lab in sorted(labels):
        if not LABEL_RE.match(lab) or lab == "le":
            # `le` is the histogram exposition's reserved label
            violations.append(f"illegal/reserved label name: {lab!r}")
    # cross-kind collisions on EXPOSED sample names
    seen = {}
    for name, kind in sorted(fams):
        for exp in exposed_names(name, kind):
            prev = seen.get(exp)
            if prev is not None and prev != (name, kind):
                violations.append(
                    f"exposed-name collision: {exp!r} produced by both "
                    f"{prev[1]} {prev[0]!r} and {kind} {name!r}")
            seen[exp] = (name, kind)
    documented = doc_table_names(doc_path)
    if documented is None:
        violations.append(
            f"doc reference table missing: no {TABLE_HEADER!r} section "
            f"in {doc_path}")
        return violations
    for name, kind in sorted(fams):
        if not any(fnmatch.fnmatchcase(name, pat) for pat in documented):
            violations.append(
                f"undocumented metric: {kind} {name!r} absent from the "
                f"{TABLE_HEADER!r} table in doc/observability.md")
    return violations


def emit_table(registry) -> str:
    """A fresh markdown table skeleton off the live registry — the
    starting point when the doc drifts far behind."""
    fams, _ = live_families(registry)
    # collapse the per-span families into their documented globs
    rows = set()
    for name, kind in fams:
        if name.startswith("span_") and name.endswith("_seconds"):
            rows.add(("span_*_seconds", "histogram"))
        else:
            rows.add((name, kind))
    out = ["| metric | kind |", "|---|---|"]
    out += [f"| `{n}` | {k} |" for n, k in sorted(rows)]
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--doc", default=DOC_PATH)
    ap.add_argument("--emit-table", action="store_true",
                    help="print a fresh reference-table skeleton "
                         "instead of checking")
    args = ap.parse_args(argv)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    registry = boot_and_drive()
    if args.emit_table:
        print(emit_table(registry))
        return 0
    violations = check(registry, args.doc)
    if violations:
        for v in violations:
            print(f"check_metrics: {v}", file=sys.stderr)
        print(f"check_metrics: {len(violations)} violation(s)",
              file=sys.stderr)
        return 1
    fams, _ = live_families(registry)
    print(f"check_metrics: OK ({len(fams)} live metric families)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
