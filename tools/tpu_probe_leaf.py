#!/usr/bin/env python
"""Decompose the leaf-path per-query wall time at the bench shapes.

TPU_CHAIN_r05.json measured k1 ~80 ms at 1M (kernel dispatch + scalar
fetch) while the bench/engine path p50s ~95 ms — this tool attributes
the ~15 ms gap by timing four variants of the same query on device-
resident inputs:

  kernel_scalar   — _run dispatch, fetch a [1,1] slice (chain k1 twin)
  kernel_fetch    — _run dispatch, fetch the FULL padded [Gp, Wp] f32
  bench_path      — fused_rate_groupsum + present_sum exactly as
                    bench.run_pallas_fused does (lazy host slice, f64
                    cast, counts numpy, np.where)
  masked_finish   — one extra jit that slices [:G, :W] and NaN-masks on
                    DEVICE, then ONE f32 fetch + f64 cast host-side
                    (the proposed leaf finisher)

Writes TPU_PROBE_r05.json; refuses non-TPU backends.
"""
import functools
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(REPO, ".jax_cache"))
OUT = os.path.join(REPO, "TPU_PROBE_r05.json")
sys.path.insert(0, os.path.join(REPO, "tools"))
from tpu_chain import build, p50  # noqa: E402

DOC = {"utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())}


def persist():
    tmp = OUT + ".tmp"
    with open(tmp, "w") as f:
        json.dump(DOC, f, indent=1)
    os.replace(tmp, OUT)


def probe_shape(jax, jnp, name, S):
    from filodb_tpu.ops import pallas_fused as pf
    sec = {"series": S, "groups": 1000}
    DOC[name] = sec
    t0 = time.perf_counter()
    plan, prep, span, W = build(S)
    sec["windows"] = W
    sec["samples_scanned_per_query"] = span
    sec["host_prep_s"] = round(time.perf_counter() - t0, 2)
    persist()
    G, Gp = 1000, pf.pad_group_count(1000)
    gather = pf.gather_default("rate_family") and plan.idx1 is not None
    mats = pf._kernel_mats(plan, over_time=False, gather=gather)

    def run_raw():
        return pf._run(prep.vals_p, prep.vbase_p, prep.gids_p, *mats,
                       num_groups=Gp, is_counter=True, is_rate=True,
                       with_drops=False, interpret=False,
                       kind="rate_family", ragged=False,
                       per_series=False, gather=gather)

    # counts are snapshot-static: device mask once, like the leaf should
    wvalid_dev = jax.device_put(np.asarray(plan.wvalid, bool))
    gsize_dev = jax.device_put(
        (np.asarray(prep.gsize) > 0).astype(np.float32))

    @functools.partial(jax.jit, static_argnums=(3, 4))
    def finish_masked(res, wv, gs, g, w):
        s = res[:g, :w]
        mask = wv[None, :w] & (gs[:g, None] > 0)
        return jnp.where(mask, s, jnp.nan)

    def q_kernel_scalar():
        np.asarray(run_raw()[:1, :1])

    def q_kernel_fetch():
        np.asarray(run_raw())

    def q_bench_path():
        sums, counts = pf.fused_rate_groupsum(
            None, None, None, plan, G, "rate", True, prepared=prep)
        return pf.present_sum(sums, counts)

    def q_masked():
        out = finish_masked(run_raw(), wvalid_dev, gsize_dev, G, W)
        return np.asarray(out).astype(np.float64)

    # conformance first (also warms every compile)
    want = q_bench_path()
    got = q_masked()
    m = np.isfinite(want)
    assert (np.isnan(want) == np.isnan(got)).all()
    err = float(np.max(np.abs(want[m] - got[m])
                       / np.maximum(np.abs(want[m]), 1e-6))) if m.any() \
        else 0.0
    sec["masked_vs_bench_max_rel_err"] = err
    for nm, fn in (("kernel_scalar", q_kernel_scalar),
                   ("kernel_fetch", q_kernel_fetch),
                   ("bench_path", q_bench_path),
                   ("masked_finish", q_masked)):
        fn()
        sec[f"{nm}_p50_s"] = round(p50(fn), 5)
        persist()
    sec["fetch_cost_ms"] = round(
        (sec["kernel_fetch_p50_s"] - sec["kernel_scalar_p50_s"]) * 1e3, 2)
    sec["bench_overhead_ms"] = round(
        (sec["bench_path_p50_s"] - sec["kernel_scalar_p50_s"]) * 1e3, 2)
    sec["masked_overhead_ms"] = round(
        (sec["masked_finish_p50_s"] - sec["kernel_scalar_p50_s"]) * 1e3, 2)
    persist()


def main():
    os.environ.pop("JAX_PLATFORMS", None)
    import jax
    import jax.numpy as jnp
    plat = jax.devices()[0].platform
    if plat == "cpu":
        print("refusing: cpu backend")
        sys.exit(2)
    # record the REAL backend, like tools/tpu_chain.py — a GPU run must
    # not mislabel the artifact (the tunneled TPU registers as 'axon')
    DOC["platform"] = "tpu" if plat == "axon" else plat
    DOC["device"] = str(jax.devices()[0])
    for name, S in (("probe_262k", 262_144), ("probe_1m", 1_048_576)):
        probe_shape(jax, jnp, name, S)
    DOC["done"] = True
    persist()
    print(json.dumps({k: v for k, v in DOC.items() if k != "utc"})[:400])


if __name__ == "__main__":
    main()
