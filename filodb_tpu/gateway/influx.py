"""InfluxDB Line Protocol parser → record batches.

Re-implements the gateway's wire-format front-end (ref:
gateway/.../conversion/InfluxProtocolParser.scala:66-198,
InfluxRecord.scala:88-260) with the same semantics:

  - `measurement[,tag=v...] field=v[,field=v...] [timestamp_ns]`
  - backslash escapes for comma/space/equals; quoted string field values;
    `123i` integer suffix
  - nanosecond timestamps truncated to ms by dropping the last 6 digits
    (ref: InfluxProtocolParser.parseUnixTime)
  - ONE field → Prom single-value record; the schema is prom-counter when the
    field is named `counter`, else gauge (ref: InfluxPromSingleRecord:88-123)
  - MANY fields → histogram: field keys are bucket `le` tops (`+Inf`/`inf`),
    plus `sum` and `count`; the record is dropped unless a +Inf bucket exists
    (ref: InfluxHistogramRecord + HistogramFieldVisitor:171-252)
"""
from __future__ import annotations

import dataclasses
import math
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

from filodb_tpu.core.partkey import PartKey
from filodb_tpu.core.records import RecordBatch, RecordBatchBuilder
from filodb_tpu.core.schemas import Schemas, DEFAULT_SCHEMAS


@dataclasses.dataclass
class InfluxRecord:
    measurement: str
    tags: Dict[str, str]
    fields: Dict[str, object]      # str values stay str; numbers are float
    ts_ms: int


def _split_raw(s: str, delim: str, quoted: bool = False) -> List[str]:
    """Split on unescaped `delim`, KEEPING escape sequences intact — so a
    later split on a different delimiter still sees them escaped (the
    reference's parseInner tracks both delimiters in one pass,
    ref: InfluxProtocolParser.scala parseInner).  quoted=True additionally
    refuses to split inside double-quoted runs (field values)."""
    out, start, i, in_quote = [], 0, 0, False
    while i < len(s):
        ch = s[i]
        if ch == "\\" and i + 1 < len(s):
            i += 2
            continue
        if quoted and ch == '"':
            in_quote = not in_quote
            i += 1
            continue
        if ch == delim and not in_quote:
            out.append(s[start:i])
            start = i + 1
        i += 1
    out.append(s[start:])
    return out


def _parse_ts(ts_str: str) -> Optional[int]:
    """ns-epoch string -> ms, None when malformed (shared by both parse
    paths so validation can't drift between them).  The WHOLE string must
    be digits (one leading '-' allowed): int() alone would silently accept
    garbage in the truncated last-6 characters, '+', or '_' separators."""
    if len(ts_str) <= 6:
        return None
    body = ts_str[1:] if ts_str[0] == "-" else ts_str
    if not (body.isascii() and body.isdigit()):
        return None
    try:
        return int(ts_str[:-6])         # ns → ms: drop last 6 digits
    except ValueError:
        return None


def _unescape(s: str) -> str:
    if "\\" not in s:
        return s
    out, i = [], 0
    while i < len(s):
        if s[i] == "\\" and i + 1 < len(s):
            out.append(s[i + 1])
            i += 2
        else:
            out.append(s[i])
            i += 1
    return "".join(out)


def _split_top(s: str) -> List[str]:
    """Split line into ≤3 space-separated sections, honoring escapes and
    quoted strings."""
    out, cur, i, in_quote = [], [], 0, False
    while i < len(s):
        ch = s[i]
        # escapes are honored inside quotes too (so \" doesn't end the
        # quoted run) — must match _split_raw's escape-before-quote order
        if ch == "\\" and i + 1 < len(s):
            cur.append(s[i: i + 2])
            i += 2
            continue
        if ch == '"':
            in_quote = not in_quote
            cur.append(ch)
            i += 1
            continue
        if ch == " " and not in_quote:
            out.append("".join(cur))
            cur = []
            i += 1
            continue
        cur.append(ch)
        i += 1
    out.append("".join(cur))
    return [p for p in out if p != ""]


def _parse_field_value(v: str):
    if len(v) >= 2 and v[0] == '"' and v[-1] == '"':
        return v[1:-1]
    if not v:
        return ""
    if v.lower() in ("t", "true"):
        return 1.0
    if v.lower() in ("f", "false"):
        return 0.0
    body = v[:-1] if v[-1] in "iu" else v
    try:
        return float(body)
    except ValueError:
        return v


def _parse_fast(line: str, now_ms: Optional[int]) -> Optional[InfluxRecord]:
    """No-escape no-quote fast path: C-speed str.split does all delimiting.
    Correct exactly when the line contains no backslash and no quote —
    ~all real metric traffic; anything else takes the general parser."""
    sections = line.split(" ")
    if len(sections) < 2 or not sections[1]:
        return None
    head = sections[0].split(",")
    measurement = head[0]
    if not measurement:
        return None
    tags: Dict[str, str] = {}
    for kv in head[1:]:
        k, eq, v = kv.partition("=")
        if eq and k and "=" not in v:   # exactly one '=', like the general path
            tags[k] = v
    fields: Dict[str, object] = {}
    for kv in sections[1].split(","):
        k, eq, v = kv.partition("=")
        if eq and k and "=" not in v:
            fields[k] = _parse_field_value(v)
    if not fields:
        return None
    if len(sections) == 3:
        ts_ms = _parse_ts(sections[2])
        if ts_ms is None:
            return None
    else:
        ts_ms = now_ms if now_ms is not None else 0
    return InfluxRecord(measurement, tags, fields, ts_ms)


def parse_influx_line(line: str, now_ms: Optional[int] = None) -> Optional[InfluxRecord]:
    """Parse one line; returns None on malformed input (the reference logs and
    skips, ref: InfluxProtocolParser.parse:127-170)."""
    line = line.strip()
    if not line or line.startswith("#"):
        return None
    if "\\" not in line and '"' not in line and "  " not in line \
            and line.count(" ") <= 2:
        return _parse_fast(line, now_ms)
    sections = _split_top(line)
    if len(sections) < 2:
        return None
    head = _split_raw(sections[0], ",")
    measurement = _unescape(head[0])
    if not measurement:
        return None
    tags: Dict[str, str] = {}
    for kv in head[1:]:
        parts = _split_raw(kv, "=")
        if len(parts) == 2 and parts[0]:
            tags[_unescape(parts[0])] = _unescape(parts[1])
    fields: Dict[str, object] = {}
    for kv in _split_raw(sections[1], ",", quoted=True):
        parts = _split_raw(kv, "=", quoted=True)
        if len(parts) == 2 and parts[0]:
            fields[_unescape(parts[0])] = _parse_field_value(
                _unescape(parts[1]))
    if not fields:
        return None
    if len(sections) >= 3:
        ts_ms = _parse_ts(sections[2])
        if ts_ms is None:
            return None
    else:
        ts_ms = now_ms if now_ms is not None else 0
    return InfluxRecord(measurement, tags, fields, ts_ms)


_SPECIAL_HIST_KEYS = ("sum", "count")


def influx_lines_to_batches(lines: Iterable[str],
                            schemas: Schemas = DEFAULT_SCHEMAS,
                            now_ms: Optional[int] = None,
                            drops: Optional[Dict[str, int]] = None
                            ) -> List[RecordBatch]:
    """Convert parsed lines into per-schema RecordBatches (the gateway's
    InputRecord → RecordBuilder container step, ref: GatewayServer.scala:101-115).

    `drops` (optional dict) is bumped per drop REASON — the per-error
    visibility the reference's InfluxProtocolParser logs per line."""
    builders: Dict[str, RecordBatchBuilder] = {}
    hist_les: Optional[np.ndarray] = None

    def drop(reason: str) -> None:
        if drops is not None:
            drops[reason] = drops.get(reason, 0) + 1

    def builder(schema_name: str) -> RecordBatchBuilder:
        b = builders.get(schema_name)
        if b is None:
            b = RecordBatchBuilder(schemas[schema_name])
            builders[schema_name] = b
        return b

    for line in lines:
        rec = parse_influx_line(line, now_ms)
        if rec is None:
            s = line.strip()
            if s and not s.startswith("#"):
                drop("parse_error")
            continue
        numeric = {k: v for k, v in rec.fields.items() if isinstance(v, float)}
        if not numeric:
            drop("no_numeric_fields")
            continue
        pk = PartKey.make(rec.measurement, rec.tags)
        if len(numeric) == 1:
            (fname, fval), = numeric.items()
            schema_name = "prom-counter" if fname == "counter" else "gauge"
            col = schemas[schema_name].data_columns[0].name
            builder(schema_name).add(pk, rec.ts_ms, **{col: fval})
        else:
            # histogram: bucket tops + sum/count; +Inf required
            buckets: List[Tuple[float, float]] = []
            hsum = hcount = float("nan")
            got_inf = False
            for k, v in numeric.items():
                if k == "sum":
                    hsum = v
                elif k == "count":
                    hcount = v
                else:
                    try:
                        top = (math.inf if k in ("+Inf", "inf", "Inf")
                               else float(k))
                    except ValueError:
                        continue
                    got_inf = got_inf or math.isinf(top)
                    buckets.append((top, v))
            if not got_inf or not buckets:
                drop("histogram_missing_inf_bucket")
                continue
            buckets.sort(key=lambda bv: bv[0])
            les = np.asarray([b[0] for b in buckets])
            vals = np.asarray([b[1] for b in buckets])
            b = builder("prom-histogram")
            if b._les is None:
                b.set_bucket_les(les)
            elif len(b._les) != len(les) or not np.array_equal(b._les, les):
                drop("histogram_scheme_mismatch")
                continue                # one scheme per batch; drop outliers
            b.add(pk, rec.ts_ms, sum=hsum, count=hcount, h=vals)
    return [b.build() for b in builders.values()]
