"""Shared per-reason drop accounting for the gateway ingest paths
(VERDICT r2 weak #6: per-error visibility like the reference's
InfluxProtocolParser logging, not one silent counter)."""
from __future__ import annotations

import logging
import threading
import time
from typing import Dict

log = logging.getLogger("filodb.gateway")


class DropLog:
    """Accumulates drop counts by reason and emits a rate-limited warning
    whenever a flush carried drops.  Used by the synchronous
    GatewayPipeline and the decoupled KafkaContainerSink alike."""

    def __init__(self, log_interval_s: float = 5.0):
        self.totals: Dict[str, int] = {}
        self._interval = log_interval_s
        self._last_log = 0.0
        self._lock = threading.Lock()

    def record(self, drops: Dict[str, int]) -> None:
        if not drops:
            return
        with self._lock:
            for reason, n in drops.items():
                self.totals[reason] = self.totals.get(reason, 0) + n
            now = time.monotonic()
            emit = now - self._last_log > self._interval
            if emit:
                self._last_log = now
            totals = dict(self.totals)
        if emit:
            log.warning("gateway dropped lines: %s (totals: %s)",
                        drops, totals)
