"""Shared per-reason drop accounting for the gateway ingest paths
(VERDICT r2 weak #6: per-error visibility like the reference's
InfluxProtocolParser logging, not one silent counter)."""
from __future__ import annotations

import logging
import threading
import time
from typing import Dict

log = logging.getLogger("filodb.gateway")


class DropLog:
    """Accumulates drop counts by reason and emits a rate-limited warning
    whenever a flush carried drops.  Used by the synchronous
    GatewayPipeline and the decoupled KafkaContainerSink alike."""

    def __init__(self, log_interval_s: float = 5.0):
        self.totals: Dict[str, int] = {}
        self._interval = log_interval_s
        self._last_log = 0.0
        self._lock = threading.Lock()

    def record(self, drops: Dict[str, int]) -> None:
        if not drops:
            return
        with self._lock:
            for reason, n in drops.items():
                self.totals[reason] = self.totals.get(reason, 0) + n
            now = time.monotonic()
            emit = now - self._last_log > self._interval
            if emit:
                self._last_log = now
            totals = dict(self.totals)
        if emit:
            log.warning("gateway dropped lines: %s (totals: %s)",
                        drops, totals)


def admit_batch(batch, ingest_limit: int, drops: Dict[str, int]):
    """Per-tenant ingest admission for a parsed RecordBatch — the Influx
    doors' parity with the remote_write front door's 429 gate (one
    admission ledger, utils/usage.admit_ingest, no door bypasses it).

    Returns (admitted batch or None, retry_after seconds or None).  The
    Influx TCP gateway has no reply channel, so a rejected tenant's
    records are dropped WITH accounting (`tenant_limit_exceeded` in the
    drop log + the tenant_ingest_rejections counter); the HTTP /influx
    endpoint surfaces retry_after as 429 + Retry-After when everything
    bounced.  Mixed-tenant batches keep the admitted tenants' records."""
    import numpy as np

    from filodb_tpu.utils.usage import usage
    if not ingest_limit or batch.num_records == 0:
        return batch, None
    tenants = [(pk.tags_dict.get("_ws_", ""), pk.tags_dict.get("_ns_", ""))
               for pk in batch.part_keys]
    per_key = np.bincount(batch.part_idx, minlength=len(batch.part_keys))
    offered: Dict[tuple, int] = {}
    for i, t in enumerate(tenants):
        offered[t] = offered.get(t, 0) + int(per_key[i])
    rejected = {}
    retry_after = None
    for t, n in offered.items():
        ra = usage.admit_ingest(t[0], t[1], n, ingest_limit)
        if ra is not None:
            rejected[t] = n
            retry_after = max(retry_after or 0.0, ra)
    if not rejected:
        return batch, None
    drops["tenant_limit_exceeded"] = \
        drops.get("tenant_limit_exceeded", 0) + sum(rejected.values())
    if len(rejected) == len(offered):
        return None, retry_after
    keep_key = np.asarray([t not in rejected for t in tenants])
    keep = keep_key[batch.part_idx]
    from filodb_tpu.core.records import RecordBatch
    return RecordBatch(batch.schema, batch.part_keys,
                       batch.part_idx[keep], batch.timestamps[keep],
                       {k: v[keep] for k, v in batch.columns.items()},
                       batch.bucket_les), retry_after
