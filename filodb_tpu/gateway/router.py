"""Gateway shard routing: batch → per-shard sub-batches → shard ingest.

The reference gateway computes `shardMapper.ingestionShard(shardKeyHash,
partitionHash, spread)` per record and publishes each container to its
shard's Kafka partition (ref: gateway/.../GatewayServer.scala:101-115,
coordinator/.../ShardMapper.scala:108-120).  Here routing produces per-shard
RecordBatches handed to local shards or serialized for a remote transport.
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional

import numpy as np

from filodb_tpu.core.records import RecordBatch
from filodb_tpu.core.schemas import Schemas, DEFAULT_SCHEMAS
from filodb_tpu.parallel.shardmapper import ShardMapper, SpreadProvider


def split_batch_by_shard(batch: RecordBatch, mapper: ShardMapper,
                         spread_provider: SpreadProvider) -> Dict[int, RecordBatch]:
    """Route each record to its shard via the spread math
    (ref: ShardMapper.ingestionShard:108-120)."""
    if batch.num_records == 0:
        return {}
    shard_of_key = np.asarray([
        mapper.ingestion_shard(
            pk.shard_key_hash(), pk.partition_hash(),
            spread_provider.spread_for(pk.shard_key()))
        for pk in batch.part_keys])
    rec_shards = shard_of_key[batch.part_idx]
    out: Dict[int, RecordBatch] = {}
    for s in np.unique(rec_shards).tolist():
        keep = rec_shards == s
        out[s] = RecordBatch(batch.schema, batch.part_keys,
                             batch.part_idx[keep], batch.timestamps[keep],
                             {k: v[keep] for k, v in batch.columns.items()},
                             batch.bucket_les)
    return out


class GatewayPipeline:
    """Influx lines → parsed batches → shard-routed ingest
    (the GatewayServer data path minus the TCP listener, which lives in
    filodb_tpu/http; ref: GatewayServer.scala:58-115)."""

    def __init__(self, memstore, dataset: str, mapper: ShardMapper,
                 spread_provider: Optional[SpreadProvider] = None,
                 schemas: Schemas = DEFAULT_SCHEMAS,
                 config=None):
        self.memstore = memstore
        self.dataset = dataset
        self.mapper = mapper
        self.spread = spread_provider or SpreadProvider(0)
        self.schemas = schemas
        self.lines_dropped = 0
        # per-tenant ingest admission parity with the remote_write front
        # door: no door bypasses the limits (utils/usage.admit_ingest)
        if config is None:
            from filodb_tpu.config import settings
            config = settings()
        self.ingest_limit = config.query.tenant_ingest_samples_limit
        # WAL manager when this dataset is durability-fronted (attached
        # by FiloServer; the remote_write sink built over this pipeline
        # reads it)
        self.wal = None
        # per-reason drop accounting + rate-limited warn (VERDICT r2
        # weak #6), shared with the decoupled sink (gateway/accounting.py)
        from filodb_tpu.gateway.accounting import DropLog
        self._drop_log = DropLog()
        # per-THREAD retry hint: the pipeline is shared across HTTP
        # handler threads, and instance-level state would let tenant A's
        # all-rejected call read tenant B's reset (silent drop where the
        # contract promises a 429) or vice versa
        import threading
        self._tls = threading.local()

    @property
    def last_retry_after(self):
        """Retry-After seconds when THIS thread's last ingest_lines call
        rejected records, else None."""
        return getattr(self._tls, "retry_after", None)

    @property
    def drops(self) -> Dict[str, int]:
        return self._drop_log.totals

    def ingest_lines(self, lines: Iterable[str],
                     now_ms: Optional[int] = None,
                     offset: int = -1):
        """Returns samples ingested.  Over-limit tenants' records drop
        with accounting; `last_retry_after` carries the window-roll hint
        for callers with a reply channel (the /influx HTTP endpoint
        turns an everything-rejected call into 429 + Retry-After)."""
        from filodb_tpu.gateway.accounting import admit_batch
        from filodb_tpu.gateway.influx import influx_lines_to_batches
        lines = list(lines)
        drops: Dict[str, int] = {}
        batches = influx_lines_to_batches(lines, self.schemas, now_ms,
                                          drops=drops)
        n = 0
        got = 0
        self._tls.retry_after = None
        for batch in batches:
            got += batch.num_records
            batch, retry_after = admit_batch(batch, self.ingest_limit,
                                             drops)
            if retry_after is not None:
                self._tls.retry_after = retry_after
            if batch is None:
                continue
            for shard_num, sub in split_batch_by_shard(
                    batch, self.mapper, self.spread).items():
                shard = self.memstore.get_shard(self.dataset, shard_num)
                if shard is not None:
                    n += shard.ingest(sub, offset)
        self.lines_dropped += len(lines) - got
        self._drop_log.record(drops)
        return n
