"""Standalone gateway server: TCP Influx listener → per-shard broker sink.

The reference's ingest backbone decouples the gateway from the DB nodes:
a Netty TCP server parses Influx lines, builds record containers, and
PUBLISHES each to its shard's Kafka partition; nodes consume their
partition and checkpoint offsets (ref:
gateway/src/main/scala/filodb/gateway/GatewayServer.scala:58-115,
gateway/.../KafkaContainerSink.scala:24-69).  This module reproduces that
as its own OS process:

    influx client --TCP--> GatewayServer --produce--> broker partition[s]
                                                        |
    node ingestion stream  <--consume/offset-checkpoint-+

Run it:  python -m filodb_tpu.gateway.server --broker-dir /var/filodb/broker
         python -m filodb_tpu.gateway.server --bootstrap-servers k1:9092

The broker is either the durable local append-log
(ingest/filebroker.FileBackedBroker — the local-disk Kafka analogue, see
its module docstring) or a real Kafka cluster via kafka-python.
"""
from __future__ import annotations

import argparse
import logging
import socket
import socketserver
import sys
import threading
import time
from typing import Callable, Dict, Iterable, Optional

from filodb_tpu.core.records import RecordBatch
from filodb_tpu.core.schemas import Schemas, DEFAULT_SCHEMAS
from filodb_tpu.gateway.accounting import DropLog
from filodb_tpu.gateway.influx import influx_lines_to_batches
from filodb_tpu.gateway.router import split_batch_by_shard
from filodb_tpu.parallel.shardmapper import ShardMapper, SpreadProvider

log = logging.getLogger("filodb.gateway")


class KafkaContainerSink:
    """Publish per-shard RecordBatch frames to broker partitions
    (ref: KafkaContainerSink.scala:24-69 — container → partition=shard).

    `produce(topic, partition, bytes) -> offset` is the only broker
    contract; FileBackedBroker and a kafka-python producer both satisfy
    it.  Drop accounting is per REASON and logged (rate-limited), not a
    single silent counter (VERDICT r2 weak #6)."""

    def __init__(self, produce: Callable[[str, int, bytes], int],
                 topic: str, mapper: ShardMapper,
                 spread_provider: Optional[SpreadProvider] = None,
                 schemas: Schemas = DEFAULT_SCHEMAS,
                 config=None):
        self.produce = produce
        self.topic = topic
        self.mapper = mapper
        self.spread = spread_provider or SpreadProvider(0)
        self.schemas = schemas
        # per-tenant ingest admission parity with the remote_write front
        # door (utils/usage.admit_ingest): the TCP gateway has no reply
        # channel, so over-limit tenants' records drop WITH accounting —
        # `tenant_limit_exceeded` in the drop log plus the
        # tenant_ingest_rejections counter — never silently
        if config is None:
            from filodb_tpu.config import settings
            config = settings()
        self.ingest_limit = config.query.tenant_ingest_samples_limit
        self.lines_in = 0
        self.records_out = 0
        self.frames_out = 0
        self._drop_log = DropLog()
        self._lock = threading.Lock()

    def publish_lines(self, lines: Iterable[str],
                      now_ms: Optional[int] = None) -> int:
        """Parse, route, and publish; returns records published.  The
        TCP door has no headers to carry a traceparent, so each flush
        batch runs under a MINTED write-path trace id (doc/
        observability.md): the parse/route/produce spans land in the
        trace ring and slow batches in /admin/ingestlog like the HTTP
        doors."""
        from filodb_tpu.utils.freshness import DoorTrace
        from filodb_tpu.utils.metrics import span
        from filodb_tpu.gateway.accounting import admit_batch
        lines = list(lines)
        door = DoorTrace("gateway", self.topic,
                         body_bytes=sum(len(ln) for ln in lines))
        published = 0
        with door, span("gateway_publish"):
            drops: Dict[str, int] = {}
            batches = influx_lines_to_batches(lines, self.schemas, now_ms,
                                              drops=drops)
            for batch in batches:
                batch, _retry = admit_batch(batch, self.ingest_limit,
                                            drops)
                if batch is None:
                    continue
                for shard_num, sub in split_batch_by_shard(
                        batch, self.mapper, self.spread).items():
                    self.produce(self.topic, shard_num, sub.to_bytes())
                    published += sub.num_records
                    with self._lock:
                        self.frames_out += 1
        with self._lock:
            self.lines_in += len(lines)
            self.records_out += published
        self._drop_log.record(drops)
        door.stats.series = len(lines)
        door.stats.samples = door.stats.ingested = published
        door.finish()
        return published

    @property
    def drops(self) -> Dict[str, int]:
        return self._drop_log.totals

    def stats(self) -> Dict[str, object]:
        with self._lock:
            return {"lines_in": self.lines_in,
                    "records_out": self.records_out,
                    "frames_out": self.frames_out,
                    "drops": dict(self._drop_log.totals)}


class GatewayServer:
    """Threaded TCP server speaking newline-delimited Influx line protocol
    (the reference's Netty pipeline: delimiter-framed UTF-8 lines,
    ref: GatewayServer.scala:139-155).  Lines buffer per connection and
    flush to the sink every `batch_lines` or on connection close."""

    def __init__(self, sink: KafkaContainerSink, host: str = "127.0.0.1",
                 port: int = 8007, batch_lines: int = 512):
        self.sink = sink
        outer = self

        max_line = 1 << 20               # the Netty pipeline's frame cap

        class _Handler(socketserver.StreamRequestHandler):
            def handle(self):
                buf = []
                skipping = False
                while True:
                    raw = self.rfile.readline(max_line)
                    if not raw:
                        break
                    if not raw.endswith(b"\n") and len(raw) >= max_line:
                        # oversized frame: account it once, then discard
                        # up to the next newline instead of buffering GBs
                        if not skipping:
                            outer.sink._drop_log.record(
                                {"line_too_long": 1})
                        skipping = True
                        continue
                    if skipping:
                        skipping = False
                        continue         # tail of the oversized line
                    buf.append(raw.decode("utf-8", "replace"))
                    if len(buf) >= batch_lines:
                        outer.sink.publish_lines(buf)
                        buf = []
                if buf:
                    outer.sink.publish_lines(buf)

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _Server((host, port), _Handler)
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="gateway-accept", daemon=True)
        self._thread.start()
        log.info("gateway listening on :%d", self.port)

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


def send_lines(host: str, port: int, lines: Iterable[str]) -> None:
    """Minimal client: ship lines to a gateway over one TCP connection."""
    with socket.create_connection((host, port)) as s:
        payload = "".join(line.rstrip("\n") + "\n" for line in lines)
        s.sendall(payload.encode("utf-8"))


def build_sink(args, schemas: Schemas = DEFAULT_SCHEMAS
               ) -> KafkaContainerSink:
    mapper = ShardMapper(args.num_shards)
    spread = SpreadProvider(args.spread)
    if args.broker_dir:
        from filodb_tpu.ingest.filebroker import FileBackedBroker
        broker = FileBackedBroker(args.broker_dir, fsync=args.fsync)
        produce = broker.produce
    else:
        try:
            from kafka import KafkaProducer  # type: ignore
        except ImportError as e:
            raise SystemExit(
                "kafka-python is not installed; use --broker-dir for the "
                "local append-log broker") from e
        producer = KafkaProducer(bootstrap_servers=args.bootstrap_servers)

        def produce(topic: str, partition: int, value: bytes) -> int:
            md = producer.send(topic, value=value,
                               partition=partition).get(timeout=30)
            return md.offset
    return KafkaContainerSink(produce, args.topic, mapper, spread, schemas)


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(
        description="FiloDB-TPU gateway server (Influx TCP -> broker)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8007,
                    help="TCP Influx listener port (0 = ephemeral)")
    ap.add_argument("--topic", default="timeseries")
    ap.add_argument("--num-shards", type=int, default=4)
    ap.add_argument("--spread", type=int, default=0)
    ap.add_argument("--broker-dir", default="",
                    help="local append-log broker directory (no Kafka)")
    ap.add_argument("--fsync", action="store_true",
                    help="fsync the broker log on every frame")
    ap.add_argument("--bootstrap-servers", default="localhost:9092")
    ap.add_argument("--stats-interval", type=float, default=0.0,
                    help="print sink stats every N seconds (0 = off)")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    sink = build_sink(args)
    server = GatewayServer(sink, args.host, args.port)
    server.start()
    # announce the bound port on stdout so callers (and tests) that asked
    # for an ephemeral port can discover it
    print(f"GATEWAY_READY port={server.port}", flush=True)
    try:
        while True:
            time.sleep(args.stats_interval or 3600)
            if args.stats_interval:
                print(f"GATEWAY_STATS {sink.stats()}", flush=True)
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()


if __name__ == "__main__":
    main(sys.argv[1:])
