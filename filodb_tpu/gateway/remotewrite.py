"""Prometheus remote_write ingest sink: protobuf series → columnar slabs.

The planet-scale ingest protocol (the Cortex / Thanos-receive front
door; FiloDB's gateway+Kafka layer in spirit, PAPER.md §1): snappy-
compressed protobuf WriteRequests arrive at POST /api/v1/write
(http/routes.py), decode via the shared prompb codec table
(http/remotepb.py), and land here.  This sink's job is SHAPE: a request
is a ragged bag of series with per-series sample lists, and the shard
wants rectangular [S, k] grids (`TimeSeriesShard.ingest_columns`) — so
series are grouped by (shard, sample-count) into RecordBatch.from_grid-
shaped slabs and appended as whole matrices, never per-sample Python
loops through the store.

Durability: with a WAL attached (wal/WalManager), every slab is
appended to the log first and the whole request waits for ONE group
commit before any ack — a crash after the 2xx replays the same slabs
through the same ingest_columns path on restart.

Backpressure: the caller (routes.py) admits the request through
usage.admit_ingest BEFORE decode work is spent on slab-building; over
the per-tenant limit the request bounces with 429 + Retry-After, never
a silent drop.
"""
from __future__ import annotations

import logging
from typing import Dict, List, Optional, Tuple

import numpy as np

from filodb_tpu.core.partkey import PartKey
from filodb_tpu.core.schemas import Schemas, DEFAULT_SCHEMAS
from filodb_tpu.parallel.shardmapper import ShardMapper, SpreadProvider
from filodb_tpu.utils.metrics import registry as metrics_registry
from filodb_tpu.utils.metrics import span as metrics_span

log = logging.getLogger("filodb.remotewrite")

SCHEMA = "gauge"          # remote_write samples are untyped doubles; the
                          # gauge schema is the Prometheus-wire-compatible
                          # landing shape (counters still rate() correctly:
                          # correction happens at query time)


class RemoteWriteSink:
    """series (decoded prompb TimeSeries) → WAL → shard-routed columnar
    ingest.  One instance per dataset, shared across HTTP handler
    threads (stateless apart from counters; shard ingest serializes
    internally)."""

    def __init__(self, memstore, dataset: str,
                 mapper: Optional[ShardMapper] = None,
                 spread_provider: Optional[SpreadProvider] = None,
                 schemas: Schemas = DEFAULT_SCHEMAS, wal=None,
                 replicator=None):
        self.memstore = memstore
        self.dataset = dataset
        self.mapper = mapper
        self.spread = spread_provider or SpreadProvider(0)
        self.schemas = schemas
        self.wal = wal
        # replication fan-out (replication/replicator.py): every slab
        # additionally ships to the shard's other owners; a shard NOT
        # locally owned routes entirely through the fan-out (distributor
        # mode) and the ack requires at least its primary's append
        self.replicator = replicator

    # ------------------------------------------------------------- ingest

    def ingest_series(self, series, stats=None) -> Tuple[int, int]:
        """Ingest decoded remotepb.PromTimeSeries; returns (samples
        ingested, samples dropped by the store — OOO/dup/quota).  Raises
        WalWriteError when durability cannot be claimed (the route turns
        it into a 503: the client must retry, the data was NOT acked).

        `stats` (utils/freshness.IngestStats, optional) is filled with
        the batch's per-stage breakdown — slab build, WAL append,
        group-commit fsync wait, replication fan-out, memstore ingest —
        plus slab/shard counts and per-tenant newest sample timestamps;
        the door feeds it to the ingest slowlog and the freshness
        histograms.  Every stage runs under the caller's trace context,
        so the spans stitch into one write-path trace."""
        import time as _time
        t0 = _time.perf_counter()
        with metrics_span("rw_build_slabs", dataset=self.dataset):
            slabs = self._build_slabs(series, stats=stats)
        t_slabs = _time.perf_counter()
        n = dropped = 0
        # WAL appends go first WITHOUT waiting: the committer thread's
        # flush+fsync overlaps the in-memory ingest below (both release
        # the GIL), and ONE group-commit wait at the end covers every
        # slab — the ack is still strictly after durability, and a crash
        # in between leaves only unacknowledged in-memory samples the
        # client will re-send
        last_seq = -1
        seqs = []
        if self.wal is not None:
            for shard_num, keys, ts, vals in slabs:
                last_seq = self.wal.append_grid(
                    shard_num, SCHEMA, keys, ts, {"value": vals},
                    wait=False)
                seqs.append(last_seq)
        t_wal = _time.perf_counter()
        repl_s = 0.0
        for i, (shard_num, keys, ts, vals) in enumerate(slabs):
            shard = self.memstore.get_shard(self.dataset, shard_num)
            offset = seqs[i] if self.wal is not None else -1
            if shard is not None:
                got = shard.ingest_columns(SCHEMA, keys, ts,
                                           {"value": vals}, offset=offset)
                n += got
                dropped += ts.size - got
            elif self.replicator is None:
                raise ConnectionError(
                    f"remote_write: shard {shard_num} of "
                    f"{self.dataset!r} is not locally owned")
            # replication fan-out: the slab ships to every OTHER owner
            # of the shard.  Locally-owned shards ack on local WAL
            # durability (replica failures degrade to lag + catch-up);
            # a shard owned elsewhere must land on at least one owner
            # (require_primary) or the request bounces un-acked
            if self.replicator is not None:
                tr = _time.perf_counter()
                res = self.replicator.replicate(
                    shard_num, SCHEMA, keys, ts, {"value": vals},
                    seq=offset, require_primary=shard is None)
                repl_s += _time.perf_counter() - tr
                if shard is None:
                    # account what the shard's OWNER actually ingested
                    # (its OOO/dup drops count as drops here, exactly
                    # like the locally-owned path); fall back to any
                    # acking owner when the primary's ack was missing
                    primary = self.mapper.node_for_shard(shard_num) \
                        if self.mapper is not None else None
                    got = res.ingested.get(primary) if primary else None
                    if got is None and res.ingested:
                        got = max(res.ingested.values())
                    got = int(got or 0)
                    n += got
                    dropped += int(ts.size) - got
        t_ingest = _time.perf_counter()
        if last_seq >= 0:
            self.wal.commit(last_seq)
        t_commit = _time.perf_counter()
        metrics_registry.counter("remote_write_samples",
                                 dataset=self.dataset).increment(n)
        if stats is not None:
            stats.dataset = stats.dataset or self.dataset
            stats.slabs = len(slabs)
            stats.shards = sorted({s for s, *_ in slabs})
            stats.ingested += n
            stats.dropped += dropped
            stats.build_slabs_s += t_slabs - t0
            stats.wal_append_s += t_wal - t_slabs
            # the fan-out ran interleaved with the local ingest loop:
            # split the loop's wall into its replication share and the
            # memstore remainder
            stats.replication_s += repl_s
            stats.ingest_s += max(t_ingest - t_wal - repl_s, 0.0)
            stats.wal_commit_wait_s += t_commit - t_ingest
        return n, dropped

    # -------------------------------------------------------- slab build

    def _build_slabs(self, series, stats=None
                     ) -> List[Tuple[int, List[PartKey], np.ndarray,
                                     np.ndarray]]:
        """Group the request's series into rectangular (shard, keys,
        ts [S, k], values [S, k]) slabs: one per (shard, sample-count)
        pair, matching RecordBatch.from_grid's grid contract.  A scrape
        push's natural shape — every series carrying the same k samples
        — collapses to one slab per shard.  With `stats`, the per-tenant
        newest sample timestamp is tracked in the same pass (the
        ingest-to-queryable freshness input — zero extra iteration)."""
        part_schema = self.schemas.part
        newest = stats.newest_ts_ms if stats is not None else None
        by_group: Dict[Tuple[int, int], List[Tuple[PartKey, list]]] = {}
        for ts_msg in series:
            if not ts_msg.samples:
                continue
            labels = dict(ts_msg.labels)
            metric = labels.pop("__name__", "") or "_unnamed_"
            if newest is not None:
                ws = labels.get("_ws_", "")
                ts_max = int(max(t for _, t in ts_msg.samples))
                if ts_max > newest.get(ws, -1):
                    newest[ws] = ts_max
            pk = PartKey.make(metric, labels, part_schema)
            if self.mapper is not None:
                shard_num = self.mapper.ingestion_shard(
                    pk.shard_key_hash(), pk.partition_hash(),
                    self.spread.spread_for(pk.shard_key()))
            else:
                shard_num = 0
            by_group.setdefault((shard_num, len(ts_msg.samples)),
                                []).append((pk, ts_msg.samples))
        slabs = []
        for (shard_num, k), rows in by_group.items():
            keys = [pk for pk, _ in rows]
            # one [S, k, 2] pass over the decoded tuples, then split —
            # the only per-sample cost is the protobuf decode itself
            mat = np.asarray([samples for _, samples in rows],
                             dtype=np.float64)          # [S, k, 2]
            vals = np.ascontiguousarray(mat[:, :, 0])
            ts = np.ascontiguousarray(mat[:, :, 1]).astype(np.int64)
            slabs.append((shard_num, keys, ts, vals))
        return slabs


def admit_series(series, header_org: Optional[str], limit: int):
    """Per-tenant ingest admission for a WriteRequest — the same ledger
    (`usage.admit_ingest`) every other door runs.

    Returns (admitted_series, retry_after_or_None, rejected_samples).
    With an X-Scope-OrgID header ("ws" or "ws/ns", the Cortex
    convention) the WHOLE request is one tenant.  Otherwise EVERY series
    is admitted under its own `_ws_`/`_ns_` labels — admission keyed off
    one representative series would let an over-limit tenant smuggle
    samples behind a foreign first series.  Mixed requests keep the
    admitted tenants' series; the caller still answers 429 when anything
    was rejected (a resend's admitted-tenant duplicates drop in store
    dedup, so nothing is lost OR double-counted in the store)."""
    from filodb_tpu.utils.usage import usage
    if not limit:
        return list(series), None, 0
    if header_org:
        ws, _, ns = header_org.partition("/")
        n = count_samples(series)
        ra = usage.admit_ingest(ws, ns, n, limit)
        return (list(series), None, 0) if ra is None else ([], ra, n)
    groups: Dict[Tuple[str, str], list] = {}
    for ts_msg in series:
        labels = dict(ts_msg.labels)
        tenant = (labels.get("_ws_", ""), labels.get("_ns_", ""))
        g = groups.setdefault(tenant, [[], 0])
        g[0].append(ts_msg)
        g[1] += len(ts_msg.samples)
    admitted: list = []
    retry_after = None
    rejected = 0
    for (ws, ns), (ser, n) in groups.items():
        ra = usage.admit_ingest(ws, ns, n, limit)
        if ra is None:
            admitted.extend(ser)
        else:
            rejected += n
            retry_after = max(retry_after or 0.0, ra)
    return admitted, retry_after, rejected


def count_samples(series) -> int:
    return sum(len(ts_msg.samples) for ts_msg in series)
