from filodb_tpu.gateway.influx import (InfluxRecord, parse_influx_line,
                                       influx_lines_to_batches)
from filodb_tpu.gateway.router import split_batch_by_shard, GatewayPipeline

__all__ = ["InfluxRecord", "parse_influx_line", "influx_lines_to_batches",
           "split_batch_by_shard", "GatewayPipeline"]
