"""Ingest fan-out — the distributor half of the replication layer.

Every columnar slab is shipped to ALL live owners of its shard (the
ShardMapper's ordered assignment list), encoded ONCE as a WalRecord body
(the WAL's own wire format) and appended through each peer's replication
door (service.py).  Ack semantics (`replication.ack_mode`):

  primary  the caller's own primary-durability claim (local WAL commit,
           or the first owner's ack in distributor mode) is the ack;
           replica appends ride an ordered per-peer async queue with lag
           tracked — catch-up (catchup.py) repairs anything dropped.
  quorum   primary-durable AND every LIVE replica acked before the call
           returns.  A replica that fails its append is marked lagging
           (journal `replica_lagging`, skipped until it acks again) so
           one corpse cannot wedge ingest — availability through a
           replica death, durability repaired by catch-up.

Per-replica lag is observable three ways: the `replica_lag_records`
gauge, `replica_lagging` / `replica_caught_up` journal events (edge-
triggered, never flooding), and the /admin/shards table.
"""
from __future__ import annotations

import dataclasses
import logging
import queue
import threading
from typing import Callable, Dict, List, Optional, Tuple

import time

from filodb_tpu.utils.events import journal
from filodb_tpu.utils.metrics import (collector, current_trace_id,
                                      registry as metrics_registry,
                                      span as metrics_span)
from filodb_tpu.wal.segment import WalRecord

_log = logging.getLogger("filodb.replication")


class ReplicationSendError(IOError):
    """No owner of the shard acknowledged the slab — nothing durable."""


# a lagging replica gets one real append attempt per this many slabs (a
# cheap liveness probe); the rest are skipped and left to catch-up
_LAG_PROBE_EVERY = 16


def _restitch_spans(trace, reply) -> None:
    """Re-record the replica-side span events that rode back in the ack
    (service.py drains them per reply, like the query transport) so the
    coordinator's collector holds ONE stitched write-path trace."""
    if not trace:
        return
    for ev in reply.get("spans") or ():
        if isinstance(ev, dict):
            collector.record(trace, ev)


@dataclasses.dataclass
class ReplicateResult:
    """One slab's fan-out outcome."""
    shard: int
    acked: List[str] = dataclasses.field(default_factory=list)
    failed: List[Tuple[str, str]] = dataclasses.field(default_factory=list)
    queued: List[str] = dataclasses.field(default_factory=list)
    # per-acking-node samples actually ingested (the peer's
    # OOO/dup/quota drops subtract here; buffered-behind-a-restore
    # appends report 0 until the window drains)
    ingested: Dict[str, int] = dataclasses.field(default_factory=dict)

    @property
    def ack_count(self) -> int:
        return len(self.acked)


class _PeerState:
    """Per-peer replication bookkeeping: ordered async queue (primary
    ack mode), pending-record lag, and the lagging edge detector."""

    def __init__(self, node: str, client, dataset: str,
                 lag_threshold: int, queue_max: int):
        self.node = node
        self.client = client
        self.dataset = dataset
        self.lag_threshold = max(int(lag_threshold), 1)
        self.lock = threading.Lock()
        self.sent = 0
        self.acked = 0
        self.failed = 0
        self.skipped = 0
        self.lagging = False
        # records this peer's copy is MISSING (failed + skipped since it
        # last held everything): a probe ack drains `pending` but cannot
        # un-lose these — only a catch-up (mark_repaired) clears them,
        # so `lagging` never self-clears into a silently-short replica
        self.lost = 0
        self.last_error = ""
        # unix time this peer's copy FIRST fell behind (pending or lost
        # records outstanding); 0 = fully caught up.  Exported as the
        # replica_lag_seconds gauge — the newest-unreplicated-record AGE
        # complementing the records-count gauge (a replica 10 records
        # behind for an hour is a worse story than 1000 behind for 2 s).
        self.behind_since = 0.0
        self.q: "queue.Queue" = queue.Queue(maxsize=max(queue_max, 1))
        # manager hook fired once at the ok->lagging edge (demotes the
        # peer's replica copies out of the query-ready set)
        self.on_lagging = None
        self._worker: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ------------------------------------------------------------- lag

    @property
    def pending(self) -> int:
        """Records this manager still owes the peer: queued + in-flight.
        Failed appends are NOT pending (they will never ack from here —
        catch-up repairs them; `failed` counts them separately)."""
        with self.lock:
            return self.pending_locked()

    def _export_lag(self) -> None:
        with self.lock:
            behind = self.pending_locked() > 0 or self.lost > 0
            if behind and not self.behind_since:
                self.behind_since = time.time()
            elif not behind:
                self.behind_since = 0.0
            since = self.behind_since
            pending = self.pending_locked()
        metrics_registry.gauge("replica_lag_records", dataset=self.dataset,
                               peer=self.node).update(pending)
        metrics_registry.gauge("replica_lag_seconds", dataset=self.dataset,
                               peer=self.node).update(
            max(time.time() - since, 0.0) if since else 0.0)

    def note_ack(self) -> None:
        with self.lock:
            self.acked += 1
            was = self.lagging
            # a probe ack alone never clears the lag: records already
            # failed/skipped exist only on other owners until a
            # catch-up repairs this peer (mark_repaired)
            if self.lagging and self.lost == 0 \
                    and self.pending_locked() < self.lag_threshold:
                self.lagging = False
        self._export_lag()
        if was and not self.lagging:
            journal.emit("replica_caught_up", subsystem="replication",
                         dataset=self.dataset, peer=self.node)

    def note_repaired(self) -> None:
        """A catch-up completed for this peer: its copy holds everything
        again — clear the lag and the lost-record debt."""
        with self.lock:
            was = self.lagging
            self.lost = 0
            self.lagging = False
        self._export_lag()
        if was:
            journal.emit("replica_caught_up", subsystem="replication",
                         dataset=self.dataset, peer=self.node,
                         repaired=True)

    def pending_locked(self) -> int:
        return max(self.sent - self.acked - self.failed, 0) + self.q.qsize()

    def note_failure(self, err: str) -> None:
        with self.lock:
            self.failed += 1
            self.lost += 1
            self.last_error = str(err)[:300]
            newly = not self.lagging
            self.lagging = True
        metrics_registry.counter("replication_append_failures",
                                 dataset=self.dataset,
                                 peer=self.node).increment()
        self._export_lag()
        if newly:
            journal.emit("replica_lagging", subsystem="replication",
                         dataset=self.dataset, peer=self.node,
                         error=str(err)[:200])
            if self.on_lagging is not None:
                self.on_lagging(self.node)

    def note_overflow(self) -> None:
        with self.lock:
            self.lost += 1
            newly = not self.lagging
            self.lagging = True
        metrics_registry.counter("replication_queue_overflow",
                                 dataset=self.dataset,
                                 peer=self.node).increment()
        if newly:
            journal.emit("replica_lagging", subsystem="replication",
                         dataset=self.dataset, peer=self.node,
                         error="send queue overflow")
            if self.on_lagging is not None:
                self.on_lagging(self.node)

    # ----------------------------------------------------------- worker

    def ensure_worker(self) -> None:
        if self._worker is None or not self._worker.is_alive():
            self._worker = threading.Thread(
                target=self._drain, daemon=True,
                name=f"repl-send-{self.dataset}-{self.node}")
            self._worker.start()

    def _drain(self) -> None:
        while not self._stop.is_set():
            try:
                body, seq, trace = self.q.get(timeout=0.2)
            except queue.Empty:
                continue
            with self.lock:
                self.sent += 1
            try:
                reply = self.client.append_record(self.dataset, body,
                                                  seq=seq, trace=trace)
                _restitch_spans(trace, reply)
                self.note_ack()
            except Exception as e:  # noqa: BLE001 — peer death is data
                self.note_failure(e)

    def stop(self) -> None:
        self._stop.set()
        if self._worker is not None:
            self._worker.join(timeout=2)

    def snapshot(self) -> dict:
        with self.lock:
            since = self.behind_since
            return {"peer": self.node, "sent": self.sent,
                    "acked": self.acked, "failed": self.failed,
                    "skipped": self.skipped, "lostRecords": self.lost,
                    "pendingRecords": self.pending_locked(),
                    "lagging": self.lagging,
                    "lagSeconds": round(time.time() - since, 3)
                    if since else 0.0,
                    "lastError": self.last_error}


class ReplicationManager:
    """One dataset's fan-out state.  `client_factory(node)` dials a
    peer's replication door; `local_node` names the node this manager
    runs on (its own copy ingests locally — never through the wire).
    Runs in two shapes: node-resident (primary ingests locally, fans to
    owners[1:]) and distributor (a gateway that owns nothing fans to
    every owner, primary ack = owners[0]'s append)."""

    def __init__(self, dataset: str, mapper, client_factory: Callable,
                 config=None, local_node: Optional[str] = None):
        from filodb_tpu.config import ReplicationConfig
        self.dataset = dataset
        self.mapper = mapper
        self.client_factory = client_factory
        self.cfg = config or ReplicationConfig()
        self.local_node = local_node
        self._peers: Dict[str, _PeerState] = {}
        self._lock = threading.Lock()

    # ------------------------------------------------------------- peers

    def _peer(self, node: str) -> _PeerState:
        with self._lock:
            st = self._peers.get(node)
            if st is None:
                st = _PeerState(node, self.client_factory(node),
                                self.dataset,
                                self.cfg.lag_records_threshold,
                                self.cfg.send_queue_max)
                st.on_lagging = self._demote_replicas
                self._peers[node] = st
            return st

    def _demote_replicas(self, node: str) -> None:
        """A peer went lagging: its REPLICA copies leave the query-ready
        set (status -> Assigned) so failover can never serve its
        silently-short copy as a full result; primary copies are not
        touched (primary death is the promotion path).  mark_repaired
        restores them after a catch-up."""
        from filodb_tpu.parallel.shardmapper import ShardStatus
        try:
            for s in self.mapper.replica_shards_for_node(node):
                self.mapper.replica_statuses[(s, node)] = \
                    ShardStatus.ASSIGNED
        except Exception:  # noqa: BLE001 — bookkeeping must not sink
            _log.exception("replica demotion for %s failed", node)

    def mark_repaired(self, node: str) -> None:
        """A catch-up completed for `node`: clear its lost-record debt
        and flip its replica copies back to query-ready ACTIVE."""
        from filodb_tpu.parallel.shardmapper import ShardStatus
        with self._lock:
            st = self._peers.get(node)
        if st is not None:
            st.note_repaired()
        for s in self.mapper.replica_shards_for_node(node):
            self.mapper.replica_statuses[(s, node)] = ShardStatus.ACTIVE

    def snapshot(self) -> List[dict]:
        with self._lock:
            peers = list(self._peers.values())
        return sorted((p.snapshot() for p in peers),
                      key=lambda d: d["peer"])

    def lag_for(self, node: str) -> Optional[dict]:
        with self._lock:
            st = self._peers.get(node)
        return st.snapshot() if st is not None else None

    def stop(self) -> None:
        with self._lock:
            peers = list(self._peers.values())
        for p in peers:
            p.stop()

    # ------------------------------------------------------------ fan-out

    def replicate(self, shard: int, schema: str, part_keys, ts, columns,
                  bucket_les=None, seq: int = -1,
                  require_primary: bool = False) -> ReplicateResult:
        """Fan one slab to every remote owner of `shard`.  `seq` is the
        primary's WAL seq (replica horizon bookkeeping; -1 = none).
        `require_primary` (distributor mode) raises
        ReplicationSendError unless at least one owner acked — the
        caller must NOT ack its client when nothing is durable
        anywhere."""
        import numpy as np
        owners = [n for n in self.mapper.owners(shard)
                  if n != self.local_node]
        res = ReplicateResult(shard)
        if not owners:
            if require_primary:
                raise ReplicationSendError(
                    f"shard {shard} of {self.dataset!r} has no owners")
            return res
        rec = WalRecord(max(seq, 0), shard, schema, list(part_keys),
                        np.asarray(ts, dtype=np.int64), columns,
                        bucket_les)
        body = rec.encode()
        sync_quorum = self.cfg.ack_mode == "quorum"
        primary_owner = self.mapper.node_for_shard(shard)
        # the write-path trace id rides the door frames: the replica
        # executes its WAL append + ingest under it and ships its span
        # events back in the ack, stitching into ONE trace (the same
        # shape the query transport's remote_exec spans use)
        trace = current_trace_id()
        with metrics_span("replication_fanout", dataset=self.dataset):
            for node in owners:
                st = self._peer(node)
                is_primary_target = node == primary_owner
                if st.lagging and not is_primary_target:
                    # a LAGGING replica is skipped (probed every Nth slab
                    # so recovery is noticed without an operator): paying
                    # a connect failure per slab would collapse ingest
                    # throughput behind one corpse — catch-up repairs it
                    with st.lock:
                        st.skipped += 1
                        probe = st.skipped % _LAG_PROBE_EVERY == 0
                        if not probe:
                            # the skipped slab exists only on other
                            # owners until a catch-up repairs this peer
                            st.lost += 1
                    if not probe:
                        res.failed.append((node, "skipped: lagging"))
                        continue
                if sync_quorum or is_primary_target:
                    with st.lock:
                        st.sent += 1
                    try:
                        with metrics_span("replica_append", peer=node):
                            reply = st.client.append_record(
                                self.dataset, body, seq=seq, trace=trace)
                        _restitch_spans(trace, reply)
                        st.note_ack()
                        res.acked.append(node)
                        res.ingested[node] = int(reply.get("ingested", 0))
                    except Exception as e:  # noqa: BLE001 — a dead owner is data
                        st.note_failure(e)
                        res.failed.append((node,
                                           f"{type(e).__name__}: {e}"))
                else:
                    st.ensure_worker()
                    try:
                        st.q.put_nowait((body, seq, trace))
                        res.queued.append(node)
                    except queue.Full:
                        st.note_overflow()
                        res.failed.append((node, "send queue overflow"))
        metrics_registry.counter("replication_slabs",
                                 dataset=self.dataset).increment()
        if require_primary and not res.acked:
            raise ReplicationSendError(
                f"no owner of shard {shard} acknowledged the slab "
                f"(failed: {res.failed})")
        return res
