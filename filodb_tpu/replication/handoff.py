"""Live shard handoff — move a shard without anyone noticing.

The admin-triggered state machine behind
`POST /admin/shards/{s}/handoff` (and the rolling-restart drain
runbook, doc/operations.md):

    pending
      -> register          the target opens a RESTORE WINDOW (live
                           appends ack-and-buffer behind it) and joins
                           the shard's assignment list as an ASSIGNED
                           (NOT query-ready) replica, so live ingest
                           fan-out (replicator.py) starts including it —
                           everything appended from here on lands on
                           both owners, without a fresh sample ever
                           OOO-dropping older history still in flight
      -> stream_snapshot   the old owner's working set streams over as
                           WalRecord grids (service.py `snapshot`);
                           the new owner's index builds as a side
                           effect of the ordinary ingest path
      -> stream_wal_tail   the old owner's WAL tail ships as segments
                           and replays shard-filtered (catchup.py) —
                           covers anything a non-replicated door
                           ingested before registration; the restore
                           window then closes, draining buffered live
                           slabs in arrival order
      -> cutover           ShardMapper.promote_replica: ATOMIC — the
                           next query materializes against the new
                           primary; the old owner stays a replica (and
                           keeps serving stragglers) until...
      -> tombstone         ...the grace elapses: old owner leaves the
                           assignment list and drops its copy
      -> done              (any step) -> failed: journaled, the new
                           owner is unregistered, nothing cut over

Every transition lands in the event journal
(`shard_handoff_started/done/failed` + a `state` field per step), and
each run ticks a `shard_handoff` job in the PR 10 registry.  Draining a
node for a rolling restart is this machine in a loop plus
`health.draining` flipping `/ready` to 503 once its shards are gone.
"""
from __future__ import annotations

import logging
import time
from typing import Callable, Dict, List, Optional

from filodb_tpu.utils.events import journal
from filodb_tpu.utils.jobs import jobs
from filodb_tpu.utils.metrics import registry as metrics_registry

_log = logging.getLogger("filodb.replication")

PENDING = "pending"
REGISTER = "register"
STREAM_SNAPSHOT = "stream_snapshot"
STREAM_WAL_TAIL = "stream_wal_tail"
CUTOVER = "cutover"
TOMBSTONE = "tombstone"
DONE = "done"
FAILED = "failed"


class HandoffError(RuntimeError):
    """A handoff step failed; the journal holds the state it died in."""


class HandoffCoordinator:
    """Drives handoffs for one dataset.  `client_for(node)` dials a
    node's replication door (service.ReplicaClient); the mapper is the
    replica-aware ShardMapper this deployment plans queries from, so
    the cutover here IS the cutover queries see."""

    def __init__(self, dataset: str, mapper,
                 client_for: Callable[[str], object],
                 tombstone_grace_s: float = 0.0,
                 health=None,
                 on_cutover: Optional[Callable[[int, str, str], None]] = None):
        self.dataset = dataset
        self.mapper = mapper
        self.client_for = client_for
        self.grace_s = float(tombstone_grace_s)
        self.health = health
        # deployment hook fired at the cutover edge (shard, old, new) —
        # e.g. re-point a node-resident flush scheduler
        self.on_cutover = on_cutover
        self._history: List[Dict] = []

    # ------------------------------------------------------------- history

    @property
    def history(self) -> List[Dict]:
        return list(self._history)

    # -------------------------------------------------------------- drive

    def handoff(self, shard: int, to_node: str,
                skip_wal_tail: bool = False) -> Dict:
        """Move `shard`'s primary copy to `to_node`.  Returns a summary
        dict; raises HandoffError (after journaling + rollback) on any
        step failure.  `skip_wal_tail` is for deployments whose every
        ingest door already fans out through the replicator — the
        registration in step 1 then closes the gap by itself."""
        from filodb_tpu.parallel.shardmapper import ShardStatus
        t0 = time.perf_counter()
        from_node = self.mapper.node_for_shard(shard)
        if from_node is None:
            raise HandoffError(f"shard {shard} has no primary to hand off")
        if to_node == from_node:
            raise HandoffError(
                f"shard {shard} is already owned by {to_node!r}")
        job = jobs.register("shard_handoff", dataset=self.dataset)
        summary: Dict = {"dataset": self.dataset, "shard": shard,
                         "from": from_node, "to": to_node,
                         "states": []}
        state = PENDING
        journal.emit("shard_handoff_started", subsystem="replication",
                     dataset=self.dataset, shard=shard,
                     frm=from_node, to=to_node)
        registered = False

        def step(new_state: str, **fields) -> None:
            nonlocal state
            state = new_state
            summary["states"].append(new_state)
            journal.emit("shard_handoff", subsystem="replication",
                         dataset=self.dataset, shard=shard,
                         state=new_state, frm=from_node, to=to_node,
                         **fields)

        try:
            with job.tick():
                job.set_progress(f"shard {shard} -> {to_node}: register")
                # 1. open the restore window on the target, THEN join
                # the assignment list (RECOVERY: not yet query-ready —
                # failover must not route to a copy that is still
                # filling).  Live fan-out slabs arriving from here on
                # are acked-and-buffered behind the window, so a fresh
                # sample can never land before its series' older
                # snapshot history and OOO-drop it.
                src = self.client_for(from_node)
                dst = self.client_for(to_node)
                step(REGISTER)
                dst.begin_restore(self.dataset, shard)
                # ASSIGNED, not RECOVERY: RECOVERY counts as
                # query_ready (a recovering primary still serves), but
                # a copy that is still FILLING must be invisible to
                # failover and to the promotion path until the restore
                # window closes
                self.mapper.register_replica(shard, to_node,
                                             status=ShardStatus.ASSIGNED)
                registered = True

                # 2. bulk copy: old owner's working set streams through
                # the new owner's ordinary ingest path (restore-flagged:
                # applied through the open window)
                job.set_progress(f"shard {shard} -> {to_node}: snapshot")
                records = 0
                for body in src.snapshot_shard(self.dataset, shard):
                    dst.append_record(self.dataset, body, restore=True)
                    records += 1
                step(STREAM_SNAPSHOT, records=records)

                # 3. WAL tail: anything the log holds that predates the
                # registration (non-replicated doors) replays shard-
                # filtered on the new owner
                if not skip_wal_tail:
                    job.set_progress(
                        f"shard {shard} -> {to_node}: wal tail")
                    tail = self._stream_wal_tail(src, dst, shard)
                    step(STREAM_WAL_TAIL, records=tail)
                # close the restore window: live slabs buffered behind
                # the copy apply in arrival order — the new owner is
                # gap-free AND ordered, so now it is query-ready
                dst.end_restore(self.dataset, shard)
                self.mapper.register_replica(shard, to_node,
                                             status=ShardStatus.ACTIVE)

                # 4. ATOMIC cutover: the next query plans against the
                # new primary; the old owner stays a (serving) replica
                # until the tombstone grace drains stragglers
                job.set_progress(f"shard {shard} -> {to_node}: cutover")
                self.mapper.promote_replica(shard, to_node,
                                            demote_old=True)
                step(CUTOVER)
                if self.on_cutover is not None:
                    self.on_cutover(shard, from_node, to_node)

                # 5. tombstone the old copy
                if self.grace_s > 0:
                    time.sleep(self.grace_s)
                job.set_progress(f"shard {shard} -> {to_node}: tombstone")
                self.mapper.unassign_replica(shard, from_node)
                try:
                    src.drop_shard(self.dataset, shard)
                except Exception as e:  # noqa: BLE001 — the old copy
                    # lingering is benign (it left the assignment list);
                    # surface, don't fail the completed move
                    _log.warning("handoff tombstone of shard %d on %s "
                                 "failed: %s", shard, from_node, e)
                    summary["tombstoneError"] = f"{e}"
                step(TOMBSTONE)
                step(DONE)
        except Exception as e:  # noqa: BLE001 — every failure journals
            journal.emit("shard_handoff_failed", subsystem="replication",
                         dataset=self.dataset, shard=shard,
                         state=state, frm=from_node, to=to_node,
                         error=f"{type(e).__name__}: {e}")
            metrics_registry.counter("shard_handoffs",
                                     dataset=self.dataset,
                                     outcome="failed").increment()
            # roll back: the half-filled new copy must not be routable
            if registered and state in (REGISTER, STREAM_SNAPSHOT,
                                        STREAM_WAL_TAIL):
                self.mapper.unassign_replica(shard, to_node)
                try:
                    self.client_for(to_node).abort_restore(self.dataset,
                                                           shard)
                except Exception:  # noqa: BLE001 — best-effort cleanup
                    pass
                try:
                    self.client_for(to_node).drop_shard(self.dataset,
                                                        shard)
                except Exception:  # noqa: BLE001 — best-effort cleanup
                    pass
            summary["error"] = f"{type(e).__name__}: {e}"
            self._history.append(summary)
            raise HandoffError(
                f"handoff of shard {shard} to {to_node!r} failed in "
                f"{state}: {e}") from e
        summary["elapsedSeconds"] = round(time.perf_counter() - t0, 3)
        metrics_registry.counter("shard_handoffs", dataset=self.dataset,
                                 outcome="done").increment()
        journal.emit("shard_handoff_done", subsystem="replication",
                     dataset=self.dataset, shard=shard, frm=from_node,
                     to=to_node,
                     elapsed_s=summary["elapsedSeconds"])
        self._history.append(summary)
        return summary

    def _stream_wal_tail(self, src, dst, shard: int) -> int:
        """Relay the old owner's WAL records for `shard` to the new
        owner through its ordinary door (catchup.relay_wal).  A source
        without a WAL contributes nothing — its memory snapshot already
        streamed."""
        from filodb_tpu.replication.catchup import relay_wal
        return relay_wal(src, dst, self.dataset, shards=[shard])

    # --------------------------------------------------------------- drain

    def drain_node(self, node: str,
                   target_for: Callable[[int], Optional[str]] = None
                   ) -> Dict:
        """Rolling-restart drain: hand every shard whose primary is
        `node` to another owner, then flip `/ready` to 503 via
        health.draining.  `target_for(shard)` picks the destination
        (default: the shard's first query-ready replica)."""
        shards = self.mapper.shards_for_node(node)
        moved, failed = [], []
        for s in shards:
            to = target_for(s) if target_for is not None else None
            if to is None:
                live = [n for n in self.mapper.replicas[s]
                        if self.mapper.owner_status(s, n).query_ready]
                to = live[0] if live else None
            if to is None:
                failed.append({"shard": s, "error": "no target replica"})
                continue
            try:
                # the target already holds a live replica copy — the
                # snapshot stream is incremental dedup on top of it
                moved.append(self.handoff(s, to))
            except HandoffError as e:
                failed.append({"shard": s, "error": str(e)})
        if self.health is not None and not failed:
            self.health.draining = f"drained {len(moved)} shard(s) " \
                                   f"off {node}"
        return {"node": node, "moved": [m["shard"] for m in moved],
                "failed": failed}
