"""Replication layer — RF-2 ingest, WAL-segment catch-up, query-time
replica failover, live shard handoff (doc/replication.md).

PR 4 made node failures degrade into FLAGGED partial results; a
production time-series store serves FULL results through a node kill by
owning every shard twice (ref: FiloDB's ShardMapper/coordinator layer,
PAPER.md §1; the Cortex distributor / Monarch replica-set stance).  The
four pieces, each its own module:

  placement   parallel/shardmapper.py + shardmanager.py grew ordered
              per-shard assignment lists (primary + replicas, never
              co-located) — this package consumes them.
  service.py  the node-side replication door: framed-TCP server
              accepting slab appends, WAL-segment fetches, working-set
              snapshot streams, and shard drops; `ReplicaClient` is the
              pooled client every other module dials peers with.
  replicator.py  ingest fan-out (the distributor): every columnar slab
              goes to all live owners of its shard, acked
              primary-durable (+ replica-acked under
              `replication.ack_mode = quorum`), with per-replica lag
              tracked as metrics and `replica_lagging` /
              `replica_caught_up` journal events.
  catchup.py  a replica joining or falling behind streams WAL segments
              from the primary (never re-scrapes) and replays them
              through the ordinary wal/replay.py ingest path, as a
              `replication_catchup` job in the PR 10 registry.
  failover.py the query-time half: `ReplicaFailoverDispatcher` prefers
              the primary and fails over to replicas on
              shard_unavailable / breaker-open BEFORE the PR 4 partial
              path engages — partials only when ALL owners are dead.
  handoff.py  admin-triggered live shard handoff: stream working set +
              WAL tail to the new owner while the old one keeps
              serving, cut the ShardMapper over atomically, then
              tombstone — every transition journaled; rolling restarts
              drain through it (`/ready` flips 503).
"""
from filodb_tpu.replication.service import (ReplicaClient,  # noqa: F401
                                            ReplicationServer,
                                            ReplicationError)
from filodb_tpu.replication.replicator import (ReplicationManager,  # noqa: F401
                                               ReplicateResult)
from filodb_tpu.replication.catchup import (CatchupStats,  # noqa: F401
                                            catchup_shards,
                                            rebuild_node)
from filodb_tpu.replication.failover import (  # noqa: F401
    ReplicaFailoverDispatcher, cold_dispatcher_factory,
    failover_dispatcher_factory)
from filodb_tpu.replication.handoff import (HandoffCoordinator,  # noqa: F401
                                            HandoffError)

__all__ = ["ReplicaClient", "ReplicationServer", "ReplicationError",
           "ReplicationManager", "ReplicateResult", "CatchupStats",
           "catchup_shards", "rebuild_node",
           "ReplicaFailoverDispatcher", "cold_dispatcher_factory",
           "failover_dispatcher_factory", "HandoffCoordinator",
           "HandoffError"]
