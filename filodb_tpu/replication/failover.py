"""Query-time replica failover — full results through a SIGKILL.

`ReplicaFailoverDispatcher` wraps one dispatcher per owner of a shard,
in assignment-list order: the primary is preferred; `shard_unavailable`
(connection refused/reset, or the peer's circuit breaker failing fast)
falls through to the next owner BEFORE the PR 4 partial-results path
ever engages.  Only when EVERY owner of the shard is unreachable does
the typed error propagate — and then the existing retry-then-degrade
machinery (engine re-plan, partial_now) takes over, so partials happen
exactly when all copies of a shard are dead.

`dispatch_timeout` / `query_timeout` / `remote_failure` do NOT fail
over: a timeout means the remote may still be executing (re-sending
elsewhere wastes the survivors' budget), and a remote_failure would
fail identically on the replica (same plan, same data).
"""
from __future__ import annotations

import logging
from typing import Callable, List, Optional, Sequence, Tuple

from filodb_tpu.query.execbase import PlanDispatcher, QueryError

_log = logging.getLogger("filodb.replication")


class ReplicaFailoverDispatcher(PlanDispatcher):
    """Ordered owner list -> first owner that answers.  `targets` is
    [(node_name, dispatcher)] in assignment order (primary first).

    Shuffle sharding (query.shuffle_shard_factor > 0): when the plan's
    context carries a tenant workspace, the walk order is re-ranked so
    owners inside the tenant's deterministic k-of-N node subset
    (qos.shuffle_shard_nodes over `all_nodes`, the cluster's node
    universe) are tried FIRST — each tenant's scatter-gather load lands
    on a bounded, tenant-stable blast radius, and a hot tenant browns
    out its own subset before anyone else's.  Failover semantics are
    unchanged: non-preferred owners remain fallbacks, so availability
    never loses to affinity."""

    def __init__(self, targets: Sequence[Tuple[str, PlanDispatcher]],
                 shard: Optional[int] = None,
                 all_nodes: Optional[Sequence[str]] = None,
                 shuffle_k: Optional[int] = None,
                 rotate: bool = False):
        import itertools
        self.targets = list(targets)
        self.shard = shard
        self.all_nodes = list(all_nodes) if all_nodes else \
            [n for n, _ in self.targets]
        self.shuffle_k = shuffle_k
        # cold-leaf load spreading (persist/objectstore.py query-only
        # nodes): every target can serve the leaf from the shared tier,
        # so successive dispatches rotate the start of the walk —
        # elastic read capacity actually takes load instead of idling as
        # a fallback.  Failover semantics unchanged: the rest of the
        # rotated list still walks on shard_unavailable.
        self.rotate = rotate
        self._rr = itertools.count()

    def pushdown_target(self):
        """Node address for aggregation pushdown (query/pushdown.py):
        the PRIMARY owner's remote dispatcher.  A pushdown group that
        cannot reach it falls back to per-shard dispatch, where this
        dispatcher's owner walk provides the replica failover — so
        grouping by primary never costs availability."""
        if not self.targets:
            return None
        fn = getattr(self.targets[0][1], "pushdown_target", None)
        return fn() if fn is not None else None

    def _walk_order(self, plan) -> Sequence[Tuple[str, PlanDispatcher]]:
        base = self.targets
        if self.rotate and len(base) > 1:
            k0 = next(self._rr) % len(base)
            base = base[k0:] + base[:k0]
        ws = getattr(getattr(plan, "ctx", None), "tenant_ws", "")
        k = self.shuffle_k
        if k is None:
            from filodb_tpu.config import settings
            k = settings().query.shuffle_shard_factor
        if not ws or k <= 0 or len(base) < 2:
            return base
        from filodb_tpu.query.qos import shuffle_shard_nodes
        preferred = set(shuffle_shard_nodes(ws, self.all_nodes, k))
        ordered = ([t for t in base if t[0] in preferred]
                   + [t for t in base if t[0] not in preferred])
        if ordered[0][0] != self.targets[0][0]:
            from filodb_tpu.utils.metrics import registry
            registry.counter("query_shuffle_shard_routed",
                             ws=ws).increment()
        return ordered

    def dispatch(self, plan, source):
        from filodb_tpu.utils.metrics import registry
        last: Optional[QueryError] = None
        targets = self._walk_order(plan)
        for i, (node, disp) in enumerate(targets):
            try:
                out = disp.dispatch(plan, source)
                if i > 0:
                    # served by a replica: a FULL answer, not a partial
                    # — counted so chaos runs can prove failover (not
                    # luck) kept availability at 1.0
                    registry.counter("query_replica_failovers",
                                     peer=node).increment()
                return out
            except QueryError as e:
                if e.code != "shard_unavailable":
                    raise
                last = e
                if i + 1 < len(targets):
                    _log.debug("shard %s owner %s unavailable (%s) — "
                               "failing over to %s", self.shard, node,
                               e, targets[i + 1][0])
        if last is None:
            raise QueryError(
                "shard_unavailable",
                f"shard {self.shard} has no owners to dispatch to")
        raise QueryError(
            "shard_unavailable",
            f"all {len(self.targets)} owner(s) of shard {self.shard} "
            f"unavailable (last: {last})")


def failover_dispatcher_factory(
        mapper, dispatcher_for: Callable[[str], PlanDispatcher],
        local_node: Optional[str] = None,
        local_dispatcher: Optional[PlanDispatcher] = None,
        shuffle_k: Optional[int] = None
        ) -> Callable[[int], Optional[PlanDispatcher]]:
    """Build a planner `dispatcher_factory(shard)` from a replica-aware
    ShardMapper: each shard's dispatcher walks its CURRENT owner list
    (read per materialization, so a promotion or handoff cutover is
    picked up by the very next query).  `dispatcher_for(node)` dials a
    remote owner; `local_node`'s copy (when this process IS an owner)
    executes through `local_dispatcher` (defaults to in-process).
    `shuffle_k` pins the shuffle-shard subset size (None = the
    query.shuffle_shard_factor setting at dispatch time)."""
    from filodb_tpu.query.execbase import InProcessPlanDispatcher

    def factory(shard: int) -> Optional[PlanDispatcher]:
        # primary always dispatches; replicas only once query-ready
        # (ACTIVE/RECOVERY) — an ASSIGNED copy still catching up would
        # serve a silently-short "full" result on failover
        primary = mapper.node_for_shard(shard)
        owners = ([primary] if primary is not None else []) + [
            n for n in mapper.replicas[shard]
            if mapper.owner_status(shard, n).query_ready]
        if not owners:
            return None
        targets: List[Tuple[str, PlanDispatcher]] = []
        for node in owners:
            if local_node is not None and node == local_node:
                targets.append((node, local_dispatcher
                                or InProcessPlanDispatcher()))
            else:
                targets.append((node, dispatcher_for(node)))
        if len(targets) == 1:
            return targets[0][1]
        # the node universe for the tenant's k-of-N subset: every node
        # holding any copy of any shard (snapshot per materialization,
        # like the owner list)
        all_nodes = sorted(
            {n for n in mapper.nodes if n is not None}
            | {n for repls in mapper.replicas for n in repls})
        return ReplicaFailoverDispatcher(targets, shard=shard,
                                         all_nodes=all_nodes,
                                         shuffle_k=shuffle_k)

    return factory


def cold_dispatcher_factory(
        mapper, dispatcher_for: Callable[[str], PlanDispatcher],
        local_node: Optional[str] = None,
        local_dispatcher: Optional[PlanDispatcher] = None,
        shuffle_k: Optional[int] = None
        ) -> Callable[[int], Optional[PlanDispatcher]]:
    """`dispatcher_factory(shard)` for the PERSISTED (cold) planner: the
    shared object tier means ANY query-capable node can serve a cold
    leaf, so targets are the shard's query-ready owners PLUS every
    registered query-only node (`mapper.query_nodes`), walked
    round-robin — adding stateless query nodes actually spreads cold
    read load instead of idling as fallbacks.  Failover semantics are
    the ordinary owner walk: `shard_unavailable` tries the next target,
    and only when EVERY target is dead does the partial-results
    machinery engage."""
    from filodb_tpu.query.execbase import InProcessPlanDispatcher

    def factory(shard: int) -> Optional[PlanDispatcher]:
        primary = mapper.node_for_shard(shard)
        owners = ([primary] if primary is not None else []) + [
            n for n in mapper.replicas[shard]
            if mapper.owner_status(shard, n).query_ready]
        extras = [n for n in getattr(mapper, "query_nodes", [])
                  if n not in owners]
        nodes = owners + extras
        if not nodes:
            return None
        targets: List[Tuple[str, PlanDispatcher]] = []
        for node in nodes:
            if local_node is not None and node == local_node:
                targets.append((node, local_dispatcher
                                or InProcessPlanDispatcher()))
            else:
                targets.append((node, dispatcher_for(node)))
        if len(targets) == 1:
            return targets[0][1]
        all_nodes = sorted(
            {n for n in mapper.nodes if n is not None}
            | {n for repls in mapper.replicas for n in repls}
            | set(extras))
        return ReplicaFailoverDispatcher(targets, shard=shard,
                                         all_nodes=all_nodes,
                                         shuffle_k=shuffle_k,
                                         rotate=True)

    return factory
