"""WAL-segment catch-up: a replica repairs its copy from the primary's
log, never by re-scraping.

A replica that joined late, fell behind (lagging fan-out), or restarted
empty streams WAL segment FILES from the shard's primary
(service.ReplicaClient.fetch_segments), lands them in a scratch
directory, and replays them through the ordinary wal/replay.py ingest
path with a shard filter and its resume point — so catch-up is the boot
recovery path pointed at a peer instead of the local disk, not a second
ingest implementation.  Idempotence comes for free from the same
store-level OOO/dup handling replay already rides.

Every run registers a `replication_catchup` job in the PR 10 registry
(GET /admin/jobs shows progress; a failing catch-up streak feeds the
health verdict) and journals `replica_caught_up` on success.
"""
from __future__ import annotations

import dataclasses
import logging
import os
import shutil
import tempfile
import time
from typing import Dict, Iterable, Optional

from filodb_tpu.utils.events import journal
from filodb_tpu.utils.jobs import jobs
from filodb_tpu.utils.metrics import registry as metrics_registry
from filodb_tpu.wal.replay import replay_dir
from filodb_tpu.wal.segment import segment_path

_log = logging.getLogger("filodb.replication")


@dataclasses.dataclass
class CatchupStats:
    segments: int = 0
    records: int = 0
    samples: int = 0
    skipped_records: int = 0
    last_seq: int = -1
    elapsed_s: float = 0.0

    @property
    def samples_per_sec(self) -> float:
        return self.samples / self.elapsed_s if self.elapsed_s > 0 else 0.0


def relay_wal(src_client, dst_client, dataset: str,
              shards: Optional[Iterable[int]] = None,
              since_seq: int = -1, restore: bool = True) -> int:
    """Coordinator-mediated catch-up: stream WAL segments from one
    peer, decode + shard-filter here, re-append through the other
    peer's ordinary replication door (so the records land in ITS WAL
    too).  Relayed records are restore-flagged by default — they are
    history, and must apply inside an open restore window instead of
    being buffered behind it.  Returns records relayed.  Used by the
    handoff WAL-tail phase and the chaos bench's respawn repair; a
    source without a WAL relays nothing."""
    from filodb_tpu.replication.service import ReplicationError
    from filodb_tpu.wal.segment import (WalCorruption, WalRecord,
                                        read_records, segment_path)
    shard_set = set(int(s) for s in shards) if shards is not None else None
    tmp = tempfile.mkdtemp(prefix="filodb-relay-")
    sent = 0
    try:
        try:
            segs = list(src_client.fetch_segments(dataset, since_seq))
        except ReplicationError:
            return 0
        for first_seq, data in segs:
            path = segment_path(tmp, first_seq)
            with open(path, "wb") as f:
                f.write(data)
            tables: dict = {}
            try:
                for body in read_records(path):
                    rec = WalRecord.decode(body, tables)
                    if shard_set is not None \
                            and rec.shard not in shard_set:
                        continue
                    if rec.seq <= since_seq:
                        continue
                    dst_client.append_record(dataset, rec.encode(),
                                             seq=rec.seq,
                                             restore=restore)
                    sent += 1
            except WalCorruption as e:
                _log.warning("WAL relay: segment %s corrupt (%s) — "
                             "continuing", path, e)
            finally:
                os.unlink(path)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return sent


def catchup_shards(client, dataset: str, memstore,
                   shards: Optional[Iterable[int]] = None,
                   since: Optional[Dict[int, int]] = None,
                   node: str = "local",
                   scratch_dir: Optional[str] = None) -> CatchupStats:
    """Stream WAL segments from `client`'s peer and replay the shards in
    `shards` (None = every shard in the log) into `memstore`.  `since`
    maps shard -> resume seq (records at or below it skip — typically
    the replica's horizon from ReplicationServer.horizon).  Returns
    CatchupStats; raises on transport failure (the caller's job streak
    then feeds the health verdict)."""
    t0 = time.perf_counter()
    since = dict(since or {})
    shard_set = set(int(s) for s in shards) if shards is not None else None
    job = jobs.register("replication_catchup", dataset=dataset)
    stats = CatchupStats()
    with job.tick() as tick:
        # fetch horizon: the MIN resume point over the TARGET shards,
        # where a shard absent from `since` replays from the beginning
        # (-1) — min(since.values()) alone would let one caught-up
        # shard's horizon skip segments a brand-new shard still needs
        if shard_set is not None:
            min_since = min((since.get(s, -1) for s in shard_set),
                            default=-1)
        else:
            # unknown target set: the log may hold shards `since` never
            # mapped, so nothing can safely bound the fetch — stream
            # everything; replay's restart_points still skip per shard
            min_since = -1
        tmp = scratch_dir or tempfile.mkdtemp(prefix="filodb-catchup-")
        own_tmp = scratch_dir is None
        try:
            os.makedirs(tmp, exist_ok=True)
            job.set_progress("streaming segments")
            for first_seq, data in client.fetch_segments(dataset,
                                                         min_since):
                path = segment_path(tmp, first_seq)
                with open(path, "wb") as f:
                    f.write(data)
                stats.segments += 1
            job.set_progress(
                f"replaying {stats.segments} segment(s)")
            rstats = replay_dir(tmp, memstore, dataset,
                                restart_points=since,
                                shard_filter=shard_set)
            stats.records = rstats.records
            stats.samples = rstats.samples
            stats.skipped_records = rstats.skipped_records
            stats.last_seq = rstats.last_seq
            if rstats.corrupt_segments:
                # acknowledged data on the PRIMARY was unreadable — the
                # copy may still be short; surface it as a failed run
                tick.handle.note_error(
                    f"{rstats.corrupt_segments} corrupt segment(s) "
                    "during catch-up")
        finally:
            if own_tmp:
                shutil.rmtree(tmp, ignore_errors=True)
    stats.elapsed_s = time.perf_counter() - t0
    metrics_registry.counter("replication_catchup_samples",
                             dataset=dataset).increment(stats.samples)
    journal.emit("replica_caught_up", subsystem="replication",
                 dataset=dataset, peer=client.where, node=node,
                 records=stats.records, samples=stats.samples,
                 last_seq=stats.last_seq,
                 elapsed_s=round(stats.elapsed_s, 3))
    job.set_progress(f"caught up to seq {stats.last_seq}")
    return stats


def rebuild_node(object_store, segment_store, client, dataset: str,
                 memstore, num_shards: int,
                 shards: Optional[Iterable[int]] = None,
                 since: Optional[Dict[int, int]] = None,
                 node: str = "local",
                 scratch_dir: Optional[str] = None):
    """Disk-loss rebuild: the replacement node recovers its COLD tier
    from the shared object store (manifest-driven,
    persist/objectstore.restore_from_objectstore) and its RAW edge from
    a live peer's WAL through the ordinary catch-up path — nothing but
    manifests + WAL tail, which is the whole durability claim of the
    disaggregated tier.  `client` may be None (single-node deployments
    restore the tail from their own surviving WAL via boot replay).
    Returns (RestoreStats, CatchupStats)."""
    from filodb_tpu.persist.objectstore import restore_from_objectstore
    rstats = restore_from_objectstore(object_store, segment_store,
                                      dataset, num_shards, node=node)
    cstats = CatchupStats()
    if client is not None:
        cstats = catchup_shards(client, dataset, memstore, shards=shards,
                                since=since, node=node,
                                scratch_dir=scratch_dir)
    journal.emit("node_rebuilt", subsystem="replication", dataset=dataset,
                 node=node, segments_fetched=rstats.segments_fetched,
                 wal_records=cstats.records, wal_samples=cstats.samples)
    return rstats, cstats
