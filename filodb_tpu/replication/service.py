"""Replication transport: the node-side door peers replicate through.

One framed-TCP server per node (next to transport.NodeQueryServer — the
query data plane stays untouched) speaking a small typed-frame protocol
built on the shared frame codec (parallel/transport._send_frame): every
message is a JSON control frame, optionally followed by binary frames
whose sizes the control frame declares.  Verbs:

  append       one columnar slab, WalRecord-encoded (the WAL's own wire
               format — replication and durability share one
               serializer, so they cannot drift): appended to the local
               WAL (durable before the ack when one is attached) and
               ingested through the ordinary `ingest_columns` path.
  fetch_wal    stream WAL segments whose records reach past `since_seq`
               — the catch-up medium (ship segments, don't re-scrape).
  snapshot     stream one shard's working set as WalRecord-encoded
               grids (the live-handoff bulk phase).
  begin_restore / end_restore / abort_restore
               the restore window: while open, LIVE appends for the
               shard are acked but BUFFERED (not ingested), while
               restore-flagged appends (snapshot / WAL-tail records)
               apply immediately; end_restore drains the buffer in
               arrival order.  Without this window a live sample
               landing before its series' older snapshot grid would
               make the store's OOO handling silently DROP the whole
               history — the double-buffering every live shard
               migration needs.
  horizon      per-shard replica horizons (highest PRIMARY seq applied)
               — catch-up resume points.
  drop_shard   tombstone a local shard copy (handoff completion).
  ping         liveness + owned-shard report.

The server never trusts the peer: record bodies go through the same
CRC/decode guards replay uses, and a failed verb answers a structured
error instead of killing the connection.
"""
from __future__ import annotations

import logging
import os
import socket
import socketserver
import threading
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from filodb_tpu.parallel.transport import (_recv_frame, _send_frame,
                                           recv_json_frame, send_json_frame)
from filodb_tpu.utils.metrics import registry as metrics_registry
from filodb_tpu.wal.segment import WalRecord

_log = logging.getLogger("filodb.replication")

# series per streamed snapshot grid: bounds per-record memory while
# keeping the per-record Python overhead amortized
SNAPSHOT_BATCH_SERIES = 1024

# restore-window buffer cap (records): past it the restore has fallen
# hopelessly behind live ingest — fail the restore loudly rather than
# silently dropping buffered acked slabs
RESTORE_BUFFER_MAX = 65_536


class ReplicationError(RuntimeError):
    """A replication verb failed on the peer (its detail rides along)."""


def iter_shard_grids(shard, batch_series: int = SNAPSHOT_BATCH_SERIES,
                     page: bool = True) -> Iterator[WalRecord]:
    """Yield one shard's working set as WalRecord grids — the snapshot
    stream's producer.  Series are grouped by sample count into the same
    rectangular [S, k] slabs `ingest_columns` consumes (ragged series
    split across groups, like gateway/remotewrite._build_slabs).  With
    `page` the flushed-but-evicted tail is demand-paged back first so
    the stream covers everything the shard can serve from memory."""
    lookup = shard.lookup_partitions([], 0, 1 << 62)
    for schema_name, pids in lookup.pids_by_schema.items():
        if page:
            try:
                shard.ensure_paged_pids(schema_name, pids, 0, 1 << 62)
            except Exception:  # noqa: BLE001 — page what we can; the
                # dense tier still streams (the new owner recovers the
                # rest from the shared column store)
                _log.exception("handoff snapshot: paging failed for %s",
                               schema_name)
        store = shard.stores[schema_name]
        for lo in range(0, len(pids), batch_series):
            chunk = pids[lo:lo + batch_series]
            rows = shard.rows_for(chunk)
            ts, cols, counts = shard.snapshot_read(
                store, lambda: store.gather_rows(rows))
            by_count: Dict[int, List[int]] = {}
            for i in range(len(chunk)):
                n = int(counts[i])
                if n > 0:
                    by_count.setdefault(n, []).append(i)
            for n, idxs in by_count.items():
                keys = [shard.partitions[int(chunk[i])].part_key
                        for i in idxs]
                sel = np.asarray(idxs)
                grid_ts = np.ascontiguousarray(ts[sel, :n]).astype(np.int64)
                grid_cols = {
                    c: np.ascontiguousarray(np.asarray(v)[sel, :n])
                    for c, v in cols.items() if v is not None}
                yield WalRecord(0, shard.shard_num, schema_name, keys,
                                grid_ts, grid_cols, store.bucket_les)


class ReplicationServer:
    """Per-node replication door.  `wals` maps dataset -> WalManager
    (may be empty: appends then skip local durability and rely on the
    primary's WAL until flush).  Tracks per-(dataset, shard) replica
    horizons — the highest PRIMARY-space seq applied here — which are
    the catch-up resume points."""

    def __init__(self, memstore, node: str = "local",
                 wals: Optional[Dict[str, object]] = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.memstore = memstore
        self.node = node
        self.wals = wals if wals is not None else {}
        self._horizons: Dict[Tuple[str, int], int] = {}
        self._hlock = threading.Lock()
        # (dataset, shard) -> buffered live records while a restore
        # window is open; None value = window overflowed (restore must
        # fail, buffered slabs were dropped past the cap)
        self._staging: Dict[Tuple[str, int], Optional[list]] = {}
        # live handler connections: stop() severs them so a stopped
        # in-proc node looks EXACTLY like a SIGKILLed one to peers with
        # pooled sockets (same stance as transport.NodeQueryServer)
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        outer = self

        class _Handler(socketserver.BaseRequestHandler):
            def setup(self):
                with outer._conns_lock:
                    outer._conns.add(self.request)

            def finish(self):
                with outer._conns_lock:
                    outer._conns.discard(self.request)

            def handle(self):
                try:
                    while True:
                        req = recv_json_frame(self.request)
                        try:
                            outer._handle(self.request, req)
                        except (ConnectionError, OSError):
                            raise
                        except Exception as e:  # noqa: BLE001 — verb errors ride the wire
                            send_json_frame(self.request, {
                                "ok": False,
                                "error": f"{type(e).__name__}: {e}"})
                except (ConnectionError, OSError, ValueError):
                    return

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _Server((host, port), _Handler)
        self._thread: Optional[threading.Thread] = None

    # ----------------------------------------------------------- lifecycle

    @property
    def address(self) -> Tuple[str, int]:
        return self._server.server_address

    def start(self) -> "ReplicationServer":
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        # stdlib shutdown() BLOCKS until serve_forever acknowledges —
        # forever if the serving thread was never started (an embedder
        # that built the door but never start()ed it must still be able
        # to tear down; same guard as http.FiloHttpServer.stop)
        if self._thread is not None:
            self._server.shutdown()
        self._server.server_close()
        with self._conns_lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        if self._thread:
            self._thread.join(timeout=5)

    def horizon(self, dataset: str, shard: int) -> int:
        with self._hlock:
            return self._horizons.get((dataset, shard), -1)

    # --------------------------------------------------------------- verbs

    def _handle(self, sock, req: Dict) -> None:
        cmd = req.get("cmd")
        if cmd == "append":
            self._append(sock, req)
        elif cmd == "fetch_wal":
            self._fetch_wal(sock, req)
        elif cmd == "snapshot":
            self._snapshot(sock, req)
        elif cmd == "horizon":
            ds = req["dataset"]
            with self._hlock:
                hs = {str(s): seq for (d, s), seq in self._horizons.items()
                      if d == ds}
            send_json_frame(sock, {"ok": True, "horizons": hs})
        elif cmd == "begin_restore":
            key = (req["dataset"], int(req["shard"]))
            with self._hlock:
                self._staging.setdefault(key, [])
            send_json_frame(sock, {"ok": True})
        elif cmd == "end_restore":
            self._end_restore(sock, req)
        elif cmd == "abort_restore":
            key = (req["dataset"], int(req["shard"]))
            with self._hlock:
                dropped = self._staging.pop(key, None)
            send_json_frame(sock, {"ok": True,
                                   "dropped": len(dropped or [])})
        elif cmd == "drop_shard":
            self._drop_shard(sock, req)
        elif cmd == "ping":
            send_json_frame(sock, {"ok": True, "node": self.node,
                                   "owned": self.memstore.shard_map()})
        else:
            send_json_frame(sock, {"ok": False,
                                   "error": f"unknown cmd {cmd!r}"})

    def _append(self, sock, req: Dict) -> None:
        """One replicated slab: body frame is a self-contained
        WalRecord.  Local WAL (when attached) commits BEFORE the ack —
        the replica's durability claim is real; the primary-space seq
        advances this shard's replica horizon.  While a restore window
        is open for the shard, LIVE slabs are acked-and-buffered
        (applied in order at end_restore) so a fresh sample can never
        land before its series' older snapshot history and trigger the
        store's OOO drop of that history; restore-flagged slabs (the
        snapshot / WAL-tail stream itself) apply immediately.

        A `trace` field in the header is the distributor's write-path
        trace id: the local WAL append + ingest run under it and the
        span events recorded here ride back in the ack (`spans`), so
        the distributor's collector holds ONE stitched cross-node trace
        — the same drain-per-reply protocol the query transport uses."""
        body = _recv_frame(sock)
        rec = WalRecord.decode(body)
        dataset = req["dataset"]
        seq = int(req.get("seq", -1))
        trace = req.get("trace") or ""
        # the buffering decision comes FIRST: a buffered live slab is
        # WAL'd at end_restore drain time, not on arrival — otherwise a
        # crash mid-window replays the live tick BEFORE the relayed
        # history still in flight and the store's OOO handling drops
        # that history all over again.  (The narrow cost: a buffered
        # slab's durability on THIS replica starts at drain; the
        # primary's own WAL already holds it, and a crashed mid-restore
        # target is rolled back and redone either way.)
        buffered = False
        if not req.get("restore"):
            key = (dataset, rec.shard)
            with self._hlock:
                buf = self._staging.get(key)
                if buf is not None:
                    if len(buf) >= RESTORE_BUFFER_MAX:
                        # past the cap: poison the window (end_restore
                        # fails loudly) instead of silently dropping
                        self._staging[key] = None
                    else:
                        buf.append((rec, seq))
                        buffered = True
                elif key in self._staging:
                    buffered = True      # poisoned: ack, restore fails
        got = 0
        spans = []
        if not buffered:
            if trace:
                from filodb_tpu.utils.metrics import (collector,
                                                      trace_context)
                with trace_context(trace):
                    offset = self._wal_append(dataset, rec)
                    got = self._apply(dataset, rec, offset, seq)
                # drain exactly the events recorded since the last reply
                # (take — never trace — so a reused connection can't
                # double-ship) and stitch them into the distributor's
                # collector via the ack
                spans = collector.take(trace)
            else:
                offset = self._wal_append(dataset, rec)
                got = self._apply(dataset, rec, offset, seq)
        metrics_registry.counter("replication_appends_received",
                                 dataset=dataset).increment()
        reply = {"ok": True, "seq": seq, "ingested": int(got),
                 "buffered": buffered}
        if spans:
            reply["spans"] = spans
        send_json_frame(sock, reply)

    def _wal_append(self, dataset: str, rec: WalRecord) -> int:
        wal = self.wals.get(dataset)
        if wal is None:
            return -1
        return wal.append_grid(rec.shard, rec.schema, rec.part_keys,
                               rec.ts, rec.columns,
                               bucket_les=rec.bucket_les)

    def _apply(self, dataset: str, rec: WalRecord, offset: int,
               seq: int) -> int:
        shard = self.memstore.get_shard(dataset, rec.shard) \
            or self.memstore.setup(dataset, rec.shard)
        got = shard.ingest_columns(rec.schema, rec.part_keys, rec.ts,
                                   rec.columns, offset=offset,
                                   bucket_les=rec.bucket_les)
        # primary-space seq travels in the HEADER (the record's own u64
        # seq field cannot carry "unknown"): it advances this shard's
        # replica horizon — the catch-up resume point
        if seq >= 0:
            with self._hlock:
                key = (dataset, rec.shard)
                if seq > self._horizons.get(key, -1):
                    self._horizons[key] = seq
        return int(got)

    def _end_restore(self, sock, req: Dict) -> None:
        dataset = req["dataset"]
        shard_num = int(req["shard"])
        key = (dataset, shard_num)
        applied = 0
        # swap-drain loop: the window stays OPEN (concurrent live
        # appends keep landing in a fresh buffer, never applying ahead
        # of older drained records) and only closes atomically once a
        # swap finds it empty — popping then applying outside the lock
        # would let a racing append OOO-drop the still-undrained tail
        while True:
            with self._hlock:
                buf = self._staging.get(key)
                if buf is None:
                    if key in self._staging:
                        self._staging.pop(key)
                        send_json_frame(sock, {
                            "ok": False,
                            "error": f"restore window for shard "
                                     f"{shard_num} overflowed "
                                     f"({RESTORE_BUFFER_MAX} records) — "
                                     "buffered live slabs were dropped; "
                                     "redo the restore"})
                        return
                    buf = []             # window never opened: no-op
                if not buf:
                    self._staging.pop(key, None)
                    break
                self._staging[key] = []
            for rec, seq in buf:
                # WAL'd here, in drain order, so a later replay
                # re-applies history and buffered live slabs in the
                # same safe order
                offset = self._wal_append(dataset, rec)
                self._apply(dataset, rec, offset, seq)
                applied += 1
        send_json_frame(sock, {"ok": True, "applied": applied})

    def _fetch_wal(self, sock, req: Dict) -> None:
        """Stream WAL segments holding records past `since_seq`: one
        {"segment": first_seq, "bytes": n} control frame + one binary
        frame per segment, then {"done": true}.  Whole files ship — the
        receiver replays with its shard filter + resume point, and
        segment self-containment (key tables intern per segment) makes
        any byte range before `safe_bytes` decodable."""
        dataset = req["dataset"]
        since = int(req.get("since_seq", -1))
        wal = self.wals.get(dataset)
        if wal is None:
            send_json_frame(sock, {"ok": False,
                                   "error": f"no WAL for {dataset!r}"})
            return
        segments, committed = wal.writer.snapshot_segments()
        sent = 0
        for first, last, path, safe_bytes in segments:
            if last < since:
                continue
            try:
                with open(path, "rb") as f:
                    data = f.read(safe_bytes)
            except OSError:
                continue                 # pruned underneath the snapshot
            send_json_frame(sock, {"ok": True, "segment": first,
                                   "last_seq": last, "bytes": len(data)})
            _send_frame(sock, data)
            sent += 1
        send_json_frame(sock, {"ok": True, "done": True,
                               "segments": sent, "committed_seq": committed})

    def _snapshot(self, sock, req: Dict) -> None:
        """Stream one shard's working set as WalRecord grids (the
        handoff bulk phase): {"record": true, "bytes": n} + binary frame
        per grid, then {"done": true, "records": k, "samples": n}."""
        dataset = req["dataset"]
        shard_num = int(req["shard"])
        shard = self.memstore.get_shard(dataset, shard_num)
        if shard is None:
            send_json_frame(sock, {"ok": False,
                                   "error": f"shard {shard_num} of "
                                            f"{dataset!r} not owned here"})
            return
        records = samples = 0
        for rec in iter_shard_grids(shard):
            body = rec.encode()
            send_json_frame(sock, {"ok": True, "record": True,
                                   "bytes": len(body)})
            _send_frame(sock, body)
            records += 1
            samples += rec.num_samples
        send_json_frame(sock, {"ok": True, "done": True,
                               "records": records, "samples": samples})

    def _drop_shard(self, sock, req: Dict) -> None:
        dataset = req["dataset"]
        shard_num = int(req["shard"])
        dropped = self.memstore.drop_shard(dataset, shard_num)
        with self._hlock:
            self._horizons.pop((dataset, shard_num), None)
        metrics_registry.counter("replication_shards_tombstoned",
                                 dataset=dataset).increment()
        send_json_frame(sock, {"ok": True, "dropped": dropped})


class ReplicaClient:
    """Pooled client for one peer's replication door (one socket per
    thread, like transport.RemoteNodeDispatcher)."""

    def __init__(self, host: str, port: int, timeout_s: float = 10.0):
        self.host, self.port = host, port
        self.timeout_s = timeout_s
        self._tls = threading.local()

    @property
    def where(self) -> str:
        return f"{self.host}:{self.port}"

    def _sock(self) -> socket.socket:
        s = getattr(self._tls, "sock", None)
        if s is None:
            s = socket.create_connection((self.host, self.port),
                                         timeout=self.timeout_s)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._tls.sock = s
        else:
            s.settimeout(self.timeout_s)
        return s

    def reset(self) -> None:
        s = getattr(self._tls, "sock", None)
        if s is not None:
            try:
                s.close()
            finally:
                self._tls.sock = None

    def _call(self, header: Dict, frames: Tuple[bytes, ...] = ()) -> Dict:
        """One verb: header + binary frames out, first control frame
        back.  Connection errors reset the pool and re-raise as OSError
        so callers classify peer death uniformly."""
        try:
            sock = self._sock()
            send_json_frame(sock, header)
            for fr in frames:
                _send_frame(sock, fr)
            reply = recv_json_frame(sock)
        except (ConnectionError, OSError, ValueError):
            self.reset()
            raise
        if not reply.get("ok"):
            raise ReplicationError(
                f"peer {self.where}: {reply.get('error', 'unknown error')}")
        return reply

    # --------------------------------------------------------------- verbs

    def ping(self) -> Dict:
        return self._call({"cmd": "ping"})

    def append_record(self, dataset: str, body: bytes,
                      seq: int = -1, restore: bool = False,
                      trace: str = "") -> Dict:
        """Ship one WalRecord-encoded slab (`seq` = the primary's WAL
        seq for replica-horizon bookkeeping; `restore` = part of a
        restore stream, applied even inside an open restore window;
        `trace` = the write-path trace id — the peer's WAL/ingest spans
        ride back in the ack under `spans`); returns the peer's ack."""
        hdr = {"cmd": "append", "dataset": dataset, "seq": seq}
        if restore:
            hdr["restore"] = True
        if trace:
            hdr["trace"] = trace
        return self._call(hdr, (body,))

    def begin_restore(self, dataset: str, shard: int) -> None:
        self._call({"cmd": "begin_restore", "dataset": dataset,
                    "shard": shard})

    def end_restore(self, dataset: str, shard: int) -> int:
        reply = self._call({"cmd": "end_restore", "dataset": dataset,
                            "shard": shard})
        return int(reply.get("applied", 0))

    def abort_restore(self, dataset: str, shard: int) -> None:
        self._call({"cmd": "abort_restore", "dataset": dataset,
                    "shard": shard})

    def horizons(self, dataset: str) -> Dict[int, int]:
        reply = self._call({"cmd": "horizon", "dataset": dataset})
        return {int(s): int(seq) for s, seq in reply["horizons"].items()}

    def drop_shard(self, dataset: str, shard: int) -> bool:
        reply = self._call({"cmd": "drop_shard", "dataset": dataset,
                            "shard": shard})
        return bool(reply.get("dropped"))

    def fetch_segments(self, dataset: str, since_seq: int = -1
                       ) -> Iterator[Tuple[int, bytes]]:
        """Yield (first_seq, segment bytes) from the peer's WAL; the
        final control frame ends iteration."""
        try:
            sock = self._sock()
            send_json_frame(sock, {"cmd": "fetch_wal", "dataset": dataset,
                                   "since_seq": since_seq})
            while True:
                ctl = recv_json_frame(sock)
                if not ctl.get("ok"):
                    raise ReplicationError(
                        f"peer {self.where}: "
                        f"{ctl.get('error', 'unknown error')}")
                if ctl.get("done"):
                    return
                data = _recv_frame(sock)
                yield int(ctl["segment"]), data
        except (ConnectionError, OSError, ValueError):
            self.reset()
            raise

    def snapshot_shard(self, dataset: str, shard: int
                       ) -> Iterator[bytes]:
        """Yield WalRecord-encoded grid bodies of the peer's shard."""
        try:
            sock = self._sock()
            send_json_frame(sock, {"cmd": "snapshot", "dataset": dataset,
                                   "shard": shard})
            while True:
                ctl = recv_json_frame(sock)
                if not ctl.get("ok"):
                    raise ReplicationError(
                        f"peer {self.where}: "
                        f"{ctl.get('error', 'unknown error')}")
                if ctl.get("done"):
                    return
                yield _recv_frame(sock)
        except (ConnectionError, OSError, ValueError):
            self.reset()
            raise
