"""Structured event journal — the node's flight recorder.

Counters say HOW MUCH; the journal says WHAT HAPPENED and WHEN, in
order.  Typed lifecycle events from every background subsystem — WAL
segment rotation/prune/commit-failure, boot replay, compaction and
retention runs, breaker open/half-open/close, mirror rebuilds and
over-cap degrades, eviction sweeps, rules/config reloads, node
join/dead from the cluster registry, server phase transitions — land in
one bounded ring with monotonic sequence numbers, served at

    GET /admin/events?since_seq=N&limit=K

so "what changed right before the p99 spike?" is one request, resumable
by sequence number (the CLI's `events --follow` tails it), and
correlatable with /admin/slowlog entries and trace ids by timestamp.
An optional JSONL sink mirrors every event to disk for post-mortem
import; the ring stays bounded either way (the Prometheus stance:
meta-monitoring must never be the thing that OOMs the monitor).

Emission is cheap (one lock, one dict) and NEVER raises: a broken sink
or a hostile field must not take down the subsystem reporting it.
"""
from __future__ import annotations

import collections
import json
import threading
import time
from typing import Dict, List, Optional


class EventJournal:

    DEFAULT_MAX = 2048

    def __init__(self, max_entries: int = DEFAULT_MAX, path: str = ""):
        self._lock = threading.Lock()
        self._seq = 0
        self._ring: "collections.deque[dict]" = collections.deque(
            maxlen=max_entries)
        self._path = path
        self._file = None

    # ----------------------------------------------------------- config

    def configure(self, max_entries: Optional[int] = None,
                  path: Optional[str] = None) -> None:
        """Re-point the ring size / JSONL sink (FiloServer calls this
        with its settings, like slowlog.configure).  Existing entries
        carry over up to the new bound."""
        with self._lock:
            if max_entries is not None and \
                    max_entries != self._ring.maxlen:
                self._ring = collections.deque(self._ring,
                                               maxlen=max(max_entries, 1))
            if path is not None and path != self._path:
                if self._file is not None:
                    try:
                        self._file.close()
                    except OSError:
                        pass
                self._path = path
                self._file = None

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            # seq keeps climbing: a follower's since_seq must stay valid
            # across an operator clear

    # ------------------------------------------------------------- emit

    def emit(self, kind: str, subsystem: str = "", **fields) -> int:
        """Record one event; returns its sequence number.  Never raises
        — the journal is observability, not control flow."""
        try:
            now = time.time()
            ev = {"kind": str(kind), "subsystem": str(subsystem),
                  "unixSeconds": round(now, 3)}
            for k, v in fields.items():
                if v is None:
                    continue
                ev[k] = v if isinstance(v, (int, float, bool)) \
                    else str(v)[:300]
            with self._lock:
                self._seq += 1
                ev["seq"] = self._seq
                self._ring.append(ev)
                seq = self._seq
                path, f = self._path, self._file
            from filodb_tpu.utils.metrics import registry
            registry.counter("events_emitted", kind=str(kind)).increment()
            if path:
                self._write_jsonl(ev)
            return seq
        except Exception:  # noqa: BLE001 — never sink the reporting caller
            return -1

    def _write_jsonl(self, ev: dict) -> None:
        try:
            with self._lock:
                if self._file is None:
                    self._file = open(self._path, "a")
                self._file.write(json.dumps(ev, separators=(",", ":"))
                                 + "\n")
                self._file.flush()
        except OSError:
            from filodb_tpu.utils.metrics import registry
            registry.counter("events_sink_errors").increment()

    # ------------------------------------------------------------- read

    @property
    def next_seq(self) -> int:
        with self._lock:
            return self._seq + 1

    def since(self, since_seq: int = 0, limit: int = 0,
              kind: str = "") -> List[dict]:
        """Events with seq > since_seq, oldest first; `limit` > 0 keeps
        the NEWEST that many (a follower catching up after a gap wants
        the recent tail, not a replay of everything it missed)."""
        with self._lock:
            out = [dict(ev) for ev in self._ring
                   if ev["seq"] > since_seq
                   and (not kind or ev["kind"] == kind)]
        if limit and len(out) > limit:
            out = out[-limit:]
        return out

    def snapshot(self) -> List[dict]:
        with self._lock:
            return [dict(ev) for ev in self._ring]


# process-wide instance (subsystems emit into it; the /admin/events
# route and the health evaluator read it)
journal = EventJournal()
