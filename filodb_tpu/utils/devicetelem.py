"""Process-wide device telemetry: the per-chip kernel ledger, HBM
occupancy model, and compile-cache observability (PR 18).

Every observability layer before this one stopped at the host boundary —
device work was a single `device_seconds` scalar per query plus a
`jit_cache_stats()` snapshot sampled at /metrics scrape time.  This
module is the device-side twin of the PR 3 query-attribution layer:

  - **kernel dispatch ledger** — every fused/general device call records
    a bounded ring entry {kernel, shape signature, device, wall seconds,
    bytes in/out, origin trace id} plus per-device cumulative counters.
    Call sites (query/fusedbatch.py, query/leafexec.py, parallel/mesh.py,
    core/devicecache.py) report through `record_dispatch`, which ALSO
    feeds the per-thread exec tally — so QueryStats.device_seconds and
    the ledger's per-query sum reconcile by construction (the parity
    test in tests/test_devicetelem.py).
  - **HBM occupancy model** — MirrorPlacer bookings, the cold segment
    cache, and the plan-mats cache feed `hbm_book(device, region, ±n)`,
    exposed as `device_hbm_booked_bytes{device,region}` gauges with a
    journaled `device_hbm_high_water` timeline.
  - **compile-cache events** — ops/pallas_fused pushes JIT compiles in
    at compile time (`record_compile`: jit_compile_seconds{kernel}
    histogram + ledger "compile" entries carrying shape + origin query),
    replacing the scrape-time `jit_cache_stats()` sampling hack.

Surfaces: `GET /admin/devices`, `filo-cli devices`, the `device`
subsystem in utils/health.HealthEvaluator, and — because everything here
lands in the plain metrics registry — the `_self_` self-scrape, so ruler
alerts fire on HBM pressure without extra plumbing.

Overhead stance: `record_dispatch` is a dict update + deque append + two
counter increments per KERNEL dispatch (not per series), bounded by the
bench gate `bench.py devicetelem` (≤2% on concurrent QPS).  The
`set_enabled(False)` kill switch skips ledger/metrics/span work but
NEVER the exec-tally feed — stats correctness is not optional.
"""
from __future__ import annotations

import collections
import math
import threading
import time
from typing import Dict, List, Optional

from filodb_tpu.utils.metrics import (NODE_NAME, collector,
                                      current_trace_id, log_error_once,
                                      note_device_call, registry)

# process-wide kill switch (bench.py devicetelem stage measures the
# ledger's own overhead by toggling this off).  The exec-tally feed in
# record_dispatch is NOT affected — only ring/metrics/span work.
TELEM_ENABLED = True


def set_enabled(flag: bool) -> None:
    global TELEM_ENABLED
    TELEM_ENABLED = bool(flag)


# utilization EWMA time constant: busy-seconds folded against a 30 s
# horizon, so a chip pegged for 30 s reads ~1.0 and an idle chip decays
# visibly within a dashboard refresh or two
EWMA_TAU_S = 30.0

# ring default — ~512 entries x ~200 B each keeps the ledger under
# ~100 KiB regardless of query rate
DEFAULT_MAX_ENTRIES = 512

# journal a device_hbm_high_water event only when the per-device total
# grows by at least this much (or 5% of the previous high water) — an
# occupancy TIMELINE, not a per-booking firehose
_HIGH_WATER_MIN_STEP = 1 << 20


def _dev_key(device) -> str:
    """Stable label value for a device: jax Devices stringify to e.g.
    'TFRT_CPU_0' / 'TPU_3', None means 'the default device'."""
    if device is None:
        return "default"
    return str(device)


class _DeviceState:
    """Per-device cumulative counters behind the telemetry lock."""

    __slots__ = ("dispatches", "busy_s", "bytes_in", "bytes_out",
                 "compiles", "compile_s", "util_ewma", "last_unix_s",
                 "kernels", "handles")

    def __init__(self):
        self.dispatches = 0
        self.busy_s = 0.0
        self.bytes_in = 0
        self.bytes_out = 0
        self.compiles = 0
        self.compile_s = 0.0
        self.util_ewma = 0.0
        self.last_unix_s = 0.0
        self.kernels: Dict[str, List[float]] = {}   # kernel -> [count, s]
        # kernel -> cached registry handles: re-resolving a tagged metric
        # per dispatch (kwargs dict + sorted tag tuple + registry lookup,
        # x5 metrics) dominated the ledger's tax on the hot dispatch path
        self.handles: Dict[str, tuple] = {}

    def fold_busy(self, seconds: float, now: float) -> None:
        """Utilization EWMA: decay by the gap since the last dispatch,
        then fold this dispatch's busy fraction in.  Approximates
        busy-seconds-per-wall-second over an EWMA_TAU_S horizon, clamped
        to 1.0 (overlapping dispatches can momentarily exceed it)."""
        if self.last_unix_s > 0.0:
            dt = max(now - self.last_unix_s, 0.0)
            self.util_ewma *= math.exp(-dt / EWMA_TAU_S)
        self.util_ewma = min(self.util_ewma + seconds / EWMA_TAU_S, 1.0)
        self.last_unix_s = now


class DeviceTelemetry:
    """The process-wide device telemetry hub (module global `telem`).

    Never raises toward a dispatch path: any internal failure is
    swallowed through metrics.log_error_once, because a broken ledger
    must not break queries."""

    def __init__(self, max_entries: int = DEFAULT_MAX_ENTRIES):
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(
            maxlen=max_entries)
        self._seq = 0
        self._devices: Dict[str, _DeviceState] = {}
        # device -> region -> bytes (the HBM occupancy model) + the
        # journaled per-device high-water mark
        self._hbm: Dict[str, Dict[str, int]] = {}
        self._high_water: Dict[str, int] = {}
        # (kernel, event) -> Counter, resolved once (hot: warm 'hit's)
        self._cache_event_counters: Dict[tuple, object] = {}

    # ------------------------------------------------------------ ledger

    def record_dispatch(self, kernel: str, device=None, shape: str = "",
                        seconds: float = 0.0, bytes_in: int = 0,
                        bytes_out: int = 0, kind: str = "kernel",
                        origin: Optional[str] = None,
                        note: bool = True) -> None:
        """One device call.  kind: 'kernel' (fused/general dispatches,
        feeds QueryStats.device_seconds parity when note=True) |
        'transfer' (mirror uploads / cold page-ins; stats attribution
        already handled by note_transfer, so note=False there) |
        'compile' (via record_compile).  `origin` defaults to the
        current trace id, tying every entry to the query that paid."""
        dev = _dev_key(device)
        if note and kind == "kernel":
            # the stats feed is unconditional — QueryStats.device_seconds
            # must not change when the ledger is toggled off
            note_device_call(dev, kernel, seconds)
        if not TELEM_ENABLED:
            return
        try:
            if origin is None:
                origin = current_trace_id() or ""
            now = time.time()
            st = self._devices.get(dev)
            if st is None:
                with self._lock:
                    st = self._devices.setdefault(dev, _DeviceState())
            h = st.handles.get(kernel)
            if h is None:
                # resolved once per (device, kernel), outside the telem
                # lock (registry has its own); a rare duplicate resolve
                # under a race lands on the same underlying metrics
                h = (registry.counter("device_kernel_dispatches",
                                      device=dev, kernel=kernel),
                     registry.counter("device_busy_seconds", device=dev),
                     registry.gauge("device_util_ewma", device=dev),
                     registry.counter("device_kernel_bytes", device=dev,
                                      dir="in"),
                     registry.counter("device_kernel_bytes", device=dev,
                                      dir="out"),
                     registry.histogram("span_kernel_dispatch_seconds",
                                        kernel=kernel))
                st.handles[kernel] = h
            with self._lock:
                self._seq += 1
                self._ring.append({
                    "seq": self._seq, "kind": kind, "kernel": kernel,
                    "device": dev, "shape": shape,
                    "seconds": round(seconds, 6),
                    "bytes_in": int(bytes_in),
                    "bytes_out": int(bytes_out),
                    "origin": origin, "unix_s": round(now, 3),
                })
                st.dispatches += 1
                st.bytes_in += int(bytes_in)
                st.bytes_out += int(bytes_out)
                if kind == "kernel":
                    st.busy_s += seconds
                    st.fold_busy(seconds, now)
                    cell = st.kernels.get(kernel)
                    if cell is None:
                        st.kernels[kernel] = [1, seconds]
                    else:
                        cell[0] += 1
                        cell[1] += seconds
                elif kind == "compile":
                    st.compiles += 1
                    st.compile_s += seconds
                util = st.util_ewma
            h[0].increment()
            if bytes_in:
                h[3].increment(bytes_in)
            if bytes_out:
                h[4].increment(bytes_out)
            if kind == "kernel":
                h[1].increment(seconds)
                h[2].update(util)
                # span event on the live trace (PR 12): the kernel shows
                # up inside the query's timeline with device tags, and
                # span_kernel_dispatch_seconds carries the exemplar
                h[5].record(seconds, exemplar=origin or None)
                if origin:
                    collector.record(origin, {
                        "span": "kernel_dispatch",
                        "dur_s": round(seconds, 6),
                        "end_unix_s": round(now, 3),
                        "node": NODE_NAME, "device": dev,
                        "kernel": kernel, "shape": shape})
        except Exception as exc:  # noqa: BLE001 — never break a dispatch
            log_error_once("devicetelem.record_dispatch", exc)

    # ---------------------------------------------------------- compiles

    def record_compile(self, kernel: str, shape: str = "",
                       seconds: float = 0.0, device=None,
                       cache_size: int = -1,
                       origin: Optional[str] = None) -> None:
        """A JIT compile observed AT COMPILE TIME (pallas_fused pushes
        these in when a jitted call grows its trace cache), replacing the
        old scrape-time jit_cache_stats() sampling — compile storms are
        attributable to query + shape, and restarts between scrapes no
        longer swallow events."""
        try:
            registry.counter("jit_compile_events", fn=kernel).increment()
            # compiles run seconds-scale, not ms — explicit bounds
            registry.histogram(
                "jit_compile_seconds",
                bounds=(0.01, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10,
                        25, 60, 120),
                kernel=kernel).record(seconds, exemplar=origin
                                      or current_trace_id())
            if cache_size >= 0:
                registry.gauge("jit_cache_entries",
                               fn=kernel).update(cache_size)
            from filodb_tpu.utils.events import journal
            journal.emit("jit_compile", subsystem="device", kernel=kernel,
                         shape=shape, seconds=round(seconds, 3),
                         origin=origin or current_trace_id() or "")
        except Exception as exc:  # noqa: BLE001
            log_error_once("devicetelem.record_compile", exc)
        self.record_dispatch(kernel, device=device, shape=shape,
                             seconds=seconds, kind="compile",
                             origin=origin, note=False)

    def record_cache_event(self, kernel: str, event: str) -> None:
        """Trace/plan-cache traffic: event = 'hit' | 'miss' | 'evict'.
        Handle-cached: 'hit' fires once per warm dispatch."""
        if not TELEM_ENABLED:
            return
        try:
            key = (kernel, event)
            c = self._cache_event_counters.get(key)
            if c is None:
                c = self._cache_event_counters.setdefault(
                    key, registry.counter("jit_cache_events",
                                          kernel=kernel, event=event))
            c.increment()
        except Exception as exc:  # noqa: BLE001
            log_error_once("devicetelem.record_cache_event", exc)

    # ----------------------------------------------------- HBM occupancy

    def hbm_book(self, device, region: str, delta: int) -> None:
        """Fold a booking delta into the per-device, per-region occupancy
        model.  Regions: 'hot' (live shard mirrors), 'cold'
        (ColdSegmentCache pages), 'planmats' (fused-plan matrix cache).
        Gauges clamp at zero — release races round down, never negative."""
        if not delta:
            return
        try:
            dev = _dev_key(device)
            with self._lock:
                regions = self._hbm.setdefault(dev, {})
                regions[region] = max(regions.get(region, 0) + int(delta),
                                      0)
                booked = regions[region]
                total = sum(regions.values())
                high = self._high_water.get(dev, 0)
                new_high = total > high + max(
                    _HIGH_WATER_MIN_STEP, int(high * 0.05))
                if new_high:
                    self._high_water[dev] = total
            registry.gauge("device_hbm_booked_bytes", device=dev,
                           region=region).update(booked)
            if new_high:
                registry.gauge("device_hbm_high_water_bytes",
                               device=dev).update(total)
                from filodb_tpu.utils.events import journal
                journal.emit("device_hbm_high_water", subsystem="device",
                             device=dev, bytes=total, region=region)
        except Exception as exc:  # noqa: BLE001
            log_error_once("devicetelem.hbm_book", exc)

    def hbm_set(self, device, region: str, nbytes: int) -> None:
        """Absolute variant of hbm_book for callers that track their own
        totals (set-to-current instead of delta arithmetic)."""
        try:
            dev = _dev_key(device)
            with self._lock:
                cur = self._hbm.get(dev, {}).get(region, 0)
            self.hbm_book(device, region, int(nbytes) - cur)
        except Exception as exc:  # noqa: BLE001
            log_error_once("devicetelem.hbm_set", exc)

    def hbm_booked(self, device, region: Optional[str] = None) -> int:
        dev = _dev_key(device)
        with self._lock:
            regions = self._hbm.get(dev, {})
            if region is not None:
                return regions.get(region, 0)
            return sum(regions.values())

    # ----------------------------------------------------------- queries

    def register_devices(self, devices) -> None:
        """Pre-register the local chips at boot so /admin/devices lists
        every device (zeroed) before the first dispatch lands."""
        try:
            with self._lock:
                for d in devices:
                    self._devices.setdefault(_dev_key(d), _DeviceState())
        except Exception as exc:  # noqa: BLE001
            log_error_once("devicetelem.register_devices", exc)

    def recent(self, limit: int = 50, device: str = "",
               kind: str = "") -> List[dict]:
        """Newest-first ledger entries, optionally filtered."""
        with self._lock:
            entries = list(self._ring)
        out = []
        for e in reversed(entries):
            if device and e["device"] != device:
                continue
            if kind and e["kind"] != kind:
                continue
            out.append(dict(e))
            if len(out) >= limit:
                break
        return out

    def snapshot(self, recent: int = 10) -> dict:
        """The /admin/devices payload: per-chip table + recent ledger."""
        with self._lock:
            now = time.time()
            devices = {}
            for dev, st in sorted(self._devices.items()):
                ewma = st.util_ewma
                if st.last_unix_s > 0.0:
                    # decay to NOW, not to the last dispatch — an idle
                    # chip must read idle without waiting for traffic
                    ewma *= math.exp(
                        -max(now - st.last_unix_s, 0.0) / EWMA_TAU_S)
                kern = sorted(st.kernels.items(),
                              key=lambda kv: -kv[1][1])
                devices[dev] = {
                    "dispatches": st.dispatches,
                    "busySeconds": round(st.busy_s, 6),
                    "utilEwma": round(ewma, 4),
                    "bytesIn": st.bytes_in,
                    "bytesOut": st.bytes_out,
                    "compiles": st.compiles,
                    "compileSeconds": round(st.compile_s, 3),
                    "lastDispatchUnixSeconds": round(st.last_unix_s, 3),
                    "hbm": dict(self._hbm.get(dev, {})),
                    "hbmHighWaterBytes": self._high_water.get(dev, 0),
                    "kernels": {k: {"count": int(c), "seconds":
                                    round(s, 6)} for k, (c, s) in kern},
                }
            # HBM-only devices (booked but never dispatched to) still
            # belong in the table — occupancy without traffic is exactly
            # the case an operator needs to see
            for dev, regions in sorted(self._hbm.items()):
                if dev not in devices and any(regions.values()):
                    devices[dev] = {
                        "dispatches": 0, "busySeconds": 0.0,
                        "utilEwma": 0.0, "bytesIn": 0, "bytesOut": 0,
                        "compiles": 0, "compileSeconds": 0.0,
                        "lastDispatchUnixSeconds": 0.0,
                        "hbm": dict(regions),
                        "hbmHighWaterBytes": self._high_water.get(dev, 0),
                        "kernels": {},
                    }
            ring = [dict(e) for e in
                    list(self._ring)[-max(recent, 0):]][::-1]
        return {"devices": devices, "recent": ring,
                "ledgerSeq": self._seq,
                "ledgerCapacity": self._ring.maxlen,
                "enabled": TELEM_ENABLED}

    def clear(self) -> None:
        """Test isolation: reset every table (NOT the metrics registry)."""
        with self._lock:
            self._ring.clear()
            self._seq = 0
            self._devices.clear()
            self._hbm.clear()
            self._high_water.clear()


telem = DeviceTelemetry()


def watched_call(kernel: str, jit_fn, shape: str, call, device=None):
    """Run `call()` (one dispatch of the jitted `jit_fn`) and detect an
    XLA compile by the trace-cache size delta around it — the compile-
    time push that replaces scrape-time jit_cache_stats() sampling.  A
    cache-size growth means THIS call paid a compile: its wall seconds
    (trace + lower + compile, dwarfing the dispatch) land in
    jit_compile_seconds{kernel} and a ledger 'compile' entry carrying
    shape + origin query, so a recompile storm is attributable.
    `_cache_size()` is a private jax API — any failure reading it
    degrades to plain dispatch, never an error."""
    if not TELEM_ENABLED:
        return call()
    before = -1
    try:
        before = int(jit_fn._cache_size())
    except Exception:  # noqa: BLE001 — private jax API, best-effort
        pass
    t0 = time.perf_counter()
    res = call()
    if before >= 0:
        try:
            after = int(jit_fn._cache_size())
            if after > before:
                telem.record_compile(kernel, shape=shape,
                                     seconds=time.perf_counter() - t0,
                                     device=device, cache_size=after)
            else:
                telem.record_cache_event(kernel, "hit")
        except Exception as exc:  # noqa: BLE001
            log_error_once("devicetelem.watched_call", exc)
    return res
