"""HOCON-lite parser: the practical subset of HOCON the reference's config
files use (ref: core/src/main/resources/filodb-defaults.conf,
conf/timeseries-dev-source.conf).

Supported: `key = value` / `key: value`, nested `block { ... }` sections
(block open on its own line), dotted paths (`a.b.c = 1`), `#` and `//`
comments, quoted and bare strings, ints/floats/booleans, `[a, b]` lists of
scalars (one line or multi-line), duration strings (`5 minutes`, `2h`)
exposed as Duration so typed consumers can convert to the unit a field
wants, and later-wins merging of duplicate paths.  Not supported (not used
by our configs): includes, substitutions (`${...}`), concatenation,
single-line inline blocks, and lists of objects — structures needing those
(e.g. spread_assignment) go in a .json config instead.
"""
from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, List, Tuple


class HoconError(ValueError):
    pass


@dataclasses.dataclass(frozen=True)
class Duration:
    """A parsed duration; consumers pick the unit (ms/s) they store."""
    millis: float

    @property
    def seconds(self) -> float:
        return self.millis / 1000.0


_DUR_UNITS = {
    "ms": 1.0, "milli": 1.0, "millis": 1.0, "millisecond": 1.0,
    "milliseconds": 1.0,
    "s": 1000.0, "second": 1000.0, "seconds": 1000.0,
    "m": 60_000.0, "minute": 60_000.0, "minutes": 60_000.0,
    "h": 3_600_000.0, "hour": 3_600_000.0, "hours": 3_600_000.0,
    "d": 86_400_000.0, "day": 86_400_000.0, "days": 86_400_000.0,
}

_DUR_RE = re.compile(r"^(\d+(?:\.\d+)?)\s*([a-zA-Z]+)$")


def _strip_comment(line: str) -> str:
    """Remove # / // comments outside quotes."""
    out = []
    in_q = False
    i = 0
    while i < len(line):
        ch = line[i]
        if ch == '"':
            in_q = not in_q
        if not in_q:
            if ch == "#":
                break
            if ch == "/" and line[i:i + 2] == "//":
                break
        out.append(ch)
        i += 1
    return "".join(out)


def _parse_scalar(tok: str) -> Any:
    tok = tok.strip()
    if tok.startswith('"') and tok.endswith('"') and len(tok) >= 2:
        return tok[1:-1]
    low = tok.lower()
    if low in ("true", "yes", "on"):
        return True
    if low in ("false", "no", "off"):
        return False
    if low in ("null", "none"):
        return None
    try:
        return int(tok)
    except ValueError:
        pass
    try:
        return float(tok)
    except ValueError:
        pass
    m = _DUR_RE.match(tok)
    if m and m.group(2).lower() in _DUR_UNITS:
        return Duration(float(m.group(1)) * _DUR_UNITS[m.group(2).lower()])
    return tok                       # bare string


def _parse_list(text: str) -> List[Any]:
    inner = text.strip()[1:-1]
    if not inner.strip():
        return []
    items = []
    depth = 0
    cur = []
    in_q = False
    for ch in inner:
        if ch == '"':
            in_q = not in_q
        if not in_q:
            if ch in "[{":
                depth += 1
            elif ch in "]}":
                depth -= 1
            elif ch == "," and depth == 0:
                items.append("".join(cur))
                cur = []
                continue
        cur.append(ch)
    if "".join(cur).strip():
        items.append("".join(cur))
    return [_parse_scalar(i) for i in items]


def _set_path(root: Dict, path: List[str], value: Any) -> None:
    cur = root
    for p in path[:-1]:
        nxt = cur.get(p)
        if not isinstance(nxt, dict):
            nxt = {}
            cur[p] = nxt
        cur = nxt
    key = path[-1]
    if isinstance(value, dict) and isinstance(cur.get(key), dict):
        _merge(cur[key], value)      # later keys merge into earlier blocks
    else:
        cur[key] = value


def _merge(dst: Dict, src: Dict) -> None:
    for k, v in src.items():
        if isinstance(v, dict) and isinstance(dst.get(k), dict):
            _merge(dst[k], v)
        else:
            dst[k] = v


def loads(text: str) -> Dict[str, Any]:
    """Parse HOCON-lite text into a nested dict."""
    root: Dict[str, Any] = {}
    stack: List[Dict[str, Any]] = [root]
    pending_list_key = None
    pending_list_buf: List[str] = []

    for lineno, raw in enumerate(text.splitlines(), 1):
        line = _strip_comment(raw).strip()
        if not line:
            continue
        if pending_list_key is not None:
            pending_list_buf.append(line)
            joined = " ".join(pending_list_buf)
            # bracket-depth check so a ']' inside a nested list does not
            # terminate the outer one
            if joined.count("]") >= joined.count("["):
                _set_path(stack[-1], pending_list_key, _parse_list(joined))
                pending_list_key = None
                pending_list_buf = []
            continue
        if line == "}":
            if len(stack) == 1:
                raise HoconError(f"line {lineno}: unmatched '}}'")
            stack.pop()
            continue
        m = re.match(r'^("?[^"={:\s]+"?(?:\.[^"={:\s]+)*)\s*[:=]?\s*\{\s*$',
                     line)
        if m:
            path = [p.strip('"') for p in m.group(1).split(".")]
            cur = stack[-1]
            for p in path:
                nxt = cur.get(p)
                if not isinstance(nxt, dict):
                    nxt = {}
                    cur[p] = nxt
                cur = nxt
            stack.append(cur)
            continue
        m = re.match(r'^("?[^"={:\s]+"?(?:\.[^"={:\s]+)*)\s*[:=]\s*(.+)$',
                     line)
        if not m:
            raise HoconError(f"line {lineno}: cannot parse {raw!r}")
        path = [p.strip('"') for p in m.group(1).split(".")]
        rhs = m.group(2).strip()
        if rhs.startswith("[") and "]" not in rhs:
            pending_list_key = path
            pending_list_buf = [rhs]
            continue
        if rhs.startswith("["):
            _set_path(stack[-1], path, _parse_list(rhs))
        else:
            _set_path(stack[-1], path, _parse_scalar(rhs))
    if pending_list_key is not None:
        raise HoconError("unterminated list")
    if len(stack) != 1:
        raise HoconError("unterminated block")
    return root


def load(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return loads(f.read())
