"""Bit-exact xxHash32/xxHash64 for partition-key and shard-key hashing.

The reference hashes partKey bytes with xxHash32 (ref:
memory/src/main/scala/filodb.memory/format/BinaryRegion.scala:14 `hasher32`) and
derives shard-key hashes from label values (ref:
core/src/main/scala/filodb.core/binaryrecord2/RecordBuilder.scala:604-619).
These hashes route every record to a shard, so gateway, ingest and query layers
must agree bit-for-bit.  A C implementation (filodb_tpu/native) is used when
built; this pure-Python one is the always-available fallback and the reference
for tests.
"""
from __future__ import annotations

import struct

_PRIME32_1 = 0x9E3779B1
_PRIME32_2 = 0x85EBCA77
_PRIME32_3 = 0xC2B2AE3D
_PRIME32_4 = 0x27D4EB2F
_PRIME32_5 = 0x165667B1
_M32 = 0xFFFFFFFF

_PRIME64_1 = 0x9E3779B185EBCA87
_PRIME64_2 = 0xC2B2AE3D27D4EB4F
_PRIME64_3 = 0x165667B19E3779F9
_PRIME64_4 = 0x85EBCA77C2B2AE63
_PRIME64_5 = 0x27D4EB2F165667C5
_M64 = 0xFFFFFFFFFFFFFFFF


def _rotl32(x: int, r: int) -> int:
    return ((x << r) | (x >> (32 - r))) & _M32


def _rotl64(x: int, r: int) -> int:
    return ((x << r) | (x >> (64 - r))) & _M64


def _round32(acc: int, lane: int) -> int:
    acc = (acc + lane * _PRIME32_2) & _M32
    return (_rotl32(acc, 13) * _PRIME32_1) & _M32


def xxhash32(data: bytes, seed: int = 0) -> int:
    """XXH32 of `data`.  Returns an unsigned 32-bit int."""
    n = len(data)
    idx = 0
    if n >= 16:
        v1 = (seed + _PRIME32_1 + _PRIME32_2) & _M32
        v2 = (seed + _PRIME32_2) & _M32
        v3 = seed & _M32
        v4 = (seed - _PRIME32_1) & _M32
        limit = n - 16
        while idx <= limit:
            l1, l2, l3, l4 = struct.unpack_from("<IIII", data, idx)
            v1 = _round32(v1, l1)
            v2 = _round32(v2, l2)
            v3 = _round32(v3, l3)
            v4 = _round32(v4, l4)
            idx += 16
        h = (_rotl32(v1, 1) + _rotl32(v2, 7) + _rotl32(v3, 12) + _rotl32(v4, 18)) & _M32
    else:
        h = (seed + _PRIME32_5) & _M32
    h = (h + n) & _M32
    while idx + 4 <= n:
        (lane,) = struct.unpack_from("<I", data, idx)
        h = (h + lane * _PRIME32_3) & _M32
        h = (_rotl32(h, 17) * _PRIME32_4) & _M32
        idx += 4
    while idx < n:
        h = (h + data[idx] * _PRIME32_5) & _M32
        h = (_rotl32(h, 11) * _PRIME32_1) & _M32
        idx += 1
    h ^= h >> 15
    h = (h * _PRIME32_2) & _M32
    h ^= h >> 13
    h = (h * _PRIME32_3) & _M32
    h ^= h >> 16
    return h


def _round64(acc: int, lane: int) -> int:
    acc = (acc + lane * _PRIME64_2) & _M64
    return (_rotl64(acc, 31) * _PRIME64_1) & _M64


def _merge64(acc: int, val: int) -> int:
    acc ^= _round64(0, val)
    return (acc * _PRIME64_1 + _PRIME64_4) & _M64


def xxhash64(data: bytes, seed: int = 0) -> int:
    """XXH64 of `data`.  Returns an unsigned 64-bit int."""
    n = len(data)
    idx = 0
    if n >= 32:
        v1 = (seed + _PRIME64_1 + _PRIME64_2) & _M64
        v2 = (seed + _PRIME64_2) & _M64
        v3 = seed & _M64
        v4 = (seed - _PRIME64_1) & _M64
        limit = n - 32
        while idx <= limit:
            l1, l2, l3, l4 = struct.unpack_from("<QQQQ", data, idx)
            v1 = _round64(v1, l1)
            v2 = _round64(v2, l2)
            v3 = _round64(v3, l3)
            v4 = _round64(v4, l4)
            idx += 32
        h = (_rotl64(v1, 1) + _rotl64(v2, 7) + _rotl64(v3, 12) + _rotl64(v4, 18)) & _M64
        h = _merge64(h, v1)
        h = _merge64(h, v2)
        h = _merge64(h, v3)
        h = _merge64(h, v4)
    else:
        h = (seed + _PRIME64_5) & _M64
    h = (h + n) & _M64
    while idx + 8 <= n:
        (lane,) = struct.unpack_from("<Q", data, idx)
        h ^= _round64(0, lane)
        h = (_rotl64(h, 27) * _PRIME64_1 + _PRIME64_4) & _M64
        idx += 8
    if idx + 4 <= n:
        (lane,) = struct.unpack_from("<I", data, idx)
        h ^= (lane * _PRIME64_1) & _M64
        h = (_rotl64(h, 23) * _PRIME64_2 + _PRIME64_3) & _M64
        idx += 4
    while idx < n:
        h ^= (data[idx] * _PRIME64_5) & _M64
        h = (_rotl64(h, 11) * _PRIME64_1) & _M64
        idx += 1
    h ^= h >> 33
    h = (h * _PRIME64_2) & _M64
    h ^= h >> 29
    h = (h * _PRIME64_3) & _M64
    h ^= h >> 32
    return h


def hash32_signed(data: bytes, seed: int = 0) -> int:
    """xxhash32 as a signed 32-bit int (the JVM reference works in Int)."""
    h = xxhash32(data, seed)
    return h - (1 << 32) if h >= (1 << 31) else h


# Optional C acceleration (filodb_tpu/native/libfilodb_native.so); falls back
# silently to the Python implementations above.
try:  # pragma: no cover - exercised only when the native lib is built
    from filodb_tpu.native import lib as _native

    if _native is not None:
        _py_xxhash32 = xxhash32
        _py_xxhash64 = xxhash64

        def xxhash32(data: bytes, seed: int = 0) -> int:  # noqa: F811
            return _native.xxhash32(data, seed)

        def xxhash64(data: bytes, seed: int = 0) -> int:  # noqa: F811
            return _native.xxhash64(data, seed)
except Exception:  # pragma: no cover
    pass
