"""Unified background-job registry — one answer to "what is this node
doing, and is any of it wedged?".

PRs 1-8 grew a fleet of recurring workers — the flush scheduler, the WAL
group committer, the segment compactor/retention pass, ruler group
runners, device-mirror background rebuilds, the trace exporter, the
self-scrape loop — each with its own scattered counters and no common
place an operator (or the health evaluator) can ask for last-run /
duration / error-streak state.  The reference ships exactly this surface
as its shard-status admin (ref: HealthRoute.scala / ClusterApiRoute.scala);
Prometheus exposes the analogue per-scrape-loop and per-rule-group.

Every worker registers a `JobHandle` and reports ticks through it:

  * `with handle.tick(): ...` — records start/end, feeds the
    `job_duration_seconds{job,dataset}` histogram, tracks lag vs the
    declared schedule (`job_lag_seconds`: gap between consecutive starts
    minus the interval — a starving scheduler shows here long before it
    misses anything visibly), and maintains the consecutive-error
    streak.  An exception escaping the tick marks it failed and
    re-raises; a loop that catches internally calls `note_error`
    mid-tick (or standalone) instead.
  * `handle.set_progress("shard 3/8")` — a human-readable string for
    the current position, shown at GET /admin/jobs.

Registry metrics (`job_runs_total`, `job_errors_total`,
`job_consecutive_errors` gauge) make every job alertable via the
self-scrape loop (utils/selfmon.py) — the shipped example alert group
fires on `job_consecutive_errors >= N`.  The registry itself is bounded
(MAX_JOBS): a pathological caller minting job names cannot grow it (or
the metric registry's tag space) without bound — overflow handles work
but are not retained or exported.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

# seconds-scale duration/lag bounds (the registry default histogram is
# tuned for millisecond latencies; background jobs run for seconds)
_SECONDS_BOUNDS = (0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 2.5,
                   5.0, 10.0, 30.0, 60.0, 300.0, 1800.0)


class JobHandle:
    """One recurring worker's observable state.  Thread-safe: ticks and
    snapshots may race (the flush thread ticks while an HTTP scrape
    snapshots)."""

    def __init__(self, name: str, interval_s: float = 0.0,
                 dataset: str = "", critical: bool = False,
                 exported: bool = True):
        self.name = name
        self.dataset = dataset
        # False for registry-overflow handles: state still tracks, but
        # no per-job metric tags are minted (hostile name churn must not
        # grow the metric registry either)
        self.exported = exported
        # declared schedule; 0 = event-driven (no lag accounting)
        self.interval_s = float(interval_s)
        # critical jobs failing (streak >= failed_streak) flip /ready to
        # 503 — the flush scheduler and WAL committer qualify; a broken
        # trace exporter does not
        self.critical = bool(critical)
        # error streak at or past this = the health verdict "failed"
        # (below it but nonzero = "degraded")
        self.failed_streak = 5
        self._lock = threading.Lock()
        self.runs = 0
        self.errors = 0
        self.consecutive_errors = 0
        self.last_start_unix_s = 0.0
        self.last_end_unix_s = 0.0
        self.last_duration_s = 0.0
        self.last_error = ""
        self.last_error_unix_s = 0.0
        self.progress = ""
        self.running = False

    # ------------------------------------------------------------- ticks

    def tick(self) -> "_Tick":
        return _Tick(self)

    def note_ok(self, duration_s: Optional[float] = None) -> None:
        """Event-driven success (jobs without a tick scope, e.g. one WAL
        group commit)."""
        now = time.time()
        with self._lock:
            self.runs += 1
            self.consecutive_errors = 0
            self.last_end_unix_s = now
            if duration_s is not None:
                self.last_duration_s = duration_s
        self._export(duration_s)

    def note_error(self, err, duration_s: Optional[float] = None) -> None:
        """One failed run (standalone, or mid-tick from a loop that
        catches its own exceptions — the enclosing tick then reports
        failed without double-counting)."""
        from filodb_tpu.utils.metrics import registry
        now = time.time()
        with self._lock:
            self.runs += 1
            self.errors += 1
            self.consecutive_errors += 1
            self.last_error = f"{err}"[:300]
            self.last_error_unix_s = now
            self.last_end_unix_s = now
            if duration_s is not None:
                self.last_duration_s = duration_s
            streak = self.consecutive_errors
        if self.exported:
            registry.counter("job_errors", **self._tags()).increment()
        self._export(duration_s)
        if streak == self.failed_streak:
            # one journal entry at the ok->failed edge (not per error:
            # a wedged job must not flood the flight recorder)
            from filodb_tpu.utils.events import journal
            journal.emit("job_failed", subsystem="jobs", job=self.name,
                         dataset=self.dataset, streak=streak,
                         error=self.last_error)

    def set_progress(self, text: str) -> None:
        self.progress = str(text)[:200]

    def _tags(self) -> Dict[str, str]:
        tags = {"job": self.name}
        if self.dataset:
            tags["dataset"] = self.dataset
        return tags

    def _export(self, duration_s: Optional[float]) -> None:
        if not self.exported:
            return
        from filodb_tpu.utils.metrics import registry
        tags = self._tags()
        registry.counter("job_runs", **tags).increment()
        registry.gauge("job_consecutive_errors", **tags).update(
            self.consecutive_errors)
        if duration_s is not None:
            registry.histogram("job_duration_seconds",
                               bounds=_SECONDS_BOUNDS,
                               **tags).record(duration_s)

    def _note_lag(self, start_unix_s: float) -> None:
        from filodb_tpu.utils.metrics import registry
        if not self.exported or self.interval_s <= 0 \
                or self.last_start_unix_s <= 0:
            return
        lag = (start_unix_s - self.last_start_unix_s) - self.interval_s
        registry.histogram("job_lag_seconds", bounds=_SECONDS_BOUNDS,
                           **self._tags()).record(max(lag, 0.0))

    # ---------------------------------------------------------- snapshot

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "job": self.name,
                "dataset": self.dataset,
                "intervalSeconds": self.interval_s,
                "critical": self.critical,
                "running": self.running,
                "runs": self.runs,
                "errors": self.errors,
                "consecutiveErrors": self.consecutive_errors,
                "failedStreak": self.failed_streak,
                "lastStartUnixSeconds": round(self.last_start_unix_s, 3),
                "lastEndUnixSeconds": round(self.last_end_unix_s, 3),
                "lastDurationSeconds": round(self.last_duration_s, 6),
                "lastError": self.last_error,
                "progress": self.progress,
            }


class _Tick:
    """One run of a job: duration + lag + streak accounting.  Exceptions
    re-raise after being recorded; `note_error` calls inside the scope
    mark the tick failed without double-counting the run; `skip()` makes
    the tick NEUTRAL — neither a run nor a streak reset."""

    def __init__(self, handle: JobHandle):
        self.handle = handle
        self._skipped = False

    def skip(self) -> None:
        """This tick attempted no work (every target was in backoff,
        nothing to do after an error): complete neutrally.  Without
        this, a loop whose only failing target is backing off would
        record empty passes as successes and reset the consecutive-
        error streak the health verdict depends on — a permanently
        broken critical job could never flip /ready."""
        self._skipped = True

    def __enter__(self):
        h = self.handle
        now = time.time()
        h._note_lag(now)
        self._errors0 = h.errors
        self._t0 = time.perf_counter()
        with h._lock:
            h.last_start_unix_s = now
            h.running = True
        return self

    def __exit__(self, exc_type, exc, tb):
        h = self.handle
        dur = time.perf_counter() - self._t0
        failed_inside = h.errors > self._errors0
        with h._lock:
            h.running = False
        if exc is not None:
            h.note_error(exc, duration_s=dur)
        elif failed_inside or self._skipped:
            # failed: note_error already counted the run.  skipped:
            # neutral — record the timing, leave runs/streak untouched
            with h._lock:
                h.last_duration_s = dur
                h.last_end_unix_s = time.time()
        else:
            h.note_ok(duration_s=dur)
        return False


class JobRegistry:
    """Process-wide registry keyed by (name, dataset).  Bounded: past
    MAX_JOBS, register() returns a working but UNRETAINED handle (and
    counts the overflow) so hostile/buggy name churn can neither grow
    this table nor the metric registry's tag space without bound."""

    MAX_JOBS = 256

    def __init__(self):
        self._lock = threading.Lock()
        self._jobs: Dict[Tuple[str, str], JobHandle] = {}

    def register(self, name: str, interval_s: float = 0.0,
                 dataset: str = "", critical: bool = False) -> JobHandle:
        key = (name, dataset)
        with self._lock:
            h = self._jobs.get(key)
            if h is not None:
                # re-registration (scheduler restart, ruler reload):
                # same handle, refreshed schedule — history carries over
                h.interval_s = float(interval_s) or h.interval_s
                h.critical = h.critical or critical
                return h
            retained = len(self._jobs) < self.MAX_JOBS
            h = JobHandle(name, interval_s, dataset, critical,
                          exported=retained)
            if retained:
                self._jobs[key] = h
            else:
                from filodb_tpu.utils.metrics import registry
                registry.counter("job_registry_overflow").increment()
        return h

    def unregister(self, name: str, dataset: str = "") -> None:
        with self._lock:
            self._jobs.pop((name, dataset), None)

    def get(self, name: str, dataset: str = "") -> Optional[JobHandle]:
        with self._lock:
            return self._jobs.get((name, dataset))

    def clear(self) -> None:
        with self._lock:
            self._jobs.clear()

    def snapshot(self) -> List[dict]:
        with self._lock:
            handles = list(self._jobs.values())
        out = [h.snapshot() for h in handles]
        out.sort(key=lambda j: (j["job"], j["dataset"]))
        return out


# process-wide instance (schedulers, the health evaluator, and the
# /admin/jobs route share it — like metrics.registry and usage.usage)
jobs = JobRegistry()
