"""Health model: fold the fleet's scattered state into one verdict tree.

The reference serves this as HealthRoute.scala / ClusterApiRoute.scala
shard-status admin; Prometheus splits it into /-/healthy (liveness) and
/-/ready (readiness).  Here:

    GET /healthz               liveness — the process and its HTTP loop
                               answer; always 200 while alive
    GET /ready                 readiness — 503 during boot WAL replay /
                               shard recovery and while a critical
                               subsystem is failed; the signal a load
                               balancer or rolling restart waits on
    GET /api/v1/status/health  the full per-subsystem verdict tree

`HealthEvaluator` computes the tree on demand from the live sources —
the job registry (consecutive-error streaks), the breaker registry
(open peers), WAL replay/commit state, shard-mapper statuses, and
recent device-mirror over-cap degrades from the event journal — so the
verdict can never go stale between polls.  Verdicts are ok | degraded |
failed, worst-wins up the tree.

Phase machinery: the server moves booting -> replaying_wal -> booted ->
serving -> stopping; every transition lands in the event journal, and
/ready answers 200 only in `serving` — which is what makes "the node
restarted, replayed its WAL, and took traffic again" one greppable
sequence in /admin/events.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

OK, DEGRADED, FAILED = "ok", "degraded", "failed"
_RANK = {OK: 0, DEGRADED: 1, FAILED: 2}

BOOTING = "booting"
REPLAYING_WAL = "replaying_wal"
BOOTED = "booted"
SERVING = "serving"
STOPPING = "stopping"

# mirror over-cap degrades older than this no longer color the verdict
# (counters are cumulative; one spill a week ago is not a live problem)
RECENT_WINDOW_S = 300.0


def _worst(verdicts) -> str:
    out = OK
    for v in verdicts:
        if _RANK.get(v, 0) > _RANK[out]:
            out = v
    return out


class HealthEvaluator:
    """One server's health state + verdict computation.  Attached to
    PromHttpApi by FiloServer; bare API constructions get a default
    instance already in `serving` so route-level tests behave as
    before."""

    def __init__(self, node_name: str = "local", phase: str = SERVING):
        self.node = node_name
        self.phase = phase
        # non-empty while this node is drained for a rolling restart
        # (replication/handoff.py drain; POST /admin/shards/../handoff
        # with drain=true): /ready answers 503 so the load balancer
        # stops routing here before the process restarts
        self.draining = ""
        self._lock = threading.Lock()
        self.started_unix_s = time.time()
        # dataset -> {"enabled", "replayDone", "replayRecords", ...}
        self._wal: Dict[str, dict] = {}
        # dataset -> ShardMapper (status snapshots on demand)
        self.shard_mappers: Dict[str, object] = {}
        # extra per-subsystem probes: name -> zero-arg callable returning
        # a {"status": ...} dict (lets tests and future subsystems plug
        # in without touching the evaluator)
        self.probes: Dict[str, Callable[[], dict]] = {}
        # disaggregated cold tier (persist/objectstore.py): dataset ->
        # manifest mount completed.  A node with the shared tier
        # configured — data node restoring on boot, or a stateless
        # query-only node — answers /ready 503 until every mount lands:
        # serving cold ranges before the catalog is readable would
        # return silently-short "full" results
        self._manifest_mounts: Dict[str, bool] = {}

    # ------------------------------------------------------------ phases

    def set_phase(self, phase: str, **fields) -> None:
        from filodb_tpu.utils.events import journal
        with self._lock:
            prev, self.phase = self.phase, phase
        if prev != phase:
            journal.emit("phase", subsystem="server", node=self.node,
                         frm=prev, to=phase, **fields)

    # --------------------------------------------------------------- wal

    def note_wal(self, dataset: str, enabled: bool,
                 replay_done: bool = False, stats=None) -> None:
        ent = {"enabled": enabled, "replayDone": replay_done}
        if stats is not None:
            ent.update({"replayRecords": stats.records,
                        "replaySamples": stats.samples,
                        "corruptSegments": stats.corrupt_segments,
                        "replaySeconds": round(stats.elapsed_s, 3)})
        with self._lock:
            self._wal[dataset] = ent

    def wal_summary(self) -> Dict[str, dict]:
        with self._lock:
            return {ds: dict(ent) for ds, ent in self._wal.items()}

    # ------------------------------------------------------- persistence

    def note_manifest_mount(self, dataset: str, mounted: bool) -> None:
        """Cold-tier manifest mount progress (persist/objectstore.py):
        registered False when the shared tier is configured, flipped
        True once the mount/restore lands — /ready gates on it."""
        with self._lock:
            self._manifest_mounts[dataset] = bool(mounted)

    def pending_manifest_mounts(self) -> List[str]:
        with self._lock:
            return sorted(ds for ds, ok in self._manifest_mounts.items()
                          if not ok)

    # --------------------------------------------------------- subsystems

    def _jobs_verdict(self) -> dict:
        from filodb_tpu.utils.jobs import jobs
        per = {}
        worst = OK
        critical_failed: List[str] = []
        for snap in jobs.snapshot():
            streak = snap["consecutiveErrors"]
            # the per-handle threshold — the same one note_error journals
            # the job_failed edge at, so the verdict and the flight
            # recorder can never disagree about where "failed" starts
            if streak >= snap["failedStreak"]:
                v = FAILED
            elif streak > 0:
                v = DEGRADED
            else:
                v = OK
            if v == FAILED and snap["critical"]:
                critical_failed.append(snap["job"])
            key = snap["job"] + (f":{snap['dataset']}"
                                 if snap["dataset"] else "")
            per[key] = {"status": v, "consecutiveErrors": streak,
                        "lastError": snap["lastError"],
                        "progress": snap["progress"]}
            worst = _worst((worst, v))
        return {"status": worst, "jobs": per,
                "criticalFailed": sorted(critical_failed)}

    def _peers_verdict(self) -> dict:
        from filodb_tpu.parallel.breaker import breakers
        open_peers, half_open = [], []
        for b in breakers.snapshot():
            if b["state"] == "open":
                open_peers.append(b["peer"])
            elif b["state"] == "half_open":
                half_open.append(b["peer"])
        status = DEGRADED if (open_peers or half_open) else OK
        return {"status": status, "open": sorted(open_peers),
                "halfOpen": sorted(half_open)}

    def _wal_verdict(self) -> dict:
        with self._lock:
            datasets = {ds: dict(ent) for ds, ent in self._wal.items()}
        worst = OK
        for ent in datasets.values():
            if ent["enabled"] and not ent["replayDone"]:
                worst = _worst((worst, DEGRADED))
            if ent.get("corruptSegments"):
                # acknowledged data was lost in the damaged region —
                # serving works, but the durability claim is degraded
                worst = _worst((worst, DEGRADED))
        return {"status": worst, "datasets": datasets}

    def _shards_verdict(self) -> dict:
        datasets = {}
        worst = OK
        recovering = 0
        for ds, mapper in self.shard_mappers.items():
            snap = mapper.status_snapshot()
            by_status: Dict[str, int] = {}
            for _i, (_addr, st) in snap.items():
                by_status[st] = by_status.get(st, 0) + 1
            active = by_status.get("Active", 0)
            rec = by_status.get("Recovery", 0)
            bad = by_status.get("Error", 0) + by_status.get("Down", 0)
            recovering += rec
            v = OK
            if rec or (bad and active):
                v = DEGRADED
            if len(snap) and active == 0:
                v = FAILED
            ent = {"counts": by_status}
            # replication intent vs reality (doc/replication.md): a
            # shard short of its owner target — in particular a primary
            # serving with ZERO live replicas — is one failure from
            # partials, so the verdict degrades even though serving is
            # currently fine
            rf = getattr(mapper, "replication_factor", 1)
            if rf >= 2 and hasattr(mapper, "live_owners"):
                under = dead = 0
                for s in range(mapper.num_shards):
                    live = len(mapper.live_owners(s))
                    if live == 0:
                        dead += 1
                    elif live < rf:
                        under += 1
                ent["underReplicated"] = under
                ent["noLiveOwners"] = dead
                if dead:
                    v = FAILED
                elif under:
                    v = _worst((v, DEGRADED))
            worst = _worst((worst, v))
            ent["status"] = v
            datasets[ds] = ent
        return {"status": worst, "datasets": datasets,
                "recovering": recovering}

    def _ingest_verdict(self) -> dict:
        """Write-path freshness SLO (utils/freshness.py): sustained
        ingest-to-ack breaches — e.g. a disk whose fsyncs started
        stalling — degrade the verdict until the breach window drains.
        A single slow batch never colors it; the tracker requires
        `ingest.freshness_breach_count` breaches inside the window."""
        from filodb_tpu.utils.freshness import freshness
        return freshness.verdict()

    def _mirror_verdict(self) -> dict:
        from filodb_tpu.utils.events import journal
        cutoff = time.time() - RECENT_WINDOW_S
        recent = [ev for ev in journal.since(0, kind="mirror_over_cap")
                  if ev["unixSeconds"] >= cutoff]
        return {"status": DEGRADED if recent else OK,
                "recentOverCap": len(recent)}

    # compile-storm threshold: this many query-attributed jit compiles
    # of the SAME kernel inside RECENT_WINDOW_S degrade the device
    # subsystem.  A storm is one kernel recompiling over and over (new
    # shapes defeating its trace cache); scattered first-compiles across
    # many kernels are a process warming up, not a storm.  Boot warmup
    # compiles carry no origin and never count.
    compile_storm_count = 10
    # a storm is *rapid* recompilation — 10 same-kernel compiles inside
    # two minutes, not 10 spread over the journal's lifetime.  Tighter
    # than RECENT_WINDOW_S on purpose: organic shape churn (new
    # datasets warming, ad-hoc queries) trickles compiles in slowly.
    compile_storm_window_s = 120.0
    # sustained HBM pressure: this many over-cap degrades in the window
    # (one spill is the mirror subsystem's business; a stream of them
    # means placement is thrashing)
    device_over_cap_count = 3

    def _device_verdict(self) -> dict:
        """Device telemetry verdict (PR 18, utils/devicetelem): a
        recompile storm (every query paying an XLA compile — new shapes
        defeating the trace cache) or sustained HBM over-cap degrades ⇒
        degraded, with the counts an operator needs to pick between
        /admin/devices and the slowlog as the next hop."""
        from filodb_tpu.utils.events import journal
        now = time.time()
        compiles = [ev for ev in journal.since(0, kind="jit_compile")
                    if ev["unixSeconds"] >= now - self.compile_storm_window_s
                    and ev.get("origin")]
        over_cap = [ev for ev in journal.since(0, kind="mirror_over_cap")
                    if ev["unixSeconds"] >= now - RECENT_WINDOW_S]
        by_kernel: dict = {}
        for ev in compiles:
            k = ev.get("kernel", "")
            by_kernel[k] = by_kernel.get(k, 0) + 1
        storm_kernel, storm_n = "", 0
        if by_kernel:
            storm_kernel = max(by_kernel, key=by_kernel.get)
            storm_n = by_kernel[storm_kernel]
        status = OK
        reasons = []
        if storm_n >= self.compile_storm_count:
            status = DEGRADED
            reasons.append("compile_storm")
        if len(over_cap) >= self.device_over_cap_count:
            status = DEGRADED
            reasons.append("hbm_over_cap")
        return {"status": status, "reasons": reasons,
                "recentCompiles": len(compiles),
                "stormKernel": storm_kernel if storm_n >= self.compile_storm_count else "",
                "recentOverCap": len(over_cap)}

    # ----------------------------------------------------------- verdicts

    def evaluate(self) -> dict:
        subs = {
            "jobs": self._jobs_verdict(),
            "peers": self._peers_verdict(),
            "wal": self._wal_verdict(),
            "shards": self._shards_verdict(),
            "mirror": self._mirror_verdict(),
            "ingest": self._ingest_verdict(),
            "device": self._device_verdict(),
        }
        for name, probe in self.probes.items():
            try:
                subs[name] = probe()
            except Exception as e:  # noqa: BLE001 — a broken probe is a
                # verdict, not a crashed health endpoint
                subs[name] = {"status": FAILED,
                              "error": f"{type(e).__name__}: {e}"[:200]}
        status = _worst(s["status"] for s in subs.values())
        if self.phase != SERVING:
            status = _worst((status, DEGRADED))
        return {"status": status, "phase": self.phase, "node": self.node,
                "startedUnixSeconds": round(self.started_unix_s, 3),
                "subsystems": subs}

    def ready(self) -> "tuple[bool, str]":
        """(ready, reason).  Not ready during boot WAL replay / shard
        recovery and while a critical subsystem is failed — exactly the
        signal a load balancer or rolling restart needs."""
        if self.phase != SERVING:
            return False, f"phase={self.phase}"
        if self.draining:
            return False, f"draining: {self.draining}"
        jv = self._jobs_verdict()
        if jv["criticalFailed"]:
            return False, ("critical job failed: "
                           + ",".join(jv["criticalFailed"]))
        sv = self._shards_verdict()
        if sv["status"] == FAILED:
            return False, "no active shards"
        if sv["recovering"]:
            return False, f"{sv['recovering']} shard(s) recovering"
        wv = self._wal_verdict()
        for ds, ent in wv["datasets"].items():
            if ent["enabled"] and not ent["replayDone"]:
                return False, f"WAL replay pending for {ds!r}"
        pending = self.pending_manifest_mounts()
        if pending:
            return False, ("cold-tier manifest mount pending for "
                           + ",".join(repr(d) for d in pending))
        return True, "serving"
