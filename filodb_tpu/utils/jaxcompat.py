"""Version shims for the jax APIs the mesh/conformance code relies on.

The distributed path was written against the consolidated top-level API
(``jax.shard_map``, ``jax.enable_x64``); older jax releases (the 0.4.x
line this container ships) expose the same functionality only under
``jax.experimental``.  Newer releases in turn REMOVED the experimental
paths, so neither spelling is safe to hard-code — 127 tier-1 tests were
failing on that exact skew (PR 3's A/B check first measured it).  All
callers import the two names from here.

Also home to ``has_ici()`` — whether cross-device collectives ride a real
chip interconnect (the partial-merge path in parallel/mesh.py routes on
it: psum over ICI when present, host-side ops/agg.reduce_phase when not).
"""
from __future__ import annotations

import jax

__all__ = ["shard_map", "enable_x64", "has_ici"]


_new_shard_map = getattr(jax, "shard_map", None)
if _new_shard_map is None:
    from jax.experimental.shard_map import shard_map as _old_shard_map
else:
    _old_shard_map = None


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None):
    """``jax.shard_map`` across versions.

    check_vma follows the NEW api's name (the varying-mesh-axes checker);
    on old jax it maps onto the equivalent ``check_rep``.  None leaves
    the version's default in place.
    """
    if _new_shard_map is not None:
        if check_vma is None:
            return _new_shard_map(f, mesh=mesh, in_specs=in_specs,
                                  out_specs=out_specs)
        try:
            return _new_shard_map(f, mesh=mesh, in_specs=in_specs,
                                  out_specs=out_specs, check_vma=check_vma)
        except TypeError:
            # 0.5.x-era top-level export still spells it check_rep
            return _new_shard_map(f, mesh=mesh, in_specs=in_specs,
                                  out_specs=out_specs, check_rep=check_vma)
    kw = {} if check_vma is None else {"check_rep": check_vma}
    return _old_shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **kw)


if hasattr(jax, "enable_x64"):
    enable_x64 = jax.enable_x64
else:  # 0.4.x: context-manager form lives under experimental
    from jax.experimental import enable_x64  # noqa: F401


def has_ici() -> bool:
    """True when same-host collectives ride a chip interconnect.  Host
    platforms (cpu) emulate collectives through host memory — there a
    plain host-side partial merge is both faster and deterministic, so
    parallel/mesh.py's partial-merge helper falls back to
    ops/agg.reduce_phase semantics."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # noqa: BLE001 — uninitialized backend: no ICI
        return False
