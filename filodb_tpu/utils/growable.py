"""Amortized-O(1) append support for flat numpy arrays.

Shared by the shard's pid tables and the tag index's liveness/time arrays
(the dense 2D store keeps its own shape-aware grow in blockstore.py).
"""
from __future__ import annotations

import numpy as np


def grow_to(arr: np.ndarray, n: int, fill=0) -> np.ndarray:
    """Return `arr` with capacity >= n, growing geometrically."""
    if n <= arr.shape[0]:
        return arr
    cap = max(n, 2 * arr.shape[0], 1024)
    out = np.full(cap, fill, dtype=arr.dtype)
    out[:arr.shape[0]] = arr
    return out
