"""Write-path freshness: per-batch IngestStats + the ingest SLO layer.

"How stale is my data?" gets a measured answer (doc/observability.md):

  * ``IngestStats`` — one ingest batch's door-to-ack record: byte /
    sample / series counts, tenant, the per-stage breakdown (decode,
    admission, WAL append, group-commit fsync wait, replication
    fan-out, memstore ingest) and the batch's trace id.  The doors fill
    it, the ingest slowlog (utils/slowlog.IngestSlowLog) records slow
    ones, and its stage seconds feed the histograms below.
  * ``ingest_ack_seconds{ws}`` — ingest-to-ack: door arrival to the
    durable ack, per tenant workspace.
  * ``ingest_freshness_seconds{ws}`` — ingest-to-queryable: the ack
    wall clock minus the batch's newest sample timestamp (how far
    behind "queryable now" the data's own clock is; compare the result
    cache's `append_horizon_ms` immutability line).  Clamped at zero
    for future-stamped samples.
  * ``FreshnessTracker`` — the SLO fold: a batch whose ack wall crosses
    ``ingest.slow_batch_threshold_s`` is a BREACH; sustained breaches
    (>= `breach_count` inside `window_s`) flip the health evaluator's
    `ingest` subsystem to degraded until they age out.  A single slow
    fsync is a blip; a pattern of them is an incident.

Everything here rides the ordinary metrics registry, so the `_self_`
self-scrape loop (utils/selfmon.py) makes all of it PromQL-queryable
and ruler-alertable with zero extra wiring.
"""
from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Dict, List, Optional

from filodb_tpu.utils.metrics import registry

# seconds-scale bounds for the ack/freshness histograms (an fsync stall
# or replica wait lives in the 0.01-10 s band; the default ms-ish span
# bounds would smear it across two buckets)
FRESHNESS_BOUNDS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 300.0)


@dataclasses.dataclass
class IngestStats:
    """One ingest batch's door-to-ack attribution (the write-path
    QueryStats analogue)."""
    origin: str = "remote_write"          # remote_write | influx | gateway
    dataset: str = ""
    trace_id: str = ""
    tenant_ws: str = ""
    tenant_ns: str = ""
    bytes_in: int = 0
    samples: int = 0
    series: int = 0
    slabs: int = 0
    shards: List[int] = dataclasses.field(default_factory=list)
    ingested: int = 0
    dropped: int = 0
    # per-stage seconds (exclusive where the stages are sequential; the
    # WAL fsync overlaps memstore ingest by design, so wal_commit_wait_s
    # is the RESIDUAL wait after the overlapped work finished)
    decode_s: float = 0.0
    admission_s: float = 0.0
    build_slabs_s: float = 0.0
    wal_append_s: float = 0.0
    wal_commit_wait_s: float = 0.0
    replication_s: float = 0.0
    ingest_s: float = 0.0
    total_s: float = 0.0
    # newest sample timestamp (ms) per tenant ws — the freshness input
    newest_ts_ms: Dict[str, int] = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict:
        d = {
            "origin": self.origin, "dataset": self.dataset,
            "trace_id": self.trace_id,
            "tenant": {"ws": self.tenant_ws, "ns": self.tenant_ns},
            "bytes_in": int(self.bytes_in),
            "samples": int(self.samples), "series": int(self.series),
            "slabs": int(self.slabs), "shards": sorted(self.shards),
            "ingested": int(self.ingested), "dropped": int(self.dropped),
            "duration_s": round(self.total_s, 6),
            "stages": {
                "decode_s": round(self.decode_s, 6),
                "admission_s": round(self.admission_s, 6),
                "build_slabs_s": round(self.build_slabs_s, 6),
                "wal_append_s": round(self.wal_append_s, 6),
                "wal_commit_wait_s": round(self.wal_commit_wait_s, 6),
                "replication_s": round(self.replication_s, 6),
                "ingest_s": round(self.ingest_s, 6),
            },
        }
        return d


class FreshnessTracker:
    """Rolling breach window -> health verdict (the `ingest` subsystem
    in utils/health.HealthEvaluator)."""

    def __init__(self, threshold_s: float = 5.0, breach_count: int = 3,
                 window_s: float = 60.0):
        self.threshold_s = threshold_s
        self.breach_count = max(int(breach_count), 1)
        self.window_s = window_s
        self._lock = threading.Lock()
        self._breaches: collections.deque = collections.deque(maxlen=1024)
        self._batches = 0
        self._last_breach_unix = 0.0

    def configure(self, threshold_s: Optional[float] = None,
                  breach_count: Optional[int] = None,
                  window_s: Optional[float] = None) -> "FreshnessTracker":
        with self._lock:
            if threshold_s is not None:
                self.threshold_s = threshold_s
            if breach_count is not None:
                self.breach_count = max(int(breach_count), 1)
            if window_s is not None:
                self.window_s = window_s
        return self

    def reset(self) -> None:
        with self._lock:
            self._breaches.clear()
            self._batches = 0
            self._last_breach_unix = 0.0

    # ------------------------------------------------------------ record

    def note_batch(self, stats: IngestStats,
                   ack_unix_ms: Optional[int] = None) -> None:
        """Fold one acked batch: the ack/freshness histograms (per
        tenant workspace, exemplar = the batch's trace id) plus the
        breach window.  Called on the ack path — everything here is a
        few dict hits and at most a handful of histogram records."""
        now = time.time()
        ack_ms = int(now * 1000) if ack_unix_ms is None else ack_unix_ms
        ws = stats.tenant_ws or "_default_"
        registry.histogram("ingest_ack_seconds", bounds=FRESHNESS_BOUNDS,
                           ws=ws, origin=stats.origin).record(
            stats.total_s, exemplar=stats.trace_id or None)
        for t_ws, newest_ms in stats.newest_ts_ms.items():
            lag_s = max((ack_ms - int(newest_ms)) / 1000.0, 0.0)
            registry.histogram("ingest_freshness_seconds",
                               bounds=FRESHNESS_BOUNDS,
                               ws=t_ws or "_default_").record(
                lag_s, exemplar=stats.trace_id or None)
        with self._lock:
            self._batches += 1
            if self.threshold_s > 0 and stats.total_s >= self.threshold_s:
                self._breaches.append(now)
                self._last_breach_unix = now
                breached = True
            else:
                breached = False
        if breached:
            registry.counter("ingest_freshness_breaches",
                             origin=stats.origin).increment()

    # ----------------------------------------------------------- verdict

    def _recent_breaches(self, now: Optional[float] = None) -> int:
        now = time.time() if now is None else now
        cutoff = now - self.window_s
        with self._lock:
            while self._breaches and self._breaches[0] < cutoff:
                self._breaches.popleft()
            return len(self._breaches)

    def verdict(self) -> dict:
        """The health evaluator's `ingest` subsystem entry: degraded
        while the breach window stays saturated; self-clears as the
        breaches age past `window_s`."""
        recent = self._recent_breaches()
        sustained = recent >= self.breach_count
        out = {
            "status": "degraded" if sustained else "ok",
            "recentBreaches": recent,
            "breachThresholdSeconds": self.threshold_s,
            "windowSeconds": self.window_s,
            "batches": self._batches,
        }
        if self._last_breach_unix:
            out["lastBreachUnixSeconds"] = round(self._last_breach_unix, 3)
        return out


# process-wide instance: the doors feed it, the health evaluator reads
# it, standalone.FiloServer configures it from FilodbSettings
freshness = FreshnessTracker()


class DoorTrace:
    """The shared per-door trace bookkeeping (remote_write, /influx,
    the TCP gateway): parse-or-mint the W3C trace id, build the
    IngestStats, run the door body under the trace context with the
    `remote_write` origin tagged, and on `finish(status)` fold acked
    batches into the freshness histograms + the ingest slowlog and
    hand back the response trace headers — ONE implementation of the
    policy instead of a copy per door."""

    def __init__(self, origin: str, dataset: str, headers=None,
                 body_bytes: int = 0,
                 threshold_s: Optional[float] = None):
        from filodb_tpu.utils.metrics import (mint_trace_id,
                                              parse_traceparent)
        self.headers = {k.lower(): v
                        for k, v in (headers or {}).items()}
        self.trace_id = parse_traceparent(
            self.headers.get("traceparent")) or mint_trace_id()
        self.stats = IngestStats(origin=origin, dataset=dataset,
                                 trace_id=self.trace_id,
                                 bytes_in=body_bytes)
        self._threshold_s = threshold_s
        self._ctx = None
        self._t0 = 0.0

    def __enter__(self) -> "DoorTrace":
        from filodb_tpu.utils.metrics import collector, trace_context
        self._t0 = time.perf_counter()
        self._ctx = trace_context(self.trace_id)
        self._ctx.__enter__()
        collector.note_origin(self.trace_id, "remote_write")
        return self

    def __exit__(self, exc_type, exc, tb):
        self._ctx.__exit__(exc_type, exc, tb)
        self.stats.total_s = time.perf_counter() - self._t0
        return False

    def trace_headers(self) -> Dict[str, str]:
        from filodb_tpu.utils.metrics import make_traceparent
        return {"X-Trace-Id": self.trace_id,
                "traceparent": make_traceparent(self.trace_id)}

    def finish(self, status: int = 200) -> Dict[str, str]:
        """Fold the batch (acked statuses only: a 4xx/5xx is the
        client's or durability's problem, not a freshness breach) and
        return the response trace headers."""
        if status < 400:
            from filodb_tpu.utils.slowlog import ingestlog
            freshness.note_batch(self.stats)
            ingestlog.maybe_record(self.stats,
                                   threshold_s=self._threshold_s)
        return self.trace_headers()
