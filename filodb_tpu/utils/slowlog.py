"""Slow-query flight recorder.

The serving frontend records every query whose total wall (queue wait
included) exceeds `query.slow_query_threshold_s` into a bounded ring
buffer: the promql, grid params, tenant, the full QueryStats phase
attribution, and the stitched cross-node span tree captured at record
time (trace buffers are bounded and recycle — a slowlog entry must not
dangle a trace id that has already been evicted).  Exposed at
GET /admin/slowlog and optionally mirrored to a JSONL sink
(`query.slowlog_path`) for offline triage.

This is the MySQL-slow-log / Monarch-query-annal shape: when the p99
spikes, the operator reads the actual offending queries with their
queue/parse/plan/exec/device/transfer breakdown instead of inferring
from aggregate histograms.  SOAK_LONG_r05's 752 s eviction-window query
is exactly the record this would have captured.
"""
from __future__ import annotations

import collections
import json
import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

log = logging.getLogger("filodb.slowlog")


class SlowQueryLog:

    def __init__(self, threshold_s: float = 10.0, max_entries: int = 128,
                 path: str = ""):
        self.threshold_s = threshold_s
        self.path = path
        self._lock = threading.Lock()
        self._entries: collections.deque = collections.deque(
            maxlen=max_entries)
        self._seq = 0

    def configure(self, threshold_s: Optional[float] = None,
                  max_entries: Optional[int] = None,
                  path: Optional[str] = None) -> "SlowQueryLog":
        """Apply config (standalone.FiloServer at boot; tests directly).
        Shrinking max_entries keeps the newest records."""
        with self._lock:
            if threshold_s is not None:
                self.threshold_s = threshold_s
            if path is not None:
                self.path = path
            if max_entries is not None and \
                    max_entries != self._entries.maxlen:
                self._entries = collections.deque(self._entries,
                                                  maxlen=max_entries)
        return self

    # ------------------------------------------------------------ record

    def maybe_record(self, promql: str, start_s: int, step_s: int,
                     end_s: int, duration_s: float, result,
                     tenant: Tuple[str, str] = ("", ""),
                     origin: str = "query_range",
                     threshold_s: Optional[float] = None) -> bool:
        """Record iff duration crossed the threshold (the caller's
        config override wins over the singleton's).  `result` is the
        QueryResult (stats + trace_id + error ride along).  Returns
        whether a record was taken."""
        thr = self.threshold_s if threshold_s is None else threshold_s
        if thr <= 0 or duration_s < thr:
            return False
        from filodb_tpu.utils.metrics import collector, registry
        trace_id = getattr(result, "trace_id", "") or ""
        spans: List[dict] = []
        if trace_id:
            # copy NOW: the trace collector's ring recycles old traces
            spans = sorted(collector.trace(trace_id),
                           key=lambda e: e.get("end_unix_s", 0))
        stats = getattr(result, "stats", None)
        rec = {
            "unix_ts": round(time.time(), 3),
            "origin": origin,
            "promql": promql,
            "start_s": int(start_s), "step_s": int(step_s),
            "end_s": int(end_s),
            "duration_s": round(duration_s, 6),
            "tenant": {"ws": tenant[0], "ns": tenant[1]},
            "trace_id": trace_id,
            "error": getattr(result, "error", None),
            "partial": bool(getattr(result, "partial", False)),
            "stats": stats.to_dict() if stats is not None else None,
            "spans": spans,
        }
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            self._entries.append(rec)
        registry.counter("slow_queries", origin=origin).increment()
        log.warning("slow query (%.2fs > %.2fs): %s [%s..%s step %s] "
                    "trace=%s", duration_s, thr, promql,
                    start_s, end_s, step_s, trace_id)
        if self.path:
            try:
                with self._lock:   # serialize appends; keep lines whole
                    with open(self.path, "a") as f:
                        f.write(json.dumps(rec) + "\n")
            except OSError as e:
                # the sink is best-effort; the ring buffer is the record
                registry.counter("slowlog_sink_errors").increment()
                log.warning("slowlog sink %s failed: %s", self.path, e)
        return True

    # ------------------------------------------------------------- read

    def entries(self, limit: int = 0) -> List[dict]:
        """Newest-last snapshot (the /admin/slowlog payload)."""
        with self._lock:
            out = list(self._entries)
        return out[-limit:] if limit else out

    def clear(self) -> int:
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
        return n

    def __len__(self) -> int:
        return len(self._entries)


# process-wide instance: the frontend records into it, /admin/slowlog
# reads it, standalone.FiloServer configures it from FilodbSettings
slowlog = SlowQueryLog()
