"""Slow-operation flight recorders (query + ingest).

The serving frontend records every query whose total wall (queue wait
included) exceeds `query.slow_query_threshold_s` into a bounded ring
buffer: the promql, grid params, tenant, the full QueryStats phase
attribution, and the stitched cross-node span tree captured at record
time (trace buffers are bounded and recycle — a slowlog entry must not
dangle a trace id that has already been evicted).  Exposed at
GET /admin/slowlog and optionally mirrored to a JSONL sink
(`query.slowlog_path`) for offline triage.

The WRITE path gets the same flight recorder: remote_write / gateway
batches whose door-to-ack wall exceeds `ingest.slow_batch_threshold_s`
land in a second ring (`IngestSlowLog`, GET /admin/ingestlog) with
tenant, byte/sample counts, the per-stage breakdown (decode, WAL
append, fsync wait, replication fan-out, memstore ingest) and the
batch's trace id — when `wal_on_vs_off_pct` dips or a replica lags, the
operator reads the actual offending batches instead of inferring from
aggregate histograms.

This is the MySQL-slow-log / Monarch-query-annal shape: when the p99
spikes, the operator reads the actual offending operations with their
breakdown.  SOAK_LONG_r05's 752 s eviction-window query is exactly the
record the query ring would have captured.
"""
from __future__ import annotations

import collections
import json
import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

log = logging.getLogger("filodb.slowlog")


class _RingLog:
    """Bounded ring + monotonic seq + optional JSONL mirror — the shared
    flight-recorder mechanics both slow logs ride on."""

    def __init__(self, threshold_s: float, max_entries: int,
                 path: str = ""):
        self.threshold_s = threshold_s
        self.path = path
        self._lock = threading.Lock()
        self._entries: collections.deque = collections.deque(
            maxlen=max_entries)
        self._seq = 0

    def configure(self, threshold_s: Optional[float] = None,
                  max_entries: Optional[int] = None,
                  path: Optional[str] = None) -> "_RingLog":
        """Apply config (standalone.FiloServer at boot; tests directly).
        Shrinking max_entries keeps the newest records."""
        with self._lock:
            if threshold_s is not None:
                self.threshold_s = threshold_s
            if path is not None:
                self.path = path
            if max_entries is not None and \
                    max_entries != self._entries.maxlen:
                self._entries = collections.deque(self._entries,
                                                  maxlen=max_entries)
        return self

    def _append(self, rec: dict) -> None:
        """Sequence + ring-append + best-effort JSONL mirror."""
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            self._entries.append(rec)
        if self.path:
            try:
                with self._lock:   # serialize appends; keep lines whole
                    with open(self.path, "a") as f:
                        f.write(json.dumps(rec) + "\n")
            except OSError as e:
                # the sink is best-effort; the ring buffer is the record
                from filodb_tpu.utils.metrics import registry
                registry.counter("slowlog_sink_errors").increment()
                log.warning("slowlog sink %s failed: %s", self.path, e)

    # ------------------------------------------------------------- read

    def entries(self, limit: int = 0) -> List[dict]:
        """Newest-last snapshot (the /admin payload)."""
        with self._lock:
            out = list(self._entries)
        return out[-limit:] if limit else out

    def clear(self) -> int:
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
        return n

    def __len__(self) -> int:
        return len(self._entries)


class SlowQueryLog(_RingLog):

    def __init__(self, threshold_s: float = 10.0, max_entries: int = 128,
                 path: str = ""):
        super().__init__(threshold_s, max_entries, path)

    # ------------------------------------------------------------ record

    def maybe_record(self, promql: str, start_s: int, step_s: int,
                     end_s: int, duration_s: float, result,
                     tenant: Tuple[str, str] = ("", ""),
                     origin: str = "query_range",
                     threshold_s: Optional[float] = None,
                     force: bool = False) -> bool:
        """Record iff duration crossed the threshold (the caller's
        config override wins over the singleton's).  `result` is the
        QueryResult (stats + trace_id + error ride along).  `force`
        records regardless of duration — the frontend uses it for SHED
        queries (verdict `shed`), which are fast by design but exactly
        what an operator triaging a tenant's 429s needs to read.
        Returns whether a record was taken."""
        thr = self.threshold_s if threshold_s is None else threshold_s
        if not force and (thr <= 0 or duration_s < thr):
            return False
        from filodb_tpu.query.activequeries import verdict_of
        from filodb_tpu.utils.metrics import collector, registry
        trace_id = getattr(result, "trace_id", "") or ""
        spans: List[dict] = []
        if trace_id:
            # copy NOW: the trace collector's ring recycles old traces
            spans = sorted(collector.trace(trace_id),
                           key=lambda e: e.get("end_unix_s", 0))
        stats = getattr(result, "stats", None)
        rec = {
            "unix_ts": round(time.time(), 3),
            "origin": origin,
            "promql": promql,
            "start_s": int(start_s), "step_s": int(step_s),
            "end_s": int(end_s),
            "duration_s": round(duration_s, 6),
            "tenant": {"ws": tenant[0], "ns": tenant[1]},
            # the stable query id IS the trace id (PR 13): both names,
            # so slowlog <-> /admin/traces/<id> correlation is a copy-
            # paste, not a manual join — and the final VERDICT
            # (completed/killed/deadline/error) rides both records
            "trace_id": trace_id,
            "query_id": trace_id,
            "verdict": verdict_of(result),
            "error": getattr(result, "error", None),
            "partial": bool(getattr(result, "partial", False)),
            "stats": stats.to_dict() if stats is not None else None,
            "spans": spans,
        }
        self._append(rec)
        if duration_s >= thr > 0:
            # genuinely slow (force-recorded sheds keep their own
            # queries_shed accounting — they are fast, that's the point)
            registry.counter("slow_queries", origin=origin).increment()
            log.warning("slow query (%.2fs > %.2fs): %s [%s..%s step %s] "
                        "trace=%s", duration_s, thr, promql,
                        start_s, end_s, step_s, trace_id)
        return True

    def seq_for_trace(self, trace_id: str) -> Optional[int]:
        """Ring seq of the newest record carrying this trace id, or None
        — the /admin/traces/<id> -> slowlog half of the cross-link."""
        if not trace_id:
            return None
        with self._lock:
            for rec in reversed(self._entries):
                if rec.get("trace_id") == trace_id:
                    return rec.get("seq")
        return None


class IngestSlowLog(_RingLog):
    """The write path's flight recorder: batches over
    `ingest.slow_batch_threshold_s` door-to-ack, with per-stage
    breakdown and trace id (GET /admin/ingestlog)."""

    def __init__(self, threshold_s: float = 5.0, max_entries: int = 128,
                 path: str = ""):
        super().__init__(threshold_s, max_entries, path)

    def maybe_record(self, stats,
                     threshold_s: Optional[float] = None) -> bool:
        """`stats` is a utils.freshness.IngestStats; records iff its
        total wall crossed the threshold.  The stitched span tree is
        copied at record time, like the query ring."""
        thr = self.threshold_s if threshold_s is None else threshold_s
        if thr <= 0 or stats.total_s < thr:
            return False
        from filodb_tpu.utils.metrics import collector, registry
        spans: List[dict] = []
        if stats.trace_id:
            spans = sorted(collector.trace(stats.trace_id),
                           key=lambda e: e.get("end_unix_s", 0))
        rec = stats.to_dict()
        rec["unix_ts"] = round(time.time(), 3)
        rec["spans"] = spans
        self._append(rec)
        registry.counter("slow_ingest_batches",
                         origin=stats.origin).increment()
        log.warning("slow ingest batch (%.3fs > %.3fs): %d samples / "
                    "%d series / %d bytes [%s] trace=%s",
                    stats.total_s, thr, stats.samples, stats.series,
                    stats.bytes_in, stats.origin, stats.trace_id)
        return True


# process-wide instances: the frontend / ingest doors record into them,
# /admin/slowlog and /admin/ingestlog read them, standalone.FiloServer
# configures both from FilodbSettings
slowlog = SlowQueryLog()
ingestlog = IngestSlowLog()
