"""Span EXPORT: ship stitched traces out of the process.

The reference exports its Kamon spans through configured reporters
(Zipkin / Prometheus; ref: coordinator/.../KamonLogger.scala:16-40,
filodb-defaults.conf kamon block).  Round 4 added cross-node trace
propagation + stitching but left /admin/traces/<id> pull-only; this
module closes the loop (round-5 "missing #3"): a background exporter
drains span events into Zipkin v2 JSON batches and ships them to

  - ``http(s)://host:port/api/v2/spans`` — POSTed as JSON (Zipkin's
    native collector endpoint), or
  - ``file:///path/to/spans.jsonl`` — appended one span per line (the
    zero-dependency option; tail it or bulk-import later).

Configured via ``FilodbSettings.trace_export_url`` (empty = disabled);
`FiloServer` wires and stops it.  Export is strictly best-effort and
non-blocking: a full queue drops spans and counts them
(``trace_export_dropped``), never stalling the query path.
"""
from __future__ import annotations

import json
import os
import queue
import threading
import time
import uuid
from typing import Optional
from urllib.request import Request, urlopen

from filodb_tpu.utils.metrics import collector, registry


def _zipkin_span(trace_id: str, event: dict) -> dict:
    """One collector event -> one Zipkin v2 span dict.

    trace ids are query uuids: stripped of dashes they are exactly the
    32 lower hex chars Zipkin wants; non-uuid ids are hashed into one.
    """
    tid = trace_id.replace("-", "").lower()
    if len(tid) not in (16, 32) or any(c not in "0123456789abcdef"
                                       for c in tid):
        tid = uuid.uuid5(uuid.NAMESPACE_OID, trace_id).hex
    dur_us = max(int(float(event.get("dur_s", 0.0)) * 1e6), 1)
    end_s = float(event.get("end_unix_s", time.time()))
    tags = {k: str(v) for k, v in event.items()
            if k not in ("span", "dur_s", "end_unix_s", "node")}
    return {
        "traceId": tid,
        "id": uuid.uuid4().hex[:16],
        "name": str(event.get("span", "span")),
        "timestamp": int((end_s - dur_us / 1e6) * 1e6),
        "duration": dur_us,
        "localEndpoint": {"serviceName": str(event.get("node") or "filodb")},
        "tags": tags,
    }


class TraceExporter:
    """Background Zipkin-v2 exporter fed by TraceCollector's sink hook."""

    def __init__(self, url: str, flush_interval_s: float = 2.0,
                 max_queue: int = 4096, batch: int = 256):
        self.url = url
        self.flush_interval_s = flush_interval_s
        self.batch = batch
        self._q: "queue.Queue[dict]" = queue.Queue(maxsize=max_queue)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # unified job registry: export ticks/streaks at /admin/jobs (NOT
        # critical — a dead Zipkin collector must never flip /ready)
        from filodb_tpu.utils.jobs import jobs
        self.job = jobs.register("trace_export",
                                 interval_s=flush_interval_s)

    # -- the collector sink (called under the query path: must not block)

    def sink(self, trace_id: str, event: dict) -> None:
        try:
            self._q.put_nowait(_zipkin_span(trace_id, event))
        except queue.Full:
            registry.counter("trace_export_dropped").increment()

    # -- lifecycle

    def start(self) -> "TraceExporter":
        collector.add_sink(self.sink)
        self._thread = threading.Thread(target=self._run,
                                        name="filodb-trace-export",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        collector.remove_sink(self.sink)
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        self._flush()                      # final drain

    # -- internals

    def _run(self) -> None:
        while not self._stop.wait(self.flush_interval_s):
            self._flush()

    def _drain(self):
        spans = []
        while len(spans) < self.batch:
            try:
                spans.append(self._q.get_nowait())
            except queue.Empty:
                break
        return spans

    def _flush(self) -> None:
        shipped = 0
        while True:
            spans = self._drain()
            if not spans:
                if shipped:
                    self.job.note_ok()
                    self.job.set_progress(f"shipped {shipped} span(s)")
                return
            try:
                self._ship(spans)
                shipped += len(spans)
                registry.counter("trace_export_spans").increment(len(spans))
            except Exception as e:  # noqa: BLE001 — export is best-effort
                registry.counter("trace_export_errors").increment()
                self.job.note_error(e)
                return

    def _ship(self, spans) -> None:
        if self.url.startswith("file://"):
            path = self.url[len("file://"):]
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            with open(path, "a") as f:
                for s in spans:
                    f.write(json.dumps(s, separators=(",", ":")) + "\n")
            return
        req = Request(self.url, data=json.dumps(spans).encode(),
                      headers={"Content-Type": "application/json"})
        with urlopen(req, timeout=5) as resp:
            resp.read()
