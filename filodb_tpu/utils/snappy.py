"""Pure-Python Snappy block-format codec.

The Prometheus remote-read/write protocol frames its protobuf payloads with
Snappy block compression (ref: http/src/main/scala/filodb/http/
PrometheusApiRoute.scala:37-62 — `Snappy.uncompress` on the request,
`Snappy.compress` on the response).  No snappy library is available in this
environment, so this implements the block format
(github.com/google/snappy/format_description.txt) directly:

- decompress() handles the full format (literals + copy ops with 1/2/4-byte
  offsets, including overlapping RLE-style copies), so payloads from real
  clients decode correctly.
- compress() emits a valid literal-only stream plus greedy back-references
  for long runs found via a tiny hash table — not snappy-optimal, but
  interoperable and fast enough for the request/response sizes involved.
"""
from __future__ import annotations

from filodb_tpu.utils.varint import (read_uvarint as _read_uvarint,
                                     write_uvarint as _write_uvarint)


def decompress(data: bytes) -> bytes:
    """Snappy block-format decompress (raises ValueError on malformed input)."""
    if not data:
        raise ValueError("empty snappy input")
    expected, pos = _read_uvarint(data, 0)
    out = bytearray()
    n = len(data)
    while pos < n:
        tag = data[pos]
        pos += 1
        kind = tag & 0x03
        if kind == 0:                       # literal
            length = (tag >> 2) + 1
            if length > 60:
                extra = length - 60
                if pos + extra > n:
                    raise ValueError("truncated literal length")
                length = int.from_bytes(data[pos:pos + extra], "little") + 1
                pos += extra
            if pos + length > n:
                raise ValueError("truncated literal")
            out += data[pos:pos + length]
            pos += length
            continue
        if kind == 1:                       # copy, 1-byte offset
            length = 4 + ((tag >> 2) & 0x07)
            offset = ((tag >> 5) << 8) | data[pos]
            pos += 1
        elif kind == 2:                     # copy, 2-byte offset
            length = (tag >> 2) + 1
            offset = int.from_bytes(data[pos:pos + 2], "little")
            pos += 2
        else:                               # copy, 4-byte offset
            length = (tag >> 2) + 1
            offset = int.from_bytes(data[pos:pos + 4], "little")
            pos += 4
        if offset == 0 or offset > len(out):
            raise ValueError("invalid copy offset")
        start = len(out) - offset
        if offset >= length:
            out += out[start:start + length]
        else:                               # overlapping copy: RLE semantics
            for i in range(length):
                out.append(out[start + i])
    if len(out) != expected:
        raise ValueError(
            f"snappy length mismatch: got {len(out)}, expected {expected}")
    return bytes(out)


def _emit_literal(out: bytearray, chunk: bytes) -> None:
    length = len(chunk)
    if length == 0:
        return
    if length <= 60:
        out.append((length - 1) << 2)
    else:
        nbytes = (max(length - 1, 1).bit_length() + 7) // 8
        out.append((59 + nbytes) << 2)
        out += (length - 1).to_bytes(nbytes, "little")
    out += chunk


def compress(data: bytes) -> bytes:
    """Valid snappy block stream: greedy 4-byte-hash matcher + literals.

    Inputs past _FAST_MIN route to the vectorized large-payload encoder
    (_compress_fast below): the WAL group-commit path frames multi-MB
    record bodies per append, and the per-byte Python hash loop here
    would throttle acknowledged ingest to a crawl (measured ~2 MB/s vs
    the ~GB/s numpy path)."""
    if len(data) >= _FAST_MIN:
        return _compress_fast(data)
    out = bytearray(_write_uvarint(len(data)))
    n = len(data)
    if n == 0:
        return bytes(out)
    table = {}
    pos = 0
    lit_start = 0
    while pos + 4 <= n:
        key = data[pos:pos + 4]
        cand = table.get(key)
        table[key] = pos
        if cand is not None and pos - cand <= 0xFFFF:
            match = 4
            limit = min(n - pos, 64)
            while (match < limit
                   and data[cand + match] == data[pos + match]):
                match += 1
            _emit_literal(out, data[lit_start:pos])
            offset = pos - cand
            out.append(((match - 1) << 2) | 2)      # 2-byte-offset copy
            out += offset.to_bytes(2, "little")
            pos += match
            lit_start = pos
        else:
            pos += 1
    _emit_literal(out, data[lit_start:])
    return bytes(out)


# threshold above which compress() switches to the vectorized encoder
_FAST_MIN = 1 << 15


def _compress_fast(data: bytes) -> bytes:
    """Vectorized snappy encoder for large payloads (WAL record bodies:
    int64 timestamp grids, f64 value matrices, key tables).

    Match detection is ONE numpy compare — byte i against byte i-8 —
    which captures exactly the redundancy those payloads have (int64/f64
    lanes repeating their high bytes, zero runs, repeated text); runs of
    equality become copy ops, everything else is emitted as literals
    (memcpy-speed, always valid snappy).  Within a detected run the data
    is period-8 by construction, so offsets double 8→16→32→64 and the
    steady state is one REPEATED 3-byte non-overlapping 64-byte copy op
    — O(1) Python per run, and the decoder's fast (offset >= length)
    slice path on the way back."""
    import numpy as np
    out = bytearray(_write_uvarint(len(data)))
    a = np.frombuffer(data, dtype=np.uint8)
    n = len(a)
    eq = np.zeros(n + 1, dtype=np.int8)
    np.equal(a[8:], a[:-8], out=eq[8:n].view(bool))
    d = np.diff(eq)
    starts = np.flatnonzero(d == 1) + 1
    ends = np.flatnonzero(d == -1) + 1
    # only LONG runs are worth ops: every run costs Python-loop work at
    # emission, and the group-commit path lives on this encoder's SPEED
    # (an incompressible body must degrade to one memcpy literal, not
    # to 16k tiny copy ops)
    keep = (ends - starts) >= 256
    starts, ends = starts[keep], ends[keep]
    op64 = bytes([((64 - 1) << 2) | 2]) + (64).to_bytes(2, "little")
    pos = 0
    for s, e in zip(starts.tolist(), ends.tolist()):
        if s > pos:
            _emit_literal(out, data[pos:s])
        # [s-8, e) is period-8: copy offset==length stays valid while
        # length <= bytes already emitted since s-8 (doubling schedule)
        rem = e - s
        avail = 8
        while rem >= 8 and avail < 64:
            take = min(avail, rem) & ~7
            if take < 8:
                break
            out.append(((take - 1) << 2) | 2)
            out += take.to_bytes(2, "little")
            rem -= take
            avail += take
        if avail >= 64:
            full, tail = divmod(rem, 64)
            out += op64 * full              # O(1) per run, not per op
            rem = tail
            if rem >= 8:
                take = rem & ~7
                out.append(((take - 1) << 2) | 2)
                out += take.to_bytes(2, "little")
                rem -= take
        if rem:
            _emit_literal(out, data[e - rem:e])
        pos = e
    if pos < n:
        _emit_literal(out, data[pos:n])
    return bytes(out)
