"""Pure-Python Snappy block-format codec.

The Prometheus remote-read/write protocol frames its protobuf payloads with
Snappy block compression (ref: http/src/main/scala/filodb/http/
PrometheusApiRoute.scala:37-62 — `Snappy.uncompress` on the request,
`Snappy.compress` on the response).  No snappy library is available in this
environment, so this implements the block format
(github.com/google/snappy/format_description.txt) directly:

- decompress() handles the full format (literals + copy ops with 1/2/4-byte
  offsets, including overlapping RLE-style copies), so payloads from real
  clients decode correctly.
- compress() emits a valid literal-only stream plus greedy back-references
  for long runs found via a tiny hash table — not snappy-optimal, but
  interoperable and fast enough for the request/response sizes involved.
"""
from __future__ import annotations

from filodb_tpu.utils.varint import (read_uvarint as _read_uvarint,
                                     write_uvarint as _write_uvarint)


def decompress(data: bytes) -> bytes:
    """Snappy block-format decompress (raises ValueError on malformed input)."""
    if not data:
        raise ValueError("empty snappy input")
    expected, pos = _read_uvarint(data, 0)
    out = bytearray()
    n = len(data)
    while pos < n:
        tag = data[pos]
        pos += 1
        kind = tag & 0x03
        if kind == 0:                       # literal
            length = (tag >> 2) + 1
            if length > 60:
                extra = length - 60
                if pos + extra > n:
                    raise ValueError("truncated literal length")
                length = int.from_bytes(data[pos:pos + extra], "little") + 1
                pos += extra
            if pos + length > n:
                raise ValueError("truncated literal")
            out += data[pos:pos + length]
            pos += length
            continue
        if kind == 1:                       # copy, 1-byte offset
            length = 4 + ((tag >> 2) & 0x07)
            offset = ((tag >> 5) << 8) | data[pos]
            pos += 1
        elif kind == 2:                     # copy, 2-byte offset
            length = (tag >> 2) + 1
            offset = int.from_bytes(data[pos:pos + 2], "little")
            pos += 2
        else:                               # copy, 4-byte offset
            length = (tag >> 2) + 1
            offset = int.from_bytes(data[pos:pos + 4], "little")
            pos += 4
        if offset == 0 or offset > len(out):
            raise ValueError("invalid copy offset")
        start = len(out) - offset
        if offset >= length:
            out += out[start:start + length]
        else:                               # overlapping copy: RLE semantics
            for i in range(length):
                out.append(out[start + i])
    if len(out) != expected:
        raise ValueError(
            f"snappy length mismatch: got {len(out)}, expected {expected}")
    return bytes(out)


def _emit_literal(out: bytearray, chunk: bytes) -> None:
    length = len(chunk)
    if length == 0:
        return
    if length <= 60:
        out.append((length - 1) << 2)
    else:
        nbytes = (max(length - 1, 1).bit_length() + 7) // 8
        out.append((59 + nbytes) << 2)
        out += (length - 1).to_bytes(nbytes, "little")
    out += chunk


def compress(data: bytes) -> bytes:
    """Valid snappy block stream: greedy 4-byte-hash matcher + literals."""
    out = bytearray(_write_uvarint(len(data)))
    n = len(data)
    if n == 0:
        return bytes(out)
    table = {}
    pos = 0
    lit_start = 0
    while pos + 4 <= n:
        key = data[pos:pos + 4]
        cand = table.get(key)
        table[key] = pos
        if cand is not None and pos - cand <= 0xFFFF:
            match = 4
            limit = min(n - pos, 64)
            while (match < limit
                   and data[cand + match] == data[pos + match]):
                match += 1
            _emit_literal(out, data[lit_start:pos])
            offset = pos - cand
            out.append(((match - 1) << 2) | 2)      # 2-byte-offset copy
            out += offset.to_bytes(2, "little")
            pos += match
            lit_start = pos
        else:
            pos += 1
    _emit_literal(out, data[lit_start:])
    return bytes(out)
