"""Base-128 varint primitives shared by the snappy codec and the protobuf
wire format (utils/snappy.py, http/remotepb.py)."""
from __future__ import annotations

from typing import Tuple


def write_uvarint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def read_uvarint(data: bytes, pos: int) -> Tuple[int, int]:
    """Returns (value, next_pos); raises ValueError on overlong or truncated
    input (IndexError from truncation is converted for uniform handling)."""
    result = 0
    shift = 0
    try:
        while True:
            b = data[pos]
            pos += 1
            result |= (b & 0x7F) << shift
            if not b & 0x80:
                return result, pos
            shift += 7
            if shift > 70:
                raise ValueError("uvarint too long")
    except IndexError:
        raise ValueError("truncated uvarint")
