import datetime


def iso_utc(unix_s: float) -> str:
    """Unix seconds -> RFC3339 UTC with the 'Z' suffix Prometheus
    payloads use (isoformat emits '+00:00')."""
    return datetime.datetime.fromtimestamp(
        unix_s, datetime.timezone.utc).isoformat().replace("+00:00", "Z")
