"""Deterministic fault injection — named fault points production code
calls as one-line no-ops.

The reference proves its failure handling with multi-JVM specs that kill
real actor systems (ref: standalone/src/multi-jvm/.../
IngestionAndRecoverySpec.scala); the TPU rebuild adds the complementary
in-process layer: a registry of NAMED fault points that tests (and the
chaos bench) arm with seeded, deterministic fault plans, so "node died
mid-scatter", "flush persist failing", "heartbeats delayed past the
liveness window" are unit-testable without real processes or clocks.

Catalog (the production call sites):

    transport.send    — coordinator-side dispatch, before the plan frame
                        is written (parallel/transport.py)
    transport.recv    — coordinator-side dispatch, the raw reply frame
                        (corrupt plans mutate the bytes)
    flush.persist     — background flush, before chunks are written to
                        the column store (core/shard.py)
    device.upload     — DeviceMirror full refresh (core/devicecache.py)
    ingest.batch      — shard ingest entry (core/shard.py; also covers
                        the ruler's recorded-series write-back)
    cluster.heartbeat — NodeAgent heartbeat RPC (parallel/cluster.py)
    ruler.notify      — alert webhook delivery attempt
                        (rules/notifier.py; retry/backoff chaos)
    wal.append        — WAL record framing/enqueue, before the bytes
                        reach the segment file (wal/writer.py)
    wal.fsync         — group commit, before the fsync that makes the
                        batch durable (wal/writer.py; a failure here
                        must fail every writer waiting on the group)
    wal.replay        — per decoded record during restart replay
                        (wal/replay.py; corrupt-mid-log chaos)
    objectstore.put   — shared cold-tier object upload, before the
                        bytes land (persist/objectstore.py; upload
                        retry/backoff + breaker chaos)
    objectstore.get   — object fetch (corrupt plans mutate the payload:
                        content-hash verification must catch it)
    objectstore.list  — manifest/object listing (a dead store must
                        degrade cold scans to flagged partials)

Plan kinds and how they surface at the call site:

    error   — raise InjectedFault (a ConnectionError: transport sites
              classify it exactly like a peer death)
    delay   — time.sleep(delay_s), then proceed
    drop    — raise socket.timeout: a dropped frame looks to the sender
              like no reply ever arriving, and raising the timeout AT
              the point exercises the identical handling path without
              spending the wall-clock wait
    corrupt — bytes payloads come back with deterministically-flipped
              bytes (frame decode must fail loudly, never mis-parse)

Firing is deterministic: `first_k` fires on exactly the first K calls;
`probability` draws from a Random seeded per plan — the same seed
always yields the same firing sequence.  The disabled fast path is one
falsy-dict check, so production cost is negligible.  Plans may also be
armed from the environment (FILODB_TPU_FAULTS, a JSON list of plan
objects) so a standalone node process can boot pre-faulted for chaos
runs.
"""
from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import random
import socket
import threading
import time
from typing import Dict, List, Optional

POINTS = frozenset({
    "transport.send", "transport.recv", "flush.persist", "device.upload",
    "ingest.batch", "cluster.heartbeat", "ruler.notify",
    "wal.append", "wal.fsync", "wal.replay",
    "objectstore.put", "objectstore.get", "objectstore.list",
})

KINDS = frozenset({"error", "delay", "drop", "corrupt"})


class InjectedFault(ConnectionError):
    """The `error` plan's exception: a ConnectionError so transport call
    sites classify an injected fault exactly like a real peer death."""


@dataclasses.dataclass
class FaultPlan:
    point: str
    kind: str
    first_k: int = 0            # fire on exactly the first K calls...
    probability: float = 0.0    # ...else per-call with this seeded chance
    seed: int = 0
    delay_s: float = 0.01
    message: str = ""
    calls: int = 0
    fired: int = 0

    def __post_init__(self):
        if self.point not in POINTS:
            raise ValueError(f"unknown fault point {self.point!r} "
                             f"(catalog: {sorted(POINTS)})")
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} "
                             f"(valid: {sorted(KINDS)})")
        self._rng = random.Random(self.seed)

    def should_fire(self) -> bool:
        """Advance the deterministic schedule by one call."""
        self.calls += 1
        if self.first_k > 0:
            fire = self.calls <= self.first_k
        else:
            fire = self._rng.random() < self.probability
        if fire:
            self.fired += 1
        return fire


class FaultRegistry:
    """Process-wide registry; `fire(point)` is the one-line production
    hook.  Thread-safe: the schedule advances under a lock so concurrent
    callers see one global deterministic call order."""

    def __init__(self, env: Optional[Dict[str, str]] = None):
        self._lock = threading.Lock()
        self._plans: Dict[str, FaultPlan] = {}
        spec = (env if env is not None else os.environ).get(
            "FILODB_TPU_FAULTS", "")
        if spec:
            for raw in json.loads(spec):
                self.arm(**raw)

    # ------------------------------------------------------------ arming

    def arm(self, point: str, kind: str, **kw) -> FaultPlan:
        plan = FaultPlan(point, kind, **kw)
        with self._lock:
            self._plans[point] = plan
        return plan

    def disarm(self, point: Optional[str] = None) -> None:
        with self._lock:
            if point is None:
                self._plans.clear()
            else:
                self._plans.pop(point, None)

    @contextlib.contextmanager
    def plan(self, point: str, kind: str, **kw):
        """Scoped arming for tests: the point is disarmed on exit even
        when the body raises (most arming ends in an exception path)."""
        p = self.arm(point, kind, **kw)
        try:
            yield p
        finally:
            self.disarm(point)

    def snapshot(self) -> List[dict]:
        with self._lock:
            return [{"point": p.point, "kind": p.kind, "calls": p.calls,
                     "fired": p.fired, "first_k": p.first_k,
                     "probability": p.probability, "seed": p.seed}
                    for p in self._plans.values()]

    # ------------------------------------------------------------ firing

    def fire(self, point: str, payload=None):
        """The production hook.  Disabled: returns `payload` untouched
        (one falsy-dict check).  Armed: advance the point's schedule and
        apply its plan — raise, sleep, or corrupt-and-return."""
        if not self._plans:
            return payload
        with self._lock:
            plan = self._plans.get(point)
            if plan is None or not plan.should_fire():
                return payload
        from filodb_tpu.utils.metrics import registry
        registry.counter("faults_injected", point=point,
                         kind=plan.kind).increment()
        if plan.kind == "delay":
            time.sleep(plan.delay_s)
            return payload
        if plan.kind == "error":
            raise InjectedFault(plan.message
                                or f"injected fault at {point}")
        if plan.kind == "drop":
            raise socket.timeout(plan.message
                                 or f"injected drop at {point}")
        # corrupt: only meaningful for bytes payloads; flip a few bytes
        # at deterministic (seeded) positions so decode fails loudly
        if isinstance(payload, (bytes, bytearray)) and len(payload):
            buf = bytearray(payload)
            with self._lock:
                idxs = [plan._rng.randrange(len(buf))
                        for _ in range(min(4, len(buf)))]
            for i in idxs:
                buf[i] ^= 0xFF
            return bytes(buf)
        raise InjectedFault(plan.message
                            or f"injected corruption at {point} "
                               f"(non-bytes payload)")


faults = FaultRegistry()
