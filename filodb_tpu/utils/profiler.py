"""Sampling profiler — the SimpleProfiler.java analogue.

The reference ships a thread-stack sampling profiler in its standalone
server (ref: standalone/src/main/java/filodb.standalone/SimpleProfiler.java
— periodic stack sampling, aggregated hot-method report).  This is the
Python equivalent: a daemon thread samples every live thread's stack via
sys._current_frames at a fixed rate and aggregates (function, file, line)
hit counts, attributing each sample to the innermost frame and to every
frame on the stack (self vs cumulative), so both hot leaves and hot call
paths show up.

Zero overhead when stopped; sampling cost is O(threads * stack depth) per
tick.  Exposed over HTTP via /admin/profiler/{start,stop,report}.
"""
from __future__ import annotations

import collections
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

FrameKey = Tuple[str, str, int]       # (function, file, first line)


class SamplingProfiler:

    MAX_HZ = 1000.0
    # bound on distinct collapsed stacks kept (each full stack tuple is
    # one Counter key); overflow hits aggregate under a sentinel frame so
    # the report says truncation happened instead of silently dropping
    MAX_STACKS = 10_000

    def __init__(self):
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        # one Event PER RUN, created by start() and captured by stop()
        # under the lock — a shared event would let a concurrent start()
        # race stop() into killing the new run or orphaning the old thread
        self._stop: Optional[threading.Event] = None
        self.samples = 0
        self._self_hits: Dict[FrameKey, int] = collections.Counter()
        self._cum_hits: Dict[FrameKey, int] = collections.Counter()
        # root-first stack tuples -> hit counts (the collapsed-stack /
        # flamegraph source; /admin/profiler/report?format=collapsed)
        self._stack_hits: Dict[Tuple[FrameKey, ...], int] = \
            collections.Counter()
        self.started_at: Optional[float] = None
        self.hz = 0.0

    # ----------------------------------------------------------- lifecycle

    def start(self, hz: float = 100.0) -> bool:
        """Begin sampling at `hz` (clamped to [1, MAX_HZ]; non-finite
        rejected — an inf rate would busy-loop the sampler).  Returns
        False if already running."""
        hz = float(hz)
        if not (0 < hz < float("inf")):      # also rejects NaN
            raise ValueError(f"hz must be a positive finite number, "
                             f"got {hz!r}")
        with self._lock:
            if self._thread is not None:
                return False
            self.hz = min(max(hz, 1.0), self.MAX_HZ)
            self.samples = 0
            self._self_hits = collections.Counter()
            self._cum_hits = collections.Counter()
            self._stack_hits = collections.Counter()
            self.started_at = time.time()
            stop_evt = threading.Event()
            self._stop = stop_evt
            self._thread = threading.Thread(
                target=self._run, args=(stop_evt,), daemon=True,
                name="sampling-profiler")
            self._thread.start()
            return True

    def stop(self) -> bool:
        with self._lock:
            t, evt = self._thread, self._stop
            self._thread, self._stop = None, None
        if t is None:
            return False
        evt.set()
        t.join(timeout=5)
        return True

    @property
    def running(self) -> bool:
        return self._thread is not None

    # ------------------------------------------------------------ sampling

    def _run(self, stop_evt: threading.Event) -> None:
        me = threading.get_ident()
        interval = 1.0 / self.hz
        while not stop_evt.wait(interval):
            frames = sys._current_frames()
            with self._lock:
                self.samples += 1
                for tid, frame in frames.items():
                    if tid == me:
                        continue
                    seen = set()
                    top = True
                    f = frame
                    stack = []                  # leaf-first while walking
                    while f is not None:
                        code = f.f_code
                        key = (code.co_name, code.co_filename,
                               code.co_firstlineno)
                        stack.append(key)
                        if top:
                            self._self_hits[key] += 1
                            top = False
                        if key not in seen:     # recursion counts once
                            self._cum_hits[key] += 1
                            seen.add(key)
                        f = f.f_back
                    # collapsed form is root-first; cap distinct stacks
                    skey = tuple(reversed(stack))
                    if skey in self._stack_hits or \
                            len(self._stack_hits) < self.MAX_STACKS:
                        self._stack_hits[skey] += 1
                    else:
                        self._stack_hits[_TRUNCATED] += 1

    # ------------------------------------------------------------- report

    def report(self, top_n: int = 30) -> str:
        """Flat text report, hottest self-time frames first (the shape of
        SimpleProfiler's aggregated output).  Percentages are per sample
        TICK: every live thread contributes at each tick, so a frame hot
        in N threads simultaneously can exceed 100%."""
        with self._lock:
            samples = self.samples
            self_hits = dict(self._self_hits)
            cum_hits = dict(self._cum_hits)
        lines: List[str] = [
            f"# sampling profiler: {samples} samples @ {self.hz:g} Hz"
            + (" (running)" if self.running else " (stopped)"),
            f"# {'self%':>6} {'cum%':>6}  location",
        ]
        if samples == 0:
            return "\n".join(lines + ["# no samples collected"])
        ranked = sorted(self_hits.items(), key=lambda kv: -kv[1])[:top_n]
        for key, hits in ranked:
            name, fname, line = key
            cum = cum_hits.get(key, hits)
            lines.append(f"  {100.0 * hits / samples:6.2f} "
                         f"{100.0 * cum / samples:6.2f}  "
                         f"{name} ({fname}:{line})")
        return "\n".join(lines)


    def report_collapsed(self) -> str:
        """Collapsed-stack output: one line per distinct stack,
        root-first frames `;`-joined, trailing hit count — directly
        loadable by speedscope / Brendan Gregg's flamegraph.pl (served
        at /admin/profiler/report?format=collapsed)."""
        with self._lock:
            stacks = dict(self._stack_hits)
        lines = []
        for skey, hits in sorted(stacks.items(),
                                 key=lambda kv: -kv[1]):
            frames = ";".join(
                f"{name} ({fname}:{line})" for name, fname, line in skey)
            lines.append(f"{frames} {hits}")
        return "\n".join(lines) + ("\n" if lines else "")


# sentinel stack for hits past the MAX_STACKS distinct-stack cap
_TRUNCATED: Tuple[FrameKey, ...] = (("[stacks-truncated]", "", 0),)


# process-wide instance the HTTP admin routes drive
profiler = SamplingProfiler()
