"""Self-scrape meta-monitoring: the TSDB monitors itself with itself.

The production stance for a monitoring system (Prometheus scrapes its
own /metrics; Google's Monarch monitors itself with itself, VLDB'20 §7)
is that the TSDB's own telemetry must be queryable and alertable
THROUGH ITS OWN query and rules engines — dashboards over
`rate(wal_fsync_seconds_count[5m])`, alerts on `job_consecutive_errors`
— not only visible to an external scraper that may not exist.

`SelfScraper` closes the loop: an in-process loop snapshots the metrics
registry every `selfmon.interval_s` and writes every counter / gauge /
histogram through the ordinary columnar `ingest_columns` path (the same
shard-routed MemstoreSink the ruler's write-back uses) under a reserved
`_self_` tenant with `job="filodb"` and an `instance` label from the
node id.  Prometheus exposition naming is preserved — counters land as
`name_total`, histograms as `name_bucket{le=...}` / `name_sum` /
`name_count` — so PromQL written against a real Prometheus scrape of
/metrics works unchanged against the self-scraped series.

The `_self_` workspace is exempt from the scan-limit gate like
`_rules_` (utils/usage.INTERNAL_WORKSPACES) but fully accounted, so
self-monitoring burn shows up in /api/v1/usage without ever starving
itself out of its own answers.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

TENANT_WS = "_self_"
TENANT_NS = "selfmon"

# seconds-scale scrape-duration bounds
_SCRAPE_BOUNDS = (0.0005, 0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                  0.5, 1.0, 2.5, 5.0)


class SelfScraper:
    """Snapshot the metrics registry -> columnar ingest, on a timer."""

    def __init__(self, memstore, dataset: str, mapper=None,
                 spread_provider=None, node_name: str = "local",
                 interval_s: float = 15.0):
        from filodb_tpu.rules import MemstoreSink
        self.dataset = dataset
        self.node = node_name
        self.interval_s = max(float(interval_s), 0.05)
        self.sink = MemstoreSink(memstore, dataset, mapper,
                                 spread_provider)
        self.scrapes = 0
        self.errors = 0
        self.last_series = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # (metric, tag tuple) -> PartKey: series identity is stable
        # across scrapes, so per-series key construction runs once per
        # NEW series, not once per scrape x series
        self._key_memo: Dict[Tuple, object] = {}
        from filodb_tpu.utils.jobs import jobs
        self._job = jobs.register("selfmon", interval_s=self.interval_s,
                                  dataset=dataset)

    # ------------------------------------------------------------ control

    def start(self) -> "SelfScraper":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="filodb-selfmon")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def _run(self) -> None:
        # first scrape immediately: a freshly-booted node's own metrics
        # must be queryable within one interval, not two
        while not self._stop.is_set():
            try:
                with self._job.tick():
                    self.scrape_once()
            except Exception:  # noqa: BLE001 — the loop must survive;
                pass           # the job tick recorded the error
            self._stop.wait(self.interval_s)

    # ------------------------------------------------------------- scrape

    def _part_key(self, name: str, tags: Tuple[Tuple[str, str], ...]):
        from filodb_tpu.core.partkey import PartKey
        memo_key = (name, tags)
        pk = self._key_memo.get(memo_key)
        if pk is None:
            labels = {"_ws_": TENANT_WS, "_ns_": TENANT_NS,
                      "job": "filodb", "instance": self.node}
            for k, v in tags:
                # a metric tag colliding with a scrape-identity label
                # (job_runs_total carries its own `job` tag) gets the
                # Prometheus honor_labels=false treatment: the scraped
                # label moves to exported_<name>, identity wins
                labels["exported_" + k if k in labels else k] = v
            pk = PartKey.make(name, labels)
            if len(self._key_memo) > 65_536:
                # hostile tag churn must not pin unbounded keys
                self._key_memo.clear()
            self._key_memo[memo_key] = pk
        return pk

    def scrape_once(self, now_ms: Optional[int] = None) -> int:
        """One registry snapshot -> one columnar write per shard;
        returns series written.  Raises on sink failure (the caller's
        job tick records it; the next interval retries)."""
        from filodb_tpu.utils.metrics import registry
        t0 = time.perf_counter()
        samples = registry.snapshot_samples()
        now_ms = int(time.time() * 1000) if now_ms is None else now_ms
        keys: List[object] = []
        vals: List[float] = []
        for name, tags, value in samples:
            keys.append(self._part_key(name, tags))
            vals.append(float(value))
        n = self.sink.write(keys, now_ms, vals)
        self.scrapes += 1
        self.last_series = n
        dur = time.perf_counter() - t0
        registry.histogram("selfmon_scrape_seconds",
                           bounds=_SCRAPE_BOUNDS).record(dur)
        registry.gauge("selfmon_series").update(n)
        registry.counter("selfmon_samples").increment(n)
        self._job.set_progress(f"{n} series @ {now_ms}")
        return n
