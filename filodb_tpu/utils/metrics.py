"""Metrics + span tracing — the Kamon analogue.

ref: the reference threads Kamon counters/gauges/histograms through every
subsystem (TimeSeriesShardStats TimeSeriesShard.scala:41-134, MemoryStats
BlockManager.scala:91-106, per-query spans exec/ExecPlan.scala:102-131)
and exposes them via reporters — a Prometheus endpoint plus log reporters
(coordinator/.../KamonLogger.scala:16-40, README:812-819).

Here: a process-wide registry of tagged counters/gauges/histograms with
Prometheus text exposition (served at /metrics by the HTTP layer), and a
`span()` context manager that records durations into histograms and feeds
optional span reporters.  Everything is thread-safe and allocation-light —
metric lookups are dict hits on interned (name, tags) keys.
"""
from __future__ import annotations

import bisect
import logging
import threading
import time
from typing import Callable, Dict, Iterator, List, Optional, Sequence, Tuple

TagTuple = Tuple[Tuple[str, str], ...]


def _tags_key(tags: Dict[str, str]) -> TagTuple:
    return tuple(sorted(tags.items()))


class Counter:
    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0.0
        self._lock = threading.Lock()

    def increment(self, by: float = 1.0) -> None:
        with self._lock:
            self.value += by


class Gauge:
    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0.0
        self._lock = threading.Lock()

    def update(self, v: float) -> None:
        with self._lock:
            self.value = v


# log2-ish bucket boundaries, milliseconds-friendly
_DEFAULT_BOUNDS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10, 50, 100,
                   500, 1000, 5000, 10000, 60000)

# process-wide exemplar kill switch (config `exemplars_enabled`): when
# off, Histogram.record drops the exemplar argument on the floor so the
# per-record cost is identical to the pre-exemplar code path
EXEMPLARS_ENABLED = True


def set_exemplars_enabled(flag: bool) -> None:
    global EXEMPLARS_ENABLED
    EXEMPLARS_ENABLED = bool(flag)


class Histogram:
    __slots__ = ("bounds", "counts", "sum", "count", "max", "exemplars",
                 "_lock")

    def __init__(self, bounds: Sequence[float] = _DEFAULT_BOUNDS):
        self.bounds = tuple(bounds)
        self.counts = [0] * (len(self.bounds) + 1)
        self.sum = 0.0
        self.count = 0
        # largest value ever recorded: bounds the overflow-bucket
        # percentile estimate (a 752 s p99 and a 5.1 s p99 both land in
        # the +Inf bucket; without the max they'd report identically)
        self.max = 0.0
        # bucket index -> (trace_id, value, unix_ts): the most recent
        # exemplar per bucket (the OpenMetrics bridge from a latency
        # histogram to the exact trace that caused it).  Lazily created —
        # histograms that never see an exemplar pay nothing.
        self.exemplars: Optional[Dict[int, Tuple[str, float, float]]] = None
        self._lock = threading.Lock()

    def record(self, v: float, exemplar: Optional[str] = None) -> None:
        """Record one observation.  `exemplar` is an optional trace id
        attached to the containing bucket (latest wins), emitted by the
        OpenMetrics exposition as `# {trace_id="..."} value ts` so an
        operator can jump from a latency spike straight to the trace."""
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self.counts[i] += 1
            self.sum += v
            self.count += 1
            if v > self.max:
                self.max = v
            if exemplar and EXEMPLARS_ENABLED:
                if self.exemplars is None:
                    self.exemplars = {}
                self.exemplars[i] = (str(exemplar), float(v), time.time())

    # Prometheus-client parity name for the same operation
    observe = record

    def percentile(self, q: float) -> float:
        """Approximate percentile, linearly interpolated within the
        containing bucket (Prometheus histogram_quantile semantics)
        instead of reporting the bucket's upper bound.  The overflow
        (+Inf) bucket interpolates between the last finite bound and the
        maximum value observed — an explicit estimate rather than the
        old behavior of capping at the top bound."""
        with self._lock:
            if self.count == 0:
                return 0.0
            target = q * self.count
            acc = 0
            for i, c in enumerate(self.counts):
                if c == 0:
                    continue
                if acc + c >= target:
                    lo = self.bounds[i - 1] if i > 0 else 0.0
                    hi = self.bounds[i] if i < len(self.bounds) \
                        else max(self.max, self.bounds[-1])
                    frac = (target - acc) / c
                    return lo + frac * (hi - lo)
                acc += c
            return max(self.max, self.bounds[-1])


def _esc_label(v: str) -> str:
    # the exposition-format label escapes: backslash, quote, newline
    # (shared by both exposition grammars — one home, no drift)
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _fmt_tags(tags: TagTuple, extra: str = "") -> str:
    items = [f'{k}="{_esc_label(v)}"' for k, v in tags]
    if extra:
        items.append(extra)
    return "{" + ",".join(items) + "}" if items else ""


class MetricsRegistry:
    """Process-wide named+tagged metrics (ref: Kamon.counter/gauge/histogram)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[Tuple[str, TagTuple], Counter] = {}
        self._gauges: Dict[Tuple[str, TagTuple], Gauge] = {}
        self._hists: Dict[Tuple[str, TagTuple], Histogram] = {}

    def counter(self, name: str, **tags) -> Counter:
        key = (name, _tags_key(tags))
        c = self._counters.get(key)
        if c is None:
            with self._lock:
                c = self._counters.setdefault(key, Counter())
        return c

    def gauge(self, name: str, **tags) -> Gauge:
        key = (name, _tags_key(tags))
        g = self._gauges.get(key)
        if g is None:
            with self._lock:
                g = self._gauges.setdefault(key, Gauge())
        return g

    def histogram(self, name: str, bounds: Optional[Sequence[float]] = None,
                  **tags) -> Histogram:
        """`bounds` applies on FIRST creation of a (name, tags) series
        only (later callers get the existing histogram unchanged) — the
        default log2-ish bounds suit millisecond latencies; seconds-scale
        series (e.g. the ruler's group-eval durations) pass their own."""
        key = (name, _tags_key(tags))
        h = self._hists.get(key)
        if h is None:
            with self._lock:
                h = self._hists.setdefault(
                    key, Histogram(bounds) if bounds else Histogram())
        return h

    def clear(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()

    # ----------------------------------------------------- sample snapshot

    def snapshot_samples(self):
        """Every metric as (series_name, tag_tuple, value) with the
        Prometheus exposition naming — counters as `name_total`,
        histograms as cumulative `name_bucket{le=...}` + `name_sum` +
        `name_count`.  The self-scrape loop (utils/selfmon.py) ingests
        exactly this set, so PromQL written against a real /metrics
        scrape works unchanged against the self-scraped series."""
        with self._lock:
            counters = list(self._counters.items())
            gauges = list(self._gauges.items())
            hists = list(self._hists.items())
        out = []
        for (name, tags), c in counters:
            out.append((f"{name}_total", tags, c.value))
        for (name, tags), g in gauges:
            out.append((name, tags, g.value))
        for (name, tags), h in hists:
            with h._lock:                  # torn-read guard, as exposition
                counts = list(h.counts)
                h_sum, h_count = h.sum, h.count
            acc = 0
            for i, b in enumerate(h.bounds):
                acc += counts[i]
                out.append((f"{name}_bucket",
                            tags + (("le", "%g" % b),), acc))
            out.append((f"{name}_bucket", tags + (("le", "+Inf"),),
                        h_count))
            out.append((f"{name}_sum", tags, h_sum))
            out.append((f"{name}_count", tags, h_count))
        return out

    # -------------------------------------------------- prometheus format

    def expose_prometheus(self) -> str:
        """Prometheus text exposition of the framework's own metrics
        (ref: Kamon prometheus reporter, README:812-819)."""
        out: List[str] = []
        fmt_tags = _fmt_tags

        # snapshot under the lock: concurrent first-seen metric creation must
        # not blow up a scrape mid-iteration
        with self._lock:
            counters = list(self._counters.items())
            gauges = list(self._gauges.items())
            hists = list(self._hists.items())
        for (name, tags), c in sorted(counters):
            out.append(f"{name}_total{fmt_tags(tags)} {c.value:g}")
        for (name, tags), g in sorted(gauges):
            out.append(f"{name}{fmt_tags(tags)} {g.value:g}")
        for (name, tags), h in sorted(hists):
            # per-histogram snapshot under ITS lock: counts/sum/count
            # mutate together in record(), and reading them lock-free
            # while formatting could emit a bucket total above _count
            # (sum updated, count not yet) — a torn exposition
            with h._lock:
                counts = list(h.counts)
                h_sum, h_count = h.sum, h.count
            acc = 0
            for i, b in enumerate(h.bounds):
                acc += counts[i]
                le_tag = 'le="%g"' % b
                out.append(f"{name}_bucket{fmt_tags(tags, le_tag)} "
                           f"{acc}")
            inf_tag = 'le="+Inf"'
            out.append(f"{name}_bucket{fmt_tags(tags, inf_tag)} "
                       f"{h_count}")
            out.append(f"{name}_sum{fmt_tags(tags)} {h_sum:g}")
            out.append(f"{name}_count{fmt_tags(tags)} {h_count}")
        return "\n".join(out) + "\n"

    # -------------------------------------------------- openmetrics format

    def expose_openmetrics(self) -> str:
        """OpenMetrics 1.0 text exposition (`/metrics?format=openmetrics`):
        `# TYPE` metadata per family, canonical-float `le` values,
        counter samples under their `_total` name, per-bucket exemplars
        (`# {trace_id="..."} value ts` — the standard bridge from a
        latency histogram to the exact trace that caused it), and the
        mandatory `# EOF` terminator.  The plain Prometheus format
        (expose_prometheus) is untouched — scrapers negotiate via the
        query param, and the legacy output stays byte-identical."""
        out: List[str] = []
        fmt_tags = _fmt_tags

        def om_float(b: float) -> str:
            # canonical float form: OpenMetrics `le` values are floats,
            # never bare ints ("1.0", not "1")
            s = "%g" % b
            return s if ("." in s or "e" in s or "inf" in s) else s + ".0"

        with self._lock:
            counters = list(self._counters.items())
            gauges = list(self._gauges.items())
            hists = list(self._hists.items())

        def grouped(items):
            fams: Dict[str, list] = {}
            for (name, tags), m in sorted(items):
                fams.setdefault(name, []).append((tags, m))
            return fams

        for name, series in grouped(counters).items():
            out.append(f"# TYPE {name} counter")
            for tags, c in series:
                out.append(f"{name}_total{fmt_tags(tags)} {c.value:g}")
        for name, series in grouped(gauges).items():
            out.append(f"# TYPE {name} gauge")
            for tags, g in series:
                out.append(f"{name}{fmt_tags(tags)} {g.value:g}")
        for name, series in grouped(hists).items():
            out.append(f"# TYPE {name} histogram")
            for tags, h in series:
                with h._lock:
                    counts = list(h.counts)
                    h_sum, h_count = h.sum, h.count
                    ex = dict(h.exemplars) if h.exemplars else {}
                acc = 0
                for i, b in enumerate(h.bounds):
                    acc += counts[i]
                    le_tag = 'le="%s"' % om_float(b)
                    line = (f"{name}_bucket{fmt_tags(tags, le_tag)} "
                            f"{acc}")
                    out.append(line + _om_exemplar(ex.get(i)))
                inf_tag = 'le="+Inf"'
                line = (f"{name}_bucket{fmt_tags(tags, inf_tag)} "
                        f"{h_count}")
                out.append(line + _om_exemplar(ex.get(len(h.bounds))))
                out.append(f"{name}_sum{fmt_tags(tags)} {h_sum:g}")
                out.append(f"{name}_count{fmt_tags(tags)} {h_count}")
        out.append("# EOF")
        return "\n".join(out) + "\n"


def _om_exemplar(ex) -> str:
    """One bucket's exemplar suffix, or "" (OpenMetrics exemplar syntax:
    ` # {trace_id="..."} value timestamp`)."""
    if not ex:
        return ""
    tid, v, ts = ex
    tid = str(tid).replace("\\", "").replace('"', "").replace("\n", "")
    return f' # {{trace_id="{tid}"}} {v:g} {ts:.3f}'


registry = MetricsRegistry()


# ----------------------------------------------------- exec resource tally

class _ExecTally(threading.local):
    """Per-thread accumulators attributing device time, host→device
    transfer, and mirror-refresh events to the exec node that triggered
    them (the Kamon-context analogue for QueryStats attribution; PR 3).

    Protocol: ExecPlan.execute_internal snapshots + zeroes the fields on
    entry, folds whatever its own work accumulated into its QueryStats on
    exit, then restores the outer values — so a parent node never
    re-claims what a child already attributed (child contributions arrive
    via QueryStats.merge instead).  `child_wall` carries nested nodes'
    wall seconds up, letting each node compute its EXCLUSIVE cpu time."""

    def __init__(self):
        self.child_wall = 0.0
        self.device_s = 0.0
        self.transfer_s = 0.0
        self.transfer_bytes = 0
        self.mirror_full = 0
        self.mirror_incremental = 0
        # (device, kernel) -> [seconds, count]: the per-chip, per-kernel
        # split of device_s (PR 18 device telemetry) — same snapshot /
        # restore protocol, folded into QueryStats.device_calls
        self.device_calls: Dict[Tuple[str, str], List[float]] = {}

    def snapshot(self):
        s = (self.child_wall, self.device_s, self.transfer_s,
             self.transfer_bytes, self.mirror_full, self.mirror_incremental,
             self.device_calls)
        self.child_wall = 0.0
        self.device_s = 0.0
        self.transfer_s = 0.0
        self.transfer_bytes = 0
        self.mirror_full = 0
        self.mirror_incremental = 0
        self.device_calls = {}
        return s

    def restore(self, snap, total_wall: float) -> None:
        (self.child_wall, self.device_s, self.transfer_s,
         self.transfer_bytes, self.mirror_full,
         self.mirror_incremental, self.device_calls) = snap
        self.child_wall += total_wall


exec_tally = _ExecTally()


def note_device_time(seconds: float) -> None:
    """Attribute device dispatch/kernel wall time to the current node."""
    exec_tally.device_s += seconds


def note_device_call(device: str, kernel: str, seconds: float) -> None:
    """Attribute one device kernel dispatch to the current node, split by
    (device, kernel) — the sum over entries equals what note_device_time
    alone would have accumulated, so QueryStats.device_seconds and the
    per-device breakdown reconcile by construction."""
    exec_tally.device_s += seconds
    cell = exec_tally.device_calls.get((device, kernel))
    if cell is None:
        exec_tally.device_calls[(device, kernel)] = [seconds, 1]
    else:
        cell[0] += seconds
        cell[1] += 1


def note_transfer(nbytes: int, seconds: float) -> None:
    """Attribute a host→device (or wire) transfer to the current node."""
    exec_tally.transfer_bytes += int(nbytes)
    exec_tally.transfer_s += seconds


def note_mirror_refresh(kind: str) -> None:
    """kind: 'full' | 'incremental' — query-path mirror uploads, so
    QueryStats can say WHICH query paid for a rebuild."""
    if kind == "full":
        exec_tally.mirror_full += 1
    else:
        exec_tally.mirror_incremental += 1


# ------------------------------------------------------------------ spans

SpanReporter = Callable[[str, float, Dict[str, str]], None]
_reporters: List[SpanReporter] = []
_active = threading.local()

# process-wide span kill switch (bench.py observability stage: measures
# the span pipeline's own overhead by toggling this off).  Stats tallies
# are NOT affected — only histogram/trace/reporter work is skipped.
SPANS_ENABLED = True


def set_spans_enabled(flag: bool) -> None:
    global SPANS_ENABLED
    SPANS_ENABLED = bool(flag)

# node identity stamped on every collected span event (set by nodeapp /
# standalone at startup) so a stitched cross-node trace shows placement
NODE_NAME = ""


class TraceCollector:
    """Bounded per-trace span-event buffer — the Zipkin-reporter analogue
    of the reference's Kamon span pipeline (ref: ExecPlan.scala:102-131
    Kamon spans around doExecute; KamonLogger.scala:16-40).  Remote nodes
    ship their events back with the query reply (parallel/transport), so
    `trace(tid)` returns ONE stitched cross-node trace."""

    def __init__(self, max_traces: int = 256, max_events: int = 512):
        import collections as _collections
        self.max_traces = max_traces
        self.max_events = max_events
        self._traces: Dict[str, List[dict]] = {}
        self._order: List[str] = []
        # trace -> origin tag (query | rule_eval | remote_write), set by
        # the doors; /admin/traces?origin= filters on it
        self._origins: Dict[str, str] = {}
        # trace -> final verdict (completed | killed | deadline | error),
        # set by the query frontend at completion; /admin/traces/<id>
        # carries it so "how did this query end" needs no slowlog join
        self._verdicts: Dict[str, str] = {}
        # ids evicted from the bounded ring: /traces/{id} answers "410
        # gone" (the trace existed, the ring recycled it) instead of a
        # 404 indistinguishable from a typo.  Bounded itself so hostile
        # churn cannot grow it without bound.
        self._evicted = _collections.deque(maxlen=max(4 * max_traces, 64))
        self._evicted_set: set = set()
        self._lock = threading.Lock()
        # push-export hooks (utils/traceexport.TraceExporter): called
        # outside the lock with every recorded event; must not block
        self._sinks: List = []

    def add_sink(self, sink) -> None:
        self._sinks.append(sink)

    def remove_sink(self, sink) -> None:
        if sink in self._sinks:
            self._sinks.remove(sink)

    def record(self, trace_id: str, event: Optional[dict]) -> None:
        """Record one span event (event=None registers the trace id in
        the ring without an event — the doors tag origins before their
        first span exits)."""
        evicted = 0
        with self._lock:
            evs = self._traces.get(trace_id)
            if evs is None:
                evs = self._traces[trace_id] = []
                self._order.append(trace_id)
                while len(self._order) > self.max_traces:
                    old = self._order.pop(0)
                    self._traces.pop(old, None)
                    self._origins.pop(old, None)
                    self._verdicts.pop(old, None)
                    if old in self._evicted_set:
                        # a re-registered-then-re-evicted id: refresh
                        # its position instead of duplicating it (a
                        # duplicate would let the rotation discard the
                        # set entry while a deque copy remains, turning
                        # a promised 410 into a 404).  O(n) on a small
                        # bounded deque, and only on this rare path.
                        try:
                            self._evicted.remove(old)
                        except ValueError:
                            pass
                    elif len(self._evicted) == self._evicted.maxlen:
                        self._evicted_set.discard(self._evicted[0])
                    self._evicted.append(old)
                    self._evicted_set.add(old)
                    evicted += 1
            if event is not None and len(evs) < self.max_events:
                evs.append(event)
        if evicted:
            registry.counter("trace_evictions").increment(evicted)
        if event is not None:
            for sink in self._sinks:
                sink(trace_id, event)

    def note_origin(self, trace_id: str, origin: str) -> None:
        """Tag a trace with its door (query | rule_eval | remote_write).
        The doors tag BEFORE their first span exits, so an unknown id is
        registered in the ring (empty event list) rather than dropped —
        the origins map shares the ring's bound either way."""
        if not trace_id or not origin:
            return
        with self._lock:
            if trace_id in self._traces:
                self._origins[trace_id] = origin
                return
        # register through record()'s eviction bookkeeping, then tag
        self.record(trace_id, None)
        with self._lock:
            if trace_id in self._traces:
                self._origins[trace_id] = origin

    def note_verdict(self, trace_id: str, verdict: str) -> None:
        """Tag a trace with its query's final verdict (completed |
        killed | deadline | error).  Only known ids are tagged — a
        verdict for an evicted trace would re-register it for nothing."""
        if not trace_id or not verdict:
            return
        with self._lock:
            if trace_id in self._traces:
                self._verdicts[trace_id] = verdict

    def verdict(self, trace_id: str) -> str:
        with self._lock:
            return self._verdicts.get(trace_id, "")

    def was_evicted(self, trace_id: str) -> bool:
        with self._lock:
            return trace_id in self._evicted_set \
                and trace_id not in self._traces

    def trace(self, trace_id: str) -> List[dict]:
        with self._lock:
            return list(self._traces.get(trace_id, ()))

    def take(self, trace_id: str) -> List[dict]:
        """Drain the trace's events (used by the node query server: each
        dispatch reply carries exactly the events recorded since the last
        one, so the coordinator's merge never duplicates)."""
        with self._lock:
            evs = self._traces.get(trace_id)
            if not evs:
                return []
            out = list(evs)
            evs.clear()
            return out

    def trace_ids(self, origin: str = "", limit: int = 0) -> List[str]:
        """Known ids, oldest first.  `origin` filters to one door's
        traces; `limit` keeps the newest N."""
        with self._lock:
            if origin:
                ids = [t for t in self._order
                       if self._origins.get(t) == origin]
            else:
                ids = list(self._order)
        return ids[-limit:] if limit > 0 else ids


collector = TraceCollector()


# ------------------------------------------------------- W3C traceparent

# the W3C Trace Context header: 00-<32 hex trace id>-<16 hex span id>-<flags>
_TRACEPARENT_RE = None


def parse_traceparent(header: Optional[str]) -> Optional[str]:
    """Extract the 32-hex trace id from a `traceparent` request header
    (W3C Trace Context).  Returns None for missing/malformed headers and
    for the all-zero (invalid) trace id — the caller mints its own."""
    global _TRACEPARENT_RE
    if not header:
        return None
    if _TRACEPARENT_RE is None:
        import re as _re
        _TRACEPARENT_RE = _re.compile(
            r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")
    m = _TRACEPARENT_RE.match(header.strip().lower())
    if not m or m.group(1) == "ff":
        return None
    tid = m.group(2)
    if tid == "0" * 32 or m.group(3) == "0" * 16:
        return None
    return tid


def make_traceparent(trace_id: str) -> str:
    """Format a trace id as an outgoing `traceparent` header (a fresh
    16-hex span id per call; non-32-hex internal ids are hashed into
    shape, matching the trace-export normalization)."""
    import uuid as _uuid
    tid = str(trace_id).replace("-", "").lower()
    if len(tid) != 32 or any(c not in "0123456789abcdef" for c in tid):
        tid = _uuid.uuid5(_uuid.NAMESPACE_OID, str(trace_id)).hex
    return f"00-{tid}-{_uuid.uuid4().hex[:16]}-01"


def mint_trace_id() -> str:
    """A fresh W3C-shaped (32 lower hex) trace id for a request that
    arrived without one."""
    import uuid as _uuid
    return _uuid.uuid4().hex


class trace_context:
    """Bind a trace id to this thread for the duration; spans entered
    inside feed TraceCollector under it.  Re-entrant (restores the outer
    id), so a node executing a dispatched subtree nests cleanly."""

    def __init__(self, trace_id: str):
        self.trace_id = trace_id

    def __enter__(self):
        self._prev = getattr(_active, "trace_id", None)
        _active.trace_id = self.trace_id
        return self

    def __exit__(self, exc_type, exc, tb):
        _active.trace_id = self._prev
        return False


def current_trace_id():
    return getattr(_active, "trace_id", None)


def add_span_reporter(rep: SpanReporter) -> None:
    """ref: KamonSpanLogReporter (KamonLogger.scala:16-40)."""
    _reporters.append(rep)


def remove_span_reporter(rep: SpanReporter) -> None:
    if rep in _reporters:
        _reporters.remove(rep)


class span:
    """Duration-recording span (ref: Kamon.spanBuilder threaded through
    ExecPlan.execute / startODPSpan).  Nesting is tracked per thread so
    reporters see parent names dotted in."""

    def __init__(self, name: str, **tags: str):
        self.name = name
        self.tags = tags

    def __enter__(self):
        if not SPANS_ENABLED:
            self._t0 = None
            return self
        stack = getattr(_active, "stack", None)
        if stack is None:
            stack = _active.stack = []
        stack.append(self.name)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._t0 is None:
            return False
        elapsed = time.perf_counter() - self._t0
        stack = _active.stack
        full = ".".join(stack)
        stack.pop()
        tid = current_trace_id()
        # the active trace id doubles as the span histogram's exemplar,
        # so every span_*_seconds family carries OpenMetrics exemplars
        # for free (histogram spike -> /admin/traces/<id> in one hop)
        registry.histogram(f"span_{self.name}_seconds",
                           **self.tags).record(elapsed, exemplar=tid)
        if tid:
            collector.record(tid, {
                "span": full, "dur_s": round(elapsed, 6),
                "end_unix_s": round(time.time(), 3),
                "node": NODE_NAME, **self.tags})
        for rep in _reporters:
            rep(full, elapsed, self.tags)
        return False


# ----------------------------------------------------- scheduler asserts


class FiloSchedulers:
    """Thread-name assertions on hot entry points (ref:
    core/.../memstore/FiloSchedulers.scala:14-20, gated by
    filodb.scheduler.enable-assertions)."""

    enabled = False
    INGEST = "ingest"
    QUERY = "query"
    FLUSH = "flush"

    @staticmethod
    def assert_thread_name(fragment: str) -> None:
        if not FiloSchedulers.enabled:
            return
        name = threading.current_thread().name
        assert fragment in name, \
            f"expected thread name containing {fragment!r}, got {name!r}"


_degrade_log = logging.getLogger("filodb.fused")
_degrade_last: Dict[str, float] = {}


def log_fused_degradation(where: str, exc: BaseException,
                          min_interval_s: float = 60.0) -> None:
    """The fused fast paths (query/exec.py leaf, parallel/mesh.py) degrade
    silently to the general path on any error; without the exception text
    the operator only sees an error counter climb with nothing to
    diagnose.  Rate-limited so a hot query loop can't flood the log."""
    now = time.monotonic()
    if now - _degrade_last.get(where, -1e9) >= min_interval_s:
        _degrade_last[where] = now
        _degrade_log.warning(
            "%s fused path degraded to general path: %s: %s",
            where, type(exc).__name__, exc)


def log_error_once(where: str, exc: BaseException,
                   min_interval_s: float = 300.0,
                   logger_name: str = "filodb") -> None:
    """Log a swallowed optimization-path exception once per (site, error
    class), rate-limited — the general form of log_fused_degradation for
    paths whose failures otherwise vanish into a bare counter (e.g. the
    device mirror's incremental-refresh fallback).  A new error CLASS at
    the same site always logs immediately, so a regression that changes
    failure mode is visible even inside the rate window.

    Every call — logged or rate-suppressed — also increments
    `suppressed_errors_total{site,class}`, so swallowed
    optimization-path errors are visible at /metrics and alertable via
    the self-scrape loop, not only greppable in logs."""
    registry.counter("suppressed_errors",
                     **{"site": where,
                        "class": type(exc).__name__}).increment()
    key = f"{where}:{type(exc).__name__}"
    now = time.monotonic()
    if now - _degrade_last.get(key, -1e9) >= min_interval_s:
        _degrade_last[key] = now
        logging.getLogger(logger_name).warning(
            "%s suppressed (optimization path fell back): %s: %s",
            where, type(exc).__name__, exc)
