"""Per-tenant (_ws_/_ns_) usage accounting + config-gated limits.

The Monarch-style operating contract for a multi-tenant TSDB: every
query and every ingest batch is attributed to the workspace/namespace
shard-key pair, accumulated both as registry counters (scraped at
/metrics, so existing dashboards see per-tenant burn) and in an
in-process table served by GET /api/v1/usage.  Limits are enforced at
the serving frontend on samples SCANNED over a rolling window:

  * warn limit — the query runs; a rate-limited log line + the
    `tenant_limit_warnings` counter fire once per window.
  * fail limit — the query is rejected with a structured
    "tenant_limit_exceeded: ..." error (the QueryError-taxonomy shape:
    clients route on the code before the colon) BEFORE any exec work.

The reference's cardinality quotas guard series CREATION
(core/ratelimit.py); this guards query-side resource burn — the two
halves of tenant isolation.
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

log = logging.getLogger("filodb.usage")

TenantKey = Tuple[str, str]                 # (_ws_, _ns_)


class _Tenant:
    __slots__ = ("queries", "query_seconds", "samples_scanned",
                 "result_bytes", "ingest_samples", "rejected",
                 "win_start", "win_samples", "win_warned",
                 "ingest_rejected", "ingest_win_start", "win_ingest")

    def __init__(self):
        self.queries = 0
        self.query_seconds = 0.0
        self.samples_scanned = 0
        self.result_bytes = 0
        self.ingest_samples = 0
        self.rejected = 0
        self.win_start = time.monotonic()
        self.win_samples = 0
        self.win_warned = False
        # write-side rolling window (admit_ingest): samples OFFERED this
        # window + rejections, independent of the scan-limit window
        self.ingest_rejected = 0
        self.ingest_win_start = time.monotonic()
        self.win_ingest = 0


# tenants past the cap fold into this sentinel row: query text is
# client-controlled, so distinct (_ws_, _ns_) pairs must not grow the
# registry/accountant without bound (each pair pins counters forever)
OVERFLOW_TENANT: TenantKey = ("_overflow_", "")

# synthetic workspaces of INTERNAL subsystems (the ruler bills as
# `_rules_`, the self-scrape loop as `_self_`): accounted like any
# tenant, but exempt from the scan-limit gate — aggregation rules
# legitimately scan the whole store every interval, and self-monitoring
# must never starve itself out of its own answers; a fail limit sized
# for external tenants would break both precisely under load
INTERNAL_WORKSPACES = frozenset({"_rules_", "_self_"})


class UsageAccountant:

    MAX_TENANTS = 512

    def __init__(self, window_s: float = 60.0):
        self.window_s = window_s
        self._lock = threading.Lock()
        self._tenants: Dict[TenantKey, _Tenant] = {}

    def clear(self) -> None:
        with self._lock:
            self._tenants.clear()

    def resolve(self, ws: str, ns: str) -> TenantKey:
        """The key a (ws, ns) pair is accounted under: itself while the
        table has room, the overflow sentinel once MAX_TENANTS distinct
        pairs exist — bounding both this table and the registry's
        tenant-tagged counter cardinality against hostile query text."""
        key = (ws, ns)
        if key in self._tenants or len(self._tenants) < self.MAX_TENANTS:
            return key
        return OVERFLOW_TENANT

    def _get(self, key: TenantKey) -> _Tenant:
        t = self._tenants.get(key)
        if t is None:
            t = self._tenants.setdefault(key, _Tenant())
        return t

    def _roll(self, t: _Tenant, now: float) -> None:
        """Roll BOTH rolling windows (scan + ingest) when expired — the
        one place window state resets."""
        if now - t.win_start >= self.window_s:
            t.win_start = now
            t.win_samples = 0
            t.win_warned = False
        if now - t.ingest_win_start >= self.window_s:
            t.ingest_win_start = now
            t.win_ingest = 0

    def _retry_after(self, win_start: float, now: float) -> float:
        """Seconds until a rolling window (scan OR ingest) resets — the
        Retry-After value every 429 this accountant produces shares, so
        scan-limit rejections, ingest rejections and the scheduler's
        overload sheds all answer a compliant client identically."""
        return max(self.window_s - (now - win_start), 0.001)

    def scan_retry_after(self, ws: str, ns: str) -> float:
        """Retry-After for a scan-limit (admit) rejection: how long
        until this tenant's scan window rolls and queries admit again.
        The read-side twin of admit_ingest's return value."""
        now = time.monotonic()
        with self._lock:
            t = self._tenants.get(self.resolve(ws, ns))
            if t is None:
                return 0.001
            self._roll(t, now)
            return self._retry_after(t.win_start, now)

    # ----------------------------------------------------------- account

    def record_query(self, ws: str, ns: str, seconds: float,
                     samples_scanned: int, result_bytes: int) -> None:
        from filodb_tpu.utils.metrics import registry
        now = time.monotonic()
        with self._lock:
            key = self.resolve(ws, ns)
            t = self._get(key)
            self._roll(t, now)
            t.queries += 1
            t.query_seconds += seconds
            t.samples_scanned += samples_scanned
            t.result_bytes += result_bytes
            t.win_samples += samples_scanned
        tags = {"ws": key[0], "ns": key[1]}
        registry.counter("tenant_queries", **tags).increment()
        registry.counter("tenant_query_seconds", **tags).increment(seconds)
        registry.counter("tenant_query_samples_scanned",
                         **tags).increment(samples_scanned)
        registry.counter("tenant_query_result_bytes",
                         **tags).increment(result_bytes)

    def record_ingest(self, ws: str, ns: str, samples: int,
                      dataset: str = "") -> None:
        from filodb_tpu.utils.metrics import registry
        with self._lock:
            key = self.resolve(ws, ns)
            self._get(key).ingest_samples += samples
        registry.counter("tenant_ingest_samples", ws=key[0], ns=key[1],
                         dataset=dataset).increment(samples)

    # ------------------------------------------------------------ limits

    def admit(self, ws: str, ns: str, warn_limit: int,
              fail_limit: int) -> Optional[str]:
        """None to admit, else the structured rejection error.  Checked
        BEFORE execution against the tenant's rolling-window scan total;
        the query that crosses the line still runs (limits bound the
        window's cumulative burn, not predict a query's cost)."""
        if not (warn_limit or fail_limit):
            return None
        if ws in INTERNAL_WORKSPACES:
            return None
        from filodb_tpu.utils.metrics import registry
        now = time.monotonic()
        with self._lock:
            ws, ns = self.resolve(ws, ns)
            t = self._get((ws, ns))
            self._roll(t, now)
            win = t.win_samples
            warn = (warn_limit and win > warn_limit and not t.win_warned)
            if warn:
                t.win_warned = True
            reject = bool(fail_limit and win > fail_limit)
            if reject:
                t.rejected += 1
        if warn and not reject:
            registry.counter("tenant_limit_warnings", ws=ws,
                             ns=ns).increment()
            log.warning(
                "tenant ws=%r ns=%r over warn limit: %d samples scanned "
                "in the current %gs window (limit %d)", ws, ns, win,
                self.window_s, warn_limit)
        if reject:
            registry.counter("tenant_limit_rejections", ws=ws,
                             ns=ns).increment()
            return (f"tenant_limit_exceeded: ws={ws!r} ns={ns!r} scanned "
                    f"{win} samples in the last {self.window_s:g}s, over "
                    f"the limit {fail_limit} — retry after the window "
                    f"rolls")
        return None

    def admit_ingest(self, ws: str, ns: str, samples: int,
                     fail_limit: int) -> Optional[float]:
        """Write-side admission at every ingest door (remote_write, the
        Influx TCP gateway, /influx): None admits `samples` and books
        them against the tenant's rolling ingest window; a float rejects
        and is the seconds until the window rolls — remote_write turns
        it into `429` + `Retry-After` (backpressure: the client re-sends,
        nothing is silently dropped).  Like the scan limits, the batch
        that CROSSES the line still lands (limits bound the window's
        cumulative offer, not predict a batch's size); everything after
        it bounces until the window resets."""
        if not fail_limit:
            return None
        if ws in INTERNAL_WORKSPACES:
            return None
        from filodb_tpu.utils.metrics import registry
        now = time.monotonic()
        with self._lock:
            ws, ns = self.resolve(ws, ns)
            t = self._get((ws, ns))
            self._roll(t, now)
            if t.win_ingest > fail_limit:
                t.ingest_rejected += 1
                retry_after = self._retry_after(t.ingest_win_start, now)
            else:
                t.win_ingest += samples
                retry_after = None
        if retry_after is not None:
            registry.counter("tenant_ingest_rejections", ws=ws,
                             ns=ns).increment()
            return retry_after
        return None

    def window_samples(self, ws: str, ns: str) -> int:
        now = time.monotonic()
        with self._lock:
            t = self._tenants.get(self.resolve(ws, ns))
            if t is None:
                return 0
            self._roll(t, now)
            return t.win_samples

    # ---------------------------------------------------------- snapshot

    def snapshot(self) -> List[dict]:
        """The /api/v1/usage payload: one row per tenant, cumulative
        since process start plus the current window's scan total."""
        now = time.monotonic()
        with self._lock:
            out = []
            for (ws, ns), t in self._tenants.items():
                self._roll(t, now)
                out.append({
                    "ws": ws, "ns": ns,
                    "queries": t.queries,
                    "querySeconds": round(t.query_seconds, 6),
                    "samplesScanned": t.samples_scanned,
                    "resultBytes": t.result_bytes,
                    "ingestSamples": t.ingest_samples,
                    "rejected": t.rejected,
                    "ingestRejected": t.ingest_rejected,
                    "windowSamplesScanned": t.win_samples,
                    "windowSamplesOffered": t.win_ingest,
                })
        out.sort(key=lambda r: (-r["querySeconds"], r["ws"], r["ns"]))
        return out


# process-wide instance (frontend + shards + routes share it)
usage = UsageAccountant()


# ------------------------------------------------- tenant identification

_tenant_memo: Dict[str, TenantKey] = {}
_TENANT_MEMO_MAX = 2048


def tenant_of(promql: str) -> TenantKey:
    """(_ws_, _ns_) from the query's first vector selector's equality
    matchers ("" where absent) — the same shard-key pair the planner
    routes by.  Memoized per distinct promql string; parse failures
    attribute to the anonymous tenant (the engine surfaces the error)."""
    got = _tenant_memo.get(promql)
    if got is not None:
        return got
    ws = ns = ""
    try:
        from filodb_tpu.promql import ast as A
        from filodb_tpu.promql.parser import parse_query_cached
        expr = parse_query_cached(promql)
        sel = _first_selector(expr)
        if sel is not None:
            for m in sel.matchers:
                if m.op == "=" and m.name == "_ws_":
                    ws = m.value
                elif m.op == "=" and m.name == "_ns_":
                    ns = m.value
    except Exception:  # noqa: BLE001 — unparsable: anonymous tenant
        pass
    if len(_tenant_memo) > _TENANT_MEMO_MAX:
        _tenant_memo.clear()
    _tenant_memo[promql] = (ws, ns)
    return ws, ns


def _first_selector(node):
    import dataclasses as _dc

    from filodb_tpu.promql import ast as A
    if isinstance(node, A.VectorSelector):
        return node
    if _dc.is_dataclass(node) and not isinstance(node, type):
        for f in _dc.fields(node):
            got = _first_selector(getattr(node, f.name))
            if got is not None:
                return got
    elif isinstance(node, (list, tuple)):
        for x in node:
            got = _first_selector(x)
            if got is not None:
                return got
    return None
