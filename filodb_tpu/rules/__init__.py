"""Ruler — recording & alerting rules engine (doc/recording_rules.md).

Standing queries with Prometheus semantics: rule groups evaluated on an
interval through the QueryFrontend, recording-rule outputs written back
through the columnar ingest path, alert rules driven through the
inactive -> pending -> firing -> keep_firing_for state machine with
`ALERTS`/`ALERTS_FOR_STATE` write-back so state survives restart by
replay (ref: Cortex's ruler; Monarch's standing queries, VLDB'20).
"""
from filodb_tpu.rules.config import (Rule, RuleGroup, RulesConfigError,
                                     load_rule_groups)
from filodb_tpu.rules.notifier import WebhookNotifier
from filodb_tpu.rules.ruler import MemstoreSink, Ruler

__all__ = ["Rule", "RuleGroup", "RulesConfigError", "load_rule_groups",
           "Ruler", "MemstoreSink", "WebhookNotifier"]
