"""The Ruler: scheduled evaluation of rule groups, recording write-back,
and the alert state machine.

Semantics follow Prometheus' rule manager (ref: Cortex's ruler service,
which runs the same manager against a remote store; Monarch's standing
queries, VLDB'20 §5):

  * one evaluation loop per group, ticks aligned to the group interval
    with a DETERMINISTIC per-group stagger (xxhash32 of the group name)
    so N groups sharing an interval don't thundering-herd the frontend
    at :00 boundaries;
  * rules evaluate SEQUENTIALLY within a group — a later rule's instant
    query sees earlier rules' freshly-recorded output at the same
    evaluation timestamp (write-back is synchronous columnar ingest);
  * an iteration that overruns its interval SKIPS the missed ticks
    (`rule_group_iterations_missed`) — standing queries precompute the
    present, they never backfill the past;
  * every rule evaluates as an instant query through the QueryFrontend —
    admission, the concurrency bound, tenant accounting as `_rules_`,
    and a per-group deadline equal to the group interval riding the PR-4
    deadline machinery (an evaluation can never outlive its slot);
  * partial results NEVER record: the iteration fails, is counted, and
    alert state holds (a dead shard must not flap a firing alert or
    write a half-aggregate the dashboards would trust).

Alert state machine: inactive -> pending (`for:`) -> firing ->
`keep_firing_for`.  Synthetic `ALERTS{alertstate=...}` and
`ALERTS_FOR_STATE` (value = activeAt unix seconds) series are written
back each iteration, so alert state survives restart by replaying
`ALERTS_FOR_STATE` from the store — exactly Prometheus' restore path.
"""
from __future__ import annotations

import math
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from filodb_tpu.core.partkey import PartKey
from filodb_tpu.query.rangevector import PlannerParams
from filodb_tpu.rules.config import (Rule, RuleGroup, RulesConfigError,
                                     load_rule_groups)
from filodb_tpu.rules.notifier import WebhookNotifier
from filodb_tpu.utils import iso_utc as _iso
from filodb_tpu.utils.hashing import xxhash32

PENDING = "pending"
FIRING = "firing"

# seconds-scale bounds for the eval-duration / group-lag histograms (the
# registry default is tuned for millisecond latencies)
_SECONDS_BOUNDS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                   2.5, 5.0, 10.0, 30.0, 60.0, 300.0)


class MemstoreSink:
    """Write-back path for recorded/synthetic series: shard-routed
    columnar ingest (`TimeSeriesShard.ingest_columns`), so recorded
    series are immediately queryable, flushable and downsample-eligible
    exactly like gateway-ingested series."""

    SCHEMA = "gauge"

    def __init__(self, memstore, dataset: str, mapper=None,
                 spread_provider=None):
        from filodb_tpu.parallel.shardmapper import SpreadProvider
        self.memstore = memstore
        self.dataset = dataset
        self.mapper = mapper
        self.spread = spread_provider or SpreadProvider(0)

    def write(self, part_keys: Sequence[PartKey], ts_ms: int,
              values: Sequence[float]) -> int:
        """One evaluation's output vector at one timestamp.  Raises on
        any shard failure — the caller fails (and counts) the WHOLE
        iteration; dashboards must never see a half-recorded aggregate
        presented as the real thing."""
        if not part_keys:
            return 0
        vals = np.asarray(values, dtype=np.float64).reshape(-1, 1)
        if self.mapper is None:
            shard_of = np.zeros(len(part_keys), dtype=np.int64)
        else:
            shard_of = np.asarray([
                self.mapper.ingestion_shard(
                    pk.shard_key_hash(), pk.partition_hash(),
                    self.spread.spread_for(pk.shard_key()))
                for pk in part_keys])
        shards = {}
        for s in np.unique(shard_of).tolist():
            shard = self.memstore.get_shard(self.dataset, s)
            if shard is None:
                raise ConnectionError(
                    f"record write: shard {s} of {self.dataset!r} "
                    "is not locally owned")
            shards[s] = shard
        n = 0
        for s, shard in shards.items():
            idx = np.flatnonzero(shard_of == s)
            keys = [part_keys[i] for i in idx.tolist()]
            ts = np.full((len(keys), 1), int(ts_ms), dtype=np.int64)
            n += shard.ingest_columns(self.SCHEMA, keys, ts,
                                      {"value": vals[idx]})
        return n


class _AlertInstance:
    __slots__ = ("labels", "state", "active_at_s", "keep_since_s",
                 "last_notified_s", "value")

    def __init__(self, labels: Dict[str, str], active_at_s: float):
        self.labels = labels
        self.state = PENDING
        self.active_at_s = active_at_s
        # first ABSENT evaluation while firing: the keep_firing_for
        # clock starts here (Prometheus keepFiringSince), not at the
        # last present tick — evaluation gaps must not eat the hold
        self.keep_since_s = 0.0
        self.last_notified_s = 0.0   # 0 = never delivered
        self.value = 0.0

    def clone(self) -> "_AlertInstance":
        """Private mutable copy — published instances are immutable to
        evaluation (HTTP payload readers hold references lock-free)."""
        c = _AlertInstance.__new__(_AlertInstance)
        for f in _AlertInstance.__slots__:
            setattr(c, f, getattr(self, f))
        return c

    def payload(self, rule: Rule) -> Dict:
        """`/api/v1/alerts` shape (the Prometheus API Alert object)."""
        return {"labels": dict(self.labels),
                "annotations": rule.annotations_dict,
                "state": self.state,
                "activeAt": _iso(self.active_at_s),
                "value": repr(float(self.value))}

    def webhook_payload(self, rule: Rule) -> Dict:
        """Alertmanager v4 webhook alert shape — status/startsAt/endsAt,
        NOT the API's state/activeAt (a receiver written against the
        webhook spec reads alert["status"]/["startsAt"])."""
        return {"status": "firing",
                "labels": dict(self.labels),
                "annotations": rule.annotations_dict,
                "startsAt": _iso(self.active_at_s),
                "endsAt": "",
                "generatorURL": ""}


class _RuleRuntime:
    """Mutable evaluation state for one rule (health, timings, alert
    instances keyed by sorted label tuple)."""
    __slots__ = ("rule", "health", "last_error", "last_eval_unix_s",
                 "eval_seconds", "alerts", "restored")

    def __init__(self, rule: Rule):
        self.rule = rule
        self.health = "unknown"          # ok | err | unknown
        self.last_error = ""
        self.last_eval_unix_s = 0.0
        self.eval_seconds = 0.0
        self.alerts: Dict[Tuple, _AlertInstance] = {}
        self.restored = rule.kind != "alerting"


class _GroupState:
    __slots__ = ("group", "runtimes", "runner", "last_eval_unix_s",
                 "eval_seconds", "generation")

    def __init__(self, group: RuleGroup):
        self.group = group
        self.runtimes = [_RuleRuntime(r) for r in group.rules]
        self.runner: Optional[threading.Thread] = None
        self.last_eval_unix_s = 0.0
        self.eval_seconds = 0.0
        self.generation = 0


class Ruler:
    """Standing-query engine over one dataset's QueryFrontend + sink."""

    TENANT_WS = "_rules_"

    def __init__(self, frontend, sink, groups: Optional[List[RuleGroup]]
                 = None, config=None, clock=time.time,
                 notifier: Optional[WebhookNotifier] = None,
                 config_source=None):
        if config is None:
            from filodb_tpu.config import settings
            config = settings().rules
        self.config = config
        # zero-arg callable returning a FRESH RulesConfig for reload();
        # standalone wires one that re-reads the conf file from disk, so
        # /admin/rules/reload picks up edited inline `rules.groups` too
        # (None: the in-memory config is the only source — its `file`
        # is still re-read every reload)
        self.config_source = config_source
        self.frontend = frontend
        self.sink = sink
        self.clock = clock
        # _own_notifier: built from config, so reload() rebuilds it when
        # the notify_* settings change; an INJECTED notifier is the
        # caller's to manage and survives reloads untouched
        self._own_notifier = notifier is None
        self.notifier = notifier or self._build_notifier(config)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._started = False
        self._groups: Dict[str, _GroupState] = {}
        for g in (groups if groups is not None
                  else load_rule_groups(config)):
            self._groups[g.name] = _GroupState(g)

    @staticmethod
    def _build_notifier(config) -> WebhookNotifier:
        return WebhookNotifier(
            url=config.notify_url, retries=config.notify_retries,
            backoff_s=config.notify_backoff_s,
            timeout_s=config.notify_timeout_s)

    @staticmethod
    def _notify_key(config) -> Tuple:
        return (config.notify_url, config.notify_retries,
                config.notify_backoff_s, config.notify_timeout_s)

    # --------------------------------------------------------- lifecycle

    def start(self) -> "Ruler":
        with self._lock:
            self._started = True
            self._stop.clear()
            for gs in self._groups.values():
                self._start_runner(gs)
        return self

    def stop(self) -> None:
        with self._lock:
            self._started = False
            self._stop.set()
            runners = [gs.runner for gs in self._groups.values()
                       if gs.runner is not None]
            for gs in self._groups.values():
                gs.generation += 1
                gs.runner = None
        for t in runners:
            t.join(timeout=5.0)

    def _start_runner(self, gs: _GroupState) -> None:
        """Caller holds the lock.  Each runner is pinned to the group
        state's generation — a reload bumps the generation and the old
        thread exits at its next wakeup instead of racing the new one."""
        gs.generation += 1
        gen = gs.generation
        t = threading.Thread(target=self._run_group, args=(gs, gen),
                             name=f"ruler-{gs.group.name}", daemon=True)
        gs.runner = t
        t.start()

    def _run_group(self, gs: _GroupState, gen: int) -> None:
        from filodb_tpu.utils.metrics import registry
        g = gs.group
        interval = g.interval_s
        # deterministic stagger: same group name -> same phase on every
        # node and every restart, spread uniformly across the interval
        stagger = (xxhash32(g.name.encode()) % max(int(
            interval * 1000.0), 1)) / 1000.0
        while not self._stop.is_set() and gs.generation == gen:
            now = self.clock()
            next_t = (math.floor((now - stagger) / interval) + 1) \
                * interval + stagger
            if self._stop.wait(max(next_t - now, 0.0)):
                return
            if gs.generation != gen:
                return
            lag = max(self.clock() - next_t, 0.0)
            registry.histogram("rule_group_lag_seconds",
                               bounds=_SECONDS_BOUNDS,
                               group=g.name).record(lag)
            # evaluate the CAPTURED state, not a name lookup: a reload
            # that swaps the group between our generation check and here
            # must not hand this retired runner the new runtimes (two
            # threads would mutate them and double-record the tick)
            self._evaluate_state(gs, ts=next_t)
            # skip-not-backfill: ticks that passed while we evaluated
            # are counted missed; the loop recomputes next_t from NOW
            behind = int((self.clock() - next_t) / interval)
            if behind >= 1:
                registry.counter("rule_group_iterations_missed",
                                 group=g.name).increment(behind)

    # -------------------------------------------------------- evaluation

    def evaluate_group(self, name: str, ts: Optional[float] = None) -> bool:
        """One full iteration of a group at evaluation timestamp `ts`
        (the SCHEDULED tick in production; tests drive a fake clock).
        Returns True iff every rule evaluated cleanly."""
        with self._lock:
            gs = self._groups.get(name)
        if gs is None:
            raise KeyError(f"no rules group {name!r}")
        return self._evaluate_state(gs, ts)

    def _evaluate_state(self, gs: _GroupState,
                        ts: Optional[float] = None) -> bool:
        from filodb_tpu.utils.jobs import jobs
        from filodb_tpu.utils.metrics import registry
        g = gs.group
        # whole-second evaluation timestamp: the instant-query API takes
        # int seconds, so a fractional tick (stagger is sub-second) would
        # evaluate at int(ts) but record at int(ts*1000) — a sample up to
        # 999 ms in the FUTURE of the eval time, invisible to the
        # second-order rules in this same group that the sequential
        # semantics promise can see it
        ts = float(int(ts if ts is not None else self.clock()))
        t0 = time.perf_counter()
        ok = True
        # unified job registry: one handle per group (idempotent across
        # reloads — history carries over), so a group whose evaluations
        # keep failing shows its streak at /admin/jobs and in the
        # job_consecutive_errors gauge the shipped self-scrape alert
        # group watches
        job = jobs.register(f"ruler:{g.name}", interval_s=g.interval_s)
        with job.tick():
            for i, rt in enumerate(gs.runtimes):
                job.set_progress(
                    f"rule {i + 1}/{len(gs.runtimes)}: {rt.rule.name}")
                ok = self._eval_rule(g, rt, ts) and ok
            if not ok:
                errs = "; ".join(rt.last_error for rt in gs.runtimes
                                 if rt.last_error)[:300]
                job.note_error(errs or "rule evaluation failed")
        gs.eval_seconds = time.perf_counter() - t0
        gs.last_eval_unix_s = ts
        registry.histogram("rule_group_eval_seconds",
                           bounds=_SECONDS_BOUNDS,
                           group=g.name).record(gs.eval_seconds)
        return ok

    def _planner_params(self, g: RuleGroup) -> PlannerParams:
        # per-group eval deadline == the group interval (PR-4 machinery,
        # enforced at every exec-node boundary).  Stamped as an absolute
        # deadline rather than timeout_s: compute_deadline caps timeout_s
        # at query.default_timeout_s (120 s default), which would silently
        # shrink the slot of any group with interval > 2 min — a stamped
        # deadline wins uncapped, and it is repr-excluded so serving keys
        # are unaffected.  Partials are hard-disabled: a degraded vector
        # must fail the iteration, never record.
        return PlannerParams(allow_partial_results=False,
                             deadline_unix_s=time.time() + g.interval_s)

    def _eval_rule(self, g: RuleGroup, rt: _RuleRuntime,
                   ts: float) -> bool:
        from filodb_tpu.utils.metrics import registry
        rule = rt.rule
        if not rt.restored:
            # restart replay BEFORE the first evaluation: pending/firing
            # clocks must not reset just because the process moved
            self._restore_alert_state(g, rt, ts)
        t0 = time.perf_counter()
        try:
            res = self.frontend.query_instant(
                rule.expr, int(ts), self._planner_params(g),
                tenant=(self.TENANT_WS, g.name), origin="rule_eval")
            if res.error:
                raise RuntimeError(res.error)
            if res.partial:
                raise RuntimeError(
                    "partial result: refusing to record/transition from "
                    "a degraded vector")
            vec = _output_vector(res)
            if rule.kind == "recording":
                self._record(g, rule, vec, ts)
            else:
                self._eval_alert(g, rt, vec, ts)
        except Exception as e:  # noqa: BLE001 — one rule must not sink the group
            registry.counter("rule_evaluation_failures",
                             group=g.name).increment()
            rt.health = "err"
            rt.last_error = f"{e}"[:500]
            rt.last_eval_unix_s = ts
            rt.eval_seconds = time.perf_counter() - t0
            return False
        rt.health = "ok"
        rt.last_error = ""
        rt.last_eval_unix_s = ts
        rt.eval_seconds = time.perf_counter() - t0
        return True

    def _record(self, g: RuleGroup, rule: Rule,
                vec: List[Tuple[Dict[str, str], float]],
                ts: float) -> None:
        from filodb_tpu.utils.metrics import registry
        keys = []
        vals = []
        overrides = rule.labels_dict
        for labels, value in vec:
            tags = dict(labels)
            tags.pop("_metric_", None)
            tags.update(overrides)
            keys.append(PartKey.make(rule.name, tags))
            vals.append(value)
        n = self.sink.write(keys, int(ts * 1000.0), vals)
        registry.counter("rule_recorded_samples",
                         group=g.name).increment(n)

    # ------------------------------------------------------ alert engine

    def _alert_labels(self, rule: Rule,
                      series_labels: Dict[str, str]) -> Dict[str, str]:
        lab = dict(series_labels)
        lab.pop("_metric_", None)
        lab.update(rule.labels_dict)
        lab["alertname"] = rule.name
        return lab

    def _eval_alert(self, g: RuleGroup, rt: _RuleRuntime,
                    vec: List[Tuple[Dict[str, str], float]],
                    ts: float) -> None:
        rule = rt.rule
        present: Dict[Tuple, Tuple[Dict[str, str], float]] = {}
        for labels, value in vec:
            lab = self._alert_labels(rule, labels)
            present[tuple(sorted(lab.items()))] = (lab, value)
        # copy-on-write: rules_payload/alerts_payload iterate rt.alerts
        # lock-free from HTTP threads, so every instance this iteration
        # touches is CLONED before mutation and the whole map published
        # with one atomic ref swap — readers never see a torn instance
        alerts = dict(rt.alerts)
        for key, (lab, value) in present.items():
            prev = alerts.get(key)
            inst = alerts[key] = (_AlertInstance(lab, ts) if prev is None
                                  else prev.clone())
            inst.keep_since_s = 0.0
            inst.value = value
            if inst.state != FIRING and \
                    ts - inst.active_at_s >= rule.for_s:
                inst.state = FIRING
        ended: List[_AlertInstance] = []
        for key, inst in list(alerts.items()):
            if key in present:
                continue
            if inst.state == FIRING and rule.keep_firing_for_s > 0:
                # the hold clock starts at the FIRST absent evaluation
                # (Prometheus keepFiringSince semantics)
                held = alerts[key] = inst.clone()
                if not held.keep_since_s:
                    held.keep_since_s = ts
                if ts - held.keep_since_s < rule.keep_firing_for_s:
                    continue             # held by keep_firing_for
                inst = held
            ended.append(inst)
            del alerts[key]              # pending cleared / resolved
        # notify firing instances that were never delivered (covers new
        # transitions AND batches dropped last interval) plus, when
        # rules.notify_resend_delay_s > 0, periodic re-sends so a real
        # Alertmanager's resolve_timeout never auto-resolves a live alert
        resend_s = float(getattr(self.config, "notify_resend_delay_s",
                                 0.0) or 0.0)
        batch = [i for i in alerts.values()
                 if i.state == FIRING and (
                     i.last_notified_s == 0.0
                     or (resend_s > 0
                         and ts - i.last_notified_s >= resend_s))]
        # synthetic series write-back: ALERTS carries the live state,
        # ALERTS_FOR_STATE the pending clock (activeAt) for restart
        # replay.  One write per rule per iteration, atomic with the
        # iteration's success — a raise here fails the iteration BEFORE
        # the new map is published, so alert state holds (no flap, no
        # transition the store never saw).
        keys = []
        vals = []
        for inst in alerts.values():
            keys.append(PartKey.make(
                "ALERTS", {**inst.labels, "alertstate": inst.state}))
            vals.append(1.0)
            keys.append(PartKey.make("ALERTS_FOR_STATE", inst.labels))
            vals.append(float(inst.active_at_s))
        # NaN staleness markers for episodes that just ENDED: without
        # them the resolved episode's last ALERTS_FOR_STATE sample stays
        # visible for the stale-lookback window, and a restart inside it
        # would resurrect the alert with its old activeAt — skipping the
        # `for:` hold entirely (Prometheus hides these with staleness
        # markers; _output_vector drops NaN, so the restore replay and
        # dashboards both see the episode end at this tick)
        for inst in ended:
            keys.append(PartKey.make(
                "ALERTS", {**inst.labels, "alertstate": inst.state}))
            vals.append(float("nan"))
            keys.append(PartKey.make("ALERTS_FOR_STATE", inst.labels))
            vals.append(float("nan"))
        self.sink.write(keys, int(ts * 1000.0), vals)
        rt.alerts = alerts
        if batch and self.notifier.notify([i.webhook_payload(rule)
                                           for i in batch]):
            # only a delivered (or queued) batch advances the clock — a
            # dropped one leaves last_notified_s at 0 so the NEXT
            # interval re-notifies the still-firing alert.  The clones
            # are already published; last_notified_s is eval-private
            # state no payload reader looks at.
            for inst in batch:
                inst.last_notified_s = ts

    def _restore_alert_state(self, g: RuleGroup, rt: _RuleRuntime,
                             ts: float) -> None:
        """Replay `ALERTS_FOR_STATE{alertname=...}` so pending/firing
        clocks survive restart.  Restored instances re-enter as PENDING
        with the ORIGINAL activeAt; the evaluation that follows promotes
        them straight back to firing when `for:` has already elapsed and
        the expr still yields the series.  Failures are non-fatal — an
        empty store (first boot) just starts clean."""
        rule = rt.rule
        rt.restored = True
        try:
            sel = f'ALERTS_FOR_STATE{{alertname="{rule.name}"}}'
            res = self.frontend.query_instant(
                sel, int(ts), self._planner_params(g),
                tenant=(self.TENANT_WS, g.name), origin="rule_restore")
            if res.error:
                return
            alerts = dict(rt.alerts)     # copy-on-write, as in _eval_alert
            for labels, value in _output_vector(res):
                lab = dict(labels)
                lab.pop("_metric_", None)
                key = tuple(sorted(lab.items()))
                if key not in alerts:
                    alerts[key] = _AlertInstance(lab, float(value))
            rt.alerts = alerts
        except Exception:  # noqa: BLE001 — replay is best-effort
            pass

    # ------------------------------------------------------- hot reload

    def reload(self, groups: Optional[List[RuleGroup]] = None) -> Dict:
        """Swap in a freshly-loaded config (None -> re-read the conf
        tree + rules file).  Groups are diffed by name; alert state and
        health carry over for rules whose identity (name, expr, timing)
        is unchanged — a reload that touches one group must not reset
        every firing alert's clock.  Raises RulesConfigError on invalid
        config, leaving the running state untouched (Prometheus reload
        semantics: a bad file never kills the live rules)."""
        if groups is not None:
            new_groups = groups
        else:
            cfg = self.config
            if self.config_source is None and not (
                    getattr(cfg, "groups", None) or
                    getattr(cfg, "file", "")):
                # programmatic embed (Ruler(groups=[...]) with a bare
                # RulesConfig): an argless reload would load [] and
                # silently retire every running group — refuse instead
                raise RulesConfigError(
                    "no reloadable rules source: this ruler was built "
                    "with programmatic groups and no config_source; "
                    "reload via ruler.reload(groups=[...])")
            if self.config_source is not None:
                try:
                    cfg = self.config_source()
                except RulesConfigError:
                    raise
                except Exception as e:  # noqa: BLE001 — bad conf file/IO
                    raise RulesConfigError(
                        f"re-reading rules config: {e}") from None
                old_cfg, self.config = self.config, cfg
                if self._own_notifier and self._notify_key(cfg) != \
                        self._notify_key(old_cfg):
                    # notify_url/retries/backoff/timeout edits must land
                    # on reload like rule edits do; the old dispatcher
                    # thread finishes draining its queue on the old
                    # settings, new batches go to the new notifier
                    self.notifier = self._build_notifier(cfg)
            new_groups = load_rule_groups(cfg)
        if len({g.name for g in new_groups}) != len(new_groups):
            raise RulesConfigError("duplicate group names in reload")
        added, removed, changed = [], [], []
        with self._lock:
            old = self._groups
            nxt: Dict[str, _GroupState] = {}
            for g in new_groups:
                prev = old.get(g.name)
                if prev is None:
                    added.append(g.name)
                    nxt[g.name] = _GroupState(g)
                elif prev.group == g:
                    nxt[g.name] = prev       # untouched: keep the runner
                else:
                    changed.append(g.name)
                    gs = _GroupState(g)
                    carried = {rt.rule.identity(): rt
                               for rt in prev.runtimes}
                    runtimes = []
                    for r in g.rules:
                        src = carried.pop(r.identity(), None)
                        rt = _RuleRuntime(r)
                        if src is not None:
                            # identity matched: state carries over into a
                            # FRESH runtime (the retired runner may still
                            # be mid-iteration on `src` — sharing the
                            # object would let two threads read-copy-write
                            # the same alerts map and lose transitions)
                            # bound to the NEW Rule — identity() excludes
                            # annotations, so an annotation-only edit
                            # still reaches payloads/notifications.
                            # Instances are clone-before-mutate, so a
                            # shallow map copy is race-free.
                            rt.health = src.health
                            rt.last_error = src.last_error
                            rt.last_eval_unix_s = src.last_eval_unix_s
                            rt.eval_seconds = src.eval_seconds
                            rt.alerts = dict(src.alerts)
                            rt.restored = src.restored
                        runtimes.append(rt)
                    gs.runtimes = runtimes
                    gs.last_eval_unix_s = prev.last_eval_unix_s
                    prev.generation += 1     # retire the old runner
                    nxt[g.name] = gs
            for name, gs in old.items():
                if name not in nxt:
                    removed.append(name)
                    gs.generation += 1       # retire the runner
            self._groups = nxt
            if self._started:
                for name in added + changed:
                    self._start_runner(nxt[name])
        # a removed group's job handle must leave the registry with it:
        # a stale failing-group streak would otherwise hold the health
        # verdict degraded (and keep the self-scraped
        # job_consecutive_errors gauge alerting) until process restart
        from filodb_tpu.utils.jobs import jobs
        for name in removed:
            jobs.unregister(f"ruler:{name}")
        from filodb_tpu.utils.events import journal
        from filodb_tpu.utils.metrics import registry
        registry.counter("rule_config_reloads").increment()
        journal.emit("rules_reloaded", subsystem="rules",
                     groups=len(new_groups), added=len(added),
                     removed=len(removed), changed=len(changed))
        return {"groups": len(new_groups), "added": sorted(added),
                "removed": sorted(removed), "changed": sorted(changed)}

    # ------------------------------------------------------ API payloads

    def rules_payload(self) -> Dict:
        """`/api/v1/rules` data (the Prometheus RuleDiscovery shape)."""
        with self._lock:
            states = list(self._groups.values())
        groups = []
        for gs in states:
            g = gs.group
            rules = []
            for rt in gs.runtimes:
                r = rt.rule
                base = {
                    "name": r.name,
                    "query": r.expr,
                    "labels": r.labels_dict,
                    "health": rt.health,
                    "lastError": rt.last_error,
                    "evaluationTime": round(rt.eval_seconds, 6),
                    "lastEvaluation": _iso(rt.last_eval_unix_s),
                }
                if r.kind == "recording":
                    base["type"] = "recording"
                else:
                    insts = sorted(rt.alerts.values(),
                                   key=lambda i: sorted(i.labels.items()))
                    state = "inactive"
                    if any(i.state == FIRING for i in insts):
                        state = "firing"
                    elif insts:
                        state = "pending"
                    base.update({
                        "type": "alerting",
                        "duration": r.for_s,
                        "keepFiringFor": r.keep_firing_for_s,
                        "annotations": r.annotations_dict,
                        "state": state,
                        "alerts": [i.payload(r) for i in insts],
                    })
                rules.append(base)
            groups.append({
                "name": g.name,
                "file": g.source,
                "interval": g.interval_s,
                "rules": rules,
                "evaluationTime": round(gs.eval_seconds, 6),
                "lastEvaluation": _iso(gs.last_eval_unix_s),
            })
        return {"groups": groups}

    def alerts_payload(self) -> Dict:
        """`/api/v1/alerts` data: every active (pending|firing) alert."""
        out = []
        with self._lock:
            states = list(self._groups.values())
        for gs in states:
            for rt in gs.runtimes:
                if rt.rule.kind != "alerting":
                    continue
                for inst in sorted(rt.alerts.values(),
                                   key=lambda i: sorted(i.labels.items())):
                    out.append(inst.payload(rt.rule))
        return {"alerts": out}

    def group_names(self) -> List[str]:
        with self._lock:
            return sorted(self._groups)


def _output_vector(res) -> List[Tuple[Dict[str, str], float]]:
    """Instant-vector extraction from a QueryResult: (labels, value) per
    series with a non-NaN sample at the evaluation step (the same edge
    QueryEngine.to_prom_vector serializes)."""
    out = []
    for key, _wends, vals in res.series():
        v = np.asarray(vals)
        if v.ndim != 1 or v.size == 0:   # histogram blocks don't record
            continue
        x = float(v[-1])
        if not math.isnan(x):
            out.append((key.labels_dict, x))
    return out
