"""Rule-group configuration: loading + validation.

Two sources, merged (group names must be unique across both):

  * the conf tree's `rules.groups` block — dict-shaped, because
    HOCON-lite has no object lists (see conf/example-filodb.conf)
  * a standalone rules file (`rules.file`) — a .json in the Prometheus
    rule-file shape ({"groups": [{"name", "interval", "rules": [...]}]})
    or a HOCON-lite .conf mirroring the inline dict shape

Every rule's `expr` is validated through the real PromQL parser at load
time (a typo'd standing query must fail the reload/boot loudly, not
silently evaluate to errors every interval), record/alert names against
the Prometheus metric-name grammar, and durations accept numbers
(seconds), duration strings ("30s", "1h30m") or HOCON-lite Durations.
"""
from __future__ import annotations

import dataclasses
import json
import re
from typing import Any, Dict, List, Optional, Tuple

_METRIC_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


class RulesConfigError(ValueError):
    """Invalid rules config — carries the full group/rule path."""


@dataclasses.dataclass(frozen=True)
class Rule:
    """One recording or alerting rule (Prometheus rule-file semantics)."""
    name: str                     # record metric name / alertname
    expr: str
    kind: str                     # "recording" | "alerting"
    labels: Tuple[Tuple[str, str], ...] = ()
    annotations: Tuple[Tuple[str, str], ...] = ()
    for_s: float = 0.0            # alerting: pending -> firing hold
    keep_firing_for_s: float = 0.0

    @property
    def labels_dict(self) -> Dict[str, str]:
        return dict(self.labels)

    @property
    def annotations_dict(self) -> Dict[str, str]:
        return dict(self.annotations)

    def identity(self) -> Tuple:
        """What must match for runtime state (alert instances, health) to
        carry across a hot reload — the Prometheus stance: same name +
        same expr + same timing semantics is the same rule."""
        return (self.kind, self.name, self.expr, self.labels,
                self.for_s, self.keep_firing_for_s)


@dataclasses.dataclass(frozen=True)
class RuleGroup:
    """Ordered rules sharing one evaluation interval.  Rules evaluate
    SEQUENTIALLY within the group — later rules see earlier rules'
    freshly-recorded output at the same evaluation timestamp."""
    name: str
    interval_s: float
    rules: Tuple[Rule, ...]
    source: str = "conf"          # "conf" or the rules-file path


def _duration_s(value, where: str) -> float:
    """Seconds from a number, a duration string, or a HOCON-lite
    Duration."""
    from filodb_tpu.utils.hoconlite import Duration
    if isinstance(value, Duration):
        return float(value.seconds)
    if isinstance(value, bool):
        raise RulesConfigError(f"{where}: expected a duration, got {value!r}")
    if isinstance(value, (int, float)):
        return float(value)
    if isinstance(value, str):
        from filodb_tpu.promql.lexer import duration_to_ms
        try:
            return duration_to_ms(value) / 1000.0
        except ValueError:
            raise RulesConfigError(
                f"{where}: not a duration: {value!r}") from None
    raise RulesConfigError(f"{where}: expected a duration, got {value!r}")


def _str_map(raw, where: str) -> Tuple[Tuple[str, str], ...]:
    if raw is None:
        return ()
    if not isinstance(raw, dict):
        raise RulesConfigError(f"{where}: expected a map, got {raw!r}")
    out = []
    for k, v in raw.items():
        if not _LABEL_RE.match(str(k)):
            raise RulesConfigError(f"{where}: bad label name {k!r}")
        out.append((str(k), str(v)))
    return tuple(sorted(out))


def _validate_expr(expr: str, where: str) -> str:
    """The standing query must parse through the REAL PromQL parser —
    the same grammar the serving path enforces per request."""
    if not isinstance(expr, str) or not expr.strip():
        raise RulesConfigError(f"{where}: missing expr")
    from filodb_tpu.promql.parser import parse_query
    try:
        parse_query(expr)
    except Exception as e:  # noqa: BLE001 — parser raises its own types
        raise RulesConfigError(f"{where}: bad expr: {e}") from None
    return expr


def _build_rule(raw: dict, where: str) -> Rule:
    if not isinstance(raw, dict):
        raise RulesConfigError(f"{where}: expected a rule object")
    raw = dict(raw)
    record = raw.pop("record", None)
    alert = raw.pop("alert", None)
    if (record is None) == (alert is None):
        raise RulesConfigError(
            f"{where}: exactly one of 'record' or 'alert' is required")
    expr = _validate_expr(raw.pop("expr", ""), where)
    labels = _str_map(raw.pop("labels", None), f"{where}.labels")
    annotations = _str_map(raw.pop("annotations", None),
                           f"{where}.annotations")
    for_s = _duration_s(raw.pop("for", 0.0), f"{where}.for")
    keep_s = _duration_s(raw.pop("keep_firing_for", 0.0),
                         f"{where}.keep_firing_for")
    if raw:
        raise RulesConfigError(
            f"{where}: unknown rule keys {sorted(raw)}")
    if record is not None:
        if not _METRIC_RE.match(str(record)):
            raise RulesConfigError(
                f"{where}: bad record metric name {record!r}")
        if for_s or keep_s:
            raise RulesConfigError(
                f"{where}: 'for'/'keep_firing_for' are alerting-only")
        if annotations:
            raise RulesConfigError(
                f"{where}: 'annotations' are alerting-only")
        return Rule(str(record), expr, "recording", labels)
    if not _METRIC_RE.match(str(alert)):
        raise RulesConfigError(f"{where}: bad alert name {alert!r}")
    return Rule(str(alert), expr, "alerting", labels, annotations,
                for_s, keep_s)


def _build_group(name: str, raw: dict, default_interval_s: float,
                 source: str) -> RuleGroup:
    where = f"rules group {name!r}"
    if not isinstance(raw, dict):
        raise RulesConfigError(f"{where}: expected a group object")
    raw = dict(raw)
    interval = _duration_s(raw.pop("interval", default_interval_s),
                           f"{where}.interval")
    if interval <= 0:
        raise RulesConfigError(f"{where}: interval must be positive")
    rules_raw = raw.pop("rules", None)
    if raw:
        raise RulesConfigError(f"{where}: unknown group keys {sorted(raw)}")
    rules: List[Rule] = []
    if isinstance(rules_raw, dict):
        # conf-tree shape: rule entries keyed by a local name; dict
        # insertion order IS the (Prometheus-semantic) evaluation order
        for rname, rraw in rules_raw.items():
            rules.append(_build_rule(rraw, f"{where}.rules.{rname}"))
    elif isinstance(rules_raw, list):
        for i, rraw in enumerate(rules_raw):
            rules.append(_build_rule(rraw, f"{where}.rules[{i}]"))
    elif rules_raw is not None:
        raise RulesConfigError(f"{where}: 'rules' must be a list or map")
    if not rules:
        raise RulesConfigError(f"{where}: no rules")
    return RuleGroup(name, interval, tuple(rules), source)


def _load_rules_file(path: str) -> Dict[str, Any]:
    if path.endswith(".json"):
        with open(path) as f:
            return json.load(f)
    from filodb_tpu.utils import hoconlite
    raw = hoconlite.load(path)
    # allow the same top-level wrapper the main config accepts
    if set(raw) == {"rules"}:
        raw = raw["rules"]
    return raw


def load_rule_groups(rules_cfg) -> List[RuleGroup]:
    """All configured groups: the conf tree's inline `groups` block plus
    the standalone rules file, validated.  Group names must be unique
    across the two sources (a silent later-wins merge would make half a
    team's rules disappear)."""
    default_s = float(rules_cfg.default_interval_s)
    groups: List[RuleGroup] = []
    seen: Dict[str, str] = {}

    def add(g: RuleGroup) -> None:
        if g.name in seen:
            raise RulesConfigError(
                f"rules group {g.name!r} defined twice "
                f"({seen[g.name]} and {g.source})")
        seen[g.name] = g.source
        groups.append(g)

    inline = rules_cfg.groups or {}
    if not isinstance(inline, dict):
        raise RulesConfigError("rules.groups must be a map of groups")
    for name, raw in inline.items():
        add(_build_group(str(name), raw, default_s, "conf"))
    if rules_cfg.file:
        try:
            raw = _load_rules_file(rules_cfg.file)
        except OSError as e:
            raise RulesConfigError(
                f"rules file {rules_cfg.file!r}: {e}") from None
        except (ValueError, KeyError) as e:
            raise RulesConfigError(
                f"rules file {rules_cfg.file!r}: {e}") from None
        glist = raw.get("groups") if isinstance(raw, dict) else None
        if isinstance(glist, dict):
            for name, graw in glist.items():
                add(_build_group(str(name), graw, default_s,
                                 rules_cfg.file))
        elif isinstance(glist, list):
            for i, graw in enumerate(glist):
                if not isinstance(graw, dict) or "name" not in graw:
                    raise RulesConfigError(
                        f"rules file {rules_cfg.file!r}: groups[{i}] "
                        "needs a 'name'")
                graw = dict(graw)
                name = str(graw.pop("name"))
                add(_build_group(name, graw, default_s, rules_cfg.file))
        else:
            raise RulesConfigError(
                f"rules file {rules_cfg.file!r}: expected a top-level "
                "'groups' list or map")
    return groups
