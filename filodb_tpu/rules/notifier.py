"""Webhook-shaped alert notifier with retry/backoff.

Fires the Alertmanager v4 webhook payload shape at `rules.notify_url`;
with no URL configured, deliveries land in the in-process `sent` ring
instead (tests and single-node ops read it at /api/v1/alerts anyway).
Every delivery ATTEMPT passes the `ruler.notify` fault point
(utils/faults.py), so the chaos harness can exercise the retry/backoff
path and the dropped-notification accounting without a real endpoint.

With a URL configured, batches are handed to a single background
dispatch thread (bounded queue) — the retry/backoff/timeout budget
(~(retries+1)×timeout_s at defaults) must never run inside the group
evaluation loop, where it would overrun the interval and skip ticks.
The in-process path stays synchronous (no I/O to block on, and tests
read `sent` right after an evaluation).
"""
from __future__ import annotations

import collections
import json
import queue
import threading
import time
from typing import Dict, List, Optional

from filodb_tpu.utils.faults import faults

_QUEUE_MAX = 64


class WebhookNotifier:

    def __init__(self, url: str = "", retries: int = 3,
                 backoff_s: float = 0.5, timeout_s: float = 5.0,
                 sleep=time.sleep):
        self.url = url
        self.retries = max(int(retries), 0)
        self.backoff_s = backoff_s
        self.timeout_s = timeout_s
        self._sleep = sleep
        self._lock = threading.Lock()
        self._queue: Optional[queue.Queue] = None
        self._worker: Optional[threading.Thread] = None
        # delivered payloads (bounded): the in-memory sink when no URL is
        # configured, and a flight recorder either way
        self.sent: collections.deque = collections.deque(maxlen=256)

    def notify(self, alerts: List[Dict]) -> bool:
        """Accept one batch of alert state changes for delivery.  URL
        mode: enqueue for the dispatch thread and return True (a full
        queue drops the batch, counted — the ruler re-notifies
        still-firing alerts whose batch never advanced their clock, or
        on the resend cadence).  In-process mode: deliver synchronously;
        a batch that exhausts its retries is DROPPED and returns False
        (counted — alert evaluation must never wedge behind a dead
        webhook; the ruler retries it next interval)."""
        if not alerts:
            return True
        payload = {"version": "4", "status": "firing", "alerts": alerts}
        if not self.url:
            return self._deliver(payload)
        with self._lock:
            if self._worker is None or not self._worker.is_alive():
                self._queue = queue.Queue(maxsize=_QUEUE_MAX)
                self._worker = threading.Thread(
                    target=self._drain, args=(self._queue,),
                    name="ruler-notify", daemon=True)
                self._worker.start()
            q = self._queue
        try:
            q.put_nowait(payload)
        except queue.Full:
            from filodb_tpu.utils.metrics import registry
            registry.counter("rule_notifications_dropped").increment()
            return False
        return True

    def _drain(self, q: "queue.Queue") -> None:
        while True:
            self._deliver(q.get())

    def _deliver(self, payload: Dict) -> bool:
        """Retry with exponential backoff; exhausted batches are dropped
        and counted."""
        from filodb_tpu.utils.metrics import registry
        last_err: Optional[Exception] = None
        for attempt in range(self.retries + 1):
            if attempt:
                registry.counter("rule_notification_retries").increment()
                self._sleep(self.backoff_s * (2 ** (attempt - 1)))
            try:
                faults.fire("ruler.notify")
                if self.url:
                    self._post(payload)
                with self._lock:
                    self.sent.append(payload)
                registry.counter("rule_notifications_sent").increment()
                return True
            except Exception as e:  # noqa: BLE001 — webhook/injected faults
                last_err = e
        registry.counter("rule_notifications_dropped").increment()
        from filodb_tpu.utils.metrics import log_error_once
        if last_err is not None:
            log_error_once("ruler.notify", last_err)
        return False

    def _post(self, payload: Dict) -> None:
        import urllib.request
        req = urllib.request.Request(
            self.url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        # urlopen raises HTTPError for any >= 400 status — the retry
        # loop's except catches it like a transport failure
        urllib.request.urlopen(req, timeout=self.timeout_s).close()

    def snapshot(self) -> List[Dict]:
        with self._lock:
            return list(self.sent)
