"""Group-committed WAL writer: append, wait for the fsync, get the ack.

The commit protocol (ref: the group-commit design every durable log
converges on — Kafka's log flush, Postgres WAL, Gorilla §4.2):

  * `append(body)` assigns the next sequence number, frames the record
    (snappy + CRC32, wal/segment.py) and buffers it into the ACTIVE
    segment file under the append lock — cheap, no I/O wait.
  * a single committer thread flushes + fsyncs whenever uncommitted
    appends exist; every writer blocked in `wait_committed` for a seq at
    or below the committed watermark is released together — one fsync
    acknowledges the whole group.  Writers that arrive while an fsync is
    in flight batch into the next one automatically, so concurrency
    amortizes fsyncs without any added latency knob.
  * `commit_interval_ms > 0` additionally SPACES fsyncs: the committer
    sleeps the remainder of the interval after each commit unless
    `commit_bytes` of uncommitted appends force an early one — fewer,
    bigger commits, at the cost of up to one interval of ack latency.

Segments rotate once the active file passes `segment_max_bytes`
(checked at commit, so one commit group never spans a rotation
boundary's fsync ordering).  `prune(horizon_seq)` unlinks every sealed
segment whose LAST record is at or below the horizon — the flush
scheduler reports the persisted horizon (min over shards of their
checkpoint offsets) and tombstoned segments disappear.

A group-commit FAILURE (disk full, injected wal.fsync fault) fails every
writer waiting on that group: their data's durability cannot be claimed,
so their acks must not happen.
"""
from __future__ import annotations

import logging
import os
import threading
from typing import List, Optional, Tuple

from filodb_tpu.utils.faults import faults
from filodb_tpu.utils.metrics import registry as metrics_registry
from filodb_tpu.utils.metrics import span as metrics_span
from filodb_tpu.wal.segment import (frame_record, list_segments,
                                    read_records, segment_path,
                                    write_segment_header, WalRecord)

_log = logging.getLogger("filodb.wal")


class WalWriteError(IOError):
    """Group commit failed — the append was NOT made durable."""


class WalWriter:

    def __init__(self, dir_path: str, dataset: str = "",
                 commit_interval_ms: float = 0.0,
                 commit_bytes: int = 1 << 20,
                 segment_max_bytes: int = 64 << 20,
                 fsync: bool = True, start_seq: int = 0):
        self.dir = dir_path
        self.dataset = dataset
        self.commit_interval_s = max(commit_interval_ms, 0.0) / 1000.0
        self.commit_bytes = commit_bytes
        self.segment_max_bytes = segment_max_bytes
        self.fsync = fsync
        os.makedirs(dir_path, exist_ok=True)
        # seq of the NEXT append; callers recovering an existing log pass
        # start_seq = last replayed seq + 1
        self._next_seq = start_seq
        self._written_seq = start_seq - 1     # newest buffered append
        self._committed_seq = start_seq - 1   # newest DURABLE append
        # highest seq whose group commit FAILED: acks at or below it are
        # permanently withheld (monotone — even if a later commit lands
        # the same bytes, the writer that observed no ack must re-send;
        # replay dedup makes the re-send harmless)
        self._failed_through = start_seq - 1
        self._pending_bytes = 0
        # RLock: the committer notifies the condition (same lock) while
        # still inside its locked commit section
        self._lock = threading.RLock()
        self._commit_cv = threading.Condition(self._lock)
        self._work = threading.Event()
        self._stop = threading.Event()
        # sealed segments: (first_seq, last_seq, path); the active segment
        # is rotated into this list at commit time
        self._sealed: List[Tuple[int, int, str]] = []
        self._active_first = self._next_seq
        self._active_last = self._next_seq - 1
        # key-table hashes already written INLINE into the active
        # segment (cleared at rotation: every segment self-contained)
        self._seg_tables: set = set()
        self._file = self._open_segment(self._active_first)
        # unified job registry: the committer is the durability heart —
        # a failing group commit means acks are being withheld, so it is
        # critical for the readiness verdict (utils/health.py)
        from filodb_tpu.utils.jobs import jobs
        self.job = jobs.register("wal_commit", dataset=dataset,
                                 critical=True)
        self._committer = threading.Thread(
            target=self._run_committer, daemon=True,
            name=f"wal-commit-{dataset or os.path.basename(dir_path)}")
        self._committer.start()

    # ------------------------------------------------------------- append

    def append_record(self, rec: WalRecord) -> int:
        """Assign rec.seq, buffer the framed record, return the seq
        WITHOUT waiting for durability (callers batch several appends,
        then `wait_committed` once for the last seq)."""
        faults.fire("wal.append")
        # write-path trace: one span per buffered append (encode + frame
        # + buffer write; the fsync is the committer's and shows up as
        # the caller's wal_commit_wait span instead)
        with metrics_span("wal_append", dataset=self.dataset):
            return self._append_record(rec)

    def _append_record(self, rec: WalRecord) -> int:
        from filodb_tpu.wal.segment import (TABLE_INLINE, TABLE_REF,
                                            key_table_entry)
        # blob+hash come from the identity memo OUTSIDE the lock (the
        # only per-series work on this path)
        blob, h = key_table_entry(rec.part_keys)
        with self._lock:
            if self._stop.is_set():
                raise WalWriteError("WAL writer is closed")
            rec.seq = self._next_seq
            self._next_seq += 1
            # within-segment key-table interning: the steady scrape
            # stream writes its series table once per segment, then
            # 9-byte references — not a multi-MB copy per append
            mode = TABLE_REF if h in self._seg_tables else TABLE_INLINE
            body = rec.encode(table=(mode, blob, h))
            frame = frame_record(body)
            self._file.write(frame)
            if mode == TABLE_INLINE:
                self._seg_tables.add(h)
            self._written_seq = rec.seq
            self._active_last = rec.seq
            self._pending_bytes += len(frame)
        self._work.set()
        metrics_registry.counter("wal_appends",
                                 dataset=self.dataset).increment()
        metrics_registry.counter("wal_append_bytes",
                                 dataset=self.dataset).increment(len(frame))
        return rec.seq

    def append(self, rec: WalRecord) -> int:
        """append_record + wait for its group commit (the common path)."""
        seq = self.append_record(rec)
        self.wait_committed(seq)
        return seq

    def wait_committed(self, seq: int, timeout_s: float = 30.0) -> None:
        """Block until `seq` is durable; WalWriteError if its group's
        commit failed or the wait times out (a wedged disk must surface
        as a failed ack, not an ingest hang)."""
        # the group-commit fsync wait: THE write-path latency suspect,
        # so it gets its own span (stitches under the batch's trace) on
        # top of the committer's wal_fsync_seconds histogram
        with metrics_span("wal_commit_wait", dataset=self.dataset):
            self._wait_committed(seq, timeout_s)

    def _wait_committed(self, seq: int, timeout_s: float = 30.0) -> None:
        with self._commit_cv:
            ok = self._commit_cv.wait_for(
                lambda: self._committed_seq >= seq
                or self._failed_through >= seq
                or self._stop.is_set(),
                timeout=timeout_s)
            # failure wins over a later successful re-commit of the same
            # bytes: once a group's fsync failed, its acks are withheld
            # deterministically (the client re-sends; dedup absorbs it)
            if self._failed_through >= seq:
                raise WalWriteError(
                    f"WAL group commit failed at or before seq {seq} — "
                    "append not durable, ack withheld")
            if self._committed_seq >= seq:
                return
            if not ok:
                raise WalWriteError(
                    f"WAL commit wait timed out after {timeout_s}s "
                    f"(seq {seq}, committed {self._committed_seq})")
            raise WalWriteError(
                f"WAL writer closed before seq {seq} committed")

    @property
    def committed_seq(self) -> int:
        return self._committed_seq

    @property
    def next_seq(self) -> int:
        return self._next_seq

    # -------------------------------------------------------------- commit

    def _open_segment(self, first_seq: int):
        path = segment_path(self.dir, first_seq)
        f = open(path, "ab", buffering=1 << 20)
        if f.tell() == 0:
            write_segment_header(f)
            # header lands immediately: replay may scan the directory
            # while this (still-empty) segment is active, and a
            # buffered-only header would read as a corrupt file
            f.flush()
        return f

    def _run_committer(self) -> None:
        while True:
            self._work.wait(timeout=0.25)
            self._work.clear()
            if self._stop.is_set():
                with self._lock:
                    dirty = self._written_seq > self._committed_seq
                if dirty:
                    self._commit_once()      # drain on close
                return
            with self._lock:
                dirty = self._written_seq > self._committed_seq
            if not dirty:
                continue
            self._commit_once()
            if self.commit_interval_s > 0:
                # pacing: space fsyncs unless enough bytes pile up
                waited = 0.0
                step = min(self.commit_interval_s, 0.005)
                while waited < self.commit_interval_s \
                        and not self._stop.is_set():
                    with self._lock:
                        if self._pending_bytes >= self.commit_bytes:
                            break
                    self._stop.wait(step)
                    waited += step

    def _commit_once(self) -> None:
        """One group commit.  The flush+fsync runs OUTSIDE the append
        lock: concurrent appenders keep buffering into the (internally
        thread-safe) BufferedWriter while the fsync is in flight and
        batch into the next commit — holding the lock here would
        serialize every append behind the disk.  The batch watermark is
        snapshotted first, so the fsync provably covers it; later
        appends riding the same fsync are simply committed early by the
        next round."""
        import time as _time
        with self._lock:
            batch_end = self._written_seq
            if batch_end <= self._committed_seq:
                return
            f = self._file
        try:
            # the fault point sits INSIDE the timed window: an injected
            # wal.fsync delay must show in the fsync-latency histogram
            # exactly like a real disk stall would
            t0 = _time.perf_counter()
            faults.fire("wal.fsync")
            f.flush()
            if self.fsync:
                os.fsync(f.fileno())
            fsync_s = _time.perf_counter() - t0
        except Exception as e:  # noqa: BLE001 — disk/injected failure
            with self._lock:
                # every writer in the group must see the failure: their
                # appends may or may not be on disk, so no ack
                self._failed_through = max(self._failed_through, batch_end)
                with self._commit_cv:
                    self._commit_cv.notify_all()
            metrics_registry.counter(
                "wal_commit_errors", dataset=self.dataset).increment()
            self.job.note_error(e)
            from filodb_tpu.utils.events import journal
            journal.emit("wal_commit_failed", subsystem="wal",
                         dataset=self.dataset,
                         first_seq=self._committed_seq + 1,
                         last_seq=batch_end, error=f"{e}")
            _log.error("WAL group commit failed (seqs %d..%d): %s",
                       self._committed_seq + 1, batch_end, e)
            return
        with self._lock:
            self._committed_seq = max(self._committed_seq, batch_end)
            self._pending_bytes = 0
            # rotate only when the active segment is FULLY committed —
            # an append that raced the fsync stays in the current
            # segment and the next commit covers (and may rotate) it
            if (self._file is f
                    and self._committed_seq >= self._active_last
                    and self._active_last >= self._active_first
                    and f.tell() >= self.segment_max_bytes):
                f.close()
                self._sealed.append((
                    self._active_first, self._active_last,
                    segment_path(self.dir, self._active_first)))
                self._active_first = self._committed_seq + 1
                self._active_last = self._committed_seq
                self._seg_tables = set()
                self._file = self._open_segment(self._active_first)
                metrics_registry.counter(
                    "wal_segment_rotations", dataset=self.dataset
                ).increment()
                from filodb_tpu.utils.events import journal
                journal.emit("wal_segment_rotated", subsystem="wal",
                             dataset=self.dataset,
                             sealed_first_seq=self._sealed[-1][0],
                             sealed_last_seq=self._sealed[-1][1],
                             sealed_segments=len(self._sealed))
            with self._commit_cv:
                self._commit_cv.notify_all()
        metrics_registry.counter("wal_commits",
                                 dataset=self.dataset).increment()
        metrics_registry.histogram("wal_fsync_seconds",
                                   dataset=self.dataset).record(fsync_s)
        self.job.note_ok(duration_s=fsync_s)

    # --------------------------------------------------------------- prune

    def prune(self, horizon_seq: int) -> int:
        """Unlink sealed segments whose last record <= horizon_seq (the
        flush-reported persisted horizon).  Returns segments removed."""
        removed = 0
        with self._lock:
            keep = []
            for first, last, path in self._sealed:
                if last <= horizon_seq:
                    try:
                        os.unlink(path)
                        removed += 1
                    except OSError as e:
                        _log.warning("WAL prune failed for %s: %s", path, e)
                        keep.append((first, last, path))
                else:
                    keep.append((first, last, path))
            self._sealed = keep
        if removed:
            metrics_registry.counter("wal_segments_pruned",
                                     dataset=self.dataset).increment(removed)
            from filodb_tpu.utils.events import journal
            journal.emit("wal_segments_pruned", subsystem="wal",
                         dataset=self.dataset, removed=removed,
                         horizon_seq=horizon_seq)
        return removed

    def segment_count(self) -> int:
        with self._lock:
            return len(self._sealed) + 1

    def snapshot_segments(self) -> Tuple[List[Tuple[int, int, str, int]], int]:
        """Read snapshot for replication catch-up streaming
        (replication/service.py `fetch_wal`): ([(first_seq, last_seq,
        path, safe_bytes)], committed_seq).  The active segment's
        buffered frames are flushed to the OS first — appends hold the
        same lock, so every frame within `safe_bytes` is whole (a
        reader must still stop at `safe_bytes`: bytes past it may be a
        frame mid-write).  Records past `committed_seq` may ride along;
        they were never acknowledged, and the replica's replay is
        idempotent either way."""
        with self._lock:
            try:
                self._file.flush()
            except (OSError, ValueError):
                pass
            out = []
            for first, last, path in self._sealed:
                try:
                    out.append((first, last, path, os.path.getsize(path)))
                except OSError:
                    continue             # pruned underneath us
            if self._active_last >= self._active_first:
                out.append((self._active_first, self._active_last,
                            segment_path(self.dir, self._active_first),
                            self._file.tell()))
            return out, self._committed_seq

    # --------------------------------------------------------------- close

    def close(self) -> None:
        if self._stop.is_set():
            return
        self._stop.set()
        self._work.set()
        self._committer.join(timeout=10)
        with self._lock:
            try:
                self._file.flush()
                if self.fsync:
                    os.fsync(self._file.fileno())
            except Exception:  # noqa: BLE001 — closing best-effort drain
                pass
            self._file.close()
        with self._commit_cv:
            self._commit_cv.notify_all()


def recover_writer_state(dir_path: str):
    """Scan an existing WAL directory -> (next_seq, sealed_segments) so a
    restarted writer continues the sequence instead of reusing seqs (a
    reused seq would defeat replay idempotence ordering).  Decodes only
    the record headers' seq field implicitly via full decode — restart is
    off the hot path.  Existing segments are treated as sealed (the new
    writer opens a fresh segment past them) so prune can reclaim them."""
    next_seq = 0
    sealed: List[Tuple[int, int, str]] = []
    for first, path in list_segments(dir_path):
        last = first - 1
        tables: dict = {}
        try:
            for body in read_records(path):
                last = max(last, WalRecord.decode(body, tables).seq)
        except Exception:  # noqa: BLE001 — replay handles/reports corruption
            pass
        if last < first:
            # header-only or torn-first-record segment: nothing in it was
            # ever acknowledged (acks wait for a complete fsynced frame),
            # and keeping it would collide with the restarted writer's
            # fresh active segment at the same first_seq
            try:
                os.unlink(path)
            except OSError:
                pass
            continue
        sealed.append((first, last, path))
        next_seq = max(next_seq, last + 1)
    return next_seq, sealed
