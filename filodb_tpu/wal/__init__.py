"""Write-ahead log — durability for acknowledged ingest.

The memstore is an in-memory serving tier: before this package, an
acknowledged sample lived only in RAM until the flush scheduler sealed
and persisted its chunk — a crash between scrape and flush silently lost
it.  The WAL closes that window with the Gorilla checkpoint+log stance
(Facebook VLDB'15 §4.2; the reference's Kafka-offset recovery protocol,
doc/ingestion.md:114-133):

    append (framed, CRC32, snappy)  ->  group commit (fsync)  ->  ACK
                                                   |
    restart:  replay segments  ->  same ingest_columns path  ->  serving

`WalManager` is the per-dataset facade the ingest doors use: it owns the
writer (wal/writer.py), tracks per-shard persisted horizons reported by
the flush scheduler, and prunes tombstoned segments.  Replay
(wal/replay.py) runs at boot before the HTTP server opens.
"""
from __future__ import annotations

import logging
import os
import threading
from typing import Dict, Optional

import numpy as np

from filodb_tpu.utils.metrics import registry as metrics_registry
from filodb_tpu.wal.replay import ReplayStats, replay_dir
from filodb_tpu.wal.segment import WalCorruption, WalRecord
from filodb_tpu.wal.writer import WalWriteError, WalWriter, \
    recover_writer_state

_log = logging.getLogger("filodb.wal")

__all__ = ["WalManager", "WalRecord", "WalWriter", "WalWriteError",
           "WalCorruption", "ReplayStats", "replay_dir"]


class WalManager:
    """One dataset's WAL: append facade + horizon-driven pruning."""

    def __init__(self, root_dir: str, dataset: str, config=None):
        from filodb_tpu.config import WalConfig
        cfg = config or WalConfig()
        self.dataset = dataset
        self.dir = os.path.join(root_dir, dataset)
        next_seq, sealed = recover_writer_state(self.dir)
        self.writer = WalWriter(
            self.dir, dataset=dataset,
            commit_interval_ms=cfg.commit_interval_ms,
            commit_bytes=cfg.commit_bytes,
            segment_max_bytes=cfg.segment_max_bytes,
            fsync=cfg.fsync, start_seq=next_seq)
        # pre-restart segments are prunable once their records persist
        self.writer._sealed = sealed + self.writer._sealed
        self._lock = threading.Lock()
        self._persisted: Dict[int, int] = {}     # shard -> horizon seq
        self._shards_seen: set = set()

    # ------------------------------------------------------------- append

    def append_grid(self, shard: int, schema: str, part_keys,
                    ts: np.ndarray, columns: Dict[str, np.ndarray],
                    bucket_les=None, wait: bool = True) -> int:
        """Append one columnar slab for `shard`; returns its seq.  With
        wait=True (default) the call blocks until the group commit makes
        it durable — callers ingesting several slabs per request should
        append them all with wait=False and `commit()` once."""
        # keep the caller's list identity: streaming callers reuse one
        # key table across appends and the record encoder memoizes its
        # serialized form by that identity (wal/segment._key_table_blob)
        keys = part_keys if isinstance(part_keys, list) else list(part_keys)
        rec = WalRecord(0, shard, schema, keys,
                        np.asarray(ts, dtype=np.int64), columns, bucket_les)
        with self._lock:
            self._shards_seen.add(shard)
        if wait:
            return self.writer.append(rec)
        return self.writer.append_record(rec)

    def commit(self, seq: int) -> None:
        self.writer.wait_committed(seq)

    # ------------------------------------------------------------ horizon

    def note_persisted(self, shard: int, horizon_seq: int) -> None:
        """Flush scheduler callback: every sample of `shard` with seq <=
        horizon_seq is in the column store.  Prunes segments wholly below
        the min horizon across every shard the log has seen."""
        with self._lock:
            if horizon_seq <= self._persisted.get(shard, -1):
                prev_min = None            # no movement: skip the prune
            else:
                self._persisted[shard] = horizon_seq
                prev_min = self._min_horizon()
        if prev_min is not None and prev_min >= 0:
            self.writer.prune(prev_min)
            metrics_registry.gauge(
                "wal_persisted_horizon", dataset=self.dataset
            ).update(prev_min)
        metrics_registry.gauge("wal_segments",
                               dataset=self.dataset).update(
            self.writer.segment_count())

    def _min_horizon(self) -> int:
        """Min persisted seq over every shard that has ever appended (a
        shard the log holds records for but whose checkpoint hasn't
        advanced pins every segment past its data — correct: pruning it
        would lose acknowledged samples)."""
        if not self._shards_seen:
            return -1
        return min(self._persisted.get(s, -1) for s in self._shards_seen)

    # ------------------------------------------------------------- replay

    def replay(self, memstore,
               restart_points: Optional[Dict[int, int]] = None
               ) -> ReplayStats:
        from filodb_tpu.utils.events import journal
        journal.emit("wal_replay_started", subsystem="wal",
                     dataset=self.dataset)
        stats = replay_dir(self.dir, memstore, self.dataset, restart_points)
        journal.emit("wal_replay_done", subsystem="wal",
                     dataset=self.dataset, records=stats.records,
                     samples=stats.samples,
                     skipped_records=stats.skipped_records,
                     corrupt_segments=stats.corrupt_segments,
                     elapsed_s=round(stats.elapsed_s, 3))
        restart_points = restart_points or {}
        with self._lock:
            # only shards with RECORDS in the log gate pruning — a shard
            # that never appended (idle, influx-only) must not pin the
            # horizon at -1 forever and let sealed segments fill the disk
            for shard, last in stats.shards.items():
                self._shards_seen.add(shard)
                # the restart point is persistence EVIDENCE: everything
                # at or below it is already in the column store, so a
                # shard whose log records were all skipped starts with
                # its horizon there instead of pinning segments it no
                # longer needs
                rp = restart_points.get(shard, -1)
                if rp > self._persisted.get(shard, -1):
                    self._persisted[shard] = rp
        for shard, rp in restart_points.items():
            # checkpoints must stay monotone across the restart: a shard
            # that replayed nothing still re-asserts its restart point as
            # its offset, so the next flush cannot regress the persisted
            # checkpoint to -1 (which would stall pruning until fresh
            # traffic arrives)
            sh = memstore.get_shard(self.dataset, shard)
            if sh is not None and rp > sh.ingested_offset:
                sh.ingested_offset = rp
        mh = self._min_horizon()
        if mh >= 0:
            self.writer.prune(mh)
        return stats

    def close(self) -> None:
        self.writer.close()
