"""WAL replay: re-drive the columnar ingest path from the log on restart.

The recovery contract (ref: the reference's recover_stream over broker
offsets, doc/ingestion.md:114-133; Gorilla §4.2 checkpoint+log):

  * records replay in sequence order through the SAME
    `TimeSeriesShard.ingest_columns` path live ingest uses — replay is
    not a second ingest implementation that can drift.
  * idempotence: records at or below a shard's persisted horizon (the
    min over its flush-group checkpoints — everything there is already
    in the column store) are skipped; records past it re-land in the
    dense tier, where re-replay and flush-overlap duplicates are
    harmless (chunk writes are idempotent, paging never duplicates
    below the dense floor, OOO dedup drops same-timestamp repeats).
  * a torn TAIL record (crash mid-append) ends replay cleanly — it was
    never acknowledged.  Mid-log corruption stops that segment LOUDLY
    (wal_replay_corruptions + log) and continues with the next segment:
    later acknowledged data must not be held hostage by one bad block.
"""
from __future__ import annotations

import dataclasses
import logging
import time
from typing import Dict, Optional

from filodb_tpu.utils.faults import faults
from filodb_tpu.utils.metrics import registry as metrics_registry
from filodb_tpu.wal.segment import (WalCorruption, WalRecord, list_segments,
                                    read_records)

_log = logging.getLogger("filodb.wal")


@dataclasses.dataclass
class ReplayStats:
    records: int = 0
    samples: int = 0
    skipped_records: int = 0          # at/below the persisted horizon
    corrupt_segments: int = 0
    last_seq: int = -1
    elapsed_s: float = 0.0
    # shard -> highest seq present in the log (replayed OR skipped):
    # the shards whose progress actually gates segment pruning
    shards: Dict[int, int] = dataclasses.field(default_factory=dict)

    @property
    def samples_per_sec(self) -> float:
        return self.samples / self.elapsed_s if self.elapsed_s > 0 else 0.0


def replay_dir(dir_path: str, memstore, dataset: str,
               restart_points: Optional[Dict[int, int]] = None,
               shard_filter: Optional[set] = None) -> ReplayStats:
    """Replay every WAL segment under `dir_path` into `memstore`'s shards
    of `dataset`.  `restart_points` maps shard -> persisted horizon seq
    (records with seq <= horizon skip); missing shards replay from the
    beginning.  `shard_filter` (replication catch-up: a replica replays
    a primary's shipped segments for only the shards it owns a copy of)
    drops foreign-shard records before any stats tracking.  Returns
    ReplayStats; the memstore's shards are created on demand (a
    restarted node re-learns its shard set from the log)."""
    stats = ReplayStats()
    restart_points = restart_points or {}
    t0 = time.perf_counter()
    shards = {}
    for first_seq, path in list_segments(dir_path):
        tables: Dict[bytes, list] = {}       # per-segment intern table
        try:
            for body in read_records(path):
                rec = WalRecord.decode(body, tables)
                faults.fire("wal.replay")
                if shard_filter is not None \
                        and rec.shard not in shard_filter:
                    continue
                stats.last_seq = max(stats.last_seq, rec.seq)
                stats.shards[rec.shard] = max(
                    stats.shards.get(rec.shard, -1), rec.seq)
                if rec.seq <= restart_points.get(rec.shard, -1):
                    stats.skipped_records += 1
                    continue
                shard = shards.get(rec.shard)
                if shard is None:
                    shard = memstore.get_shard(dataset, rec.shard) \
                        or memstore.setup(dataset, rec.shard)
                    shards[rec.shard] = shard
                shard.ingest_columns(rec.schema, rec.part_keys, rec.ts,
                                     rec.columns, offset=rec.seq,
                                     bucket_les=rec.bucket_les)
                stats.records += 1
                stats.samples += rec.num_samples
        except WalCorruption as e:
            stats.corrupt_segments += 1
            metrics_registry.counter("wal_replay_corruptions",
                                     dataset=dataset).increment()
            _log.error("WAL replay: segment %s corrupt (%s) — continuing "
                       "with the next segment; acknowledged records in "
                       "the damaged region are LOST", path, e)
    stats.elapsed_s = time.perf_counter() - t0
    metrics_registry.counter("wal_replay_records",
                             dataset=dataset).increment(stats.records)
    metrics_registry.counter("wal_replay_samples",
                             dataset=dataset).increment(stats.samples)
    if stats.records or stats.corrupt_segments:
        _log.info("WAL replay %s: %d records / %d samples in %.2fs "
                  "(%d skipped below horizon, %d corrupt segments)",
                  dataset, stats.records, stats.samples, stats.elapsed_s,
                  stats.skipped_records, stats.corrupt_segments)
    return stats
