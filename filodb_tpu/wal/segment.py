"""WAL segment files: snappy-framed, CRC32-guarded append records.

One segment is a bounded append-only file (ref: the reference's
IngestionStream recovery over Kafka offsets, doc/ingestion.md:114-133;
Gorilla's append log, Facebook VLDB'15 §4.2).  Layout:

    header:  b"FWAL" + u16 version + u16 reserved
    record:  u32 frame_len | u32 crc32(frame) | frame = snappy(body)

The CRC covers the COMPRESSED frame, so a torn tail (crash mid-write) or
bit rot is detected before snappy/decode ever parse attacker-shaped
bytes.  `read_records` stops cleanly at the first torn/short tail frame
(the normal crash artifact — everything before it was fsynced) and
reports it, so replay can distinguish "clean end" from "mid-log
corruption" (the latter means acknowledged data after it is gone and
must be surfaced loudly, never skipped silently).

The record BODY is the columnar append itself — the same rectangular
[S, k] grid `TimeSeriesShard.ingest_columns` consumes, serialized with
whole-array tobytes (never per-sample Python):

    u64 seq | u16 shard | u8 len + schema_name utf-8
    u32 S | u32 k
    u8 table_mode | u64 table_hash
      mode 0 (inline): u32 blob_len | S x (u32 len + PartKey bytes)
      mode 1 (ref):    nothing — the table was written inline by an
                       EARLIER record of the SAME segment
    u8 ts_mode
      mode 0 (full):   ts: S*k int64
      mode 1 (shared): ts: k int64 — every series carries the SAME
                       timestamp row (the scrape-cycle shape; detected
                       free on broadcast inputs, one vectorized compare
                       otherwise) and replay re-broadcasts it
    u16 ncols, per col: u8 len + name | u32 B (0 = scalar) | f64 payload
    u16 nles + bucket_les f64

Key-table interning: a steady scrape stream appends the SAME series
table every cycle, and re-writing (and re-fsyncing, and re-decoding) a
multi-MB table per record would dominate the whole durability path —
the Prometheus WAL splits series records from sample records for the
same reason.  Here a record references a previously-inlined table by
blake2b-64 content hash, scoped WITHIN one segment so every segment
stays self-contained (pruning can never orphan a reference).
"""
from __future__ import annotations

import dataclasses
import io
import os
import struct
import zlib
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from filodb_tpu.core.partkey import PartKey
from filodb_tpu.utils import snappy

MAGIC = b"FWAL"
VERSION = 1
_HEADER = MAGIC + struct.pack("<HH", VERSION, 0)
_FRAME_HDR = struct.Struct("<II")            # frame_len, crc32


class WalCorruption(ValueError):
    """Mid-log CRC/decode failure — data after this point is unrecoverable
    from this segment (a torn TAIL is not corruption; see read_records)."""


@dataclasses.dataclass
class WalRecord:
    """One group of appends for one shard, grid-shaped."""
    seq: int
    shard: int
    schema: str
    part_keys: List[PartKey]
    ts: np.ndarray                            # int64 [S, k]
    columns: Dict[str, np.ndarray]            # [S, k] f64 or [S, k, B]
    bucket_les: Optional[np.ndarray] = None

    @property
    def num_samples(self) -> int:
        return int(self.ts.size)

    def encode(self, table: Optional[tuple] = None) -> bytes:
        """`table` is (mode, blob, hash) from the writer's interning
        state; None (tests, bare callers) always inlines."""
        ts = np.asarray(self.ts)
        if ts.dtype != np.int64:
            ts = ts.astype(np.int64)
        S, k = ts.shape
        if table is None:
            blob, h = key_table_entry(self.part_keys)
            mode = TABLE_INLINE
        else:
            mode, blob, h = table
        buf = io.BytesIO()
        name = self.schema.encode("utf-8")
        buf.write(struct.pack("<QHB", self.seq, self.shard, len(name)))
        buf.write(name)
        buf.write(struct.pack("<II", S, k))
        buf.write(struct.pack("<B", mode))
        buf.write(h)
        if mode == TABLE_INLINE:
            buf.write(struct.pack("<I", len(blob)))
            buf.write(blob)
        # shared-row timestamps: a scrape cycle stamps every series with
        # one row — serializing S copies of it would double the fsync
        # payload.  Broadcast inputs (stride 0) detect free; otherwise
        # one vectorized compare decides (cheap next to the copy saved).
        shared = S > 1 and k > 0 and (
            ts.strides[0] == 0 or bool((ts[1:] == ts[0]).all()))
        buf.write(struct.pack("<B", 1 if shared else 0))
        if shared:
            buf.write(np.ascontiguousarray(ts[0]).tobytes())
        else:
            buf.write(np.ascontiguousarray(ts).tobytes())
        buf.write(struct.pack("<H", len(self.columns)))
        for cname, arr in self.columns.items():
            cb = cname.encode("utf-8")
            arr = np.ascontiguousarray(arr, dtype=np.float64)
            B = arr.shape[2] if arr.ndim == 3 else 0
            buf.write(struct.pack("<B", len(cb)))
            buf.write(cb)
            buf.write(struct.pack("<I", B))
            buf.write(arr.tobytes())
        if self.bucket_les is not None:
            les = np.ascontiguousarray(self.bucket_les, dtype=np.float64)
            buf.write(struct.pack("<H", len(les)))
            buf.write(les.tobytes())
        else:
            buf.write(struct.pack("<H", 0))
        return buf.getvalue()

    @staticmethod
    def decode(data: bytes,
               tables: Optional[Dict[bytes, list]] = None) -> "WalRecord":
        """`tables` is the reader's per-segment intern dict (hash ->
        part_keys); inline records register into it, ref records resolve
        from it.  None works for self-contained inline records."""
        try:
            return WalRecord._decode(data, tables)
        except (struct.error, IndexError, ValueError) as e:
            if isinstance(e, WalCorruption):
                raise
            raise WalCorruption(f"undecodable WAL record body: {e}") from e

    @staticmethod
    def _decode(data: bytes, tables: Optional[Dict[bytes, list]]
                ) -> "WalRecord":
        off = 0
        seq, shard, nlen = struct.unpack_from("<QHB", data, off)
        off += 11
        schema = data[off:off + nlen].decode("utf-8")
        off += nlen
        S, k = struct.unpack_from("<II", data, off)
        off += 8
        (mode,) = struct.unpack_from("<B", data, off)
        off += 1
        h = data[off:off + 8]
        off += 8
        if mode == TABLE_INLINE:
            (blob_len,) = struct.unpack_from("<I", data, off)
            off += 4
            part_keys = _decode_key_table(data[off:off + blob_len], S)
            off += blob_len
            if tables is not None:
                tables[h] = part_keys
        elif mode == TABLE_REF:
            part_keys = (tables or {}).get(h)
            if part_keys is None or len(part_keys) != S:
                raise WalCorruption(
                    f"key-table ref {h.hex()} not interned earlier in "
                    "this segment")
        else:
            raise WalCorruption(f"unknown key-table mode {mode}")
        n = S * k
        (ts_mode,) = struct.unpack_from("<B", data, off)
        off += 1
        if ts_mode == 1:
            row = np.frombuffer(data, dtype=np.int64, count=k,
                                offset=off).copy()
            # read-only broadcast view: replay's ingest_columns only
            # reads the grid, so S copies never materialize
            ts = np.broadcast_to(row, (S, k))
            off += 8 * k
        elif ts_mode == 0:
            ts = np.frombuffer(data, dtype=np.int64, count=n,
                               offset=off).reshape(S, k).copy()
            off += 8 * n
        else:
            raise WalCorruption(f"unknown ts mode {ts_mode}")
        (ncols,) = struct.unpack_from("<H", data, off)
        off += 2
        columns: Dict[str, np.ndarray] = {}
        for _ in range(ncols):
            (clen,) = struct.unpack_from("<B", data, off)
            off += 1
            cname = data[off:off + clen].decode("utf-8")
            off += clen
            (B,) = struct.unpack_from("<I", data, off)
            off += 4
            cnt = n * (B or 1)
            arr = np.frombuffer(data, dtype=np.float64, count=cnt,
                                offset=off)
            columns[cname] = (arr.reshape(S, k, B) if B
                              else arr.reshape(S, k)).copy()
            off += 8 * cnt
        (nles,) = struct.unpack_from("<H", data, off)
        off += 2
        les = None
        if nles:
            les = np.frombuffer(data, dtype=np.float64, count=nles,
                                offset=off).copy()
        return WalRecord(seq, shard, schema, part_keys, ts, columns, les)


TABLE_INLINE, TABLE_REF = 0, 1

# key-table encode memo: streaming sources reuse ONE part_keys list
# across appends (the shard's _resolve_key_table pattern), so the
# per-key length-prefix loop and the content hash — the only per-series
# Python on the WAL append path — run once per table, not once per
# scrape cycle.  Keyed by list identity, validated by the pinned
# reference.
_KEY_BLOB_MEMO: Dict[int, tuple] = {}
_KEY_BLOB_MEMO_MAX = 8


def key_table_entry(part_keys) -> Tuple[bytes, bytes]:
    """-> (serialized table blob, blake2b-64 content hash)."""
    import hashlib
    ent = _KEY_BLOB_MEMO.get(id(part_keys))
    if ent is not None and ent[0] is part_keys \
            and len(part_keys) == ent[3]:
        return ent[1], ent[2]
    buf = bytearray()
    for pk in part_keys:
        kb = pk.to_bytes()
        buf += struct.pack("<I", len(kb))
        buf += kb
    blob = bytes(buf)
    h = hashlib.blake2b(blob, digest_size=8).digest()
    if isinstance(part_keys, list):
        _KEY_BLOB_MEMO[id(part_keys)] = (part_keys, blob, h,
                                         len(part_keys))
        while len(_KEY_BLOB_MEMO) > _KEY_BLOB_MEMO_MAX:
            _KEY_BLOB_MEMO.pop(next(iter(_KEY_BLOB_MEMO)))
    return blob, h


# key-table decode memo: replay re-reads the same inlined table once
# per segment; decoding S PartKeys per occurrence (65k+ Python object
# builds) would dominate replay, so decoded lists are shared by blob
# content.  Returning the SAME list object also lets the shard's
# _resolve_key_table identity cache hit across replayed records.
_KEY_DECODE_MEMO: Dict[bytes, list] = {}
_KEY_DECODE_MEMO_MAX = 8


def _decode_key_table(raw: bytes, S: int) -> list:
    got = _KEY_DECODE_MEMO.get(raw)
    if got is not None and len(got) == S:
        return got
    part_keys = []
    off = 0
    for _ in range(S):
        (ln,) = struct.unpack_from("<I", raw, off)
        off += 4
        part_keys.append(PartKey.from_bytes(raw[off:off + ln]))
        off += ln
    if off != len(raw):
        raise WalCorruption("key-table blob length mismatch")
    _KEY_DECODE_MEMO[raw] = part_keys
    while len(_KEY_DECODE_MEMO) > _KEY_DECODE_MEMO_MAX:
        _KEY_DECODE_MEMO.pop(next(iter(_KEY_DECODE_MEMO)))
    return part_keys


# --------------------------------------------------------------- framing

def frame_record(body: bytes) -> bytes:
    """body -> [len][crc][snappy(body)] — the on-disk unit."""
    frame = snappy.compress(body)
    return _FRAME_HDR.pack(len(frame), zlib.crc32(frame)) + frame


def segment_path(dir_path: str, first_seq: int) -> str:
    return os.path.join(dir_path, f"wal-{first_seq:016d}.seg")


def list_segments(dir_path: str) -> List[Tuple[int, str]]:
    """(first_seq, path) ascending for every segment in the directory."""
    out = []
    if not os.path.isdir(dir_path):
        return out
    for name in os.listdir(dir_path):
        if name.startswith("wal-") and name.endswith(".seg"):
            try:
                out.append((int(name[4:-4]), os.path.join(dir_path, name)))
            except ValueError:
                continue
    out.sort()
    return out


def write_segment_header(f) -> None:
    f.write(_HEADER)


def read_records(path: str) -> Iterator[bytes]:
    """Yield decompressed record BODIES in append order.

    A short/CRC-failed TAIL frame ends iteration cleanly (crash-torn
    final write: nothing after it was ever acknowledged).  A CRC failure
    with MORE data after it raises WalCorruption — acknowledged records
    are unreachable and the operator must know."""
    with open(path, "rb") as f:
        header = f.read(len(_HEADER))
        if len(header) < len(_HEADER) or header[:4] != MAGIC:
            raise WalCorruption(f"{path}: bad segment header")
        version = struct.unpack_from("<H", header, 4)[0]
        if version != VERSION:
            raise WalCorruption(f"{path}: unsupported WAL version {version}")
        data = f.read()
    pos, n = 0, len(data)
    while pos < n:
        if pos + _FRAME_HDR.size > n:
            return                                    # torn tail header
        frame_len, crc = _FRAME_HDR.unpack_from(data, pos)
        start = pos + _FRAME_HDR.size
        end = start + frame_len
        if end > n:
            return                                    # torn tail frame
        frame = data[start:end]
        if zlib.crc32(frame) != crc:
            if end < n:
                raise WalCorruption(
                    f"{path}: CRC mismatch at offset {pos} with "
                    f"{n - end} bytes following — mid-log corruption")
            return                                    # torn tail bytes
        try:
            yield snappy.decompress(frame)
        except ValueError as e:
            raise WalCorruption(
                f"{path}: CRC-valid frame failed snappy decode at "
                f"offset {pos}: {e}") from e
        pos = end
