"""Device select / binary-op kernels for whole-expression fusion (PR 17).

Two small jitted programs that let non-leaf PromQL nodes stay on the
device instead of round-tripping through host Python:

  * ``gather_binop`` — a vector-matching binary operator as ONE compiled
    program: gather the matched rows from both sides and apply the
    arithmetic/comparison op.  The host resolves label matching once
    into ``(mi, oi)`` index maps (see query/exprfuse.py, which caches
    them on the block's ``cache_token``); the device never sees labels.
  * ``topk_keep_rows`` — the node-local partial-select behind exact
    ``topk``/``bottomk`` pushdown: a row may be pruned from a candidate
    partial iff it makes NO per-window node-local top-k, because the
    global top-k over a union is contained in the union of local
    top-ks (same containment argument as the streaming fold, see
    query/nonleaf.py ``_AggStreamFold``).

Pure-XLA path — runs on any backend; no Pallas, no host callbacks.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .agg import topk_mask
from .instant import apply_binary_op


@functools.partial(jax.jit,
                   static_argnames=("op", "bool_modifier", "keep_side"))
def gather_binop(lhs_vals: jax.Array, rhs_vals: jax.Array,
                 mi: jax.Array, oi: jax.Array, *, op: str,
                 bool_modifier: bool = False,
                 keep_side: str = "lhs") -> jax.Array:
    """``lhs_vals[mi] <op> rhs_vals[oi]`` fused into one program.

    ``lhs_vals``/``rhs_vals`` are the two sides' value blocks
    ``[N_l, W]`` / ``[N_r, W]``; ``mi``/``oi`` are the host-resolved
    match index maps ``[P]`` (one entry per output pair).  Returns the
    ``[P, W]`` result with PromQL absent/NaN semantics from
    ``apply_binary_op``.
    """
    a = jnp.take(lhs_vals, mi, axis=0)
    b = jnp.take(rhs_vals, oi, axis=0)
    return apply_binary_op(a, b, op=op, bool_modifier=bool_modifier,
                           keep_side=keep_side)


@functools.partial(jax.jit, static_argnames=("num_groups", "k", "largest"))
def topk_keep_rows(vals: jax.Array, group_ids: jax.Array,
                   num_groups: int, k: int,
                   largest: bool = True) -> jax.Array:
    """Rows worth shipping for an exact distributed top/bottom-k.

    ``vals`` is a candidate partial's ``[N, W]`` value block.  Returns a
    ``[N]`` bool mask: True iff the row lands in its group's per-window
    top-k for AT LEAST ONE window.  Rows outside every window's local
    top-k cannot appear in any global top-k and are safe to drop before
    the partial crosses the wire.
    """
    return jnp.any(topk_mask(vals, group_ids, num_groups, k,
                             largest=largest), axis=1)
