"""Instant (scalar) functions and binary operators, applied elementwise to
periodic sample matrices [S, W].

ref: query/.../exec/rangefn/InstantFunction.scala:72 (abs..sqrt + date parts),
query/.../exec/RangeVectorTransformer.scala:61 InstantVectorFunctionMapper,
ScalarOperationMapper:186, and BinaryOperator evaluation in BinaryJoinExec.
NaN propagates naturally (absent stays absent).
"""
from __future__ import annotations

from typing import Callable, Dict

import jax
import jax.numpy as jnp

_SECONDS_PER_DAY = 86400.0


def _days_in_month(y, m):
    thirty_one = ((m == 1) | (m == 3) | (m == 5) | (m == 7) | (m == 8)
                  | (m == 10) | (m == 12))
    thirty = (m == 4) | (m == 6) | (m == 9) | (m == 11)
    leap = ((y % 4 == 0) & (y % 100 != 0)) | (y % 400 == 0)
    feb = jnp.where(leap, 29.0, 28.0)
    return jnp.where(thirty_one, 31.0, jnp.where(thirty, 30.0, feb))


def _civil_from_epoch_days(days):
    """Gregorian (y, m, d) from days since 1970-01-01 (Howard Hinnant's
    civil_from_days algorithm, branchless)."""
    z = days + 719468.0
    era = jnp.floor(z / 146097.0)
    doe = z - era * 146097.0
    yoe = jnp.floor((doe - jnp.floor(doe / 1460.0) + jnp.floor(doe / 36524.0)
                     - jnp.floor(doe / 146096.0)) / 365.0)
    y = yoe + era * 400.0
    doy = doe - (365.0 * yoe + jnp.floor(yoe / 4.0) - jnp.floor(yoe / 100.0))
    mp = jnp.floor((5.0 * doy + 2.0) / 153.0)
    d = doy - jnp.floor((153.0 * mp + 2.0) / 5.0) + 1.0
    m = mp + jnp.where(mp < 10.0, 3.0, -9.0)
    y = y + (m <= 2.0)
    return y, m, d


def _epoch_parts(v):
    days = jnp.floor(v / _SECONDS_PER_DAY)
    return _civil_from_epoch_days(days)


INSTANT_FUNCTIONS: Dict[str, Callable] = {
    "abs": jnp.abs,
    "ceil": jnp.ceil,
    "floor": jnp.floor,
    "exp": jnp.exp,
    "ln": jnp.log,
    "log2": jnp.log2,
    "log10": jnp.log10,
    "sqrt": jnp.sqrt,
    "round": lambda v, to_nearest=1.0: jnp.floor(v / to_nearest + 0.5) * to_nearest,
    "clamp_min": lambda v, lo: jnp.maximum(v, lo),
    "clamp_max": lambda v, hi: jnp.minimum(v, hi),
    "clamp": lambda v, lo, hi: jnp.clip(v, lo, hi),
    "sgn": jnp.sign,
    "deg": jnp.degrees,
    "rad": jnp.radians,
    "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan,
    "asin": jnp.arcsin, "acos": jnp.arccos, "atan": jnp.arctan,
    "sinh": jnp.sinh, "cosh": jnp.cosh, "tanh": jnp.tanh,
    "asinh": jnp.arcsinh, "acosh": jnp.arccosh, "atanh": jnp.arctanh,
    # date parts operate on the sample VALUE as epoch seconds (PromQL semantics)
    "minute": lambda v: jnp.floor(v / 60.0) % 60.0,
    "hour": lambda v: jnp.floor(v / 3600.0) % 24.0,
    "day_of_week": lambda v: (jnp.floor(v / _SECONDS_PER_DAY) + 4.0) % 7.0,
    "day_of_month": lambda v: _epoch_parts(v)[2],
    "month": lambda v: _epoch_parts(v)[1],
    "year": lambda v: _epoch_parts(v)[0],
    "days_in_month": lambda v: _days_in_month(_epoch_parts(v)[0], _epoch_parts(v)[1]),
    "day_of_year": lambda v: _day_of_year(v),
}


def _day_of_year(v):
    """1..365/366 (PromQL day_of_year): days since Jan 1 of the value's
    year, via the same civil-date math as the other date parts."""
    y, _, _ = _epoch_parts(v)
    # epoch day number of Jan 1 of year y (inverse of _civil_from_epoch_days
    # for month=1 day=1): shift to the March-based era used there
    ys = y - 1.0                           # era math with March-year m=11
    era = jnp.floor(ys / 400.0)
    yoe = ys - era * 400.0
    # day-of-era for March 1 of civil year y-1 is doe(yoe, doy=306) —
    # civil Jan 1 of year y is 306 days after March 1 of year y-1
    doy_m = 306.0                          # Jan 1 in the March calendar
    doe = yoe * 365.0 + jnp.floor(yoe / 4.0) - jnp.floor(yoe / 100.0) \
        + doy_m
    jan1_days = era * 146097.0 + doe - 719468.0
    days = jnp.floor(v / _SECONDS_PER_DAY)
    return days - jan1_days + 1.0


def apply_instant_function(name: str, vals: jax.Array, *params) -> jax.Array:
    fn = INSTANT_FUNCTIONS[name]
    return fn(vals, *params)


# ---------------------------------------------------------- binary operators

def _safe_div(a, b):
    return a / b  # IEEE: x/0 = inf, 0/0 = nan — PromQL follows IEEE here


def _pow(a, b):
    return jnp.power(a, b)


def _mod(a, b):
    # PromQL mod follows Go math.Mod: result has sign of dividend
    return jnp.fmod(a, b)


ARITH_OPERATORS: Dict[str, Callable] = {
    "+": jnp.add,
    "-": jnp.subtract,
    "*": jnp.multiply,
    "/": _safe_div,
    "%": _mod,
    "^": _pow,
    "atan2": jnp.arctan2,
}

COMPARISON_OPERATORS: Dict[str, Callable] = {
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
    ">": lambda a, b: a > b,
    "<": lambda a, b: a < b,
    ">=": lambda a, b: a >= b,
    "<=": lambda a, b: a <= b,
}


def apply_binary_op(lhs: jax.Array, rhs: jax.Array, *, op: str,
                    bool_modifier: bool = False,
                    keep_side: str = "lhs") -> jax.Array:
    """Vector op vector/scalar.  Comparison without `bool` filters: keeps the
    vector side's value (keep_side) where true, NaN where false; with `bool`
    returns 1/0.  ref: query BinaryOperator semantics +
    ScalarOperationMapper:186."""
    absent = jnp.isnan(lhs) | jnp.isnan(rhs)
    if op in ARITH_OPERATORS:
        out = ARITH_OPERATORS[op](lhs, rhs)
        return jnp.where(absent, jnp.nan, out)
    cmp = COMPARISON_OPERATORS[op](lhs, rhs)
    if bool_modifier:
        return jnp.where(absent, jnp.nan, cmp.astype(lhs.dtype))
    kept = lhs if keep_side == "lhs" else rhs
    return jnp.where(~absent & cmp, kept, jnp.nan)
