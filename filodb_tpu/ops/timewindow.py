"""Window-grid math for periodic-sample evaluation on device.

The reference iterates windows over compressed chunks host-side
(ref: query/.../exec/PeriodicSamplesMapper.scala:202-292 ChunkedWindowIterator).
On TPU the same contract — for each output step, the window (wend-range, wend]
of samples — becomes vectorized index math over dense [series, time] arrays:
per-row binary search for window boundaries, then gather/cumsum tricks for the
window reductions.  All shapes are static under jit; timestamps are int32
millisecond offsets from a host-side int64 base (fits 24 days of window span,
long ranges are split by the planner like the reference's time-splitting,
ref: SingleClusterPlanner.scala:91-117).
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

# Padding sentinel for ts offsets beyond each row's sample count.  Chosen well
# below int32 max so `pad + range_ms` cannot overflow.
PAD_TS = np.int32(1 << 30)


def make_window_ends(start_ms: int, end_ms: int, step_ms: int) -> np.ndarray:
    """Absolute output step timestamps: start, start+step, ..., <= end
    (PromQL range-query grid)."""
    return np.arange(start_ms, end_ms + 1, step_ms, dtype=np.int64)


def to_offsets(ts: np.ndarray, counts: np.ndarray, base_ms: int) -> np.ndarray:
    """Host-side: int64 absolute ms -> padded int32 offsets from base."""
    pos = np.arange(ts.shape[1])[None, :]
    off = np.clip(ts - base_ms, -(1 << 30), 1 << 30).astype(np.int32)
    return np.where(pos < counts[:, None], off, PAD_TS)


def series_value_base(vals: np.ndarray) -> np.ndarray:
    """Host-side per-series value base for f64->f32 rebasing: the first
    finite value along time.  [S, T] -> [S]; [S, T, B] -> [S, B].

    Subtracting this in f64 BEFORE the device downcast keeps counter deltas
    exact in f32 even for counters >= 2^24, where absolute f32 storage loses
    every per-sample increment (the value-space analogue of the epoch-ms
    timestamp rebasing; ref rate math: rangefn/RateFunctions.scala:37-76).
    """
    finite = np.isfinite(vals)
    first = finite.argmax(axis=1)
    if vals.ndim == 3:
        base = np.take_along_axis(vals, first[:, None, :], axis=1)[:, 0, :]
    else:
        base = vals[np.arange(vals.shape[0]), first]
    return np.where(finite.any(axis=1), base, 0.0)


@functools.partial(jax.jit, static_argnames=())
def window_bounds(ts_off: jax.Array, wstart: jax.Array, wend: jax.Array
                  ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Per (series, window) first/last sample indices and counts.

    ts_off: int32 [S, T], ascending per row, PAD_TS beyond each row's count.
    wstart/wend: int32 [W] inclusive window bounds (wstart = wend - range + 1).
    Returns (first [S,W], last [S,W], n [S,W]); n == 0 means empty window.
    """
    def row(ts_row):
        first = jnp.searchsorted(ts_row, wstart, side="left")
        last = jnp.searchsorted(ts_row, wend, side="right") - 1
        return first, last
    first, last = jax.vmap(row)(ts_off)
    n = jnp.maximum(last - first + 1, 0)
    return first.astype(jnp.int32), last.astype(jnp.int32), n.astype(jnp.int32)


def gather_at(arr: jax.Array, idx: jax.Array) -> jax.Array:
    """Gather arr[s, idx[s, w]] -> [S, W]; idx clipped (caller masks).

    Fast path: idx [1, W] (shared time grid across series) lowers to a
    rank-1 column gather — contiguous lanes, no per-row dynamic gather —
    which is the difference between an MXU-friendly program and a scalar
    mess on TPU."""
    safe = jnp.clip(idx, 0, arr.shape[1] - 1)
    if safe.shape[0] == 1 and arr.shape[0] != 1:
        return arr[:, safe[0]]
    if arr.shape[0] == 1 and safe.shape[0] != 1:
        # shared [1, T] row gathered at per-series indices (the ragged
        # rate family's valid boundaries on the shared scrape grid)
        return jnp.take(arr[0], safe, axis=0)
    return jnp.take_along_axis(arr, safe, axis=1)


def windowed_cumsum_delta(csum: jax.Array, first: jax.Array, last: jax.Array,
                          n: jax.Array) -> jax.Array:
    """Window sums from a cumulative array: csum[last] - csum[first-1].
    csum: [S, T] inclusive cumsum along time.  Returns [S, W] (0 where n==0)."""
    hi = gather_at(csum, last)
    lo = jnp.where(first > 0, gather_at(csum, first - 1), 0.0)
    return jnp.where(n > 0, hi - lo, 0.0)
