"""Counter reset (drop) detection and correction as an associative scan.

The reference detects drops at ingest and carries per-chunk correction
metadata so query-time rate is O(chunks) (ref:
memory/.../format/vectors/DoubleVector.scala:301 CorrectingDoubleVectorReader,
DoubleCounterAppender:442; query/.../rangefn/RangeFunction.scala:126
CounterChunkedRangeFunction).  On TPU the whole series row is resident as a
dense array, so correction is simply a prefix sum of observed drops — an
associative scan the hardware does in one fused pass (SURVEY.md section 7
"counter correction semantics on device").
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


def host_counter_correct(vals: np.ndarray) -> np.ndarray:
    """Reset-correction in f64 on HOST, before the f32 device downcast.

    This is the ingest-side drop detection of the reference
    (ref: memory/.../format/vectors/DoubleVector.scala:442
    DoubleCounterAppender records drops at ingest) moved to the gather
    boundary: counter columns are corrected (made monotone) in f64 so that
    after per-series rebasing every delta the device computes is exact in
    f32 — including across resets, where the drop magnitude itself can
    exceed f32 resolution at large counter values.  Accepts [S, T] or
    [S, T, B] (histogram buckets are counters too); NaNs pass through.
    """
    v = np.asarray(vals, dtype=np.float64)
    orig_shape = v.shape
    if v.ndim == 3:
        v = np.moveaxis(v, 2, 1).reshape(-1, orig_shape[1])
    S, T = v.shape
    valid = np.isfinite(v)
    idx = np.where(valid, np.arange(T)[None, :], -1)
    last_valid = np.maximum.accumulate(idx, axis=1)
    prev_idx = np.concatenate(
        [np.full((S, 1), -1, dtype=last_valid.dtype), last_valid[:, :-1]],
        axis=1)
    prev = np.where(prev_idx >= 0,
                    np.take_along_axis(v, np.maximum(prev_idx, 0), axis=1),
                    np.nan)
    # a reset adds the FULL previous value (the counter restarted from 0;
    # everything up to `prev` already happened) — Prometheus semantics and
    # the reference's `_correction += last` (ref: DoubleVector.scala:328).
    # Divergence kept deliberately: the reference also converts NaN to 0
    # and counts it as a reset ("end of time series marker" kludge its own
    # comment marks TODO); here NaN samples are skipped and `prev` tracks
    # the last finite value, which composes with the incremental mirror's
    # seeded-tail correction (core/devicecache._tail_state contract).
    drops = np.where(valid & np.isfinite(prev) & (prev > v), prev, 0.0)
    out = v + np.cumsum(drops, axis=1)
    if len(orig_shape) == 3:
        out = np.moveaxis(out.reshape(orig_shape[0], orig_shape[2],
                                      orig_shape[1]), 1, 2)
    return out


def rebase_values(vals: np.ndarray, correct_counter: bool,
                  return_corrected: bool = False,
                  _block_rows: int = 65_536):
    """The single host-side prep step for device value columns: optional f64
    reset correction, then per-series rebasing.  Returns (rebased f64, vbase)
    with vbase [S] (or [S, B] for histograms) — plus the corrected f64
    matrix itself when return_corrected (so callers needing it don't run
    the O(S*T) correction scan twice).  Both the leaf exec raw path and the
    DeviceMirror upload MUST use this so the two paths cannot diverge
    numerically.

    Rows are processed in blocks (correction and rebasing are per-row
    independent): at 1M x 720 the whole-matrix form materialized ~5 full
    f64 temporaries (~30 GB) and took minutes host-side; blocking caps the
    temporaries at ~block-sized arrays without changing any output bit."""
    from filodb_tpu.ops.timewindow import series_value_base
    v_in = np.asarray(vals)
    S = v_in.shape[0]
    if S <= _block_rows and v_in.dtype == np.float64:
        v64 = v_in
        if correct_counter:
            v64 = host_counter_correct(v64)
        vbase = series_value_base(v64)
        rebased = v64 - (vbase[:, None, :] if v64.ndim == 3
                         else vbase[:, None])
        return (rebased, vbase, v64) if return_corrected \
            else (rebased, vbase)
    rebased = np.empty(v_in.shape, np.float64)
    corrected = np.empty(v_in.shape, np.float64) if return_corrected \
        else None
    vbase_parts = []
    for i in range(0, S, _block_rows):
        j = min(i + _block_rows, S)
        blk = v_in[i:j].astype(np.float64)
        if correct_counter:
            blk = host_counter_correct(blk)
        vb = series_value_base(blk)
        vbase_parts.append(vb)
        rebased[i:j] = blk - (vb[:, None, :] if blk.ndim == 3
                              else vb[:, None])
        if corrected is not None:
            corrected[i:j] = blk
    if vbase_parts:
        vbase = (np.concatenate(vbase_parts) if len(vbase_parts) > 1
                 else vbase_parts[0])
    else:
        vbase = series_value_base(rebased)
    if return_corrected:
        return rebased, vbase, corrected
    return rebased, vbase


def _prev_valid(vals: jax.Array) -> jax.Array:
    """prev[s, t] = most recent non-NaN value at an index < t (NaN if none).
    Forward-fill via an associative carry scan, so NaN gaps inside a row do
    not hide a reset that happened across the gap."""
    valid = ~jnp.isnan(vals)
    def combine(a, b):
        av, af = a
        bv, bf = b
        return jnp.where(bf, bv, av), af | bf
    filled, _ = jax.lax.associative_scan(
        combine, (jnp.where(valid, vals, 0.0), valid), axis=1)
    any_before = jnp.cumsum(valid.astype(jnp.int32), axis=1) > 0
    filled = jnp.where(any_before, filled, jnp.nan)
    return jnp.concatenate(
        [jnp.full_like(vals[:, :1], jnp.nan), filled[:, :-1]], axis=1)


def drops(vals: jax.Array, vbase=None) -> jax.Array:
    """Per-sample reset correction: the FULL previous valid value where the
    counter dropped (Prometheus/reference semantics, ref:
    DoubleVector.scala:328 `_correction += last`), 0 at NaN samples.

    vbase [S]: when vals are REBASED (raw - vbase), the true previous raw
    value is prev + vbase — the correction amount is NOT base-invariant
    (unlike the old prev-cur delta), so callers on rebased data must pass
    their base."""
    valid = ~jnp.isnan(vals)
    prev = _prev_valid(vals)
    amount = prev if vbase is None else prev + vbase[:, None]
    return jnp.where(valid & ~jnp.isnan(prev) & (prev > vals), amount, 0.0)


def counter_correct(vals: jax.Array, vbase=None) -> jax.Array:
    """Reset-corrected values: vals + cumulative drop sum; monotone per row."""
    correction = jnp.cumsum(drops(vals, vbase), axis=1)
    return jnp.where(jnp.isnan(vals), vals, vals + correction)


def total_correction_and_last(vals: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Per-series (total correction, last raw value) for cross-block carry —
    the chunk-level correction metadata analogue used when a query spans
    multiple dense blocks."""
    valid = ~jnp.isnan(vals)
    total = jnp.sum(drops(vals), axis=1)
    idx = jnp.where(valid, jnp.arange(vals.shape[1])[None, :], -1)
    last_idx = jnp.max(idx, axis=1)
    last = jnp.take_along_axis(
        vals, jnp.maximum(last_idx, 0)[:, None], axis=1)[:, 0]
    return total, jnp.where(last_idx >= 0, last, jnp.nan)
