"""Counter reset (drop) detection and correction as an associative scan.

The reference detects drops at ingest and carries per-chunk correction
metadata so query-time rate is O(chunks) (ref:
memory/.../format/vectors/DoubleVector.scala:301 CorrectingDoubleVectorReader,
DoubleCounterAppender:442; query/.../rangefn/RangeFunction.scala:126
CounterChunkedRangeFunction).  On TPU the whole series row is resident as a
dense array, so correction is simply a prefix sum of observed drops — an
associative scan the hardware does in one fused pass (SURVEY.md section 7
"counter correction semantics on device").
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def _prev_valid(vals: jax.Array) -> jax.Array:
    """prev[s, t] = most recent non-NaN value at an index < t (NaN if none).
    Forward-fill via an associative carry scan, so NaN gaps inside a row do
    not hide a reset that happened across the gap."""
    valid = ~jnp.isnan(vals)
    def combine(a, b):
        av, af = a
        bv, bf = b
        return jnp.where(bf, bv, av), af | bf
    filled, _ = jax.lax.associative_scan(
        combine, (jnp.where(valid, vals, 0.0), valid), axis=1)
    any_before = jnp.cumsum(valid.astype(jnp.int32), axis=1) > 0
    filled = jnp.where(any_before, filled, jnp.nan)
    return jnp.concatenate(
        [jnp.full_like(vals[:, :1], jnp.nan), filled[:, :-1]], axis=1)


def drops(vals: jax.Array) -> jax.Array:
    """Per-sample drop magnitude max(0, prev_valid - cur), 0 at NaN samples."""
    valid = ~jnp.isnan(vals)
    prev = _prev_valid(vals)
    return jnp.where(valid & ~jnp.isnan(prev) & (prev > vals), prev - vals, 0.0)


def counter_correct(vals: jax.Array) -> jax.Array:
    """Reset-corrected values: vals + cumulative drop sum; monotone per row."""
    correction = jnp.cumsum(drops(vals), axis=1)
    return jnp.where(jnp.isnan(vals), vals, vals + correction)


def total_correction_and_last(vals: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Per-series (total correction, last raw value) for cross-block carry —
    the chunk-level correction metadata analogue used when a query spans
    multiple dense blocks."""
    valid = ~jnp.isnan(vals)
    total = jnp.sum(drops(vals), axis=1)
    idx = jnp.where(valid, jnp.arange(vals.shape[1])[None, :], -1)
    last_idx = jnp.max(idx, axis=1)
    last = jnp.take_along_axis(
        vals, jnp.maximum(last_idx, 0)[:, None], axis=1)[:, 0]
    return total, jnp.where(last_idx >= 0, last, jnp.nan)
