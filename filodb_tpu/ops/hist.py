"""Histogram kernels: histogram_quantile / histogram_max_quantile /
histogram_bucket over dense bucket matrices.

The reference evaluates quantiles over first-class histogram vectors
(ref: query/.../rangefn/InstantFunction.scala HistogramQuantileImpl area,
memory/.../vectors/Histogram.scala:17 `quantile`) and can also assemble
Prometheus-style `_bucket` series into histograms
(ref: query/.../exec/HistogramQuantileMapper.scala:149).  Buckets are
cumulative counts with ascending `le` upper bounds, last bucket +Inf.

TPU layout: bucket values arrive as [S, W, B] (range function already applied
per bucket, e.g. rate), `les` is [B].  The quantile search is a vectorized
searchsorted over the bucket axis + linear interpolation inside the bucket —
Prometheus's algorithm exactly.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def histogram_quantile(q, buckets, les):
    """q scalar, buckets [S, W, B] cumulative counts, les [B] -> [S, W].

    Prometheus semantics: rank = q * total; find first bucket with
    cumulative >= rank; linearly interpolate within [lower_le, upper_le].
    If the located bucket is +Inf -> return the last finite le; if it is the
    first bucket -> interpolate from 0 (or from le if le <= 0).
    q < 0 -> -Inf, q > 1 -> +Inf, empty histogram -> NaN.

    Host-resident inputs of modest size run the numpy twin: aggregated
    comps are [G, W, B] host arrays, and shipping them to the chip costs
    a per-panel dispatch (~70 ms through the tunnel) for microseconds of
    math — the round-4 quantile-dashboard batching measured only 1.37x
    end-to-end because every panel re-paid exactly this (round-5 verdict
    item 5).
    """
    if isinstance(buckets, np.ndarray) and buckets.size <= 8_000_000 \
            and not isinstance(q, jax.Array):
        return _histogram_quantile_np(float(q), buckets, np.asarray(les))
    return _histogram_quantile_jax(q, buckets, les)


@functools.partial(jax.jit, static_argnames=())
def _histogram_quantile_jax(q, buckets, les):
    B = buckets.shape[-1]
    # enforce monotone non-decreasing cumulative counts (mirrors the
    # ensureMonotonic fixup Prometheus applies for float jitter)
    cum = jax.lax.associative_scan(jnp.maximum, buckets, axis=-1)
    total = cum[..., -1]
    rank = q * total

    # first bucket index with cum >= rank  (per cell binary search)
    ge = cum >= rank[..., None]
    idx = jnp.argmax(ge, axis=-1)                     # first True
    none_ge = ~jnp.any(ge, axis=-1)
    idx = jnp.where(none_ge, B - 1, idx)

    les_b = jnp.broadcast_to(les, buckets.shape)
    count_at = jnp.take_along_axis(cum, idx[..., None], axis=-1)[..., 0]
    le_at = jnp.take_along_axis(les_b, idx[..., None], axis=-1)[..., 0]
    prev_idx = jnp.maximum(idx - 1, 0)
    count_prev = jnp.where(idx > 0,
                           jnp.take_along_axis(cum, prev_idx[..., None], axis=-1)[..., 0],
                           0.0)
    le_prev = jnp.where(idx > 0,
                        jnp.take_along_axis(les_b, prev_idx[..., None], axis=-1)[..., 0],
                        0.0)
    # first bucket with negative upper bound: lower bound is le itself
    le_prev = jnp.where((idx == 0) & (le_at <= 0), le_at, le_prev)

    bucket_count = count_at - count_prev
    frac = jnp.where(bucket_count > 0, (rank - count_prev) / bucket_count, 0.0)
    interp = le_prev + (le_at - le_prev) * frac

    # +Inf bucket: return highest finite le (Prometheus returns les[B-2])
    has_inf_top = jnp.isinf(le_at)
    finite_les = jnp.where(jnp.isinf(les), -jnp.inf, les)
    max_finite = jnp.max(finite_les)
    out = jnp.where(has_inf_top, max_finite, interp)

    out = jnp.where(total > 0, out, jnp.nan)
    out = jnp.where(jnp.isnan(rank), jnp.nan, out)
    out = jnp.where(q < 0, -jnp.inf, out)
    out = jnp.where(q > 1, jnp.inf, out)
    return out


def _histogram_quantile_np(q: float, buckets: np.ndarray,
                           les: np.ndarray) -> np.ndarray:
    """Numpy twin of histogram_quantile — identical semantics, no device
    dispatch (kept in lockstep; parity-tested in tests/test_hist_scheme)."""
    B = buckets.shape[-1]
    cum = np.maximum.accumulate(buckets, axis=-1)
    total = cum[..., -1]
    rank = q * total
    ge = cum >= rank[..., None]
    idx = np.argmax(ge, axis=-1)
    none_ge = ~np.any(ge, axis=-1)
    idx = np.where(none_ge, B - 1, idx)

    les_b = np.broadcast_to(les, buckets.shape)
    count_at = np.take_along_axis(cum, idx[..., None], axis=-1)[..., 0]
    le_at = np.take_along_axis(les_b, idx[..., None], axis=-1)[..., 0]
    prev_idx = np.maximum(idx - 1, 0)
    count_prev = np.where(
        idx > 0,
        np.take_along_axis(cum, prev_idx[..., None], axis=-1)[..., 0], 0.0)
    le_prev = np.where(
        idx > 0,
        np.take_along_axis(les_b, prev_idx[..., None], axis=-1)[..., 0],
        0.0)
    le_prev = np.where((idx == 0) & (le_at <= 0), le_at, le_prev)

    bucket_count = count_at - count_prev
    with np.errstate(invalid="ignore", divide="ignore"):
        frac = np.where(bucket_count > 0,
                        (rank - count_prev) / bucket_count, 0.0)
    interp = le_prev + (le_at - le_prev) * frac

    has_inf_top = np.isinf(le_at)
    finite_les = np.where(np.isinf(les), -np.inf, les)
    max_finite = np.max(finite_les)
    out = np.where(has_inf_top, max_finite, interp)

    out = np.where(total > 0, out, np.nan)
    out = np.where(np.isnan(rank), np.nan, out)
    if q < 0:
        out = np.full_like(out, -np.inf)
    elif q > 1:
        out = np.full_like(out, np.inf)
    return out


def histogram_bucket(le: float, buckets: jax.Array, les: jax.Array) -> jax.Array:
    """Extract one bucket's series [S, W] by upper bound (ref:
    InstantFunction.scala histogram_bucket)."""
    matches = jnp.isclose(les, le) | (jnp.isinf(les) & jnp.isinf(jnp.asarray(le)))
    idx = jnp.argmax(matches)
    found = jnp.any(matches)
    out = buckets[..., idx]
    return jnp.where(found, out, jnp.nan)


def hist_sum_rv(buckets: jax.Array) -> jax.Array:
    """Sum across series of bucket matrices (HistSum aggregate, ref:
    exec/aggregator/HistSumRowAggregator) — elementwise NaN-aware sum."""
    present = ~jnp.isnan(buckets)
    s = jnp.sum(jnp.where(present, buckets, 0.0), axis=0)
    any_present = jnp.any(present, axis=0)
    return jnp.where(any_present, s, jnp.nan)
