"""Pallas TPU kernel: fused windowed-rate + group-sum in one HBM pass.

The headline query shape — `sum by (...) (rate(counter[5m]))` — costs the
XLA path several passes over the [S, T] value matrix (validity mask, reset
correction scan, boundary gathers, then a scatter-add segment sum).  On a
bandwidth-bound chip the passes are the latency.  This kernel computes the
whole thing in ONE read of the values, by turning every data-dependent
access into an MXU matmul against tiny host-built selection matrices:

- boundary gathers  v[:, first[w]]  ->  v @ O1, O1[t, w] = 1{t == first[w]}
- cumulative reset corrections      ->  drops @ L1, L1[t, w] = 1{t <= first[w]}
  (drops[s, t] = max(prev - cur, 0) is local once rows are dense)
- group segment-sum                 ->  onehot(gids) @ rate  on the MXU

Preconditions (the caller gates, see `can_fuse`): one shared scrape grid
across series (the devicecache/shared_grid invariant) and dense rows — no
NaN inside the counted region.  Anything else falls back to the general
XLA path in ops/rangefns.py; semantics here match it bit-for-bit in f32
(same extrapolation rules, ref: RateFunctions.scala:37-76; same 3-phase
aggregate contract, ref: exec/AggrOverRangeVectors.scala:17-125).

Works on CPU via interpret=True (tests); on TPU via the MXU.
"""
from __future__ import annotations

import functools
import os
import threading
from typing import NamedTuple, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_LANE = 128
_MIN_BS = 32
try:
    _BS = int(os.environ.get("FILODB_FUSED_BS", "256"))
except ValueError:
    raise ValueError(
        f"FILODB_FUSED_BS={os.environ['FILODB_FUSED_BS']!r} is not an "
        f"integer") from None
"""Series rows per grid step (VMEM-sized).  Env-overridable for on-chip
block-size sweeps (tools/tpu_tune.py); pick_block still shrinks from here
whenever the VMEM estimate demands it."""
if _BS < _MIN_BS or (_BS & (_BS - 1)):
    raise ValueError(
        f"FILODB_FUSED_BS={_BS} must be a power of two >= {_MIN_BS}: "
        f"padding (pad_values) and the pick_block halving ladder both "
        f"assume it, and a block below _MIN_BS would silently drop "
        f"trailing series rows in interpret mode")

_PRECISION = os.environ.get("FILODB_FUSED_PRECISION", "episplit")
"""MXU precision strategy for the kernel's matmuls — see _matmuls()."""
if _PRECISION not in ("highest", "split", "episplit"):
    raise ValueError(
        f"FILODB_FUSED_PRECISION={_PRECISION!r}: expected 'highest', "
        f"'split' or 'episplit' (a typo here would silently mislabel a "
        f"tuning sweep)")

_GATHER = os.environ.get("FILODB_FUSED_GATHER", "1") != "0"
"""Boundary selection strategy for the rate family + last_over_time: the
default replaces the v @ o1 / v @ o2 one-hot selection MATMULS (6-pass
f32-HIGHEST emulation over a >=99%-zero [Tp, Wp] matrix) with exact
per-128-lane-tile dynamic gathers at host-built indices — pure data
movement, bit-identical selections (tools/probe_slice.py: tiled
tpu.dynamic_gather compiles on v5e; cross-vreg gathers do not).  "0"
keeps the matmul path for A/B measurement (tools/tpu_chain.py)."""


def gather_default(kind: str) -> bool:
    """Whether the gather strategy applies to this kernel kind (the
    over_time band kinds keep their window-sum matmuls: a cumsum+
    gather-diff replacement would change f32 summation order)."""
    return _GATHER and kind in ("rate_family", "last_over_time")


def _dot_hi(a, b):
    return jnp.dot(a, b, preferred_element_type=jnp.float32,
                   precision=jax.lax.Precision.HIGHEST)


def _dot_1p(a, b):
    """One bf16 MXU pass (f32 operands truncated), f32 accumulation."""
    return jnp.dot(a, b, preferred_element_type=jnp.float32,
                   precision=jax.lax.Precision.DEFAULT)


def _split3(x):
    """x == hi + mid + lo with hi/mid exactly bf16-representable and lo
    carrying the last ~8 mantissa bits (its own bf16 truncation error is
    ~|x|*2^-24, i.e. f32 epsilon)."""
    hi = x.astype(jnp.bfloat16).astype(jnp.float32)
    r = x - hi
    mid = r.astype(jnp.bfloat16).astype(jnp.float32)
    return hi, mid, r - mid


def _matmuls():
    """Per-operand MXU precision for the kernel's matmuls.

    Every matmul in this kernel has at least one exact-in-bf16 operand:
    the 0/1 selection/band/one-hot matrices, or a 0/1 validity mask.
    Full f32 emulation (HIGHEST ~ 6 bf16 MXU passes) therefore wastes
    passes on a side that cannot lose bits.  "split" mode decomposes the
    VALUES operand into 3 bf16 terms (Mosaic rejects per-operand
    `precision` tuples, so the decomposition HIGHEST would do internally
    is spelled out) and runs 3 single-pass matmuls against the binary
    operand: the hi/mid passes are exact, the lo pass carries ~f32-
    epsilon truncation — the same |v|*2^-24 error the f32 *storage* of
    the values already imposes on every path.  Binary x binary matmuls
    (validity counts) are exact at DEFAULT outright (0/1 products, f32
    MXU accumulation): 1 pass.  Returns (mmv, mmg, mmb): values x
    binary, binary x values (group epilogue), binary x binary.

    Measured on a real v5e (TPU_TUNE_r04.json, tools/tpu_tune.py): at
    262k x 720 full "split" is NOT faster — dense p50 regressed ~20%
    (three separate single-pass dots + the VPU decomposition schedule
    worse than Mosaic's fused multi-pass emulation) and ragged gained
    only ~6%, while results stayed bit-identical (max_rel_err 0.0).
    That regression was the since-removed selection matmuls' schedule,
    not the epilogue's: "episplit" (round 5, the DEFAULT) applies the
    decomposition ONLY to the group epilogue (mmg) and keeps the
    over_time band matmuls (mmv) at HIGHEST — with gather selections
    the default for the rate family, mmg is that kernel's only large
    matmul.  Measured (TPU_CHAIN_r05.json *_episplit vs *_gather):
    epilogue attribution 1.84 -> 1.18 ms at 262k, 7.40 -> 4.52 ms at
    1M; total device time at the 1M north star 15.95 -> 13.15 ms
    (55.0B samples/s device rate).  mmb (binary x binary presence
    counts) is single-pass in every mode: 0/1 operands are exact in
    bf16 and the MXU accumulates in f32, so DEFAULT is mathematically
    exact there — emulation passes on it buy nothing.

    (Mosaic lowers only DEFAULT and HIGHEST; Precision.HIGH and
    per-operand precision tuples are rejected.)"""
    def mmg_split(a, b):
        hi, mid, lo = _split3(b)
        return _dot_1p(a, hi) + _dot_1p(a, mid) + _dot_1p(a, lo)

    if _PRECISION == "episplit":
        return _dot_hi, mmg_split, _dot_1p
    if _PRECISION != "split":
        return _dot_hi, _dot_hi, _dot_1p

    def mmv(a, b):
        hi, mid, lo = _split3(a)
        return _dot_1p(hi, b) + _dot_1p(mid, b) + _dot_1p(lo, b)

    return mmv, mmg_split, _dot_1p


def _pad_to(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _bucket_up(n: int, quantum: int, exact_below: int) -> int:
    """Round n up to a {8/8, 9/8, ..., 16/8} x 2^k geometric ladder of
    quantum multiples (adjacent rungs <= 1.125x, so padding <= 12.5%) —
    shape canonicalization so near-identical working sets share ONE
    compiled program.

    Under live ingest the series count drifts every snapshot refresh;
    without bucketing each drift changes Sp and every query pays a full
    XLA recompile (measured 43-73 s at 262k-1M, BENCH_r04.json) — the
    prime suspect for SOAK_r04's 9x query degradation.  Below
    `exact_below` the plain quantum pad is kept: small shapes are cheap
    to compile and common in tests that assert exact padding."""
    if n <= exact_below:
        return _pad_to(max(n, 1), quantum)
    k = 1
    while quantum * 16 * k < n:
        k *= 2
    for m in range(8, 17):
        cand = quantum * k * m
        if cand >= n:
            return cand
    raise AssertionError("unreachable: the loop exits with 16*k*quantum >= n")


def pad_series_count(S: int) -> int:
    """Canonical padded series count: multiple of _BS (every pick_block
    block size divides it) on the geometric ladder."""
    return _bucket_up(S, _BS, 8 * _BS)


def pad_group_count(G: int) -> int:
    """Canonical padded group count for the kernel epilogue (multiple of
    8, geometric ladder above 64 so group-count drift reuses programs)."""
    return _bucket_up(max(G, 8), 8, 64)


class FusedPlan(NamedTuple):
    """Host-built query plan: selection matrices + shared window scalars."""
    o1: np.ndarray       # [Tp, Wp] f32  one-hot at first[w]
    o2: np.ndarray       # [Tp, Wp] f32  one-hot at last[w]
    l2: np.ndarray       # [Tp, Wp] f32  1{t <= last[w]}  (drops path)
    l1: np.ndarray       # [Tp, Wp] f32  1{t <= first[w]} (drops path)
    t1: np.ndarray       # [1, Wp] f32   ts at first[w]
    t2: np.ndarray       # [1, Wp] f32   ts at last[w]
    n: np.ndarray        # [1, Wp] f32   samples in window
    wstart_x: np.ndarray  # [1, Wp] f32  window start boundary (exclusive-1)
    wend_x: np.ndarray   # [1, Wp] f32
    wvalid: np.ndarray   # [W] bool      n >= 2 (rate family)
    wvalid1: np.ndarray  # [W] bool      n >= 1 (*_over_time family)
    n1: np.ndarray       # [1, Wp] f32   TRUE samples in window (0 empty)
    W: int
    Tp: int
    # raw shared-grid timestamps [1, Tp] f32 (0 pad tail): the ragged rate
    # family selects per-series VALID boundary timestamps in-kernel
    tsrow: np.ndarray = None
    # boundary slot indices [1, Wp] f32 (first[w] / last[w]; 0 sentinel
    # for empty + padded windows) — the gather-strategy kernel selects
    # columns at these host-built positions instead of multiplying the
    # o1/o2 one-hot matrices (gather_default)
    idx1: np.ndarray = None
    idx2: np.ndarray = None


def build_plan(ts_row: np.ndarray, wends: np.ndarray,
               range_ms: int) -> FusedPlan:
    """Window boundary math once, host-side (shared grid: one ts row)."""
    ts_row = np.asarray(ts_row, dtype=np.int64)
    wend = np.asarray(wends, dtype=np.int64)
    wstart = wend - int(range_ms) + 1
    first = np.searchsorted(ts_row, wstart, side="left")
    last = np.searchsorted(ts_row, wend, side="right") - 1
    n = window_counts(ts_row, wend, range_ms)
    W, T = len(wend), len(ts_row)
    Wp, Tp = _pad_to(max(W, 1), _LANE), _pad_to(max(T, 1), _LANE)
    # selection matrices cover every NON-EMPTY window (n >= 1): the
    # over_time band needs single-sample windows, and the rate family is
    # harmless on them (first == last -> delta == 0 -> contributes 0; its
    # host mask wvalid stays n >= 2)
    valid = n >= 1

    def sel(idx, leq):
        m = np.zeros((Tp, Wp), np.float32)
        t = np.arange(Tp)[:, None]
        iw = np.where(valid, np.clip(idx, 0, T - 1), -1)[None, :]
        body = (t <= iw) if leq else (t == iw)
        m[:, :W] = body.astype(np.float32)
        return m

    def row(v):
        out = np.zeros((1, Wp), np.float32)
        out[0, :W] = v
        return out

    fi = np.clip(first, 0, T - 1)
    la = np.clip(last, 0, T - 1)
    tsr = np.zeros((1, Tp), np.float32)
    tsr[0, :T] = ts_row
    return FusedPlan(
        o1=sel(first, False), o2=sel(last, False),
        l2=sel(last, True), l1=sel(first, True),
        t1=row(np.where(valid, ts_row[fi], 0)),
        t2=row(np.where(valid, ts_row[la], 0)),
        n=row(np.maximum(n, 2)),           # safe: invalid windows masked out
        wstart_x=row(wstart - 1), wend_x=row(wend),
        wvalid=(n >= 2), wvalid1=(n >= 1), n1=row(n), W=W, Tp=Tp,
        tsrow=tsr,
        idx1=row(np.where(valid, fi, 0)), idx2=row(np.where(valid, la, 0)))


_PLAN_MATS_CACHE: dict = {}
_PLAN_MATS_LOCK = threading.Lock()


def plan_device_mats(plan: "FusedPlan", device=None) -> tuple:
    """Device-resident copies of a plan's selection matrices + window
    rows, uploaded ONCE per (plan object, device).

    Measured on the tunneled v5e (TPU_CHAIN_r05.json): the kernel's true
    device time at 262k x 720 is ~6 ms, but the per-call p50 was ~113 ms
    against a ~63 ms dispatch floor — most of the unexplained ~44 ms was
    this function's absence: every query re-uploaded ~1.6 MB of numpy
    plan matrices through `jnp.asarray`.  Keyed by id(plan) with the
    plan pinned (id-reuse safe), matching the leaf/mesh plan caches'
    lifetime.  One cache entry per plan holds ALL its per-device uploads
    (the multi-chip per-device dispatch path pins the same plan on every
    participating device), so device fan-out can't thrash the LRU."""
    from filodb_tpu.utils.devicetelem import telem
    k = id(plan)
    dk = None if device is None else device
    with _PLAN_MATS_LOCK:
        ent = _PLAN_MATS_CACHE.get(k)
        if ent is not None and ent[0] is plan and dk in ent[1]:
            # LRU touch: eviction pops the oldest entry, and a hot mesh
            # plan hit on every query must not age out under mixed
            # leaf+mesh traffic filling the cap
            _PLAN_MATS_CACHE.pop(k)
            _PLAN_MATS_CACHE[k] = ent
            telem.record_cache_event("plan_mats", "hit")
            return ent[1][dk]
    telem.record_cache_event("plan_mats", "miss")
    W = plan.t1.shape[1]
    idx1 = plan.idx1 if plan.idx1 is not None else np.zeros((1, W),
                                                            np.float32)
    idx2 = plan.idx2 if plan.idx2 is not None else np.zeros((1, W),
                                                            np.float32)
    put = (jnp.asarray if device is None
           else (lambda m: jax.device_put(m, device)))
    mats = tuple(put(m) for m in
                 (plan.o1, plan.o2, plan.l1, plan.l2, plan.t1, plan.t2,
                  plan.n, plan.n1, plan.wstart_x, plan.wend_x, plan.tsrow,
                  idx1, idx2))
    released: list = []
    with _PLAN_MATS_LOCK:
        ent = _PLAN_MATS_CACHE.get(k)
        if ent is None or ent[0] is not plan:
            if ent is not None:
                released.append(ent)        # id-reuse: old plan replaced
            ent = (plan, {})
            _PLAN_MATS_CACHE[k] = ent
        if dk not in ent[1]:                # a concurrent build may have
            ent[1][dk] = mats               # won: book each upload once
            telem.hbm_book(dk, "planmats", _mats_nbytes(mats))
        while len(_PLAN_MATS_CACHE) > 8:
            released.append(
                _PLAN_MATS_CACHE.pop(next(iter(_PLAN_MATS_CACHE))))
    for _, uploads in released:
        telem.record_cache_event("plan_mats", "evict")
        for dk2, mats2 in uploads.items():
            telem.hbm_book(dk2, "planmats", -_mats_nbytes(mats2))
    return mats


def _mats_nbytes(mats) -> int:
    """Device bytes of one plan's uploaded matrix set (the 'planmats'
    HBM occupancy region)."""
    return int(sum(getattr(m, "nbytes", 0) for m in mats))


_SEL_DUMMY: dict = {}


def _sel_dummy(device=None):
    """Tiny stand-in for the unused selection matrices in gather mode —
    the kernel never reads them, and the small block frees their ~1.5 MB
    of VMEM for larger series blocks.  One per device: the per-device
    dispatch path needs every kernel operand committed to ITS chip."""
    dk = None if device is None else device
    d = _SEL_DUMMY.get(dk)
    if d is None:
        z = np.zeros((8, _LANE), np.float32)
        d = jnp.asarray(z) if device is None else jax.device_put(z, device)
        _SEL_DUMMY[dk] = d
    return d


def _committed_device(arr):
    """The single device `arr` is committed to, else None — uncommitted
    arrays follow jax's default placement, no pin needed.  Used to route
    plan-matrix uploads to the chip that already holds a working set
    (sharded DeviceMirror mode), so dispatch never drags the ~1.6 MB of
    selection matrices cross-device per call."""
    try:
        if getattr(arr, "committed", False):
            devs = arr.devices()
            if len(devs) == 1:
                return next(iter(devs))
    except Exception:  # noqa: BLE001 — non-jax arrays (numpy fallback)
        pass
    return None


def _kernel_mats(plan: "FusedPlan", over_time: bool,
                 gather: bool = False, device=None) -> tuple:
    """The 12 operands _run expects after (vals, vbase, gids), with `n`
    resolved to true counts for the over_time kinds and the o1..l2
    selection matrices swapped for dummies in gather mode.  `device`
    pins the upload (per-device dispatch, parallel/mesh.py)."""
    m = plan_device_mats(plan, device)
    sel = (_sel_dummy(device),) * 4 if gather else m[:4]
    return sel + m[4:6] + (m[7] if over_time else m[6],) + m[8:]


def _shift_r(x, k: int, fill):
    return jnp.concatenate([jnp.full_like(x[:, :k], fill), x[:, :-k]],
                           axis=1)


def _shift_l(x, k: int, fill):
    return jnp.concatenate([x[:, k:], jnp.full_like(x[:, :k], fill)],
                           axis=1)


def _fill_scan(x, ok, left: bool):
    """Forward (left=False) / backward (left=True) fill of valid values
    along time in log2(T) shift-and-select steps — the in-kernel form of a
    lax.associative_scan carry, Pallas-friendly (static shapes, no dynamic
    control flow).  Positions with no valid neighbor on the fill side keep
    their input value; callers mask those via window valid-counts.

    Validity travels as f32 0/1, NOT bool: Mosaic cannot shift/concat i1
    vregs on real TPU (`tpu.bitcast_vreg vector<8x128xi1> -> i32` is
    rejected as an invalid vector register cast; interpret mode accepted
    the bool form, which hid this until the first on-chip ragged compile).
    Returns (filled x, f32 validity)."""
    shift = _shift_l if left else _shift_r
    okf = ok.astype(jnp.float32)
    k = 1
    while k < x.shape[1]:
        xs = shift(x, k, 0.0)
        oks = shift(okf, k, 0.0)
        x = jnp.where(okf > 0, x, xs)
        okf = jnp.maximum(okf, oks)
        k *= 2
    return x, okf


def _fill_scan2(x, y, ok, left: bool):
    """_fill_scan over two carriers sharing ONE validity evolution — the
    ragged rate path fills values and timestamps against the same mask,
    and sharing the okf carry halves the live [bs, Tp] scan temporaries
    (the footprint that forces the series-block shrink)."""
    shift = _shift_l if left else _shift_r
    okf = ok.astype(jnp.float32)
    k = 1
    while k < x.shape[1]:
        xs = shift(x, k, 0.0)
        ys = shift(y, k, 0.0)
        oks = shift(okf, k, 0.0)
        keep = okf > 0
        x = jnp.where(keep, x, xs)
        y = jnp.where(keep, y, ys)
        okf = jnp.maximum(okf, oks)
        k *= 2
    return x, y, okf


def _cumsum_lanes(x):
    """Inclusive prefix sum along time (Hillis-Steele doubling shifts)."""
    k = 1
    while k < x.shape[1]:
        x = x + _shift_r(x, k, 0.0)
        k *= 2
    return x


def _gather_cols(x, idx):
    """out[s, w] = x[s, idx[0, w]] — the one-hot selection matmul as pure
    data movement.  Mosaic lowers take_along_axis to tpu.dynamic_gather
    only within one 128-lane vreg (the cross-vreg form fails to compile,
    tools/probe_slice.py), so the row is gathered per 128-lane tile and
    the right tile selected per window.  Exact: no arithmetic touches
    the values."""
    bs, Tp = x.shape
    Wp = idx.shape[1]
    chunks = []
    for wc in range(0, Wp, _LANE):
        ic = jnp.broadcast_to(idx[:, wc:wc + _LANE], (bs, _LANE))
        acc = jnp.zeros((bs, _LANE), x.dtype)
        for k in range(0, Tp, _LANE):
            tile = x[:, k:k + _LANE]
            local = jnp.clip(ic - k, 0, _LANE - 1)
            g = jnp.take_along_axis(tile, local, axis=1,
                                    mode="promise_in_bounds")
            acc = jnp.where((ic >= k) & (ic < k + _LANE), g, acc)
        chunks.append(acc)
    return chunks[0] if len(chunks) == 1 else jnp.concatenate(chunks, axis=1)


def _kernel(vals_ref, vbase_ref, gids_ref, o1_ref, o2_ref, l1_ref, l2_ref,
            t1_ref, t2_ref, n_ref, ws_ref, we_ref, ts_ref, i1_ref, i2_ref,
            *out_refs,
            num_groups: int, is_counter: bool, is_rate: bool,
            with_drops: bool, kind: str = "rate_family",
            ragged: bool = False, per_series: bool = False,
            gather: bool = False):
    v = vals_ref[:]                                   # [BS, Tp]
    # The MXU's default single bf16 pass truncates f32 mantissas (1e-2
    # relative error on counter magnitudes); _matmuls() picks multi-pass
    # f32 decompositions per operand — see its docstring.
    mmv, mmg, mmb = _matmuls()
    if kind == "last_over_time":
        # instant-vector selector (`sum by (x) (metric)` with staleness
        # lookback): the last sample in each window is the o2 one-hot
        # gather; empty windows contribute 0 and are masked by counts.
        # Ragged keeps SLOT semantics deliberately — a NaN in the newest
        # slot is a staleness marker that makes the series absent, not a
        # hole to skip (unlike the rate family's range-vector filtering)
        if ragged:
            m = v == v
            if gather:
                idx2 = i2_ref[:].astype(jnp.int32)
                sel = _gather_cols(jnp.where(m, v, 0.0), idx2)
                # empty windows gather column idx 0 (a plan sentinel):
                # the true-count mask zeroes their presence, matching
                # the all-zero o2 column the matmul form relied on
                pres = _gather_cols(m.astype(jnp.float32), idx2) \
                    * jnp.minimum(n_ref[:], 1.0)
            else:
                sel = mmv(jnp.where(m, v, 0.0), o2_ref[:])
                pres = mmb(m.astype(jnp.float32), o2_ref[:])
            out = (sel + vbase_ref[:]) * pres
            _epilogue(mmg, gids_ref, out, pres, out_refs, num_groups,
                      per_series, mmb=mmb)
            return
        if gather:
            sel = _gather_cols(v, i2_ref[:].astype(jnp.int32)) \
                * jnp.minimum(n_ref[:], 1.0)
        else:
            sel = mmv(v, o2_ref[:])
        out = sel + vbase_ref[:] * jnp.minimum(n_ref[:], 1.0)
        _epilogue(mmg, gids_ref, out, None, out_refs, num_groups, per_series)
        return
    if kind in ("sum_over_time", "avg_over_time", "count_over_time"):
        # window sums as ONE matmul against the band matrix
        # band[t, w] = 1{first[w] <= t <= last[w]} = l2 - l1 + o1;
        # the ABSOLUTE sum re-adds the per-series base as vb * n.
        # Ragged (NaN-holed) rows: validity-weighted variant — zero the
        # holes, take per-(series, window) counts from a second matmul of
        # the validity mask against the same band (VERDICT r2 item 2).
        band = l2_ref[:] - l1_ref[:] + o1_ref[:]
        if ragged:
            validf = (v == v).astype(jnp.float32)     # NaN-aware
            s = mmv(jnp.where(v == v, v, 0.0), band)
            n = mmb(validf, band)                      # [BS, Wp] valid counts
            pres = (n > 0).astype(jnp.float32)
        else:
            s = mmv(v, band)
            n = n_ref[:]                              # [1, Wp] true counts
            pres = None
        if kind == "sum_over_time":
            out = s + vbase_ref[:] * n
        elif kind == "avg_over_time":
            out = s / jnp.maximum(n, 1.0) + vbase_ref[:]
            if ragged:
                out = out * pres      # no vbase leak into absent cells
        else:                                         # count_over_time
            out = n * jnp.ones_like(s)
            if ragged:
                # count's presence is SLOT-based: a window whose grid slots
                # exist but hold only NaN emits 0, not absent (ref:
                # AggrOverTimeFunctions.scala:367-382), unlike sum/avg
                pres = (n_ref[:] > 0).astype(jnp.float32) * jnp.ones_like(s)
        _epilogue(mmg, gids_ref, out, pres, out_refs, num_groups,
                  per_series, mmb=mmb)
        return
    pres = None
    if ragged:
        # ragged rate family: NaN holes are ABSENT samples (upstream
        # filters staleness markers out of range vectors before the rate
        # math, ref: RateFunctions.scala:140-196 iterates stored samples
        # only) — so the boundaries are each series' first/last VALID
        # sample inside the window.  Forward/backward fill scans reduce
        # the per-series boundary search to the same shared one-hot
        # matmuls as the dense path, keeping everything in one HBM pass.
        m = v == v
        vz = jnp.where(m, v, 0.0)
        if with_drops:
            fv, fok = _fill_scan(vz, m, left=False)
            prev = _shift_r(fv, 1, 0.0)
            pok = _shift_r(fok, 1, 0.0)                # f32 validity
            # reset vs the previous VALID value; correction adds the full
            # previous RAW value (prev + vbase), cumulative across the row
            d = jnp.where(m & (pok > 0) & (vz < prev),
                          prev + vbase_ref[:], 0.0)
            c = vz + _cumsum_lanes(d)
        else:
            c = vz
        tsb = jnp.where(m, jnp.broadcast_to(ts_ref[:], v.shape), 0.0)
        f_c, f_t, _ = _fill_scan2(c, tsb, m, left=False)
        b_c, b_t, _ = _fill_scan2(c, tsb, m, left=True)
        if gather:
            # exact selections at first/last window slots (the fill scans
            # made those slots carry the boundary VALID values), and the
            # validity count as a cumsum difference — all integer-in-f32,
            # bit-identical to the matmul form.  Empty windows gather
            # slot 0: nv <= 1 there, so presence masks them exactly as
            # the all-zero selection columns did.
            idx1 = i1_ref[:].astype(jnp.int32)
            idx2 = i2_ref[:].astype(jnp.int32)
            mf = m.astype(jnp.float32)
            cs_m = _cumsum_lanes(mf)
            nv = _gather_cols(cs_m, idx2) - _gather_cols(cs_m, idx1) \
                + _gather_cols(mf, idx1)
            v1 = _gather_cols(b_c, idx1)
            v2 = _gather_cols(f_c, idx2)
            t1 = _gather_cols(b_t, idx1)
            t2 = _gather_cols(f_t, idx2)
        else:
            band = l2_ref[:] - l1_ref[:] + o1_ref[:]
            nv = mmb(m.astype(jnp.float32), band)      # [BS, Wp] valid count
            v1 = mmv(b_c, o1_ref[:])
            v2 = mmv(f_c, o2_ref[:])
            t1 = mmv(b_t, o1_ref[:])
            t2 = mmv(f_t, o2_ref[:])
        n = jnp.maximum(nv, 2.0)                      # math-safe; masked
        pres = (nv >= 2.0).astype(jnp.float32)
    else:
        if gather:
            idx1 = i1_ref[:].astype(jnp.int32)
            idx2 = i2_ref[:].astype(jnp.int32)
            if with_drops:
                prev = jnp.concatenate([v[:, :1], v[:, :-1]], axis=1)
                d = jnp.where(v < prev, prev + vbase_ref[:], 0.0)
                col = jax.lax.broadcasted_iota(jnp.int32, d.shape, 1)
                d = jnp.where(col == 0, 0.0, d)
                # v@o1 + d@l1 == (v + cumsum(d)) selected at first[w]
                # (l1 is the <=first[w] step matrix); ditto last[w]
                c = v + _cumsum_lanes(d)
            else:
                c = v
            v1 = _gather_cols(c, idx1)                 # [BS, Wp]
            v2 = _gather_cols(c, idx2)
        else:
            v1 = mmv(v, o1_ref[:])                     # [BS, Wp]
            v2 = mmv(v, o2_ref[:])
            if with_drops:
                prev = jnp.concatenate([v[:, :1], v[:, :-1]], axis=1)
                # first column has no predecessor; padded tail columns
                # are never selected by l1/l2 (first/last < T <= padded
                # region).  A reset adds the FULL previous RAW value =
                # prev + vbase (rebased rows; ref: DoubleVector.scala:328)
                d = jnp.where(v < prev, prev + vbase_ref[:], 0.0)
                col = jax.lax.broadcasted_iota(jnp.int32, d.shape, 1)
                d = jnp.where(col == 0, 0.0, d)
                v1 = v1 + mmv(d, l1_ref[:])
                v2 = v2 + mmv(d, l2_ref[:])
        t1, t2 = t1_ref[:], t2_ref[:]                 # [1, Wp]
        n = n_ref[:]
    ws, we = ws_ref[:], we_ref[:]

    dur_start = (t1 - ws) / 1000.0
    dur_end = (we - t2) / 1000.0
    sampled = jnp.maximum((t2 - t1) / 1000.0, 1e-9)
    avg_between = sampled / (n - 1.0)
    delta = v2 - v1
    if is_counter:
        va = v1 + vbase_ref[:]                        # absolute first value
        dur_zero = sampled * (va / jnp.where(delta == 0.0, jnp.inf, delta))
        take_zero = (delta > 0) & (va >= 0) & (dur_zero < dur_start)
        dur_start = jnp.where(take_zero, dur_zero, dur_start)
    threshold = avg_between * 1.1
    extrap = sampled \
        + jnp.where(dur_start < threshold, dur_start, avg_between / 2) \
        + jnp.where(dur_end < threshold, dur_end, avg_between / 2)
    out = delta * (extrap / sampled)
    if is_rate:
        out = out / jnp.maximum(we - ws, 1.0) * 1000.0
    if pres is not None:
        out = out * pres                              # no NaN into the MXU

    _epilogue(mmg, gids_ref, out, pres, out_refs, num_groups,
              per_series, mmb=mmb)


def _epilogue(mm, gids_ref, out, pres, out_refs, num_groups: int,
              per_series: bool, mmb=None):
    """Shared epilogue.  Group mode: one-hot segment-sum on the MXU,
    accumulated across sequential grid steps (pad rows carry gid -1: no
    match); `pres` (ragged presence [BS, Wp]) feeds a second accumulated
    output so present-counts ride the same kernel.  Per-series mode
    (agg min/max: sum is the MXU's semiring, min is not): write the raw
    [BS, Wp] block and let an XLA segment reduction finish on the
    T/W-times-smaller output."""
    if per_series:
        out_refs[0][:] = out
        if pres is not None:
            out_refs[1][:] = pres
        return
    gids = gids_ref[:]                                # [BS, P] int32
    groups = jax.lax.broadcasted_iota(jnp.int32, (num_groups, out.shape[0]),
                                      0)
    onehot = (groups == gids[:, 0][None, :]).astype(jnp.float32)
    # multi-grouping batch (merge_groups): each extra column is another
    # panel's grouping over DISJOINT group-id ranges, so the sum stays a
    # 0/1 matrix and P dashboard panels ride ONE kernel dispatch
    for p in range(1, gids.shape[1]):
        onehot = onehot + (groups == gids[:, p][None, :]).astype(jnp.float32)
    part = mm(onehot, out)                            # [Gp, Wp]

    @pl.when(pl.program_id(0) == 0)
    def _():
        for r in out_refs:
            r[:] = jnp.zeros_like(r)
    out_refs[0][:] += part
    if pres is not None:
        # presence is 0/1 x 0/1: the binary matmul is exact in one pass
        out_refs[1][:] += (mmb or mm)(onehot, pres)


def _run_shape_sig(vals_p, plan, Gp: int, kind: str, ragged: bool) -> str:
    """The compile-cache shape signature recorded with jit compile
    events (utils/devicetelem): the padded dims + static flags that key
    the trace cache, so a recompile storm names the shape that drove it."""
    Sp, Tp = vals_p.shape
    return (f"S{Sp}xT{Tp}xW{plan.t1.shape[1]}xG{Gp}:{kind}"
            + (":ragged" if ragged else ""))


@functools.partial(jax.jit, static_argnames=(
    "num_groups", "is_counter", "is_rate", "with_drops", "interpret",
    "kind", "ragged", "per_series", "gather"))
def _run(vals_p, vbase_p, gids_p, o1, o2, l1, l2, t1, t2, n, ws, we, ts,
         idx1, idx2,
         num_groups: int, is_counter: bool, is_rate: bool,
         with_drops: bool, interpret: bool, kind: str = "rate_family",
         ragged: bool = False, per_series: bool = False,
         gather: bool = False):
    from jax.experimental.pallas import tpu as pltpu

    Sp, Tp = vals_p.shape
    Wp = t1.shape[1]
    Gp = num_groups
    # adaptive series block: the ragged rate family's scan temporaries
    # scale with bs*Tp, so long rows shrink the block instead of OOMing
    # scoped vmem (or being rejected by the eligibility gate).  All
    # shapes here are static at trace time; Sp is padded to _BS, which
    # every smaller power-of-two block divides.
    bs = pick_block(Tp, Wp, Gp, kind in OVER_TIME_FNS,
                    ragged and kind == "rate_family",
                    panels=gids_p.shape[1], gather=gather)
    if bs is None:
        if interpret:
            bs = _MIN_BS            # no scoped-vmem limit off-chip
        else:
            # fail loudly here rather than with an opaque Mosaic
            # scoped-vmem OOM at lowering: gated callers (leafexec, mesh)
            # never reach this, but direct fused_rate_groupsum users can
            raise ValueError(
                f"fused kernel shape exceeds VMEM budget at every block "
                f"size (Tp={Tp}, Wp={Wp}, Gp={Gp}, kind={kind}, "
                f"ragged={ragged}); use the general path")
    grid = Sp // bs
    space = {} if interpret else {"memory_space": pltpu.VMEM}
    row_spec = pl.BlockSpec((bs, Tp), lambda i: (i, 0), **space)
    col_spec = pl.BlockSpec((bs, 1), lambda i: (i, 0), **space)
    # gids may carry P grouping columns (multi-panel batch, merge_groups)
    gid_spec = pl.BlockSpec((bs, gids_p.shape[1]), lambda i: (i, 0), **space)
    fix = lambda shape: pl.BlockSpec(shape, lambda i: (0, 0), **space)  # noqa: E731
    kern = functools.partial(_kernel, num_groups=Gp, is_counter=is_counter,
                             is_rate=is_rate, with_drops=with_drops,
                             kind=kind, ragged=ragged, per_series=per_series,
                             gather=gather)
    with_counts = ragged                 # presence rides a second output
    if per_series:
        out_spec = pl.BlockSpec((bs, Wp), lambda i: (i, 0), **space)
        out_shape = jax.ShapeDtypeStruct((Sp, Wp), jnp.float32)
    else:
        out_spec = fix((Gp, Wp))
        out_shape = jax.ShapeDtypeStruct((Gp, Wp), jnp.float32)
    out_specs = [out_spec, out_spec] if with_counts else out_spec
    out_shapes = [out_shape, out_shape] if with_counts else out_shape
    # selection-matrix specs follow the operands' actual shapes: gather-
    # mode callers pass tiny dummies for the unused o1/o2/l1/l2, freeing
    # their ~1.5 MB of VMEM for larger series blocks
    return pl.pallas_call(
        kern,
        grid=(grid,),
        in_specs=[row_spec, col_spec, gid_spec,
                  fix(o1.shape), fix(o2.shape), fix(l1.shape),
                  fix(l2.shape),
                  fix((1, Wp)), fix((1, Wp)), fix((1, Wp)), fix((1, Wp)),
                  fix((1, Wp)), fix((1, Tp)), fix((1, Wp)), fix((1, Wp))],
        out_specs=out_specs,
        out_shape=out_shapes,
        interpret=interpret,
    )(vals_p, vbase_p, gids_p, o1, o2, l1, l2, t1, t2, n, ws, we, ts,
      idx1, idx2)


VMEM_BUDGET = 12 << 20          # per-core VMEM is ~16MB; leave headroom


def vmem_estimate(Tp: int, Wp: int, Gp: int,
                  over_time: bool = False,
                  ragged_rate: bool = False, bs: int = _BS,
                  panels: int = 1, gather: bool = False) -> int:
    """Rough resident-bytes model for one grid step: the 4 selection
    matrices (plus the over_time kinds' band temporary), the
    double-buffered values block, the group one-hot + accumulator, and
    [bs, Wp] f32 temporaries.  The ragged rate family's fill/prefix
    scans keep ~19 [bs, Tp] temporaries live (calibrated against the
    Mosaic scoped-vmem allocation report on a real v5e: 21.36 MiB at
    bs=256, Tp=768, Wp=128, Gp=1000 — the first on-chip ragged compile
    OOM'd scoped vmem where the old 8-temporary model predicted 13 MiB).
    Callers divert to the general XLA path when this exceeds VMEM_BUDGET
    instead of failing at kernel lowering; _run shrinks its series block
    (pick_block) before giving up, so the gate must test the SMALLEST
    block, not _BS."""
    # gather mode ships 4 KB dummies instead of the o1..l2 matrices
    # (the over_time band kinds still need them — gather never applies)
    sel = 4 * 8 * _LANE * 4 if gather else \
        (5 if over_time else 4) * Tp * Wp * 4
    vals = 2 * bs * Tp * 4
    if ragged_rate:
        # 19 was calibrated BEFORE _fill_scan2 halved the scan carries;
        # kept until the next on-chip window re-measures it (conservative
        # = smaller blocks than strictly needed, never an OOM)
        vals += 19 * bs * Tp * 4
    # multi-panel epilogue (merge_groups): each extra grouping column
    # builds another [Gp, bs] one-hot compare temporary feeding the
    # accumulated multi-hot — a large merged batch that fit the P=1
    # model could still exceed scoped VMEM at Mosaic lowering on-chip
    group = Gp * (Wp * 8 + bs * 4 * max(panels, 1))
    inter = 12 * bs * Wp * 4
    return sel + vals + group + inter


def pick_block(Tp: int, Wp: int, Gp: int, over_time: bool = False,
               ragged_rate: bool = False, panels: int = 1,
               gather: bool = False) -> Optional[int]:
    """Largest series-block size whose vmem_estimate fits VMEM_BUDGET
    (None when even _MIN_BS doesn't — the caller must divert to the
    general path).  The ragged rate family's scan temporaries scale with
    bs*Tp, so long rows fuse fine at a smaller block: at Tp=768 the
    dense kernel keeps bs=256 while ragged rate drops to 64 instead of
    falling off the fused path entirely."""
    bs = _BS
    while bs >= _MIN_BS:
        if vmem_estimate(Tp, Wp, Gp, over_time, ragged_rate,
                         bs=bs, panels=panels,
                         gather=gather) <= VMEM_BUDGET:
            return bs
        bs //= 2
    return None


def window_counts(ts_row: np.ndarray, wends: np.ndarray,
                  range_ms: int) -> np.ndarray:
    """Per-window sample counts over one shared grid — the single source
    of the window-inclusion convention ((wend-range, wend], matching
    build_plan and ops/timewindow.window_bounds)."""
    ts_row = np.asarray(ts_row, dtype=np.int64)
    wend = np.asarray(wends, dtype=np.int64)
    first = np.searchsorted(ts_row, wend - int(range_ms) + 1, side="left")
    last = np.searchsorted(ts_row, wend, side="right") - 1
    return np.maximum(last - first + 1, 0)


FUSABLE_FNS = ("rate", "increase", "delta", "sum_over_time",
               "avg_over_time", "last_over_time", "count_over_time",
               "min_over_time", "max_over_time")
OVER_TIME_FNS = ("sum_over_time", "avg_over_time", "last_over_time",
                 "count_over_time")
# kinds whose validity-weighted variant handles NaN-holed (ragged) rows
RAGGED_FNS = ("sum_over_time", "avg_over_time", "count_over_time")
# kinds served by the XLA reduce_window path (min-plus is not the MXU's
# semiring; reduce_window is the TPU-native windowed order-statistic)
MINMAX_FNS = ("min_over_time", "max_over_time")
FUSABLE_AGGS = ("sum", "avg", "count", "min", "max")


def can_fuse(fn_name: str, agg_op: str, shared_grid: bool,
             dense: bool) -> bool:
    """Leaf fused-path eligibility (VERDICT r2 item 2 broadened set).

    dense=False means a shared scrape grid whose VALUES have NaN holes.
    Every fusable kind now takes ragged rows (VERDICT r3 item 2): the
    over_time family is validity-weighted, min/max ride reduce_window,
    the rate family finds per-series valid boundaries with in-kernel fill
    scans, and last_over_time keeps slot/staleness semantics via a
    validity one-hot.  `dense` no longer gates anything but stays in the
    signature: callers still route on it (kernel variant selection) and
    the parameter documents the eligibility contract they must compute."""
    del dense
    return (shared_grid and agg_op in FUSABLE_AGGS
            and fn_name in FUSABLE_FNS)


# traceable entry for callers composing the kernel inside shard_map (the
# mesh executor); the jit wrapper inlines under an enclosing trace.
# idx1/idx2 optional for legacy 13-operand callers (matmul path only).
def run_kernel(vals_p, vbase_p, gids_p, o1, o2, l1, l2, t1, t2, n, ws, we,
               ts, idx1=None, idx2=None, *, gather: bool = False, **kw):
    if idx1 is None or idx2 is None:
        if gather:
            raise ValueError("gather=True requires idx1/idx2 operands")
        z = jnp.zeros((1, t1.shape[1]), jnp.float32)
        idx1 = idx2 = z
    return _run(vals_p, vbase_p, gids_p, o1, o2, l1, l2, t1, t2, n, ws, we,
                ts, idx1, idx2, gather=gather, **kw)


class PreparedInputs(NamedTuple):
    """Padded device-resident query inputs — build once per working set
    (the pad is a full [S, T] device copy; never pay it per query)."""
    vals_p: jax.Array    # [Sp, Tp] f32
    vbase_p: jax.Array   # [Sp, 1] f32
    gids_p: jax.Array    # [Sp, 1] int32 (-1 pad rows)
    gsize: np.ndarray    # [num_groups] series per group


class PaddedValues(NamedTuple):
    """The grouping-independent (and byte-dominant) half of PreparedInputs
    — cacheable once per (working set, column) across grouping variants."""
    vals_p: jax.Array    # [Sp, Tp] f32
    vbase_p: jax.Array   # [Sp, 1] f32


class PaddedGroups(NamedTuple):
    """The small grouping-dependent half — one per (by, without) variant."""
    gids_p: jax.Array    # [Sp, 1] int32 (-1 pad rows)
    gsize: np.ndarray    # [num_groups]


def pad_values(vals, vbase, plan: FusedPlan, device=None) -> PaddedValues:
    S = vals.shape[0]
    Sp = pad_series_count(S)
    if device is not None:
        # commit the inputs straight to the owning chip so the pad
        # computes (and its result lives) there — staging through
        # jnp.asarray would materialize the full [S, T] block on the
        # default device first and pay the copy twice; uncommitted
        # operands then follow the committed ones
        v = jax.device_put(np.asarray(vals, np.float32), device)
        vb = jax.device_put(np.asarray(vbase, np.float32), device)
    else:
        v = jnp.asarray(vals, jnp.float32)
        vb = jnp.asarray(vbase, jnp.float32)
    vals_p = jnp.zeros((Sp, plan.Tp), jnp.float32)
    vals_p = vals_p.at[:S, :vals.shape[1]].set(v)
    vbase_p = jnp.zeros((Sp, 1), jnp.float32)
    vbase_p = vbase_p.at[:S, 0].set(vb)
    return PaddedValues(vals_p, vbase_p)


def pad_groups(gids, S: int, num_groups: int,
               device=None) -> PaddedGroups:
    Sp = pad_series_count(S)
    gids_np = np.asarray(gids, np.int32)
    g = (jnp.asarray(gids_np) if device is None
         else jax.device_put(gids_np, device))
    gids_p = jnp.full((Sp, 1), -1, jnp.int32)
    gids_p = gids_p.at[:S, 0].set(g)
    gsize = np.bincount(gids_np, minlength=num_groups)[:num_groups]
    return PaddedGroups(gids_p, gsize)


def pad_inputs(vals, vbase, gids, plan: FusedPlan,
               num_groups: int, device=None) -> PreparedInputs:
    v = pad_values(vals, vbase, plan, device=device)
    g = pad_groups(gids, vals.shape[0], num_groups, device=device)
    return PreparedInputs(v.vals_p, v.vbase_p, g.gids_p, g.gsize)


def fused_rate_groupsum(vals, vbase, gids, plan: FusedPlan,
                        num_groups: int, fn_name: str = "rate",
                        precorrected: bool = False,
                        interpret: bool = False,
                        prepared: Optional[PreparedInputs] = None,
                        ragged: bool = False,
                        gather: Optional[bool] = None,
                        device=None
                        ) -> Tuple[jax.Array, np.ndarray]:
    """-> (sums [G, W] device array, counts [G, W] numpy).

    vals: [S, T] f32 rebased values (dense, shared grid); ignored when
    `prepared` is given.  vbase: [S] f32 per-series value base (absolute
    = rebased + vbase).  Present-count is shared across series under the
    dense/shared-grid precondition: counts[g, w] = |group g| * 1{n[w] >= 2}
    — NaN where 0, matching ops/agg.py present().  ragged=True runs the
    validity-aware kernel variant instead; counts then come back from the
    kernel's per-cell presence output.

    `device` pins every operand (values, plan mats) to that chip so the
    jit executes THERE — the per-device unit of the multi-chip dispatch
    path (parallel/mesh.py), which runs this exact function once per
    device and merges the [G, W] partials it returns.
    """
    is_counter = fn_name in ("rate", "increase")
    is_rate = fn_name == "rate"
    with_drops = is_counter and not precorrected
    over_time = fn_name in OVER_TIME_FNS
    kind = fn_name if over_time else "rate_family"
    if prepared is None:
        prepared = pad_inputs(vals, vbase, gids, plan, num_groups,
                              device=device)
    elif device is None:
        # caller-prepared inputs may already be pinned (sharded mirror
        # mode) — keep the plan matrices on the same chip
        device = _committed_device(prepared.vals_p)
    Gp = pad_group_count(num_groups)
    if gather is None:
        gather = gather_default(kind) and plan.idx1 is not None
    from filodb_tpu.utils.devicetelem import watched_call
    mats = _kernel_mats(plan, over_time, gather, device=device)
    res = watched_call(
        "fused_run", _run,
        _run_shape_sig(prepared.vals_p, plan, Gp, kind, ragged),
        lambda: _run(prepared.vals_p, prepared.vbase_p, prepared.gids_p,
                     *mats,
                     num_groups=Gp, is_counter=is_counter,
                     is_rate=is_rate, with_drops=with_drops,
                     interpret=interpret, kind=kind, ragged=ragged,
                     gather=gather),
        device=device)
    if ragged:
        sums, cnts = res
        counts = np.asarray(cnts, np.float64)[:num_groups, :plan.W]
    else:
        sums = res
        wvalid = plan.wvalid1 if over_time else plan.wvalid
        counts = prepared.gsize[:, None].astype(np.float64) * \
            wvalid[None, :].astype(np.float64)
    return sums[:num_groups, :plan.W], counts


def warmup_compile(S: int, T: int, W: int, G: int,
                   fn_name: str = "rate") -> float:
    """Compile (or cache-deserialize) the fused kernel for the canonical
    padded shape of (S series, T samples, W windows, G groups) using
    device zeros — the boot-warmup hook behind config.warmup_shapes.
    Returns wall seconds spent.  The compiled program is keyed by the
    BUCKETED shape, so any production working set in the same buckets
    hits it."""
    import time
    t0 = time.perf_counter()
    step = 10_000
    W = max(min(W, T), 1)
    ts_row = np.arange(T, dtype=np.int64) * step
    wends = ts_row[-1] - np.arange(W, dtype=np.int64)[::-1] * step
    plan = build_plan(ts_row, wends, 300_000)
    vals = jnp.zeros((S, T), jnp.float32)
    vbase = jnp.zeros((S,), jnp.float32)
    gids = (np.arange(S) % max(G, 1)).astype(np.int32)
    interpret = jax.default_backend() != "tpu"   # leafexec's gate, exactly
    sums, _ = fused_rate_groupsum(vals, vbase, gids, plan, max(G, 1),
                                  fn_name, precorrected=True,
                                  interpret=interpret)
    sums.block_until_ready()
    # also warm the general XLA path at this shape — the 20-40s-class
    # compile (BENCH_r04) the persistent cache + warmup exist for; any
    # non-fusable query over the same working-set shape hits it
    try:
        from filodb_tpu.ops import agg as agg_ops
        from filodb_tpu.ops.rangefns import evaluate_range_function
        from filodb_tpu.ops.timewindow import to_offsets

        ts_one = to_offsets(ts_row[None, :], np.full(1, T), 0)

        @jax.jit
        def _general(ts_off, v, vb, g, w):
            res = evaluate_range_function(ts_off, v, w, 300_000, fn_name,
                                          shared_grid=True, vbase=vb,
                                          precorrected=True)
            return agg_ops.aggregate("sum", res, g, max(G, 1))

        _general(jnp.asarray(ts_one), vals, vbase, jnp.asarray(gids),
                 jnp.asarray(wends.astype(np.int32))).block_until_ready()
    except Exception:  # noqa: BLE001 — fused warmup alone is still useful
        pass
    return time.perf_counter() - t0


def present_sum(sums, counts) -> np.ndarray:
    """Finish the 3-phase contract host-side: NaN where no contributors."""
    s = np.asarray(sums, np.float64)
    return np.where(counts > 0, s, np.nan)


def jit_cache_stats() -> dict:
    """Entry counts of the jitted query kernels' compile caches.  Kept
    for ad-hoc inspection; the /metrics surface no longer samples this
    at scrape time — utils/devicetelem pushes compile events in at
    compile time (watched_call around every dispatch), so events between
    scrapes or before a restart are never lost."""
    out = {}
    for name, fn in (("fused_run", _run),
                     ("fused_minmax", _fused_minmax_jit)):
        try:
            out[name] = int(fn._cache_size())
        except Exception:  # noqa: BLE001 — private jax API: best-effort
            pass
    return out


# ------------------------------------------------------- broadened leaf API
# (VERDICT r2 item 2: count/avg/min/max group-aggs, min/max_over_time via
# reduce_window, ragged/NaN working sets)

def uniform_window_geometry(ts_row: np.ndarray, wends: np.ndarray,
                            range_ms: int):
    """(first0, stride_samples, width_samples, t_needed) when every window
    covers a constant-width, constant-stride span of the (conceptually
    extended) uniform grid — the precondition for lax.reduce_window — else
    None.  Closed-form from the grid spacing, so windows hanging past the
    data's right edge (the `end=now` dashboard shape) stay uniform:
    t_needed > len(ts_row) tells the caller to NaN-pad that tail and run
    the ragged variant.  Irregular grids/steps or left-clipped windows
    fall back to the general path."""
    ts_row = np.asarray(ts_row, dtype=np.int64)
    wend = np.asarray(wends, dtype=np.int64)
    T = ts_row.size
    if wend.size == 0 or T < 2:
        return None
    d = int(ts_row[1] - ts_row[0])
    if d <= 0 or (np.diff(ts_row) != d).any():
        return None
    t0 = int(ts_row[0])
    if wend.size > 1:
        s = int(wend[1] - wend[0])
        if s <= 0 or (np.diff(wend) != s).any() or s % d:
            return None
        stride = s // d
    else:
        stride = 1
    f0 = -(-(int(wend[0]) - int(range_ms) + 1 - t0) // d)      # ceil div
    l0 = (int(wend[0]) - t0) // d
    width = l0 - f0 + 1
    if f0 < 0 or width < 1:
        return None
    t_needed = l0 + stride * (wend.size - 1) + 1
    return f0, stride, width, t_needed


def fused_minmax_agg(vals, vbase, gids, f0: int, stride: int, width: int,
                     W: int, fn_name: str, agg_op: str, num_groups: int,
                     ragged: bool):
    """Compile-watched wrapper over the jitted body (_fused_minmax_jit):
    the trace-cache delta around the call pushes compile events into the
    device telemetry ledger at compile time (utils/devicetelem)."""
    from filodb_tpu.utils.devicetelem import watched_call
    shape = (f"S{vals.shape[0]}xT{vals.shape[1]}xW{W}xG{num_groups}"
             f":{fn_name}" + (":ragged" if ragged else ""))
    return watched_call(
        "fused_minmax", _fused_minmax_jit, shape,
        lambda: _fused_minmax_jit(vals, vbase, gids, f0, stride, width,
                                  W, fn_name, agg_op, num_groups,
                                  ragged),
        device=_committed_device(vals))


@functools.partial(jax.jit, static_argnames=(
    "f0", "stride", "width", "W", "fn_name", "agg_op", "num_groups",
    "ragged"))
def _fused_minmax_jit(vals, vbase, gids, f0: int, stride: int, width: int,
                      W: int, fn_name: str, agg_op: str, num_groups: int,
                      ragged: bool):
    """min/max_over_time + group aggregation in ONE jit: a strided
    lax.reduce_window over the values (one HBM pass; the VPU's native
    windowed order-statistic) straight into the 3-phase map (segment
    reduction on the T/W-times-smaller [S, W] intermediate) with no host
    round trip.  Runs on any backend — pure XLA, no Pallas.

    vals [S, T] (absolute values = vals + vbase broadcast), gids [S].
    Returns partial components [G, W, C] per ops/agg.AGGREGATORS.
    """
    from jax import lax

    from filodb_tpu.ops import agg as agg_ops

    is_min = fn_name == "min_over_time"
    seg = vals[:, f0:f0 + stride * (W - 1) + width]
    if vbase is not None:
        seg = seg + vbase[:, None]
    init = jnp.inf if is_min else -jnp.inf
    valid = ~jnp.isnan(seg)
    x = jnp.where(valid, seg, init) if ragged else seg
    red = lax.reduce_window(
        x, init, lax.min if is_min else lax.max,
        window_dimensions=(1, width), window_strides=(1, stride),
        padding="VALID")                               # [S, W]
    if ragged:
        # absence = no VALID sample in the window, counted explicitly — a
        # sentinel check on `red` would misreport windows whose real
        # samples are themselves +/-Inf (legal float samples)
        cnt = lax.reduce_window(
            valid.astype(jnp.float32), 0.0, lax.add,
            window_dimensions=(1, width), window_strides=(1, stride),
            padding="VALID")
        red = jnp.where(cnt > 0, red, jnp.nan)
    return agg_ops.map_phase(agg_op, red, gids, num_groups)


def fused_leaf_agg(plan: FusedPlan, prepared: PreparedInputs,
                   gids: np.ndarray, num_groups: int, fn_name: str,
                   agg_op: str, precorrected: bool = False,
                   interpret: bool = False, ragged: bool = False
                   ) -> np.ndarray:
    """One fused leaf evaluation -> partial components [G, W, C] (float64,
    ops/agg.AGGREGATORS layout) for any (fusable fn, agg) combination on
    the matmul kernel path.  agg sum/avg/count ride the group matmul;
    agg min/max use the kernel's per-series output mode plus an XLA
    segment reduction (ops/agg.map_phase) on the small [S, W] result.
    Single-panel form of fused_leaf_agg_batch."""
    values = PaddedValues(prepared.vals_p, prepared.vbase_p)
    groups = PaddedGroups(prepared.gids_p, prepared.gsize)
    return fused_leaf_agg_batch(
        plan, values, [(groups, num_groups, agg_op)], fn_name,
        precorrected=precorrected, interpret=interpret, ragged=ragged,
        num_series=len(gids))[0]


def merge_groups(groups_list, num_groups_list):
    """Stack P panel groupings into one [Sp, P] gid matrix over DISJOINT
    group-id ranges (panel p's ids are offset by sum of earlier panels'
    group counts; -1 pad rows stay -1).  The kernel epilogue turns the
    columns into one multi-hot matrix, so P groupings cost ONE dispatch.
    Returns (gids_multi, offsets, total_groups)."""
    cols, offsets, off = [], [], 0
    for g, n in zip(groups_list, num_groups_list):
        col = g.gids_p[:, 0]
        cols.append(jnp.where(col >= 0, col + off, -1))
        offsets.append(off)
        off += int(n)
    return jnp.stack(cols, axis=1), offsets, off


def fused_leaf_agg_batch(plan: FusedPlan, values: PaddedValues, panels,
                         fn_name: str, precorrected: bool = False,
                         interpret: bool = False, ragged: bool = False,
                         num_series: Optional[int] = None,
                         lazy: bool = False):
    """Evaluate P aggregation panels over ONE working set in at most two
    kernel dispatches — the dashboard case (same metric + window grid,
    different `by (...)` groupings / agg ops), where the per-call
    dispatch latency dominates device time (doc/kernels.md).

    panels: [(PaddedGroups, num_groups, agg_op)].  All panels share
    (plan, values, fn_name, precorrected, ragged).  sum/avg/count panels
    merge into one group-mode run via merge_groups (disjoint id spaces,
    multi-hot epilogue); min/max panels share one per-series-mode run
    finished by per-panel XLA segment reductions; dense count panels are
    host-only math.  Returns per-panel [G, W, C] float64 components in
    input order (ops/agg.AGGREGATORS layout).

    lazy=True returns a zero-arg finisher instead: the kernel work is
    DISPATCHED before returning, but the synchronizing host readback
    waits until the finisher is called — so a multi-shard batch whose
    working sets live on different chips (sharded DeviceMirror mode)
    dispatches everything first and the chips compute concurrently."""
    is_counter = fn_name in ("rate", "increase")
    is_rate = fn_name == "rate"
    with_drops = is_counter and not precorrected
    over_time = fn_name in OVER_TIME_FNS
    kind = fn_name if over_time else "rate_family"
    wvalid = plan.wvalid1 if over_time else plan.wvalid

    gather = gather_default(kind) and plan.idx1 is not None
    # sharded DeviceMirror mode: the working set is committed to its
    # shard's chip — pin the plan matrices there too, or every dispatch
    # re-ships them from the default device (the per-call upload
    # pathology plan_device_mats exists to kill)
    device = _committed_device(values.vals_p)

    def run(gids_p, Gp, per_series):
        from filodb_tpu.utils.devicetelem import watched_call
        mats = _kernel_mats(plan, over_time, gather, device=device)
        return watched_call(
            "fused_run", _run,
            _run_shape_sig(values.vals_p, plan, Gp, kind, ragged),
            lambda: _run(values.vals_p, values.vbase_p, gids_p, *mats,
                         num_groups=Gp, is_counter=is_counter,
                         is_rate=is_rate, with_drops=with_drops,
                         interpret=interpret, kind=kind, ragged=ragged,
                         per_series=per_series, gather=gather),
            device=device)

    def dense_counts(groups):
        return groups.gsize[:, None].astype(np.float64) * \
            wvalid[None, :].astype(np.float64)

    mm_idx = [i for i, (_, _, op) in enumerate(panels)
              if op in ("sum", "avg") or (op == "count" and ragged)]
    ps_idx = [i for i, (_, _, op) in enumerate(panels)
              if op in ("min", "max")]
    bad = [op for _, _, op in panels
           if op not in ("sum", "avg", "count", "min", "max")]
    if bad:
        raise ValueError(f"unsupported fused agg {bad[0]}")

    out: list = [None] * len(panels)
    # ---- dispatch phase: every device call is issued here, nothing is
    # read back — all results below are lazy device arrays
    mm_res = offsets = None
    if mm_idx:
        gids_multi, offsets, total = merge_groups(
            [panels[i][0] for i in mm_idx], [panels[i][1] for i in mm_idx])
        Gp = pad_group_count(total)
        mm_res = run(gids_multi, Gp, per_series=False)
    ps_comps: dict = {}
    if ps_idx:
        from filodb_tpu.ops import agg as agg_ops
        S = num_series
        if S is None:
            gp0 = panels[ps_idx[0]][0].gids_p[:, 0]
            S = int(np.asarray(gp0 >= 0).sum())
        # one shared per-series run: the [S, W] output is group-agnostic
        res = run(panels[ps_idx[0]][0].gids_p, 8, per_series=True)
        if ragged:
            per_raw, pres = res
            per = jnp.where(pres[:S, :plan.W] > 0, per_raw[:S, :plan.W],
                            jnp.nan)
        else:
            per = jnp.where(jnp.asarray(wvalid)[None, :],
                            res[:S, :plan.W], jnp.nan)
        for i in ps_idx:
            groups, G, op = panels[i]
            ps_comps[i] = agg_ops.map_phase(op, per, groups.gids_p[:S, 0],
                                            G)

    # ---- finish phase: synchronizing host readbacks + assembly
    def finish():
        if mm_idx:
            if ragged:
                sums_all, cnts_all = (np.asarray(r, np.float64)
                                      for r in mm_res)
            else:
                sums_all = np.asarray(mm_res, np.float64)
                cnts_all = None
            for j, i in enumerate(mm_idx):
                groups, G, op = panels[i]
                lo = offsets[j]
                sums = sums_all[lo:lo + G, :plan.W]
                counts = (cnts_all[lo:lo + G, :plan.W] if ragged
                          else dense_counts(groups))
                if op == "count":
                    out[i] = counts[..., None]
                else:
                    out[i] = np.stack([sums * (counts > 0), counts],
                                      axis=-1)
        for i in ps_idx:
            out[i] = np.asarray(ps_comps[i], np.float64)
        for i, (groups, G, op) in enumerate(panels):
            if out[i] is None:          # dense count: pure host math
                out[i] = dense_counts(groups)[..., None]
        return out

    return finish if lazy else finish()
