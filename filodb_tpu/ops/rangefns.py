"""PromQL range functions as vectorized TPU kernels.

Each function evaluates all (series, window) cells at once over dense
[S, T] arrays — the TPU-native rebuild of the reference's per-window chunked
iterators (ref: query/.../exec/rangefn/RangeFunction.scala:86
ChunkedRangeFunction hierarchy, AggrOverTimeFunctions.scala, RateFunctions.scala).

Window convention matches the reference: a window for output step `wend`
contains samples with timestamp in [wend - range + 1, wend]; the extrapolation
boundary passed to the rate formula is wend - range (ref:
ChunkedRateFunctionBase.apply "windowStart - 1", RateFunctions.scala:176-184).

Strategies:
  - O(1)-per-window functions (sum/count/avg/stddev/rate/...) use cumulative
    sums along time + boundary gathers.
  - order-statistics functions (min/max/quantile) use a masked broadcast over
    window tiles (bounded memory), an MXU/VPU-dense pattern.
  - counter functions apply the reset-correction prefix scan first
    (ops/counter.py).
Absent results are NaN, filtered at serialization like the reference's
removal of NaN rows.
"""
from __future__ import annotations

import functools
from typing import Callable, Dict, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from filodb_tpu.ops import counter as counter_ops
from filodb_tpu.ops.timewindow import (PAD_TS, gather_at, window_bounds,
                                       windowed_cumsum_delta)


class WindowCtx(NamedTuple):
    """Precomputed per-query window state shared by all range functions."""
    ts_off: jax.Array      # i32 [S, T]
    vals: jax.Array        # f [S, T] (rebased: absolute value - vbase[s])
    valid: jax.Array       # bool [S, T]
    wstart: jax.Array      # i32 [W] inclusive
    wend: jax.Array        # i32 [W] inclusive
    first: jax.Array       # i32 [S, W]
    last: jax.Array        # i32 [S, W]
    n: jax.Array           # i32 [S, W] samples in window
    base_ms: jax.Array     # i64/f scalar: absolute ms of offset 0
    vbase: jax.Array       # f [S] per-series value base (0 when not rebased)
    # True when the host already reset-corrected counter values in f64
    # (ops/counter.rebase_values) -> the device drop scan is a no-op and
    # is skipped.  Python bool, constant-folded under jit.
    precorrected: bool = False
    # False when values may carry NaN holes (staleness markers): the rate
    # family then computes per-series VALID boundaries instead of slot
    # boundaries — upstream filters markers out of range vectors, so a NaN
    # at a window edge must not poison the rate.  Python bool, static.
    dense: bool = True


def make_ctx(ts_off: jax.Array, vals: jax.Array,
             wends: jax.Array, range_ms, base_ms=0,
             shared_grid: bool = False, vbase=None,
             precorrected: bool = False, dense: bool = True) -> WindowCtx:
    """shared_grid=True asserts every series row of ts_off is identical
    (one scrape grid — the common case); window bounds are then computed
    once from row 0 and kept [1, W], turning every downstream gather into
    a cheap column gather (see timewindow.gather_at).

    vbase is the per-series value base subtracted host-side in f64 before
    the downcast to the device dtype.  Difference-based functions (the rate
    family, stddev, deriv, ...) run directly on the rebased values — this
    is what keeps counter deltas exact in f32 even for counters >= 2^24
    (ref: rate semantics RateFunctions.scala:37-76; the reference computes
    in f64 where cancellation is benign).  Absolute-value functions add the
    base back via _absolute()."""
    wend = wends.astype(jnp.int32)
    wstart = (wend - jnp.int32(range_ms) + 1).astype(jnp.int32)
    valid = (~jnp.isnan(vals)) & (ts_off < PAD_TS)
    # NaN samples must not satisfy boundary gathers; they are masked in sums
    first, last, n = window_bounds(ts_off[:1] if shared_grid else ts_off,
                                   wstart, wend)
    if vbase is None:
        vbase = jnp.zeros(vals.shape[:1], vals.dtype)
    return WindowCtx(ts_off, vals, valid, wstart, wend, first, last, n,
                     jnp.asarray(base_ms, vals.dtype),
                     vbase.astype(vals.dtype), precorrected, dense)


def _absolute(ctx: WindowCtx) -> WindowCtx:
    """Ctx with absolute values restored (for functions whose OUTPUT is in
    absolute value space).  Precision equals shipping absolute f32 directly,
    so rebasing never regresses these functions."""
    return ctx._replace(vals=ctx.vals + ctx.vbase[:, None],
                        vbase=jnp.zeros_like(ctx.vbase))


def _counter_values(ctx: WindowCtx) -> jax.Array:
    """Reset-corrected values: free when the host pre-corrected in f64.
    ctx.vals are rebased, so the base rides along: the reset correction
    adds the full previous RAW value (prev + vbase)."""
    return ctx.vals if ctx.precorrected \
        else counter_ops.counter_correct(ctx.vals, ctx.vbase)


def _cumsum(x: jax.Array) -> jax.Array:
    return jnp.cumsum(x, axis=1)


def _masked(ctx: WindowCtx, arr: Optional[jax.Array] = None) -> jax.Array:
    a = ctx.vals if arr is None else arr
    return jnp.where(ctx.valid, a, 0.0)


def _nan_where(cond: jax.Array, x: jax.Array) -> jax.Array:
    return jnp.where(cond, x, jnp.nan)


# --------------------------------------------------------------- extrapolation

def extrapolated_rate(window_start, window_end, n, t1, v1, t2, v2,
                      is_counter: bool, is_rate: bool,
                      v1_abs=None) -> jax.Array:
    """Vectorized Prometheus extrapolation (semantics of ref:
    RateFunctions.scala:37-76 extrapolatedRate; all args [S, W] except the
    window bounds which broadcast [W]).  v1_abs is the ABSOLUTE first value
    for the counter-started-at-zero heuristic when v1/v2 are rebased; the
    heuristic only gates a threshold so f32 absolute precision suffices."""
    dur_start = (t1 - window_start) / 1000.0
    dur_end = (window_end - t2) / 1000.0
    sampled = (t2 - t1) / 1000.0
    avg_between = sampled / (n - 1.0)
    delta = v2 - v1
    if is_counter:
        va = v1 if v1_abs is None else v1_abs
        dur_zero = sampled * (va / jnp.where(delta == 0, jnp.inf, delta))
        take_zero = (delta > 0) & (va >= 0) & (dur_zero < dur_start)
        dur_start = jnp.where(take_zero, dur_zero, dur_start)
    threshold = avg_between * 1.1
    extrap = sampled
    extrap = extrap + jnp.where(dur_start < threshold, dur_start, avg_between / 2)
    extrap = extrap + jnp.where(dur_end < threshold, dur_end, avg_between / 2)
    scaled = delta * (extrap / sampled)
    if is_rate:
        return scaled / (window_end - window_start) * 1000.0
    return scaled


def _valid_bounds(ctx: WindowCtx):
    """Per-series first/last VALID sample index in each window + valid
    count, for the NaN-skipping rate-family boundaries on ragged data
    (upstream drops staleness markers from range vectors before the rate
    math).  Running scans over the validity mask turn the per-window
    search into two column gathers:

      lastrun[s, t]  = newest valid index <= t   (cummax over iota)
      firstrun[s, t] = oldest valid index >= t   (reverse cummin)

    Returns (firstv [S,W], lastv [S,W], nv [S,W], lastrun [S,T]); callers
    mask with nv >= k, which also covers windows whose nearest valid
    samples lie outside the slot bounds."""
    T = ctx.vals.shape[1]
    iota = jnp.arange(T, dtype=jnp.int32)[None, :]
    lastrun = jax.lax.cummax(jnp.where(ctx.valid, iota, jnp.int32(-1)),
                             axis=1)
    firstrun = jnp.flip(jax.lax.cummin(
        jnp.flip(jnp.where(ctx.valid, iota, jnp.int32(T)), axis=1),
        axis=1), axis=1)
    lastv = gather_at(lastrun, ctx.last)
    firstv = gather_at(firstrun, ctx.first)
    nv = windowed_cumsum_delta(
        _cumsum(ctx.valid.astype(ctx.vals.dtype)), ctx.first, ctx.last,
        ctx.n).astype(jnp.int32)
    return firstv, lastv, nv, lastrun


def _rate_family(ctx: WindowCtx, is_counter: bool, is_rate: bool) -> jax.Array:
    vals = _counter_values(ctx) if is_counter else ctx.vals
    if ctx.dense:
        first, last, n = ctx.first, ctx.last, ctx.n
    else:
        first, last, n, _ = _valid_bounds(ctx)
    t1 = gather_at(ctx.ts_off, first).astype(vals.dtype)
    t2 = gather_at(ctx.ts_off, last).astype(vals.dtype)
    v1 = gather_at(vals, first)
    v2 = gather_at(vals, last)
    # boundary per ChunkedRateFunctionBase: windowStart - 1 == wend - range
    wstart_x = (ctx.wstart - 1).astype(vals.dtype)[None, :]
    wend_x = ctx.wend.astype(vals.dtype)[None, :]
    v1_abs = v1 + ctx.vbase[:, None] if is_counter else None
    out = extrapolated_rate(wstart_x, wend_x, n.astype(vals.dtype),
                            t1, v1, t2, v2, is_counter, is_rate,
                            v1_abs=v1_abs)
    return _nan_where(n >= 2, out)


def rate(ctx: WindowCtx) -> jax.Array:
    return _rate_family(ctx, True, True)


def increase(ctx: WindowCtx) -> jax.Array:
    return _rate_family(ctx, True, False)


def delta_fn(ctx: WindowCtx) -> jax.Array:
    return _rate_family(ctx, False, False)


def _instant_pair(ctx: WindowCtx):
    """(last, prev, ok): the newest two sample indices in each window for
    irate/idelta — slot math when dense, last two VALID samples when the
    data may hold staleness-marker NaNs."""
    if ctx.dense:
        return (ctx.last, ctx.last - 1,
                (ctx.n >= 2) & (ctx.last - 1 >= ctx.first))
    _, lastv, nv, lastrun = _valid_bounds(ctx)
    prev = gather_at(lastrun, jnp.maximum(lastv - 1, 0))
    return lastv, prev, nv >= 2


def irate(ctx: WindowCtx) -> jax.Array:
    vals = _counter_values(ctx)
    last, prev, ok = _instant_pair(ctx)
    t2 = gather_at(ctx.ts_off, last).astype(vals.dtype)
    t1 = gather_at(ctx.ts_off, prev).astype(vals.dtype)
    v2 = gather_at(vals, last)
    v1 = gather_at(vals, prev)
    out = (v2 - v1) / ((t2 - t1) / 1000.0)
    return _nan_where(ok, out)


def idelta(ctx: WindowCtx) -> jax.Array:
    last, prev, ok = _instant_pair(ctx)
    t2 = gather_at(ctx.ts_off, last).astype(ctx.vals.dtype)
    t1 = gather_at(ctx.ts_off, prev).astype(ctx.vals.dtype)
    v2 = gather_at(ctx.vals, last)
    v1 = gather_at(ctx.vals, prev)
    return _nan_where(ok, v2 - v1)


# ------------------------------------------------------------- over_time / sums

def _valid_count(ctx: WindowCtx) -> jax.Array:
    """Per-window count of VALID (non-NaN) samples — the presence gate for
    the value-summing functions.  A window whose grid slots exist but whose
    values are all NaN is ABSENT for sum/avg/min/..., matching the
    reference's NaN-skipping accumulators that start at NaN (ref:
    AggrOverTimeFunctions.scala:153-165 SumOverTimeChunkedFunctionD), while
    count_over_time emits 0 there (ref: :367-382)."""
    return windowed_cumsum_delta(_cumsum(ctx.valid.astype(ctx.vals.dtype)),
                                 ctx.first, ctx.last, ctx.n)


def sum_over_time(ctx: WindowCtx) -> jax.Array:
    s = windowed_cumsum_delta(_cumsum(_masked(ctx)), ctx.first, ctx.last, ctx.n)
    return _nan_where(_valid_count(ctx) > 0, s)


def count_over_time(ctx: WindowCtx) -> jax.Array:
    c = _valid_count(ctx)
    return _nan_where(ctx.n > 0, c)


def avg_over_time(ctx: WindowCtx) -> jax.Array:
    s = windowed_cumsum_delta(_cumsum(_masked(ctx)), ctx.first, ctx.last, ctx.n)
    c = _valid_count(ctx)
    return _nan_where(c > 0, s / jnp.maximum(c, 1.0))


def _var_over_time(ctx: WindowCtx) -> Tuple[jax.Array, jax.Array]:
    s = windowed_cumsum_delta(_cumsum(_masked(ctx)), ctx.first, ctx.last, ctx.n)
    s2 = windowed_cumsum_delta(_cumsum(_masked(ctx, ctx.vals * ctx.vals)),
                               ctx.first, ctx.last, ctx.n)
    c = _valid_count(ctx)
    cs = jnp.maximum(c, 1.0)
    mean = s / cs
    var = jnp.maximum(s2 / cs - mean * mean, 0.0)
    return var, c


def stdvar_over_time(ctx: WindowCtx) -> jax.Array:
    var, c = _var_over_time(ctx)
    return _nan_where((ctx.n > 0) & (c > 0.5), var)


def stddev_over_time(ctx: WindowCtx) -> jax.Array:
    var, c = _var_over_time(ctx)
    return _nan_where((ctx.n > 0) & (c > 0.5), jnp.sqrt(var))


def last_over_time(ctx: WindowCtx) -> jax.Array:
    return _nan_where(ctx.n > 0, gather_at(ctx.vals, ctx.last))


def timestamp_fn(ctx: WindowCtx) -> jax.Array:
    """Timestamp of each series' last VALID sample in the window.  Slot
    presence is not enough: a ragged series whose freshest grid slots are
    NaN holes has no sample there, and fabricating the hole's time would
    keep a dead series alive past the lookback (review r3).  Running max
    of valid-sample times, gathered at the window boundary."""
    tsb = jnp.broadcast_to(ctx.ts_off, ctx.vals.shape).astype(ctx.vals.dtype)
    vt = jnp.where(ctx.valid, tsb, -jnp.inf)
    run = jax.lax.cummax(vt, axis=1)               # [S, T]
    t = gather_at(run, ctx.last)                   # [S, W] (column gather)
    in_window = (t >= jnp.broadcast_to(
        ctx.wstart[None, :].astype(ctx.vals.dtype), t.shape)) \
        & jnp.isfinite(t) & (_n_full(ctx) > 0)
    return _nan_where(in_window, (t + ctx.base_ms) / 1000.0)


def _n_full(ctx: WindowCtx) -> jax.Array:
    """ctx.n broadcast to [S, W] — under shared_grid the bounds stay [1, W],
    but functions whose OUTPUT derives only from n must still return [S, W]."""
    return jnp.broadcast_to(ctx.n, (ctx.vals.shape[0], ctx.n.shape[-1]))


def absent_over_time(ctx: WindowCtx) -> jax.Array:
    n = _n_full(ctx)
    return jnp.where(n == 0, 1.0, jnp.nan).astype(ctx.vals.dtype)


def present_over_time(ctx: WindowCtx) -> jax.Array:
    n = _n_full(ctx)
    return jnp.where(n > 0, 1.0, jnp.nan).astype(ctx.vals.dtype)


# ------------------------------------------------ pairwise-indicator functions

def _pair_indicator_window(ctx: WindowCtx, indicator: jax.Array) -> jax.Array:
    """Sum indicator[t] (attributed to pair (prev_valid, t)) for pairs whose
    BOTH members are valid samples inside the window — Prometheus
    changes()/resets() start fresh at the window's first valid sample, so a
    pair reaching back past the window start (including across a leading NaN
    gap) must not count.  Sum over indices strictly after the first valid
    in-window sample: cum[last] - cum[first_valid]."""
    cum = _cumsum(indicator)
    cv = jnp.cumsum(ctx.valid.astype(jnp.int32), axis=1)     # [S, T]
    rank_before = jnp.where(ctx.first > 0,
                            gather_at(cv, ctx.first - 1), 0)  # [S, W]
    # index of the (rank_before+1)-th valid sample = first valid in window
    first_valid = jax.vmap(
        lambda cv_row, tgt: jnp.searchsorted(cv_row, tgt, side="left")
    )(cv, rank_before + 1)
    nvalid = gather_at(cv, ctx.last) - rank_before
    hi = gather_at(cum, ctx.last)
    lo = gather_at(cum, first_valid)
    return jnp.where(nvalid >= 2, hi - lo, 0.0)


def resets(ctx: WindowCtx) -> jax.Array:
    # detect on the VALUE ordering (v < prev), not on drops()'s correction
    # AMOUNT — the amount is the previous raw value, which on rebased rows
    # can be <= 0 even at a genuine reset
    prev = counter_ops._prev_valid(ctx.vals)
    ind = (ctx.valid & ~jnp.isnan(prev)
           & (ctx.vals < prev)).astype(ctx.vals.dtype)
    return _nan_where(ctx.n > 0, _pair_indicator_window(ctx, ind))


def changes(ctx: WindowCtx) -> jax.Array:
    prev = counter_ops._prev_valid(ctx.vals)
    ind = (ctx.valid & ~jnp.isnan(prev) & (ctx.vals != prev)).astype(ctx.vals.dtype)
    return _nan_where(ctx.n > 0, _pair_indicator_window(ctx, ind))


# ------------------------------------------------------- regression functions

def _linreg(ctx: WindowCtx) -> Tuple[jax.Array, jax.Array]:
    """Least-squares slope+intercept over (t seconds relative to window end,
    value) like Prometheus deriv/predict_linear."""
    t_sec = jnp.where(ctx.valid,
                      ctx.ts_off.astype(ctx.vals.dtype) / 1000.0, 0.0)
    v = _masked(ctx)
    n = jnp.maximum(windowed_cumsum_delta(
        _cumsum(ctx.valid.astype(ctx.vals.dtype)), ctx.first, ctx.last, ctx.n), 1.0)
    st = windowed_cumsum_delta(_cumsum(t_sec), ctx.first, ctx.last, ctx.n)
    sv = windowed_cumsum_delta(_cumsum(v), ctx.first, ctx.last, ctx.n)
    stt = windowed_cumsum_delta(_cumsum(t_sec * t_sec), ctx.first, ctx.last, ctx.n)
    stv = windowed_cumsum_delta(_cumsum(t_sec * v), ctx.first, ctx.last, ctx.n)
    denom = n * stt - st * st
    slope = (n * stv - st * sv) / jnp.where(denom == 0, jnp.nan, denom)
    intercept = (sv - slope * st) / n
    return slope, intercept


def deriv(ctx: WindowCtx) -> jax.Array:
    slope, _ = _linreg(ctx)
    return _nan_where(ctx.n >= 2, slope)


def predict_linear(ctx: WindowCtx, t_ahead_s: float) -> jax.Array:
    slope, intercept = _linreg(ctx)
    at = ctx.wend.astype(ctx.vals.dtype)[None, :] / 1000.0 + t_ahead_s
    return _nan_where(ctx.n >= 2, slope * at + intercept)


def z_score(ctx: WindowCtx) -> jax.Array:
    var, c = _var_over_time(ctx)
    s = windowed_cumsum_delta(_cumsum(_masked(ctx)), ctx.first, ctx.last, ctx.n)
    mean = s / c
    lastv = gather_at(ctx.vals, ctx.last)
    std = jnp.sqrt(var)
    # std == 0 (e.g. single sample): 0/0 — NaN, not +/-inf from rounding
    return _nan_where((ctx.n > 0) & (std > 0), (lastv - mean) / std)


# ----------------------------------------------- masked-broadcast reductions

def _window_tile_reduce(ctx: WindowCtx, reducer: Callable[[jax.Array, jax.Array], jax.Array],
                        tile_elems: int = 1 << 26) -> jax.Array:
    """Evaluate reducer(masked_vals [S, wt, T], mask) over window tiles.
    Memory bounded to ~tile_elems array cells per tile."""
    S, T = ctx.vals.shape
    W = ctx.wend.shape[0]
    wt = max(1, min(W, tile_elems // max(S * T, 1)))
    n_tiles = -(-W // wt)
    pad = n_tiles * wt - W
    ws = jnp.pad(ctx.wstart, (0, pad)).reshape(n_tiles, wt)
    we = jnp.pad(ctx.wend, (0, pad), constant_values=-(1 << 30)).reshape(n_tiles, wt)

    def tile(args):
        ws_t, we_t = args
        in_win = ((ctx.ts_off[:, None, :] >= ws_t[None, :, None])
                  & (ctx.ts_off[:, None, :] <= we_t[None, :, None])
                  & ctx.valid[:, None, :])
        return reducer(ctx.vals[:, None, :], in_win)

    out = jax.lax.map(tile, (ws, we))          # [n_tiles, S, wt]
    out = jnp.moveaxis(out, 0, 1).reshape(S, n_tiles * wt)
    return out[:, :W]


def min_over_time(ctx: WindowCtx) -> jax.Array:
    r = _window_tile_reduce(
        ctx, lambda v, m: jnp.min(jnp.where(m, v, jnp.inf), axis=-1))
    # absence = zero VALID samples (the reference accumulator starts NaN
    # and skips only NaN) — counted explicitly so windows whose real
    # samples are +/-Inf still emit their inf, not absent
    return _nan_where(_valid_count(ctx) > 0, r)


def max_over_time(ctx: WindowCtx) -> jax.Array:
    r = _window_tile_reduce(
        ctx, lambda v, m: jnp.max(jnp.where(m, v, -jnp.inf), axis=-1))
    return _nan_where(_valid_count(ctx) > 0, r)


def _masked_quantile(vals: jax.Array, mask: jax.Array, q: float) -> jax.Array:
    """Linear-interpolated quantile of masked values along the last axis.
    vals broadcastable to mask's shape; invalid cells sort to +inf past the
    valid prefix."""
    big = jnp.where(mask, vals, jnp.inf)
    srt = jnp.sort(big, axis=-1)
    cnt = jnp.sum(mask, axis=-1).astype(srt.dtype)
    rank = q * (cnt - 1.0)
    lo = jnp.floor(rank).astype(jnp.int32)
    hi = jnp.ceil(rank).astype(jnp.int32)
    frac = rank - lo.astype(srt.dtype)
    vlo = jnp.take_along_axis(srt, jnp.maximum(lo, 0)[..., None], axis=-1)[..., 0]
    vhi = jnp.take_along_axis(srt, jnp.maximum(hi, 0)[..., None], axis=-1)[..., 0]
    return vlo + (vhi - vlo) * frac


def quantile_over_time(ctx: WindowCtx, q: float) -> jax.Array:
    r = _window_tile_reduce(
        ctx, lambda v, m: _masked_quantile(jnp.broadcast_to(v, m.shape), m, q))
    if not 0.0 <= q <= 1.0:
        # _n_full, not ctx.n: under shared_grid the bounds stay [1, W] but
        # the output must be per-series
        return jnp.where(_n_full(ctx) > 0,
                         jnp.inf if q > 1 else -jnp.inf, jnp.nan).astype(ctx.vals.dtype)
    return _nan_where(ctx.n > 0, r)


def mad_over_time(ctx: WindowCtx) -> jax.Array:
    """Median absolute deviation: median(|x - median(x)|) over the window
    (ref: query/.../exec/rangefn/AggrOverTimeFunctions.scala MedianAbsoluteDeviation).
    Shift-invariant, so it runs on rebased values — exact in f32 even for
    large-magnitude series."""
    def reducer(v, m):
        vb = jnp.broadcast_to(v, m.shape)
        med = _masked_quantile(vb, m, 0.5)
        dev = jnp.abs(vb - med[..., None])
        return _masked_quantile(dev, m, 0.5)
    r = _window_tile_reduce(ctx, reducer)
    return _nan_where(ctx.n > 0, r)


def holt_winters(ctx: WindowCtx, sf: float, tf: float) -> jax.Array:
    """Double exponential smoothing (ref: AggrOverTimeFunctions.scala holt-winters).
    Sequential per window -> scan over time inside a window tile."""
    # upstream rejects out-of-range factors instead of smoothing with a
    # divergent recurrence (prometheus functions.go funcHoltWinters:
    # sf must be in (0, 1) exclusive, tf in (0, 1] — tf == 1 is legal)
    if not 0 < sf < 1:
        raise ValueError(
            f"invalid smoothing factor {sf}: expected 0 < sf < 1")
    if not 0 < tf <= 1:
        raise ValueError(
            f"invalid trend factor {tf}: expected 0 < tf <= 1")
    def reducer(v, m):
        # v: [S, wt, T] broadcastable, m: [S, wt, T].  Prometheus recurrence:
        # s1 := x0; b := x1 - x0; then for i >= 1:
        #   b    = i==1 ? b : tf*(s_prev - s_prev2) + (1-tf)*b     (trend FIRST,
        #                       from the previous two smoothed values)
        #   s    = sf*x_i + (1-sf)*(s_prev + b)
        vb = jnp.broadcast_to(v, m.shape)

        def step(carry, xt):
            s_prev2, s_prev, b_prev, cnt = carry
            x, valid = xt
            b_eff = jnp.where(cnt == 1, x - s_prev,
                              tf * (s_prev - s_prev2) + (1 - tf) * b_prev)
            s_new = sf * x + (1 - sf) * (s_prev + b_eff)
            s_upd = jnp.where(cnt == 0, x, s_new)
            b_upd = jnp.where(cnt == 0, jnp.zeros_like(x), b_eff)
            s_prev2_out = jnp.where(valid, s_prev, s_prev2)
            s_out = jnp.where(valid, s_upd, s_prev)
            b_out = jnp.where(valid, b_upd, b_prev)
            cnt_out = cnt + valid.astype(jnp.int32)
            return (s_prev2_out, s_out, b_out, cnt_out), None

        init = (jnp.zeros(m.shape[:-1], v.dtype),
                jnp.zeros(m.shape[:-1], v.dtype),
                jnp.zeros(m.shape[:-1], v.dtype),
                jnp.zeros(m.shape[:-1], jnp.int32))
        (_, s_fin, _, cnt), _ = jax.lax.scan(
            step, init, (jnp.moveaxis(vb, -1, 0), jnp.moveaxis(m, -1, 0)))
        return jnp.where(cnt >= 2, s_fin, jnp.nan)
    r = _window_tile_reduce(ctx, reducer)
    return _nan_where(ctx.n >= 2, r)


# ------------------------------------------------------------------ dispatch

class RangeFnSpec(NamedTuple):
    fn: Callable
    needs_params: int = 0       # number of scalar params consumed
    is_counter: bool = False
    # output lives in absolute value space -> re-add the per-series base.
    # Difference-/shape-based functions (rate family, stddev, deriv, changes,
    # z_score, ...) are shift-invariant and run on rebased values directly,
    # which is exactly where the f32 precision win lives.
    absolute: bool = False


RANGE_FUNCTIONS: Dict[str, RangeFnSpec] = {
    "rate": RangeFnSpec(rate, is_counter=True),
    "increase": RangeFnSpec(increase, is_counter=True),
    "delta": RangeFnSpec(delta_fn),
    "irate": RangeFnSpec(irate, is_counter=True),
    "idelta": RangeFnSpec(idelta),
    "resets": RangeFnSpec(resets),
    "changes": RangeFnSpec(changes),
    "deriv": RangeFnSpec(deriv),
    "predict_linear": RangeFnSpec(predict_linear, needs_params=1,
                                  absolute=True),
    "sum_over_time": RangeFnSpec(sum_over_time, absolute=True),
    "count_over_time": RangeFnSpec(count_over_time),
    "avg_over_time": RangeFnSpec(avg_over_time, absolute=True),
    "min_over_time": RangeFnSpec(min_over_time, absolute=True),
    "max_over_time": RangeFnSpec(max_over_time, absolute=True),
    "stddev_over_time": RangeFnSpec(stddev_over_time),
    "stdvar_over_time": RangeFnSpec(stdvar_over_time),
    "last_over_time": RangeFnSpec(last_over_time, absolute=True),
    "quantile_over_time": RangeFnSpec(quantile_over_time, needs_params=1,
                                      absolute=True),
    "holt_winters": RangeFnSpec(holt_winters, needs_params=2, absolute=True),
    "z_score": RangeFnSpec(z_score),
    "mad_over_time": RangeFnSpec(mad_over_time),
    "timestamp": RangeFnSpec(timestamp_fn),
    "absent_over_time": RangeFnSpec(absent_over_time),
    "present_over_time": RangeFnSpec(present_over_time),
}


def evaluate_range_function(ts_off: jax.Array, vals: jax.Array,
                            wends: jax.Array, range_ms,
                            fn_name: Optional[str],
                            params: Tuple[float, ...] = (),
                            base_ms=0, shared_grid: bool = False,
                            vbase=None, precorrected: bool = False,
                            dense: bool = True) -> jax.Array:
    """The fused leaf kernel: window bounds + range function in one jit.

    fn_name None means plain periodic samples (instant-vector selector):
    last sample within the stale-lookback window, which callers express by
    passing range_ms = lookback and fn_name = 'last_over_time'.
    shared_grid: all ts_off rows identical -> column-gather fast path.

    base_ms crosses the jit boundary as float: epoch-ms magnitudes overflow
    int32 canonicalization on TPU (no x64).  On f32 backends an epoch base
    rounds to ~2-minute granularity, so the only consumer needing exact
    epoch values — timestamp_fn — is fed base_ms=0 by PeriodicSamplesMapper,
    which re-adds the base host-side in f64.  Tracer/array inputs
    (mesh-inner calls already under jit) pass through untouched.
    """
    if isinstance(base_ms, (int, float)):
        base_ms = float(base_ms)
    if vbase is None:
        vbase = jnp.zeros(vals.shape[:1], vals.dtype)
    return _evaluate_range_function(ts_off, vals, wends, range_ms,
                                    base_ms, vbase, fn_name, params,
                                    shared_grid, precorrected, dense)


@functools.partial(jax.jit,
                   static_argnames=("fn_name", "params", "shared_grid",
                                    "precorrected", "dense"))
def _evaluate_range_function(ts_off, vals, wends, range_ms, base_ms,
                             vbase, fn_name, params, shared_grid,
                             precorrected, dense):
    ctx = make_ctx(ts_off, vals, wends, range_ms, base_ms, shared_grid,
                   vbase, precorrected, dense)
    name = fn_name or "last_over_time"
    spec = RANGE_FUNCTIONS[name]
    if spec.absolute:
        ctx = _absolute(ctx)
    if spec.needs_params:
        return spec.fn(ctx, *params[: spec.needs_params])
    return spec.fn(ctx)
