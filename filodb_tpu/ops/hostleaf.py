"""Host numpy evaluation of small fused-leaf working sets.

On-chip, a leaf query pays a ~65 ms dispatch floor regardless of size
(TPU_CHAIN_r05.json intercepts), so an 8k-series dashboard panel that
host numpy evaluates in single-digit ms is ~10x slower on the chip —
bench r5's `vs_iterator_c = 0.7` at 8k made the crossover explicit.
This module is the host side of the cost-based router (round-5 verdict
item 6): the same (fusable fn x agg) set as `ops/pallas_fused`, dense
shared-grid working sets only, computed with vectorized numpy straight
from the FusedPlan's indices.  Ragged/histogram sets stay on the device
paths.  Semantics mirror the kernel bit-for-bit in structure (same
boundary indices, same extrapolation formula, f64 math — strictly more
precise than the f32 kernel; ref: RateFunctions.scala:37-76,
AggrOverTimeFunctions.scala).

The routing decision lives in leafexec._try_fused (threshold:
query.host_route_max_samples) and is observable via the
`leaf_host_routed` counter and the explain tree's `route=host` tag.
"""
from __future__ import annotations

import contextlib
import threading

import numpy as np

# ------------------------------------------------- batch gather memo (PR 17)
#
# Under engine.query_range_batch, N panels over one working set each ran
# the SAME per-shard windowed host gather AND its post-processing during
# their fused preflight (the PR 6 deferred host-route inefficiency): the
# scan + to_offsets + rebase_values/host_counter_correct chain is keyed
# by (dataset, shard, chunk span, column, correction mode, row set, keys
# epoch), all identical across the merged set — and the counter
# correction alone costs more than the scan.  The engine opens this
# scope around the batch's prepare phase; leafexec._do_execute consults
# it so the working set is scanned and corrected ONCE and the processed
# (ts_off, vals, vbase, counts, dense) arrays are shared — safe because
# every downstream consumer (the host/kernel fused paths and the general
# transformers) reads them immutably; none writes in place.  Scope is
# thread-local: concurrent batches on other threads never see each
# other's entries, and outside a scope the memo is inert (zero overhead
# on the single-query path).

_MEMO = threading.local()


@contextlib.contextmanager
def batch_gather_memo():
    """Scope the per-shard gather memo over one batch's prepare phase."""
    prev = getattr(_MEMO, "entries", None)
    _MEMO.entries = {}
    try:
        yield
    finally:
        _MEMO.entries = prev


def memo_get(key):
    entries = getattr(_MEMO, "entries", None)
    if entries is None:
        return None
    hit = entries.get(key)
    if hit is not None:
        from filodb_tpu.utils.metrics import registry
        registry.counter("leaf_gather_memo_hits").increment()
    return hit


def memo_put(key, value) -> None:
    entries = getattr(_MEMO, "entries", None)
    if entries is not None:
        entries[key] = value


def host_leaf_agg(plan, vals: np.ndarray, vbase, gids: np.ndarray,
                  num_groups: int, fn_name: str, agg_op: str) -> np.ndarray:
    """-> partial components [G, W, C] (float64, ops/agg.AGGREGATORS
    layout) for a dense shared-grid working set.  `plan` is a
    pallas_fused.FusedPlan; vals [S, T] rebased f32/f64; vbase [S] or
    None."""
    S = vals.shape[0]
    W = plan.W
    v = np.asarray(vals, np.float64)
    vb = (np.zeros(S) if vbase is None
          else np.asarray(vbase, np.float64))
    idx1 = plan.idx1[0, :W].astype(np.int64)
    idx2 = plan.idx2[0, :W].astype(np.int64)
    n1 = plan.n1[0, :W].astype(np.float64)

    over_time = fn_name in ("sum_over_time", "avg_over_time",
                            "count_over_time", "last_over_time")
    if fn_name == "last_over_time":
        per = v[:, idx2] + vb[:, None]
        per = np.where(plan.wvalid1[None, :], per, np.nan)
    elif over_time:
        cs = np.cumsum(np.concatenate(
            [np.zeros((S, 1)), v], axis=1), axis=1)       # exclusive
        s = cs[:, idx2 + 1] - cs[:, idx1]
        if fn_name == "sum_over_time":
            per = s + vb[:, None] * n1[None, :]
        elif fn_name == "avg_over_time":
            per = s / np.maximum(n1[None, :], 1.0) + vb[:, None]
        else:                                             # count_over_time
            per = np.broadcast_to(n1[None, :], (S, W)).copy()
        per = np.where(plan.wvalid1[None, :], per, np.nan)
    elif fn_name in ("min_over_time", "max_over_time"):
        red = np.minimum if fn_name == "min_over_time" else np.maximum
        per = np.empty((S, W))
        av = v + vb[:, None]
        for w in range(W):                                # W is small
            per[:, w] = red.reduce(av[:, idx1[w]:idx2[w] + 1], axis=1) \
                if idx2[w] >= idx1[w] else np.nan
        per = np.where(plan.wvalid1[None, :], per, np.nan)
    else:
        # rate family (precorrected dense): the kernel's formula, f64
        t1 = plan.t1[0, :W].astype(np.float64)
        t2 = plan.t2[0, :W].astype(np.float64)
        n = plan.n[0, :W].astype(np.float64)
        ws = plan.wstart_x[0, :W].astype(np.float64)
        we = plan.wend_x[0, :W].astype(np.float64)
        v1 = v[:, idx1]
        v2 = v[:, idx2]
        dur_start = (t1 - ws) / 1000.0
        dur_end = (we - t2) / 1000.0
        sampled = np.maximum((t2 - t1) / 1000.0, 1e-9)
        avg_between = sampled / (n - 1.0)
        delta = v2 - v1
        if fn_name in ("rate", "increase"):
            va = v1 + vb[:, None]
            with np.errstate(invalid="ignore", divide="ignore"):
                dur_zero = sampled * (va / np.where(delta == 0.0, np.inf,
                                                    delta))
            take = (delta > 0) & (va >= 0) & (dur_zero < dur_start)
            dur_start = np.where(take, dur_zero, dur_start)
        threshold = avg_between * 1.1
        extrap = sampled \
            + np.where(dur_start < threshold, dur_start, avg_between / 2) \
            + np.where(dur_end < threshold, dur_end, avg_between / 2)
        per = delta * (extrap / sampled)
        if fn_name == "rate":
            per = per / np.maximum(we - ws, 1.0) * 1000.0
        per = np.where(plan.wvalid[None, :], per, np.nan)

    # 3-phase map IN NUMPY (agg.map_phase is jitted — it would dispatch
    # to the chip and defeat the routing): same component layout and
    # combiner semantics as ops/agg.AGGREGATORS
    present = ~np.isnan(per)
    zeroed = np.where(present, per, 0.0)
    cnt = present.astype(np.float64)
    G = num_groups

    def seg_sum(x):
        out = np.zeros((G,) + x.shape[1:])
        np.add.at(out, gids, x)             # S x W small by routing gate
        return out

    def seg_ext(x, red, init):
        out = np.full((G,) + x.shape[1:], init)
        red.at(out, gids, x)
        return out

    if agg_op in ("sum", "avg"):
        comp = np.stack([seg_sum(zeroed), seg_sum(cnt)], axis=-1)
    elif agg_op == "count":
        comp = seg_sum(cnt)[..., None]
    elif agg_op == "min":
        comp = np.stack([seg_ext(np.where(present, per, np.inf),
                                 np.minimum, np.inf),
                         seg_ext(cnt, np.maximum, -np.inf)], axis=-1)
    elif agg_op == "max":
        comp = np.stack([seg_ext(np.where(present, per, -np.inf),
                                 np.maximum, -np.inf),
                         seg_ext(cnt, np.maximum, -np.inf)], axis=-1)
    else:
        raise ValueError(f"host route: unsupported agg {agg_op}")
    return comp
