"""Cross-series aggregation kernels with the 3-phase map/reduce/present contract.

The reference distributes aggregations as AggregateMapReduce at leaves,
ReduceAggregateExec at intermediates, and AggregatePresenter at the root
(ref: query/.../exec/AggrOverRangeVectors.scala:17-125,
exec/aggregator/RowAggregator.scala:140, doc/query-engine.md:311-330).
The TPU rebuild keeps exactly that contract so partial aggregates can ride
mesh collectives: `map_phase` produces component arrays [G, W, C] per shard,
`reduce_phase` combines them (psum/pmin/pmax across the shard mesh axis),
and `present` finishes (divide for avg, sqrt for stddev, ...).

Group ids are computed host-side from `by`/`without` label hashing; NaN
values mean 'series absent at this step' and never contribute.
"""
from __future__ import annotations

import functools
from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp


class AggSpec(NamedTuple):
    num_components: int
    # one op for every component, or a per-component tuple
    # ('sum' | 'min' | 'max')
    combiner: object


AGGREGATORS: Dict[str, AggSpec] = {
    "sum":    AggSpec(2, "sum"),     # (sum, count) — count masks empty steps
    "count":  AggSpec(1, "sum"),
    "avg":    AggSpec(2, "sum"),     # (sum, count)
    # min/max carry an explicit presence flag (combined with max = OR):
    # the +/-inf identity alone cannot mark absence because +/-Inf are
    # legal sample values the result must preserve
    "min":    AggSpec(2, ("min", "max")),   # (min-or-+inf, present)
    "max":    AggSpec(2, ("max", "max")),   # (max-or--inf, present)
    "stddev": AggSpec(3, "sum"),     # (sum, sumsq, count)
    "stdvar": AggSpec(3, "sum"),
    "group":  AggSpec(1, "max"),     # group() = 1 for any present series
    "hist_sum": AggSpec(0, "sum"),   # [B buckets + count]; B is data-dependent
}


def combiners_for(op: str, ncomp: int):
    """Normalized per-component combiner tuple for an op's partials."""
    comb = AGGREGATORS.get(op, AggSpec(1, "sum")).combiner
    return comb if isinstance(comb, tuple) else (comb,) * ncomp


def _seg(op, vals, group_ids, num_groups):
    if op == "sum":
        return jax.ops.segment_sum(vals, group_ids, num_segments=num_groups)
    if op == "min":
        return jax.ops.segment_min(vals, group_ids, num_segments=num_groups)
    if op == "max":
        return jax.ops.segment_max(vals, group_ids, num_segments=num_groups)
    raise ValueError(op)


@functools.partial(jax.jit, static_argnames=("op", "num_groups"))
def map_phase(op: str, vals: jax.Array, group_ids: jax.Array,
              num_groups: int) -> jax.Array:
    """vals [S, W] (NaN absent) -> partial components [G, W, C]."""
    present = ~jnp.isnan(vals)
    zeroed = jnp.where(present, vals, 0.0)
    cnt = present.astype(vals.dtype)
    if op == "sum":
        comp = [zeroed, cnt]
    elif op == "count":
        comp = [cnt]
    elif op == "avg":
        comp = [zeroed, cnt]
    elif op in ("stddev", "stdvar"):
        comp = [zeroed, zeroed * zeroed, cnt]
    elif op == "min":
        comp = [jnp.where(present, vals, jnp.inf), cnt]
    elif op == "max":
        comp = [jnp.where(present, vals, -jnp.inf), cnt]
    elif op == "group":
        comp = [jnp.where(present, 1.0, -jnp.inf)]
    else:
        raise ValueError(f"unknown aggregate {op}")
    combs = combiners_for(op, len(comp))
    if len(set(combs)) == 1:
        stacked = jnp.stack(comp, axis=-1)        # [S, W, C]
        return _seg(combs[0], stacked, group_ids, num_groups)
    return jnp.stack([_seg(c, x, group_ids, num_groups)
                      for c, x in zip(combs, comp)], axis=-1)


def reduce_phase(op: str, a: jax.Array, b: jax.Array) -> jax.Array:
    """Combine two partials [G, W, C] (inter-shard tree reduce)."""
    combs = combiners_for(op, a.shape[-1])

    def one(comb, x, y):
        if comb == "sum":
            return x + y
        return jnp.minimum(x, y) if comb == "min" else jnp.maximum(x, y)
    if len(set(combs)) == 1:
        return one(combs[0], a, b)
    return jnp.stack([one(c, a[..., i], b[..., i])
                      for i, c in enumerate(combs)], axis=-1)


@functools.partial(jax.jit, static_argnames=("op",))
def present(op: str, partial: jax.Array) -> jax.Array:
    """Partial components [G, W, C] -> final [G, W] (NaN where no series)."""
    if op == "sum":
        s, c = partial[..., 0], partial[..., 1]
        return jnp.where(c > 0, s, jnp.nan)
    if op == "count":
        c = partial[..., 0]
        return jnp.where(c > 0, c, jnp.nan)
    if op == "avg":
        s, c = partial[..., 0], partial[..., 1]
        return jnp.where(c > 0, s / jnp.maximum(c, 1.0), jnp.nan)
    if op in ("stddev", "stdvar"):
        s, s2, c = partial[..., 0], partial[..., 1], partial[..., 2]
        cs = jnp.maximum(c, 1.0)
        var = jnp.maximum(s2 / cs - (s / cs) ** 2, 0.0)
        out = jnp.sqrt(var) if op == "stddev" else var
        return jnp.where(c > 0, out, jnp.nan)
    if op in ("min", "max"):
        v, c = partial[..., 0], partial[..., 1]
        return jnp.where(c > 0, v, jnp.nan)
    if op == "group":
        v = partial[..., 0]
        return jnp.where(jnp.isinf(v), jnp.nan, v)
    raise ValueError(op)


@functools.partial(jax.jit, static_argnames=("op", "num_groups"))
def aggregate(op: str, vals: jax.Array, group_ids: jax.Array,
              num_groups: int) -> jax.Array:
    """Single-shard shortcut: map + present in one pass -> [G, W]."""
    return present(op, map_phase(op, vals, group_ids, num_groups))


# ----------------------------------------------------------- rank aggregates

@functools.partial(jax.jit, static_argnames=("k", "largest", "num_groups"))
def topk_mask(vals: jax.Array, group_ids: jax.Array, num_groups: int,
              k: int, largest: bool = True) -> jax.Array:
    """Per-(group, step) top/bottom-k selection mask [S, W].

    Computes each value's rank within its group per step via lexicographic
    sort (group asc, value desc), the vectorized equivalent of the
    reference's TopBottomK RowAggregator (ref: exec/aggregator/
    TopBottomKRowAggregator note in RowAggregator.scala area).
    """
    S, W = vals.shape
    key_vals = jnp.where(jnp.isnan(vals), -jnp.inf if largest else jnp.inf, vals)
    sign = -1.0 if largest else 1.0

    def per_step(v_col):
        order = jnp.lexsort((sign * v_col, group_ids))      # stable: group, value
        # rank within group = position - first position of that group
        g_sorted = group_ids[order]
        first_of_group = jnp.searchsorted(g_sorted, jnp.arange(num_groups))
        pos = jnp.arange(S)
        rank_sorted = pos - first_of_group[g_sorted]
        rank = jnp.zeros(S, jnp.int32).at[order].set(rank_sorted.astype(jnp.int32))
        return rank

    ranks = jax.vmap(per_step, in_axes=1, out_axes=1)(key_vals)   # [S, W]
    return (ranks < k) & ~jnp.isnan(vals)


@functools.partial(jax.jit, static_argnames=("num_groups",))
def quantile_agg(vals: jax.Array, group_ids: jax.Array, num_groups: int,
                 q) -> jax.Array:
    """quantile(q, expr) by group -> [G, W].  Exact (sort-based) rather than
    the reference's t-digest approximation (ref: exec/aggregator/
    QuantileRowAggregator.scala:87) — bitonic sort on TPU is cheap."""
    S, W = vals.shape

    def per_group(g):
        m = (group_ids == g)[:, None]
        v = jnp.where(m & ~jnp.isnan(vals), vals, jnp.inf)
        srt = jnp.sort(v, axis=0)                            # [S, W]
        cnt = jnp.sum((~jnp.isinf(srt)).astype(jnp.int32), axis=0)
        rank = q * (cnt.astype(vals.dtype) - 1.0)
        lo = jnp.clip(jnp.floor(rank).astype(jnp.int32), 0, S - 1)
        hi = jnp.clip(jnp.ceil(rank).astype(jnp.int32), 0, S - 1)
        frac = rank - lo.astype(vals.dtype)
        vlo = jnp.take_along_axis(srt, lo[None, :], axis=0)[0]
        vhi = jnp.take_along_axis(srt, hi[None, :], axis=0)[0]
        out = vlo + (vhi - vlo) * frac
        return jnp.where(cnt > 0, out, jnp.nan)

    return jax.vmap(per_group)(jnp.arange(num_groups))
