"""Mergeable quantile sketch for distributed aggregation.

The reference aggregates `quantile()` across shards with t-digest partials
(ref: query/.../exec/aggregator/QuantileRowAggregator.scala:87 — serialized
TDigest per group/window) so the wire cost is O(groups), not O(series).
This is the numpy equivalent: per (group, window) an equal-depth centroid
summary [K, 2] of (mean, weight), built vectorized over the window axis.

Properties:
- exact when a cell holds <= K samples (centroids are singletons, and the
  quantile interpolation below reduces to Prometheus' linear interpolation
  over sorted values);
- mergeable: concatenate centroid lists, re-compress to K by cumulative
  weight (same shape regardless of how many shards contributed);
- bounded size: K*(2 float64) per (group, window) on the wire.
"""
from __future__ import annotations

import numpy as np

K_DEFAULT = 64


def sketch_from_values(vals: np.ndarray, gids: np.ndarray, num_groups: int,
                       k: int = K_DEFAULT) -> np.ndarray:
    """Build [G, W, K, 2] sketches from per-series values [N, W] with group
    assignment gids [N].  NaN samples are absent.  Slot 0 = mean, 1 = weight
    (weight 0 = unused centroid, mean NaN)."""
    N, W = vals.shape
    out = np.zeros((num_groups, W, k, 2))
    out[..., 0] = np.nan
    # one stable sort, then contiguous slices per group — O(N log N) total
    # instead of a full boolean mask per group (O(G*N))
    order = np.argsort(gids, kind="stable")
    sorted_gids = gids[order]
    g_ids = np.arange(num_groups)
    starts = np.searchsorted(sorted_gids, g_ids, side="left")
    ends = np.searchsorted(sorted_gids, g_ids, side="right")
    for g in range(num_groups):
        rows = vals[order[starts[g]:ends[g]]]         # [n_g, W]
        n_g = rows.shape[0]
        if n_g == 0:
            continue
        srt = np.sort(rows, axis=0)                   # NaN sorts last
        cnt = (~np.isnan(rows)).sum(axis=0)           # [W]
        if n_g <= k:
            # singleton centroids: exact
            out[g, :, :n_g, 0] = srt.T
            pos = np.arange(n_g)[None, :]
            out[g, :, :n_g, 1] = (pos < cnt[:, None]).astype(float)
            out[g, :, :n_g, 0] = np.where(out[g, :, :n_g, 1] > 0,
                                          out[g, :, :n_g, 0], np.nan)
            continue
        # equal-depth bins per window: bin i covers sorted ranks
        # [floor(i*c/k), floor((i+1)*c/k))
        cs = np.nancumsum(srt, axis=0)                # [n_g, W]
        cs = np.vstack([np.zeros((1, W)), cs])        # prefix sums, 1-indexed
        edges = (np.arange(k + 1)[:, None] * cnt[None, :]) // k   # [k+1, W]
        lo, hi = edges[:-1], edges[1:]                # [k, W]
        w = (hi - lo).astype(float)
        sums = np.take_along_axis(cs, hi, axis=0) - \
            np.take_along_axis(cs, lo, axis=0)
        mean = np.divide(sums, w, out=np.full_like(sums, np.nan),
                         where=w > 0)
        out[g, :, :, 0] = mean.T
        out[g, :, :, 1] = w.T
    return out


def _centroid_order(means: np.ndarray, wts: np.ndarray) -> np.ndarray:
    """Content-based total order over the centroid axis: live before
    dead, then by (mean, weight).  A pure function of the centroid
    MULTISET — centroids tied on both mean and weight are identical and
    interchangeable — so every consumer below is insensitive to the
    order shards/nodes concatenated in (a mean-only sort left equal-
    mean ties at the mercy of concat order, which broke bit-identity
    once node-level pushdown regrouped the shard merge tree)."""
    key_mean = np.where(wts > 0, means, np.inf)
    key_wt = np.where(wts > 0, wts, np.inf)
    return np.lexsort((key_wt, key_mean), axis=-1)


def merge_sketches(sk: np.ndarray, k: int = K_DEFAULT) -> np.ndarray:
    """Compress [G, W, M, 2] (concatenated centroids) back to [G, W, K, 2].
    Whole centroids are assigned to equal-weight bins by their cumulative
    weight midpoint; bin mean is the weighted mean of its centroids."""
    G, W, M, _ = sk.shape
    if M <= k:
        out = np.zeros((G, W, k, 2))
        out[..., 0] = np.nan
        out[:, :, :M] = sk
        return out
    means, wts = sk[..., 0], sk[..., 1]
    order = _centroid_order(means, wts)
    means = np.take_along_axis(means, order, axis=-1)
    wts = np.take_along_axis(wts, order, axis=-1)
    cum = np.cumsum(wts, axis=-1)
    total = cum[..., -1:]                             # [G, W, 1]
    mid = cum - wts / 2.0
    with np.errstate(invalid="ignore", divide="ignore"):
        bin_idx = np.where(total > 0,
                           (mid / total * k).astype(np.int64), 0)
    bin_idx = np.clip(bin_idx, 0, k - 1)
    # segment-sum weights and weight*mean into bins
    gw = np.repeat(np.arange(G * W), M)
    flat_bin = bin_idx.reshape(-1)
    idx = gw * k + flat_bin
    wsum = np.zeros(G * W * k)
    msum = np.zeros(G * W * k)
    fw = wts.reshape(-1)
    fm = np.where(np.isnan(means), 0.0, means).reshape(-1)
    np.add.at(wsum, idx, fw)
    np.add.at(msum, idx, fm * fw)
    wsum = wsum.reshape(G, W, k)
    msum = msum.reshape(G, W, k)
    out = np.zeros((G, W, k, 2))
    out[..., 1] = wsum
    with np.errstate(invalid="ignore"):
        out[..., 0] = np.where(wsum > 0, msum / np.maximum(wsum, 1e-300),
                               np.nan)
    return out


def sketch_quantile(sk: np.ndarray, q: float) -> np.ndarray:
    """Estimate the q-quantile per (group, window) cell -> [G, W].

    Centroid i of weight w_i occupies sample ranks
    [cum_{i-1}, cum_{i-1}+w_i); its representative rank is the midpoint
    cum_{i-1} + (w_i - 1)/2.  Linear interpolation between representative
    ranks reproduces Prometheus' `quantile()` exactly for singleton
    centroids and is the standard t-digest estimator otherwise."""
    means, wts = sk[..., 0], sk[..., 1]
    order = _centroid_order(means, wts)
    means = np.take_along_axis(means, order, axis=-1)
    wts = np.take_along_axis(wts, order, axis=-1)
    cum = np.cumsum(wts, axis=-1)
    total = cum[..., -1]                              # [G, W]
    rank = np.where(wts > 0, cum - wts + (wts - 1) / 2.0, np.inf)
    target = q * (total - 1.0)                        # [G, W]
    if q < 0:
        return np.where(total > 0, -np.inf, np.nan)
    if q > 1:
        return np.where(total > 0, np.inf, np.nan)
    # hi = first LIVE centroid whose rank >= target; lo = hi - 1.  Dead
    # (weight-0) centroids must not win — their rank is +inf and their mean
    # NaN, which would turn high quantiles into NaN whenever live and dead
    # slots coexist (e.g. after a merge with a sparse shard)
    ge = (rank >= target[..., None]) & (wts > 0)
    hi = np.argmax(ge, axis=-1)
    any_ge = ge.any(axis=-1)
    last_live = np.maximum((wts > 0).sum(axis=-1) - 1, 0)
    hi = np.where(any_ge, hi, last_live)
    lo = np.maximum(hi - 1, 0)
    take = lambda a, i: np.take_along_axis(a, i[..., None], axis=-1)[..., 0]  # noqa: E731
    r_lo, r_hi = take(rank, lo), take(rank, hi)
    m_lo, m_hi = take(means, lo), take(means, hi)
    first_rank = take(rank, np.zeros_like(hi))
    span = np.where(r_hi > r_lo, r_hi - r_lo, 1.0)
    frac = np.clip((target - r_lo) / span, 0.0, 1.0)
    est = m_lo + (m_hi - m_lo) * frac
    est = np.where(target <= first_rank, take(means, np.zeros_like(hi)), est)
    return np.where(total > 0, est, np.nan)
