"""Server-side query micro-batching.

Dashboard clients (Grafana, the Prometheus UI) issue ONE HTTP request
per panel, all sharing the dashboard's time range and step.  On TPU a
fused leaf query is dispatch-bound (doc/kernels.md), so the server
coalesces concurrent `query_range` calls over the same window grid into
one `engine.query_range_batch` — merged kernel dispatches for clients
that know nothing about batching.  The trade is explicit: a request may
wait up to `window_s` for peers to arrive, in exchange for the panels
sharing one dispatch (measured 4.7-5.5x for 8 panels,
TPU_BATCH_r04.json / bench.py dashboard_batch).

No reference analogue — the iterator engine has nothing to amortize;
this is the TPU-shaped server feature enabled by
`query.batch_window_ms` (0 = off, the default).
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple


class _Group:
    __slots__ = ("queries", "results", "error", "done")

    def __init__(self):
        self.queries: List[str] = []
        self.results = None
        self.error: Optional[BaseException] = None
        self.done = threading.Event()


class QueryCoalescer:
    """Wraps one QueryEngine; `query_range` blocks up to `window_s` while
    concurrent callers with the same (start, step, end, planner params)
    pile into the same batch.  The first arrival leads: it sleeps out the
    window, snapshots the group, runs query_range_batch, and wakes the
    followers.  Failures fall back to per-query execution — coalescing
    must never lose a query that would have succeeded alone."""

    def __init__(self, engine, window_s: float):
        self.engine = engine
        self.window_s = float(window_s)
        self._lock = threading.Lock()
        self._groups: Dict[Tuple, _Group] = {}

    def query_range(self, promql: str, start_s: int, step_s: int,
                    end_s: int, planner_params=None):
        if self.window_s <= 0:
            return self.engine.query_range(promql, start_s, step_s, end_s,
                                           planner_params)
        key = (start_s, step_s, end_s, repr(planner_params))
        with self._lock:
            grp = self._groups.get(key)
            leader = grp is None
            if leader:
                grp = _Group()
                self._groups[key] = grp
            idx = len(grp.queries)
            grp.queries.append(promql)
        completed = True
        dl = getattr(planner_params, "deadline_unix_s", 0.0) \
            if planner_params is not None else 0.0
        if leader:
            time.sleep(self.window_s)
            with self._lock:
                # close the window: later arrivals start a new group
                if self._groups.get(key) is grp:
                    del self._groups[key]
            try:
                grp.results = self.engine.query_range_batch(
                    grp.queries, start_s, step_s, end_s, planner_params)
            except Exception as e:  # noqa: BLE001 — followers must wake
                grp.error = e
                grp.done.set()
            except BaseException as e:
                # KeyboardInterrupt/SystemExit: wake followers (they fall
                # back to solo execution) but PROPAGATE the exit — the
                # leader thread must not swallow an interpreter shutdown
                grp.error = e
                grp.done.set()
                raise
            else:
                grp.done.set()
        else:
            # generous bound: a wedged leader must not strand followers.
            # The follower's deadline bounds the wait too — the solo
            # fallback then returns the structured query_timeout from
            # the exec-boundary check instead of blocking past budget.
            # The wait is sliced against the follower's OWN cancel token
            # (it is registered and holds a scheduler slot while parked
            # here): a kill/disconnect frees the slot within ~50 ms
            # instead of riding out the leader.
            from filodb_tpu.query.activequeries import peek_admission
            from filodb_tpu.query.rangevector import remaining_budget
            bound = remaining_budget(planner_params,
                                     max(300.0, 10 * self.window_s))
            ent = peek_admission()
            tok = ent.token if ent is not None else None
            if tok is None:
                completed = grp.done.wait(timeout=bound)
            else:
                deadline = time.perf_counter() + bound
                completed = False
                while not completed:
                    if tok.cancelled:
                        from filodb_tpu.query.rangevector import \
                            QueryResult
                        return QueryResult(
                            [], error=("query_canceled: query killed "
                                       "waiting on a coalesce leader "
                                       f"(reason={tok.reason or 'admin'})"))
                    left = deadline - time.perf_counter()
                    if left <= 0:
                        break
                    completed = grp.done.wait(timeout=min(left, 0.05))
        if grp.error is not None or grp.results is None:
            # batch failed (or leader timed out): run alone
            res = self.engine.query_range(promql, start_s, step_s, end_s,
                                          planner_params)
            deadline_expired = (not leader and dl and time.time() >= dl)
            if not completed and not deadline_expired:
                # the wedged-leader fallback must be visible: count it
                # and flag the follower's stats so an operator can see
                # WHY this poll ran solo (satellite of PR 4)
                from filodb_tpu.utils.metrics import registry
                registry.counter("coalesce_leader_timeouts").increment()
                if res is not None:
                    res.stats.warnings.append(
                        "coalesce leader timed out; follower fell back "
                        "to solo execution")
            return res
        res = grp.results[idx]
        if not leader and res is not None and res.error is not None \
                and (res.error.startswith("query_timeout")
                     or res.error.startswith("query_canceled")):
            # the LEADER's budget expired or it was killed — not this
            # follower (budgets/kills are per-request, repr-excluded
            # from the group key): re-run solo under our own
            # deadline/token instead of inheriting the expiry
            return self.engine.query_range(promql, start_s, step_s, end_s,
                                           planner_params)
        return res
